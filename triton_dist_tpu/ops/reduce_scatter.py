"""ReduceScatter kernels over ICI.

Reference: ``python/triton_dist/kernels/nvidia/reduce_scatter.py`` (831
LoC: P2P-write producer + reduction consumer with per-tile signals). TPU
redesign: a single ring kernel per device — at each step the running
partial sum for one chunk is forwarded one hop right and accumulated,
so every chunk crosses every device once (bandwidth-optimal on a ring).

Data path per step: recv (RDMA from left, HBM) → VMEM add with the local
chunk → HBM send buffer → RDMA right.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import triton_dist_tpu.lang as dl
from triton_dist_tpu.lang import core_call
from triton_dist_tpu.parallel.mesh import MeshContext


def reduce_scatter_ref(x, *, axis: str = "tp", **_):
    """``jax.lax.psum_scatter`` along ``axis`` over dim 0 (tiled)."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)


def _ring_kernel(x_ref, out_ref, recv_hbm, send_hbm, acc_v, tmp_v,
                 send_sem, recv_sem, *,
                 axis: str, ctx: MeshContext):
    n = dl.num_ranks(axis)
    me = dl.rank(axis)
    csize = out_ref.shape[0]
    right = jax.lax.rem(me + 1, n)

    dl.barrier_tile(axis, ctx=ctx)

    def chunk(ref, c):
        return ref.at[pl.ds(c * csize, csize)]

    # Per-step receive slots and semaphores: each is written/consumed
    # exactly once, so arbitrary neighbour skew cannot overrun a slot
    # that has not been read yet (no credit round-trips needed; the extra
    # HBM footprint is one input's worth).
    for step in range(n - 1):
        # Chunk currently flowing through this device (ends at device c).
        c = jax.lax.rem(me - step - 1 + n, n)
        if step == 0:
            # First hop: send the raw local chunk.
            src = chunk(x_ref, c)
        else:
            # recv[step-1] holds the partial for chunk c (arrived last
            # step); add our local contribution in VMEM.
            pltpu.sync_copy(recv_hbm.at[step - 1], tmp_v)
            pltpu.sync_copy(chunk(x_ref, c), acc_v)
            acc_v[...] = acc_v[...] + tmp_v[...]
            pltpu.sync_copy(acc_v, send_hbm)
            src = send_hbm
        copy = dl.remote_put(src, recv_hbm.at[step], send_sem.at[step],
                             recv_sem.at[step], right, axis=axis, ctx=ctx)
        copy.wait()

    if n > 1:
        # Last arrival holds the sum over the other n-1 devices for
        # chunk ``me``.
        pltpu.sync_copy(recv_hbm.at[n - 2], tmp_v)
        pltpu.sync_copy(chunk(x_ref, me), acc_v)
        acc_v[...] = acc_v[...] + tmp_v[...]
        pltpu.sync_copy(acc_v, out_ref)
    else:
        # Rankless (forced): the scatter of one chunk is the chunk.
        pltpu.sync_copy(chunk(x_ref, me), acc_v)
        pltpu.sync_copy(acc_v, out_ref)


def reduce_scatter(x, *, ctx: MeshContext, axis: str = "tp",
                   mode: str = "ring", force_kernel: bool = False):
    """Per-shard ReduceScatter along ``axis`` over dim 0 (inside shard_map).

    ``x``: shape ``(n * c, ...)`` → returns ``(c, ...)`` summed across the
    axis.
    """
    n = ctx.size(axis)
    if n == 1 and not force_kernel:
        return x
    if x.shape[0] % n:
        raise ValueError(f"dim0 {x.shape[0]} not divisible by axis size {n}")
    csize = x.shape[0] // n
    rest = tuple(x.shape[1:])
    out_shape = jax.ShapeDtypeStruct((csize,) + rest, x.dtype)
    kernel = functools.partial(_ring_kernel, axis=axis, ctx=ctx)
    # Ring buffers are extra outputs (no HBM scratch on real TPUs).
    out, _recv_ws, _send_ws = core_call(
        kernel,
        comm=True,
        out_shape=(out_shape,
                   jax.ShapeDtypeStruct((max(n - 1, 1), csize) + rest,
                                        x.dtype),
                   jax.ShapeDtypeStruct((csize,) + rest, x.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[
            pltpu.VMEM((csize,) + rest, x.dtype),       # acc_v
            pltpu.VMEM((csize,) + rest, x.dtype),       # tmp_v
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),   # send_sem
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),   # recv_sem
        ],
    )(x)
    return out
