"""Host-side benchmarking and profiling helpers.

Reference: ``python/triton_dist/profiler_utils.py`` (629 LoC) —
``perf_func`` :355, ``perf_func_with_l2_reset`` :330, ``group_profile``
:205 (per-rank torch-profiler traces merged to one JSON),
``benchmark_latency_memory`` :372.

TPU redesign: ``jax.profiler`` natively emits Perfetto/TensorBoard
traces for every device in one capture (no per-rank merging needed);
``perf_func`` uses dependency-chained in-jit iteration with two-point
slope timing so fixed dispatch/tunnel overhead cancels (async dispatch
makes naive wall-clocking meaningless — see bench.py).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Sequence

import numpy as np


def perf_func(fn: Callable, args: Sequence, *, iters_lo: int = 8,
              iters_hi: int = 40, repeats: int = 3,
              chain: bool = True) -> float:
    """Seconds per invocation of ``fn(*args)``.

    With ``chain=True`` (default) runs dependency-chained iterations
    inside one jit and returns the two-point slope — use for
    device-bound measurements. ``chain=False`` wall-clocks dispatches
    (only meaningful with a locally-attached backend).
    """
    import jax
    import jax.numpy as jnp

    if not chain:
        r = fn(*args)
        jax.block_until_ready(r)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(iters_hi):
                r = fn(*args)
            jax.block_until_ready(r)
            best = min(best, (time.perf_counter() - t0) / iters_hi)
        return best

    lead = args[0]

    def make_chain(iters):
        @jax.jit
        def chained(*a):
            def body(_, x):
                out = fn(x, *a[1:])
                first = jax.tree.leaves(out)[0]
                bump = (first.reshape(-1)[0].astype(jnp.float32) * 1e-3
                        ).astype(x.dtype)
                return jnp.clip(x + bump, -4.0, 4.0)
            s = jax.lax.fori_loop(0, iters, body, a[0])
            return jnp.sum(s.astype(jnp.float32))
        return chained

    times = {}
    for iters in (iters_lo, iters_hi):
        chained = make_chain(iters)
        v = np.asarray(chained(*args))
        if not np.isfinite(v):
            raise FloatingPointError("perf chain produced non-finite value")
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            np.asarray(chained(*args))
            best = min(best, time.perf_counter() - t0)
        times[iters] = best
    return (times[iters_hi] - times[iters_lo]) / (iters_hi - iters_lo)


@contextlib.contextmanager
def group_profile(name: str = "trace", *, log_dir: str = "/tmp/tdt_traces",
                  create_perfetto_link: bool = False,
                  create_perfetto_trace: bool = False):
    """Capture a multi-device profile viewable in Perfetto/TensorBoard.

    Reference ``group_profile`` merges per-rank torch traces
    (``profiler_utils.py:100-204``); ``jax.profiler.trace`` already
    captures every local device into one trace directory.
    ``create_perfetto_trace`` additionally materializes the capture as
    ``perfetto_trace.json.gz`` in the session directory (forwarded to
    ``jax.profiler.trace`` when this jax supports it; silently dropped
    on older versions — the ``*.trace.json.gz`` the capture always
    writes is what :func:`~triton_dist_tpu.obs.extract_xprof_spans`
    mines either way).
    """
    import inspect

    import jax

    path = f"{log_dir}/{name}"
    kw = {"create_perfetto_link": create_perfetto_link}
    if create_perfetto_trace:
        sig = inspect.signature(jax.profiler.trace)
        if "create_perfetto_trace" in sig.parameters:
            kw["create_perfetto_trace"] = True
    with jax.profiler.trace(path, **kw):
        yield path


def benchmark_latency(fn, args, **kw) -> dict:
    """Latency + achieved-bytes helper (reference
    ``benchmark_latency_memory``)."""
    sec = perf_func(fn, args, **kw)
    return {"seconds": sec, "ms": sec * 1e3}
