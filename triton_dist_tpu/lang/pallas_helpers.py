"""pallas_call plumbing shared by every kernel in the package.

The analogue of the reference's ``@triton_dist.jit`` overlay
(``python/triton_dist/jit.py``): where that wrapper injects the SHMEM
extern lib and registers modules with the SHMEM runtime, ours injects the
interpret-mode switch (CPU mesh testing), communication compiler params
(``has_side_effects`` + ``collective_id``), and default cost estimates.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.utils.distributed import interpret_arg

# Mosaic requires distinct collective_ids for concurrently-running
# collective kernels; a process-wide counter keeps them unique per traced
# kernel (cached tracings reuse their id, which is the requirement). The
# hardware barrier-semaphore table is small, so ids cycle mod 32 —
# aliasing would need >32 distinct comm kernels genuinely in flight.
_collective_ids = itertools.count(1)


def next_collective_id() -> int:
    return next(_collective_ids) % 32


def comm_compiler_params(collective_id: Optional[int] = None,
                         **kwargs) -> pltpu.CompilerParams:
    """CompilerParams for kernels that perform remote DMA / barriers."""
    if collective_id is None:
        collective_id = next_collective_id()
    return pltpu.CompilerParams(
        has_side_effects=True, collective_id=collective_id, **kwargs)


def core_call(kernel, *, comm: bool = False,
              compiler_params: Optional[pltpu.CompilerParams] = None,
              interpret: Any = None, **pallas_kwargs):
    """``pl.pallas_call`` with package defaults applied.

    - ``interpret`` defaults to the global interpret switch
      (on for non-TPU platforms → the CPU-mesh test backend).
    - ``comm=True`` marks a communicating kernel: side effects + a fresh
      ``collective_id`` unless explicit ``compiler_params`` are given.
    """
    if interpret is None:
        interpret = interpret_arg()
    if compiler_params is None and comm:
        compiler_params = comm_compiler_params()
    if compiler_params is not None:
        pallas_kwargs["compiler_params"] = compiler_params
    return pl.pallas_call(kernel, interpret=interpret, **pallas_kwargs)
