"""SHMEM teams over mesh axes.

Reference: ``language/extra/libshmem_device.py`` team API — ``team_my_pe``
(:69), ``team_n_pes`` (:74), ``barrier(team)`` (:126), ``team_translate_pe``
(:475), plus the ``TEAM_WORLD / NODE`` constants (:512 onward).

TPU redesign: a *team* is a tuple of named mesh axes. The mesh already
carries the team structure the reference builds at runtime (NVSHMEM team
split): ``Team(ctx, ("tp",))`` is the TP ring, ``Team(ctx, ("dp", "tp"))``
is the world over both axes (outer-major flat PE order, matching the
canonical mesh linearization in ``parallel/mesh.py``). PE numbering is
the row-major flat index over the team's axes; translation between teams
is coordinate re-linearization — no membership tables, no registration.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax

from triton_dist_tpu.parallel.mesh import MeshContext


@dataclasses.dataclass(frozen=True)
class Team:
    """A SHMEM team = an ordered tuple of mesh axes (outer-major)."""

    ctx: MeshContext
    axes: Tuple[str, ...]

    def __post_init__(self):
        for a in self.axes:
            if a not in self.ctx.axes:
                raise ValueError(f"axis {a!r} not in mesh {self.ctx.axes}")

    # -- static queries ----------------------------------------------------
    def n_pes(self) -> int:
        """Reference ``team_n_pes`` (:74)."""
        return math.prod(self.ctx.size(a) for a in self.axes)

    # -- traced queries (inside shard_map) ---------------------------------
    def my_pe(self):
        """Flat PE id in this team (reference ``team_my_pe`` :69)."""
        pe = 0
        for a in self.axes:
            pe = pe * self.ctx.size(a) + jax.lax.axis_index(a)
        return pe

    def coords(self, pe):
        """Per-axis coordinates of flat PE id (outer-major)."""
        out = []
        for a in reversed(self.axes):
            size = self.ctx.size(a)
            out.append(jax.lax.rem(pe, size))
            pe = jax.lax.div(pe, size)
        return tuple(reversed(out))

    def device_id(self, pe):
        """Logical device id of team PE ``pe`` (my coordinates on every
        axis outside the team). This is what remote DMA / semaphore
        signals take — the analogue of NVSHMEM PE translation to
        TEAM_WORLD before ``putmem`` (``team_translate_pe`` :475)."""
        coords = dict(zip(self.axes, self.coords(pe)))
        device_id = 0
        for name, size in zip(self.ctx.axes, self.ctx.sizes):
            idx = coords.get(name)
            if idx is None:
                idx = jax.lax.axis_index(name)
            device_id = device_id * size + idx
        return device_id

    def translate_pe(self, pe, dest: "Team"):
        """Reference ``team_translate_pe(src_team, pe, dest_team)``: the
        PE id in ``dest`` of the device that is ``pe`` here, or -1-free
        TPU form: only valid when that device is in ``dest`` (a device
        is in every axis-team of its own mesh, so translation between
        teams over subsets of axes is total given my off-team coords)."""
        coords = dict(zip(self.axes, self.coords(pe)))
        out = 0
        for a in dest.axes:
            idx = coords.get(a)
            if idx is None:
                idx = jax.lax.axis_index(a)
            out = out * dest.ctx.size(a) + idx
        return out


def team_world(ctx: MeshContext) -> Team:
    """All mesh axes, outer-major — NVSHMEM ``TEAM_WORLD``."""
    return Team(ctx, tuple(ctx.axes))


def team_axis(ctx: MeshContext, axis: str) -> Team:
    """Single-axis team — the reference's NODE/intra-scope teams."""
    return Team(ctx, (axis,))
