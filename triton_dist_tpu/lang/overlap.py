"""Shared overlap engine: rank-swizzled chunk schedules, prefetch-depth
panel staging, and coalesced per-chunk signalling.

The signature perf trick of the reference (the threadblock swizzle of
``allgather_gemm.py:~200`` and its gemm_rs / all-to-all siblings) is
reordering each rank's chunk traversal so compute starts on
locally-resident data while remote chunks are still in flight. Until
this module, that machinery lived only inside ``ops/ag_gemm.py``; every
other fused op hand-rolled a simpler (or no) overlap schedule. This
module is the one place the three reusable pieces live:

(a) **Schedule generator** — :func:`chunk_at` / :func:`step_of` /
    :func:`schedule`: a pure function family mapping grid step to chunk
    id per ``swizzle_mode``:

    - ``"ag"``  (all-gather consumer):   chunk ``(rank - step) % world``
      — the local chunk first, then ring-arrival order.
    - ``"rs"``  (reduce-scatter producer): chunk
      ``(rank - step - 1) % world`` — each chunk's running sum visits
      ranks in ring sequence, finishing at its owner.
    - ``"a2a"`` (all-to-all consumer):   chunk ``(rank + step) % world``
      — the local chunk first, then peers by ring offset.
    - ``"identity"``: chunk ``step`` — the unswizzled baseline every
      swizzled schedule is parity-tested (and benchmarked) against.

(b) **Panel stager** — :class:`PanelStager` + :func:`choose_depth`: the
    prefetch-depth-parameterized generalization of ag_gemm's hardcoded
    two-buffer cross-chunk prefetch. ``depth`` panels are in flight at
    once (1 = stage-and-wait, 2 = classic double buffering, 3 = deeper
    pipelining for when one panel of lead time cannot cover the
    arrival/HBM latency). :func:`stream_scoped` packages the same
    buffer-parity/semaphore algebra as a *scoped-VMEM block stream*
    (``pl.run_scoped`` scratch allocated per grid body, the
    ``paged_flash_decode`` per-parity prefetch idiom) — the staging
    core of the pipelined ``ag_gemm`` variant; :func:`stream_plan` is
    its staging schedule as a pure host function.

(c) **Coalesced signalling** — :func:`a2a_slot` (the handshake-free
    arrival-slot arithmetic shared by every all-to-all-shaped sender/
    receiver pair) and :func:`drain_sends` (consume outstanding
    per-chunk send credits before kernel exit). Sub-tile results are
    staged locally and each chunk rides ONE remote put + ONE semaphore
    signal — never per-tile signals.

Interpret-mesh rule (see ``utils/compat.py``): remote puts must be
rank-CONVERGENT — the same put sites in the same order on every rank.
Swizzle modes therefore only reorder *waits and compute*; the put
schedule of an op never depends on the mode (the "identity" mode of a
ring op pumps the whole ring convergently before compute instead).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import shmem_device as dl

__all__ = [
    "SWIZZLE_MODES",
    "schedule",
    "chunk_at",
    "step_of",
    "a2a_slot",
    "ring_chunk",
    "pump_ring",
    "pump_ring_event",
    "PanelStager",
    "choose_depth",
    "stream_plan",
    "stream_scoped",
    "drain_sends",
]

SWIZZLE_MODES = ("ag", "rs", "a2a", "identity")


def _check_mode(mode: str) -> None:
    if mode not in SWIZZLE_MODES:
        raise ValueError(f"unknown swizzle_mode {mode!r} "
                         f"(expected one of {SWIZZLE_MODES})")


def _rem(x, n: int):
    """``x % n`` for either Python ints or traced values (``n`` static,
    ``x`` possibly negative by less than ``n``)."""
    if isinstance(x, int):
        return x % n
    return jax.lax.rem(x + n, n)


def chunk_at(step, rank, world: int, mode: str):
    """Chunk id computed at grid ``step`` by ``rank`` under ``mode``.

    Pure arithmetic: works on Python ints (host-side schedule
    construction, tests) and on traced values (inside kernels and
    BlockSpec index maps) alike.
    """
    _check_mode(mode)
    if mode == "ag":
        return _rem(rank - step, world)
    if mode == "rs":
        return _rem(rank - step - 1, world)
    if mode == "a2a":
        if isinstance(step, int) and isinstance(rank, int):
            return (rank + step) % world
        return jax.lax.rem(rank + step, world)
    return step  # identity


def step_of(chunk, rank, world: int, mode: str):
    """Inverse of :func:`chunk_at`: the grid step at which ``rank``
    computes ``chunk``."""
    _check_mode(mode)
    if mode == "ag":
        return _rem(rank - chunk, world)
    if mode == "rs":
        return _rem(rank - chunk - 1, world)
    if mode == "a2a":
        return _rem(chunk - rank, world)
    return chunk  # identity


def schedule(rank: int, world: int, n_chunks: int, mode: str):
    """Full traversal order as a tuple (host-side form of
    :func:`chunk_at` — the reference's threadblock-swizzle table).

    ``n_chunks`` must equal ``world`` for the ring modes; for
    ``identity`` any count is allowed.
    """
    _check_mode(mode)
    if mode != "identity" and n_chunks != world:
        raise ValueError(f"mode {mode!r} schedules exactly world="
                         f"{world} chunks (got n_chunks={n_chunks})")
    return tuple(chunk_at(s, rank, world, mode) for s in range(n_chunks))


def ring_chunk(event, rank, world: int):
    """Chunk delivered to ``rank`` by ring event ``event`` (the
    ``event``-th hop of a rightward all-gather ring): ``event = 0`` is
    the local chunk, event ``r`` >= 1 the chunk that left rank
    ``rank - r``."""
    return _rem(rank - event, world)


def a2a_slot(src, dst, world: int):
    """Arrival-semaphore slot for chunk ``src`` landing at ``dst`` in an
    all-to-all-shaped exchange: ``(src - dst) % world - 1``.

    Both sides derive it from rank arithmetic — no handshake. ``dst``
    processes ``src``'s chunk at step ``(dst - src) % world`` of the
    "a2a" schedule, i.e. slot ``world - step - 1``; per-source slots
    mean a consumer never blocks on traffic it does not read, whatever
    order chunks arrive (or are consumed) in.
    """
    return _rem(src - dst, world) - 1


def pump_ring(events, *, me, world: int, right, chunk_of: Callable,
              send_sem, recv_sem, axis: str, ctx,
              sim_src_of: Optional[Callable] = None):
    """Process all-gather ring events ``events`` (an iterable of static
    ints >= 1, ascending): certify ring chunk ``r``'s arrival (slot
    ``r - 1``), then issue the put delivering ring chunk ``r + 1`` into
    slot ``r`` (real mode: forward my just-received chunk right; sim
    mode: a self-put sourcing the true data from ``sim_src_of``).

    Event 0 — the kickoff put delivering ring chunk 1 — is the caller's
    entry-body job (its source is the local input, which only the
    caller can name).
    """
    for r in events:
        assert 1 <= r <= world - 1, f"ring event {r} out of range"
        c = ring_chunk(r, me, world)
        dl.wait_arrivals(recv_sem.at[r - 1], chunk_of(c), 1)
        if r < world - 1:
            if sim_src_of is not None:
                nxt = ring_chunk(r + 1, me, world)
                dl.remote_put(sim_src_of(nxt), chunk_of(nxt),
                              send_sem.at[r], recv_sem.at[r], me,
                              axis=axis, ctx=ctx)
            else:
                dl.remote_put(chunk_of(c), chunk_of(c), send_sem.at[r],
                              recv_sem.at[r], right, axis=axis, ctx=ctx)


def pump_ring_event(event, *, me, world: int, right, chunk_of: Callable,
                    send_sem, recv_sem, axis: str, ctx,
                    sim_src_of: Optional[Callable] = None) -> None:
    """Process ONE ring event whose index is a TRACED value (the "ag"
    schedule processes event ``k`` at grid chunk boundary ``k``, where
    ``k`` is a grid index): certify ring chunk ``event``'s arrival (slot
    ``event - 1``) and issue the put delivering ring chunk ``event + 1``
    into slot ``event`` (skipped via ``pl.when`` past the last hop).

    The put site is rank-uniform (the event index is the same grid
    value on every rank) — safe on the interpret mesh.
    """
    c = ring_chunk(event, me, world)
    dl.wait_arrivals(recv_sem.at[event - 1], chunk_of(c), 1)

    @pl.when(event < world - 1)
    def _():
        if sim_src_of is not None:
            nxt = ring_chunk(event + 1, me, world)
            dl.remote_put(sim_src_of(nxt), chunk_of(nxt),
                          send_sem.at[event], recv_sem.at[event], me,
                          axis=axis, ctx=ctx)
        else:
            dl.remote_put(chunk_of(c), chunk_of(c), send_sem.at[event],
                          recv_sem.at[event], right, axis=axis, ctx=ctx)


def choose_depth(requested: int, panel_bytes: int, budget: int,
                 chunk_len: Optional[int], n_panels: int) -> int:
    """Resolve a ``prefetch_depth`` request against the VMEM budget and
    the grid geometry.

    ``requested = 0`` means auto (the historical policy: 2 when a
    double-buffered pair fits and there are >= 2 bodies per chunk).
    Explicit depths are clamped — never rejected — so one tuned config
    stays runnable across shapes: depth can only help when there are at
    least ``depth`` panels and the buffers fit the budget, and
    cross-chunk prefetch needs >= 2 bodies per chunk.

    ``chunk_len = None`` declares that staging is NOT cross-chunk —
    every panel's source needs no arrival certification (local input,
    or block-granular staging inside one chunk) — so the >= 2-bodies
    guard does not apply and only the panel count and VMEM budget
    clamp the depth.
    """
    if requested < 0 or requested > 3:
        raise ValueError(f"prefetch_depth must be 0 (auto) or 1..3, got "
                         f"{requested}")
    d = 2 if requested == 0 else requested
    d = min(d, max(n_panels, 1))
    while d > 1 and d * panel_bytes > budget:
        d -= 1
    if chunk_len is not None and chunk_len < 2:
        d = 1  # no body ahead of the boundary to hide staging under
    return max(d, 1)


class PanelStager:
    """Depth-``d`` rotating panel buffers over per-buffer DMA semaphores.

    ``panel_ref`` is a ``(depth, ...)`` VMEM scratch and ``sem`` a
    ``(depth,)`` DMA-semaphore array: each buffer waits on its own
    semaphore, so up to ``depth - 1`` staging DMAs may be in flight at
    once without completion-order ambiguity (a shared semaphore cannot
    tell WHICH panel landed).

    Panels are identified by a GLOBAL panel index ``p`` (monotone
    across chunk boundaries, e.g. ``k * n_i + i``), so consecutive
    panels rotate buffers even across chunks. The staging discipline —
    who stages which panel when — is the caller's (see the staging-plan
    comment below for the closed-form rule); this class owns only
    buffers, semaphores, and waits.
    """

    def __init__(self, panel_ref, sem, depth: int):
        self.panel = panel_ref
        self.sem = sem
        self.depth = depth

    def buf(self, p):
        """Buffer slot of global panel ``p``."""
        if self.depth == 1:
            return 0
        return _rem(p, self.depth)

    def start(self, src_ref, p) -> None:
        """Begin staging ``src_ref`` into panel ``p``'s buffer."""
        b = self.buf(p)
        pltpu.make_async_copy(src_ref, self.panel.at[b],
                              self.sem.at[b]).start()

    def wait(self, p) -> None:
        """Block until panel ``p``'s staging DMA completed."""
        b = self.buf(p)
        pltpu.make_async_copy(self.panel.at[b], self.panel.at[b],
                              self.sem.at[b]).wait()

    def read(self, p):
        """The staged panel value (post-:meth:`wait`)."""
        return self.panel[self.buf(p)]

    # -- the staging plan (pure index arithmetic) -------------------------
    #
    # With depth d, a chunk's panel offsets split into two responsibility
    # ranges, covering every offset exactly once:
    #
    # - ``lead_range``: offsets 0 .. min(d-1, n_i)-1, staged AHEAD of
    #   the chunk — at the warm-up site for the schedule's first chunk,
    #   and at the previous chunk's boundary body (post-certification)
    #   for every later chunk;
    # - in-chunk: at panel offset ``i``'s wait point, stage offset
    #   ``i + d - 1`` when it is still inside the chunk (a traced
    #   predicate the kernel emits: ``i + d - 1 < n_i``). Offsets below
    #   d-1 never match (i >= 0), so the ranges cannot double-stage.
    #
    # Buffer safety: offset q's buffer (q % d) was last used by global
    # panel q - d, whose compute completed strictly before either
    # staging site runs (grid bodies are sequential, and the boundary
    # body stages only d-1 ahead — never the buffer of a panel still
    # computing).

    def lead_range(self, n_i: int) -> range:
        """Panel offsets a chunk needs staged ahead of its first wait
        (see the plan above)."""
        if self.depth == 1:
            return range(0)
        return range(min(self.depth - 1, max(n_i, 1)))


def stream_plan(total: int, depth: int):
    """Staging schedule of a depth-``depth`` block stream over ``total``
    blocks, as pure host data (the plan :func:`stream_scoped` executes).

    Returns ``(lead, stages)``:

    - ``lead``: block indices staged BEFORE the stream loop (the cold
      lead loads — ``PanelStager.lead_range`` specialized to a stream
      whose source needs no arrival certification);
    - ``stages``: per step ``t`` of the loop, the tuple of block
      indices whose staging DMA is issued at ``t``'s prefetch site
      (right after block ``t``'s wait). ``depth == 1`` degenerates to
      stage-and-wait: block ``t`` is staged at step ``t`` itself.

    Invariants the unit tests pin down (and the kernels rely on):
    every block in ``range(total)`` is staged exactly once, and block
    ``q``'s buffer (``q % depth``) is never restaged before step
    ``q - depth``'s compute finished (the prefetch site of ``q`` is
    step ``q - depth + 1``, strictly after).
    """
    if total < 0:
        raise ValueError(f"total must be >= 0, got {total}")
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    if depth == 1:
        return (), tuple((t,) for t in range(total))
    lead = tuple(range(min(depth - 1, total)))
    stages = tuple(
        ((t + depth - 1,) if t + depth - 1 < total else ())
        for t in range(total))
    return lead, stages


def stream_scoped(*, total: int, depth: int, buffers: dict,
                  start: Callable, body: Callable) -> None:
    """Depth-buffered block stream over scoped VMEM — the
    buffer-parity/semaphore core of the pipelined ``ag_gemm`` variant
    (and the generalization the ``paged_flash_decode`` per-parity page
    prefetch hand-rolls at depth 2).

    Allocates, inside ``pl.run_scoped`` (so the buffers live only for
    this grid body), one ``(depth,) + shape`` VMEM rotating buffer and
    one ``(depth,)`` DMA-semaphore array per named stream, wraps each
    in a :class:`PanelStager`, and drives the staging plan of
    :func:`stream_plan`: lead blocks staged cold, then per step ``t``
    wait block ``t`` on every stream, issue block ``t + depth - 1``'s
    prefetch behind it, and hand the resident blocks to ``body``.

    ``buffers``: ordered ``{name: (block_shape, dtype)}``.
    ``start(t, stagers)``: issue block ``t``'s staging copies — call
    ``stagers[name].start(src_ref, t)`` for every stream (the caller
    owns source selection, e.g. ``pl.when`` branching between a local
    input and a ring workspace). ``t`` may be traced.
    ``body(t, stagers)``: consume block ``t`` via
    ``stagers[name].read(t)`` — every stream's block ``t`` is resident.

    Scoped scratch is per-body: all DMAs started here complete before
    the scope closes (the final waits), so nothing leaks across grid
    bodies — which is exactly why the source's *arrival* (ring chunk
    certification) must be handled by the caller before the stream
    runs (``choose_depth(chunk_len=None)`` is the matching depth
    resolver).
    """
    if total <= 0:
        return
    names = list(buffers)

    def scoped(*refs):
        stagers = {name: PanelStager(refs[2 * ix], refs[2 * ix + 1], depth)
                   for ix, name in enumerate(names)}

        def wait(t):
            for name in names:
                stagers[name].wait(t)

        if depth > 1:
            for t, _ in zip(range(depth - 1), range(total)):
                start(jnp.int32(t), stagers)

        def step(t, carry):
            if depth == 1:
                start(t, stagers)
            wait(t)
            if depth > 1:
                @pl.when(t + (depth - 1) < total)
                def _():
                    start(t + (depth - 1), stagers)
            body(t, stagers)
            return carry

        jax.lax.fori_loop(0, total, step, 0)

    scratch = []
    for name in names:
        shape, dtype = buffers[name]
        scratch.append(pltpu.VMEM((depth,) + tuple(shape), dtype))
        scratch.append(pltpu.SemaphoreType.DMA((depth,)))
    pl.run_scoped(scoped, *scratch)


def drain_sends(send_sem, ref, slots: Sequence[int]) -> None:
    """Consume one send credit per slot before kernel exit (a comm
    kernel must not retire with outstanding DMA semaphores)."""
    for s in slots:
        dl.wait_arrivals(send_sem.at[s], ref, 1)
