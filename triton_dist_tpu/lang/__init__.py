"""In-kernel communication primitives ("the language layer").

TPU-native analogue of ``triton_dist.language`` (reference:
``python/triton_dist/language/distributed_ops.py`` — wait/consume_token/
rank/num_ranks/symm_at/notify — and ``language/extra/libshmem_device.py``,
the portable SHMEM device API). Here the primitives are Pallas/Mosaic
operations: one-sided puts are ICI/DCN remote DMAs, signal words are
hardware semaphores, and waits are semaphore waits — no spin loops on HBM.
"""

# The whole libshmem_device-parity surface (gated by __all__ there;
# tests/test_shmem.py asserts one-to-one reference-name coverage).
from triton_dist_tpu.lang.shmem_device import *  # noqa: F401,F403
from triton_dist_tpu.lang.teams import (  # noqa: F401
    Team,
    team_world,
    team_axis,
)
from triton_dist_tpu.lang.pallas_helpers import (  # noqa: F401
    core_call,
    comm_compiler_params,
    next_collective_id,
)
# Shared overlap engine (rank-swizzled schedules, prefetch-depth panel
# staging, coalesced per-chunk signalling) — consumed by the fused-op
# family. Imported last: it builds on shmem_device.
from triton_dist_tpu.lang import overlap  # noqa: F401
