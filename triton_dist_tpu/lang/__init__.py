"""In-kernel communication primitives ("the language layer").

TPU-native analogue of ``triton_dist.language`` (reference:
``python/triton_dist/language/distributed_ops.py`` — wait/consume_token/
rank/num_ranks/symm_at/notify — and ``language/extra/libshmem_device.py``,
the portable SHMEM device API). Here the primitives are Pallas/Mosaic
operations: one-sided puts are ICI/DCN remote DMAs, signal words are
hardware semaphores, and waits are semaphore waits — no spin loops on HBM.
"""

from triton_dist_tpu.lang.shmem_device import (  # noqa: F401
    rank,
    num_ranks,
    my_pe,
    n_pes,
    remote_put,
    putmem_block,
    putmem_signal_block,
    putmem_signal_nbi_block,
    putmem_nbi_block,
    putmem_warp,
    putmem_wave,
    putmem_wg,
    getmem_block,
    getmem_nbi_block,
    getmem_warp,
    getmem_wave,
    getmem_wg,
    broadcastmem,
    fcollect,
    amo_add,
    signal_op,
    notify,
    wait,
    wait_arrivals,
    signal_wait_until,
    consume_token,
    barrier_all,
    barrier_tile,
    local_copy,
    local_copy_async,
    fence,
    quiet,
    SIGNAL_SET,
    SIGNAL_ADD,
)
from triton_dist_tpu.lang.teams import (  # noqa: F401
    Team,
    team_world,
    team_axis,
)
from triton_dist_tpu.lang.pallas_helpers import (  # noqa: F401
    core_call,
    comm_compiler_params,
    next_collective_id,
)
