"""Device-side one-sided communication primitives for Pallas TPU kernels.

Semantics map (reference → here):

- ``dl.rank()/num_ranks()`` (``language/distributed_ops.py:84,90``)
  → :func:`rank` / :func:`num_ranks` over a named mesh axis.
- ``libshmem_device.putmem_block(dst, src, nbytes, pe)``
  (``language/extra/libshmem_device.py:~120``) → :func:`putmem_block` —
  an async remote DMA; completion is a *semaphore*, not a flag word.
- ``libshmem_device.putmem_signal_block(..., sig_ptr, sig_val, SIGNAL_SET, pe)``
  → :func:`putmem_signal_block` — remote DMA plus a remote semaphore
  signal the consumer waits on.
- ``dl.notify(ptr, rank, signal=v, comm_scope=...)``
  (``distributed_ops.py:103``) → :func:`notify` — remote semaphore signal.
- ``dl.wait(barrierPtrs, N, scope, semantic)`` (``distributed_ops.py:57``)
  → :func:`wait` — semaphore wait. TPU semaphores are counting, so the
  reference's ``signal_wait_until(CMP_EQ, value)`` value-compare protocol
  becomes a count protocol: producers ``inc`` by 1, consumers wait for a
  target count (SURVEY.md §7 "hard parts" — phase/parity re-design).
- ``dl.consume_token`` → :func:`consume_token` (no-op: Mosaic orders
  memory through semaphore waits; kept for API parity).
- ``libshmem_device.barrier_all()`` → :func:`barrier_all`.

All functions must be called inside a Pallas kernel traced under
``shard_map`` (they use ``jax.lax.axis_index``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.parallel.mesh import logical_device_id

SIGNAL_SET = "set"   # reference: SignalOp::SET (DistributedAttrDefs.td:36)
SIGNAL_ADD = "add"   # reference: SignalOp::ADD


# ---------------------------------------------------------------------------
# Rank queries
# ---------------------------------------------------------------------------

def rank(axis: str):
    """This device's rank along ``axis`` (reference: dl.rank())."""
    return jax.lax.axis_index(axis)


def num_ranks(axis: str) -> int:
    """Static size of ``axis`` (reference: dl.num_ranks())."""
    return jax.lax.axis_size(axis)


# SHMEM-flavoured aliases (reference: libshmem_device.my_pe/n_pes)
my_pe = rank
n_pes = num_ranks


def _resolve_device_id(ctx, axis: str, peer):
    """Logical device id of ``peer`` along ``axis`` given a MeshContext."""
    if ctx is None:
        # Single-axis mesh: the peer rank is the logical id.
        return peer
    return logical_device_id(ctx.axes, axis, peer, ctx.sizes)


# ---------------------------------------------------------------------------
# One-sided puts / gets
# ---------------------------------------------------------------------------

def remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, *, axis: str,
               ctx=None, start: bool = True):
    """One-sided put: copy ``src_ref`` into ``dst_ref`` on device ``peer``
    (rank along ``axis``). Returns the DMA handle; caller may ``.wait()``
    the send side, the remote side waits its ``recv_sem``.

    Reference: ``libshmem_device.putmem_nbi_block`` lowered to NVSHMEM
    (``NVIDIA/DistributedOpToLLVM.cpp:94-154``); here it is a single
    Mosaic ``make_async_remote_copy`` riding ICI (or DCN across slices).
    """
    copy = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=_resolve_device_id(ctx, axis, peer),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    if start:
        copy.start()
    return copy


def putmem_block(dst_ref, src_ref, peer, send_sem, recv_sem, *, axis: str,
                 ctx=None):
    """SHMEM-argument-order alias of :func:`remote_put` (dst first)."""
    return remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, axis=axis,
                      ctx=ctx)


def putmem_signal_block(dst_ref, src_ref, sig_sem, peer, send_sem, recv_sem,
                        *, axis: str, ctx=None, sig_inc: int = 1):
    """Put + remote user-semaphore signal.

    ORDERING CAVEAT (differs from NVSHMEM putmem_signal): the remote
    ``sig_sem`` signal is issued after the local send drains
    (``wait_send``) and may overtake the bulk data in flight. Only the
    DMA's own ``recv_sem`` certifies data arrival on the destination —
    consumers must wait ``recv_sem`` before reading ``dst_ref`` and use
    ``sig_sem`` purely for application-level sequencing (tile counters
    etc.). The fused ops in this package follow that discipline.

    Reference: ``libshmem_device.putmem_signal_block`` / ``_nbi``.
    """
    copy = remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, axis=axis,
                      ctx=ctx)
    copy.wait_send()
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=sig_inc)
    return copy


def getmem_block(dst_ref, src_ref, peer, send_sem, recv_sem, *, axis: str,
                 ctx=None):
    """One-sided get: fetch ``src_ref`` from ``peer`` into local ``dst_ref``.

    TPU remote DMA is push-only, so a get is expressed as a remote-issued
    put in the SPMD program: every device issues the symmetric put that
    realises its peers' gets. For the common symmetric patterns
    (all-gather pull schedules) this is what the collective kernels do;
    a true single-sided get is emulated with a request/response semaphore
    pair. Provided for API parity with ``libshmem_device.getmem_block``.
    """
    raise NotImplementedError(
        "TPU RDMA is push-only; restructure as symmetric puts "
        "(see ops/collectives) or use p2p request/response (ops/p2p).")


# ---------------------------------------------------------------------------
# Signal / wait
# ---------------------------------------------------------------------------

def notify(sem, peer=None, *, axis: Optional[str] = None, ctx=None,
           inc: int = 1):
    """Signal a semaphore, optionally on a remote device.

    Reference: ``dl.notify`` (``distributed_ops.py:103``) — release-store /
    ``signal_op`` by CommScope (``NVIDIA/DistributedOpToLLVM.cpp:243-353``).
    Local signal: ``notify(sem)``. Remote: ``notify(sem, peer, axis="tp")``.
    """
    if peer is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        pltpu.semaphore_signal(
            sem, inc=inc,
            device_id=_resolve_device_id(ctx, axis, peer),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )


def signal_op(sig_sem, signal, sig_op: str, peer, *, axis: str, ctx=None):
    """Reference ``libshmem_device.signal_op(ptr, val, SIGNAL_*, pe)``.

    TPU semaphores are counting: ADD maps to an increment; SET-to-value
    protocols must be re-expressed as counts (the collective kernels use
    monotonically increasing per-tile counts instead of set-flags).
    """
    if sig_op != SIGNAL_ADD:
        raise NotImplementedError(
            "SIGNAL_SET has no TPU analogue; use counting (SIGNAL_ADD) "
            "protocols — see ops/collectives for the patterns.")
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=signal)


def wait(sem, value: int = 1):
    """Block until ``sem``'s count reaches ``value``; decrements by
    ``value`` (TPU semaphore-wait semantics).

    Reference: ``dl.wait(barrierPtrs, numBarriers, scope, semantic)``
    (``distributed_ops.py:57``) — the PTX acquire spin loop
    (``DistributedOpToLLVM.cpp:156-229``) becomes a hardware semaphore
    wait: no SM/core spinning, the scalar unit sleeps until count.
    """
    pltpu.semaphore_wait(sem, value)


def signal_wait_until(sem, cmp: str, value: int):
    """Reference ``libshmem_device.signal_wait_until(ptr, CMP_EQ, val)``.

    Only >=-then-consume (counting) semantics exist on TPU; CMP_EQ with
    monotone counters is equivalent to waiting for the count."""
    if cmp not in ("eq", "ge"):
        raise NotImplementedError(f"cmp {cmp!r} not expressible on TPU")
    pltpu.semaphore_wait(sem, value)


def wait_arrivals(sem, ref, count: int = 1):
    """Wait for ``count`` DMA deliveries of ``ref``'s size on a *DMA*
    semaphore. TPU DMA semaphores count transfer units, so an aggregate
    arrival wait is expressed as ``count`` descriptor waits of the common
    chunk shape (``count`` must be static).

    This is the consumer half of the reference's per-tile
    ``signal_wait_until`` on flag words (``distributed_ops.py:57``).
    """
    for _ in range(count):
        pltpu.make_async_copy(ref, ref, sem).wait()


def consume_token(value, token=None):
    """API-parity no-op (reference ``dl.consume_token``,
    ``distributed_ops.py:74``): Mosaic already orders reads after the
    semaphore waits that guard them."""
    return value


# ---------------------------------------------------------------------------
# Barriers
# ---------------------------------------------------------------------------

def barrier_all(axis: str, *, ctx=None):
    """Barrier over all devices along ``axis``.

    Full-mesh signal + wait on the global barrier semaphore — the
    analogue of ``libshmem_device.barrier_all`` / the reference's
    ``barrier_all_intra_node_*`` kernels (``kernels/nvidia/common_ops.py``).
    Requires ``collective_id`` in the kernel's CompilerParams.
    """
    n = num_ranks(axis)
    sem = pltpu.get_barrier_semaphore()
    for peer in range(n):
        pltpu.semaphore_signal(
            sem, inc=1,
            device_id=_resolve_device_id(ctx, axis, peer),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(sem, n)


def barrier_tile(axis: str, *, ctx=None, sem=None):
    """Neighbour-pair barrier (cheaper than :func:`barrier_all`): signal
    both ring neighbours, wait for both.

    Uses the *global* barrier semaphore (keyed by the kernel's
    ``collective_id``) by default: unlike scratch semaphores it is safe
    against skewed kernel entry — a fast peer's signal cannot alias into
    whatever kernel this device is still running.
    """
    if sem is None:
        sem = pltpu.get_barrier_semaphore()
    n = num_ranks(axis)
    me = rank(axis)
    left = jax.lax.rem(me + n - 1, n)
    right = jax.lax.rem(me + 1, n)
    notify(sem, left, axis=axis, ctx=ctx)
    notify(sem, right, axis=axis, ctx=ctx)
    wait(sem, 2)


# ---------------------------------------------------------------------------
# Local copies (HBM<->VMEM staging helpers)
# ---------------------------------------------------------------------------

def local_copy(src_ref, dst_ref):
    """Synchronous local DMA (for ANY/HBM-space refs)."""
    pltpu.sync_copy(src_ref, dst_ref)


def local_copy_async(src_ref, dst_ref, sem, *, start: bool = True):
    copy = pltpu.make_async_copy(src_ref, dst_ref, sem)
    if start:
        copy.start()
    return copy
