"""Device-side one-sided communication primitives for Pallas TPU kernels.

Semantics map (reference → here):

- ``dl.rank()/num_ranks()`` (``language/distributed_ops.py:84,90``)
  → :func:`rank` / :func:`num_ranks` over a named mesh axis.
- ``libshmem_device.putmem_block(dst, src, nbytes, pe)``
  (``language/extra/libshmem_device.py:~120``) → :func:`putmem_block` —
  an async remote DMA; completion is a *semaphore*, not a flag word.
- ``libshmem_device.putmem_signal_block(..., sig_ptr, sig_val, SIGNAL_SET, pe)``
  → :func:`putmem_signal_block` — remote DMA plus a remote semaphore
  signal the consumer waits on.
- ``dl.notify(ptr, rank, signal=v, comm_scope=...)``
  (``distributed_ops.py:103``) → :func:`notify` — remote semaphore signal.
- ``dl.wait(barrierPtrs, N, scope, semantic)`` (``distributed_ops.py:57``)
  → :func:`wait` — semaphore wait. TPU semaphores are counting, so the
  reference's ``signal_wait_until(CMP_EQ, value)`` value-compare protocol
  becomes a count protocol: producers ``inc`` by 1, consumers wait for a
  target count (SURVEY.md §7 "hard parts" — phase/parity re-design).
- ``dl.consume_token`` → :func:`consume_token` (no-op: Mosaic orders
  memory through semaphore waits; kept for API parity).
- ``libshmem_device.barrier_all()`` → :func:`barrier_all`.

All functions must be called inside a Pallas kernel traced under
``shard_map`` (they use ``jax.lax.axis_index``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.parallel.mesh import logical_device_id

SIGNAL_SET = "set"   # reference: SignalOp::SET (DistributedAttrDefs.td:36)
SIGNAL_ADD = "add"   # reference: SignalOp::ADD


def _barriers_vacuous() -> bool:
    """True when kernel-entry barriers have no meaning (and no
    implementation): the old generic discharge interpreter runs the
    mesh bulk-synchronously and has no rule for
    ``get_barrier_semaphore`` — see ``utils/compat.py``."""
    from triton_dist_tpu.utils import compat

    return compat.degraded_interpret()

# The full public surface (tests/test_shmem.py asserts this covers the
# reference's ~80-name libshmem_device API one-to-one).
__all__ = [
    "SIGNAL_SET", "SIGNAL_ADD",
    "rank", "num_ranks", "my_pe", "n_pes",
    "remote_put",
    "putmem", "putmem_block", "putmem_warp", "putmem_wave", "putmem_wg",
    "putmem_nbi", "putmem_nbi_block", "putmem_nbi_warp",
    "putmem_nbi_wave", "putmem_nbi_wg",
    "putmem_rma", "putmem_rma_block", "putmem_rma_warp",
    "putmem_rma_nbi", "putmem_rma_nbi_block", "putmem_rma_nbi_warp",
    "putmem_signal", "putmem_signal_block", "putmem_signal_warp",
    "putmem_signal_wave", "putmem_signal_wg",
    "putmem_signal_nbi", "putmem_signal_nbi_block",
    "putmem_signal_nbi_warp", "putmem_signal_nbi_wave",
    "putmem_signal_nbi_wg",
    "putmem_signal_rma", "putmem_signal_rma_block",
    "putmem_signal_rma_warp", "putmem_signal_rma_nbi",
    "putmem_signal_rma_nbi_block", "putmem_signal_rma_nbi_warp",
    "ulong_put_signal", "int_p",
    "getmem", "getmem_block", "getmem_warp", "getmem_wave", "getmem_wg",
    "getmem_nbi", "getmem_nbi_block", "getmem_nbi_warp",
    "getmem_nbi_wave", "getmem_nbi_wg",
    "broadcast", "broadcast_block", "broadcast_warp",
    "broadcastmem", "broadcastmem_block", "broadcastmem_warp",
    "fcollect", "fcollect_block", "fcollect_warp",
    "fcollectmem", "fcollectmem_block", "fcollectmem_warp",
    "amo_add", "fence", "quiet", "quiet_pe",
    "notify", "signal_op", "wait", "signal_wait_until",
    "uint64_wait_until_equals", "wait_arrivals", "consume_token",
    "barrier", "barrier_block", "barrier_warp",
    "barrier_all", "barrier_all_block", "barrier_all_vec",
    "barrier_all_warp", "barrier_all_wave", "barrier_all_wg",
    "barrier_tile",
    "sync_all", "sync_all_block", "sync_all_warp",
    "team_sync_block", "team_sync_warp",
    "team_my_pe", "team_n_pes", "team_translate_pe",
    "local_copy", "local_copy_async",
    "remote_ptr", "remote_mc_ptr", "set_rocshmem_ctx",
]


# ---------------------------------------------------------------------------
# Rank queries
# ---------------------------------------------------------------------------

def rank(axis: str):
    """This device's rank along ``axis`` (reference: dl.rank())."""
    return jax.lax.axis_index(axis)


def num_ranks(axis: str) -> int:
    """Static size of ``axis`` (reference: dl.num_ranks())."""
    return jax.lax.axis_size(axis)


# SHMEM-flavoured aliases (reference: libshmem_device.my_pe/n_pes)
my_pe = rank
n_pes = num_ranks


def _resolve_device_id(ctx, axis: str, peer):
    """Logical device id of ``peer`` along ``axis`` given a MeshContext."""
    if ctx is None:
        # Single-axis mesh: the peer rank is the logical id.
        return peer
    return logical_device_id(ctx.axes, axis, peer, ctx.sizes)


# ---------------------------------------------------------------------------
# One-sided puts / gets
# ---------------------------------------------------------------------------

def remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, *, axis: str,
               ctx=None, start: bool = True):
    """One-sided put: copy ``src_ref`` into ``dst_ref`` on device ``peer``
    (rank along ``axis``). Returns the DMA handle; caller may ``.wait()``
    the send side, the remote side waits its ``recv_sem``.

    Reference: ``libshmem_device.putmem_nbi_block`` lowered to NVSHMEM
    (``NVIDIA/DistributedOpToLLVM.cpp:94-154``); here it is a single
    Mosaic ``make_async_remote_copy`` riding ICI (or DCN across slices).

    Fault-injection hook (``resilience.faults``): inside an active
    plan's op scope a put may be delayed (a dependent-FLOP spin folded
    into the device id on the target rank), dropped, or duplicated —
    the adversarial schedules the signal protocols must tolerate or
    detect. Free when no plan is active.
    """
    from triton_dist_tpu.resilience import faults

    fault = faults.put_fault() if start else None
    device_id = _resolve_device_id(ctx, axis, peer)
    if fault is not None and fault.kind == "delay_dma" and fault.iters:
        # The spin's result feeds the DMA descriptor, so it cannot be
        # dead-code-eliminated; it costs iters dependent FLOPs on
        # fault.rank and nothing elsewhere.
        device_id = device_id + faults.rank_spin_zero(
            axis, fault.rank, fault.iters)
    copy = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=device_id,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    if start:
        if fault is not None and fault.kind == "drop_put":
            @pl.when(jax.lax.axis_index(axis) != fault.rank)
            def _():
                copy.start()
        elif fault is not None and fault.kind == "dup_put":
            copy.start()

            @pl.when(jax.lax.axis_index(axis) == fault.rank)
            def _():
                copy.start()   # second descriptor bind = duplicate DMA
        else:
            copy.start()
    return copy


def putmem_block(dst_ref, src_ref, peer, send_sem, recv_sem, *, axis: str,
                 ctx=None):
    """SHMEM-argument-order alias of :func:`remote_put` (dst first)."""
    return remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, axis=axis,
                      ctx=ctx)


def putmem_signal_block(dst_ref, src_ref, sig_sem, peer, send_sem, recv_sem,
                        *, axis: str, ctx=None, sig_inc: int = 1):
    """Put + remote user-semaphore signal.

    ORDERING CAVEAT (differs from NVSHMEM putmem_signal): the remote
    ``sig_sem`` signal is issued after the local send drains
    (``wait_send``) and may overtake the bulk data in flight. Only the
    DMA's own ``recv_sem`` certifies data arrival on the destination —
    consumers must wait ``recv_sem`` before reading ``dst_ref`` and use
    ``sig_sem`` purely for application-level sequencing (tile counters
    etc.). The fused ops in this package follow that discipline.

    The returned handle's send side is ALREADY drained — do not pass it
    to :func:`fence`/:func:`quiet` again. TPU semaphore waits consume
    counts (unlike NVSHMEM quiet, which is idempotent), so a second
    drain blocks forever.

    Reference: ``libshmem_device.putmem_signal_block`` / ``_nbi``.
    """
    copy = remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, axis=axis,
                      ctx=ctx)
    copy.wait_send()
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=sig_inc)
    return copy


def getmem_block(dst_ref, src_ref, peer, requester, send_sem, recv_sem, *,
                 axis: str, ctx=None):
    """One-sided get in SPMD lockstep form: fetch ``peer``'s ``src_ref``
    into my ``dst_ref`` (reference ``libshmem_device.getmem_block``).

    TPU remote DMA is push-only, so the get is realised by the data
    owner. In an SPMD kernel every rank executes the same get, making
    the access pattern a rank permutation: I pull from ``peer``, and by
    symmetry ``requester`` — the rank with ``peer(requester) == me`` —
    pulls from me (for a shift ``peer = (me+off) % n`` that is
    ``requester = (me-off) % n``). This call issues the put that
    realises the *requester's* get (my ``src_ref`` → the requester's
    ``dst_ref``, symmetric address); my own ``dst_ref`` is filled by my
    peer's matching put. Consume the result with
    ``wait_arrivals(recv_sem, dst_ref, 1)`` — the reference's blocking
    get maps to put + arrival wait. The full-mesh *pull* allgather
    schedule (``low_latency_allgather.py``) is this pattern n-1 times.
    """
    return remote_put(src_ref, dst_ref, send_sem, recv_sem, requester,
                      axis=axis, ctx=ctx)


# ---------------------------------------------------------------------------
# Granularity / nbi tiers of the put-get surface
#
# The reference's libshmem_device multiplies every transfer op by a
# thread-granularity suffix (_block/_warp/_wave/_wg — which SIMT lanes
# participate, ``libshmem_device.py:~120-320``) and an _nbi (non-
# blocking) tier. A TPU core drives ONE DMA engine — there are no
# sub-core lanes to scope a transfer to — so every granularity maps to
# the same whole-core async DMA, and *all* puts here are already nbi
# (completion is the semaphore, not the call). The aliases keep the
# reference surface addressable one-to-one.
# ---------------------------------------------------------------------------

putmem = putmem_block
putmem_nbi = putmem_block
putmem_nbi_block = putmem_block
putmem_nbi_warp = putmem_block
putmem_nbi_wave = putmem_block
putmem_nbi_wg = putmem_block
putmem_warp = putmem_block
putmem_wave = putmem_block
putmem_wg = putmem_block
getmem = getmem_block
getmem_nbi = getmem_block
getmem_nbi_block = getmem_block
getmem_nbi_warp = getmem_block
getmem_nbi_wave = getmem_block
getmem_nbi_wg = getmem_block
getmem_warp = getmem_block
getmem_wave = getmem_block
getmem_wg = getmem_block

# The reference's _rma tier pins transfers to the proxy/RMA engine
# (IBGDA vs P2P copy, ``libshmem_device.py`` putmem_rma*). TPU exposes
# exactly one remote-DMA path — the ICI/DCN DMA engine — so the RMA
# tier IS the normal put.
putmem_rma = putmem_block
putmem_rma_block = putmem_block
putmem_rma_warp = putmem_block
putmem_rma_nbi = putmem_block
putmem_rma_nbi_block = putmem_block
putmem_rma_nbi_warp = putmem_block


def putmem_signal_nbi_block(dst_ref, src_ref, sig_sem, peer, send_sem,
                            recv_sem, *, axis: str, ctx=None,
                            sig_inc: int = 1):
    """Non-blocking put+signal: the signal is issued WITHOUT draining
    the send side first, so it may overtake the bulk data in flight
    (stronger caveat than :func:`putmem_signal_block`, same as the
    reference's ``putmem_signal_nbi`` ordering). Consumers must wait
    the DMA's own ``recv_sem`` before reading; ``sig_sem`` is
    application-level sequencing only."""
    copy = remote_put(src_ref, dst_ref, send_sem, recv_sem, peer,
                      axis=axis, ctx=ctx)
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=sig_inc)
    return copy


# put+signal granularity/rma tiers (same collapse as the puts above).
putmem_signal = putmem_signal_block
putmem_signal_warp = putmem_signal_block
putmem_signal_wave = putmem_signal_block
putmem_signal_wg = putmem_signal_block
putmem_signal_rma = putmem_signal_block
putmem_signal_rma_block = putmem_signal_block
putmem_signal_rma_warp = putmem_signal_block
putmem_signal_nbi = putmem_signal_nbi_block
putmem_signal_nbi_warp = putmem_signal_nbi_block
putmem_signal_nbi_wave = putmem_signal_nbi_block
putmem_signal_nbi_wg = putmem_signal_nbi_block
putmem_signal_rma_nbi = putmem_signal_nbi_block
putmem_signal_rma_nbi_block = putmem_signal_nbi_block
putmem_signal_rma_nbi_warp = putmem_signal_nbi_block
def ulong_put_signal(dst_ref, value, staging_ref, sig_sem, peer,
                     send_sem, recv_sem, *, axis: str, ctx=None,
                     sig_inc: int = 1):
    """Word-sized put of an immediate + remote signal (reference
    ``libshmem_device.ulong_put_signal(ptr, value, sig, ...)``).

    Like :func:`int_p`, TPU DMA sources from memory: the immediate is
    staged through the caller's 1-element ``staging_ref`` and shipped
    as a normal put+signal (same ordering caveats as
    :func:`putmem_signal_block`)."""
    staging_ref[...] = jnp.full_like(staging_ref[...], value)
    return putmem_signal_block(dst_ref, staging_ref, sig_sem, peer,
                               send_sem, recv_sem, axis=axis, ctx=ctx,
                               sig_inc=sig_inc)


def int_p(dst_ref, value, staging_ref, peer, send_sem, recv_sem, *,
          axis: str, ctx=None):
    """Single-word put of an immediate (reference
    ``libshmem_device.int_p(ptr, value, pe)``).

    TPU DMA sources from memory, not immediates, so the caller provides
    a 1-element ``staging_ref`` (SMEM/VMEM scratch); the value is
    stored there and shipped with the normal remote DMA. Arrival is the
    destination's ``recv_sem`` — there is no raced flag-word store.
    """
    staging_ref[...] = jnp.full_like(staging_ref[...], value)
    return remote_put(staging_ref, dst_ref, send_sem, recv_sem, peer,
                      axis=axis, ctx=ctx)


# ---------------------------------------------------------------------------
# In-kernel team collectives (broadcast / fcollect)
# ---------------------------------------------------------------------------

def broadcastmem(dst_ref, src_ref, root: int, send_sem, recv_sem, *,
                 axis: str, ctx=None, barrier: bool = True):
    """In-kernel broadcast: the root pushes ``src_ref`` into every
    peer's ``dst_ref``; non-roots block until arrival. Completes fully
    before returning on every rank (reference
    ``libshmem_device.broadcast[mem]``; ``root`` is a static int,
    matching the reference's PE_root argument).

    By default an internal :func:`barrier_all` precedes the puts: the
    scratch recv semaphore is only safe once every target has entered
    the kernel (the skewed-entry hazard — see :func:`barrier_tile`'s
    caveat). Pass ``barrier=False`` ONLY if the caller already ran a
    full barrier over ``axis`` in this kernel."""
    me = rank(axis)
    n = num_ranks(axis)
    if _barriers_vacuous():
        # Generic discharge interpreter: the root-only put below is a
        # rank-DIVERGENT site, and divergent sites deadlock the hidden
        # collectives that interpreter resolves remote DMA with. Use a
        # uniform ring relay instead: every rank forwards its dst right
        # each step, with the root re-seeding its dst from src first
        # (the incoming left-neighbour value would otherwise erase the
        # payload and the relay would carry a single moving wave instead
        # of a growing prefix). After n-1 steps every rank holds the
        # root's payload. Semantics are bulk-synchronous there (every
        # DMA site is a barrier), so no waits.
        right = jax.lax.rem(me + 1, n)
        for _step in range(n - 1):
            @pl.when(me == root)
            def _():
                pltpu.sync_copy(src_ref, dst_ref)
            remote_put(dst_ref, dst_ref, send_sem, recv_sem, right,
                       axis=axis, ctx=ctx)

        @pl.when(me == root)
        def _():
            pltpu.sync_copy(src_ref, dst_ref)
        return
    if barrier:
        barrier_all(axis, ctx=ctx)

    @pl.when(me == root)
    def _():
        pltpu.sync_copy(src_ref, dst_ref)
        for off in range(1, n):
            peer = jax.lax.rem(root + off, n)
            remote_put(src_ref, dst_ref, send_sem, recv_sem, peer,
                       axis=axis, ctx=ctx)
        for _ in range(n - 1):
            pltpu.make_async_copy(src_ref, src_ref, send_sem).wait()

    @pl.when(me != root)
    def _():
        wait_arrivals(recv_sem, dst_ref, 1)


def fcollect(dst_ref, src_ref, send_sem, recv_sem, *, axis: str,
             ctx=None, barrier: bool = True):
    """In-kernel all-gather ("flat collect"): every rank pushes its
    ``src_ref`` into slot ``me`` of every peer's ``dst_ref``
    ((n, *src.shape)); returns with all n slots valid on every rank
    (reference ``libshmem_device.fcollect[mem]`` — the full-mesh push
    form, the same schedule as ``ops/allgather.py`` mode
    "full_mesh" but usable mid-kernel on arbitrary refs).

    Like that schedule, a full :func:`barrier_all` precedes the puts by
    default — full-mesh traffic on scratch semaphores is unsafe under
    skewed kernel entry (only the collective-id-keyed barrier semaphore
    tolerates skew). ``barrier=False`` only after the caller's own full
    barrier over ``axis``."""
    me = rank(axis)
    n = num_ranks(axis)
    if barrier:
        barrier_all(axis, ctx=ctx)
    pltpu.sync_copy(src_ref, dst_ref.at[me])
    for off in range(1, n):
        peer = jax.lax.rem(me + off, n)
        remote_put(src_ref, dst_ref.at[me], send_sem, recv_sem, peer,
                   axis=axis, ctx=ctx)
    for _ in range(n - 1):
        pltpu.make_async_copy(src_ref, src_ref, send_sem).wait()
    wait_arrivals(recv_sem, dst_ref.at[0], n - 1)


# Typed-value and granularity tiers of broadcast/fcollect: Pallas refs
# are typed (there is no separate bytes-vs-elements form), and one DMA
# engine per core collapses the thread tiers — so the reference's
# broadcast/broadcastmem x {,_block,_warp} six-way split is one
# function each.
broadcast = broadcastmem
broadcast_block = broadcastmem
broadcast_warp = broadcastmem
broadcastmem_block = broadcastmem
broadcastmem_warp = broadcastmem
fcollect_block = fcollect
fcollect_warp = fcollect
fcollectmem = fcollect
fcollectmem_block = fcollect
fcollectmem_warp = fcollect


# ---------------------------------------------------------------------------
# AMO (atomic memory operations)
#
# The reference exposes remote word atomics (atomic_fetch_add / set /
# compare_swap, ``libshmem_device.py`` AMO constants). TPU has no
# remote atomics on arbitrary HBM words; the hardware's atomic
# primitive is the COUNTING SEMAPHORE, so add-style AMO protocols map
# to remote semaphore increments (amo_add below == signal_op ADD) and
# fetch/compare styles must be re-designed around counts
# (docs/primitives.md). This is the documented semantic delta, not an
# emulation.
# ---------------------------------------------------------------------------

def amo_add(sem, value: int, peer, *, axis: str, ctx=None):
    """Remote add on a semaphore "word" (the TPU AMO analogue)."""
    notify(sem, peer, axis=axis, ctx=ctx, inc=value)


# ---------------------------------------------------------------------------
# Memory ordering (fence / quiet)
# ---------------------------------------------------------------------------

def fence(*copies):
    """Local ordering of my outstanding puts (reference
    ``libshmem_device.fence`` :176). Drains the given handles' send
    semaphores: my source buffers are reusable and the payloads are
    committed to the interconnect in order.

    WEAKER THAN NVSHMEM fence: send-drain does NOT order *remote
    delivery* — a subsequent :func:`notify` can still overtake the bulk
    data in flight (same caveat as :func:`putmem_signal_block`). Remote
    arrival is only certified on the receiver by its ``recv_sem`` wait;
    there is no sender-side primitive for it on TPU.
    """
    for c in copies:
        c.wait_send()


def quiet(*copies):
    """Local completion of my outstanding puts (reference
    ``libshmem_device.quiet`` :166): after return, every given handle's
    send side has drained — source buffers are safe to overwrite.

    WEAKER THAN NVSHMEM quiet, which certifies remote completion: on
    TPU only the *receiver* can certify arrival (its ``recv_sem``).
    Do not follow quiet with a raced flag signal — consumers must wait
    the DMA's own recv semaphore before reading the destination.

    NOT idempotent (also unlike NVSHMEM): each handle's send side can
    be drained exactly once — by quiet/fence, ``copy.wait()``, or a
    put+signal helper's internal drain — a second wait consumes counts
    that never come.
    """
    for c in copies:
        c.wait_send()


def quiet_pe(peer, *copies):
    """Per-PE quiet (reference ``libshmem_device.quiet_pe``): TPU DMA
    handles are already per-transfer, so draining the handles aimed at
    ``peer`` IS the per-PE form — the caller passes exactly those."""
    del peer
    quiet(*copies)


# ---------------------------------------------------------------------------
# Signal / wait
# ---------------------------------------------------------------------------

def notify(sem, peer=None, *, axis: Optional[str] = None, ctx=None,
           inc: int = 1):
    """Signal a semaphore, optionally on a remote device.

    Reference: ``dl.notify`` (``distributed_ops.py:103``) — release-store /
    ``signal_op`` by CommScope (``NVIDIA/DistributedOpToLLVM.cpp:243-353``).
    Local signal: ``notify(sem)``. Remote: ``notify(sem, peer, axis="tp")``.

    Fault-injection hook: an active drop_signal/dup_signal fault zeroes
    or doubles the increment on the target rank (uniformly traced — the
    site executes on every rank, only the increment diverges).
    """
    if axis is not None:
        from triton_dist_tpu.resilience import faults

        fault = faults.signal_fault()
        if fault is not None:
            me = jax.lax.axis_index(axis)
            scale = 0 if fault.kind == "drop_signal" else 2
            inc = jnp.where(me == fault.rank, scale * inc,
                            inc).astype(jnp.int32)
    if peer is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        pltpu.semaphore_signal(
            sem, inc=inc,
            device_id=_resolve_device_id(ctx, axis, peer),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )


def signal_op(sig_sem, signal, sig_op: str, peer, *, axis: str, ctx=None):
    """Reference ``libshmem_device.signal_op(ptr, val, SIGNAL_*, pe)``.

    TPU semaphores are counting: ADD maps to an increment; SET-to-value
    protocols must be re-expressed as counts (the collective kernels use
    monotonically increasing per-tile counts instead of set-flags).
    """
    if sig_op != SIGNAL_ADD:
        raise NotImplementedError(
            "SIGNAL_SET has no TPU analogue; use counting (SIGNAL_ADD) "
            "protocols — see ops/collectives for the patterns.")
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=signal)


def wait(sem, value: int = 1):
    """Block until ``sem``'s count reaches ``value``; decrements by
    ``value`` (TPU semaphore-wait semantics).

    Reference: ``dl.wait(barrierPtrs, numBarriers, scope, semantic)``
    (``distributed_ops.py:57``) — the PTX acquire spin loop
    (``DistributedOpToLLVM.cpp:156-229``) becomes a hardware semaphore
    wait: no SM/core spinning, the scalar unit sleeps until count.
    """
    pltpu.semaphore_wait(sem, value)


def signal_wait_until(sem, cmp: str, value: int):
    """Reference ``libshmem_device.signal_wait_until(ptr, CMP_EQ, val)``.

    Only >=-then-consume (counting) semantics exist on TPU; CMP_EQ with
    monotone counters is equivalent to waiting for the count."""
    if cmp not in ("eq", "ge"):
        raise NotImplementedError(f"cmp {cmp!r} not expressible on TPU")
    pltpu.semaphore_wait(sem, value)


def uint64_wait_until_equals(sem, value: int):
    """Reference ``libshmem_device.uint64_wait_until_equals(ptr, val)``
    — the word is a counting semaphore here (see
    :func:`signal_wait_until` for the count-protocol mapping)."""
    signal_wait_until(sem, "eq", value)


def wait_arrivals(sem, ref, count: int = 1):
    """Wait for ``count`` DMA deliveries of ``ref``'s size on a *DMA*
    semaphore. TPU DMA semaphores count transfer units, so an aggregate
    arrival wait is expressed as ``count`` descriptor waits of the common
    chunk shape (``count`` must be static).

    This is the consumer half of the reference's per-tile
    ``signal_wait_until`` on flag words (``distributed_ops.py:57``).
    """
    for _ in range(count):
        pltpu.make_async_copy(ref, ref, sem).wait()


def consume_token(value, token=None):
    """API-parity no-op (reference ``dl.consume_token``,
    ``distributed_ops.py:74``): Mosaic already orders reads after the
    semaphore waits that guard them."""
    return value


# ---------------------------------------------------------------------------
# Barriers
# ---------------------------------------------------------------------------

def barrier_all(axis: str, *, ctx=None):
    """Barrier over all devices along ``axis``.

    Full-mesh signal + wait on the global barrier semaphore — the
    analogue of ``libshmem_device.barrier_all`` / the reference's
    ``barrier_all_intra_node_*`` kernels (``kernels/nvidia/common_ops.py``).
    Requires ``collective_id`` in the kernel's CompilerParams.
    """
    if _barriers_vacuous():
        return
    n = num_ranks(axis)
    inc = _skewed_barrier_inc(axis)
    sem = pltpu.get_barrier_semaphore()
    for peer in range(n):
        pltpu.semaphore_signal(
            sem, inc=inc if peer == 0 else 1,
            device_id=_resolve_device_id(ctx, axis, peer),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(sem, n)


def _skewed_barrier_inc(axis: str):
    """Barrier-signal increment carrying an injected arrival skew: a
    skew_barrier fault spins the target rank before its first signal
    (the spin result rides the increment so it cannot be DCE'd; the
    increment stays exactly 1)."""
    from triton_dist_tpu.resilience import faults

    fault = faults.barrier_fault()
    if fault is None or not fault.iters:
        return 1
    return 1 + faults.rank_spin_zero(axis, fault.rank, fault.iters)


def barrier_tile(axis: str, *, ctx=None, sem=None):
    """Neighbour-pair barrier (cheaper than :func:`barrier_all`): signal
    both ring neighbours, wait for both.

    Uses the *global* barrier semaphore (keyed by the kernel's
    ``collective_id``) by default: unlike scratch semaphores it is safe
    against skewed kernel entry — a fast peer's signal cannot alias into
    whatever kernel this device is still running.
    """
    if sem is None:
        if _barriers_vacuous():
            return
        sem = pltpu.get_barrier_semaphore()
    n = num_ranks(axis)
    me = rank(axis)
    left = jax.lax.rem(me + n - 1, n)
    right = jax.lax.rem(me + 1, n)
    notify(sem, left, axis=axis, ctx=ctx, inc=_skewed_barrier_inc(axis))
    notify(sem, right, axis=axis, ctx=ctx)
    wait(sem, 2)


def barrier(team):
    """Barrier over a :class:`~triton_dist_tpu.lang.teams.Team`
    (reference ``libshmem_device.barrier(team)`` :126): every team PE
    signals every other and waits for the full team count on the
    collective-id-keyed barrier semaphore.

    NVSHMEM's ``barrier`` implies quiet (outstanding puts complete);
    here put completion is certified per-DMA by the receiver's
    ``recv_sem`` — this barrier orders *kernel progress* only, which
    makes it the same operation as :func:`sync_all` scoped to a team
    (the delta :func:`quiet` documents).
    """
    if _barriers_vacuous():
        return
    sem = pltpu.get_barrier_semaphore()
    n = team.n_pes()
    for pe in range(n):
        pltpu.semaphore_signal(
            sem, inc=1,
            device_id=team.device_id(pe),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(sem, n)


# Granularity tiers (one core drives the kernel — see the put tiers).
barrier_block = barrier
barrier_warp = barrier
barrier_all_block = barrier_all
barrier_all_vec = barrier_all
barrier_all_warp = barrier_all
barrier_all_wave = barrier_all
barrier_all_wg = barrier_all

# NVSHMEM splits barrier_all (quiet + sync) from sync_all (sync only).
# On TPU put completion is the receiver's recv_sem, never a sender-side
# global drain, so the split collapses: barrier_all IS sync-only, and
# sync_all is the same function (documented in barrier()/quiet()).
sync_all = barrier_all
sync_all_block = barrier_all
sync_all_warp = barrier_all

# Team sync tiers: barrier(team) is already sync-only (see above).
team_sync_block = barrier
team_sync_warp = barrier


# ---------------------------------------------------------------------------
# Team queries — function forms of lang.teams.Team's methods, matching
# the reference's flat-function surface (``team_my_pe`` :69,
# ``team_n_pes`` :74, ``team_translate_pe`` :475).
# ---------------------------------------------------------------------------

def team_my_pe(team):
    return team.my_pe()


def team_n_pes(team) -> int:
    return team.n_pes()


def team_translate_pe(src_team, pe, dest_team):
    return src_team.translate_pe(pe, dest_team)


# ---------------------------------------------------------------------------
# Local copies (HBM<->VMEM staging helpers)
# ---------------------------------------------------------------------------

def local_copy(src_ref, dst_ref):
    """Synchronous local DMA (for ANY/HBM-space refs)."""
    pltpu.sync_copy(src_ref, dst_ref)


def local_copy_async(src_ref, dst_ref, sem, *, start: bool = True):
    copy = pltpu.make_async_copy(src_ref, dst_ref, sem)
    if start:
        copy.start()
    return copy


# ---------------------------------------------------------------------------
# Documented platform impossibilities.
#
# These reference symbols expose raw device pointers or vendor-runtime
# state; Pallas has no device-pointer type — remote addressing is the
# DMA descriptor's ``device_id`` — so they cannot exist on TPU. They
# raise (rather than being absent) so reference-surface callers get the
# redesign pointer instead of an AttributeError.
# ---------------------------------------------------------------------------

def remote_ptr(local_ref, peer):
    """Reference ``libshmem_device.remote_ptr(ptr, pe)``: translate a
    symmetric address to a peer's raw pointer for direct ld/st. No TPU
    analogue — remote memory is reached only through DMA descriptors
    (:func:`remote_put`) and semaphore signals (:func:`notify`)."""
    raise NotImplementedError(
        "TPU has no raw remote pointers; address peers via remote_put/"
        "notify device_id (docs/primitives.md)")


def remote_mc_ptr(team, local_ref):
    """Reference ``libshmem_device.remote_mc_ptr`` (NVLS multicast
    pointer): no ICI analogue — multimem stores do not exist; one-shot
    multicast is expressed as the full-mesh push schedule
    (:func:`fcollect`, ``ops/allreduce.py`` one-shot)."""
    raise NotImplementedError(
        "no ICI multicast pointer; use the full-mesh push schedules "
        "(fcollect / ops.allreduce one-shot)")


def set_rocshmem_ctx(ctx):
    """Reference ``libshmem_device.set_rocshmem_ctx`` (ROCSHMEM device
    context registration): vendor-runtime state with no TPU counterpart
    — Mosaic kernels carry their communication identity in
    ``collective_id`` CompilerParams (``lang/pallas_helpers.py``)."""
    raise NotImplementedError(
        "no device SHMEM context on TPU; collective identity is the "
        "kernel's collective_id (lang/pallas_helpers.core_call)")
