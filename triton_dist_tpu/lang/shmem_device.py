"""Device-side one-sided communication primitives for Pallas TPU kernels.

Semantics map (reference → here):

- ``dl.rank()/num_ranks()`` (``language/distributed_ops.py:84,90``)
  → :func:`rank` / :func:`num_ranks` over a named mesh axis.
- ``libshmem_device.putmem_block(dst, src, nbytes, pe)``
  (``language/extra/libshmem_device.py:~120``) → :func:`putmem_block` —
  an async remote DMA; completion is a *semaphore*, not a flag word.
- ``libshmem_device.putmem_signal_block(..., sig_ptr, sig_val, SIGNAL_SET, pe)``
  → :func:`putmem_signal_block` — remote DMA plus a remote semaphore
  signal the consumer waits on.
- ``dl.notify(ptr, rank, signal=v, comm_scope=...)``
  (``distributed_ops.py:103``) → :func:`notify` — remote semaphore signal.
- ``dl.wait(barrierPtrs, N, scope, semantic)`` (``distributed_ops.py:57``)
  → :func:`wait` — semaphore wait. TPU semaphores are counting, so the
  reference's ``signal_wait_until(CMP_EQ, value)`` value-compare protocol
  becomes a count protocol: producers ``inc`` by 1, consumers wait for a
  target count (SURVEY.md §7 "hard parts" — phase/parity re-design).
- ``dl.consume_token`` → :func:`consume_token` (no-op: Mosaic orders
  memory through semaphore waits; kept for API parity).
- ``libshmem_device.barrier_all()`` → :func:`barrier_all`.

All functions must be called inside a Pallas kernel traced under
``shard_map`` (they use ``jax.lax.axis_index``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.parallel.mesh import logical_device_id

SIGNAL_SET = "set"   # reference: SignalOp::SET (DistributedAttrDefs.td:36)
SIGNAL_ADD = "add"   # reference: SignalOp::ADD


# ---------------------------------------------------------------------------
# Rank queries
# ---------------------------------------------------------------------------

def rank(axis: str):
    """This device's rank along ``axis`` (reference: dl.rank())."""
    return jax.lax.axis_index(axis)


def num_ranks(axis: str) -> int:
    """Static size of ``axis`` (reference: dl.num_ranks())."""
    return jax.lax.axis_size(axis)


# SHMEM-flavoured aliases (reference: libshmem_device.my_pe/n_pes)
my_pe = rank
n_pes = num_ranks


def _resolve_device_id(ctx, axis: str, peer):
    """Logical device id of ``peer`` along ``axis`` given a MeshContext."""
    if ctx is None:
        # Single-axis mesh: the peer rank is the logical id.
        return peer
    return logical_device_id(ctx.axes, axis, peer, ctx.sizes)


# ---------------------------------------------------------------------------
# One-sided puts / gets
# ---------------------------------------------------------------------------

def remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, *, axis: str,
               ctx=None, start: bool = True):
    """One-sided put: copy ``src_ref`` into ``dst_ref`` on device ``peer``
    (rank along ``axis``). Returns the DMA handle; caller may ``.wait()``
    the send side, the remote side waits its ``recv_sem``.

    Reference: ``libshmem_device.putmem_nbi_block`` lowered to NVSHMEM
    (``NVIDIA/DistributedOpToLLVM.cpp:94-154``); here it is a single
    Mosaic ``make_async_remote_copy`` riding ICI (or DCN across slices).
    """
    copy = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=_resolve_device_id(ctx, axis, peer),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    if start:
        copy.start()
    return copy


def putmem_block(dst_ref, src_ref, peer, send_sem, recv_sem, *, axis: str,
                 ctx=None):
    """SHMEM-argument-order alias of :func:`remote_put` (dst first)."""
    return remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, axis=axis,
                      ctx=ctx)


def putmem_signal_block(dst_ref, src_ref, sig_sem, peer, send_sem, recv_sem,
                        *, axis: str, ctx=None, sig_inc: int = 1):
    """Put + remote user-semaphore signal.

    ORDERING CAVEAT (differs from NVSHMEM putmem_signal): the remote
    ``sig_sem`` signal is issued after the local send drains
    (``wait_send``) and may overtake the bulk data in flight. Only the
    DMA's own ``recv_sem`` certifies data arrival on the destination —
    consumers must wait ``recv_sem`` before reading ``dst_ref`` and use
    ``sig_sem`` purely for application-level sequencing (tile counters
    etc.). The fused ops in this package follow that discipline.

    Reference: ``libshmem_device.putmem_signal_block`` / ``_nbi``.
    """
    copy = remote_put(src_ref, dst_ref, send_sem, recv_sem, peer, axis=axis,
                      ctx=ctx)
    copy.wait_send()
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=sig_inc)
    return copy


def getmem_block(dst_ref, src_ref, peer, requester, send_sem, recv_sem, *,
                 axis: str, ctx=None):
    """One-sided get in SPMD lockstep form: fetch ``peer``'s ``src_ref``
    into my ``dst_ref`` (reference ``libshmem_device.getmem_block``).

    TPU remote DMA is push-only, so the get is realised by the data
    owner. In an SPMD kernel every rank executes the same get, making
    the access pattern a rank permutation: I pull from ``peer``, and by
    symmetry ``requester`` — the rank with ``peer(requester) == me`` —
    pulls from me (for a shift ``peer = (me+off) % n`` that is
    ``requester = (me-off) % n``). This call issues the put that
    realises the *requester's* get (my ``src_ref`` → the requester's
    ``dst_ref``, symmetric address); my own ``dst_ref`` is filled by my
    peer's matching put. Consume the result with
    ``wait_arrivals(recv_sem, dst_ref, 1)`` — the reference's blocking
    get maps to put + arrival wait. The full-mesh *pull* allgather
    schedule (``low_latency_allgather.py``) is this pattern n-1 times.
    """
    return remote_put(src_ref, dst_ref, send_sem, recv_sem, requester,
                      axis=axis, ctx=ctx)


# ---------------------------------------------------------------------------
# Granularity / nbi tiers of the put-get surface
#
# The reference's libshmem_device multiplies every transfer op by a
# thread-granularity suffix (_block/_warp/_wave/_wg — which SIMT lanes
# participate, ``libshmem_device.py:~120-320``) and an _nbi (non-
# blocking) tier. A TPU core drives ONE DMA engine — there are no
# sub-core lanes to scope a transfer to — so every granularity maps to
# the same whole-core async DMA, and *all* puts here are already nbi
# (completion is the semaphore, not the call). The aliases keep the
# reference surface addressable one-to-one.
# ---------------------------------------------------------------------------

putmem_nbi_block = putmem_block
putmem_warp = putmem_block
putmem_wave = putmem_block
putmem_wg = putmem_block
getmem_nbi_block = getmem_block
getmem_warp = getmem_block
getmem_wave = getmem_block
getmem_wg = getmem_block


def putmem_signal_nbi_block(dst_ref, src_ref, sig_sem, peer, send_sem,
                            recv_sem, *, axis: str, ctx=None,
                            sig_inc: int = 1):
    """Non-blocking put+signal: the signal is issued WITHOUT draining
    the send side first, so it may overtake the bulk data in flight
    (stronger caveat than :func:`putmem_signal_block`, same as the
    reference's ``putmem_signal_nbi`` ordering). Consumers must wait
    the DMA's own ``recv_sem`` before reading; ``sig_sem`` is
    application-level sequencing only."""
    copy = remote_put(src_ref, dst_ref, send_sem, recv_sem, peer,
                      axis=axis, ctx=ctx)
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=sig_inc)
    return copy


# ---------------------------------------------------------------------------
# In-kernel team collectives (broadcast / fcollect)
# ---------------------------------------------------------------------------

def broadcastmem(dst_ref, src_ref, root: int, send_sem, recv_sem, *,
                 axis: str, ctx=None, barrier: bool = True):
    """In-kernel broadcast: the root pushes ``src_ref`` into every
    peer's ``dst_ref``; non-roots block until arrival. Completes fully
    before returning on every rank (reference
    ``libshmem_device.broadcast[mem]``; ``root`` is a static int,
    matching the reference's PE_root argument).

    By default an internal :func:`barrier_all` precedes the puts: the
    scratch recv semaphore is only safe once every target has entered
    the kernel (the skewed-entry hazard — see :func:`barrier_tile`'s
    caveat). Pass ``barrier=False`` ONLY if the caller already ran a
    full barrier over ``axis`` in this kernel."""
    me = rank(axis)
    n = num_ranks(axis)
    if barrier:
        barrier_all(axis, ctx=ctx)

    @pl.when(me == root)
    def _():
        pltpu.sync_copy(src_ref, dst_ref)
        for off in range(1, n):
            peer = jax.lax.rem(root + off, n)
            remote_put(src_ref, dst_ref, send_sem, recv_sem, peer,
                       axis=axis, ctx=ctx)
        for _ in range(n - 1):
            pltpu.make_async_copy(src_ref, src_ref, send_sem).wait()

    @pl.when(me != root)
    def _():
        wait_arrivals(recv_sem, dst_ref, 1)


def fcollect(dst_ref, src_ref, send_sem, recv_sem, *, axis: str,
             ctx=None, barrier: bool = True):
    """In-kernel all-gather ("flat collect"): every rank pushes its
    ``src_ref`` into slot ``me`` of every peer's ``dst_ref``
    ((n, *src.shape)); returns with all n slots valid on every rank
    (reference ``libshmem_device.fcollect[mem]`` — the full-mesh push
    form, the same schedule as ``ops/allgather.py`` mode
    "full_mesh" but usable mid-kernel on arbitrary refs).

    Like that schedule, a full :func:`barrier_all` precedes the puts by
    default — full-mesh traffic on scratch semaphores is unsafe under
    skewed kernel entry (only the collective-id-keyed barrier semaphore
    tolerates skew). ``barrier=False`` only after the caller's own full
    barrier over ``axis``."""
    me = rank(axis)
    n = num_ranks(axis)
    if barrier:
        barrier_all(axis, ctx=ctx)
    pltpu.sync_copy(src_ref, dst_ref.at[me])
    for off in range(1, n):
        peer = jax.lax.rem(me + off, n)
        remote_put(src_ref, dst_ref.at[me], send_sem, recv_sem, peer,
                   axis=axis, ctx=ctx)
    for _ in range(n - 1):
        pltpu.make_async_copy(src_ref, src_ref, send_sem).wait()
    wait_arrivals(recv_sem, dst_ref.at[0], n - 1)


# ---------------------------------------------------------------------------
# AMO (atomic memory operations)
#
# The reference exposes remote word atomics (atomic_fetch_add / set /
# compare_swap, ``libshmem_device.py`` AMO constants). TPU has no
# remote atomics on arbitrary HBM words; the hardware's atomic
# primitive is the COUNTING SEMAPHORE, so add-style AMO protocols map
# to remote semaphore increments (amo_add below == signal_op ADD) and
# fetch/compare styles must be re-designed around counts
# (docs/primitives.md). This is the documented semantic delta, not an
# emulation.
# ---------------------------------------------------------------------------

def amo_add(sem, value: int, peer, *, axis: str, ctx=None):
    """Remote add on a semaphore "word" (the TPU AMO analogue)."""
    notify(sem, peer, axis=axis, ctx=ctx, inc=value)


# ---------------------------------------------------------------------------
# Memory ordering (fence / quiet)
# ---------------------------------------------------------------------------

def fence(*copies):
    """Local ordering of my outstanding puts (reference
    ``libshmem_device.fence`` :176). Drains the given handles' send
    semaphores: my source buffers are reusable and the payloads are
    committed to the interconnect in order.

    WEAKER THAN NVSHMEM fence: send-drain does NOT order *remote
    delivery* — a subsequent :func:`notify` can still overtake the bulk
    data in flight (same caveat as :func:`putmem_signal_block`). Remote
    arrival is only certified on the receiver by its ``recv_sem`` wait;
    there is no sender-side primitive for it on TPU.
    """
    for c in copies:
        c.wait_send()


def quiet(*copies):
    """Local completion of my outstanding puts (reference
    ``libshmem_device.quiet`` :166): after return, every given handle's
    send side has drained — source buffers are safe to overwrite.

    WEAKER THAN NVSHMEM quiet, which certifies remote completion: on
    TPU only the *receiver* can certify arrival (its ``recv_sem``).
    Do not follow quiet with a raced flag signal — consumers must wait
    the DMA's own recv semaphore before reading the destination.
    """
    for c in copies:
        c.wait_send()


# ---------------------------------------------------------------------------
# Signal / wait
# ---------------------------------------------------------------------------

def notify(sem, peer=None, *, axis: Optional[str] = None, ctx=None,
           inc: int = 1):
    """Signal a semaphore, optionally on a remote device.

    Reference: ``dl.notify`` (``distributed_ops.py:103``) — release-store /
    ``signal_op`` by CommScope (``NVIDIA/DistributedOpToLLVM.cpp:243-353``).
    Local signal: ``notify(sem)``. Remote: ``notify(sem, peer, axis="tp")``.
    """
    if peer is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        pltpu.semaphore_signal(
            sem, inc=inc,
            device_id=_resolve_device_id(ctx, axis, peer),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )


def signal_op(sig_sem, signal, sig_op: str, peer, *, axis: str, ctx=None):
    """Reference ``libshmem_device.signal_op(ptr, val, SIGNAL_*, pe)``.

    TPU semaphores are counting: ADD maps to an increment; SET-to-value
    protocols must be re-expressed as counts (the collective kernels use
    monotonically increasing per-tile counts instead of set-flags).
    """
    if sig_op != SIGNAL_ADD:
        raise NotImplementedError(
            "SIGNAL_SET has no TPU analogue; use counting (SIGNAL_ADD) "
            "protocols — see ops/collectives for the patterns.")
    notify(sig_sem, peer, axis=axis, ctx=ctx, inc=signal)


def wait(sem, value: int = 1):
    """Block until ``sem``'s count reaches ``value``; decrements by
    ``value`` (TPU semaphore-wait semantics).

    Reference: ``dl.wait(barrierPtrs, numBarriers, scope, semantic)``
    (``distributed_ops.py:57``) — the PTX acquire spin loop
    (``DistributedOpToLLVM.cpp:156-229``) becomes a hardware semaphore
    wait: no SM/core spinning, the scalar unit sleeps until count.
    """
    pltpu.semaphore_wait(sem, value)


def signal_wait_until(sem, cmp: str, value: int):
    """Reference ``libshmem_device.signal_wait_until(ptr, CMP_EQ, val)``.

    Only >=-then-consume (counting) semantics exist on TPU; CMP_EQ with
    monotone counters is equivalent to waiting for the count."""
    if cmp not in ("eq", "ge"):
        raise NotImplementedError(f"cmp {cmp!r} not expressible on TPU")
    pltpu.semaphore_wait(sem, value)


def wait_arrivals(sem, ref, count: int = 1):
    """Wait for ``count`` DMA deliveries of ``ref``'s size on a *DMA*
    semaphore. TPU DMA semaphores count transfer units, so an aggregate
    arrival wait is expressed as ``count`` descriptor waits of the common
    chunk shape (``count`` must be static).

    This is the consumer half of the reference's per-tile
    ``signal_wait_until`` on flag words (``distributed_ops.py:57``).
    """
    for _ in range(count):
        pltpu.make_async_copy(ref, ref, sem).wait()


def consume_token(value, token=None):
    """API-parity no-op (reference ``dl.consume_token``,
    ``distributed_ops.py:74``): Mosaic already orders reads after the
    semaphore waits that guard them."""
    return value


# ---------------------------------------------------------------------------
# Barriers
# ---------------------------------------------------------------------------

def barrier_all(axis: str, *, ctx=None):
    """Barrier over all devices along ``axis``.

    Full-mesh signal + wait on the global barrier semaphore — the
    analogue of ``libshmem_device.barrier_all`` / the reference's
    ``barrier_all_intra_node_*`` kernels (``kernels/nvidia/common_ops.py``).
    Requires ``collective_id`` in the kernel's CompilerParams.
    """
    n = num_ranks(axis)
    sem = pltpu.get_barrier_semaphore()
    for peer in range(n):
        pltpu.semaphore_signal(
            sem, inc=1,
            device_id=_resolve_device_id(ctx, axis, peer),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(sem, n)


def barrier_tile(axis: str, *, ctx=None, sem=None):
    """Neighbour-pair barrier (cheaper than :func:`barrier_all`): signal
    both ring neighbours, wait for both.

    Uses the *global* barrier semaphore (keyed by the kernel's
    ``collective_id``) by default: unlike scratch semaphores it is safe
    against skewed kernel entry — a fast peer's signal cannot alias into
    whatever kernel this device is still running.
    """
    if sem is None:
        sem = pltpu.get_barrier_semaphore()
    n = num_ranks(axis)
    me = rank(axis)
    left = jax.lax.rem(me + n - 1, n)
    right = jax.lax.rem(me + 1, n)
    notify(sem, left, axis=axis, ctx=ctx)
    notify(sem, right, axis=axis, ctx=ctx)
    wait(sem, 2)


# ---------------------------------------------------------------------------
# Local copies (HBM<->VMEM staging helpers)
# ---------------------------------------------------------------------------

def local_copy(src_ref, dst_ref):
    """Synchronous local DMA (for ANY/HBM-space refs)."""
    pltpu.sync_copy(src_ref, dst_ref)


def local_copy_async(src_ref, dst_ref, sem, *, start: bool = True):
    copy = pltpu.make_async_copy(src_ref, dst_ref, sem)
    if start:
        copy.start()
    return copy
