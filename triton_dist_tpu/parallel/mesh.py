"""Device-mesh conventions and rank addressing.

The reference is SPMD-one-process-per-GPU with rank arithmetic done by hand
in every kernel (``rank``/``num_ranks``/``local_world_size``; see
``python/triton_dist/language/distributed_ops.py:84-96``). The TPU-native
design centralises this: a :class:`jax.sharding.Mesh` with canonical axis
names, and :class:`MeshContext` resolving per-axis ranks to the *logical
device ids* that Pallas remote DMA (``pltpu.make_async_remote_copy``) and
``pltpu.semaphore_signal`` take.

Canonical axis order (outer → inner): ``dp, pp, ep, sp, tp``. Innermost
axes map to the fastest ICI loops; ``tp`` traffic rides nearest-neighbour
links. Inter-slice (DCN) axes should be outermost — the analogue of the
reference's ``CommScope.INTRA_NODE``/``INTER_NODE`` split
(``DistributedAttrDefs.td:45``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")


def make_mesh(*, dp: int = 1, pp: int = 1, ep: int = 1, sp: int = 1,
              tp: int = 1, devices: Optional[Sequence[jax.Device]] = None,
              allow_split_physical_axes: bool = True) -> Mesh:
    """Build a mesh over the given (or all) devices with canonical axes.

    Axes of size 1 are still present so the same kernels address any
    configuration uniformly.
    """
    sizes = {"dp": dp, "pp": pp, "ep": ep, "sp": sp, "tp": tp}
    total = math.prod(sizes.values())
    explicit_devices = devices is not None
    if devices is None:
        devices = jax.devices()
    if total != len(devices):
        raise ValueError(
            f"mesh {sizes} needs {total} devices, have {len(devices)}")
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    if not explicit_devices and devices[0].platform in ("tpu", "axon"):
        # Topology-aware placement: inner axes land on ICI-adjacent chips.
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            shape, allow_split_physical_axes=allow_split_physical_axes)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def logical_device_id(mesh_axes: Sequence[str], axis: str, target_rank,
                      axis_sizes: Sequence[int]):
    """Linearized (row-major over ``mesh_axes``) logical device id of the
    device that has rank ``target_rank`` along ``axis`` and this device's
    coordinates along every other axis.

    Must be called inside a ``shard_map``-traced region (uses
    ``jax.lax.axis_index``). This is how a one-sided put targets "my TP
    peer r" on a multi-axis mesh — the analogue of NVSHMEM PE numbering
    (reference: ``language/extra/libshmem_device.py:50`` ``my_pe`` and
    the team-translate helpers).
    """
    device_id = 0
    for name, size in zip(mesh_axes, axis_sizes):
        idx = target_rank if name == axis else jax.lax.axis_index(name)
        device_id = device_id * size + idx
    return device_id


@dataclasses.dataclass(frozen=True)
class MeshContext:
    """Static description of the mesh as seen by a kernel.

    Carried by every op context (the analogue of the reference's
    ``rank/world_size/local_world_size`` triplet in e.g.
    ``AllGatherGEMMTensorParallelContext``,
    ``kernels/nvidia/allgather_gemm.py:449``).
    """

    axes: tuple  # tuple[str, ...] — mesh axis names, outer→inner
    sizes: tuple  # tuple[int, ...] — corresponding sizes

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "MeshContext":
        return cls(axes=tuple(mesh.axis_names),
                   sizes=tuple(mesh.shape[a] for a in mesh.axis_names))

    def size(self, axis: str) -> int:
        return self.sizes[self.axes.index(axis)]

    def rank(self, axis: str):
        """Traced: this device's rank along ``axis``."""
        return jax.lax.axis_index(axis)

    def device_id(self, axis: str, target_rank):
        """Traced: logical device id of ``target_rank`` along ``axis``."""
        return logical_device_id(self.axes, axis, target_rank, self.sizes)

    def spec(self, *names) -> P:
        """PartitionSpec helper: ``ctx.spec("tp", None)`` etc."""
        return P(*names)


def flat_axis_rank(axis):
    """(total size, my flat rank) over one axis name or an
    outer-major tuple of axis names — THE convention shared by
    ``P((outer, inner))`` shardings, ``EP2DContext`` expert ownership,
    and multi-slice cache layouts. Must be called inside shard_map.
    """
    import jax.numpy as jnp

    if isinstance(axis, (tuple, list)):
        n, me = 1, jnp.int32(0)
        for nm in tuple(axis):
            sz = jax.lax.axis_size(nm)
            n *= sz
            me = me * sz + jax.lax.axis_index(nm)
        return n, me
    return jax.lax.axis_size(axis), jax.lax.axis_index(axis)
