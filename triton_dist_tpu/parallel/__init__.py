from triton_dist_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    MeshContext,
    make_mesh,
    logical_device_id,
)
