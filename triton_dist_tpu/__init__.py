"""triton_dist_tpu — a TPU-native framework for computation–communication
overlapping kernels.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
ByteDance-Seed/Triton-distributed (see SURVEY.md at the repo root):

- one-sided tile-granular communication primitives over ICI/DCN remote DMA
  (``triton_dist_tpu.lang``) — the analogue of the reference's Distributed
  dialect + libshmem_device (reference: python/triton_dist/language/),
- a symmetric-workspace runtime over a ``shard_map`` mesh
  (``triton_dist_tpu.shmem``, reference: shmem/ + triton_dist/utils.py),
- fused overlapped operators: AllGather+GEMM, GEMM+ReduceScatter,
  GEMM+AllReduce, EP dispatch/combine, Ulysses and KV-allgather sequence
  parallelism, distributed flash-decode (``triton_dist_tpu.ops``,
  reference: python/triton_dist/kernels/),
- nn-style TP/EP/SP/PP layers (``triton_dist_tpu.layers``),
- Qwen3 dense/MoE models + an inference Engine (``triton_dist_tpu.models``),
- a distributed-aware autotuner with a persistent cache
  (``triton_dist_tpu.autotuner`` / ``triton_dist_tpu.tune``),
- an intra-kernel profiler with Perfetto export
  (``triton_dist_tpu.profiler``),
- a megakernel runtime executing a whole decode step as one persistent
  per-core Pallas kernel (``triton_dist_tpu.megakernel``).
"""

__version__ = "0.1.0"

# JAX-version compat shims must install before any submodule touches the
# aliased APIs (pallas_helpers evaluates pltpu.CompilerParams at def
# time). Additive-only: a no-op on current JAX.
from triton_dist_tpu.utils import compat as _compat  # noqa: E402

_compat.install()

from triton_dist_tpu.parallel.mesh import MeshContext, make_mesh  # noqa: F401
from triton_dist_tpu.utils.distributed import (  # noqa: F401
    dist_print,
    initialize_distributed,
    on_tpu,
    use_interpret,
)
