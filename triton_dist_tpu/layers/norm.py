"""RMSNorm (Qwen3 uses pre-norm RMSNorm everywhere, plus per-head q/k
norms; reference: the torch ops inside ``models/dense.py`` layers)."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    x = x * jnp.reciprocal(jnp.sqrt(var + eps))
    return (x * weight.astype(jnp.float32)).astype(dtype)
