"""Rotary position embeddings (Qwen3 NTK-free rope, half-rotation
layout as in HF transformers)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_freqs(head_dim: int, theta: float = 1_000_000.0):
    """Inverse frequencies, shape (head_dim // 2,)."""
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                            / head_dim))


def apply_rope(x, positions, inv_freq):
    """x: (..., seq, heads, head_dim); positions: (..., seq) int32."""
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # (..., S, hd/2)
    cos = jnp.cos(angles)[..., :, None, :]  # (..., S, 1, hd/2)
    sin = jnp.sin(angles)[..., :, None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
