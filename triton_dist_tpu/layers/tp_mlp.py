"""Tensor-parallel SwiGLU MLP.

Reference: ``layers/nvidia/tp_mlp.py:52`` ``TP_MLP`` — gate/up column-
parallel (fed by ag_gemm), down row-parallel (into gemm_rs), or
gemm_allreduce mode for small batches.

Sequence-parallel residual layout: activations enter and leave sharded
over tokens (dim 0) along the tp axis; ``fwd`` gathers tokens into the
column-parallel GEMMs and reduce-scatters back (the reference's
AG+GEMM → GEMM+RS sandwich, ``e2e_dense.md:21``).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import ag_gemm, gemm_rs, gemm_ar


def init(key, cfg, dtype=jnp.float32) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    d, ff = cfg.hidden_size, cfg.intermediate_size
    scale = d ** -0.5
    return {
        "w_gate": (jax.random.normal(k1, (d, ff), dtype) * scale),
        "w_up": (jax.random.normal(k2, (d, ff), dtype) * scale),
        "w_down": (jax.random.normal(k3, (ff, d), dtype) * (ff ** -0.5)),
    }


def param_specs(axis: str = "tp") -> Dict:
    return {
        "w_gate": P(None, axis),   # column-parallel
        "w_up": P(None, axis),
        "w_down": P(axis, None),   # row-parallel
    }


def fwd(params, x, *, mode: str = "xla", axis: str = "tp",
        ag_ctx=None, rs_ctx=None, ar_ctx=None):
    """x: (tokens_loc, d) sharded over tokens → same layout out.

    mode="fused_ar" takes/returns *replicated* tokens (decode path,
    reference ``GemmARLayer``).
    """
    if mode == "xla":
        x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        h = _swiglu(x_full, params["w_gate"], params["w_up"])
        partial = jnp.dot(h, params["w_down"],
                          preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(partial, axis, scatter_dimension=0,
                                    tiled=True).astype(x.dtype)
    if mode == "xla_ar":
        # Replicated tokens (decode): local partial + psum.
        h = _swiglu(x, params["w_gate"], params["w_up"])
        partial = jnp.dot(h, params["w_down"],
                          preferred_element_type=jnp.float32)
        return jax.lax.psum(partial, axis).astype(x.dtype)
    if mode == "fused":
        # One AG feeds both column GEMMs: reuse the gathered copy.
        g, x_full = ag_gemm(x, params["w_gate"], ag_ctx, return_ag=True)
        u = jnp.dot(x_full, params["w_up"],
                    preferred_element_type=jnp.float32).astype(x.dtype)
        h = (jax.nn.silu(g.astype(jnp.float32)) * u.astype(jnp.float32)
             ).astype(x.dtype)
        return gemm_rs(h, params["w_down"], rs_ctx)
    if mode == "fused_ar":
        h = _swiglu(x, params["w_gate"], params["w_up"])
        return gemm_ar(h, params["w_down"], ar_ctx)
    raise ValueError(f"unknown TP_MLP mode {mode!r}")


def _swiglu(x, w_gate, w_up):
    g = jnp.dot(x, w_gate, preferred_element_type=jnp.float32)
    u = jnp.dot(x, w_up, preferred_element_type=jnp.float32)
    return (jax.nn.silu(g) * u).astype(x.dtype)
