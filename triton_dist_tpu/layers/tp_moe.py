"""Tensor-parallel MoE layer (experts replicated, ffn dim sharded).

Reference: ``layers/nvidia/tp_moe.py:48`` ``TP_MoE`` — AG tokens →
grouped GEMM over the local ffn slice of every expert → weighted
combine → ReduceScatter (the AG-MoE / moe_reduce_rs pipeline,
``kernels/nvidia/allgather_group_gemm.py`` + ``moe_reduce_rs.py``).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers.ep_moe import init, route  # shared weights/router
from triton_dist_tpu.ops.group_gemm import sort_by_expert, grouped_swiglu


def param_specs(axis: str = "tp") -> Dict:
    return {
        "router": P(None, None),
        "w_gate": P(None, None, axis),  # ffn dim sharded
        "w_up": P(None, None, axis),
        "w_down": P(None, axis, None),
    }


def fwd(params, x, *, topk: int, num_experts: int, axis: str = "tp",
        norm_topk_prob: bool = True, mesh_ctx=None):
    """x: (tokens_loc, d) token-sharded along ``axis`` → same layout out.

    With ``mesh_ctx`` the epilogue runs the fused
    :func:`~triton_dist_tpu.ops.moe_reduce.moe_reduce_rs` kernel (the
    reference ``moe_reduce_rs.py`` pairing) instead of the XLA
    combine + ``psum_scatter`` round-trip."""
    x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
    t, d = x_full.shape
    topk_ids, topk_w = route(params["router"], x_full, topk,
                             norm_topk_prob=norm_topk_prob)

    # Replicate each token per selected expert, sort by expert, grouped
    # GEMM over the local ffn slice, then weighted un-sort.
    k = topk_ids.shape[1]
    flat_exp = topk_ids.reshape(-1)
    tok_rep = jnp.repeat(x_full, k, axis=0)
    sorted_tok, group_sizes, inv = sort_by_expert(tok_rep, flat_exp,
                                                  num_experts)
    out = grouped_swiglu(sorted_tok, params["w_gate"], params["w_up"],
                         params["w_down"], group_sizes)
    out = out[inv].reshape(t, k, d)
    if mesh_ctx is not None:
        from triton_dist_tpu.ops.moe_reduce import moe_reduce_rs

        # topk_w stays float32 — the kernel combines in f32 either way,
        # and downcasting first would diverge from the unfused path.
        return moe_reduce_rs(out, topk_w, ctx=mesh_ctx, axis=axis)
    partial = jnp.einsum("tkd,tk->td", out.astype(jnp.float32),
                         topk_w.astype(jnp.float32))
    return jax.lax.psum_scatter(partial, axis, scatter_dimension=0,
                                tiled=True).astype(x.dtype)
