"""Tensor-parallel MoE layer (experts replicated, ffn dim sharded).

Reference: ``layers/nvidia/tp_moe.py:48`` ``TP_MoE`` — AG tokens →
grouped GEMM over the local ffn slice of every expert → weighted
combine → ReduceScatter (the AG-MoE / moe_reduce_rs pipeline,
``kernels/nvidia/allgather_group_gemm.py`` + ``moe_reduce_rs.py``).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers.ep_moe import (  # shared weights/router
    init, route, shared_expert_out,
)
from triton_dist_tpu.ops.group_gemm import sort_by_expert, grouped_swiglu


def param_specs(axis: str = "tp", cfg=None) -> Dict:
    s = {
        "router": P(None, None),
        "w_gate": P(None, None, axis),  # ffn dim sharded
        "w_up": P(None, None, axis),
        "w_down": P(None, axis, None),
    }
    if cfg is not None and getattr(cfg, "shared_expert_intermediate_size",
                                   0):
        # Shared expert shards its ffn dim like tp_mlp; the scalar gate
        # vector is replicated so each rank's partial carries the same
        # sigmoid factor.
        s["w_shared_gate"] = P(None, axis)
        s["w_shared_up"] = P(None, axis)
        s["w_shared_down"] = P(axis, None)
        s["shared_gate"] = P(None)
    return s


def _expert_mlp(params, x, *, topk: int, num_experts: int,
                norm_topk_prob: bool):
    """Shared expert-compute core: route → replicate per selected
    expert → sort by expert → grouped SwiGLU over the local ffn slice →
    weighted un-sort. Returns ``(out (t, k, d), topk_w (t, k))`` —
    prefill (`fwd`) and decode (`fwd_ar`) differ only in the
    surrounding collectives."""
    t, d = x.shape
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    k = topk_ids.shape[1]
    flat_exp = topk_ids.reshape(-1)
    tok_rep = jnp.repeat(x, k, axis=0)
    sorted_tok, group_sizes, inv = sort_by_expert(tok_rep, flat_exp,
                                                  num_experts)
    out = grouped_swiglu(sorted_tok, params["w_gate"], params["w_up"],
                         params["w_down"], group_sizes)
    return out[inv].reshape(t, k, d), topk_w


def fwd(params, x, *, topk: int, num_experts: int, axis: str = "tp",
        norm_topk_prob: bool = True, mesh_ctx=None):
    """x: (tokens_loc, d) token-sharded along ``axis`` → same layout out.

    With ``mesh_ctx`` the epilogue runs the fused
    :func:`~triton_dist_tpu.ops.moe_reduce.moe_reduce_rs` kernel (the
    reference ``moe_reduce_rs.py`` pairing) instead of the XLA
    combine + ``psum_scatter`` round-trip."""
    x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
    out, topk_w = _expert_mlp(params, x_full, topk=topk,
                              num_experts=num_experts,
                              norm_topk_prob=norm_topk_prob)
    sh = shared_expert_out(params, x_full)   # TP partial (or None)
    if mesh_ctx is not None:
        from triton_dist_tpu.ops.moe_reduce import moe_reduce_rs

        # topk_w stays float32 — the kernel combines in f32 either way,
        # and downcasting first would diverge from the unfused path.
        if sh is not None:
            # Ride the fused combine as one more "expert" column with
            # weight 1 (the sigmoid gate is already folded in).
            out = jnp.concatenate(
                [out, sh.astype(out.dtype)[:, None]], axis=1)
            topk_w = jnp.concatenate(
                [topk_w, jnp.ones_like(topk_w[:, :1])], axis=1)
        return moe_reduce_rs(out, topk_w, ctx=mesh_ctx, axis=axis)
    partial = jnp.einsum("tkd,tk->td", out.astype(jnp.float32),
                         topk_w.astype(jnp.float32))
    if sh is not None:
        partial = partial + sh
    return jax.lax.psum_scatter(partial, axis, scatter_dimension=0,
                                tiled=True).astype(x.dtype)


def fwd_ar(params, x, *, topk: int, num_experts: int, axis: str = "tp",
           norm_topk_prob: bool = True):
    """Decode-path TP-MoE on a *replicated* batch (the GEMM+AR regime,
    reference ``gemm_allreduce_layer.py`` pairing for MoE): every rank
    routes the same rows, computes the grouped SwiGLU over its ffn
    shard, and the weighted combine is completed by one AllReduce.

    x: (b, d) identical on all ranks → (b, d) identical on all ranks.
    """
    out, topk_w = _expert_mlp(params, x, topk=topk,
                              num_experts=num_experts,
                              norm_topk_prob=norm_topk_prob)
    partial = jnp.einsum("tkd,tk->td", out.astype(jnp.float32),
                         topk_w.astype(jnp.float32))
    sh = shared_expert_out(params, x)       # TP partial: inside the sum
    if sh is not None:
        partial = partial + sh
    return jax.lax.psum(partial, axis).astype(x.dtype)


def fwd_fused(params, x, *, topk: int, num_experts: int, mesh_ctx,
              axis: str = "tp", block_m: int = 64, block_n: int = 256,
              block_k: int = 512, norm_topk_prob: bool = True,
              epilogue: str = "rs"):
    """Fully-fused TP-MoE forward: AG fused into the gate/up grouped
    GEMM (:func:`~triton_dist_tpu.ops.ag_moe.ag_group_gemm`), Pallas
    down-projection in the sorted layout, and a fused combine epilogue —
    the reference's ``allgather_group_gemm.py`` + ``moe_reduce_rs.py``
    (``epilogue="rs"``) / ``moe_reduce_ar.py`` (``epilogue="ar"``)
    pipeline. The *activation* tensors never ride an XLA collective;
    routing metadata (tile→expert maps, source indices, top-k weights —
    a few KB) still allgathers in XLA, and the un-sort back to flat
    token order is an XLA scatter-add.

    x: (T_loc, d) token-sharded along ``axis``. Returns (T_loc, d)
    token-sharded for ``"rs"``; the full replicated (n·T_loc, d) for
    ``"ar"`` (decode: every rank needs the activations).
    """
    from triton_dist_tpu.ops.ag_moe import (
        create_ag_moe_context, ag_group_gemm, prepare_grouped_tokens,
        suggested_block_m,
    )
    from triton_dist_tpu.ops.group_gemm import grouped_gemm_tiles
    from triton_dist_tpu.ops.moe_reduce import moe_reduce_ar, moe_reduce_rs

    if epilogue not in ("rs", "ar"):
        raise ValueError(f"unknown epilogue {epilogue!r} "
                         "(expected 'rs' or 'ar')")
    n = mesh_ctx.size(axis)
    t_loc, d = x.shape
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    # Cap the row tile for large-E configs so expert-segment padding
    # (E·(block_m-1) worst case) stays bounded by the real rows.
    block_m = suggested_block_m(t_loc, topk, num_experts, block_m)
    x_s, te, row_src = prepare_grouped_tokens(x, topk_ids, num_experts,
                                              block_m)
    s_loc = x_s.shape[0]

    w_gu = jnp.concatenate([params["w_gate"], params["w_up"]], axis=-1)
    f_loc = params["w_gate"].shape[-1]
    agctx = create_ag_moe_context(
        mesh_ctx, num_experts=num_experts, axis=axis, block_m=block_m,
        block_n=min(block_n, 2 * f_loc), block_k=min(block_k, d))
    # One gather serves both the AG-GEMM weight prefetch ((n, tiles)
    # layout) and the down-projection's global map (flat layout).
    te_all = jax.lax.all_gather(te, axis, axis=0)
    h = ag_group_gemm(x_s, w_gu, te, agctx, te_all=te_all)  # (S_full, 2F)
    g, u = h[:, :f_loc], h[:, f_loc:]
    act = (jax.nn.silu(g.astype(jnp.float32)) * u.astype(jnp.float32)
           ).astype(x.dtype)

    y_sorted = grouped_gemm_tiles(
        act, params["w_down"], te_all.reshape(-1),
        block_n=min(block_n, d), block_k=min(block_k, f_loc))

    # Un-sort the gathered rows to (T_full, K, d) flat order; padding
    # rows add zero into row 0.
    src_all = jax.lax.all_gather(row_src, axis, axis=0, tiled=True)
    chunk_base = jnp.repeat(
        jnp.arange(n, dtype=jnp.int32) * (t_loc * topk), s_loc)
    valid = src_all >= 0
    gsrc = jnp.where(valid, src_all + chunk_base, 0)
    y = jnp.zeros((n * t_loc * topk, d), y_sorted.dtype).at[gsrc].add(
        jnp.where(valid[:, None], y_sorted, 0))
    y = y.reshape(n * t_loc, topk, d)

    w_full = jax.lax.all_gather(topk_w, axis, axis=0, tiled=True)
    sh = shared_expert_out(
        params, jax.lax.all_gather(x, axis, axis=0, tiled=True))
    if sh is not None:
        # Extra "expert" column with weight 1 (gate folded in); the
        # activation gather here is the small dense branch only — the
        # routed path's activations still never ride an XLA collective.
        y = jnp.concatenate([y, sh.astype(y.dtype)[:, None]], axis=1)
        w_full = jnp.concatenate(
            [w_full, jnp.ones_like(w_full[:, :1])], axis=1)
    if epilogue == "ar":
        return moe_reduce_ar(y, w_full, ctx=mesh_ctx, axis=axis)
    return moe_reduce_rs(y, w_full, ctx=mesh_ctx, axis=axis)
