"""Ulysses sequence-parallel attention layer.

Reference: ``layers/nvidia/ulysses_sp_a2a_layer.py:29``
``UlyssesSPAllToAllLayer`` + pre/post attn A2A op layers
(``pre_attn_a2a_layer.py:71,199``, ``post_attn_a2a_layer.py:66``) and
the fused QKV/O GEMM+A2A kernels they wrap.

Layer form: QKV projection on the local sequence shard, head-resharding
A2A, attention over the full sequence, inverse A2A, O projection.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.layers.rope import apply_rope, rope_freqs
from triton_dist_tpu.layers import tp_attn
from triton_dist_tpu.ops.ulysses import pre_attn_a2a, post_attn_a2a
from triton_dist_tpu.parallel.mesh import MeshContext


def init(key, cfg, dtype=jnp.float32):
    """Same weight shapes as tp_attn; heads stay *unsharded*. The
    Ulysses fwd applies no projection biases and assumes the q/k norm,
    so bias-carrying / norm-free (Seed-OSS-class) configs are rejected
    rather than silently mis-served."""
    if (getattr(cfg, "attention_bias", False)
            or not getattr(cfg, "qk_norm", True)
            or getattr(cfg, "attn_gate", False)):
        raise NotImplementedError(
            "ulysses_sp covers the Qwen3 layer shape (no attention "
            "biases or output gate, per-head q/k norm)")
    return tp_attn.init(key, cfg, dtype)


def param_specs() -> Dict:
    """Ulysses shards the *sequence*, not the weights."""
    return {"wq": P(None, None), "wk": P(None, None),
            "wv": P(None, None), "wo": P(None, None),
            "q_norm": P(None), "k_norm": P(None)}


def fwd(params, x, cfg, *, axis: str = "sp", ctx: MeshContext = None,
        impl: str = "pallas", causal: bool = True):
    """x: (S_loc, d) sequence-sharded along ``axis`` → same layout out.

    ``impl``: "xla" (lax.all_to_all transport), "pallas" (direct-put
    A2A kernel), or "fused" — the QKV projection scatters tiles to
    their head-owners as the GEMM produces them and the O projection
    consumes arriving partials under the MXU
    (``ops/ulysses_fused``, the reference's defining Ulysses kernels).
    """
    if impl == "fused":
        return _fwd_fused(params, x, cfg, axis=axis, ctx=ctx,
                          causal=causal)
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    hd = cfg.head_dim
    h, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
    s_loc = x.shape[0]

    q = jnp.dot(x, params["wq"]).reshape(s_loc, h, hd)
    k = jnp.dot(x, params["wk"]).reshape(s_loc, kvh, hd)
    v = jnp.dot(x, params["wv"]).reshape(s_loc, kvh, hd)

    # q/k norm + rope with *global* positions (this rank's seq slice).
    positions = (me * s_loc + jnp.arange(s_loc))[None]
    q, k = tp_attn._norm_rope(q[None], k[None], params, cfg, positions)
    q, k = q[0], k[0]

    # Head-reshard, attend over the full sequence, reshard back.
    qh = pre_attn_a2a(q, axis=axis, ctx=ctx, impl=impl)
    kh = pre_attn_a2a(k, axis=axis, ctx=ctx, impl=impl)
    vh = pre_attn_a2a(v, axis=axis, ctx=ctx, impl=impl)
    o = tp_attn.sdpa(qh[None], kh[None], vh[None], causal=causal)[0]
    o = post_attn_a2a(o, axis=axis, ctx=ctx, impl=impl)

    return jnp.dot(o.reshape(s_loc, h * hd), params["wo"]).astype(x.dtype)


def _fwd_fused(params, x, cfg, *, axis: str, ctx: MeshContext,
               causal: bool):
    """Fused path: GEMM+A2A both directions (``ulysses_attn_fused``);
    q/k norm + rope applied on the post-A2A full-sequence heads via the
    ``qk_transform`` hook (elementwise per (position, head), so the
    order swap with the transport is exact)."""
    from triton_dist_tpu.ops.ulysses_fused import (
        create_ulysses_fused_context, ulysses_attn_fused,
        group_qkv_columns, group_o_rows)

    n = ctx.size(axis)
    hd = cfg.head_dim
    h, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
    s = n * x.shape[0]
    fctx = create_ulysses_fused_context(ctx, axis=axis)

    # Group the projection columns by owner rank (serving code should
    # pre-group once; under jit on constant params XLA folds this).
    w_qkv = group_qkv_columns(
        jnp.concatenate([params["wq"], params["wk"], params["wv"]],
                        axis=1),
        n=n, num_heads=h, num_kv_heads=kvh, head_dim=hd)
    w_o = group_o_rows(params["wo"], n=n, num_heads=h, head_dim=hd)

    def norm_rope(q, k):
        positions = jnp.arange(s)[None]  # global positions, src-major
        q, k = tp_attn._norm_rope(q[None], k[None], params, cfg,
                                  positions)
        return q[0], k[0]

    return ulysses_attn_fused(
        x, w_qkv, w_o, fctx, num_heads=h, num_kv_heads=kvh, head_dim=hd,
        causal=causal, qk_transform=norm_rope).astype(x.dtype)
