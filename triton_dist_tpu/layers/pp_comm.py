"""Pipeline-parallel communication + schedules.

Reference: ``layers/nvidia/pp_block.py:36,102`` ``PPCommLayer`` /
``PyTorchP2P`` over the p2p put/get kernels (``kernels/nvidia/p2p.py``),
benchmarked by ``bench_pp.py``.

TPU form (SPMD over a ``pp`` mesh axis):

- :func:`send_next` — stage boundary as one one-sided put
  (``ops/p2p.py``) or ``lax.ppermute``.
- :func:`gpipe_forward` — the real pipeline schedule: the batch is
  split into M microbatches and run for ``M + S - 1`` lockstep ticks
  inside ``lax.scan``; each rank computes ONLY its own stage per tick
  (params are pp-sharded, so the rank-local ``stage_fn`` *is* the
  stage), activations shift one stage per tick. Per-rank FLOPs are
  ``(M + S - 1) / (M · S)`` of the sequential total — → 1/S for large
  M, against the ``jnp.where``-masked relay's S× waste (the round-2
  shim this replaces).
- Backward: the schedule is a pure ``scan``+``ppermute`` program, so
  ``jax.grad`` through it yields the reverse pipeline automatically —
  backward microbatches drain in LIFO order, which is exactly the
  synchronous GPipe backward. This holds for both boundary impls:
  ``impl="xla"`` differentiates ``lax.ppermute`` natively, and
  ``impl="pallas"`` differentiates through :func:`p2p_put`'s custom
  VJP (cotangents ride the inverted permutation). Wrap ``stage_fn`` in ``jax.checkpoint``
  to keep activation memory at one stash per tick (the 1F1B memory
  motivation, achieved here by rematerialization instead of schedule
  interleaving — the TPU/XLA-idiomatic trade).
- :func:`pipeline_forward` — the unbatched relay (kept for inference
  bring-up and as the oracle in tests); it computes every stage's
  ``where``-mask on every rank and is NOT the performance path.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.p2p import p2p_put
from triton_dist_tpu.parallel.mesh import MeshContext


def send_next(x, *, axis: str = "pp", ctx: MeshContext = None,
              impl: str = "pallas"):
    """Shift activations one pipeline stage forward (last stage's output
    wraps to stage 0, which ignores it)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    if impl == "xla" or ctx is None:
        return jax.lax.ppermute(x, axis, perm)
    return p2p_put(x, perm, ctx=ctx, axis=axis)


def gpipe_forward(stage_fn: Callable, x_mb, *, axis: str = "pp",
                  ctx: MeshContext = None, impl: str = "xla",
                  collect: str = "broadcast", remat: bool = False):
    """Microbatched GPipe schedule (the reference's ``pp_block`` relay
    generalized to a full pipeline, ``bench_pp.py`` workload).

    stage_fn: ``h -> h`` for THIS rank's stage — close over the
    rank-local (pp-sharded) parameters; it runs once per tick, so each
    rank performs only its own stage's FLOPs.
    x_mb: ``(M, mb, ...)`` microbatches; only stage 0 reads them.
    collect: ``"broadcast"`` returns ``(M, mb, ...)`` replicated on all
    ranks (a one-hot psum off the last stage); ``"last"`` returns the
    raw per-rank tick outputs for schedule-level tests.
    remat: wrap the per-tick stage compute in ``jax.checkpoint`` so the
    backward pipeline rematerializes instead of stashing every tick.
    """
    me = jax.lax.axis_index(axis)
    n = jax.lax.axis_size(axis)
    m = x_mb.shape[0]
    ticks = m + n - 1

    def one_tick(h_carry, t):
        # Receive the upstream stage's previous output; stage 0 feeds
        # the next microbatch instead (clipped index — ticks past M
        # feed a dummy that never reaches the output window).
        h_in = send_next(h_carry, axis=axis, ctx=ctx, impl=impl)
        feed = x_mb[jnp.clip(t, 0, m - 1)]
        h_in = jnp.where(me == 0, feed.astype(h_carry.dtype), h_in)
        h_out = (jax.checkpoint(stage_fn) if remat else stage_fn)(h_in)
        return h_out.astype(h_carry.dtype), h_out

    h0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
    _, ys = jax.lax.scan(one_tick, h0, jnp.arange(ticks))
    # Microbatch i leaves the last stage at tick i + n - 1.
    outs = ys[n - 1:]
    if collect == "last":
        return outs
    # where, not multiply-by-mask: warmup/drain ticks run stage_fn on
    # garbage carries on non-final ranks, and a NaN there would poison
    # the psum (NaN·0 = NaN).
    return jax.lax.psum(jnp.where(me == n - 1, outs, 0), axis)


def pipeline_forward(stage_fn: Callable, x, *, num_stages: int,
                     axis: str = "pp", ctx: MeshContext = None,
                     impl: str = "xla"):
    """Unbatched stage relay: activations ripple through all stages with
    every rank lockstep-computing and ``where``-masking. S× redundant
    compute — bring-up/oracle only; use :func:`gpipe_forward` with
    microbatches for the real schedule."""
    me = jax.lax.axis_index(axis)
    h = x
    for stage in range(num_stages):
        active = me == stage
        h_new = stage_fn(stage, h)
        h = jnp.where(active, h_new, h)
        if stage < num_stages - 1:
            h = send_next(h, axis=axis, ctx=ctx, impl=impl)
    keep = (me == num_stages - 1).astype(h.dtype)
    return jax.lax.psum(h * keep, axis)
