"""Pipeline-parallel communication layer.

Reference: ``layers/nvidia/pp_block.py:36,102`` ``PPCommLayer`` /
``PyTorchP2P`` over the p2p put/get kernels (``kernels/nvidia/p2p.py``),
benchmarked by ``bench_pp.py``.

TPU form: stage boundaries are one-sided puts to the next stage
(``ops/p2p.py``) or ``lax.ppermute`` (``impl="xla"``); a simple
GPipe-style microbatch schedule helper runs a list of stage functions
under ``shard_map``.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.p2p import p2p_put
from triton_dist_tpu.parallel.mesh import MeshContext


def send_next(x, *, axis: str = "pp", ctx: MeshContext = None,
              impl: str = "pallas"):
    """Shift activations one pipeline stage forward (last stage's output
    wraps to stage 0, which ignores it)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    if impl == "xla" or ctx is None:
        return jax.lax.ppermute(x, axis, perm)
    return p2p_put(x, perm, ctx=ctx, axis=axis)


def pipeline_forward(stage_fn: Callable, x, *, num_stages: int,
                     axis: str = "pp", ctx: MeshContext = None,
                     impl: str = "xla"):
    """Run ``stage_fn(stage_index, h)`` through all pipeline stages.

    Every rank holds its stage's layers; activations flow stage to
    stage; rank ``num_stages-1`` ends with the final output, which is
    broadcast back. (A microbatched 1F1B schedule is the training-side
    extension; inference forward only needs the relay.)
    """
    me = jax.lax.axis_index(axis)
    h = x
    for stage in range(num_stages):
        active = me == stage
        h_new = stage_fn(stage, h)
        h = jnp.where(active, h_new, h)
        if stage < num_stages - 1:
            h = send_next(h, axis=axis, ctx=ctx, impl=impl)
            # Only the next stage consumes it; others carry h unchanged.
    # Broadcast final stage's result to all ranks (psum of a one-hot).
    keep = (me == num_stages - 1).astype(h.dtype)
    return jax.lax.psum(h * keep, axis)
