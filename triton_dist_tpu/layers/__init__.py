"""nn-style distributed layers (analogue of ``python/triton_dist/layers/``,
SURVEY.md §2.6).

Layers are *functional*: each is a namespace of ``init(key, cfg) ->
params`` / ``fwd(params, x, ...) -> y`` functions operating on per-shard
values inside ``shard_map``, plus a ``param_specs`` pytree of
PartitionSpecs for placing the weights on the mesh. Forward-mode
selection mirrors the reference's ``set_fwd('torch'|'triton_dist'|
'triton_dist_AR')`` (``models/dense.py:146``): ``"xla"`` (lax
collectives — oracle/portable), ``"fused"`` (ag_gemm + gemm_rs
overlapped kernels), ``"fused_ar"`` (gemm_ar decode path).
"""

from triton_dist_tpu.layers.norm import rms_norm  # noqa: F401
from triton_dist_tpu.layers.rope import apply_rope, rope_freqs  # noqa: F401
from triton_dist_tpu.layers import tp_mlp, tp_attn  # noqa: F401
