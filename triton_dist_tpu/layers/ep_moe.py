"""Expert-parallel MoE layer.

Reference: ``layers/nvidia/ep_moe.py:65`` ``EP_MoE`` (+ ``EPAll2AllLayer``
``ep_a2a_layer.py:220`` and the low-latency variant): router → dispatch
all-to-all → grouped expert MLP → combine all-to-all.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.ep_a2a import EPContext, ep_dispatch, ep_combine
from triton_dist_tpu.ops.ep_fused import EPFusedContext, ep_moe_fused
from triton_dist_tpu.ops.group_gemm import sort_by_expert, grouped_swiglu


def init(key, cfg, dtype=jnp.float32) -> Dict:
    """cfg needs: hidden_size, moe_intermediate_size, num_experts
    (+ shared_expert_intermediate_size for the qwen3_next-style
    always-on shared expert, 0 = none)."""
    kr, kg, ku, kd, ksg, ksu, ksd, kss = jax.random.split(key, 8)
    d, f, e = cfg.hidden_size, cfg.moe_intermediate_size, cfg.num_experts
    scale = d ** -0.5
    p = {
        "router": jax.random.normal(kr, (d, e), dtype) * scale,
        "w_gate": jax.random.normal(kg, (e, d, f), dtype) * scale,
        "w_up": jax.random.normal(ku, (e, d, f), dtype) * scale,
        "w_down": jax.random.normal(kd, (e, f, d), dtype) * (f ** -0.5),
    }
    fs = getattr(cfg, "shared_expert_intermediate_size", 0)
    if fs:
        # Shared expert (Qwen3NextSparseMoeBlock): a dense SwiGLU every
        # token takes, scaled by a sigmoid scalar gate, added to the
        # routed combine.
        p["w_shared_gate"] = jax.random.normal(ksg, (d, fs), dtype) * scale
        p["w_shared_up"] = jax.random.normal(ksu, (d, fs), dtype) * scale
        p["w_shared_down"] = jax.random.normal(
            ksd, (fs, d), dtype) * (fs ** -0.5)
        p["shared_gate"] = jax.random.normal(kss, (d,), dtype) * scale
    return p


def param_specs(axis: str = "ep", cfg=None) -> Dict:
    s = {
        "router": P(None, None),
        "w_gate": P(axis, None, None),  # experts sharded
        "w_up": P(axis, None, None),
        "w_down": P(axis, None, None),
    }
    if cfg is not None and getattr(cfg, "shared_expert_intermediate_size",
                                   0):
        # EP shards experts, not ffn dims: the dense shared expert is
        # replicated and applied to each rank's own tokens.
        s["w_shared_gate"] = P(None, None)
        s["w_shared_up"] = P(None, None)
        s["w_shared_down"] = P(None, None)
        s["shared_gate"] = P(None)
    return s


def route(router_w, x, topk: int, *, norm_topk_prob: bool = True):
    """Qwen3-MoE router: softmax over experts then top-k, weights
    renormalized (reference ``models/qwen_moe.py``)."""
    logits = jnp.dot(x.astype(jnp.float32), router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    topk_w, topk_ids = jax.lax.top_k(probs, topk)
    if norm_topk_prob:
        topk_w = topk_w / jnp.sum(topk_w, axis=-1, keepdims=True)
    return topk_ids.astype(jnp.int32), topk_w


def shared_expert_out(params, x):
    """Sigmoid-gated dense SwiGLU branch (qwen3_next shared expert);
    None when the layer has no shared expert. Under TP ffn-sharded
    weights the result is a PARTIAL sum (the caller's reduce completes
    it — the sigmoid gate uses the replicated ``shared_gate`` vector so
    every rank scales by the same factor); under replicated weights
    (EP) it is the full contribution."""
    if "w_shared_gate" not in params:
        return None
    g = jnp.dot(x, params["w_shared_gate"])
    u = jnp.dot(x, params["w_shared_up"])
    act = (jax.nn.silu(g.astype(jnp.float32))
           * u.astype(jnp.float32)).astype(x.dtype)
    out = jnp.dot(act, params["w_shared_down"],
                  preferred_element_type=jnp.float32)
    gate = jax.nn.sigmoid(jnp.dot(x.astype(jnp.float32),
                                  params["shared_gate"]
                                  .astype(jnp.float32)))
    return out * gate[:, None]


def fwd(params, x, ep_ctx: EPContext, *, topk: int,
        norm_topk_prob: bool = True):
    """x: (T_loc, d) — every ep rank holds *its own* tokens (the data
    dimension rides the ep axis, as in DeepEP). Returns (T_loc, d)."""
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)

    recv_tok, recv_exp, state = ep_dispatch(x, topk_ids, ep_ctx)
    sorted_tok, group_sizes, inv = sort_by_expert(
        recv_tok, recv_exp, ep_ctx.experts_per_rank)
    expert_out = grouped_swiglu(sorted_tok, params["w_gate"],
                                params["w_up"], params["w_down"],
                                group_sizes)
    expert_out = expert_out[inv]  # back to slot order
    y = ep_combine(expert_out, state, topk_w, ep_ctx)
    sh = shared_expert_out(params, x)   # replicated weights: full value
    return y if sh is None else (y + sh.astype(y.dtype))


def fwd_2d(params, x, ep2d_ctx, *, topk: int,
           norm_topk_prob: bool = True):
    """Hierarchical (ICI×DCN) EP forward: same structure as :func:`fwd`
    but the dispatch/combine ride the two-hop schedule
    (``ops/ep_a2a.ep_dispatch_2d`` — ICI hop first, one aggregated DCN
    exchange; reference ``all_to_all_vdev_2d_offset_inter_node.py``)."""
    from triton_dist_tpu.ops.ep_a2a import ep_dispatch_2d, ep_combine_2d

    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    recv_tok, recv_exp, state = ep_dispatch_2d(x, topk_ids, ep2d_ctx)
    sorted_tok, group_sizes, inv = sort_by_expert(
        recv_tok, recv_exp, ep2d_ctx.experts_per_rank)
    expert_out = grouped_swiglu(sorted_tok, params["w_gate"],
                                params["w_up"], params["w_down"],
                                group_sizes)
    y = ep_combine_2d(expert_out[inv], state, topk_w, ep2d_ctx)
    sh = shared_expert_out(params, x)
    return y if sh is None else (y + sh.astype(y.dtype))


def fwd_decode(params, x, *, topk: int, axis: str = "ep",
               norm_topk_prob: bool = True):
    """Replicated-token EP decode (the small-batch AR regime): every
    rank computes only its LOCAL expert shard's contributions for the
    whole (tiny) batch and one AllReduce completes the combine — zero
    dispatch round-trips. This is the TPU latency-optimal analogue of
    the reference's low-latency EP a2a decode
    (``low_latency_all_to_all_v2.py``): at decode M, two a2a hops cost
    more than the masked local compute (each rank runs E/n experts over
    B rows; B is a handful at decode, so FLOPs are noise and the psum
    rides the layer's existing collective slot).

    x: (B, d) identical on all ranks → (B, d) identical on all ranks.
    """
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    from triton_dist_tpu.parallel.mesh import flat_axis_rank

    if isinstance(axis, (tuple, list)):
        # Hierarchical expert sharding (outer-major rank order, matching
        # EP2DContext and P((outer, inner)) param specs).
        axis = tuple(axis)
    _, me = flat_axis_rank(axis)
    e_loc = params["w_gate"].shape[0]        # local expert shard
    ge = me * e_loc + jnp.arange(e_loc)      # my experts' global ids
    # (B, e_loc) combine weight mass routed to my experts.
    sel = (topk_ids[:, :, None] == ge[None, None, :])
    w_be = jnp.einsum("bk,bke->be", topk_w.astype(jnp.float32),
                      sel.astype(jnp.float32))
    xg = jnp.einsum("bd,edf->ebf", x, params["w_gate"])
    xu = jnp.einsum("bd,edf->ebf", x, params["w_up"])
    act = jax.nn.silu(xg.astype(jnp.float32)) * xu.astype(jnp.float32)
    y = jnp.einsum("ebf,efd->ebd", act.astype(x.dtype),
                   params["w_down"])        # (e_loc, B, d)
    out = jnp.einsum("ebd,be->bd", y.astype(jnp.float32), w_be)
    out = jax.lax.psum(out, axis).astype(x.dtype)
    # Replicated shared-expert weights: the full contribution adds
    # AFTER the reduce (inside it, n ranks would count it n times).
    sh = shared_expert_out(params, x)
    return out if sh is None else (out + sh.astype(out.dtype))


def fwd_fused(params, x, ep_ctx: EPFusedContext, *, topk: int,
              norm_topk_prob: bool = True):
    """Mega-EP forward: dispatch fused into the up-projection grouped
    GEMM, down-projection fused into the combine (``ops/ep_fused.py``).
    Returns ((T_loc, d), num_dropped)."""
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    y, dropped = ep_moe_fused(x, topk_ids, topk_w, params["w_gate"],
                              params["w_up"], params["w_down"], ep_ctx,
                              w_gu=params.get("w_gu"))
    sh = shared_expert_out(params, x)   # replicated weights: full value
    if sh is not None:
        y = y + sh.astype(y.dtype)
    return y, dropped
