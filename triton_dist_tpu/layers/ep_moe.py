"""Expert-parallel MoE layer.

Reference: ``layers/nvidia/ep_moe.py:65`` ``EP_MoE`` (+ ``EPAll2AllLayer``
``ep_a2a_layer.py:220`` and the low-latency variant): router → dispatch
all-to-all → grouped expert MLP → combine all-to-all.

Decode-path transports (:func:`fwd_decode`): the serving decode batch is
replicated across the ep axis, and the ``transport`` knob picks how its
tokens reach their experts —

- ``"ar"`` (legacy default): no dispatch at all — every rank runs its
  local expert shard over the whole (tiny) batch and one psum completes
  the combine.
- ``"ragged"``: the generic exact-splits :func:`~triton_dist_tpu.ops
  .ep_a2a.ep_dispatch`/``ep_combine`` round-trip (counts exchange +
  ragged transport).
- ``"ll"``: the low-latency path — a count-free, wire-quantized
  :func:`~triton_dist_tpu.ops.low_latency.ll_a2a` exchange statically
  sized at B·K slots per peer (the decode batch's fixed assignment
  count), the reference's ``fast_all_to_all``/``dispatch_kernel_v2``
  shape. Supports hot-expert :func:`replica <init_replicas>` rerouting.
- ``"ll2d"``: the hierarchical 2-hop ll path for (DCN, ICI) 2-axis
  meshes (:class:`~triton_dist_tpu.ops.ep_a2a.EP2DContext`): same
  count-free fixed-slot protocol, but the exchange rides
  :func:`~triton_dist_tpu.ops.ll_a2a_2d.ll_a2a_2d` — an intra-node ICI
  shuffle followed by ONE aggregated slab put per peer node over DCN,
  shrinking DCN puts by the ICI group factor.
- ``"auto"``: the :mod:`~triton_dist_tpu.tune`-persisted winner for
  this (mesh-hierarchy, batch, hidden, dtype) key
  (:func:`tune_transport`), else ``"ll"`` on a flat mesh / ``"ll2d"``
  on a hierarchical one — never a silent ``"ar"`` fallback.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.ep_a2a import (EPContext, EP2DContext,
                                        ep_dispatch, ep_combine)
from triton_dist_tpu.ops.ep_fused import EPFusedContext, ep_moe_fused
from triton_dist_tpu.ops.group_gemm import sort_by_expert, grouped_swiglu

DECODE_TRANSPORTS = ("ar", "ragged", "ll", "ll2d", "auto")


def init(key, cfg, dtype=jnp.float32) -> Dict:
    """cfg needs: hidden_size, moe_intermediate_size, num_experts
    (+ shared_expert_intermediate_size for the qwen3_next-style
    always-on shared expert, 0 = none)."""
    kr, kg, ku, kd, ksg, ksu, ksd, kss = jax.random.split(key, 8)
    d, f, e = cfg.hidden_size, cfg.moe_intermediate_size, cfg.num_experts
    scale = d ** -0.5
    p = {
        "router": jax.random.normal(kr, (d, e), dtype) * scale,
        "w_gate": jax.random.normal(kg, (e, d, f), dtype) * scale,
        "w_up": jax.random.normal(ku, (e, d, f), dtype) * scale,
        "w_down": jax.random.normal(kd, (e, f, d), dtype) * (f ** -0.5),
    }
    fs = getattr(cfg, "shared_expert_intermediate_size", 0)
    if fs:
        # Shared expert (Qwen3NextSparseMoeBlock): a dense SwiGLU every
        # token takes, scaled by a sigmoid scalar gate, added to the
        # routed combine.
        p["w_shared_gate"] = jax.random.normal(ksg, (d, fs), dtype) * scale
        p["w_shared_up"] = jax.random.normal(ksu, (d, fs), dtype) * scale
        p["w_shared_down"] = jax.random.normal(
            ksd, (fs, d), dtype) * (fs ** -0.5)
        p["shared_gate"] = jax.random.normal(kss, (d,), dtype) * scale
    return p


def param_specs(axis: str = "ep", cfg=None) -> Dict:
    s = {
        "router": P(None, None),
        "w_gate": P(axis, None, None),  # experts sharded
        "w_up": P(axis, None, None),
        "w_down": P(axis, None, None),
    }
    if cfg is not None and getattr(cfg, "shared_expert_intermediate_size",
                                   0):
        # EP shards experts, not ffn dims: the dense shared expert is
        # replicated and applied to each rank's own tokens.
        s["w_shared_gate"] = P(None, None)
        s["w_shared_up"] = P(None, None)
        s["w_shared_down"] = P(None, None)
        s["shared_gate"] = P(None)
    return s


def route(router_w, x, topk: int, *, norm_topk_prob: bool = True):
    """Qwen3-MoE router: softmax over experts then top-k, weights
    renormalized (reference ``models/qwen_moe.py``)."""
    logits = jnp.dot(x.astype(jnp.float32), router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    topk_w, topk_ids = jax.lax.top_k(probs, topk)
    if norm_topk_prob:
        topk_w = topk_w / jnp.sum(topk_w, axis=-1, keepdims=True)
    return topk_ids.astype(jnp.int32), topk_w


def shared_expert_out(params, x):
    """Sigmoid-gated dense SwiGLU branch (qwen3_next shared expert);
    None when the layer has no shared expert. Under TP ffn-sharded
    weights the result is a PARTIAL sum (the caller's reduce completes
    it — the sigmoid gate uses the replicated ``shared_gate`` vector so
    every rank scales by the same factor); under replicated weights
    (EP) it is the full contribution."""
    if "w_shared_gate" not in params:
        return None
    g = jnp.dot(x, params["w_shared_gate"])
    u = jnp.dot(x, params["w_shared_up"])
    act = (jax.nn.silu(g.astype(jnp.float32))
           * u.astype(jnp.float32)).astype(x.dtype)
    out = jnp.dot(act, params["w_shared_down"],
                  preferred_element_type=jnp.float32)
    gate = jax.nn.sigmoid(jnp.dot(x.astype(jnp.float32),
                                  params["shared_gate"]
                                  .astype(jnp.float32)))
    return out * gate[:, None]


def fwd(params, x, ep_ctx: EPContext, *, topk: int,
        norm_topk_prob: bool = True):
    """x: (T_loc, d) — every ep rank holds *its own* tokens (the data
    dimension rides the ep axis, as in DeepEP). Returns (T_loc, d)."""
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)

    recv_tok, recv_exp, state = ep_dispatch(x, topk_ids, ep_ctx)
    sorted_tok, group_sizes, inv = sort_by_expert(
        recv_tok, recv_exp, ep_ctx.experts_per_rank)
    expert_out = grouped_swiglu(sorted_tok, params["w_gate"],
                                params["w_up"], params["w_down"],
                                group_sizes)
    expert_out = expert_out[inv]  # back to slot order
    y = ep_combine(expert_out, state, topk_w, ep_ctx)
    sh = shared_expert_out(params, x)   # replicated weights: full value
    return y if sh is None else (y + sh.astype(y.dtype))


def fwd_2d(params, x, ep2d_ctx, *, topk: int,
           norm_topk_prob: bool = True):
    """Hierarchical (ICI×DCN) EP forward: same structure as :func:`fwd`
    but the dispatch/combine ride the two-hop schedule
    (``ops/ep_a2a.ep_dispatch_2d`` — ICI hop first, one aggregated DCN
    exchange; reference ``all_to_all_vdev_2d_offset_inter_node.py``)."""
    from triton_dist_tpu.ops.ep_a2a import ep_dispatch_2d, ep_combine_2d

    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    recv_tok, recv_exp, state = ep_dispatch_2d(x, topk_ids, ep2d_ctx)
    sorted_tok, group_sizes, inv = sort_by_expert(
        recv_tok, recv_exp, ep2d_ctx.experts_per_rank)
    expert_out = grouped_swiglu(sorted_tok, params["w_gate"],
                                params["w_up"], params["w_down"],
                                group_sizes)
    y = ep_combine_2d(expert_out[inv], state, topk_w, ep2d_ctx)
    sh = shared_expert_out(params, x)
    return y if sh is None else (y + sh.astype(y.dtype))


def fwd_decode(params, x, *, topk: int, axis: str = "ep",
               norm_topk_prob: bool = True, transport: str = "ar",
               ep_ctx: Optional[EPContext] = None, replicas=None,
               layer: int = 0, counts: Optional[List] = None):
    """Replicated-token EP decode: one fixed-shape (B, d) batch,
    identical on all ranks in, identical out.

    ``transport`` picks the expert path (module docstring):

    - ``"ar"`` (default): masked local experts + psum — zero dispatch
      round-trips; at decode M two a2a hops cost more than computing
      E/n experts over a handful of rows.
    - ``"ragged"``: the exact-splits dispatch/combine round-trip
      (:func:`~triton_dist_tpu.ops.ep_a2a.ep_dispatch`); needs
      ``ep_ctx``.
    - ``"ll"``: count-free wire-quantized :func:`~triton_dist_tpu.ops
      .low_latency.ll_a2a` exchange over B·K static slots per peer;
      needs ``ep_ctx``. Consults ``replicas`` (hot-expert weight
      copies, :func:`init_replicas`) for rerouting — replica choice is
      data, not trace, so refreshing it never recompiles. NOTE: ``ll``
      ALWAYS rides a quantized wire — int8 unless ``ctx.wire_dtype``
      picks fp8 — unlike dispatch/combine, where ``wire_dtype=None``
      means full precision; pick ``"ragged"`` when wire-quantization
      tolerance is unacceptable.
    - ``"ll2d"``: the same count-free slot protocol over a
      hierarchical (DCN, ICI) mesh — two single-axis hops with the
      DCN traffic coalesced to one slab per peer node
      (:func:`~triton_dist_tpu.ops.ll_a2a_2d.ll_a2a_2d`); needs an
      :class:`~triton_dist_tpu.ops.ep_a2a.EP2DContext` as ``ep_ctx``.
      Quantizes once per fabric (two wire round-trips total).
    - ``"auto"``: host-side tune-cache resolution
      (:func:`resolve_transport`).

    ``layer`` keys the ll slot parity (two a2a calls per MoE layer get
    distinct static parities). ``counts``, when a list, receives this
    layer's per-expert routed-assignment counts (E,) int32 — the
    on-device expert-load telemetry the serving layer aggregates.
    """
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    if counts is not None:
        num_experts = (ep_ctx.num_experts if ep_ctx is not None
                       else params["router"].shape[1])
        counts.append(jnp.bincount(
            topk_ids.reshape(-1), length=num_experts).astype(jnp.int32))

    if transport == "auto":
        transport = resolve_transport(
            "auto", ctx=ep_ctx, batch=x.shape[0], hidden=x.shape[1],
            dtype=x.dtype, topk=topk)
    if transport not in ("ar", "ragged", "ll", "ll2d"):
        raise ValueError(f"transport must be one of {DECODE_TRANSPORTS},"
                         f" got {transport!r}")
    if transport == "ll2d":
        if not isinstance(ep_ctx, EP2DContext):
            raise ValueError(
                "transport='ll2d' needs a hierarchical EP2DContext "
                "(create_ep2d_context) — flat meshes ride 'll'")
        if replicas is not None:
            raise ValueError(
                "hot-expert replication rides the flat 'll' transport;"
                " transport='ll2d' does not consult replicas")
        out = _fwd_decode_ll2d(params, x, topk_ids, topk_w,
                               ctx=ep_ctx, layer=layer)
        sh = shared_expert_out(params, x)
        return out if sh is None else (out + sh.astype(out.dtype))
    if transport in ("ragged", "ll"):
        if ep_ctx is None or not isinstance(ep_ctx, EPContext):
            raise ValueError(
                f"transport={transport!r} needs a flat EPContext "
                "(hierarchical 2D meshes ride transport='ll2d')")
        if transport == "ragged":
            out = _fwd_decode_ragged(params, x, topk_ids, topk_w,
                                     ctx=ep_ctx)
        else:
            out = _fwd_decode_ll(params, x, topk_ids, topk_w,
                                 ctx=ep_ctx, replicas=replicas,
                                 layer=layer)
        sh = shared_expert_out(params, x)
        return out if sh is None else (out + sh.astype(out.dtype))

    from triton_dist_tpu.parallel.mesh import flat_axis_rank

    if isinstance(axis, (tuple, list)):
        # Hierarchical expert sharding (outer-major rank order, matching
        # EP2DContext and P((outer, inner)) param specs).
        axis = tuple(axis)
    _, me = flat_axis_rank(axis)
    e_loc = params["w_gate"].shape[0]        # local expert shard
    ge = me * e_loc + jnp.arange(e_loc)      # my experts' global ids
    # (B, e_loc) combine weight mass routed to my experts.
    sel = (topk_ids[:, :, None] == ge[None, None, :])
    w_be = jnp.einsum("bk,bke->be", topk_w.astype(jnp.float32),
                      sel.astype(jnp.float32))
    xg = jnp.einsum("bd,edf->ebf", x, params["w_gate"])
    xu = jnp.einsum("bd,edf->ebf", x, params["w_up"])
    act = jax.nn.silu(xg.astype(jnp.float32)) * xu.astype(jnp.float32)
    y = jnp.einsum("ebf,efd->ebd", act.astype(x.dtype),
                   params["w_down"])        # (e_loc, B, d)
    out = jnp.einsum("ebd,be->bd", y.astype(jnp.float32), w_be)
    out = jax.lax.psum(out, axis).astype(x.dtype)
    # Replicated shared-expert weights: the full contribution adds
    # AFTER the reduce (inside it, n ranks would count it n times).
    sh = shared_expert_out(params, x)
    return out if sh is None else (out + sh.astype(out.dtype))


def _fwd_decode_ragged(params, x, topk_ids, topk_w, *, ctx: EPContext):
    """Decode via the generic exact-splits round-trip: every rank
    dispatches the (replicated) batch's assignments, owners run the
    grouped SwiGLU, combine returns each rank its own copies — output
    replicated without a reduce."""
    recv_tok, recv_exp, state = ep_dispatch(x, topk_ids, ctx)
    sorted_tok, group_sizes, inv = sort_by_expert(
        recv_tok, recv_exp, ctx.experts_per_rank)
    expert_out = grouped_swiglu(sorted_tok, params["w_gate"],
                                params["w_up"], params["w_down"],
                                group_sizes)
    return ep_combine(expert_out[inv], state, topk_w, ctx)


def _fwd_decode_ll(params, x, topk_ids, topk_w, *, ctx: EPContext,
                   replicas=None, layer: int = 0):
    """Low-latency decode dispatch: COUNT-FREE fixed-slot exchange.

    Every (token, k) assignment owns static slot ``j = t·K + k`` in a
    (n, B·K, d) wire buffer; rank ``dest[j]`` finds token ``j // K`` in
    slot j and every other destination sees a zero row — no splits
    exchange, no cumsum, no ragged transport: the slot count IS the
    protocol (reference ``dispatch_kernel_v2`` /
    ``low_latency_all_to_all_v2.py:156``). Payload rows are
    wire-quantized inside :func:`~triton_dist_tpu.ops.low_latency
    .ll_a2a` (per-row absmax int8/fp8 + scales); the return hop
    broadcasts each owner's outputs back through the same transport at
    the opposite slot parity.

    ``replicas`` (``None`` = off) reroutes alternate assignments of a
    replicated expert to the replica's rank: ``replica_rank`` (E,)
    names the rank holding a copy, ``slot_expert`` (R,) maps replica
    weight slots to expert ids. Routing is a pure function of
    (topk_ids, replicas), identical on every rank, and the replica
    weights are exact copies — greedy tokens cannot change.
    """
    mesh, axis = ctx.mesh, ctx.axis
    n = mesh.size(axis)
    b, d = x.shape
    k = topk_ids.shape[1]
    e_loc = params["w_gate"].shape[0]
    wire = ctx.wire_dtype if ctx.wire_dtype is not None else jnp.int8

    flat_e = topk_ids.reshape(-1).astype(jnp.int32)       # (BK,)
    owner = flat_e // e_loc
    n_rep = 0 if replicas is None else replicas["slot_expert"].shape[0]
    if n_rep:
        rep_rank = replicas["replica_rank"][flat_e]       # (BK,)
        # Deterministic 50/50 split: an assignment's position among its
        # expert's assignments decides owner vs replica — replicated
        # inputs make every rank compute the same route.
        one_hot = jax.nn.one_hot(flat_e, ctx.num_experts,
                                 dtype=jnp.int32)
        pos = jnp.cumsum(one_hot, axis=0) - 1             # (BK, E)
        pos_e = jnp.take_along_axis(pos, flat_e[:, None], axis=1)[:, 0]
        use_rep = jnp.logical_and(rep_rank >= 0, pos_e % 2 == 1)
        dest = jnp.where(use_rep, rep_rank, owner)
        # Replica-slot id of each assignment's expert (-1 = none).
        slot_match = (replicas["slot_expert"][None, :]
                      == flat_e[:, None])                 # (BK, R)
        rep_slot = jnp.argmax(slot_match, axis=1)
    else:
        use_rep = jnp.zeros(flat_e.shape, bool)
        rep_slot = jnp.zeros(flat_e.shape, jnp.int32)
        dest = owner

    from triton_dist_tpu.ops.low_latency import ll_a2a

    rep_tok = jnp.repeat(x, k, axis=0)                    # (BK, d)
    slots = jnp.arange(b * k)
    send = jnp.zeros((n, b * k, d), x.dtype).at[dest, slots].set(rep_tok)
    recv = ll_a2a(send, ctx=mesh, axis=axis, step=2 * layer,
                  wire_dtype=wire)                        # (n, BK, d)

    me = jax.lax.axis_index(axis)
    # Replicated routing ⇒ every source staged the same slot content;
    # my copy of the batch is the chunk addressed through me.
    tok = jnp.take(recv, me, axis=0)                      # (BK, d)
    # Local group id per slot: owner-routed rows use the local expert
    # shard, replica-routed rows use the replica slots appended after
    # it; rows bound elsewhere sort to the tail (-1).
    loc = jnp.where(use_rep, e_loc + rep_slot, flat_e % e_loc)
    mine = dest == me
    loc = jnp.where(mine, loc, -1).astype(jnp.int32)
    if n_rep:
        w_gate = jnp.concatenate(
            [params["w_gate"],
             replicas["w_gate"].astype(params["w_gate"].dtype)], axis=0)
        w_up = jnp.concatenate(
            [params["w_up"],
             replicas["w_up"].astype(params["w_up"].dtype)], axis=0)
        w_down = jnp.concatenate(
            [params["w_down"],
             replicas["w_down"].astype(params["w_down"].dtype)], axis=0)
    else:
        w_gate, w_up, w_down = (params["w_gate"], params["w_up"],
                                params["w_down"])
    sorted_tok, group_sizes, inv = sort_by_expert(tok, loc,
                                                  e_loc + n_rep)
    y = grouped_swiglu(sorted_tok, w_gate, w_up, w_down,
                       group_sizes)[inv]
    y = jnp.where(mine[:, None], y, 0).astype(x.dtype)    # (BK, d)

    # Return hop: every owner broadcasts its rows to all peers through
    # the opposite-parity slots; back[r, j] = slot j as computed at r.
    back = ll_a2a(jnp.broadcast_to(y[None], (n, b * k, d)),
                  ctx=mesh, axis=axis, step=2 * layer + 1,
                  wire_dtype=wire)
    gathered = back[dest, slots].reshape(b, k, d)
    return jnp.einsum("bkd,bk->bd", gathered.astype(jnp.float32),
                      topk_w.astype(jnp.float32)).astype(x.dtype)


def _fwd_decode_ll2d(params, x, topk_ids, topk_w, *,
                     ctx: EP2DContext, layer: int = 0):
    """Hierarchical low-latency decode dispatch: the :func:`_fwd_decode_ll`
    slot protocol (j = t·K + k, replicated routing, zero rows for
    non-destinations) with the exchange factored over the 2-axis mesh
    by :func:`~triton_dist_tpu.ops.ll_a2a_2d.ll_a2a_2d` — ICI shuffle
    first, then ONE coalesced slab put per peer node over DCN. Global
    rank order is outer-major (``flat_axis_rank`` over
    (outer, inner)), matching ``EP2DContext`` expert ownership
    ``e // experts_per_rank``, so ``dest = flat_e // e_loc`` addresses
    the wire buffer directly.

    Two wire quantizations per hop direction (once per fabric) — the
    acceptance bar is greedy-token parity with ``"ar"``, same as the
    flat ``"ll"`` transport's.
    """
    from triton_dist_tpu.ops.ll_a2a_2d import ll_a2a_2d
    from triton_dist_tpu.parallel.mesh import flat_axis_rank

    mesh = ctx.mesh
    n = mesh.size(ctx.outer_axis) * mesh.size(ctx.inner_axis)
    b, d = x.shape
    k = topk_ids.shape[1]
    e_loc = params["w_gate"].shape[0]
    wire = ctx.wire_dtype if ctx.wire_dtype is not None else jnp.int8

    flat_e = topk_ids.reshape(-1).astype(jnp.int32)       # (BK,)
    dest = flat_e // e_loc                # outer-major global rank
    rep_tok = jnp.repeat(x, k, axis=0)                    # (BK, d)
    slots = jnp.arange(b * k)
    send = jnp.zeros((n, b * k, d), x.dtype).at[dest, slots].set(rep_tok)
    recv = ll_a2a_2d(send, ctx=mesh, outer_axis=ctx.outer_axis,
                     inner_axis=ctx.inner_axis, step=2 * layer,
                     wire_dtype=wire, impl=ctx.impl)      # (n, BK, d)

    _, me = flat_axis_rank((ctx.outer_axis, ctx.inner_axis))
    # Replicated routing ⇒ every source staged the same slot content;
    # my copy of the batch is the chunk addressed through me.
    tok = jnp.take(recv, me, axis=0)                      # (BK, d)
    mine = dest == me
    loc = jnp.where(mine, flat_e % e_loc, -1).astype(jnp.int32)
    sorted_tok, group_sizes, inv = sort_by_expert(tok, loc, e_loc)
    y = grouped_swiglu(sorted_tok, params["w_gate"], params["w_up"],
                       params["w_down"], group_sizes)[inv]
    y = jnp.where(mine[:, None], y, 0).astype(x.dtype)    # (BK, d)

    # Return hop: owners broadcast their rows back through both
    # fabrics at the opposite slot parity; back[r, j] = slot j as
    # computed at global rank r.
    back = ll_a2a_2d(jnp.broadcast_to(y[None], (n, b * k, d)),
                     ctx=mesh, outer_axis=ctx.outer_axis,
                     inner_axis=ctx.inner_axis, step=2 * layer + 1,
                     wire_dtype=wire, impl=ctx.impl)
    gathered = back[dest, slots].reshape(b, k, d)
    return jnp.einsum("bkd,bk->bd", gathered.astype(jnp.float32),
                      topk_w.astype(jnp.float32)).astype(x.dtype)


# --- decode-transport autotune + hot-expert replica state -------------------

def _transport_key(ctx, *, batch: int, hidden: int, dtype,
                   topk: int) -> str:
    from triton_dist_tpu import tune

    if isinstance(ctx, EP2DContext):
        axis = f"{ctx.outer_axis}+{ctx.inner_axis}"
        hier = (f"{ctx.mesh.size(ctx.outer_axis)}"
                f"x{ctx.mesh.size(ctx.inner_axis)}")
    else:
        axis = ctx.axis
        # Flat mesh = degenerate 1×n hierarchy: the hierarchy shape is
        # part of the key, so a 2D tuning can never shadow a flat one
        # (or vice versa) on meshes of equal total size.
        hier = f"1x{ctx.mesh.size(ctx.axis)}"
    return tune.make_key(
        "ep_decode_transport", mesh=tune.mesh_key(ctx.mesh),
        axis=axis, hier=hier, batch=batch, hidden=hidden,
        # Canonicalize: jnp.float32 (a type) and np.dtype("float32")
        # must key identically or a tuned winner is never found.
        dtype=str(jnp.dtype(dtype)),
        topk=topk, experts=ctx.num_experts)


def resolve_transport(transport: str, *, ctx,
                      batch: int, hidden: int, dtype,
                      topk: int) -> str:
    """Host-side resolution of the decode ``transport`` knob.

    Explicit values pass through; ``"auto"`` loads the
    :func:`tune_transport` winner persisted for this
    (mesh-hierarchy, batch, hidden, dtype) key and falls back to the
    latency-optimized default when never tuned — ``"ll"`` on a flat
    :class:`EPContext`, ``"ll2d"`` on a hierarchical
    :class:`~triton_dist_tpu.ops.ep_a2a.EP2DContext` (an untuned 2D
    mesh dispatches over both fabrics rather than silently paying the
    ``"ar"`` full-reduce) — or ``"ar"`` when no EP context exists to
    dispatch over."""
    if transport != "auto":
        return transport
    if isinstance(ctx, EP2DContext):
        from triton_dist_tpu import tune

        cached = tune.load_autotune_data(_transport_key(
            ctx, batch=batch, hidden=hidden, dtype=dtype, topk=topk))
        if cached and cached.get("transport") in ("ar", "ll2d"):
            return cached["transport"]
        return "ll2d"
    if ctx is None or not isinstance(ctx, EPContext):
        return "ar"
    from triton_dist_tpu import tune

    cached = tune.load_autotune_data(_transport_key(
        ctx, batch=batch, hidden=hidden, dtype=dtype, topk=topk))
    if cached and cached.get("transport") in ("ar", "ragged", "ll"):
        return cached["transport"]
    return "ll"


def tune_transport(mesh, params, ctx, *, batch: int,
                   topk: int, norm_topk_prob: bool = True, reps: int = 3,
                   use_cache: bool = True) -> str:
    """OFFLINE transport sweep for one decode shape: time each
    candidate's jitted replicated-batch dispatch on ``mesh`` and
    persist the winner under the (mesh-hierarchy, batch, hidden,
    dtype) key ``transport="auto"`` resolves (the ``tune_schedule``
    pattern). A flat :class:`EPContext` sweeps ``ragged`` vs ``ll``; a
    hierarchical :class:`~triton_dist_tpu.ops.ep_a2a.EP2DContext`
    sweeps ``ar`` vs ``ll2d`` (the two candidates that exist on a 2D
    mesh).

    ``params`` is one MoE layer's param dict (expert-sharded on the
    mesh or replicated — timing only). Returns the winning transport.
    """
    import time as _time

    import numpy as np
    from triton_dist_tpu import tune

    is2d = isinstance(ctx, EP2DContext)
    sweep = ("ar", "ll2d") if is2d else ("ragged", "ll")
    ep_axis = ((ctx.outer_axis, ctx.inner_axis) if is2d else ctx.axis)
    d = params["router"].shape[0]
    dtype = params["w_gate"].dtype
    key = _transport_key(ctx, batch=batch, hidden=d, dtype=dtype,
                         topk=topk)
    if use_cache:
        cached = tune.load_autotune_data(key)
        if cached and cached.get("transport") in (("ar",) + sweep):
            return cached["transport"]

    x = jax.random.normal(jax.random.PRNGKey(0), (batch, d), dtype)
    # Specs keyed off the ACTUAL param tree: layers with a shared
    # expert carry four extra (replicated-under-EP) leaves that a bare
    # param_specs(axis) call would omit, crashing the shard_map.
    shared = {"w_shared_gate": P(None, None),
              "w_shared_up": P(None, None),
              "w_shared_down": P(None, None), "shared_gate": P(None)}
    full = {**param_specs(ep_axis), **shared}
    specs = {k: full[k] for k in params}
    times = {}
    for tr in sweep:
        step = jax.jit(jax.shard_map(
            lambda p, v, _tr=tr: fwd_decode(
                p, v, topk=topk, axis=ep_axis,
                norm_topk_prob=norm_topk_prob, transport=_tr,
                ep_ctx=ctx),
            mesh=mesh, in_specs=(specs, P(None, None)),
            out_specs=P(None, None), check_vma=False))
        np.asarray(step(params, x))            # compile + warmup
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            np.asarray(step(params, x))
            best = min(best, _time.perf_counter() - t0)
        times[tr] = best
    winner = min(times, key=times.get)
    tune.store_autotune_data(
        key, {"transport": winner,
              "times_ms": {t: round(v * 1e3, 3)
                           for t, v in times.items()}},
        times[winner])
    return winner


def init_replicas(cfg, *, slots: int, num_layers: Optional[int] = None,
                  dtype=jnp.float32) -> Dict:
    """Empty hot-expert replica state consulted by the ``"ll"`` decode
    transport: ``slots`` replica weight slots per MoE layer, all free.

    Layout (all replicated across the mesh — replica slots are few and
    small next to the sharded expert banks): ``w_gate``/``w_up``
    (L, R, d, f), ``w_down`` (L, R, f, d), ``slot_expert`` (L, R)
    global expert id held by each slot (-1 free), ``replica_rank``
    (L, E) rank serving a replica of expert e (-1 none). Contents are
    DATA: the serving layer refreshes them between steps from host-side
    load stats with zero recompilation."""
    L = (num_layers if num_layers is not None
         else getattr(cfg, "num_hidden_layers", 1))
    d, f, e = (cfg.hidden_size, cfg.moe_intermediate_size,
               cfg.num_experts)
    return {
        "w_gate": jnp.zeros((L, slots, d, f), dtype),
        "w_up": jnp.zeros((L, slots, d, f), dtype),
        "w_down": jnp.zeros((L, slots, f, d), dtype),
        "slot_expert": jnp.full((L, slots), -1, jnp.int32),
        "replica_rank": jnp.full((L, e), -1, jnp.int32),
    }


def replica_specs() -> Dict:
    """PartitionSpecs for :func:`init_replicas` state (replicated)."""
    return {"w_gate": P(None, None, None, None),
            "w_up": P(None, None, None, None),
            "w_down": P(None, None, None, None),
            "slot_expert": P(None, None),
            "replica_rank": P(None, None)}


def replica_layer(replicas: Dict, layer: int) -> Dict:
    """One layer's slice of the replica state (what
    :func:`fwd_decode` consumes)."""
    return {k: v[layer] for k, v in replicas.items()}


def install_replica_layers(replicas: Dict, slot: int, expert: int,
                           rank: int, w_gate, w_up, w_down) -> Dict:
    """Host-side batched install: copy ONE expert's weights into slot
    ``slot`` across EVERY layer in one pass. ``w_*`` are (L, d, f) /
    (L, f, d) stacks (layer-major). One ``.at[:, slot].set`` per
    buffer — a per-layer install loop would materialize the full
    replica slab L times. Evicted experts (per layer, whatever held
    the slot) have their routing entries cleared first. Pure —
    returns the updated pytree."""
    L = replicas["slot_expert"].shape[0]
    old = replicas["slot_expert"][:, slot]                # (L,)
    rows = jnp.arange(L)
    rr = replicas["replica_rank"]
    rr = rr.at[rows, jnp.maximum(old, 0)].set(
        jnp.where(old >= 0, -1, rr[rows, jnp.maximum(old, 0)]))
    return {
        "w_gate": replicas["w_gate"].at[:, slot].set(
            w_gate.astype(replicas["w_gate"].dtype)),
        "w_up": replicas["w_up"].at[:, slot].set(
            w_up.astype(replicas["w_up"].dtype)),
        "w_down": replicas["w_down"].at[:, slot].set(
            w_down.astype(replicas["w_down"].dtype)),
        "slot_expert": replicas["slot_expert"].at[:, slot].set(
            int(expert)),
        "replica_rank": rr.at[:, int(expert)].set(int(rank)),
    }




def fwd_fused(params, x, ep_ctx: EPFusedContext, *, topk: int,
              norm_topk_prob: bool = True):
    """Mega-EP forward: dispatch fused into the up-projection grouped
    GEMM, down-projection fused into the combine (``ops/ep_fused.py``).
    Returns ((T_loc, d), num_dropped)."""
    topk_ids, topk_w = route(params["router"], x, topk,
                             norm_topk_prob=norm_topk_prob)
    y, dropped = ep_moe_fused(x, topk_ids, topk_w, params["w_gate"],
                              params["w_up"], params["w_down"], ep_ctx,
                              w_gu=params.get("w_gu"))
    sh = shared_expert_out(params, x)   # replicated weights: full value
    if sh is not None:
        y = y + sh.astype(y.dtype)
    return y, dropped
