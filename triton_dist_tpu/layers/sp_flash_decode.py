"""Sequence-parallel GQA flash-decode attention layer.

Reference: ``layers/nvidia/sp_flash_decode_layer.py:44``
``SpGQAFlashDecodeAttention`` — decode-time attention with the KV cache
sequence-sharded across ranks (1→32 GPU scaling, ``README.md:205``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.layers.rope import apply_rope, rope_freqs
from triton_dist_tpu.ops.flash_decode import sp_flash_decode

init = None  # uses tp_attn-style params passed by the caller


def fwd(params, x, cfg, k_cache, v_cache, cache_len, *, axis="sp",
        fused: bool = False, ctx=None, page: int = 128):
    """One decode step with a sequence-sharded cache.

    x: (B, d) replicated along ``axis``; caches (B, T_loc, KV, hd) —
    this rank's contiguous slice of the global (B, n*T_loc, KV, hd)
    cache; cache_len: scalar global length. The new token's KV is
    appended on the owning rank only. Returns (y (B, d), caches).

    ``axis`` may be an ``(outer, inner)`` tuple for multi-slice caches
    (shards in outer-major order; the combine rides both axes — see
    ``ops/flash_decode.sp_flash_decode``).

    CAPACITY CONTRACT: ``cache_len`` must be < n*T_loc. At full
    capacity no rank owns the append slot (owner == n) and the newest
    token's KV would be silently dropped — callers must size caches or
    guard the step count (as ``Engine.decode`` does for the TP cache).

    ``fused=True``: caches are HEAD-MAJOR (B, KV, T_loc, hd) and the
    attention step runs as ONE Pallas kernel (online softmax + in-kernel
    RDMA partial exchange, :func:`ops.sp_flash_decode_fused`) instead of
    the pmax+2psum XLA composition. ``page`` tiles T_loc through VMEM
    (min(page, T_loc) is used; T_loc must divide evenly). ``ctx`` (a
    MeshContext) is required for tuple ``axis`` under ``fused``.
    """
    from triton_dist_tpu.parallel.mesh import flat_axis_rank

    if isinstance(axis, (tuple, list)):
        axis = tuple(axis)
    # Only `me` feeds the owner-rank append; the capacity contract
    # (cache_len < n*T_loc) is the CALLER's guard (see docstring).
    _, me = flat_axis_rank(axis)
    hd = cfg.head_dim
    h, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
    b = x.shape[0]
    t_loc = k_cache.shape[2] if fused else k_cache.shape[1]

    q = jnp.dot(x, params["wq"]).reshape(b, 1, h, hd)
    k = jnp.dot(x, params["wk"]).reshape(b, 1, kvh, hd)
    v = jnp.dot(x, params["wv"]).reshape(b, 1, kvh, hd)
    positions = jnp.full((b, 1), cache_len, jnp.int32)
    from triton_dist_tpu.layers import tp_attn
    q, k = tp_attn._norm_rope(q, k, params, cfg, positions)

    # Append on the rank that owns slot ``cache_len``.
    owner = cache_len // t_loc
    local_slot = cache_len - owner * t_loc
    is_owner = owner == me
    kv_len = jnp.full((b,), cache_len + 1, jnp.int32)

    def append(cache, new, idx, sizes):
        """Owner-gated append at ``idx`` (non-owners rewrite the
        existing slice — a no-op that keeps the SPMD step uniform)."""
        upd = jnp.where(is_owner, new.astype(cache.dtype),
                        jax.lax.dynamic_slice(cache, idx, sizes))
        return jax.lax.dynamic_update_slice(cache, upd, idx)

    if fused:
        # Head-major caches: the new token is a (B, KV, 1, hd) slice.
        idx, sizes = (0, 0, local_slot, 0), (b, kvh, 1, hd)
        k_cache = append(k_cache, jnp.transpose(k, (0, 2, 1, 3)), idx,
                         sizes)
        v_cache = append(v_cache, jnp.transpose(v, (0, 2, 1, 3)), idx,
                         sizes)
        from triton_dist_tpu.ops.paged_flash_decode import (
            sp_flash_decode_fused,
        )

        o = sp_flash_decode_fused(q[:, 0], k_cache, v_cache, kv_len,
                                  ctx=ctx, axis=axis,
                                  page=min(page, t_loc))
    else:
        idx, sizes = (0, local_slot, 0, 0), (b, 1, kvh, hd)
        k_cache = append(k_cache, k, idx, sizes)
        v_cache = append(v_cache, v, idx, sizes)
        o = sp_flash_decode(q[:, 0], k_cache, v_cache, kv_len, axis=axis)
    y = jnp.dot(o.reshape(b, h * hd), params["wo"]).astype(x.dtype)
    return y, (k_cache, v_cache)
