"""Tensor-parallel attention (GQA + rope + Qwen3 q/k-norm).

Reference: ``layers/nvidia/tp_attn.py:80`` ``TP_Attn`` — QKV via ag_gemm
(AG buffer reused across the three projections), flash attention, O via
gemm_rs; gemm_ar mode for decode.

Heads are sharded along ``tp``; the residual stream is token-sharded
(sequence parallel) in "xla"/"fused" modes and replicated in "fused_ar"
decode mode.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from math import sqrt as np_sqrt

from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.layers.rope import apply_rope, rope_freqs
from triton_dist_tpu.ops import ag_gemm, gemm_rs, gemm_ar


def init(key, cfg, dtype=jnp.float32) -> Dict:
    kq, kk, kv, ko = jax.random.split(key, 4)
    d = cfg.hidden_size
    hd = cfg.head_dim
    scale = d ** -0.5
    p = {
        "wq": jax.random.normal(kq, (d, cfg.num_attention_heads * hd),
                                dtype) * scale,
        "wk": jax.random.normal(kk, (d, cfg.num_key_value_heads * hd),
                                dtype) * scale,
        "wv": jax.random.normal(kv, (d, cfg.num_key_value_heads * hd),
                                dtype) * scale,
        "wo": jax.random.normal(
            ko, (cfg.num_attention_heads * hd, d), dtype
        ) * ((cfg.num_attention_heads * hd) ** -0.5),
    }
    if getattr(cfg, "qk_norm", True):
        p["q_norm"] = jnp.ones((hd,), dtype)
        p["k_norm"] = jnp.ones((hd,), dtype)
    if getattr(cfg, "attn_gate", False):
        # Qwen3-Next gated attention: q_proj emits per-head [q | gate]
        # (modeling_qwen3_next.Qwen3NextAttention); de-interleaved to a
        # separate column-parallel matrix so gate columns shard with
        # their heads.
        p["wqg"] = jax.random.normal(
            jax.random.fold_in(kq, 1),
            (d, cfg.num_attention_heads * hd), dtype) * scale
    if getattr(cfg, "attention_bias", False):
        # Seed-OSS / Qwen2-style projection biases (the reference
        # shards q_proj.bias etc. the same way, layer init path).
        p["bq"] = jnp.zeros((cfg.num_attention_heads * hd,), dtype)
        p["bk"] = jnp.zeros((cfg.num_key_value_heads * hd,), dtype)
        p["bv"] = jnp.zeros((cfg.num_key_value_heads * hd,), dtype)
        p["bo"] = jnp.zeros((d,), dtype)
    return p


def param_specs(axis: str = "tp", cfg=None) -> Dict:
    """``cfg=None`` keeps the legacy Qwen3 layout (q/k norms, no
    biases); pass a config to match :func:`init`'s conditional keys."""
    s = {
        "wq": P(None, axis),
        "wk": P(None, axis),
        "wv": P(None, axis),
        "wo": P(axis, None),
    }
    if cfg is None or getattr(cfg, "qk_norm", True):
        s["q_norm"] = P(None)
        s["k_norm"] = P(None)
    if cfg is not None and getattr(cfg, "attn_gate", False):
        s["wqg"] = P(None, axis)
    if cfg is not None and getattr(cfg, "attention_bias", False):
        s["bq"] = P(axis)
        s["bk"] = P(axis)
        s["bv"] = P(axis)
        # Row-parallel o-proj: the bias adds ONCE after the reduce, so
        # it stays replicated.
        s["bo"] = P(None)
    return s


def _head_split(cfg, n: int):
    """Per-device head counts; KV-head replication for n > KV-heads is
    not implemented yet, so fail loudly rather than mis-reshape."""
    if cfg.num_attention_heads % n:
        raise ValueError(
            f"num_attention_heads={cfg.num_attention_heads} not divisible "
            f"by tp={n}")
    if cfg.num_key_value_heads % n:
        raise ValueError(
            f"num_key_value_heads={cfg.num_key_value_heads} not divisible "
            f"by tp={n} (KV-head replication unimplemented)")
    return cfg.num_attention_heads // n, cfg.num_key_value_heads // n


def _project_qkv(params, x, *, mode, axis, ag_ctx):
    """Returns (q, k, v, gate) as (tokens_full, *_loc); ``gate`` is
    None unless the layer carries the Qwen3-Next attention gate."""
    if mode == "xla":
        x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        q = jnp.dot(x_full, params["wq"])
        k = jnp.dot(x_full, params["wk"])
        v = jnp.dot(x_full, params["wv"])
    elif mode == "fused":
        q, x_full = ag_gemm(x, params["wq"], ag_ctx, return_ag=True)
        k = jnp.dot(x_full, params["wk"])
        v = jnp.dot(x_full, params["wv"])
    elif mode == "fused_ar":
        # Replicated tokens: plain local projections.
        x_full = x
        q = jnp.dot(x, params["wq"])
        k = jnp.dot(x, params["wk"])
        v = jnp.dot(x, params["wv"])
    else:
        raise ValueError(f"unknown TP_Attn mode {mode!r}")
    if "bq" in params:
        # Column-parallel biases: each shard owns its output columns.
        q = q + params["bq"]
        k = k + params["bk"]
        v = v + params["bv"]
    gate = jnp.dot(x_full, params["wqg"]) if "wqg" in params else None
    return q, k, v, gate


def _o_bias(params, y):
    """Row-parallel output bias — applied AFTER the cross-shard reduce
    (a per-shard add would count it n times)."""
    return y + params["bo"] if "bo" in params else y


def _norm_rope(q, k, params, cfg, positions):
    """q: (B, S, H_loc, hd); k: (B, S, KV_loc, hd)."""
    if "q_norm" in params:       # Qwen3 per-head norm; absent for
        q = rms_norm(q, params["q_norm"], cfg.rms_norm_eps)  # Seed-OSS
        k = rms_norm(k, params["k_norm"], cfg.rms_norm_eps)
    # Partial RoPE (Qwen3-Next rotates only the first fraction of each
    # head; the rest passes through position-free).
    rot = int(cfg.head_dim * getattr(cfg, "partial_rotary_factor", 1.0))
    if rot % 2:
        raise ValueError(
            f"rotary dim {rot} (head_dim {cfg.head_dim} × factor "
            f"{cfg.partial_rotary_factor}) must be even")
    inv_freq = rope_freqs(rot, cfg.rope_theta)
    if rot == cfg.head_dim:
        return (apply_rope(q, positions, inv_freq),
                apply_rope(k, positions, inv_freq))
    rope_part = lambda t: jnp.concatenate(
        [apply_rope(t[..., :rot], positions, inv_freq), t[..., rot:]],
        axis=-1)
    return rope_part(q), rope_part(k)


def sdpa(q, k, v, *, causal: bool, kv_len=None, use_flash=None):
    """q: (B, Sq, H, hd); k/v: (B, Skv, KV, hd). GQA by head repeat.

    ``kv_len`` may be (B,) — one ragged length per batch row — or
    (B, Sq) — a PER-QUERY length, the speculative-verification form
    where query j of a slot attends the paged history plus its own
    candidate block prefix (lens + j + 1).

    On real TPUs with long sequences the bundled Pallas flash-attention
    kernel handles the softmax online (O(S) memory); the jnp path is the
    portable oracle (and handles ragged kv_len masking).
    """
    b, sq, h, hd = q.shape
    skv, kvh = k.shape[1], k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if use_flash is None:
        from triton_dist_tpu.utils.distributed import on_tpu, use_interpret
        use_flash = (on_tpu() and not use_interpret() and kv_len is None
                     and sq >= 128 and skv >= 128 and hd >= 64)
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention)
        o = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal,
            sm_scale=1.0 / float(np_sqrt(hd)))
        return o.transpose(0, 2, 1, 3)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    if causal:
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(skv)[None, :]
        offset = skv - sq  # cache prefix
        mask = ki <= (qi + offset)
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    if kv_len is not None:
        ki = jnp.arange(skv)[None, None, None, :]
        if kv_len.ndim == 2:       # per-query lengths (B, Sq)
            scores = jnp.where(ki < kv_len[:, None, :, None], scores,
                               -jnp.inf)
        else:
            scores = jnp.where(ki < kv_len[:, None, None, None],
                               scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def fwd_prefill(params, x, cfg, *, batch: int, mode: str = "xla",
                axis: str = "tp", ag_ctx=None, rs_ctx=None, ar_ctx=None,
                kv_out: bool = True):
    """x: (tokens_loc, d) token-sharded (or replicated for fused_ar).
    Returns (y in the same layout, (k_cache, v_cache) per-shard)."""
    n = jax.lax.axis_size(axis)
    hd = cfg.head_dim
    h_loc, kv_loc = _head_split(cfg, n)

    q, k, v, gate = _project_qkv(params, x, mode=mode, axis=axis,
                                 ag_ctx=ag_ctx)
    tokens = q.shape[0]
    seq = tokens // batch
    q = q.reshape(batch, seq, h_loc, hd)
    k = k.reshape(batch, seq, kv_loc, hd)
    v = v.reshape(batch, seq, kv_loc, hd)
    positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
    q, k = _norm_rope(q, k, params, cfg, positions)

    o = sdpa(q, k, v, causal=True)
    o = o.reshape(tokens, h_loc * hd)
    if gate is not None:   # Qwen3-Next: sigmoid gate before o_proj
        o = o * jax.nn.sigmoid(gate.astype(jnp.float32)).astype(o.dtype)

    if mode == "xla":
        partial = jnp.dot(o, params["wo"], preferred_element_type=jnp.float32)
        y = jax.lax.psum_scatter(partial, axis, scatter_dimension=0,
                                 tiled=True).astype(x.dtype)
    elif mode == "fused":
        y = gemm_rs(o, params["wo"], rs_ctx)
    else:  # fused_ar
        y = gemm_ar(o, params["wo"], ar_ctx)
    y = _o_bias(params, y)
    return (y, (k, v)) if kv_out else y


def decode_project(params, x, cfg, positions, *, axis: str = "tp"):
    """Project one token per row: QKV + q/k norm + rope.

    x: (B, d) replicated; ``positions``: (B,) int32 — PER-ROW cache
    positions. Two callers, one contract: the continuous-batching
    decode step ropes each SLOT at its own length (one token per slot;
    the single-request form passes a broadcast scalar), and the
    chunked-prefill step ropes a CHUNK of consecutive tokens of one
    slot (rows = positions ``start + arange(C)``) — the projection is
    row-independent, so the same kernel serves both.
    Returns (q (B, 1, H_loc, hd), k_tok (B, 1, KV_loc, hd),
    v_tok (B, 1, KV_loc, hd)); the caller places k/v through the
    cache's ``append_decode`` / ``write_chunk`` contract before
    attending.
    """
    n = jax.lax.axis_size(axis)
    hd = cfg.head_dim
    h_loc, kv_loc = _head_split(cfg, n)
    b = x.shape[0]

    q = jnp.dot(x, params["wq"])
    k = jnp.dot(x, params["wk"])
    v = jnp.dot(x, params["wv"])
    if "bq" in params:
        q, k, v = q + params["bq"], k + params["bk"], v + params["bv"]
    q = q.reshape(b, 1, h_loc, hd)
    k = k.reshape(b, 1, kv_loc, hd)
    v = v.reshape(b, 1, kv_loc, hd)
    pos2 = jnp.asarray(positions, jnp.int32).reshape(b, 1)
    q, k = _norm_rope(q, k, params, cfg, pos2)
    return q, k, v


def decode_output(params, o, x, *, mode: str = "xla", axis: str = "tp",
                  ar_ctx=None):
    """Attention output path of a decode step: optional Qwen3-Next
    sigmoid gate (projected from the layer input ``x``), row-parallel
    o-proj, and the cross-shard reduce. o: (B, h_loc·hd); returns
    (B, d) replicated."""
    if "wqg" in params:   # Qwen3-Next: sigmoid gate before o_proj
        gate = jnp.dot(x, params["wqg"])
        o = o * jax.nn.sigmoid(gate.astype(jnp.float32)).astype(o.dtype)
    if mode in ("xla",):
        y = jax.lax.psum(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis).astype(x.dtype)
    else:  # fused / fused_ar decode both use gemm_ar (small M)
        y = gemm_ar(o, params["wo"], ar_ctx)
    return _o_bias(params, y)


def fwd_decode(params, x, cfg, k_cache, v_cache, cache_len, *,
               mode: str = "xla", axis: str = "tp", ar_ctx=None):
    """Single-token decode. x: (B, d) replicated; caches
    (B, max_len, KV_loc, hd); cache_len: scalar current length.
    Returns (y (B, d) replicated, updated caches).

    Composition of :func:`decode_project` → cache append →
    :func:`sdpa` → :func:`decode_output`; kept as the whole-layer
    entry point for per-layer-cache callers (qwen_next's hybrid
    decode). The Engine's dense path drives the same pieces through
    :meth:`KVCache.append_decode` instead.

    Reference: decode path of ``TP_Attn`` + ``KV_Cache``
    (``models/kv_cache.py``), gemm_ar mode (``e2e_dense.md:34``).
    """
    b = x.shape[0]
    positions = jnp.broadcast_to(cache_len, (b,)).astype(jnp.int32)
    q, k, v = decode_project(params, x, cfg, positions, axis=axis)

    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (0, cache_len, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (0, cache_len, 0, 0))

    kv_len = jnp.full((b,), cache_len + 1, dtype=jnp.int32)
    o = sdpa(q, k_cache, v_cache, causal=False, kv_len=kv_len)
    o = o.reshape(b, -1)
    y = decode_output(params, o, x, mode=mode, axis=axis, ar_ctx=ar_ctx)
    return y, (k_cache, v_cache)
