"""Tensor-parallel Gated-DeltaNet mixer (Qwen3-Next linear attention).

Reference: the GDN kernel ``kernels/nvidia/gdn.py`` (chunked gated
delta-rule forward, built for Qwen3-Next). This layer gives it the same
TP treatment ``layers/nvidia/tp_attn.py`` gives softmax attention:

- heads sharded along ``tp``; residual stream token-sharded in
  "xla"/"fused" modes, replicated in "fused_ar" decode mode;
- in-projections ride :func:`~triton_dist_tpu.ops.ag_gemm` ("fused":
  the AG buffer is reused across q/k/v/gate projections, the reference
  TP_Attn trick), the out-projection rides
  :func:`~triton_dist_tpu.ops.gemm_rs` / :func:`~triton_dist_tpu.ops.
  gemm_ar`;
- prefill runs the chunked WY-form kernel
  (:func:`~triton_dist_tpu.ops.gdn.gdn_fwd_chunked`), decode the O(1)
  recurrent step — the recurrent state (H_loc, dk, dv) is the "KV
  cache" of this layer family and stays head-sharded like KV heads.

Gate parameterization: ``g = -softplus(x·wg + g_bias)`` (decay ≤ 0),
``beta = sigmoid(x·wb)`` — the standard gated-delta-net form.

TWO CELLS share this module, selected by ``cfg.gdn_conv_kernel``:

- 0 — the in-framework simplified cell above (wq/wk/wv/wg/wb/g_bias/wo,
  equal k/v head counts, no conv);
- >0 — the HF-checkpoint-faithful Qwen3-Next GatedDeltaNet
  (``transformers/models/qwen3_next`` ``Qwen3NextGatedDeltaNet``):
  short causal depthwise conv over (q,k,v) with SiLU, separate key/value
  head counts with GQA repeat, ``g = -exp(A_log)·softplus(a+dt_bias)``,
  z-gated per-head RMSNorm before the out-projection, q scaled by
  ``dk**-0.5``. The mapper (``models/hf_loader.py``) de-interleaves
  ``in_proj_qkvz``/``in_proj_ba``/``conv1d`` into this head-major
  TP-shardable layout at load time.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import ag_gemm, gemm_rs, gemm_ar
from triton_dist_tpu.ops.gdn import gdn_fwd_chunked, gdn_decode_step


def init(key, cfg, dtype=jnp.float32) -> Dict:
    if getattr(cfg, "gdn_conv_kernel", 0):
        return init_hf(key, cfg, dtype)
    kq, kk, kv, kg, kb, ko = jax.random.split(key, 6)
    d = cfg.hidden_size
    h = cfg.gdn_num_heads
    dk = cfg.gdn_head_dim_k
    dv = cfg.gdn_head_dim_v
    scale = d ** -0.5
    return {
        "wq": jax.random.normal(kq, (d, h * dk), dtype) * scale,
        "wk": jax.random.normal(kk, (d, h * dk), dtype) * scale,
        "wv": jax.random.normal(kv, (d, h * dv), dtype) * scale,
        "wg": jax.random.normal(kg, (d, h), dtype) * scale,
        "wb": jax.random.normal(kb, (d, h), dtype) * scale,
        # Bias init so decays start slow (exp(-softplus(1)) ≈ 0.27/token
        # would forget too fast at random init; +2 keeps early training
        # stable and tests numerically interesting).
        "g_bias": jnp.full((h,), 2.0, dtype),
        "wo": jax.random.normal(ko, (h * dv, d), dtype) * (
            (h * dv) ** -0.5),
    }


def param_specs(axis: str = "tp", cfg=None) -> Dict:
    if cfg is not None and getattr(cfg, "gdn_conv_kernel", 0):
        return param_specs_hf(axis)
    return {
        "wq": P(None, axis),
        "wk": P(None, axis),
        "wv": P(None, axis),
        "wg": P(None, axis),
        "wb": P(None, axis),
        "g_bias": P(None),
        "wo": P(axis, None),
    }


# ---------------------------------------------------------------------------
# HF-faithful Qwen3-Next cell
# ---------------------------------------------------------------------------

def init_hf(key, cfg, dtype=jnp.float32) -> Dict:
    """Checkpoint-compatible parameter tree, already de-interleaved to
    head-major per-projection matrices (the layout the mapper emits)."""
    ks = jax.random.split(key, 8)
    d = cfg.hidden_size
    hk, hv = cfg.gdn_num_kh, cfg.gdn_num_heads
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    kk = cfg.gdn_conv_kernel
    s = d ** -0.5
    return {
        "wq": jax.random.normal(ks[0], (d, hk * dk), dtype) * s,
        "wk": jax.random.normal(ks[1], (d, hk * dk), dtype) * s,
        "wv": jax.random.normal(ks[2], (d, hv * dv), dtype) * s,
        "wz": jax.random.normal(ks[3], (d, hv * dv), dtype) * s,
        "wb": jax.random.normal(ks[4], (d, hv), dtype) * s,
        "wa": jax.random.normal(ks[5], (d, hv), dtype) * s,
        # Depthwise causal conv taps, channel-major [q | k | v] in the
        # head-major flat layout (so channel rows shard with the heads).
        "conv_q": jnp.zeros((hk * dk, kk), dtype).at[:, -1].set(1.0),
        "conv_k": jnp.zeros((hk * dk, kk), dtype).at[:, -1].set(1.0),
        "conv_v": jnp.zeros((hv * dv, kk), dtype).at[:, -1].set(1.0),
        "A_log": jnp.zeros((hv,), dtype),
        "dt_bias": jnp.ones((hv,), dtype),
        "norm_w": jnp.ones((dv,), dtype),
        "wo": jax.random.normal(ks[6], (hv * dv, d), dtype) * (
            (hv * dv) ** -0.5),
    }


def param_specs_hf(axis: str = "tp") -> Dict:
    return {
        "wq": P(None, axis), "wk": P(None, axis),
        "wv": P(None, axis), "wz": P(None, axis),
        "wb": P(None, axis), "wa": P(None, axis),
        "conv_q": P(axis, None), "conv_k": P(axis, None),
        "conv_v": P(axis, None),
        "A_log": P(axis), "dt_bias": P(axis),
        "norm_w": P(None),          # per-head dv weight — replicated
        "wo": P(axis, None),
    }


def _hf_heads_loc(cfg, n: int):
    hk, hv = cfg.gdn_num_kh, cfg.gdn_num_heads
    if hk % n or hv % n:
        raise ValueError(f"gdn heads ({hk} k, {hv} v) not divisible "
                         f"by tp={n}")
    return hk // n, hv // n


def _causal_conv(x, w, k_size: int, state=None):
    """Depthwise causal conv along seq. x: (B, S, C); w: (C, K);
    ``state``: (B, C, K-1) trailing raw inputs from the previous
    segment (None = zero history). Returns (y (B, S, C) with SiLU
    applied, new_state (B, C, K-1))."""
    b, s, c = x.shape
    if state is None:
        xp = jnp.pad(x, ((0, 0), (k_size - 1, 0), (0, 0)))
    else:
        xp = jnp.concatenate([state.swapaxes(1, 2), x], axis=1)
    y = sum(xp[:, j:j + s, :] * w[:, j] for j in range(k_size))
    new_state = xp[:, xp.shape[1] - (k_size - 1):, :].swapaxes(1, 2)
    return jax.nn.silu(y.astype(jnp.float32)).astype(x.dtype), new_state


def _hf_gdn_core(q, k, v, z, b, a, params, cfg, h_kloc, h_vloc, *,
                 decode: bool, state, chunk: int = 64):
    """Shared post-projection math: decay/beta parameterization, GQA
    repeat, delta rule, z-gated RMSNorm. Shapes are (B, S, ...) flats;
    decode means S == 1 with a recurrent state."""
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    rep = cfg.gdn_num_heads // cfg.gdn_num_kh
    bsz, s = q.shape[0], q.shape[1]

    q = q.reshape(bsz, s, h_kloc, dk)
    k = k.reshape(bsz, s, h_kloc, dk)
    v = v.reshape(bsz, s, h_vloc, dv)
    if rep > 1:
        q = jnp.repeat(q, rep, axis=2)
        k = jnp.repeat(k, rep, axis=2)

    beta = jax.nn.sigmoid(b.astype(jnp.float32))
    g = (-jnp.exp(params["A_log"].astype(jnp.float32))
         * jax.nn.softplus(a.astype(jnp.float32)
                           + params["dt_bias"].astype(jnp.float32)))
    scale = dk ** -0.5

    if decode:
        o, s_new = jax.vmap(
            lambda S_, q_, k_, v_, g_, b_: gdn_decode_step(
                S_, q_, k_, v_, g_, b_, scale=scale)
        )(state, q[:, 0], k[:, 0], v[:, 0], g[:, 0], beta[:, 0])
        o = o[:, None]                       # (B, 1, Hv_loc, dv)
    else:
        o, s_new = jax.vmap(
            lambda q_, k_, v_, g_, b_: gdn_fwd_chunked(
                q_, k_, v_, g_, b_, chunk=chunk, scale=scale)
        )(q, k, v, g, beta)

    # Z-gated per-head RMSNorm (HF Qwen3NextRMSNormGated: norm then
    # weight then SiLU(z) gate, fp32 internally).
    z = z.reshape(bsz, s, h_vloc, dv).astype(jnp.float32)
    o32 = o.astype(jnp.float32)
    var = jnp.mean(o32 * o32, axis=-1, keepdims=True)
    o32 = o32 * jax.lax.rsqrt(var + cfg.rms_norm_eps)
    o32 = o32 * params["norm_w"].astype(jnp.float32)
    o32 = o32 * jax.nn.silu(z)
    return o32.astype(v.dtype).reshape(bsz, s, h_vloc * dv), s_new


def fwd_prefill_hf(params, x, cfg, *, batch: int, mode: str = "xla",
                   axis: str = "tp", ag_ctx=None, rs_ctx=None,
                   ar_ctx=None, chunk: int = 64):
    """HF-cell prefill. x: (tokens_loc, d) token-sharded. Returns
    (out tokens_loc-sharded, (state (B, Hv_loc, dk, dv),
    conv_state (B, C_loc, K-1)))."""
    n = jax.lax.axis_size(axis)
    h_kloc, h_vloc = _hf_heads_loc(cfg, n)
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    kk = cfg.gdn_conv_kernel

    if mode == "xla":
        x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        q = jnp.dot(x_full, params["wq"])
    elif mode == "fused":
        q, x_full = ag_gemm(x, params["wq"], ag_ctx, return_ag=True)
    else:
        raise ValueError(f"unknown GDN prefill mode {mode!r}")
    k = jnp.dot(x_full, params["wk"])
    v = jnp.dot(x_full, params["wv"])
    z = jnp.dot(x_full, params["wz"])
    b = jnp.dot(x_full, params["wb"])
    a = jnp.dot(x_full, params["wa"])

    s_full = x_full.shape[0] // batch
    seq = lambda t: t.reshape(batch, s_full, t.shape[-1])
    q, k, v, z, b, a = map(seq, (q, k, v, z, b, a))

    # Causal depthwise conv + SiLU over the local (q,k,v) channels.
    conv_w = jnp.concatenate(
        [params["conv_q"], params["conv_k"], params["conv_v"]], axis=0)
    qkv, conv_state = _causal_conv(
        jnp.concatenate([q, k, v], axis=-1), conv_w, kk)
    q, k, v = jnp.split(
        qkv, [h_kloc * dk, 2 * h_kloc * dk], axis=-1)

    o, state = _hf_gdn_core(q, k, v, z, b, a, params, cfg,
                            h_kloc, h_vloc, decode=False, state=None,
                            chunk=chunk)
    o = o.reshape(batch * s_full, h_vloc * dv)

    if mode == "fused":
        out = gemm_rs(o, params["wo"], rs_ctx)
    else:
        out = jax.lax.psum_scatter(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis, scatter_dimension=0, tiled=True).astype(x.dtype)
    return out, (state, conv_state)


def fwd_decode_hf(params, x, cfg, state, conv_state, *,
                  mode: str = "xla", axis: str = "tp", ar_ctx=None):
    """HF-cell decode. x: (B, d) replicated; state (B, Hv_loc, dk, dv);
    conv_state (B, C_loc, K-1). Returns (out, state', conv_state')."""
    n = jax.lax.axis_size(axis)
    h_kloc, h_vloc = _hf_heads_loc(cfg, n)
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    kk = cfg.gdn_conv_kernel
    bsz = x.shape[0]

    q = jnp.dot(x, params["wq"])[:, None]
    k = jnp.dot(x, params["wk"])[:, None]
    v = jnp.dot(x, params["wv"])[:, None]
    z = jnp.dot(x, params["wz"])[:, None]
    b = jnp.dot(x, params["wb"])[:, None]
    a = jnp.dot(x, params["wa"])[:, None]

    conv_w = jnp.concatenate(
        [params["conv_q"], params["conv_k"], params["conv_v"]], axis=0)
    qkv, conv_state = _causal_conv(
        jnp.concatenate([q, k, v], axis=-1), conv_w, kk,
        state=conv_state)
    q, k, v = jnp.split(
        qkv, [h_kloc * dk, 2 * h_kloc * dk], axis=-1)

    o, s_new = _hf_gdn_core(q, k, v, z, b, a, params, cfg,
                            h_kloc, h_vloc, decode=True, state=state)
    o = o.reshape(bsz, h_vloc * dv)

    if mode == "fused_ar":
        out = gemm_ar(o, params["wo"], ar_ctx)
    else:
        out = jax.lax.psum(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis).astype(x.dtype)
    return out, s_new, conv_state


def _heads_loc(cfg, n: int) -> int:
    if cfg.gdn_num_heads % n:
        raise ValueError(f"gdn_num_heads={cfg.gdn_num_heads} not "
                         f"divisible by tp={n}")
    return cfg.gdn_num_heads // n


def _gates(x_full, params, h_loc, axis, n):
    """g (≤ 0) and beta from the gathered tokens; wg/wb are
    column-parallel so each rank computes its heads' gates locally."""
    me = jax.lax.axis_index(axis)
    bias = jax.lax.dynamic_slice_in_dim(params["g_bias"], me * h_loc,
                                        h_loc, 0)
    g_raw = jnp.dot(x_full, params["wg"]) + bias
    g = -jax.nn.softplus(g_raw.astype(jnp.float32))
    beta = jax.nn.sigmoid(jnp.dot(x_full, params["wb"]
                                  ).astype(jnp.float32))
    return g, beta


def fwd_prefill(params, x, cfg, *, batch: int, mode: str = "xla",
                axis: str = "tp", ag_ctx=None, rs_ctx=None, ar_ctx=None,
                chunk: int = 16):
    """x: (tokens_loc, d) token-sharded ("xla"/"fused"). Returns
    (out tokens_loc-sharded, state (B, H_loc, dk, dv))."""
    n = jax.lax.axis_size(axis)
    h_loc = _heads_loc(cfg, n)
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v

    if mode == "xla":
        x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        q = jnp.dot(x_full, params["wq"])
    elif mode == "fused":
        q, x_full = ag_gemm(x, params["wq"], ag_ctx, return_ag=True)
    else:
        raise ValueError(f"unknown GDN prefill mode {mode!r}")
    k = jnp.dot(x_full, params["wk"])
    v = jnp.dot(x_full, params["wv"])
    g, beta = _gates(x_full, params, h_loc, axis, n)

    s_full = x_full.shape[0] // batch
    shp = lambda t, hd: t.reshape(batch, s_full, h_loc, hd)
    q, k = shp(q, dk), shp(k, dk)
    v = shp(v, dv)
    g = g.reshape(batch, s_full, h_loc)
    beta = beta.reshape(batch, s_full, h_loc)

    o, state = jax.vmap(
        lambda q_, k_, v_, g_, b_: gdn_fwd_chunked(q_, k_, v_, g_, b_,
                                                   chunk=chunk)
    )(q, k, v, g, beta)
    o = o.reshape(batch * s_full, h_loc * dv)

    if mode == "fused":
        out = gemm_rs(o, params["wo"], rs_ctx)
    else:
        out = jax.lax.psum_scatter(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis, scatter_dimension=0, tiled=True).astype(x.dtype)
    return out, state


def fwd_decode(params, x, cfg, state, *, mode: str = "xla",
               axis: str = "tp", ar_ctx=None):
    """One token per sequence. x: (B, d) replicated; state:
    (B, H_loc, dk, dv). Returns (out (B, d) replicated, new state)."""
    n = jax.lax.axis_size(axis)
    h_loc = _heads_loc(cfg, n)
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    b = x.shape[0]

    q = jnp.dot(x, params["wq"]).reshape(b, h_loc, dk)
    k = jnp.dot(x, params["wk"]).reshape(b, h_loc, dk)
    v = jnp.dot(x, params["wv"]).reshape(b, h_loc, dv)
    g, beta = _gates(x, params, h_loc, axis, n)

    o, new_state = jax.vmap(gdn_decode_step)(state, q, k, v, g, beta)
    o = o.reshape(b, h_loc * dv)

    if mode == "fused_ar":
        out = gemm_ar(o, params["wo"], ar_ctx)
    else:
        out = jax.lax.psum(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis).astype(x.dtype)
    return out, new_state
