"""Tensor-parallel Gated-DeltaNet mixer (Qwen3-Next linear attention).

Reference: the GDN kernel ``kernels/nvidia/gdn.py`` (chunked gated
delta-rule forward, built for Qwen3-Next). This layer gives it the same
TP treatment ``layers/nvidia/tp_attn.py`` gives softmax attention:

- heads sharded along ``tp``; residual stream token-sharded in
  "xla"/"fused" modes, replicated in "fused_ar" decode mode;
- in-projections ride :func:`~triton_dist_tpu.ops.ag_gemm` ("fused":
  the AG buffer is reused across q/k/v/gate projections, the reference
  TP_Attn trick), the out-projection rides
  :func:`~triton_dist_tpu.ops.gemm_rs` / :func:`~triton_dist_tpu.ops.
  gemm_ar`;
- prefill runs the chunked WY-form kernel
  (:func:`~triton_dist_tpu.ops.gdn.gdn_fwd_chunked`), decode the O(1)
  recurrent step — the recurrent state (H_loc, dk, dv) is the "KV
  cache" of this layer family and stays head-sharded like KV heads.

Gate parameterization: ``g = -softplus(x·wg + g_bias)`` (decay ≤ 0),
``beta = sigmoid(x·wb)`` — the standard gated-delta-net form.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import ag_gemm, gemm_rs, gemm_ar
from triton_dist_tpu.ops.gdn import gdn_fwd_chunked, gdn_decode_step


def init(key, cfg, dtype=jnp.float32) -> Dict:
    kq, kk, kv, kg, kb, ko = jax.random.split(key, 6)
    d = cfg.hidden_size
    h = cfg.gdn_num_heads
    dk = cfg.gdn_head_dim_k
    dv = cfg.gdn_head_dim_v
    scale = d ** -0.5
    return {
        "wq": jax.random.normal(kq, (d, h * dk), dtype) * scale,
        "wk": jax.random.normal(kk, (d, h * dk), dtype) * scale,
        "wv": jax.random.normal(kv, (d, h * dv), dtype) * scale,
        "wg": jax.random.normal(kg, (d, h), dtype) * scale,
        "wb": jax.random.normal(kb, (d, h), dtype) * scale,
        # Bias init so decays start slow (exp(-softplus(1)) ≈ 0.27/token
        # would forget too fast at random init; +2 keeps early training
        # stable and tests numerically interesting).
        "g_bias": jnp.full((h,), 2.0, dtype),
        "wo": jax.random.normal(ko, (h * dv, d), dtype) * (
            (h * dv) ** -0.5),
    }


def param_specs(axis: str = "tp") -> Dict:
    return {
        "wq": P(None, axis),
        "wk": P(None, axis),
        "wv": P(None, axis),
        "wg": P(None, axis),
        "wb": P(None, axis),
        "g_bias": P(None),
        "wo": P(axis, None),
    }


def _heads_loc(cfg, n: int) -> int:
    if cfg.gdn_num_heads % n:
        raise ValueError(f"gdn_num_heads={cfg.gdn_num_heads} not "
                         f"divisible by tp={n}")
    return cfg.gdn_num_heads // n


def _gates(x_full, params, h_loc, axis, n):
    """g (≤ 0) and beta from the gathered tokens; wg/wb are
    column-parallel so each rank computes its heads' gates locally."""
    me = jax.lax.axis_index(axis)
    bias = jax.lax.dynamic_slice_in_dim(params["g_bias"], me * h_loc,
                                        h_loc, 0)
    g_raw = jnp.dot(x_full, params["wg"]) + bias
    g = -jax.nn.softplus(g_raw.astype(jnp.float32))
    beta = jax.nn.sigmoid(jnp.dot(x_full, params["wb"]
                                  ).astype(jnp.float32))
    return g, beta


def fwd_prefill(params, x, cfg, *, batch: int, mode: str = "xla",
                axis: str = "tp", ag_ctx=None, rs_ctx=None, ar_ctx=None,
                chunk: int = 16):
    """x: (tokens_loc, d) token-sharded ("xla"/"fused"). Returns
    (out tokens_loc-sharded, state (B, H_loc, dk, dv))."""
    n = jax.lax.axis_size(axis)
    h_loc = _heads_loc(cfg, n)
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v

    if mode == "xla":
        x_full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        q = jnp.dot(x_full, params["wq"])
    elif mode == "fused":
        q, x_full = ag_gemm(x, params["wq"], ag_ctx, return_ag=True)
    else:
        raise ValueError(f"unknown GDN prefill mode {mode!r}")
    k = jnp.dot(x_full, params["wk"])
    v = jnp.dot(x_full, params["wv"])
    g, beta = _gates(x_full, params, h_loc, axis, n)

    s_full = x_full.shape[0] // batch
    shp = lambda t, hd: t.reshape(batch, s_full, h_loc, hd)
    q, k = shp(q, dk), shp(k, dk)
    v = shp(v, dv)
    g = g.reshape(batch, s_full, h_loc)
    beta = beta.reshape(batch, s_full, h_loc)

    o, state = jax.vmap(
        lambda q_, k_, v_, g_, b_: gdn_fwd_chunked(q_, k_, v_, g_, b_,
                                                   chunk=chunk)
    )(q, k, v, g, beta)
    o = o.reshape(batch * s_full, h_loc * dv)

    if mode == "fused":
        out = gemm_rs(o, params["wo"], rs_ctx)
    else:
        out = jax.lax.psum_scatter(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis, scatter_dimension=0, tiled=True).astype(x.dtype)
    return out, state


def fwd_decode(params, x, cfg, state, *, mode: str = "xla",
               axis: str = "tp", ar_ctx=None):
    """One token per sequence. x: (B, d) replicated; state:
    (B, H_loc, dk, dv). Returns (out (B, d) replicated, new state)."""
    n = jax.lax.axis_size(axis)
    h_loc = _heads_loc(cfg, n)
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    b = x.shape[0]

    q = jnp.dot(x, params["wq"]).reshape(b, h_loc, dk)
    k = jnp.dot(x, params["wk"]).reshape(b, h_loc, dk)
    v = jnp.dot(x, params["wv"]).reshape(b, h_loc, dv)
    g, beta = _gates(x, params, h_loc, axis, n)

    o, new_state = jax.vmap(gdn_decode_step)(state, q, k, v, g, beta)
    o = o.reshape(b, h_loc * dv)

    if mode == "fused_ar":
        out = gemm_ar(o, params["wo"], ar_ctx)
    else:
        out = jax.lax.psum(
            jnp.dot(o, params["wo"], preferred_element_type=jnp.float32),
            axis).astype(x.dtype)
    return out, new_state
