"""ModelBuilder: record a decode step as tasks, schedule natively, run
as ONE persistent Pallas kernel.

Reference: ``mega_triton_kernel/models/model_builder.py:86``
``ModelBuilder`` — records ops via task builders (:192), ``compile()``
:514 (dep opt → enqueue → codegen → import), ``run()`` :557 launching
``MEGA_TRITON_KERNEL[grid=(NUM_SMS,)]``.

TPU differences: instead of generating Triton source text, the kernel
is a *task interpreter* — grid = the core's work queue, task descriptors
arrive via scalar prefetch, dispatch is ``lax.switch``
(``megakernel/kernels.py``); the C++ scheduler orders/packs the queue.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.lang import core_call, comm_compiler_params
from triton_dist_tpu.megakernel import kernels as K
from triton_dist_tpu.megakernel.graph import Graph, comm_priority
from triton_dist_tpu.megakernel.scheduler import (
    prune_deps, schedule_dyn, schedule_mc, simulate_static)
from triton_dist_tpu.megakernel.task import ARGS_MAX, TaskType
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.parallel.mesh import MeshContext


def _cdiv(a, b):
    return -(-a // b)


# Region kinds a serving checkpoint must carry: the KV pools and their
# quantization scales, the hybrid recurrent state, and the in-arena
# counters (everything else is weights — repacked from params — or
# per-step activation scratch).
SNAPSHOT_KINDS = ("kv", "scale", "state", "counter")

# Kinds that occupy rows of the (rows, w) arena itself; the rest are
# named DEVICE BUFFERS (KV pools, scale tables, GDN state) that ride
# beside the arena through the kernel's aliased operands.
ARENA_KINDS = ("weight", "activation", "workspace", "counter", "io")


@dataclasses.dataclass(frozen=True)
class ArenaRegion:
    """One named region of the megakernel's memory layout.

    In-arena kinds (``weight``/``activation``/``workspace``/
    ``counter``/``io``) describe ``rows`` rows at ``offset`` of the
    (arena_rows, w) arena; buffer kinds (``kv``/``scale``/``state``)
    describe a standalone device array of ``shape``/``dtype`` that the
    kernel addresses through its own aliased operand."""

    name: str
    kind: str
    offset: int = 0
    rows: int = 0
    shape: Tuple[int, ...] = ()
    dtype: str = "float32"

    @property
    def in_arena(self) -> bool:
        return self.kind in ARENA_KINDS


class ArenaSchema:
    """Described memory layout of a megakernel build: every region —
    weight tiles, activation tiles, the allreduce workspace, MoE
    router counters, KV pools and their per-(layer, page, kv_head)
    scale tables, GDN state — by name, with offset/rows (in-arena) or
    shape/dtype (device buffers). Replaces the bare ``_alloc`` cursor
    arithmetic: consumers (engine checkpoint/restore, the chaos
    sweep's arena-coherence check, docs) address regions by NAME, so
    adding a region is one ``alloc``/``add_buffer`` call, never
    offset bookkeeping (see docs/megakernel.md, "Arena schema")."""

    def __init__(self, w: int):
        self.w = int(w)
        self._regions: "Dict[str, ArenaRegion]" = {}
        self._cursor = 0

    # -- building ----------------------------------------------------
    def alloc(self, name: str, rows: int, kind: str = "activation"
              ) -> int:
        """Claim ``rows`` arena rows for ``name``; returns the offset
        (the cursor allocator, now with provenance)."""
        if kind not in ARENA_KINDS:
            raise ValueError(f"kind {kind!r} is not an in-arena kind "
                             f"{ARENA_KINDS}")
        if name in self._regions:
            raise ValueError(f"arena region {name!r} already allocated")
        off = self._cursor
        self._regions[name] = ArenaRegion(name=name, kind=kind,
                                          offset=off, rows=int(rows))
        self._cursor += int(rows)
        return off

    def add_buffer(self, name: str, shape, dtype, kind: str) -> None:
        """Register a named device buffer (KV pool, scale table, GDN
        state) that lives beside the row arena."""
        if kind in ARENA_KINDS:
            raise ValueError(f"kind {kind!r} is an in-arena kind — use "
                             "alloc()")
        if name in self._regions:
            raise ValueError(f"arena region {name!r} already allocated")
        self._regions[name] = ArenaRegion(
            name=name, kind=kind, shape=tuple(int(s) for s in shape),
            dtype=str(dtype))

    # -- reading -----------------------------------------------------
    @property
    def rows(self) -> int:
        """Total arena rows claimed so far (the pack/zero footprint)."""
        return self._cursor

    def __contains__(self, name: str) -> bool:
        return name in self._regions

    def __iter__(self):
        return iter(self._regions.values())

    def region(self, name: str) -> ArenaRegion:
        return self._regions[name]

    def regions(self, kind: Optional[str] = None):
        """All regions, or just one kind's, in allocation order."""
        return [r for r in self._regions.values()
                if kind is None or r.kind == kind]

    def snapshot_regions(self):
        """The regions a checkpoint snapshots by name (KV + scales +
        counters + GDN state — bit-exact at any kv_dtype)."""
        return [r for r in self._regions.values()
                if r.kind in SNAPSHOT_KINDS]

    def check_disjoint(self) -> None:
        """Arena coherence: in-arena regions must tile [0, rows) with
        no overlap and no gap — the invariant the chaos sweep asserts
        per tick (a drifted offset would silently alias a weight tile
        onto an activation or counter)."""
        spans = sorted((r.offset, r.offset + r.rows, r.name)
                       for r in self._regions.values() if r.in_arena)
        at = 0
        for start, end, name in spans:
            if start != at:
                kind = "overlaps the previous region" \
                    if start < at else "leaves an unclaimed gap"
                raise ValueError(
                    f"arena region {name!r} at [{start}, {end}) {kind} "
                    f"(cursor was at {at})")
            at = end
        if at != self._cursor:
            raise ValueError(
                f"arena regions cover {at} rows but the cursor claims "
                f"{self._cursor}")

    def describe(self):
        """Plain-data region table (docs / diagnostics)."""
        out = []
        for r in self._regions.values():
            if r.in_arena:
                out.append({"name": r.name, "kind": r.kind,
                            "offset": r.offset, "rows": r.rows})
            else:
                out.append({"name": r.name, "kind": r.kind,
                            "shape": list(r.shape), "dtype": r.dtype})
        return out


def calibrate_cost_table(observations) -> dict:
    """Profile-feedback calibration: solve per-task-type unit times
    from wall-clock observations of whole megakernel steps.

    observations: list of (unit_counts, wall_seconds) where
    ``unit_counts`` is :meth:`ModelBuilder.task_unit_counts` for that
    build — at least as many observations as distinct task types, from
    builds that vary the type mix (layer count, batch, seq). Solves the
    least-squares system ``counts @ x = wall`` (x >= 0) and returns a
    ``cost_table`` {task_type: weight} normalized so the smallest
    positive weight is 1.0 — feed it back into
    ``ModelBuilder(cost_table=...)`` to re-schedule ``cost_lpt`` from
    measured times (reference ``enable_runtime_scheduler``,
    ``model_builder.py:521-524``, answered at schedule time).

    Raises ``ValueError`` when the observation mix is rank-deficient
    (e.g. proportional count vectors): the minimum-norm solution would
    be weights proportional to counts — garbage the schedule (and a
    Perfetto export labeled "calibrated") would then trust. Vary the
    shapes until every type's unit time is identifiable.
    """
    types = sorted({k for counts, _ in observations for k in counts})
    a = np.array([[counts.get(k, 0) for k in types]
                  for counts, _ in observations], np.float64)
    b = np.array([w for _, w in observations], np.float64)
    rank = np.linalg.matrix_rank(a)
    if rank < len(types):
        raise ValueError(
            f"calibrate_cost_table: observation matrix rank {rank} < "
            f"{len(types)} task types — per-type unit times are not "
            "identifiable; add observations with different type mixes "
            "(vary layer count / batch / seq)")
    x, *_ = np.linalg.lstsq(a, b, rcond=None)
    x = np.clip(x, 0.0, None)
    pos = x[x > 0]
    if pos.size == 0:
        return {k: 1.0 for k in types}
    x = x / pos.min()
    return {k: float(v) for k, v in zip(types, x)}


class ModelBuilder:
    """Builds the Qwen3 dense decode step as a megakernel."""

    def __init__(self, cfg: ModelConfig, mesh, *, batch: int,
                 max_len: int, axis: str = "tp",
                 tile_w: Optional[int] = None, t_tile: Optional[int] = None,
                 num_cores: int = 1, strategy: str = "round_robin",
                 schedule: str = "static",
                 seq: int = 1, paged: bool = False,
                 page: Optional[int] = None, profile: bool = False,
                 cost_table: Optional[dict] = None,
                 expert_load=None, kv_quant: Optional[str] = None,
                 qblock: bool = False, chunk: bool = False,
                 counts_rows: Optional[int] = None):
        """``num_cores`` > 1 packs tasks onto per-core queues executed
        over a CORE_PARALLEL grid dimension (TPU megacore; v4/v5p have
        two TensorCores) with cross-core deps enforced by edge
        semaphores — the reference's per-SM queues + scoreboard
        (``core/scheduler.py:42-100``). ``strategy="cost_lpt"`` is the
        static load-balanced analogue of the reference's
        ``enable_runtime_scheduler`` (TPU cores share no atomic queue
        head, so balancing happens at schedule time from task costs).

        ``schedule="dynamic"`` replaces the per-core slot lists with
        the dynamic scoreboard scheduler: a comm-priority-ordered claim
        list popped at run time via a claim counter in the scoreboard
        workspace (SMEM counter + per-priority-bucket claim
        semaphores), so no slot carries a precomputed task binding and
        the merged-order NOOP padding disappears — the closest TPU form
        of the reference's in-kernel atomic queue head. ``strategy`` is
        ignored in dynamic mode; the claim order comes from
        ``graph.comm_priority`` (remote-peer-unblocking collectives
        first, critical path as tiebreak), sharpened by the same
        ``cost_table`` feedback ``cost_lpt`` uses."""
        if getattr(cfg, "attention_bias", False) or not getattr(
                cfg, "qk_norm", True):
            raise NotImplementedError(
                "megakernel task set covers the Qwen3 layer shape "
                "(no attention biases, per-head q/k norm); serve "
                "bias-carrying / norm-free checkpoints (Seed-OSS) "
                "through the layer Engine")
        if getattr(cfg, "gdn_conv_kernel", 0) or getattr(
                cfg, "attn_gate", False):
            raise NotImplementedError(
                "megakernel hybrid tasks cover the simplified "
                "(conv-free) GDN cell; serve HF qwen3_next checkpoints "
                "(conv + attention gate) through the layer Engine")
        self.cfg = cfg
        self.mesh = mesh
        self.mctx = MeshContext.from_mesh(mesh)
        self.axis = axis
        self.n = self.mctx.size(axis)
        self.batch = batch
        self.max_len = max_len
        self.num_cores = num_cores
        self.strategy = strategy
        if schedule not in ("static", "dynamic"):
            raise ValueError(f"schedule must be 'static' or 'dynamic', "
                             f"got {schedule!r}")
        self.schedule = schedule
        # Scoreboard progress tracing (see _kernel): env-gated so the
        # resilience harness can attribute a wedged schedule to its
        # last-completed queue slot.
        self.trace_progress = os.environ.get(
            "TRITON_DIST_TPU_TRACE_PROGRESS") == "1"
        # profile=True: the step emits a 4th output — one (task_type,
        # arg0) row per executed queue slot — feeding core_activity()
        # (the reference megakernel's SM-activity metric,
        # model_builder.py:164-190) and the Perfetto exporter.
        self.profile = profile
        # cost_table: measured per-unit weights {int(TaskType): float}
        # multiplying the static unit estimates — the profile-feedback
        # loop (calibrate_cost_table) re-schedules cost_lpt from
        # MEASURED task times, the static-TPU answer to the reference's
        # runtime scheduler (model_builder.py:521-524: no cross-core
        # atomic queue head exists here, so balance moves to schedule
        # time but from silicon numbers).
        self.cost_table = dict(cost_table) if cost_table else None
        # expert_load: per-expert weights (the serving layer's load
        # EWMA) biasing the DYNAMIC claim order toward hot experts'
        # group-GEMM/combine chains (graph.comm_priority expert_load).
        # Refresh between steps via reprioritize() — claim tables are
        # host data, so no graph rebuild is needed.
        self.expert_load = (list(expert_load) if expert_load is not None
                            else None)
        # seq > 1: batched prefill — ``batch`` counts ROWS (B*S, b-major)
        # and the attention/cache tasks use the causal prefill bodies.
        # qblock=True instead selects the Q-BLOCK VERIFICATION pair
        # (WRITE_KV_QBLOCK/ATTN_QBLOCK): seq = K rows per slot, each
        # row at its OWN per-row position (len_s[row]; < 0 masks the
        # row) — the speculative-decode verification chain as one
        # megakernel launch.
        self.seq = seq
        self.qblock = bool(qblock)
        # chunk=True selects the PREFILL-CHUNK pair (WRITE_KV_CHUNK/
        # ATTN_CHUNK): one C-row prompt chunk per launch (batch = seq
        # = C, one slot), per-row positions sign-encoded in the
        # cache_len vector (kernels._chunk_apos) — the bucketed
        # chunked-prefill contract (ops/chunked_prefill) as megakernel
        # tasks.
        self.chunk = bool(chunk)
        # Engine-wide moe_counts region height: every builder sharing
        # one arena must claim the SAME offset AND rows for the
        # counters, or a smaller builder's next region starts inside a
        # larger one's counter span (the engine passes the max batch
        # over all its builders).
        self.counts_rows = (int(counts_rows) if counts_rows is not None
                            else None)
        if batch % seq:
            raise ValueError(f"batch rows {batch} not divisible by "
                             f"seq {seq}")
        if self.qblock and self.chunk:
            raise ValueError("qblock and chunk are mutually exclusive "
                             "task-set selectors (verification rows vs "
                             "prompt-chunk rows)")
        if self.qblock:
            if seq < 2:
                raise ValueError("qblock builds verify K >= 2 "
                                 f"candidates per slot (seq={seq})")
            if not paged:
                raise ValueError("the Q-block verification task set "
                                 "addresses the cache through block "
                                 "tables — build with paged=True")
        if self.chunk:
            if batch != seq:
                raise ValueError(
                    "chunk builds run ONE prompt chunk per launch: "
                    f"batch ({batch}) must equal seq ({seq}) — the "
                    "chunk rows ARE the batch rows")
            if not paged:
                raise ValueError("the prefill-chunk task set addresses "
                                 "the cache through block tables — "
                                 "build with paged=True")
        # kv_quant: int8/fp8 pools with per-(layer, page, kv_head)
        # fp32 scale tables riding as extra aliased operands —
        # quantize fused into write_kv, dequant into every cache read.
        # qmax comes from the layer path's ONE quantization table
        # (kv_quant_spec), so the in-kernel quantizer can never
        # silently diverge from serving.blocks._quantize.
        self.kv_qmax = 0.0
        if kv_quant is not None:
            from triton_dist_tpu.serving.blocks import kv_quant_spec

            qdtype, qmax = kv_quant_spec(kv_quant)
            if qdtype is None:
                kv_quant = None
            else:
                self.kv_qmax = float(qmax)
        if kv_quant is not None:
            if not paged:
                raise ValueError(
                    "quantized megakernel KV needs paged=True (scales "
                    "are per (layer, page, kv_head))")
            if seq > 1 and not (self.qblock or self.chunk):
                raise NotImplementedError(
                    "the batched-prefill bodies have no fused-quant "
                    "write; quantized engines stream prompts through "
                    "the prefill lane (decode kernel) or chunk tasks")
        self.kv_quant = kv_quant
        hd = cfg.head_dim
        self.w = tile_w or max(128, hd)
        if self.w % hd:
            raise ValueError(f"tile width {self.w} must be a multiple of "
                             f"head_dim {hd}")
        self.t_tile = t_tile or min(128, max_len)
        if max_len % self.t_tile:
            raise ValueError(f"t_tile={self.t_tile} must divide max_len={max_len}")
        # Paged KV: the caches become page pools + a block table
        # (reference mega_triton_kernel paged flash_decode). Alignment
        # contract for single-slice access (kernels._kv_slice): cache
        # reads span t_tile and prefill writes span seq, so both must
        # divide the page; prefill bases must be seq-aligned.
        self.paged = paged
        self.page = 0
        self.p_max = 0
        if paged:
            self.page = page or max(self.t_tile, seq)
            # qblock/chunk rows write one position each (never a
            # seq-span block store), so only the t_tile and max_len
            # alignment applies there.
            seq_align = seq > 1 and not (self.qblock or self.chunk)
            if (self.page % self.t_tile
                    or (seq_align and self.page % seq)
                    or max_len % self.page):
                raise ValueError(
                    f"page={self.page} needs t_tile|page, seq|page and "
                    f"page|max_len (t_tile={self.t_tile}, seq={seq}, "
                    f"max_len={max_len})")
            self.p_max = max_len // self.page

        n = self.n
        self.h_loc = cfg.num_attention_heads // n
        self.kv_loc = cfg.num_key_value_heads // n
        self.d_tiles = _cdiv(cfg.hidden_size, self.w)
        self.hq_tiles = _cdiv(self.h_loc * hd, self.w)
        self.kv_tiles = _cdiv(self.kv_loc * hd, self.w)
        self.ff_tiles = _cdiv(cfg.intermediate_size // n, self.w)
        # Hybrid (qwen_next): GDN layers carry a recurrent state
        # buffer instead of KV rows; decode-only in the megakernel
        # (prefill via MegaKernelEngine.prefill_chain / the layer
        # engine). Head slices must sit inside lane tiles.
        self.hybrid = cfg.is_hybrid
        if self.hybrid:
            if self.kv_quant:
                raise NotImplementedError(
                    "quantized KV covers the attention families; the "
                    "hybrid GDN state is fp32 recurrent, not paged")
            if self.qblock:
                raise NotImplementedError(
                    "Q-block verification needs position-addressed KV; "
                    "the hybrid GDN recurrent state cannot rewind a "
                    "rejected draft")
            if self.chunk:
                raise NotImplementedError(
                    "prefill-chunk tasks need position-addressed KV; "
                    "the hybrid GDN recurrent state is sequential — "
                    "prefill via prefill_chain")
            if self.seq > 1:
                raise ValueError("hybrid megakernel is decode-only "
                                 "(seq == 1); prefill via prefill_chain")
            if cfg.is_moe:
                raise NotImplementedError(
                    "hybrid+MoE megakernel not wired; the layer Engine "
                    "serves qwen_next MoE")
            if cfg.gdn_num_heads % n:
                raise ValueError(f"gdn_num_heads={cfg.gdn_num_heads} "
                                 f"not divisible by tp={n}")
            self.gdn_h_loc = cfg.gdn_num_heads // n
            if (self.w % cfg.gdn_head_dim_k or self.w % cfg.gdn_head_dim_v
                    or self.gdn_h_loc > self.w):
                raise ValueError(
                    "GDN head dims must divide the tile width and local "
                    f"heads fit one tile (w={self.w}, "
                    f"dk={cfg.gdn_head_dim_k}, dv={cfg.gdn_head_dim_v}, "
                    f"h_loc={self.gdn_h_loc})")
            self.gq_tiles = _cdiv(self.gdn_h_loc * cfg.gdn_head_dim_k,
                                  self.w)
            self.gv_tiles = _cdiv(self.gdn_h_loc * cfg.gdn_head_dim_v,
                                  self.w)
            from triton_dist_tpu.models.qwen_next import _layer_kinds
            self.layer_kinds, _, self.n_gdn = _layer_kinds(cfg)
        # MoE (qwen_moe): per-expert ffn dim sharded over tp (the TP
        # regime); decode computes EVERY expert and weight-combines —
        # fully static task graph, the same small-batch trade as
        # ep_moe.fwd_decode. Router logits must fit one lane tile.
        self.moe = cfg.is_moe
        if self.moe:
            if cfg.num_experts > self.w:
                raise ValueError(
                    f"megakernel MoE needs num_experts={cfg.num_experts}"
                    f" <= tile width {self.w} (router logits tile)")
            if cfg.moe_intermediate_size % n:
                raise ValueError(
                    f"moe_intermediate_size={cfg.moe_intermediate_size} "
                    f"not divisible by tp={n}")
            if cfg.num_experts_per_tok > cfg.num_experts:
                raise ValueError(
                    f"num_experts_per_tok={cfg.num_experts_per_tok} > "
                    f"num_experts={cfg.num_experts} (the static top-k "
                    "loop would pick zero-probability padded columns)")
            self.ffe_tiles = _cdiv(cfg.moe_intermediate_size // n,
                                   self.w)

        self._offsets: Dict[str, int] = {}
        self.schema = ArenaSchema(self.w)
        self.graph = Graph()
        self._weight_entries: List[Tuple[str, int]] = []
        self._build()

    # ---------------- arena layout -------------------------------------
    # The described memory layout: every _alloc lands in the schema
    # with a name + kind, so consumers (checkpoint/restore, the chaos
    # arena sweep, docs) address regions by NAME instead of trusting
    # cursor arithmetic.
    def _alloc(self, name: str, rows: int,
               kind: str = "activation") -> int:
        off = self.schema.alloc(name, rows, kind)
        self._offsets[name] = off
        return off

    def _alloc_act(self, name: str, tiles: int) -> int:
        return self._alloc(name, tiles * self.batch)

    # ---------------- recording helpers --------------------------------
    def _linear(self, in_off, w_off, out_off, k_tiles, n_tiles, *,
                layer, in_rows, w_rows, expert: int = -1):
        b = self.batch
        for j in range(n_tiles):
            self.graph.add(
                TaskType.LINEAR,
                (in_off, w_off, out_off, k_tiles, n_tiles, j),
                reads=[(in_off, in_rows), (w_off, w_rows)],
                writes=[(out_off + j * b, b)], layer=layer,
                expert=expert)

    def _build(self):
        cfg, b, w = self.cfg, self.batch, self.w
        d_t, hq_t, kv_t, ff_t = (self.d_tiles, self.hq_tiles,
                                 self.kv_tiles, self.ff_tiles)

        # Weights region (per layer) — order defines pack_arena.
        def walloc(name, k_tiles, n_tiles):
            rows = k_tiles * n_tiles * w
            off = self._alloc(name, rows, kind="weight")
            self._weight_entries.append((name, rows))
            return off

        def vecalloc(name, tiles):
            off = self._alloc(name, tiles, kind="weight")
            self._weight_entries.append((name, tiles))
            return off

        # lm_head is vocab-sharded along tp (models.dense.param_specs);
        # each shard holds vocab/n rows.
        if cfg.vocab_size % self.n:
            raise ValueError(f"vocab_size={cfg.vocab_size} not divisible "
                             f"by tp={self.n}")
        self.vocab_loc = cfg.vocab_size // self.n
        self.vloc_tiles = _cdiv(self.vocab_loc, w)
        L = cfg.num_hidden_layers
        for li in range(L):
            if self.hybrid and self.layer_kinds[li][0] == "gdn":
                gq_t, gv_t = self.gq_tiles, self.gv_tiles
                walloc(f"l{li}.gwq", d_t, gq_t)
                walloc(f"l{li}.gwk", d_t, gq_t)
                walloc(f"l{li}.gwv", d_t, gv_t)
                walloc(f"l{li}.gwg", d_t, 1)
                walloc(f"l{li}.gwb", d_t, 1)
                vecalloc(f"l{li}.g_bias", 1)
                walloc(f"l{li}.gwo", gv_t, d_t)
            else:
                walloc(f"l{li}.wq", d_t, hq_t)
                walloc(f"l{li}.wk", d_t, kv_t)
                walloc(f"l{li}.wv", d_t, kv_t)
                walloc(f"l{li}.wo", hq_t, d_t)
            if self.moe:
                walloc(f"l{li}.router", d_t, 1)
                for e in range(cfg.num_experts):
                    walloc(f"l{li}.e{e}.w_gate", d_t, self.ffe_tiles)
                    walloc(f"l{li}.e{e}.w_up", d_t, self.ffe_tiles)
                    walloc(f"l{li}.e{e}.w_down", self.ffe_tiles, d_t)
            else:
                walloc(f"l{li}.w_gate", d_t, ff_t)
                walloc(f"l{li}.w_up", d_t, ff_t)
                walloc(f"l{li}.w_down", ff_t, d_t)
            vecalloc(f"l{li}.ln_attn", d_t)
            vecalloc(f"l{li}.ln_mlp", d_t)
            if not (self.hybrid and self.layer_kinds[li][0] == "gdn"):
                vecalloc(f"l{li}.q_norm", 1)
                vecalloc(f"l{li}.k_norm", 1)
        vecalloc("ln_f", d_t)
        # Embedding table vocab-sharded like lm_head: vocab/n entries
        # per rank; the gather task zero-fills off-shard tokens and an
        # allreduce sums the single real contribution.
        vecalloc("embed", self.vocab_loc * d_t)
        walloc("lm_head_T", d_t, self.vloc_tiles)

        # MoE expert-load counters: one (counts_rows, w) arena region
        # the router epilogue ACCUMULATES its top-k selection mask
        # into, every layer, every step — the decode dispatch's
        # on-device expert telemetry (read back by
        # engine.expert_counts(); the serving layer diffs snapshots
        # per tick). Monotonic: arena packs zeroed, so no per-step
        # reset task is needed. Placed directly after the (batch-
        # independent) weight region and sized engine-wide, so every
        # builder sharing the arena claims the SAME [offset, rows)
        # span — chunk/verify/prefill launches accumulate into the
        # decode counters instead of scribbling them with activations
        # (the old layout put moe_counts after the batch-dependent
        # ar_ws/x regions, so any batched prefill builder's
        # activation tail aliased the decode builder's counters).
        self.moe_counts_off = 0
        if self.moe:
            self.moe_counts_off = self._alloc(
                "moe_counts", max(b, self.counts_rows or 0),
                kind="counter")

        # Allreduce workspace + I/O regions.
        ar_max_tiles = max(d_t, 1)
        self.ar_ws_off = self._alloc("ar_ws", self.n * ar_max_tiles * b,
                                     kind="workspace")
        self.ar_max_tiles = ar_max_tiles
        x_off = self._alloc_act("x", d_t)
        self.x_off = x_off

        # Embedding lookup inside the kernel (token ids via prefetch),
        # then an allreduce to sum the vocab-shard contributions.
        self.graph.add(TaskType.GATHER,
                       (self._offsets["embed"], x_off, d_t,
                        self.vocab_loc),
                       reads=[(self._offsets["embed"],
                               self.vocab_loc * d_t)],
                       writes=[(x_off, d_t * b)])
        self.graph.add(TaskType.ALLREDUCE, (x_off, d_t),
                       reads=[(x_off, d_t * b)],
                       writes=[(x_off, d_t * b),
                               (self.ar_ws_off,
                                self.n * ar_max_tiles * b)])

        # Per-layer tasks.
        g = self.graph
        o = self._offsets
        for li in range(L):
            t0 = self._alloc_act(f"l{li}.t0", d_t)
            if not (self.hybrid and self.layer_kinds[li][0] == "gdn"):
                q = self._alloc_act(f"l{li}.q", hq_t)
                kx = self._alloc_act(f"l{li}.k", kv_t)
                vx = self._alloc_act(f"l{li}.v", kv_t)
                attn = self._alloc_act(f"l{li}.attn", hq_t)
            opart = self._alloc_act(f"l{li}.opart", d_t)
            x1 = self._alloc_act(f"l{li}.x1", d_t)
            t1 = self._alloc_act(f"l{li}.t1", d_t)
            if not self.moe:
                gx = self._alloc_act(f"l{li}.g", ff_t)
                ux = self._alloc_act(f"l{li}.u", ff_t)
                hx = self._alloc_act(f"l{li}.h", ff_t)
            mpart = self._alloc_act(f"l{li}.mpart", d_t)
            x2 = self._alloc_act(f"l{li}.x2", d_t)

            g.add(TaskType.RMSNORM,
                  (x_off, o[f"l{li}.ln_attn"], t0, d_t),
                  reads=[(x_off, d_t * b), (o[f"l{li}.ln_attn"], d_t)],
                  writes=[(t0, d_t * b)], layer=li)
            if self.hybrid and self.layer_kinds[li][0] == "gdn":
                # GDN mixer: q/k/v/g/beta projections then the
                # recurrent delta-rule step (state in the states
                # buffer; ordinal = position among GDN layers).
                gq_t, gv_t = self.gq_tiles, self.gv_tiles
                ordinal = self.layer_kinds[li][1]
                gq = self._alloc_act(f"l{li}.gq", gq_t)
                gk = self._alloc_act(f"l{li}.gk", gq_t)
                gv = self._alloc_act(f"l{li}.gv", gv_t)
                graw = self._alloc_act(f"l{li}.graw", 1)
                braw = self._alloc_act(f"l{li}.braw", 1)
                go = self._alloc_act(f"l{li}.go", gv_t)
                self._linear(t0, o[f"l{li}.gwq"], gq, d_t, gq_t,
                             layer=li, in_rows=d_t * b,
                             w_rows=d_t * gq_t * w)
                self._linear(t0, o[f"l{li}.gwk"], gk, d_t, gq_t,
                             layer=li, in_rows=d_t * b,
                             w_rows=d_t * gq_t * w)
                self._linear(t0, o[f"l{li}.gwv"], gv, d_t, gv_t,
                             layer=li, in_rows=d_t * b,
                             w_rows=d_t * gv_t * w)
                self._linear(t0, o[f"l{li}.gwg"], graw, d_t, 1,
                             layer=li, in_rows=d_t * b, w_rows=d_t * w)
                self._linear(t0, o[f"l{li}.gwb"], braw, d_t, 1,
                             layer=li, in_rows=d_t * b, w_rows=d_t * w)
                g.add(TaskType.GDN_DECODE,
                      (gq, gk, gv, graw, braw, o[f"l{li}.g_bias"], go,
                       ordinal),
                      reads=[(gq, gq_t * b), (gk, gq_t * b),
                             (gv, gv_t * b), (graw, b), (braw, b),
                             (o[f"l{li}.g_bias"], 1), (go, gv_t * b)],
                      writes=[(go, gv_t * b)], layer=li)
                self._linear(go, o[f"l{li}.gwo"], opart, gv_t, d_t,
                             layer=li, in_rows=gv_t * b,
                             w_rows=gv_t * d_t * w)
            else:
                self._linear(t0, o[f"l{li}.wq"], q, d_t, hq_t, layer=li,
                             in_rows=d_t * b, w_rows=d_t * hq_t * w)
                self._linear(t0, o[f"l{li}.wk"], kx, d_t, kv_t, layer=li,
                             in_rows=d_t * b, w_rows=d_t * kv_t * w)
                self._linear(t0, o[f"l{li}.wv"], vx, d_t, kv_t, layer=li,
                             in_rows=d_t * b, w_rows=d_t * kv_t * w)
                kv_layer = (self.layer_kinds[li][1] if self.hybrid
                            else li)
                if self.chunk:
                    wk_type = TaskType.WRITE_KV_CHUNK
                    at_type = TaskType.ATTN_CHUNK
                elif self.qblock:
                    wk_type = TaskType.WRITE_KV_QBLOCK
                    at_type = TaskType.ATTN_QBLOCK
                elif self.seq == 1:
                    wk_type = TaskType.WRITE_KV
                    at_type = TaskType.ATTN_DECODE
                else:
                    wk_type = TaskType.WRITE_KV_PREFILL
                    at_type = TaskType.ATTN_PREFILL
                g.add(wk_type,
                      (kx, vx, kv_layer, o[f"l{li}.k_norm"]),
                      reads=[(kx, kv_t * b), (vx, kv_t * b),
                             (o[f"l{li}.k_norm"], 1)],
                      writes=[], layer=li)
                # ATTN reads the cache written by WRITE_KV — encode the
                # ordering as an artificial region keyed off the task
                # above.
                attn_task = g.add(at_type,
                                  (q, attn, kv_layer,
                                   o[f"l{li}.q_norm"]),
                                  reads=[(q, hq_t * b),
                                         (o[f"l{li}.q_norm"], 1)],
                                  writes=[(attn, hq_t * b)], layer=li)
                attn_task.deps.append(g.tasks[-2].task_id)  # after W_KV
                self._linear(attn, o[f"l{li}.wo"], opart, hq_t, d_t,
                             layer=li, in_rows=hq_t * b,
                             w_rows=hq_t * d_t * w)
            g.add(TaskType.ALLREDUCE, (opart, d_t),
                  reads=[(opart, d_t * b)],
                  writes=[(opart, d_t * b),
                          (self.ar_ws_off, self.n * ar_max_tiles * b)],
                  layer=li)
            g.add(TaskType.ADD, (x_off, opart, x1, d_t),
                  reads=[(x_off, d_t * b), (opart, d_t * b)],
                  writes=[(x1, d_t * b)], layer=li)
            g.add(TaskType.RMSNORM,
                  (x1, o[f"l{li}.ln_mlp"], t1, d_t),
                  reads=[(x1, d_t * b), (o[f"l{li}.ln_mlp"], d_t)],
                  writes=[(t1, d_t * b)], layer=li)
            if self.moe:
                # MoE FFN: router → combine weights → every expert's
                # swiglu (ffn-sharded over tp) → weighted accumulate
                # into mpart (partial; summed by the allreduce below).
                E, ffe_t = cfg.num_experts, self.ffe_tiles
                rl = self._alloc_act(f"l{li}.rl", 1)
                wbe = self._alloc_act(f"l{li}.wbe", 1)
                self._linear(t1, o[f"l{li}.router"], rl, d_t, 1,
                             layer=li, in_rows=d_t * b,
                             w_rows=d_t * w)
                # The router epilogue also accumulates its selection
                # mask into the shared counts region — the read+write
                # chains the per-layer MOE_WEIGHTS tasks, which the
                # residual stream serializes anyway.
                g.add(TaskType.MOE_WEIGHTS,
                      (rl, wbe, E, self.moe_counts_off),
                      reads=[(rl, b), (self.moe_counts_off, b)],
                      writes=[(wbe, b), (self.moe_counts_off, b)],
                      layer=li)
                for e in range(E):
                    ge = self._alloc_act(f"l{li}.e{e}.g", ffe_t)
                    ue = self._alloc_act(f"l{li}.e{e}.u", ffe_t)
                    he = self._alloc_act(f"l{li}.e{e}.h", ffe_t)
                    pe = self._alloc_act(f"l{li}.e{e}.part", d_t)
                    self._linear(t1, o[f"l{li}.e{e}.w_gate"], ge, d_t,
                                 ffe_t, layer=li, in_rows=d_t * b,
                                 w_rows=d_t * ffe_t * w, expert=e)
                    self._linear(t1, o[f"l{li}.e{e}.w_up"], ue, d_t,
                                 ffe_t, layer=li, in_rows=d_t * b,
                                 w_rows=d_t * ffe_t * w, expert=e)
                    g.add(TaskType.SILU_MUL, (ge, ue, he, ffe_t),
                          reads=[(ge, ffe_t * b), (ue, ffe_t * b)],
                          writes=[(he, ffe_t * b)], layer=li, expert=e)
                    self._linear(he, o[f"l{li}.e{e}.w_down"], pe, ffe_t,
                                 d_t, layer=li, in_rows=ffe_t * b,
                                 w_rows=ffe_t * d_t * w, expert=e)
                    # init on e==0 writes; later experts accumulate —
                    # the shared (mpart, wbe) read/write regions chain
                    # the experts' combines in order.
                    g.add(TaskType.WEIGHTED_ADD,
                          (mpart, pe, wbe, e, d_t, 1 if e == 0 else 0),
                          reads=[(pe, d_t * b), (wbe, b),
                                 (mpart, d_t * b)],
                          writes=[(mpart, d_t * b)], layer=li,
                          expert=e)
            else:
                self._linear(t1, o[f"l{li}.w_gate"], gx, d_t, ff_t,
                             layer=li, in_rows=d_t * b,
                             w_rows=d_t * ff_t * w)
                self._linear(t1, o[f"l{li}.w_up"], ux, d_t, ff_t,
                             layer=li, in_rows=d_t * b,
                             w_rows=d_t * ff_t * w)
                g.add(TaskType.SILU_MUL, (gx, ux, hx, ff_t),
                      reads=[(gx, ff_t * b), (ux, ff_t * b)],
                      writes=[(hx, ff_t * b)], layer=li)
                self._linear(hx, o[f"l{li}.w_down"], mpart, ff_t, d_t,
                             layer=li, in_rows=ff_t * b,
                             w_rows=ff_t * d_t * w)
            g.add(TaskType.ALLREDUCE, (mpart, d_t),
                  reads=[(mpart, d_t * b)],
                  writes=[(mpart, d_t * b),
                          (self.ar_ws_off, self.n * ar_max_tiles * b)],
                  layer=li)
            g.add(TaskType.ADD, (x1, mpart, x2, d_t),
                  reads=[(x1, d_t * b), (mpart, d_t * b)],
                  writes=[(x2, d_t * b)], layer=li)
            x_off = x2

        out_off = self._alloc_act("x_final", d_t)
        g.add(TaskType.RMSNORM, (x_off, o["ln_f"], out_off, d_t),
              reads=[(x_off, d_t * b), (o["ln_f"], d_t)],
              writes=[(out_off, d_t * b)])
        self.out_off = out_off
        # LM head inside the kernel: logits over this rank's vocab shard.
        logits_off = self._alloc("logits", self.vloc_tiles * b,
                                 kind="io")
        self._linear(out_off, o["lm_head_T"], logits_off, d_t,
                     self.vloc_tiles, layer=-1, in_rows=d_t * b,
                     w_rows=d_t * self.vloc_tiles * w)
        self.logits_off = logits_off
        self.arena_rows = self.schema.rows
        self.schema.check_disjoint()

        # -------- native schedule --------
        # The kernel's allreduce body substitutes the STATIC
        # ar_max_tiles for the (traced) tiles descriptor arg — enforce
        # the contract here so a future task recording a narrower
        # collective fails loudly at build time, not by reducing
        # garbage tiles on device.
        for t in g.tasks:
            if (t.task_type == TaskType.ALLREDUCE
                    and t.args[1] != self.ar_max_tiles):
                raise ValueError(
                    f"ALLREDUCE task {t.task_id} moves {t.args[1]} "
                    f"tiles but the kernel body is specialized to "
                    f"ar_max_tiles={self.ar_max_tiles}")
        src, dst = g.edges()
        # Collectives pin to core 0: the SPMD comm order must match
        # across chips, and the ICI semaphores live on one core.
        pin = np.array(
            [0 if t.task_type == TaskType.ALLREDUCE else -1
             for t in g.tasks], np.int32)
        cost = np.array([self._task_cost(t) for t in g.tasks], np.int32)
        # Prune once so the static packing, the dynamic claim order,
        # and both timed simulators all see the same edge set.
        if len(src):
            psrc, pdst = prune_deps(len(g.tasks), src, dst)
        else:
            psrc = pdst = np.zeros(0, np.int32)
        self._pruned_edges = (psrc, pdst)
        self._pin, self._cost = pin, cost
        if self.schedule == "dynamic":
            self._schedule_dynamic(psrc, pdst, pin, cost)
        else:
            self._schedule_static(psrc, pdst, pin, cost)

    def reprioritize(self, expert_load) -> None:
        """Recompute the DYNAMIC claim order under a fresh per-expert
        load vector (graph.comm_priority ``expert_load``) — the
        between-steps hot-expert rebalance hook. Host-only: the graph,
        arena, and task bodies are untouched; only the claim tables and
        scoreboard edge plan are re-emitted. The engine must rebuild
        its jitted step so the new tables take effect
        (:meth:`MegaKernelEngine.set_expert_load` does both)."""
        if self.schedule != "dynamic":
            raise ValueError(
                "reprioritize() adjusts the dynamic claim order; this "
                f"builder runs schedule={self.schedule!r}")
        self.expert_load = (list(expert_load)
                            if expert_load is not None else None)
        psrc, pdst = self._pruned_edges
        self._schedule_dynamic(psrc, pdst, self._pin, self._cost)

    def _schedule_static(self, src, dst, pin, cost):
        """Precomputed per-core slot lists (round_robin / zig_zag /
        cost_lpt) with merged-order NOOP padding — the original static
        scoreboard."""
        g = self.graph
        sched = schedule_mc(len(g.tasks), src, dst,
                            num_cores=self.num_cores,
                            strategy=self.strategy, task_cost=cost,
                            pin_core=pin, dep_opt=False)
        self.sched = sched
        queue = sched["queue"]                     # (Q, C) ids or -1
        self.qlen = queue.shape[0]
        self.n_edges = sched["n_edges"]
        sim = simulate_static(len(g.tasks), src, dst, queue,
                              task_cost=cost)
        self.idle_units = sim["idle_units"]
        self.makespan = sim["makespan"]
        # Static mode runs no claim protocol; keep the bucket tables
        # at their 1-element placeholders (uniform kernel signature).
        self.n_buckets = 1
        self.bucket_claims = np.zeros(1, np.int32)
        self.claim_bucket = np.zeros(queue.size, np.int32)
        self._emit_slot_tables(queue.reshape(-1), queue.shape, sched)

    def _schedule_dynamic(self, src, dst, pin, cost):
        """Dynamic scoreboard schedule: ONE comm-priority-ordered claim
        list (scheduler.schedule_dyn) the kernel pops via the claim
        counter in the scoreboard workspace — the TPU analogue of the
        reference's in-kernel runtime scheduler (model_builder.py:89,
        124: SMs claiming off an atomic queue head). No merged-order
        padding: the claim order is topological, so idle (NOOP) slots
        shrink to pinning holes + tail round-up."""
        g = self.graph
        prio, bkt, n_buckets = comm_priority(
            g.tasks, n_ranks=self.n, task_cost=cost,
            expert_load=self.expert_load)
        dyn = schedule_dyn(len(g.tasks), src, dst,
                           num_cores=self.num_cores, priority=prio,
                           bucket=bkt, task_cost=cost, pin_core=pin,
                           dep_opt=False)
        self.sched = dyn
        C = self.num_cores
        n_claims = dyn["n_claims"]
        self.n_claims = n_claims
        self.qlen = _cdiv(max(n_claims, 1), C)
        self.n_edges = dyn["n_edges"]
        self.idle_units = dyn["idle_units"]
        self.makespan = dyn["makespan"]
        claims = np.full(self.qlen * C, -1, np.int32)
        claims[:n_claims] = dyn["claim_order"]
        self.claims = claims.reshape(self.qlen, C)
        # Per-claim bucket (holes/tail count against bucket 0) and the
        # per-bucket claim totals the last slot drains the claim
        # semaphores by. EVERY slot signals exactly one bucket, so the
        # totals sum to qlen * C.
        self.n_buckets = n_buckets
        bkt_arr = np.asarray(bkt, np.int32)
        self.claim_bucket = np.where(claims >= 0, bkt_arr[claims], 0
                                     ).astype(np.int32)
        self.bucket_claims = np.bincount(
            self.claim_bucket, minlength=n_buckets).astype(np.int32)
        self._emit_slot_tables(claims, self.claims.shape, dyn)

    def _emit_slot_tables(self, qc, shape, sched):
        """Flat slot list (static merged queue or dynamic claim order)
        → the prefetched type/arg/wait/signal tables."""
        g = self.graph
        noop_args = [0] * ARGS_MAX
        self.task_types = np.array(
            [g.tasks[t].task_type if t >= 0 else int(TaskType.NOOP)
             for t in qc], np.int32).reshape(shape)
        # Static work units per queue slot — the progress-counter →
        # time model's design row (slot_durations()).
        self.slot_units = np.array(
            [self._task_units(g.tasks[t]) if t >= 0 else 0
             for t in qc], np.int64).reshape(shape)
        self.task_args = np.array(
            [g.tasks[t].encoded_args() if t >= 0 else noop_args
             for t in qc], np.int32).reshape(*shape, ARGS_MAX)
        self._used_types = {int(v) for v in np.unique(self.task_types)}
        # Per-slot wait/signal tables (edge-semaphore scoreboard).
        wtab, stab = [], []
        wedges, sedges, scores_ = [], [], []
        for t in qc:
            if t < 0:
                wtab.append((0, 0))
                stab.append((0, 0))
                continue
            ws, wc = sched["wait_start"][t], sched["wait_count"][t]
            ss, sc = sched["sig_start"][t], sched["sig_count"][t]
            wtab.append((len(wedges), wc))
            wedges.extend(sched["wait_edges"][ws:ws + wc])
            stab.append((len(sedges), sc))
            sedges.extend(sched["sig_edges"][ss:ss + sc])
            scores_.extend(sched["sig_cores"][ss:ss + sc])
        self.wait_tab = np.array(wtab, np.int32).reshape(*shape, 2)
        self.sig_tab = np.array(stab, np.int32).reshape(*shape, 2)
        self.wait_edges = np.array(wedges or [0], np.int32)
        self.sig_edges = np.array(sedges or [0], np.int32)
        self.sig_cores = np.array(scores_ or [0], np.int32)

    def noop_slots(self) -> int:
        """Idle scoreboard steps in the schedule: grid slots that
        execute no task (static merged-order padding, or dynamic
        pinning holes + tail round-up). The interpret-mode step counter
        the static-vs-dynamic comparison is scored on."""
        return int((self.task_types == int(TaskType.NOOP)).sum())

    def _task_cost(self, t) -> int:
        """Cost estimate feeding the cost_lpt strategy: static work
        units, optionally reweighted by a measured ``cost_table``."""
        units = self._task_units(t)
        if self.cost_table is None:
            return units
        w = self.cost_table.get(int(t.task_type), 1.0)
        return max(int(round(units * w)), 0)

    def task_unit_counts(self) -> dict:
        """Total static work units per task type over the whole graph —
        the design matrix row for :func:`calibrate_cost_table`."""
        counts = {}
        for t in self.graph.tasks:
            k = int(t.task_type)
            counts[k] = counts.get(k, 0) + self._task_units(t)
        return counts

    def profile_unit_counts(self, prof) -> dict:
        """Unit counts per task type from a warmup step's EXECUTED slot
        records (``profile=True`` output) — the profile-guided
        counterpart of :meth:`task_unit_counts`. Where the static count
        trusts the graph, this counts what the scoreboard actually ran
        (slot tags paired with the schedule's per-slot units), so a
        ``(profile_unit_counts(prof), wall_seconds)`` observation feeds
        :func:`calibrate_cost_table` with measured executions; the
        resulting ``cost_table`` re-schedules BOTH ``cost_lpt`` and the
        dynamic claim order on step 2+."""
        tags = np.asarray(prof)[:, 0].reshape(-1)
        units = np.asarray(self.slot_units).reshape(-1)
        counts = {}
        for tag, u in zip(tags.tolist(), units.tolist()):
            k = int(tag) - 1         # tags are task_type + 1
            if tag <= 0 or k == int(TaskType.NOOP):
                continue
            counts[k] = counts.get(k, 0) + int(u)
        return counts

    def _task_units(self, t) -> int:
        """Static work-unit estimate per task (pre-reweighting)."""
        if t.task_type == TaskType.LINEAR:
            return int(t.args[3])          # k_tiles MXU passes
        if t.task_type == TaskType.ATTN_DECODE:
            return 4 * self.d_tiles
        if t.task_type == TaskType.ATTN_PREFILL:
            # S-row blocked flash attention: the prefill heavyweight.
            return 8 * self.d_tiles * max(self.seq // 8, 1)
        if t.task_type == TaskType.ATTN_QBLOCK:
            # K per-row online-softmax streams per slot.
            return 4 * self.d_tiles * self.seq
        if t.task_type == TaskType.ATTN_CHUNK:
            # C per-row online-softmax streams — the chunk heavyweight
            # (same per-row stream as the Q-block verify body).
            return 4 * self.d_tiles * self.seq
        if t.task_type == TaskType.WRITE_KV_PREFILL:
            return 2 * max(self.seq // 8, 1)
        if t.task_type == TaskType.WRITE_KV_QBLOCK:
            return 2 * self.seq
        if t.task_type == TaskType.WRITE_KV_CHUNK:
            return 2 * self.seq
        if t.task_type == TaskType.ALLREDUCE:
            return 2 * int(t.args[1])
        if t.task_type == TaskType.WEIGHTED_ADD:
            return int(t.args[4])          # tiles copied + fused mul-add
        if t.task_type == TaskType.GDN_DECODE:
            # The body loops every (batch, local-head) pair.
            return 2 * self.batch * self.gdn_h_loc
        return 1

    # ---------------- arena packing ------------------------------------
    def _tile_weight(self, wmat, k_tiles, n_tiles):
        w = self.w
        kpad, npad = k_tiles * w, n_tiles * w
        wm = jnp.zeros((kpad, npad), jnp.float32).at[
            :wmat.shape[0], :wmat.shape[1]].set(wmat.astype(jnp.float32))
        return wm.reshape(k_tiles, w, n_tiles, w).transpose(
            0, 2, 1, 3).reshape(k_tiles * n_tiles * w, w)

    def _pad_vec(self, vec, tiles):
        w = self.w
        out = jnp.zeros((tiles * w,), jnp.float32).at[
            :vec.shape[0]].set(vec.astype(jnp.float32))
        return out.reshape(tiles, w)

    def pack_arena(self, params) -> jax.Array:
        """Per-shard: assemble the weight region + zeroed activation
        region into the (arena_rows, w) arena (traced; run inside
        shard_map so ``params`` are the local shards)."""
        cfg = self.cfg
        d_t, hq_t, kv_t, ff_t = (self.d_tiles, self.hq_tiles,
                                 self.kv_tiles, self.ff_tiles)
        parts = []
        for li in range(cfg.num_hidden_layers):
            lp = params["layers"][li]
            mixer_key = "mixer" if self.hybrid else "attn"
            mx = lp[mixer_key]
            if self.hybrid and self.layer_kinds[li][0] == "gdn":
                gq_t, gv_t = self.gq_tiles, self.gv_tiles
                me = jax.lax.axis_index(self.axis)
                h_loc = self.gdn_h_loc
                # Column-parallel gdn projections: local shards already
                # hold this rank's head columns; g_bias needs slicing
                # (replicated param, like the embedding below).
                parts.append(self._tile_weight(mx["wq"], d_t, gq_t))
                parts.append(self._tile_weight(mx["wk"], d_t, gq_t))
                parts.append(self._tile_weight(mx["wv"], d_t, gv_t))
                parts.append(self._tile_weight(mx["wg"], d_t, 1))
                parts.append(self._tile_weight(mx["wb"], d_t, 1))
                bias = jax.lax.dynamic_slice_in_dim(
                    mx["g_bias"], me * h_loc, h_loc, 0)
                parts.append(self._pad_vec(bias, 1))
                parts.append(self._tile_weight(mx["wo"], gv_t, d_t))
            else:
                parts.append(self._tile_weight(mx["wq"], d_t, hq_t))
                parts.append(self._tile_weight(mx["wk"], d_t, kv_t))
                parts.append(self._tile_weight(mx["wv"], d_t, kv_t))
                parts.append(self._tile_weight(mx["wo"], hq_t, d_t))
            if self.moe:
                mp = lp["moe"]
                parts.append(self._tile_weight(mp["router"], d_t, 1))
                for e in range(cfg.num_experts):
                    parts.append(self._tile_weight(
                        mp["w_gate"][e], d_t, self.ffe_tiles))
                    parts.append(self._tile_weight(
                        mp["w_up"][e], d_t, self.ffe_tiles))
                    parts.append(self._tile_weight(
                        mp["w_down"][e], self.ffe_tiles, d_t))
            else:
                parts.append(self._tile_weight(lp["mlp"]["w_gate"],
                                               d_t, ff_t))
                parts.append(self._tile_weight(lp["mlp"]["w_up"],
                                               d_t, ff_t))
                parts.append(self._tile_weight(lp["mlp"]["w_down"],
                                               ff_t, d_t))
            parts.append(self._pad_vec(lp["ln_attn"], d_t))
            parts.append(self._pad_vec(lp["ln_mlp"], d_t))
            if not (self.hybrid and self.layer_kinds[li][0] == "gdn"):
                parts.append(self._pad_vec(mx["q_norm"], 1))
                parts.append(self._pad_vec(mx["k_norm"], 1))
        parts.append(self._pad_vec(params["ln_f"], d_t))
        # Embedding table shard: this rank's vocab/n rows, laid out as
        # (vocab_loc * d_tiles, w). Params keep embed replicated
        # (dense.param_specs), so slice the local shard here.
        me = jax.lax.axis_index(self.axis)
        emb = jax.lax.dynamic_slice_in_dim(
            params["embed"].astype(jnp.float32), me * self.vocab_loc,
            self.vocab_loc, axis=0)
        vpad = jnp.zeros((self.vocab_loc, d_t * self.w), jnp.float32
                         ).at[:, :cfg.hidden_size].set(emb)
        parts.append(vpad.reshape(self.vocab_loc * d_t, self.w))
        # LM head transposed: x @ lm_head.T with lm_head (vocab_loc, d).
        parts.append(self._tile_weight(params["lm_head"].T, d_t,
                                       self.vloc_tiles))
        weights = jnp.concatenate(parts, axis=0)
        pad = jnp.zeros((self.arena_rows - weights.shape[0], self.w),
                        jnp.float32)
        return jnp.concatenate([weights, pad], axis=0)

    # ---------------- the megakernel -----------------------------------
    def kernel_config(self) -> K.KernelConfig:
        return K.KernelConfig(
            w=self.w, batch=self.batch, h_loc=self.h_loc,
            kv_loc=self.kv_loc, hd=self.cfg.head_dim,
            rope_theta=self.cfg.rope_theta, rms_eps=self.cfg.rms_norm_eps,
            n_ranks=self.n, axis=self.axis, mesh=self.mctx,
            ar_ws_off=self.ar_ws_off, ar_max_tiles=self.ar_max_tiles,
            seq=self.seq, paged=self.paged, page=self.page,
            p_max=self.p_max,
            moe_topk=(self.cfg.num_experts_per_tok if self.moe else 0),
            moe_norm=self.cfg.norm_topk_prob,
            gdn_h_loc=(self.gdn_h_loc if self.hybrid else 0),
            gdn_dk=self.cfg.gdn_head_dim_k,
            gdn_dv=self.cfg.gdn_head_dim_v,
            kv_quant=self.kv_quant,
            qmax=self.kv_qmax,
            qblock=self.qblock,
            chunk=self.chunk)

    def _n_state(self) -> int:
        """Aliased state operands: arena + K/V pools, plus the scale
        tables (quantized) and the GDN state buffer (hybrid)."""
        return (3 + (2 if self.kv_quant else 0)
                + (1 if self.hybrid else 0))

    def _kernel(self, types_s, args_s, wait_tab_s, sig_tab_s,
                wait_edges_s, sig_edges_s, bucket_s, bsizes_s, len_s,
                tok_s, tbl_s, *tail):
        # Inputs are aliased onto the outputs — skip the input refs
        # and unpack the output half (arena, K/V pools, [scales],
        # [states]), then prof, scratches, semaphores.
        tail = tail[self._n_state():]
        arena, k_cache, v_cache = tail[:3]
        tail = tail[3:]
        if self.kv_quant:
            k_scale, v_scale = tail[:2]
            tail = tail[2:]
        else:
            k_scale = v_scale = None
        if self.hybrid:
            states, tail = tail[0], tail[1:]
        else:
            states = None
        if self.profile:
            prof_ref, tail = tail[0], tail[1:]
        else:
            prof_ref = None
        (va, vb, vc, vw, acc, vhd, vkt, vsq) = tail[:8]
        tail = tail[8:]
        if self.hybrid:
            vrow, vrow2, vS = tail[:3]
            tail = tail[3:]
        else:
            vrow = vrow2 = vS = None
        if self.kv_quant:
            vqt, vqd, vscl = tail[:3]
            tail = tail[3:]
        else:
            vqt = vqd = vscl = None
        claim_cnt, claim_sem, edge_sem, send_sem, recv_sem = tail
        cfg = self.kernel_config()
        q = pl.program_id(0)
        c = pl.program_id(1)
        C = self.num_cores
        if self.schedule == "dynamic":
            # Device-side task claiming: no slot carries a precomputed
            # task binding — each grid slot pops the next entry off the
            # claim counter in the scoreboard workspace and executes
            # whatever the counter hands it (reference: the runtime
            # scheduler's atomic queue head, model_builder.py:89,124).
            # Under the sequential merged order the claim sequence is
            # deterministic (slot (q, c) draws claim q*C + c), which is
            # what keeps the SPMD collective order identical across
            # chips; a concurrent megacore claim draws the same values
            # through fetch-add order on the per-core subsequences.
            @pl.when(jnp.logical_and(q == 0, c == 0))
            def _():
                claim_cnt[0] = 0

            slot = claim_cnt[0]
            claim_cnt[0] = slot + 1
            # Per-priority-bucket claim accounting, visible in the
            # scoreboard workspace as semaphore counts (the wait/signal
            # tables' sibling): every slot signals exactly one bucket.
            pltpu.semaphore_signal(claim_sem.at[bucket_s[slot]], 1)
        else:
            slot = q * C + c
        ttype = types_s[slot]
        args = tuple(args_s[slot, j] for j in range(ARGS_MAX))
        refs = {"arena": arena, "k_cache": k_cache, "v_cache": v_cache,
                "va": va, "vb": vb, "vc": vc, "vw": vw, "acc": acc,
                "vhd": vhd, "vkt": vkt, "vsq": vsq, "send_sem": send_sem,
                "recv_sem": recv_sem, "tbl_s": tbl_s, "states": states,
                "vrow": vrow, "vrow2": vrow2, "vS": vS,
                "k_scale": k_scale, "v_scale": v_scale,
                "vqt": vqt, "vqd": vqd, "vscl": vscl}

        # Progress tracing (TRITON_DIST_TPU_TRACE_PROGRESS=1): one line
        # per queue slot as the scoreboard advances. In interpret mode
        # this is the only progress signal that survives a wedged
        # kernel — the resilience harness parses the last line to name
        # the slot a deadlocked schedule stopped at. Dynamic mode
        # reports the CLAIM COUNTER value, not a static queue position:
        # feed it to scheduler.describe_claim to name the claimed task.
        if self.trace_progress:
            if self.schedule == "dynamic":
                pl.debug_print("TDT-PROGRESS claim={} task_type={}",
                               slot, ttype)
            else:
                pl.debug_print("TDT-PROGRESS q={} c={}", q, c)

        # Scoreboard waits: block until every cross-core predecessor's
        # edge semaphore has been signalled (reference
        # scoreboard_wait_deps).
        wstart, wcount = wait_tab_s[slot, 0], wait_tab_s[slot, 1]

        def wait_step(k, _):
            pltpu.semaphore_wait(edge_sem.at[wait_edges_s[wstart + k]], 1)
            return 0

        jax.lax.fori_loop(0, wcount, wait_step, 0)

        branches = [
            lambda: K.rmsnorm_body(cfg, args, refs),
            lambda: K.linear_body(cfg, args, refs),
            lambda: K.add_body(cfg, args, refs),
            lambda: K.silu_mul_body(cfg, args, refs),
            lambda: K.attn_decode_body(cfg, args, refs, len_s),
            lambda: K.write_kv_body(cfg, args, refs, len_s),
            lambda: K.allreduce_body(cfg, args, refs),
            lambda: K.gather_body(cfg, args, refs, tok_s),
            lambda: None,   # NOOP (queue padding)
            lambda: K.write_kv_prefill_body(cfg, args, refs, len_s),
            lambda: K.attn_prefill_body(cfg, args, refs, len_s),
            lambda: K.moe_weights_body(cfg, args, refs),
            lambda: K.weighted_add_body(cfg, args, refs),
            (lambda: K.gdn_decode_body(cfg, args, refs))
            if self.hybrid else (lambda: None),
            lambda: K.attn_qblock_body(cfg, args, refs, len_s),
            lambda: K.write_kv_qblock_body(cfg, args, refs, len_s),
            lambda: K.attn_chunk_body(cfg, args, refs, len_s),
            lambda: K.write_kv_chunk_body(cfg, args, refs, len_s),
        ]
        # lax.switch traces EVERY branch, scheduled or not — and a body
        # whose geometry does not fit this build (the decode cache
        # bodies under a prefill-shaped cfg where batch counts B*S
        # rows) fails at trace time. Stub types absent from the
        # schedule; the queue can never select them.
        used = self._used_types
        branches = [br if i in used else (lambda: None)
                    for i, br in enumerate(branches)]
        jax.lax.switch(ttype, branches)
        if prof_ref is not None:
            # tag = task_type + 1: the Perfetto exporter treats a
            # (0, 0) row as an unused slot, and RMSNORM is type 0.
            prof_ref[...] = jnp.stack(
                [ttype + 1, args[0]]).astype(jnp.int32).reshape(1, 2)

        # Mark completion: signal each outgoing cross-core edge. (A
        # true CORE_PARALLEL execution additionally needs the signal
        # targeted at the consumer core — sig_cores in the schedule
        # carries that mapping — but no execution environment available
        # here runs that variant, so the kernel does not consume it.)
        sstart, scount = sig_tab_s[slot, 0], sig_tab_s[slot, 1]

        # Fault hook: a drop_edge plan suppresses one edge's completion
        # signal — the canonical scoreboard failure (a consumer's wait
        # then never satisfies; a blocking backend deadlocks, which the
        # resilience harness must detect and attribute).
        from triton_dist_tpu.resilience import faults

        dropped_edge = faults.edge_drop("megakernel")

        def sig_step(k, _):
            edge = sig_edges_s[sstart + k]
            if dropped_edge is None:
                pltpu.semaphore_signal(edge_sem.at[edge], 1)
            else:
                @pl.when(edge != dropped_edge)
                def _():
                    pltpu.semaphore_signal(edge_sem.at[edge], 1)
            return 0

        jax.lax.fori_loop(0, scount, sig_step, 0)

        if self.schedule == "dynamic":
            # Drain the per-bucket claim semaphores once every claim
            # has been accounted (a TPU kernel must exit with zeroed
            # semaphores). The final slot waits for each bucket's full
            # claim total — by then all qlen*C signals have been (or,
            # concurrently, will be) raised.
            @pl.when(jnp.logical_and(q == self.qlen - 1, c == C - 1))
            def _():
                def drain(k, _):
                    pltpu.semaphore_wait(claim_sem.at[k], bsizes_s[k])
                    return 0

                jax.lax.fori_loop(0, self.n_buckets, drain, 0)

    def step_fn(self):
        """Per-shard decode step:
        (arena, k_cache, v_cache, token_ids (B,), cache_len)
        → (logits (B, vocab_loc), arena, k_cache, v_cache)
        [+ prof (qlen·cores, 2) as a 5th element when ``profile=True``:
        one (task_type+1, arg0) row per queue slot].
        Embedding, the transformer stack, and the vocab-sharded LM head
        all run inside the kernel. Call inside shard_map; donate arena +
        caches at jit level."""
        b, w, d_t = self.batch, self.w, self.d_tiles
        cfg = self.cfg
        # Slot tables are prefetched FLAT (slot-major): static slots
        # index them at q*C + c, dynamic slots at the claim-counter
        # value — one kernel, two binding rules.
        n_slots = self.qlen * self.num_cores
        types = jnp.asarray(self.task_types).reshape(n_slots)
        args = jnp.asarray(self.task_args).reshape(n_slots, ARGS_MAX)
        wait_tab = jnp.asarray(self.wait_tab).reshape(n_slots, 2)
        sig_tab = jnp.asarray(self.sig_tab).reshape(n_slots, 2)
        wait_edges = jnp.asarray(self.wait_edges)
        sig_edges = jnp.asarray(self.sig_edges)
        bucket = jnp.asarray(self.claim_bucket).reshape(-1)
        bsizes = jnp.asarray(self.bucket_claims)

        def step(arena, k_cache, v_cache, token_ids, cache_len,
                 block_table=None, states=None, k_scale=None,
                 v_scale=None):
            if self.hybrid and states is None:
                raise ValueError("hybrid megakernel step needs the GDN "
                                 "states buffer")
            if self.kv_quant and (k_scale is None or v_scale is None):
                raise ValueError("quantized megakernel step needs the "
                                 "k_scale/v_scale tables")
            # cache_len: scalar (uniform batch, the classic form) or a
            # (batch,) vector of PER-ROW positions — the live-slot
            # serving form (qblock builds: per-ROW verification
            # positions, < 0 masks a row). Either way the kernel sees
            # a (batch,) SMEM vector; write_kv/attn_decode index it
            # per row, the prefill bodies read the shared base at [0].
            len_arr = jnp.broadcast_to(
                jnp.asarray(cache_len, jnp.int32).reshape(-1), (b,))
            tok_arr = jnp.asarray(token_ids, jnp.int32)
            if block_table is None:
                # Dense mode: a 1-element placeholder keeps the prefetch
                # slot (and the traced signature) uniform.
                block_table = jnp.zeros((1,), jnp.int32)
            tbl_arr = jnp.asarray(block_table, jnp.int32).reshape(-1)

            C = self.num_cores
            n_big = self._n_state()
            out_specs = [pl.BlockSpec(memory_space=pl.ANY)] * n_big
            if self.profile:
                # One (task_type, arg0) row per executed queue slot,
                # written via the regular output pipeline.
                out_specs.append(pl.BlockSpec(
                    (1, 2), lambda q, c, *_: (q * C + c, 0),
                    memory_space=pltpu.VMEM))
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=11,
                grid=(self.qlen, self.num_cores),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_big,
                out_specs=out_specs,
                scratch_shapes=[
                    pltpu.VMEM((b, w), jnp.float32),       # va
                    pltpu.VMEM((b, w), jnp.float32),       # vb
                    pltpu.VMEM((b, w), jnp.float32),       # vc
                    pltpu.VMEM((w, w), jnp.float32),       # vw
                    pltpu.VMEM((b, w), jnp.float32),       # acc
                    pltpu.VMEM((b, self.cfg.head_dim), jnp.float32),
                    pltpu.VMEM((self.t_tile, self.cfg.head_dim),
                               jnp.float32),                # vkt
                    pltpu.VMEM((self.seq, self.cfg.head_dim),
                               jnp.float32),                # vsq
                ] + ([
                    pltpu.VMEM((1, w), jnp.float32),        # vrow
                    pltpu.VMEM((1, w), jnp.float32),        # vrow2
                    pltpu.VMEM((self.cfg.gdn_head_dim_k,
                                self.cfg.gdn_head_dim_v),
                               jnp.float32),                # vS
                ] if self.hybrid else []) + ([
                    pltpu.VMEM((self.t_tile, self.cfg.head_dim),
                               k_cache.dtype),              # vqt
                    pltpu.VMEM((1, self.cfg.head_dim),
                               k_cache.dtype),              # vqd
                    pltpu.VMEM((1, 1), jnp.float32),        # vscl
                ] if self.kv_quant else []) + [
                    pltpu.SMEM((1,), jnp.int32),            # claim_cnt
                    pltpu.SemaphoreType.REGULAR(
                        (max(self.n_buckets, 1),)),         # claim_sem
                    pltpu.SemaphoreType.REGULAR(
                        (max(self.n_edges, 1),)),           # scoreboard
                    pltpu.SemaphoreType.DMA((max(self.n - 1, 1),)),
                    pltpu.SemaphoreType.DMA(()),
                ],
            )
            # Execution model: the grid walks the merged (q-major)
            # interleave of the per-core queues, with every cross-core
            # dependency enforced by explicit edge-semaphore waits and
            # completion signals — the scoreboard protocol, fully
            # active and testable on any part. The scheduler's padding
            # constraint (task merged-index > all preds') makes this
            # order deadlock-free even when executed sequentially. On a
            # megacore part the core dim is hoisted leading and marked
            # CORE_PARALLEL so each TensorCore walks its own queue
            # concurrently; neither this chip (single TensorCore) nor
            # the CPU interpreter (randomized 'parallel' core maps that
            # cannot honor a static cross-core signal plan) can execute
            # that variant, so it is not wired up here rather than
            # pretending coverage we cannot have; the
            # schedule's sig_cores mapping is ready for it.
            out_shape = [
                jax.ShapeDtypeStruct(arena.shape, arena.dtype),
                jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
            ]
            if self.kv_quant:
                out_shape.append(jax.ShapeDtypeStruct(
                    k_scale.shape, k_scale.dtype))
                out_shape.append(jax.ShapeDtypeStruct(
                    v_scale.shape, v_scale.dtype))
            if self.hybrid:
                out_shape.append(jax.ShapeDtypeStruct(
                    states.shape, states.dtype))
            if self.profile:
                out_shape.append(jax.ShapeDtypeStruct(
                    (self.qlen * self.num_cores, 2), jnp.int32))
            outs_fn = core_call(
                self._kernel,
                grid_spec=grid_spec,
                out_shape=tuple(out_shape),
                input_output_aliases={
                    11 + i: i for i in range(n_big)},
                # A rankless megakernel traces no barrier: Mosaic
                # rejects a collective_id without one.
                compiler_params=(comm_compiler_params() if self.n > 1
                                 else pltpu.CompilerParams(
                                     has_side_effects=True)),
            )
            operands = [types, args, wait_tab, sig_tab, wait_edges,
                        sig_edges, bucket, bsizes, len_arr, tok_arr,
                        tbl_arr, arena, k_cache, v_cache]
            if self.kv_quant:
                operands += [k_scale, v_scale]
            if self.hybrid:
                operands.append(states)
            outs = list(outs_fn(*operands))
            arena, k_cache, v_cache = outs[:3]
            outs = outs[3:]
            if self.kv_quant:
                k_scale, v_scale = outs[:2]
                outs = outs[2:]
            if self.hybrid:
                states, outs = outs[0], outs[1:]
            prof = outs[0] if self.profile else None

            lt = self.vloc_tiles
            out_rows = jax.lax.dynamic_slice(
                arena, (self.logits_off, 0), (lt * b, w))
            logits = out_rows.reshape(lt, b, w).transpose(1, 0, 2
                                                          ).reshape(b, lt * w)
            ret = [logits[:, :self.vocab_loc], arena, k_cache, v_cache]
            if self.kv_quant:
                ret += [k_scale, v_scale]
            if self.hybrid:
                ret.append(states)
            if self.profile:
                ret.append(prof)
            return tuple(ret)

        return step

    def prof_tracks(self, prof):
        """Reshape a step's profile output ((qlen·num_cores, 2) rows,
        slot-major) into per-core tracks (num_cores, qlen, 2) — the
        exporter's buffer layout, aligned with
        :meth:`slot_durations`."""
        p = np.asarray(prof).reshape(self.qlen, self.num_cores, 2)
        return np.transpose(p, (1, 0, 2))

    def slot_durations(self, cost_table: dict, unit_s: float):
        """Calibrated progress-counter→time model: per-queue-slot
        durations in seconds, ``units * weight[task_type] * unit_s``
        with weights from a MEASURED :func:`calibrate_cost_table` and
        ``unit_s`` the fit's base unit time. Feed to
        ``profiler.export_to_perfetto_trace(prof_tracks(prof),
        slot_durations=...)`` — the export then carries spans labeled
        ``calibrated`` (model times), never passing reconstructed order
        off as measurement. Returns (num_cores, qlen), matching
        :meth:`prof_tracks`."""
        w = np.array([cost_table.get(int(t), 1.0)
                      for t in self.task_types.reshape(-1)],
                     np.float64).reshape(self.task_types.shape)
        return (self.slot_units * w * unit_s).T

    def core_activity(self, prof) -> "np.ndarray":
        """Per-core busy fraction from a profile output: share of queue
        slots that executed a real task (non-NOOP) — the reference
        megakernel's SM-activity metric (model_builder.py:164-190)."""
        t = np.asarray(prof)[:, 0].reshape(self.qlen, self.num_cores)
        return (t != int(TaskType.NOOP) + 1).mean(axis=0)
