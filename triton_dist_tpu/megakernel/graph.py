"""Dependency graph (reference: ``mega_triton_kernel/core/graph.py:101``
``Graph`` with dependency optimization under ``enable_dep_opt``)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from triton_dist_tpu.megakernel.task import Task, TaskType


class Graph:
    """Records tasks and infers dependencies from arena data flow:
    a task depends on the most recent writers of the regions it reads
    and the most recent accessor of regions it writes (WAR/WAW)."""

    def __init__(self):
        self.tasks: List[Task] = []
        self._last_writer: Dict[Tuple[int, int], int] = {}
        self._readers: Dict[Tuple[int, int], List[int]] = {}

    def add(self, task_type: TaskType, args, *, reads, writes,
            layer: int = -1) -> Task:
        """reads/writes: list of (offset, size) arena regions."""
        t = Task(task_id=len(self.tasks), task_type=task_type,
                 args=tuple(int(a) for a in args), layer=layer)
        deps = set()
        for region in reads:
            for key, writer in self._overlapping(self._last_writer, region):
                deps.add(writer)
            self._readers.setdefault(self._key(region), []).append(t.task_id)
        for region in writes:
            for key, writer in self._overlapping(self._last_writer, region):
                deps.add(writer)  # WAW
            for key, readers in self._overlapping(self._readers, region):
                deps.update(readers)  # WAR
            self._last_writer[self._key(region)] = t.task_id
            self._readers[self._key(region)] = []
        t.deps = sorted(d for d in deps if d != t.task_id)
        self.tasks.append(t)
        return t

    @staticmethod
    def _key(region):
        return (int(region[0]), int(region[1]))

    @staticmethod
    def _overlap(a, b):
        return a[0] < b[0] + b[1] and b[0] < a[0] + a[1]

    def _overlapping(self, table, region):
        return [(k, v) for k, v in table.items() if self._overlap(k, region)]

    def edges(self):
        src, dst = [], []
        for t in self.tasks:
            for d in t.deps:
                src.append(d)
                dst.append(t.task_id)
        return src, dst
