"""Dependency graph (reference: ``mega_triton_kernel/core/graph.py:101``
``Graph`` with dependency optimization under ``enable_dep_opt``) and
the comm-aware priority policy feeding the dynamic scoreboard
scheduler."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from triton_dist_tpu.megakernel.task import (COLLECTIVE_TYPES, Task,
                                             TaskType)


class Graph:
    """Records tasks and infers dependencies from arena data flow:
    a task depends on the most recent writers of the regions it reads
    and the most recent accessor of regions it writes (WAR/WAW)."""

    def __init__(self):
        self.tasks: List[Task] = []
        self._last_writer: Dict[Tuple[int, int], int] = {}
        self._readers: Dict[Tuple[int, int], List[int]] = {}

    def add(self, task_type: TaskType, args, *, reads, writes,
            layer: int = -1, expert: int = -1) -> Task:
        """reads/writes: list of (offset, size) arena regions.
        ``expert`` tags MoE per-expert FFN work for the expert-load
        claim priority (:func:`comm_priority`)."""
        t = Task(task_id=len(self.tasks), task_type=task_type,
                 args=tuple(int(a) for a in args), layer=layer,
                 expert=expert)
        deps = set()
        for region in reads:
            for key, writer in self._overlapping(self._last_writer, region):
                deps.add(writer)
            self._readers.setdefault(self._key(region), []).append(t.task_id)
        for region in writes:
            for key, writer in self._overlapping(self._last_writer, region):
                deps.add(writer)  # WAW
            for key, readers in self._overlapping(self._readers, region):
                deps.update(readers)  # WAR
            self._last_writer[self._key(region)] = t.task_id
            self._readers[self._key(region)] = []
        t.deps = sorted(d for d in deps if d != t.task_id)
        self.tasks.append(t)
        return t

    @staticmethod
    def _key(region):
        return (int(region[0]), int(region[1]))

    @staticmethod
    def _overlap(a, b):
        return a[0] < b[0] + b[1] and b[0] < a[0] + a[1]

    def _overlapping(self, table, region):
        return [(k, v) for k, v in table.items() if self._overlap(k, region)]

    def edges(self):
        src, dst = [], []
        for t in self.tasks:
            for d in t.deps:
                src.append(d)
                dst.append(t.task_id)
        return src, dst


# Priority layout: comm weight in the high bits, critical path in the
# low 15 — both fit comfortably in an int32 for any decode graph.
_CP_BITS = 15
N_PRIORITY_BUCKETS = 3


def comm_priority(tasks: Sequence[Task], *, n_ranks: int = 1,
                  task_cost: Sequence[int] = None,
                  expert_load: Sequence[float] = None):
    """Comm-aware claim priority for the dynamic scheduler, computed
    host-side from the task graph.

    A collective's completion releases ``n_ranks - 1`` remote chips
    (every peer's matching allreduce spins until this rank's
    contribution lands), so tasks are ordered by how many remote-peer-
    unblocking collectives their completion leads to:

    - ``priority[t]``: (#distinct collective descendants of t) *
      (n_ranks - 1) in the high bits — the number of remote-peer
      releases t's completion contributes to — with the cost-weighted
      critical-path length to a sink as the low-bits tiebreak (longest
      path first, the classic list-scheduling heuristic; with
      ``n_ranks == 1`` it is the whole priority).
    - ``bucket[t]`` (0 = claimed first):
      0 — collectives and their direct predecessors (completion
          immediately releases, or enables the release of, remote
          peers);
      1 — tasks with any collective downstream;
      2 — the local-only tail (e.g. the LM head after the last
          allreduce) — nothing remote ever waits on these.

    ``task_cost`` feeds the critical-path term — pass the SAME costs
    the schedule uses so profile-guided ``cost_table`` reweighting
    (builder.calibrate_cost_table) sharpens the dynamic claim order
    exactly as it sharpens ``cost_lpt``.

    ``expert_load`` (per-expert weights, e.g. the serving layer's load
    EWMA) reweights the cost of tasks tagged with ``Task.expert``
    before the critical-path walk: a hot expert's group-GEMM and
    combine chain grows a longer (scaled) path to the sink and is
    claimed earlier — the megakernel answer to decode-time expert skew
    (the source of the hidden serialization arXiv 2605.00686 measures
    when comm slots are statically scheduled).

    Returns ``(priority, bucket, n_buckets)`` as int32 lists.
    Task ids must be topologically ordered (Graph.add guarantees it:
    dependencies only ever point at earlier ids).
    """
    n = len(tasks)
    cost = list(task_cost) if task_cost is not None else [1] * n
    if expert_load is not None:
        load = [max(float(v), 0.0) for v in expert_load]
        mean = (sum(load) / len(load)) if load else 0.0
        if mean > 0:
            for t in tasks:
                e = getattr(t, "expert", -1)
                if 0 <= e < len(load):
                    # 1 + load/mean: a uniform load is the identity;
                    # a 100%-hot expert scales its chain by ~1+E.
                    scale = 1.0 + load[e] / mean
                    cost[t.task_id] = max(
                        int(round(cost[t.task_id] * scale)), 1)
    succ: List[List[int]] = [[] for _ in range(n)]
    for t in tasks:
        for d in t.deps:
            succ[d].append(t.task_id)

    # Distinct-collective descendant sets as bitmasks over the (small)
    # collective population; python ints make the union O(words).
    bit = {}
    for t in tasks:
        if t.task_type in COLLECTIVE_TYPES:
            bit[t.task_id] = len(bit)
    mask = [0] * n
    cp = [0] * n
    for tid in reversed(range(n)):
        m = (1 << bit[tid]) if tid in bit else 0
        best = 0
        for s in succ[tid]:
            m |= mask[s]
            if cp[s] > best:
                best = cp[s]
        mask[tid] = m
        cp[tid] = best + max(int(cost[tid]), 0)

    max_cp = max(cp) if cp else 1
    peers = max(n_ranks - 1, 0)
    pre_comm = set()
    for t in tasks:
        if t.task_type in COLLECTIVE_TYPES:
            pre_comm.update(t.deps)

    priority, bucket = [], []
    for t in tasks:
        tid = t.task_id
        unblocks = bin(mask[tid]).count("1") * peers
        cp_scaled = (cp[tid] * ((1 << _CP_BITS) - 1)) // max(max_cp, 1)
        priority.append((unblocks << _CP_BITS) + cp_scaled)
        if t.task_type in COLLECTIVE_TYPES or tid in pre_comm:
            bucket.append(0)
        elif mask[tid]:
            bucket.append(1)
        else:
            bucket.append(2)
    return priority, bucket, N_PRIORITY_BUCKETS
