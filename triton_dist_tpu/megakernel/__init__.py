"""Megakernel runtime: a whole decode step as one persistent per-core
Pallas kernel (reference: ``python/triton_dist/mega_triton_kernel/``,
SURVEY.md §2.8).

Execution model mapping:

- reference: every SM loops over a private work queue, spin-waiting on a
  ``scoreboard[layer, task, tile]`` tensor (``core/scheduler.py:71-100``)
  and dispatching generated if/elif task bodies
  (``core/code_generator.py:193-243``).
- here: a TPU core runs its whole queue as the grid of one Pallas call —
  grid iteration = queue slot; task descriptors arrive via scalar
  prefetch; dispatch is a ``lax.switch`` over task types reading/writing
  one HBM arena at dynamic offsets. Per-core ordering subsumes the
  scoreboard; cross-chip tasks (allreduce) synchronize with DMA
  semaphores. The native C++ scheduler (``csrc/megakernel_scheduler.cc``)
  orders tasks, packs multi-core queues, and prunes dependencies.
- ``schedule="dynamic"``: instead of walking precomputed per-core slot
  lists, each grid slot pops the next task off a claim counter in the
  scoreboard workspace (comm-priority-ordered ready list, per-bucket
  claim semaphores) — the TPU form of the reference's in-kernel
  runtime scheduler (docs/megakernel.md, "Dynamic scoreboard
  scheduling").
"""

from triton_dist_tpu.megakernel.task import (  # noqa: F401
    COLLECTIVE_TYPES, Task, TaskType,
)
from triton_dist_tpu.megakernel.graph import Graph, comm_priority  # noqa: F401
from triton_dist_tpu.megakernel.scheduler import (  # noqa: F401
    describe_claim, describe_slot, prune_deps, schedule, schedule_dyn,
    simulate_static,
)
from triton_dist_tpu.megakernel.builder import (  # noqa: F401
    ArenaRegion, ArenaSchema, ModelBuilder, calibrate_cost_table,
)
