"""Per-task device code for the megakernel interpreter.

Reference: ``mega_triton_kernel/kernels/`` (linear, flash_decode paged,
norm, activation, allreduce via symm buffers, barrier) — one Triton
function per task type, dispatched by generated if/elif
(``core/code_generator.py:193-243``).

TPU redesign: task bodies are closures over a static ``KernelConfig``;
dispatch is ``lax.switch`` on the prefetched task type. All tensors live
in one HBM arena of shape ``(rows, W)`` — activations as consecutive
``(B, W)`` tiles, weights pre-tiled into ``(W, W)`` blocks (tile-major),
so every dynamic access is a contiguous ``pl.ds`` row slice.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import triton_dist_tpu.lang as dl
from triton_dist_tpu.megakernel.task import ARGS_MAX, TaskType
from triton_dist_tpu.parallel.mesh import MeshContext


@dataclasses.dataclass(frozen=True)
class KernelConfig:
    w: int                  # arena lane width (tile size)
    batch: int              # decode batch B
    h_loc: int              # local attention heads
    kv_loc: int             # local KV heads
    hd: int                 # head dim (<= w)
    rope_theta: float
    rms_eps: float
    n_ranks: int            # TP size
    axis: str               # mesh axis name ("tp")
    mesh: MeshContext
    ar_ws_off: int          # arena row offset of the allreduce workspace
    ar_max_tiles: int       # max (B, W) tiles a single allreduce moves
    seq: int = 1            # rows per batch entry (prefill: B*S rows)
    # Paged KV (reference mega_triton_kernel paged flash_decode task):
    # the cache is a page pool (layers, n_pages, page, kv_loc, hd) and a
    # per-batch block table maps page index -> pool slot.
    paged: bool = False
    page: int = 0           # page length (builder: t_tile | page, seq | page)
    p_max: int = 0          # pages per sequence (max_len // page)
    # MoE (qwen_moe): static routing hyperparams for the MOE_WEIGHTS
    # task (top-k is a static python loop in the body).
    moe_topk: int = 0
    moe_norm: bool = True
    # Hybrid (qwen_next) GDN geometry (0 = no GDN layers).
    gdn_h_loc: int = 0
    gdn_dk: int = 0
    gdn_dv: int = 0
    # Quantized KV pools (``kv_quant="int8"|"fp8"``, paged only): the
    # cache arrays store 1 B/elem with one fp32 scale per (layer, page,
    # kv_head) riding in the k_scale/v_scale operands — quantize fused
    # into write_kv, dequant into every cache read (the
    # ops/paged_flash_qblock scheme applied to the persistent lane).
    # None = the original fp32 pools, bit-identical code path.
    kv_quant: "str | None" = None
    qmax: float = 0.0
    # Q-block verification build (WRITE_KV_QBLOCK/ATTN_QBLOCK): batch
    # rows are (slot, j) pairs, ``seq`` rows per slot, each at its own
    # per-row position.
    qblock: bool = False
    # Prefill-chunk build (WRITE_KV_CHUNK/ATTN_CHUNK): one C-row prompt
    # chunk per launch, per-row positions SIGN-ENCODED in the cache_len
    # vector (see ``_chunk_apos``) so resident-prefix rows attend
    # without re-writing and bucket-padding rows are dead.
    chunk: bool = False


def _act(arena, off, tiles_b):
    """Contiguous activation slab: ``tiles_b`` rows of the arena."""
    return arena.at[pl.ds(off, tiles_b)]


def _kv_slice(cache, refs, cfg, layer, bb, start, span, kv_head):
    """Cache slice (span, hd) of batch ``bb`` at global KV position
    ``start``: dense direct index, or block-table indirection in paged
    mode (pool slot ``tbl[bb, start // page]``, offset ``start % page``).
    The builder guarantees spans never cross a page (t_tile | page,
    seq | page, and page-aligned bases), so one slice is always enough —
    the same alignment contract as ``ops/paged_flash_decode``."""
    if not cfg.paged:
        return cache.at[layer, bb, pl.ds(start, span), kv_head, :]
    tbl_s = refs["tbl_s"]
    pid = tbl_s[bb * cfg.p_max + start // cfg.page]
    return cache.at[layer, pid,
                    pl.ds(jax.lax.rem(start, cfg.page), span), kv_head, :]


# ---------------------------------------------------------------------------
# Quantized-pool helpers (cfg.kv_quant): symmetric max-abs per
# (layer, page, kv_head), the layer path's PagedKVCache scheme fused
# into the persistent kernel. Scales live in the k_scale/v_scale
# operands shaped (layers, num_pages, kv_loc, 1); a (1, 1) VMEM
# scratch (refs["vscl"]) stages each scalar DMA.
# ---------------------------------------------------------------------------

def _quant_cast(x, qdtype, qmax):
    """fp32 → pool storage dtype (int8 rounds-to-nearest, fp8 is a
    saturating cast) — must track serving.blocks._quantize."""
    if jnp.dtype(qdtype) == jnp.dtype(jnp.int8):
        return jnp.clip(jnp.round(x), -qmax, qmax).astype(jnp.int8)
    return jnp.clip(x, -qmax, qmax).astype(qdtype)


def _read_scale(refs, which, layer, pid, kv_head):
    """One (layer, page, kv_head) scale scalar off the HBM table.
    ``kv_head`` must be a STATIC int (the quantized bodies run static
    head loops for exactly this reason)."""
    vscl = refs["vscl"]
    pltpu.sync_copy(refs[which].at[layer, pid, pl.ds(kv_head, 1)], vscl)
    return vscl[0, 0]


def _write_scale(refs, which, layer, pid, kv_head, s):
    vscl = refs["vscl"]
    vscl[...] = jnp.reshape(s, (1, 1))
    pltpu.sync_copy(vscl, refs[which].at[layer, pid, pl.ds(kv_head, 1)])


def _quant_store_token(cfg, refs, cache, scale_name, layer, pid, off,
                       kv_head, head_row):
    """Quantize ONE token's (1, hd) row into a quantized page at
    ``(pid, off, kv_head)``, maintaining the per-(layer, page, kv_head)
    running max-abs scale: the page's FIRST position (``off == 0``)
    RESETS the scale, so a freed-and-reused page never inherits a
    stale one; a later token whose amax exceeds the running amax grows
    the scale and RESCALES the already-stored page content to it first
    — the in-kernel form of the layer path's dequant→merge→requant
    (double-rounds old tokens exactly like the XLA merge does)."""
    qmax = cfg.qmax
    vqd, vqt = refs["vqd"], refs["vqt"]
    amax = jnp.max(jnp.abs(head_row))
    s_old = _read_scale(refs, scale_name, layer, pid, kv_head)
    fresh = off == 0
    s_tok = jnp.where(amax > 0, amax / qmax, 0.0)
    s_new = jnp.where(fresh,
                      jnp.where(amax > 0, amax / qmax, 1.0),
                      jnp.maximum(s_old, s_tok))

    @pl.when(jnp.logical_and(jnp.logical_not(fresh), s_new > s_old))
    def _():
        ratio = s_old / s_new
        t_tile = vqt.shape[0]
        for tt in range(cfg.page // t_tile):     # static: t_tile | page
            sl = cache.at[layer, pid, pl.ds(tt * t_tile, t_tile),
                          kv_head, :]
            pltpu.sync_copy(sl, vqt)
            vqt[...] = _quant_cast(
                vqt[...].astype(jnp.float32) * ratio, vqt.dtype, qmax)
            pltpu.sync_copy(vqt, sl)

    vqd[...] = _quant_cast(head_row / s_new, vqd.dtype, qmax)
    pltpu.sync_copy(vqd, cache.at[layer, pid, pl.ds(off, 1),
                                  kv_head, :])
    _write_scale(refs, scale_name, layer, pid, kv_head, s_new)


def _dequant_tile(cfg, refs, cache, scale_name, layer, pid, start,
                  kv_head):
    """One (t_tile, hd) cache tile dequantized to fp32 — the read half
    of the fused scheme (start is the in-page offset; the builder's
    t_tile | page contract keeps the tile inside one page)."""
    vqt = refs["vqt"]
    s = _read_scale(refs, scale_name, layer, pid, kv_head)
    pltpu.sync_copy(cache.at[layer, pid, pl.ds(start, vqt.shape[0]),
                             kv_head, :], vqt)
    return vqt[...].astype(jnp.float32) * s


# ---------------------------------------------------------------------------
# Task bodies. Common closure args: cfg + refs
# (args_s, len_s, arena, k_cache, v_cache, vmem scratches, sems).
# ---------------------------------------------------------------------------

def rmsnorm_body(cfg, args, refs):
    arena, va, vb, vc, acc = (refs["arena"], refs["va"], refs["vb"],
                              refs["vc"], refs["acc"])
    in_off, w_off, out_off, d_tiles = args[0], args[1], args[2], args[3]
    b = cfg.batch

    def ssq_step(j, ssq):
        pltpu.sync_copy(arena.at[pl.ds(in_off + j * b, b)], va)
        x = va[...].astype(jnp.float32)
        return ssq + jnp.sum(x * x, axis=1, keepdims=True)

    ssq = jax.lax.fori_loop(0, d_tiles, ssq_step,
                            jnp.zeros((b, 1), jnp.float32))
    inv = jax.lax.rsqrt(ssq / (d_tiles * cfg.w).astype(jnp.float32)
                        + cfg.rms_eps)

    def norm_step(j, _):
        pltpu.sync_copy(arena.at[pl.ds(in_off + j * b, b)], va)
        pltpu.sync_copy(arena.at[pl.ds(w_off + j, 1)],
                        vc.at[pl.ds(0, 1)])
        vb[...] = (va[...].astype(jnp.float32) * inv
                   * vc[0:1, :].astype(jnp.float32))
        pltpu.sync_copy(vb, arena.at[pl.ds(out_off + j * b, b)])
        return 0

    jax.lax.fori_loop(0, d_tiles, norm_step, 0)


def linear_body(cfg, args, refs):
    arena, va, vw, acc = (refs["arena"], refs["va"], refs["vw"],
                          refs["acc"])
    in_off, w_off, out_off = args[0], args[1], args[2]
    k_tiles, n_tiles, j = args[3], args[4], args[5]
    b, w = cfg.batch, cfg.w

    def kt_step(kt, a):
        pltpu.sync_copy(arena.at[pl.ds(in_off + kt * b, b)], va)
        pltpu.sync_copy(
            arena.at[pl.ds(w_off + (kt * n_tiles + j) * w, w)], vw)
        return a + jnp.dot(va[...], vw[...],
                           preferred_element_type=jnp.float32)

    out = jax.lax.fori_loop(0, k_tiles, kt_step,
                            jnp.zeros((b, w), jnp.float32))
    acc[...] = out
    pltpu.sync_copy(acc, arena.at[pl.ds(out_off + j * b, b)])


def add_body(cfg, args, refs):
    arena, va, vb, vc = refs["arena"], refs["va"], refs["vb"], refs["vc"]
    a_off, b_off, out_off, tiles = args[0], args[1], args[2], args[3]
    b = cfg.batch

    def step(j, _):
        pltpu.sync_copy(arena.at[pl.ds(a_off + j * b, b)], va)
        pltpu.sync_copy(arena.at[pl.ds(b_off + j * b, b)], vb)
        vc[...] = va[...] + vb[...]
        pltpu.sync_copy(vc, arena.at[pl.ds(out_off + j * b, b)])
        return 0

    jax.lax.fori_loop(0, tiles, step, 0)


def silu_mul_body(cfg, args, refs):
    arena, va, vb, vc = refs["arena"], refs["va"], refs["vb"], refs["vc"]
    g_off, u_off, out_off, tiles = args[0], args[1], args[2], args[3]
    b = cfg.batch

    def step(j, _):
        pltpu.sync_copy(arena.at[pl.ds(g_off + j * b, b)], va)
        pltpu.sync_copy(arena.at[pl.ds(u_off + j * b, b)], vb)
        g = va[...].astype(jnp.float32)
        vc[...] = jax.nn.silu(g) * vb[...].astype(jnp.float32)
        pltpu.sync_copy(vc, arena.at[pl.ds(out_off + j * b, b)])
        return 0

    jax.lax.fori_loop(0, tiles, step, 0)


def moe_weights_body(cfg, args, refs):
    """Router epilogue: softmax over the first ``n_experts`` columns of
    the router-logits tile, keep the top-``cfg.moe_topk`` per row
    (static iterative argmax extraction — no in-kernel sort), optional
    renormalization; writes the (B, W) combine-weight tile (reference:
    the megakernel's routing happens host-side; in-kernel routing keeps
    the whole MoE decode step one launch)."""
    arena, va, vb, vc = (refs["arena"], refs["va"], refs["vb"],
                         refs["vc"])
    rl_off, wout_off, e_n = args[0], args[1], args[2]
    cnt_off = args[3]
    b = cfg.batch

    pltpu.sync_copy(arena.at[pl.ds(rl_off, b)], va)
    lg = va[...].astype(jnp.float32)
    col = jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    lg = jnp.where(col < e_n, lg, -jnp.inf)
    p = jax.nn.softmax(lg, axis=-1)
    p = jnp.where(col < e_n, p, 0.0)
    mask = jnp.zeros(p.shape, jnp.bool_)
    work = p
    for _ in range(cfg.moe_topk):
        amax = jnp.argmax(work, axis=-1)
        pick = col == amax[:, None]
        mask = jnp.logical_or(mask, pick)
        work = jnp.where(pick, -jnp.inf, work)
    wbe = jnp.where(mask, p, 0.0)
    if cfg.moe_norm:
        wbe = wbe / jnp.maximum(jnp.sum(wbe, axis=-1, keepdims=True),
                                1e-30)
    vc[...] = wbe
    pltpu.sync_copy(vc, arena.at[pl.ds(wout_off, b)])
    # Expert-load telemetry: accumulate this layer's top-k selection
    # mask into the shared counts region (column e = expert e; rows
    # summed host-side). Monotonic across steps — the arena packs
    # zeroed and the host diffs snapshots; float32 stays count-exact
    # to 2^24 selections.
    pltpu.sync_copy(arena.at[pl.ds(cnt_off, b)], vb)
    vb[...] = vb[...] + mask.astype(jnp.float32)
    pltpu.sync_copy(vb, arena.at[pl.ds(cnt_off, b)])


def weighted_add_body(cfg, args, refs):
    """acc[+]= part * wbe[:, e] — the per-expert combine of the MoE
    FFN block (``init`` selects write vs accumulate; the expert-e
    column is selected maskwise, no dynamic gather)."""
    arena, va, vb, vc = (refs["arena"], refs["va"], refs["vb"],
                         refs["vc"])
    acc_off, part_off, wbe_off = args[0], args[1], args[2]
    e_idx, tiles, init = args[3], args[4], args[5]
    b = cfg.batch

    pltpu.sync_copy(arena.at[pl.ds(wbe_off, b)], va)
    col = jax.lax.broadcasted_iota(jnp.int32, va.shape, 1)
    wcol = jnp.sum(jnp.where(col == e_idx,
                             va[...].astype(jnp.float32), 0.0),
                   axis=1, keepdims=True)                   # (B, 1)

    def step(j, _):
        pltpu.sync_copy(arena.at[pl.ds(part_off + j * b, b)], vb)
        pltpu.sync_copy(arena.at[pl.ds(acc_off + j * b, b)], vc)
        term = vb[...].astype(jnp.float32) * wcol
        vc[...] = jnp.where(init == 1, term, vc[...] + term)
        pltpu.sync_copy(vc, arena.at[pl.ds(acc_off + j * b, b)])
        return 0

    jax.lax.fori_loop(0, tiles, step, 0)


def _rms_rows(x, w_row, eps):
    """Row-wise RMSNorm of (rows, hd) fp32 with (hd,) weight."""
    var = jnp.mean(x * x, axis=1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * w_row[None]


def _write_kv_body_quant(cfg, args, refs, len_s):
    """Quantized form of :func:`write_kv_body` (paged pools only):
    same per-row append, with quantize-on-write through the running
    per-(layer, page, kv_head) scales. Loops are STATIC python (the
    scale DMA needs a static head index); op-for-op the math matches
    the fp32 body, so the stored values dequantize to the same tokens
    the unquantized lane would have written, modulo quantization."""
    arena, k_cache, v_cache = (refs["arena"], refs["k_cache"],
                               refs["v_cache"])
    va, vb = refs["va"], refs["vb"]
    tbl_s = refs["tbl_s"]
    k_off, v_off, layer, knorm_off = args[0], args[1], args[2], args[3]
    b, hd, kv_loc, w = cfg.batch, cfg.hd, cfg.kv_loc, cfg.w
    heads_per_tile = w // hd
    kv_tiles = -(-(kv_loc * hd) // w)
    pos_rows = jnp.concatenate(
        [jnp.full((1, 1), len_s[bb], jnp.int32) for bb in range(b)],
        axis=0)

    pltpu.sync_copy(arena.at[pl.ds(knorm_off, 1)], vb.at[pl.ds(0, 1)])
    wrow = vb[0, :hd].astype(jnp.float32)

    for j in range(kv_tiles):                      # static tile loop
        pltpu.sync_copy(arena.at[pl.ds(k_off + j * b, b)], va)
        kt = va[...].astype(jnp.float32)
        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh      # STATIC head index
            if kv_head >= kv_loc:
                continue                           # padding head
            head = kt[:, hh * hd:(hh + 1) * hd]
            head = _rms_rows(head, wrow, cfg.rms_eps)
            head = _rope_rows(head, pos_rows, hd, cfg.rope_theta)
            for bb in range(b):
                pos = len_s[bb]
                pid = tbl_s[bb * cfg.p_max + pos // cfg.page]
                off = jax.lax.rem(pos, cfg.page)
                _quant_store_token(cfg, refs, k_cache, "k_scale",
                                   layer, pid, off, kv_head,
                                   head[bb:bb + 1])
        pltpu.sync_copy(arena.at[pl.ds(v_off + j * b, b)], va)
        vt = va[...].astype(jnp.float32)
        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh
            if kv_head >= kv_loc:
                continue
            for bb in range(b):
                pos = len_s[bb]
                pid = tbl_s[bb * cfg.p_max + pos // cfg.page]
                off = jax.lax.rem(pos, cfg.page)
                _quant_store_token(cfg, refs, v_cache, "v_scale",
                                   layer, pid, off, kv_head,
                                   vt[bb:bb + 1, hh * hd:(hh + 1) * hd])


def write_kv_body(cfg, args, refs, len_s):
    """Append the new token's K/V (with k-norm + rope on K) to the cache
    at EACH BATCH ROW'S OWN position ``len_s[bb]`` — the live-slot form
    the serving layer drives (a uniform batch passes a broadcast
    vector and degenerates to the old single-position append). Builder
    guarantees hd | w. Quantized pools route to the fused
    quantize-on-write variant; the fp32 path below is untouched (and
    stays bit-identical to the pre-quantization kernel)."""
    if cfg.kv_quant:
        return _write_kv_body_quant(cfg, args, refs, len_s)
    arena, k_cache, v_cache = (refs["arena"], refs["k_cache"],
                               refs["v_cache"])
    va, vb, vhd = refs["va"], refs["vb"], refs["vhd"]
    k_off, v_off, layer, knorm_off = args[0], args[1], args[2], args[3]
    b, hd, kv_loc, w = cfg.batch, cfg.hd, cfg.kv_loc, cfg.w
    heads_per_tile = w // hd
    kv_tiles = pl.cdiv(kv_loc * hd, w)
    # Per-row positions as a (b, 1) value vector (SMEM reads are
    # scalar; b is tiny and the loop static).
    pos_rows = jnp.concatenate(
        [jnp.full((1, 1), len_s[bb], jnp.int32) for bb in range(b)],
        axis=0)
    # Uniform-batch predicate: the classic decode (scalar broadcast)
    # keeps its ONE batched store per (tile, K/V) fast path; only a
    # genuinely ragged serving batch pays the per-row copies.
    uniform = jnp.bool_(True)
    for bb in range(1, b):
        uniform = jnp.logical_and(uniform, len_s[bb] == len_s[0])

    pltpu.sync_copy(arena.at[pl.ds(knorm_off, 1)],
                    vb.at[pl.ds(0, 1)])  # (1, w) k_norm
    wrow = vb[0, :hd].astype(jnp.float32)

    # Head loops are STATIC Python (and so are the column slices):
    # Mosaic has no lowering for value-level dynamic_slice with traced
    # starts, and heads_per_tile is tiny.
    def per_tile(j, _):
        pltpu.sync_copy(arena.at[pl.ds(k_off + j * b, b)], va)
        kt = va[...].astype(jnp.float32)        # (b, w)

        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh

            @pl.when(kv_head < cfg.kv_loc)  # skip padding heads
            def _():
                head = kt[:, hh * hd:(hh + 1) * hd]
                head = _rms_rows(head, wrow, cfg.rms_eps)
                head = _rope_rows(head, pos_rows, hd, cfg.rope_theta)
                vhd[...] = head.astype(vhd.dtype)
                if not cfg.paged:
                    @pl.when(uniform)
                    def _():
                        # Dense + uniform: all batches of one position
                        # are contiguous — one copy.
                        pltpu.sync_copy(
                            vhd, k_cache.at[layer, pl.ds(0, b),
                                            len_s[0], kv_head, :])

                    @pl.when(jnp.logical_not(uniform))
                    def _():
                        for bb in range(b):  # per-row positions
                            pltpu.sync_copy(
                                vhd.at[pl.ds(bb, 1)],
                                _kv_slice(k_cache, refs, cfg, layer,
                                          bb, len_s[bb], 1, kv_head))
                else:
                    for bb in range(b):  # per-batch pages
                        pltpu.sync_copy(
                            vhd.at[pl.ds(bb, 1)],
                            _kv_slice(k_cache, refs, cfg, layer, bb,
                                      len_s[bb], 1, kv_head))

        pltpu.sync_copy(arena.at[pl.ds(v_off + j * b, b)], va)
        vt = va[...]

        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh

            @pl.when(kv_head < cfg.kv_loc)
            def _():
                vhd[...] = vt[:, hh * hd:(hh + 1) * hd].astype(vhd.dtype)
                if not cfg.paged:
                    @pl.when(uniform)
                    def _():
                        pltpu.sync_copy(
                            vhd, v_cache.at[layer, pl.ds(0, b),
                                            len_s[0], kv_head, :])

                    @pl.when(jnp.logical_not(uniform))
                    def _():
                        for bb in range(b):
                            pltpu.sync_copy(
                                vhd.at[pl.ds(bb, 1)],
                                _kv_slice(v_cache, refs, cfg, layer,
                                          bb, len_s[bb], 1, kv_head))
                else:
                    for bb in range(b):
                        pltpu.sync_copy(
                            vhd.at[pl.ds(bb, 1)],
                            _kv_slice(v_cache, refs, cfg, layer, bb,
                                      len_s[bb], 1, kv_head))
        return 0

    jax.lax.fori_loop(0, kv_tiles, per_tile, 0)


def _attn_decode_body_quant(cfg, args, refs, len_s):
    """Quantized form of :func:`attn_decode_body`: the same per-row
    online-softmax stream with the dequant fused into each (t_tile,
    hd) page read — pre-gathered scales are impossible here because
    write_kv of the SAME launch updates them, so each tile reads its
    page's scale live. Static head loops (scale DMA needs a static
    head index); per-(1, hd) query math is op-for-op the fp32 body's,
    so bf16-vs-quant divergence is the quantization error only."""
    arena, k_cache, v_cache, va = (refs["arena"], refs["k_cache"],
                                   refs["v_cache"], refs["va"])
    tbl_s = refs["tbl_s"]
    q_off, out_off, layer, qnorm_off = args[0], args[1], args[2], args[3]
    b, hd, w = cfg.batch, cfg.hd, cfg.w
    h_loc, kv_loc = cfg.h_loc, cfg.kv_loc
    t_tile = refs["vqt"].shape[0]
    pos_rows = jnp.concatenate(
        [jnp.full((1, 1), len_s[bb], jnp.int32) for bb in range(b)],
        axis=0)
    group = h_loc // kv_loc
    heads_per_tile = w // hd

    pltpu.sync_copy(arena.at[pl.ds(qnorm_off, 1)],
                    refs["vb"].at[pl.ds(0, 1)])
    qn_row = refs["vb"][0, :hd].astype(jnp.float32)

    q_tiles = -(-(h_loc * hd) // w)
    for j in range(q_tiles):                       # static tile loop
        pltpu.sync_copy(arena.at[pl.ds(q_off + j * b, b)], va)
        qtile = va[...].astype(jnp.float32)
        col_blocks = []
        for hh in range(heads_per_tile):
            h_idx = j * heads_per_tile + hh        # STATIC head index
            if h_idx >= h_loc:
                col_blocks.append(jnp.zeros((b, hd), jnp.float32))
                continue
            kv_head = h_idx // group
            q = qtile[:, hh * hd:(hh + 1) * hd]
            q = _rms_rows(q, qn_row, cfg.rms_eps)
            q = _rope_rows(q, pos_rows, hd, cfg.rope_theta)
            q = q / jnp.sqrt(jnp.float32(hd))
            row_blocks = []
            for bb in range(b):
                kv_len = len_s[bb] + 1
                n_tiles_t = pl.cdiv(kv_len, t_tile)

                def tstep(tt, carry, bb=bb, q=q, kv_head=kv_head,
                          kv_len=kv_len):
                    m, l, acc = carry
                    pid = tbl_s[bb * cfg.p_max
                                + (tt * t_tile) // cfg.page]
                    start = jax.lax.rem(tt * t_tile, cfg.page)
                    kt = _dequant_tile(cfg, refs, k_cache, "k_scale",
                                       layer, pid, start, kv_head)
                    s = jnp.dot(q[bb:bb + 1], kt.T,
                                preferred_element_type=jnp.float32)
                    tpos = tt * t_tile + jax.lax.broadcasted_iota(
                        jnp.int32, (1, t_tile), 1)
                    s = jnp.where(tpos < kv_len, s, -jnp.inf)
                    m_new = jnp.maximum(
                        m, jnp.max(s, axis=1, keepdims=True))
                    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                    p = jnp.where(jnp.isfinite(s),
                                  jnp.exp(s - m_safe), 0.0)
                    corr = jnp.where(jnp.isfinite(m),
                                     jnp.exp(m - m_safe), 0.0)
                    vt = _dequant_tile(cfg, refs, v_cache, "v_scale",
                                       layer, pid, start, kv_head)
                    acc = acc * corr + jnp.dot(
                        p, vt, preferred_element_type=jnp.float32)
                    l = l * corr + jnp.sum(p, axis=1, keepdims=True)
                    return (m_new, l, acc)

                m0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
                l0 = jnp.zeros((1, 1), jnp.float32)
                acc0 = jnp.zeros((1, hd), jnp.float32)
                m, l, acc = jax.lax.fori_loop(0, n_tiles_t, tstep,
                                              (m0, l0, acc0))
                row_blocks.append(acc / jnp.maximum(l, 1e-30))
            col_blocks.append(jnp.concatenate(row_blocks, axis=0))
        refs["acc"][...] = jnp.concatenate(col_blocks, axis=1)
        pltpu.sync_copy(refs["acc"],
                        arena.at[pl.ds(out_off + j * b, b)])


def attn_decode_body(cfg, args, refs, len_s):
    """Single-token GQA flash decode over the (already appended) cache.

    q: (B, h_loc*hd) activation; out same shape. Loops heads × batch;
    each (head, batch) pair streams the cache in (T_TILE, hd) tiles with
    online-softmax accumulation — at EACH ROW'S OWN length ``len_s[bb]``
    (the live-slot serving form; a uniform batch degenerates to the old
    single-length decode, including the per-row tile-loop trip counts).
    Quantized pools route to the fused-dequant variant; the fp32 path
    below is untouched.
    """
    if cfg.kv_quant:
        return _attn_decode_body_quant(cfg, args, refs, len_s)
    arena, k_cache, v_cache, va, vkt = (refs["arena"], refs["k_cache"],
                                        refs["v_cache"], refs["va"],
                                        refs["vkt"])
    q_off, out_off, layer, qnorm_off = args[0], args[1], args[2], args[3]
    b, hd, w = cfg.batch, cfg.hd, cfg.w
    h_loc, kv_loc = cfg.h_loc, cfg.kv_loc
    t_tile = vkt.shape[0]
    pos_rows = jnp.concatenate(
        [jnp.full((1, 1), len_s[bb], jnp.int32) for bb in range(b)],
        axis=0)
    group = h_loc // kv_loc
    heads_per_tile = w // hd

    pltpu.sync_copy(arena.at[pl.ds(qnorm_off, 1)],
                    refs["vb"].at[pl.ds(0, 1)])
    qn_row = refs["vb"][0, :hd].astype(jnp.float32)

    def per_qtile(j, _):
        pltpu.sync_copy(arena.at[pl.ds(q_off + j * b, b)], va)
        qtile = va[...].astype(jnp.float32)     # (b, w)
        col_blocks = []

        # Static head/batch loops with concat assembly: Mosaic lowers
        # neither dynamic_slice nor dynamic_update_slice on values.
        for hh in range(heads_per_tile):
            h_idx = j * heads_per_tile + hh
            # Padding heads beyond h_loc compute garbage that is
            # discarded below; clamp the cache index to stay in bounds.
            kv_head = jnp.minimum(h_idx // group, cfg.kv_loc - 1)
            q = qtile[:, hh * hd:(hh + 1) * hd]
            q = _rms_rows(q, qn_row, cfg.rms_eps)
            q = _rope_rows(q, pos_rows, hd, cfg.rope_theta)
            q = q / jnp.sqrt(jnp.float32(hd))
            row_blocks = []

            for bb in range(b):
                kv_len = len_s[bb] + 1
                n_tiles_t = pl.cdiv(kv_len, t_tile)

                # All-2-D online softmax: Mosaic has no 1-D vector ops.
                def tstep(tt, carry, bb=bb, q=q, kv_head=kv_head,
                          kv_len=kv_len):
                    m, l, acc = carry
                    pltpu.sync_copy(
                        _kv_slice(k_cache, refs, cfg, layer, bb,
                                  tt * t_tile, t_tile, kv_head), vkt)
                    kt = vkt[...].astype(jnp.float32)   # (t_tile, hd)
                    s = jnp.dot(q[bb:bb + 1], kt.T,
                                preferred_element_type=jnp.float32)
                    tpos = tt * t_tile + jax.lax.broadcasted_iota(
                        jnp.int32, (1, t_tile), 1)
                    s = jnp.where(tpos < kv_len, s, -jnp.inf)  # (1, T)
                    m_new = jnp.maximum(
                        m, jnp.max(s, axis=1, keepdims=True))
                    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                    p = jnp.where(jnp.isfinite(s),
                                  jnp.exp(s - m_safe), 0.0)
                    corr = jnp.where(jnp.isfinite(m),
                                     jnp.exp(m - m_safe), 0.0)
                    pltpu.sync_copy(
                        _kv_slice(v_cache, refs, cfg, layer, bb,
                                  tt * t_tile, t_tile, kv_head), vkt)
                    vt = vkt[...].astype(jnp.float32)
                    acc = acc * corr + jnp.dot(
                        p, vt, preferred_element_type=jnp.float32)
                    l = l * corr + jnp.sum(p, axis=1, keepdims=True)
                    return (m_new, l, acc)

                m0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
                l0 = jnp.zeros((1, 1), jnp.float32)
                acc0 = jnp.zeros((1, hd), jnp.float32)
                m, l, acc = jax.lax.fori_loop(0, n_tiles_t, tstep,
                                              (m0, l0, acc0))
                row_blocks.append(acc / jnp.maximum(l, 1e-30))  # (1,hd)

            blk = jnp.concatenate(row_blocks, axis=0)   # (b, hd)
            # h_idx is traced (j rides the tile fori); zero padded heads.
            col_blocks.append(jnp.where(h_idx < cfg.h_loc, blk, 0.0))

        refs["acc"][...] = jnp.concatenate(col_blocks, axis=1)
        pltpu.sync_copy(refs["acc"], arena.at[pl.ds(out_off + j * b, b)])
        return 0

    q_tiles = pl.cdiv(h_loc * hd, w)
    jax.lax.fori_loop(0, q_tiles, per_qtile, 0)


def gather_body(cfg, args, refs, tok_s):
    """Embedding lookup over the *vocab-sharded* table: each rank holds
    ``vocab_loc`` entries; non-owners write zeros and the following
    ALLREDUCE task sums the one real contribution. Token ids arrive via
    scalar prefetch; out-of-shard (including out-of-vocab) ids simply
    produce a zero contribution, so no arena row outside the table is
    ever addressed."""
    arena, vb = refs["arena"], refs["vb"]
    table_off, out_off, d_tiles, vocab_loc = (args[0], args[1], args[2],
                                              args[3])
    b = cfg.batch
    me = dl.rank(cfg.axis)

    for bb in range(b):  # static batch
        tok_local = tok_s[bb] - me * vocab_loc
        owner = jnp.logical_and(tok_local >= 0, tok_local < vocab_loc)
        tok_safe = jnp.clip(tok_local, 0, vocab_loc - 1)

        def per_tile(j, _):
            @pl.when(owner)
            def _():
                pltpu.sync_copy(
                    arena.at[pl.ds(table_off + tok_safe * d_tiles + j, 1)],
                    vb.at[pl.ds(0, 1)])

            @pl.when(jnp.logical_not(owner))
            def _():
                vb[pl.ds(0, 1), :] = jnp.zeros((1, cfg.w), vb.dtype)

            pltpu.sync_copy(
                vb.at[pl.ds(0, 1)],
                arena.at[pl.ds(out_off + j * b + bb, 1)])
            return 0

        jax.lax.fori_loop(0, d_tiles, per_tile, 0)


def allreduce_body(cfg, args, refs):
    """One-shot in-kernel allreduce of an arena slab across the TP axis
    (reference: megakernel allreduce + barrier tasks,
    ``mega_triton_kernel/kernels/allreduce.py``)."""
    arena, va, vb, send_sem, recv_sem = (
        refs["arena"], refs["va"], refs["vb"], refs["send_sem"],
        refs["recv_sem"])
    # args[1] (tiles) is a traced prefetch read, but every ALLREDUCE the
    # builder records moves exactly ``ar_max_tiles`` tiles — use the
    # static value so the slab slice has a static SIZE (Mosaic needs
    # one, and the jax-0.4.x discharge interpreter rejects traced
    # dynamic-slice shapes — the one blocker that kept the whole
    # megakernel family off the CPU compat backend).
    buf_off, tiles = args[0], cfg.ar_max_tiles
    b, n = cfg.batch, cfg.n_ranks
    if n == 1:
        return
    me = dl.rank(cfg.axis)
    rows = tiles * b
    slab = arena.at[pl.ds(buf_off, rows)]
    my_slot = arena.at[pl.ds(cfg.ar_ws_off + me * cfg.ar_max_tiles * b,
                             rows)]

    dl.barrier_all(cfg.axis, ctx=cfg.mesh)
    copies = []
    for off in range(1, n):
        peer = jax.lax.rem(me + off, n)
        copies.append(dl.remote_put(slab, my_slot, send_sem.at[off - 1],
                                    recv_sem, peer, axis=cfg.axis,
                                    ctx=cfg.mesh))
    for c in copies:
        c.wait_send()
    dl.wait_arrivals(recv_sem, slab, n - 1)

    def step(j, _):
        pltpu.sync_copy(arena.at[pl.ds(buf_off + j * b, b)], va)
        acc = va[...].astype(jnp.float32)
        for r_off in range(1, n):
            peer = jax.lax.rem(me + r_off, n)
            pltpu.sync_copy(
                arena.at[pl.ds(cfg.ar_ws_off
                               + peer * cfg.ar_max_tiles * b + j * b, b)],
                vb)
            acc = acc + vb[...].astype(jnp.float32)
        va[...] = acc
        pltpu.sync_copy(va, arena.at[pl.ds(buf_off + j * b, b)])
        return 0

    jax.lax.fori_loop(0, tiles, step, 0)


def _rope_rows(x, pos_rows, hd, theta):
    """x: (rows, hd) fp32; per-row positions pos_rows (rows, 1)."""
    half = hd // 2
    # Integer iota + cast: tpu.iota only produces integer vectors.
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, half), 1
                                   ).astype(jnp.float32) * 2.0
    inv = 1.0 / (theta ** (idx / hd))                 # (1, half)
    ang = pos_rows.astype(jnp.float32) * inv          # (rows, half)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[:, :half], x[:, half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=1)


def write_kv_prefill_body(cfg, args, refs, len_s):
    """Batched prefill cache append: rows are (batch, seq) pairs in
    b-major order; row r writes cache position base + r % seq of batch
    r // seq. The whole (S, hd) block per (batch, head) lands in ONE
    store — the real prefill path the round-1 decode chain lacked."""
    arena, k_cache, v_cache = (refs["arena"], refs["k_cache"],
                               refs["v_cache"])
    va, vb, vsq = refs["va"], refs["vb"], refs["vsq"]
    k_off, v_off, layer, knorm_off = args[0], args[1], args[2], args[3]
    rows, hd, w = cfg.batch, cfg.hd, cfg.w
    seq = cfg.seq
    nb = rows // seq
    base = len_s[0]
    heads_per_tile = w // hd
    kv_tiles = pl.cdiv(cfg.kv_loc * hd, w)
    row_pos = base + jax.lax.rem(
        jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0), seq)

    pltpu.sync_copy(arena.at[pl.ds(knorm_off, 1)], vb.at[pl.ds(0, 1)])
    wrow = vb[0, :hd].astype(jnp.float32)

    # Static head/batch loops with static column slices — Mosaic has
    # no lowering for value-level dynamic_slice with traced starts.
    def per_tile(j, _):
        pltpu.sync_copy(arena.at[pl.ds(k_off + j * rows, rows)], va)
        kt = va[...].astype(jnp.float32)

        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh

            @pl.when(kv_head < cfg.kv_loc)
            def _():
                head = kt[:, hh * hd:(hh + 1) * hd]
                head = _rms_rows(head, wrow, cfg.rms_eps)
                head = _rope_rows(head, row_pos, hd, cfg.rope_theta)
                for bb in range(nb):  # static batch
                    vsq[...] = head[bb * seq:(bb + 1) * seq].astype(
                        vsq.dtype)
                    pltpu.sync_copy(
                        vsq, _kv_slice(k_cache, refs, cfg, layer, bb,
                                       base, seq, kv_head))

        pltpu.sync_copy(arena.at[pl.ds(v_off + j * rows, rows)], va)
        vt = va[...]

        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh

            @pl.when(kv_head < cfg.kv_loc)
            def _():
                for bb in range(nb):
                    vsq[...] = vt[bb * seq:(bb + 1) * seq,
                                  hh * hd:(hh + 1) * hd].astype(vsq.dtype)
                    pltpu.sync_copy(
                        vsq, _kv_slice(v_cache, refs, cfg, layer, bb,
                                       base, seq, kv_head))
        return 0

    jax.lax.fori_loop(0, kv_tiles, per_tile, 0)


def attn_prefill_body(cfg, args, refs, len_s):
    """Batched causal prefill attention over the just-appended cache.

    Rows are (batch, seq) pairs; row s of batch b attends cache
    positions <= base + s. Each (batch, head) pair runs a (S, t_tile)
    blocked online softmax — S query rows per MXU pass instead of the
    decode body's single row (reference megakernel flash_attn task)."""
    arena, k_cache, v_cache, va, vkt = (refs["arena"], refs["k_cache"],
                                        refs["v_cache"], refs["va"],
                                        refs["vkt"])
    q_off, out_off, layer, qnorm_off = args[0], args[1], args[2], args[3]
    rows, hd, w = cfg.batch, cfg.hd, cfg.w
    seq = cfg.seq
    nb = rows // seq
    t_tile = vkt.shape[0]
    base = len_s[0]
    kv_len = base + seq
    n_tiles_t = pl.cdiv(kv_len, t_tile)
    group = cfg.h_loc // cfg.kv_loc
    heads_per_tile = w // hd
    row_pos = base + jax.lax.rem(
        jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0), seq)

    pltpu.sync_copy(arena.at[pl.ds(qnorm_off, 1)],
                    refs["vb"].at[pl.ds(0, 1)])
    qn_row = refs["vb"][0, :hd].astype(jnp.float32)

    def per_qtile(j, _):
        pltpu.sync_copy(arena.at[pl.ds(q_off + j * rows, rows)], va)
        qtile = va[...].astype(jnp.float32)
        col_blocks = []

        for hh in range(heads_per_tile):
            h_idx = j * heads_per_tile + hh
            kv_head = jnp.minimum(h_idx // group, cfg.kv_loc - 1)
            q = qtile[:, hh * hd:(hh + 1) * hd]
            q = _rms_rows(q, qn_row, cfg.rms_eps)
            q = _rope_rows(q, row_pos, hd, cfg.rope_theta)
            q = q / jnp.sqrt(jnp.float32(hd))
            row_blocks = []

            for bb in range(nb):
                qb = q[bb * seq:(bb + 1) * seq]
                srow = jax.lax.broadcasted_iota(jnp.int32, (seq, 1), 0)

                def tstep(tt, carry, bb=bb, qb=qb, kv_head=kv_head):
                    m, l, acc = carry
                    pltpu.sync_copy(
                        _kv_slice(k_cache, refs, cfg, layer, bb,
                                  tt * t_tile, t_tile, kv_head), vkt)
                    kt = vkt[...].astype(jnp.float32)   # (t_tile, hd)
                    s = jnp.dot(qb, kt.T,
                                preferred_element_type=jnp.float32)
                    tpos = tt * t_tile + jax.lax.broadcasted_iota(
                        jnp.int32, (1, t_tile), 1)
                    mask = tpos <= (base + srow)        # causal
                    s = jnp.where(mask, s, -jnp.inf)
                    m_new = jnp.maximum(
                        m, jnp.max(s, axis=1, keepdims=True))
                    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                    p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe),
                                  0.0)
                    corr = jnp.where(jnp.isfinite(m),
                                     jnp.exp(m - m_safe), 0.0)
                    pltpu.sync_copy(
                        _kv_slice(v_cache, refs, cfg, layer, bb,
                                  tt * t_tile, t_tile, kv_head), vkt)
                    vt = vkt[...].astype(jnp.float32)
                    acc = acc * corr + jnp.dot(
                        p, vt, preferred_element_type=jnp.float32)
                    l = l * corr + jnp.sum(p, axis=1, keepdims=True)
                    return (m_new, l, acc)

                m0 = jnp.full((seq, 1), -jnp.inf, jnp.float32)
                l0 = jnp.zeros((seq, 1), jnp.float32)
                acc0 = jnp.zeros((seq, hd), jnp.float32)
                m, l, acc = jax.lax.fori_loop(0, n_tiles_t, tstep,
                                              (m0, l0, acc0))
                row_blocks.append(acc / jnp.maximum(l, 1e-30))

            blk = jnp.concatenate(row_blocks, axis=0)   # (rows, hd)
            col_blocks.append(jnp.where(h_idx < cfg.h_loc, blk, 0.0))

        refs["acc"][...] = jnp.concatenate(col_blocks, axis=1)
        pltpu.sync_copy(refs["acc"],
                        arena.at[pl.ds(out_off + j * rows, rows)])
        return 0

    q_tiles = pl.cdiv(cfg.h_loc * hd, w)
    jax.lax.fori_loop(0, q_tiles, per_qtile, 0)


def _chunk_apos(enc):
    """Decode one chunk row's sign-encoded position to its ATTEND
    position (clamped ≥ 0 for rope/mask arithmetic). The encoding —
    shared with :func:`ops.chunked_prefill.chunk_row_codes` — packs the
    chunk task's three row kinds into the existing per-row cache_len
    vector, so no extra prefetch operand exists:

    - ``enc >= 0``       write + attend at position ``enc``;
    - ``enc <= -2``      attend-only at position ``-enc - 2`` (prefix-
      resident positions below ``wfrom`` — their K/V was written by the
      first sharer and is never re-blitted, exactly the
      ``chunk_write_ids`` scratch-routing rule);
    - ``enc == -1``      dead row (bucket padding) — decodes to
      position 0, computes garbage the host discards, and the write
      body's ``enc >= 0`` store mask keeps it out of every page.
    """
    return jnp.maximum(jnp.where(enc >= 0, enc, -enc - 2), 0)


def write_kv_qblock_body(cfg, args, refs, len_s):
    """Q-block (speculative verification) cache append: batch rows are
    (slot, j) pairs in slot-major order (``cfg.seq`` = K rows per
    slot); row r appends K/V at its OWN position ``len_s[r]`` through
    slot ``r // K``'s block-table row. ``len_s[r] < 0`` MASKS the row
    entirely (over-budget candidates near a request's token budget,
    parked slots) — masked rows write nothing, so real pages and, on
    quantized pools, their scales are never touched. Math is
    op-for-op :func:`write_kv_body`'s per-row path, so on UNQUANTIZED
    pools a committed candidate's stored K/V is bit-identical to what
    the sequential decode lane would have written at that position
    (greedy spec exactness). Quantized pools are token-AGREEING only:
    an in-budget draft that is later rejected can have grown a page's
    running scale (rescaling committed tokens once) — exactly the
    layer path's merge behaviour, bounded by the quantization
    contract."""
    arena, k_cache, v_cache = (refs["arena"], refs["k_cache"],
                               refs["v_cache"])
    va, vb, vhd = refs["va"], refs["vb"], refs["vhd"]
    tbl_s = refs["tbl_s"]
    k_off, v_off, layer, knorm_off = args[0], args[1], args[2], args[3]
    rows, hd, kv_loc, w = cfg.batch, cfg.hd, cfg.kv_loc, cfg.w
    kq = cfg.seq
    heads_per_tile = w // hd
    kv_tiles = -(-(kv_loc * hd) // w)
    pos_rows = jnp.concatenate(
        [jnp.full((1, 1), jnp.maximum(len_s[r], 0), jnp.int32)
         for r in range(rows)], axis=0)

    pltpu.sync_copy(arena.at[pl.ds(knorm_off, 1)], vb.at[pl.ds(0, 1)])
    wrow = vb[0, :hd].astype(jnp.float32)

    def _store(cache, scale_name, r, kv_head, head_row):
        slot = r // kq
        pos = jnp.maximum(len_s[r], 0)
        pid = tbl_s[slot * cfg.p_max + pos // cfg.page]
        off = jax.lax.rem(pos, cfg.page)

        @pl.when(len_s[r] >= 0)
        def _():
            if cfg.kv_quant:
                _quant_store_token(cfg, refs, cache, scale_name, layer,
                                   pid, off, kv_head, head_row)
            else:
                vhd[pl.ds(0, 1), :] = head_row.astype(vhd.dtype)
                pltpu.sync_copy(
                    vhd.at[pl.ds(0, 1)],
                    cache.at[layer, pid, pl.ds(off, 1), kv_head, :])

    for j in range(kv_tiles):                      # static tile loop
        pltpu.sync_copy(arena.at[pl.ds(k_off + j * rows, rows)], va)
        kt = va[...].astype(jnp.float32)
        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh      # static head index
            if kv_head >= kv_loc:
                continue
            head = kt[:, hh * hd:(hh + 1) * hd]
            head = _rms_rows(head, wrow, cfg.rms_eps)
            head = _rope_rows(head, pos_rows, hd, cfg.rope_theta)
            for r in range(rows):
                _store(k_cache, "k_scale", r, kv_head, head[r:r + 1])
        pltpu.sync_copy(arena.at[pl.ds(v_off + j * rows, rows)], va)
        vt = va[...].astype(jnp.float32)
        for hh in range(heads_per_tile):
            kv_head = j * heads_per_tile + hh
            if kv_head >= kv_loc:
                continue
            for r in range(rows):
                _store(v_cache, "v_scale", r, kv_head,
                       vt[r:r + 1, hh * hd:(hh + 1) * hd])


def write_kv_chunk_body(cfg, args, refs, len_s):
    """Prefill-chunk cache append: store row r's K/V iff its encoded
    position is non-negative — which under the :func:`_chunk_apos`
    encoding is EXACTLY the Q-block body's ``len_s[r] >= 0`` store
    mask, so this delegates verbatim. Attend-only rows (prefix-resident
    positions, encoded ``<= -2``) and dead padding rows (``-1``) never
    touch a page or, on quantized pools, a scale — the in-kernel form
    of ``chunk_write_ids``'s scratch routing. Rows store one token
    each, in ascending-position row order per (layer, page, kv_head),
    so a quantized page's running-scale evolution (and the ``off == 0``
    page-start reset that handles ragged chunk tails reusing freed
    pages) is the same per-head sequence the one-token lane produces.
    """
    write_kv_qblock_body(cfg, args, refs, len_s)


def attn_qblock_body(cfg, args, refs, len_s):
    """Q-block verification attention: each slot's K query rows attend
    the (just-appended) cache under the PER-QUERY causal mask
    ``key_pos <= len_s[row]`` — the ``ops/paged_flash_qblock`` mask as
    a megakernel task. One task covers a whole K-token verification
    chain's attention for one layer; each query row runs the SAME
    (1, hd) online-softmax stream as :func:`attn_decode_body`, so a
    committed candidate's logits are bit-identical to the sequential
    decode's (the greedy-acceptance exactness contract). Rows with
    ``len_s[row] < 0`` compute garbage the host discards."""
    _attn_rowpos_body(cfg, args, refs,
                      [jnp.maximum(len_s[r], 0)
                       for r in range(cfg.batch)])


def attn_chunk_body(cfg, args, refs, len_s):
    """Prefill-chunk attention: the Q-block per-query causal stream
    over one C-token prompt chunk, row positions decoded from the
    sign-encoded cache_len vector (:func:`_chunk_apos`). Row r attends
    keys at positions ``<= apos[r]`` — :func:`ops.chunked_prefill.
    chunk_attend`'s global causal mask — which covers earlier chunks,
    the shared prefix, AND this chunk's own earlier rows (the paired
    WRITE_KV_CHUNK task already appended them; the task dep enforces
    the order), so chunk boundaries are invisible to the math. Dead
    (padding) rows compute garbage the host discards."""
    _attn_rowpos_body(cfg, args, refs,
                      [_chunk_apos(len_s[r]) for r in range(cfg.batch)])


def _attn_rowpos_body(cfg, args, refs, row_pos):
    """Shared per-row-position attention core of
    :func:`attn_qblock_body` / :func:`attn_chunk_body`: ``row_pos`` is
    a python list of ``cfg.batch`` traced int32 scalars (≥ 0), row r's
    query rope position and causal horizon (``kv_len = row_pos[r]+1``).
    """
    arena, k_cache, v_cache, va, vkt = (refs["arena"], refs["k_cache"],
                                        refs["v_cache"], refs["va"],
                                        refs["vkt"])
    tbl_s = refs["tbl_s"]
    q_off, out_off, layer, qnorm_off = args[0], args[1], args[2], args[3]
    rows, hd, w = cfg.batch, cfg.hd, cfg.w
    h_loc, kv_loc = cfg.h_loc, cfg.kv_loc
    kq = cfg.seq
    t_tile = (refs["vqt"].shape[0] if cfg.kv_quant else vkt.shape[0])
    group = h_loc // kv_loc
    heads_per_tile = w // hd
    pos_rows = jnp.concatenate(
        [jnp.full((1, 1), row_pos[r], jnp.int32)
         for r in range(rows)], axis=0)

    pltpu.sync_copy(arena.at[pl.ds(qnorm_off, 1)],
                    refs["vb"].at[pl.ds(0, 1)])
    qn_row = refs["vb"][0, :hd].astype(jnp.float32)

    q_tiles = -(-(h_loc * hd) // w)
    for j in range(q_tiles):                       # static tile loop
        pltpu.sync_copy(arena.at[pl.ds(q_off + j * rows, rows)], va)
        qtile = va[...].astype(jnp.float32)
        col_blocks = []
        for hh in range(heads_per_tile):
            h_idx = j * heads_per_tile + hh        # static head index
            if h_idx >= h_loc:
                col_blocks.append(jnp.zeros((rows, hd), jnp.float32))
                continue
            kv_head = h_idx // group
            q = qtile[:, hh * hd:(hh + 1) * hd]
            q = _rms_rows(q, qn_row, cfg.rms_eps)
            q = _rope_rows(q, pos_rows, hd, cfg.rope_theta)
            q = q / jnp.sqrt(jnp.float32(hd))
            row_blocks = []
            for r in range(rows):
                slot = r // kq
                kv_len = row_pos[r] + 1
                n_tiles_t = pl.cdiv(kv_len, t_tile)

                def tstep(tt, carry, slot=slot, r=r, q=q,
                          kv_head=kv_head, kv_len=kv_len):
                    m, l, acc = carry
                    if cfg.kv_quant:
                        pid = tbl_s[slot * cfg.p_max
                                    + (tt * t_tile) // cfg.page]
                        start = jax.lax.rem(tt * t_tile, cfg.page)
                        kt = _dequant_tile(cfg, refs, k_cache,
                                           "k_scale", layer, pid,
                                           start, kv_head)
                    else:
                        pltpu.sync_copy(
                            _kv_slice(k_cache, refs, cfg, layer, slot,
                                      tt * t_tile, t_tile, kv_head),
                            vkt)
                        kt = vkt[...].astype(jnp.float32)
                    s = jnp.dot(q[r:r + 1], kt.T,
                                preferred_element_type=jnp.float32)
                    tpos = tt * t_tile + jax.lax.broadcasted_iota(
                        jnp.int32, (1, t_tile), 1)
                    s = jnp.where(tpos < kv_len, s, -jnp.inf)
                    m_new = jnp.maximum(
                        m, jnp.max(s, axis=1, keepdims=True))
                    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                    p = jnp.where(jnp.isfinite(s),
                                  jnp.exp(s - m_safe), 0.0)
                    corr = jnp.where(jnp.isfinite(m),
                                     jnp.exp(m - m_safe), 0.0)
                    if cfg.kv_quant:
                        vt = _dequant_tile(cfg, refs, v_cache,
                                           "v_scale", layer, pid,
                                           start, kv_head)
                    else:
                        pltpu.sync_copy(
                            _kv_slice(v_cache, refs, cfg, layer, slot,
                                      tt * t_tile, t_tile, kv_head),
                            vkt)
                        vt = vkt[...].astype(jnp.float32)
                    acc = acc * corr + jnp.dot(
                        p, vt, preferred_element_type=jnp.float32)
                    l = l * corr + jnp.sum(p, axis=1, keepdims=True)
                    return (m_new, l, acc)

                m0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
                l0 = jnp.zeros((1, 1), jnp.float32)
                acc0 = jnp.zeros((1, hd), jnp.float32)
                m, l, acc = jax.lax.fori_loop(0, n_tiles_t, tstep,
                                              (m0, l0, acc0))
                row_blocks.append(acc / jnp.maximum(l, 1e-30))
            col_blocks.append(jnp.concatenate(row_blocks, axis=0))
        refs["acc"][...] = jnp.concatenate(col_blocks, axis=1)
        pltpu.sync_copy(refs["acc"],
                        arena.at[pl.ds(out_off + j * rows, rows)])


def gdn_decode_body(cfg, args, refs):
    """Gated-delta-rule decode step for one GDN layer, all (batch,
    local-head) pairs: S ← exp(g)·S + β·k(v − Sᵀk)ᵀ; o = Sᵀq
    (``ops/gdn.gdn_decode_step`` math, normalize_qk on). Head slices
    live inside lane tiles (w % dk == 0, w % dv == 0 — builder
    contract); per-(b, h) scalars are extracted with masked reduces
    (no dynamic vector indexing). Row DMAs are grouped per lane tile —
    each q/k row loads once per batch entry, each v/output row once
    per v-tile — and the recurrent state rides the ``states`` buffer,
    the hybrid family's KV-cache analogue."""
    arena, states = refs["arena"], refs["states"]
    va, vb = refs["va"], refs["vb"]
    vrow, vrow2, vS = refs["vrow"], refs["vrow2"], refs["vS"]
    q_off, k_off, v_off = args[0], args[1], args[2]
    graw_off, braw_off, gbias_off = args[3], args[4], args[5]
    out_off, gl = args[6], args[7]
    b, w = cfg.batch, cfg.w
    h_loc, dk, dv = cfg.gdn_h_loc, cfg.gdn_dk, cfg.gdn_dv

    pltpu.sync_copy(arena.at[pl.ds(graw_off, b)], va)     # g raw (b, w)
    pltpu.sync_copy(arena.at[pl.ds(braw_off, b)], vb)     # beta raw
    pltpu.sync_copy(arena.at[pl.ds(gbias_off, 1)], vrow)  # bias (1, w)
    g_all = -jax.nn.softplus(va[...].astype(jnp.float32)
                             + vrow[...].astype(jnp.float32))
    beta_all = jax.nn.sigmoid(vb[...].astype(jnp.float32))
    rows_i = jax.lax.broadcasted_iota(jnp.int32, (b, w), 0)
    cols_i = jax.lax.broadcasted_iota(jnp.int32, (b, w), 1)

    # Static DMA plan: heads grouped by their q-tile; within a group,
    # v/output rows reload only when the v-tile changes (heads are
    # ascending, so v-tiles are nondecreasing). g_all/beta_all live in
    # registers, freeing va/vb as the q/k row buffers.
    gq_tiles = -(-(h_loc * dk) // w)

    def bstep(bb, _):
        qrow = va.at[0:1]
        krow = vb.at[0:1]
        cur_jv = [None]

        def flush_out():
            if cur_jv[0] is not None:
                pltpu.sync_copy(
                    vrow2, arena.at[pl.ds(out_off + cur_jv[0] * b + bb,
                                          1)])

        for jq in range(gq_tiles):
            heads = [hh for hh in range(h_loc) if (hh * dk) // w == jq]
            if not heads:
                continue
            pltpu.sync_copy(arena.at[pl.ds(q_off + jq * b + bb, 1)],
                            qrow)
            pltpu.sync_copy(arena.at[pl.ds(k_off + jq * b + bb, 1)],
                            krow)
            for h in heads:
                cq = (h * dk) % w
                jv, cv = (h * dv) // w, (h * dv) % w
                if jv != cur_jv[0]:
                    flush_out()
                    pltpu.sync_copy(
                        arena.at[pl.ds(v_off + jv * b + bb, 1)], vrow)
                    pltpu.sync_copy(
                        arena.at[pl.ds(out_off + jv * b + bb, 1)],
                        vrow2)
                    cur_jv[0] = jv
                sel = jnp.logical_and(rows_i == bb, cols_i == h)
                g_s = jnp.exp(jnp.sum(jnp.where(sel, g_all, 0.0)))
                b_s = jnp.sum(jnp.where(sel, beta_all, 0.0))
                q = qrow[0:1, cq:cq + dk].astype(jnp.float32)
                k = krow[0:1, cq:cq + dk].astype(jnp.float32)
                # FLA-convention L2 norm — must track ops/gdn._l2norm
                # (the layer oracle this kernel is tested against).
                q = q * jax.lax.rsqrt(
                    jnp.sum(q * q, axis=1, keepdims=True) + 1e-6)
                k = k * jax.lax.rsqrt(
                    jnp.sum(k * k, axis=1, keepdims=True) + 1e-6)
                v = vrow[0:1, cv:cv + dv].astype(jnp.float32)

                pltpu.sync_copy(states.at[gl, bb, h], vS)
                S = vS[...] * g_s
                pred = jnp.dot(k, S,
                               preferred_element_type=jnp.float32)
                delta = (v - pred) * b_s
                S = S + jnp.dot(k.reshape(dk, 1), delta,
                                preferred_element_type=jnp.float32)
                o = jnp.dot(q, S, preferred_element_type=jnp.float32)
                vS[...] = S
                pltpu.sync_copy(vS, states.at[gl, bb, h])
                vrow2[0:1, cv:cv + dv] = o.astype(vrow2.dtype)
        flush_out()
        return 0

    jax.lax.fori_loop(0, b, bstep, 0)
