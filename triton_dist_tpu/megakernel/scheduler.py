"""ctypes bindings for the native scheduler
(``csrc/megakernel_scheduler.cc``) with lazy compilation via g++.

Reference analogue: ``mega_triton_kernel/core/scheduler.py`` — here the
graph algorithms live in C++ (the natural native component of the
runtime) and Python only marshals arrays.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import List, Sequence, Tuple

import numpy as np

_LIB = None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _cache_dir() -> str:
    """Fallback .so location for read-only checkouts."""
    from triton_dist_tpu import tune

    path = os.path.join(tune.cache_dir(), "csrc")
    os.makedirs(path, exist_ok=True)
    return path


def _compile_so(src: str, so: str) -> None:
    """Compile ``src`` into ``so`` safely under concurrency: g++ writes
    a process-private temp file which is then atomically renamed into
    place. Two racing processes each build a complete .so and the
    rename winner-takes-last — a reader can never dlopen a half-written
    library (the failure mode of compiling straight to the shared
    path)."""
    fd, tmp = tempfile.mkstemp(suffix=".so", prefix=".tdt_sched_",
                               dir=os.path.dirname(so))
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared", "-o", tmp,
             src],
            check=True)
        os.chmod(tmp, 0o755)  # mkstemp's 0600 would break shared caches
        os.replace(tmp, so)   # atomic within the directory
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load_lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    csrc = os.path.join(_repo_root(), "csrc")
    src = os.path.join(csrc, "megakernel_scheduler.cc")
    # Content-hash keyed binary in BOTH locations (the csrc/Makefile
    # builds the same name): a scheduler edit — e.g. the dynamic-queue
    # precompute — can never be shadowed by a stale mtime-fresh .so,
    # and checkouts sharing a cache dir cannot accept each other's
    # builds.
    with open(src, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:12]
    name = f"libtdt_scheduler-{digest}.so"
    so = os.path.join(csrc, name)
    if not os.path.exists(so):
        try:
            _compile_so(src, so)
        except (OSError, PermissionError):
            # Read-only checkout: build into the user cache dir instead.
            so = os.path.join(_cache_dir(), name)
            if not os.path.exists(so):
                _compile_so(src, so)
    lib = ctypes.CDLL(so)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.tdt_schedule.restype = ctypes.c_int32
    lib.tdt_schedule.argtypes = [ctypes.c_int32, i32p, i32p,
                                 ctypes.c_int32, ctypes.c_int32,
                                 ctypes.c_int32, i32p, i32p, i32p, i32p,
                                 i32p]
    lib.tdt_prune_deps.restype = ctypes.c_int32
    lib.tdt_prune_deps.argtypes = [ctypes.c_int32, i32p, i32p,
                                   ctypes.c_int32]
    lib.tdt_schedule_mc.restype = ctypes.c_int32
    lib.tdt_schedule_mc.argtypes = [
        ctypes.c_int32, i32p, i32p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, i32p, i32p, ctypes.c_int32, i32p, i32p, i32p,
        i32p, i32p, i32p, i32p, i32p, i32p]
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.tdt_schedule_dyn.restype = ctypes.c_int32
    lib.tdt_schedule_dyn.argtypes = [
        ctypes.c_int32, i32p, i32p, ctypes.c_int32, ctypes.c_int32,
        i32p, i32p, i32p, i32p, ctypes.c_int32, i32p, i32p, i32p, i32p,
        i32p, i32p, i32p, i32p, i32p, i64p]
    lib.tdt_sim_static.restype = ctypes.c_int32
    lib.tdt_sim_static.argtypes = [
        ctypes.c_int32, i32p, i32p, ctypes.c_int32, i32p,
        ctypes.c_int32, ctypes.c_int32, i32p, i64p]
    _LIB = lib
    return lib


def _as_i32(a):
    return np.ascontiguousarray(np.asarray(a, np.int32))


def _ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def prune_deps(n_tasks: int, src: Sequence[int], dst: Sequence[int]
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Transitive-reduction pruning (reference enable_dep_opt)."""
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    kept = lib.tdt_prune_deps(n_tasks, _ptr(s), _ptr(d), len(s))
    return s[:kept], d[:kept]


def schedule(n_tasks: int, src: Sequence[int], dst: Sequence[int], *,
             num_cores: int = 1, strategy: str = "round_robin",
             dep_opt: bool = True):
    """Returns dict with order, core, pos, cross-core deps arrays."""
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    if dep_opt and len(s):
        s, d = prune_deps(n_tasks, s, d)
    order = np.zeros(n_tasks, np.int32)
    core = np.zeros(n_tasks, np.int32)
    pos = np.zeros(n_tasks, np.int32)
    nxdeps = np.zeros(n_tasks, np.int32)
    xdeps = np.zeros(max(len(s), 1), np.int32)
    rc = lib.tdt_schedule(n_tasks, _ptr(s), _ptr(d), len(s), num_cores,
                          1 if strategy == "zig_zag" else 0, _ptr(order),
                          _ptr(core), _ptr(pos), _ptr(nxdeps),
                          _ptr(xdeps))
    if rc == -1:
        raise ValueError("dependency cycle in task graph")
    if rc != 0:
        raise ValueError(f"scheduler error {rc}")
    n_x = int(nxdeps.sum())
    return {"order": order, "core": core, "pos": pos,
            "n_cross_deps": nxdeps, "cross_deps": xdeps[:n_x]}


def schedule_mc(n_tasks: int, src: Sequence[int], dst: Sequence[int], *,
                num_cores: int, strategy: str = "round_robin",
                task_cost: Sequence[int] = None,
                pin_core: Sequence[int] = None, dep_opt: bool = True):
    """Multi-core schedule with the sequential-safety guarantee
    (``tdt_schedule_mc``): per-core queues padded with -1 NOOP slots so
    merged (q-major) order respects every dependency, plus the edge
    semaphore scoreboard (wait/signal tables per task).

    strategy: "round_robin" | "zig_zag" | "cost_lpt" (static
    load-balanced analogue of the reference's runtime scheduler).
    """
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    if dep_opt and len(s):
        s, d = prune_deps(n_tasks, s, d)
    strat = {"round_robin": 0, "zig_zag": 1, "cost_lpt": 2}[strategy]
    cost = _as_i32(task_cost if task_cost is not None
                   else np.ones(n_tasks))
    pin = _as_i32(pin_core if pin_core is not None
                  else -np.ones(n_tasks))
    # Worst case every task pads a full round: generous cap.
    qlen_cap = 2 * n_tasks + num_cores
    queue = np.zeros(qlen_cap * num_cores, np.int32)
    wait_start = np.zeros(max(n_tasks, 1), np.int32)
    wait_count = np.zeros(max(n_tasks, 1), np.int32)
    wait_edges = np.zeros(max(len(s), 1), np.int32)
    sig_start = np.zeros(max(n_tasks, 1), np.int32)
    sig_count = np.zeros(max(n_tasks, 1), np.int32)
    sig_edges = np.zeros(max(len(s), 1), np.int32)
    sig_cores = np.zeros(max(len(s), 1), np.int32)
    meta = np.zeros(2, np.int32)
    rc = lib.tdt_schedule_mc(
        n_tasks, _ptr(s), _ptr(d), len(s), num_cores, strat, _ptr(cost),
        _ptr(pin), qlen_cap, _ptr(queue), _ptr(wait_start),
        _ptr(wait_count), _ptr(wait_edges), _ptr(sig_start),
        _ptr(sig_count), _ptr(sig_edges), _ptr(sig_cores), _ptr(meta))
    if rc == -1:
        raise ValueError("dependency cycle in task graph")
    if rc != 0:
        raise ValueError(f"scheduler error {rc}")
    qlen, n_edges = int(meta[0]), int(meta[1])
    return {
        "queue": queue[:qlen * num_cores].reshape(qlen, num_cores),
        "wait_start": wait_start, "wait_count": wait_count,
        "wait_edges": wait_edges[:int(wait_count.sum())],
        "sig_start": sig_start, "sig_count": sig_count,
        "sig_edges": sig_edges[:int(sig_count.sum())],
        "sig_cores": sig_cores[:int(sig_count.sum())],
        "n_edges": n_edges,
    }


def schedule_dyn(n_tasks: int, src: Sequence[int], dst: Sequence[int],
                 *, num_cores: int, priority: Sequence[int] = None,
                 bucket: Sequence[int] = None,
                 task_cost: Sequence[int] = None,
                 pin_core: Sequence[int] = None, dep_opt: bool = True):
    """Dynamic-claim schedule (``tdt_schedule_dyn``): ONE priority-
    ordered claim list the device pops via the scoreboard claim
    counter, instead of per-core slot lists. Claim index ``i`` binds
    to core ``i % num_cores``; ``-1`` entries are holes (NOOP claims
    emitted when the next index's core has no eligible pinned task).

    Returns dict with:
      ``claim_order`` (n_claims,), ``claim_of`` (task -> claim idx),
      ``bucket`` (per task), task-indexed ``wait_*``/``sig_*``
      scoreboard tables (edges for deps whose claim cores differ),
      ``n_claims``, ``n_edges``, ``num_cores``, and the timed-model
      ``idle_units`` / ``makespan`` (compare with
      :func:`simulate_static` on the same costs).
    """
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    if dep_opt and len(s):
        s, d = prune_deps(n_tasks, s, d)
    prio = _as_i32(priority if priority is not None
                   else np.zeros(n_tasks))
    bkt = _as_i32(bucket if bucket is not None else np.zeros(n_tasks))
    cost = _as_i32(task_cost if task_cost is not None
                   else np.ones(n_tasks))
    pin = _as_i32(pin_core if pin_core is not None
                  else -np.ones(n_tasks))
    # Holes only arise from pinning: at most num_cores - 1 per claim.
    cap = n_tasks * num_cores + num_cores
    order = np.zeros(max(cap, 1), np.int32)
    claim_of = np.zeros(max(n_tasks, 1), np.int32)
    wait_start = np.zeros(max(n_tasks, 1), np.int32)
    wait_count = np.zeros(max(n_tasks, 1), np.int32)
    wait_edges = np.zeros(max(len(s), 1), np.int32)
    sig_start = np.zeros(max(n_tasks, 1), np.int32)
    sig_count = np.zeros(max(n_tasks, 1), np.int32)
    sig_edges = np.zeros(max(len(s), 1), np.int32)
    sig_cores = np.zeros(max(len(s), 1), np.int32)
    meta = np.zeros(4, np.int64)
    rc = lib.tdt_schedule_dyn(
        n_tasks, _ptr(s), _ptr(d), len(s), num_cores, _ptr(prio),
        _ptr(bkt), _ptr(cost), _ptr(pin), cap, _ptr(order),
        _ptr(claim_of), _ptr(wait_start), _ptr(wait_count),
        _ptr(wait_edges), _ptr(sig_start), _ptr(sig_count),
        _ptr(sig_edges), _ptr(sig_cores),
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc == -1:
        raise ValueError("dependency cycle in task graph")
    if rc != 0:
        raise ValueError(f"scheduler error {rc}")
    n_claims, n_edges = int(meta[0]), int(meta[1])
    return {
        "claim_order": order[:n_claims], "claim_of": claim_of,
        "bucket": bkt, "num_cores": num_cores,
        "wait_start": wait_start, "wait_count": wait_count,
        "wait_edges": wait_edges[:int(wait_count.sum())],
        "sig_start": sig_start, "sig_count": sig_count,
        "sig_edges": sig_edges[:int(sig_count.sum())],
        "sig_cores": sig_cores[:int(sig_count.sum())],
        "n_claims": n_claims, "n_edges": n_edges,
        "idle_units": int(meta[2]), "makespan": int(meta[3]),
    }


def simulate_static(n_tasks: int, src: Sequence[int],
                    dst: Sequence[int], queue, *,
                    task_cost: Sequence[int] = None) -> dict:
    """Timed replay of a :func:`schedule_mc` queue under the dynamic
    scheduler's cost model (``tdt_sim_static``): per-core columns in
    order, a task starts at max(core free, preds' finish), NOOPs are
    free. Returns {"idle_units", "makespan"} — the static baseline the
    dynamic claim schedule's metrics are compared against.

    Pass the SAME (possibly pruned) edges the schedule was built from;
    this function does not re-prune."""
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    qarr = _as_i32(queue)
    qlen, cores = qarr.shape
    cost = _as_i32(task_cost if task_cost is not None
                   else np.ones(n_tasks))
    meta = np.zeros(2, np.int64)
    rc = lib.tdt_sim_static(
        n_tasks, _ptr(s), _ptr(d), len(s), _ptr(qarr.reshape(-1)),
        qlen, cores, _ptr(cost),
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        raise ValueError(f"simulator error {rc}")
    return {"idle_units": int(meta[0]), "makespan": int(meta[1])}


def describe_slot(sched: dict, q: int, c: int) -> dict:
    """Map a scoreboard step counter — the (queue position, core) pair
    a progress trace or a watchdog reports — back to the task occupying
    it, with the edge semaphores it waits on and signals.

    The diagnostic half of the scoreboard: a deadlocked schedule stops
    at some (q, c); this names the task and the exact edges whose
    missing counts wedged it. ``task == -1`` is a NOOP padding slot.

    Accepts either a static :func:`schedule_mc` dict or a dynamic
    :func:`schedule_dyn` dict — for the latter, slot (q, c) is the
    claim-counter value ``q * num_cores + c`` and the answer names the
    CLAIMED task (see :func:`describe_claim`), not a static queue
    position.
    """
    if "claim_order" in sched:
        cores = int(sched["num_cores"])
        return describe_claim(sched, q * cores + c)
    queue = sched["queue"]
    qlen, cores = queue.shape
    if not (0 <= q < qlen and 0 <= c < cores):
        raise IndexError(f"slot ({q}, {c}) outside queue {queue.shape}")
    task = int(queue[q, c])
    out = {"q": q, "core": c, "task": task,
           "merged_index": q * cores + c}
    if task >= 0:
        ws, wc = int(sched["wait_start"][task]), int(
            sched["wait_count"][task])
        ss, sc = int(sched["sig_start"][task]), int(
            sched["sig_count"][task])
        out["waits_on_edges"] = [int(e) for e in
                                 sched["wait_edges"][ws:ws + wc]]
        out["signals_edges"] = [int(e) for e in
                                sched["sig_edges"][ss:ss + sc]]
    return out


def describe_claim(sched: dict, claim: int) -> dict:
    """Dynamic-mode counterpart of :func:`describe_slot`: attribute a
    claim-counter value (what the dynamic kernel's progress trace and
    the watchdog report) to the claimed task, its priority bucket, and
    the edge semaphores it waits on / signals. ``task == -1`` is a
    hole (NOOP claim). Claims beyond ``n_claims`` are tail padding
    NOOPs of the last partially-filled grid row."""
    n_claims = int(sched["n_claims"])
    cores = int(sched["num_cores"])
    if claim < 0:
        raise IndexError(f"claim {claim} negative")
    out = {"claim": claim, "core": claim % cores,
           "schedule": "dynamic"}
    if claim >= n_claims:
        out["task"] = -1
        out["tail_padding"] = True
        return out
    task = int(sched["claim_order"][claim])
    out["task"] = task
    if task >= 0:
        out["bucket"] = int(sched["bucket"][task])
        ws, wc = int(sched["wait_start"][task]), int(
            sched["wait_count"][task])
        ss, sc = int(sched["sig_start"][task]), int(
            sched["sig_count"][task])
        out["waits_on_edges"] = [int(e) for e in
                                 sched["wait_edges"][ws:ws + wc]]
        out["signals_edges"] = [int(e) for e in
                                sched["sig_edges"][ss:ss + sc]]
    return out
