"""ctypes bindings for the native scheduler
(``csrc/megakernel_scheduler.cc``) with lazy compilation via g++.

Reference analogue: ``mega_triton_kernel/core/scheduler.py`` — here the
graph algorithms live in C++ (the natural native component of the
runtime) and Python only marshals arrays.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Sequence, Tuple

import numpy as np

_LIB = None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _load_lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    csrc = os.path.join(_repo_root(), "csrc")
    so = os.path.join(csrc, "libtdt_scheduler.so")
    src = os.path.join(csrc, "megakernel_scheduler.cc")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared", "-o", so, src],
            check=True)
    lib = ctypes.CDLL(so)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.tdt_schedule.restype = ctypes.c_int32
    lib.tdt_schedule.argtypes = [ctypes.c_int32, i32p, i32p,
                                 ctypes.c_int32, ctypes.c_int32,
                                 ctypes.c_int32, i32p, i32p, i32p, i32p,
                                 i32p]
    lib.tdt_prune_deps.restype = ctypes.c_int32
    lib.tdt_prune_deps.argtypes = [ctypes.c_int32, i32p, i32p,
                                   ctypes.c_int32]
    _LIB = lib
    return lib


def _as_i32(a):
    return np.ascontiguousarray(np.asarray(a, np.int32))


def _ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def prune_deps(n_tasks: int, src: Sequence[int], dst: Sequence[int]
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Transitive-reduction pruning (reference enable_dep_opt)."""
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    kept = lib.tdt_prune_deps(n_tasks, _ptr(s), _ptr(d), len(s))
    return s[:kept], d[:kept]


def schedule(n_tasks: int, src: Sequence[int], dst: Sequence[int], *,
             num_cores: int = 1, strategy: str = "round_robin",
             dep_opt: bool = True):
    """Returns dict with order, core, pos, cross-core deps arrays."""
    lib = _load_lib()
    s, d = _as_i32(src), _as_i32(dst)
    if dep_opt and len(s):
        s, d = prune_deps(n_tasks, s, d)
    order = np.zeros(n_tasks, np.int32)
    core = np.zeros(n_tasks, np.int32)
    pos = np.zeros(n_tasks, np.int32)
    nxdeps = np.zeros(n_tasks, np.int32)
    xdeps = np.zeros(max(len(s), 1), np.int32)
    rc = lib.tdt_schedule(n_tasks, _ptr(s), _ptr(d), len(s), num_cores,
                          1 if strategy == "zig_zag" else 0, _ptr(order),
                          _ptr(core), _ptr(pos), _ptr(nxdeps),
                          _ptr(xdeps))
    if rc == -1:
        raise ValueError("dependency cycle in task graph")
    if rc != 0:
        raise ValueError(f"scheduler error {rc}")
    n_x = int(nxdeps.sum())
    return {"order": order, "core": core, "pos": pos,
            "n_cross_deps": nxdeps, "cross_deps": xdeps[:n_x]}
