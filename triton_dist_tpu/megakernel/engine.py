"""Megakernel inference engine: greedy decode where every step is ONE
persistent Pallas kernel (reference: ``mega_triton_kernel/test/models/``
chat demo / ``model_server.py`` / ``bench_qwen3.py``).

The entire decode step runs inside the kernel: embedding gather (over
the vocab-sharded table), norms, projections, rope, flash decode over
the cache, SwiGLU, the TP allreduces, and the vocab-sharded LM head —
token ids in, logits out.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.megakernel.builder import ModelBuilder
from triton_dist_tpu.models import dense
from triton_dist_tpu.models.config import ModelConfig


class MegaKernelEngine:
    def __init__(self, cfg: ModelConfig, mesh: Mesh, *, batch: int,
                 max_len: int = 512, axis: str = "tp", params=None,
                 seed: int = 0, tile_w=None, t_tile=None,
                 keep_params: bool = False, prefill_seq: int = 0,
                 num_cores: int = 1, strategy: str = "round_robin",
                 schedule: str = "static",
                 paged: bool = False, page=None, num_pages=None,
                 cost_table=None, timeout_s=None,
                 profile: bool = False, kv_dtype: str = "bf16",
                 spec_k: int = 0, prefill_buckets=None):
        """``timeout_s`` arms a per-step watchdog: every
        :meth:`decode_step` / :meth:`prefill` blocks on its result
        under a deadline and raises a structured
        :class:`~triton_dist_tpu.resilience.CommTimeoutError` (rank,
        op, last-completed step counter — see :meth:`progress`) instead
        of hanging on a wedged scoreboard. ``None`` keeps the
        non-blocking async-dispatch behaviour.

        ``schedule``: ``"static"`` (per-core slot lists packed by
        ``strategy``), ``"dynamic"`` (device-side claim counter over a
        comm-priority-ordered ready list — see docs/megakernel.md), or
        ``"auto"`` (the :func:`tune_schedule` winner persisted in the
        tune cache for this (model, mesh, batch, cores) key; falls
        back to static when never tuned).

        ``profile=True`` threads the builder's slot recorder through
        the decode step: after every :meth:`decode_step`,
        :attr:`last_prof` holds the (qlen·num_cores, 2) per-slot
        (task_type, arg0) log — ``builder.prof_tracks(last_prof)``
        shapes it for the Perfetto exporters, and a serving
        :meth:`~triton_dist_tpu.serving.server.ServingEngine.trace`
        session collects it into the merged trace automatically
        (docs/observability.md). Decode-only: the batched prefill
        builder never records, and neither does the ``spec_k``
        verification step — a traced speculative serve carries host
        spans but no megakernel slot records.

        ``kv_dtype``: ``"bf16"`` keeps the original fp32 pools
        (bit-identical code path); ``"int8"``/``"fp8"`` store the K/V
        pools quantized with per-(layer, page, kv_head) fp32 scale
        tables, quantize fused into ``write_kv`` and dequant into
        every cache read — ~3.8x pages per HBM byte on the persistent
        lane's fastest decode path. Requires ``paged=True`` (scales
        are per page); attention families only (hybrid GDN rejected);
        prompts stream through the prefill lane (``prefill_seq`` is
        incompatible).

        ``spec_k=K`` (>= 2) additionally builds the Q-BLOCK
        VERIFICATION step (:meth:`verify_step`): one launch scores K
        drafted tokens per slot under the per-query causal mask —
        the serving layer's speculative decode on the megakernel
        lane. Same constraints as ``kv_dtype`` (paged, non-hybrid,
        no ``prefill_seq``).

        ``prefill_buckets=(C1, C2, ...)`` additionally builds one
        PREFILL-CHUNK step per bucket (:meth:`prefill_chunk`): one
        launch ingests a C-token prompt chunk for one slot through the
        WRITE_KV_CHUNK/ATTN_CHUNK task pair (per-row sign-encoded
        positions, per-query causal mask) — the serving layer's
        bucketed chunked prefill on the megakernel lane, replacing the
        one-token-per-tick prefill lane for prompt ingestion. Same
        constraints as ``kv_dtype`` (paged, non-hybrid, no
        ``prefill_seq``); composes with both ``kv_dtype`` (fused
        quantize-on-write) and ``spec_k``."""
        from triton_dist_tpu.serving.blocks import kv_quant_spec

        qdtype, _ = kv_quant_spec(kv_dtype)
        self.kv_dtype = "bf16" if qdtype is None else kv_dtype
        self.spec_k = int(spec_k or 0)
        if self.spec_k == 1:
            self.spec_k = 0            # K=1 degenerates to plain decode
        self.prefill_buckets = (tuple(sorted(set(
            int(c) for c in prefill_buckets)))
            if prefill_buckets else None)
        if self.prefill_buckets and self.prefill_buckets[0] < 1:
            raise ValueError(f"prefill buckets must be positive ints, "
                             f"got {prefill_buckets!r}")
        for knob, on in (("kv_dtype", qdtype is not None),
                         ("spec_k", bool(self.spec_k)),
                         ("prefill_buckets",
                          bool(self.prefill_buckets))):
            if not on:
                continue
            if not paged:
                raise ValueError(f"{knob} needs paged=True (per-page "
                                 "scales / block-table addressing)")
            if cfg.is_hybrid:
                raise NotImplementedError(
                    f"{knob} covers the attention families; the hybrid "
                    "GDN recurrent state is neither paged nor "
                    "rewindable")
            if prefill_seq > 1:
                raise ValueError(
                    f"{knob} is incompatible with prefill_seq: stream "
                    "prompts through the prefill lane (the decode "
                    "kernel) instead")
        if self.spec_k and self.spec_k < 2:
            raise ValueError(f"spec_k must be 0 or >= 2, got {spec_k}")
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.max_len = max_len
        self.batch = batch
        self.paged = paged
        self.timeout_s = timeout_s
        self.profile = bool(profile)
        self.last_prof = None
        if schedule == "auto":
            schedule = lookup_schedule(cfg, mesh, batch=batch,
                                       num_cores=num_cores, axis=axis)
        self.schedule = schedule
        # Host-side progress counters for watchdog/timeout diagnostics:
        # how many megakernel launches completed, and the queue shape
        # a wedged launch would be stuck inside.
        self.steps_done = 0
        # Resolve the tile once; both builders and the page default use
        # the same value (no silently-divergent default formulas).
        t_tile = t_tile or min(128, max_len)
        if paged and page is None:
            # One page size shared by the decode and prefill builders
            # (they address the same pools): honor both alignment
            # contracts (t_tile | page, prefill_seq | page).
            import math
            page = math.lcm(t_tile,
                            prefill_seq if prefill_seq > 1 else 1)
        self._kv_quant = (None if self.kv_dtype == "bf16"
                          else self.kv_dtype)
        # Engine-wide moe_counts height: every builder sharing the
        # arena claims the same counter span, sized by the LARGEST
        # row count any of them runs (verify = batch·K rows, chunk =
        # bucket rows, prefill = batch·seq rows) — so chunked-prefill
        # and verification traffic accumulates into the decode
        # counters instead of overlapping them (expert_counts()).
        counts_rows = batch
        if self.spec_k:
            counts_rows = max(counts_rows, batch * self.spec_k)
        for c in (self.prefill_buckets or ()):
            counts_rows = max(counts_rows, c)
        if prefill_seq > 1:
            counts_rows = max(counts_rows, batch * prefill_seq)
        self.builder = ModelBuilder(cfg, mesh, batch=batch,
                                    max_len=max_len, axis=axis,
                                    tile_w=tile_w, t_tile=t_tile,
                                    num_cores=num_cores,
                                    strategy=strategy,
                                    schedule=self.schedule, paged=paged,
                                    page=page, cost_table=cost_table,
                                    profile=self.profile,
                                    kv_quant=self._kv_quant,
                                    counts_rows=counts_rows)
        # Q-block verification builder: the SAME weight layout at
        # batch*K rows (seq=K, one row per drafted candidate), sharing
        # the decode arena — its (bigger) activation tail sizes the
        # buffer, exactly the batched-prefill arrangement.
        self.verify_builder = None
        if self.spec_k:
            self.verify_builder = ModelBuilder(
                cfg, mesh, batch=batch * self.spec_k, max_len=max_len,
                axis=axis, tile_w=tile_w, t_tile=t_tile,
                seq=self.spec_k, qblock=True, num_cores=num_cores,
                strategy=strategy, schedule=self.schedule, paged=True,
                page=page, cost_table=cost_table,
                kv_quant=self._kv_quant, counts_rows=counts_rows)
        # Prefill-chunk builders: ONE per bucket (the build cache is
        # bounded by the bucket count by construction), each a C-row
        # single-slot chunk launch (batch = seq = C) sharing the
        # decode arena's weight region like the verify/prefill builds.
        self.chunk_builders = {}
        for c in (self.prefill_buckets or ()):
            self.chunk_builders[c] = ModelBuilder(
                cfg, mesh, batch=c, max_len=max_len, axis=axis,
                tile_w=tile_w, t_tile=t_tile, seq=c, chunk=True,
                num_cores=num_cores, strategy=strategy,
                schedule=self.schedule, paged=True, page=page,
                cost_table=cost_table, kv_quant=self._kv_quant,
                counts_rows=counts_rows)
        if cfg.is_hybrid:
            # Hybrid (qwen_next): GDN layers keep a recurrent-state
            # buffer; prefill runs via prefill_chain (decode-only
            # builder).
            from triton_dist_tpu.models import qwen_next

            specs = qwen_next.param_specs(cfg, axis)
            if params is None:
                params = qwen_next.init_params(jax.random.PRNGKey(seed),
                                               cfg)
        elif cfg.is_moe:
            # MoE megakernel runs the TP expert regime (every expert's
            # ffn dim sharded over tp; routing in-kernel).
            from triton_dist_tpu.models import qwen_moe

            specs = qwen_moe.param_specs(cfg, moe_impl="tp", axis=axis)
            if params is None:
                params = qwen_moe.init_params(jax.random.PRNGKey(seed),
                                              cfg)
        else:
            specs = dense.param_specs(cfg, axis)
            if params is None:
                params = dense.init_params(jax.random.PRNGKey(seed), cfg)
        placed = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs)

        kvspec = P(None, None, None, axis, None)
        tblspec = P(None)
        # Batched prefill shares the decode arena: both builders
        # allocate the (identical) weight region first, so offsets
        # coincide; the activation tail is per-run scratch and the
        # bigger (prefill) footprint sizes the buffer.
        self.prefill_builder = None
        if cfg.is_hybrid and prefill_seq > 1:
            raise ValueError(
                "hybrid (GDN) megakernel is decode-only: batched "
                "prefill (prefill_seq > 1) is unsupported — ingest "
                "prompts with prefill_chain(), or serve prefill via "
                "the layer Engine")
        if prefill_seq > 1:
            self.prefill_builder = ModelBuilder(
                cfg, mesh, batch=batch * prefill_seq, max_len=max_len,
                axis=axis, tile_w=tile_w, t_tile=t_tile,
                seq=prefill_seq, num_cores=num_cores, strategy=strategy,
                schedule=self.schedule, paged=paged, page=page,
                cost_table=cost_table, counts_rows=counts_rows)
            self.prefill_seq = prefill_seq
            pstep = self.prefill_builder.step_fn()
            self._prefill_step = jax.jit(jax.shard_map(
                pstep, mesh=mesh,
                in_specs=(P(axis, None), kvspec, kvspec, P(None),
                          P(None), tblspec),
                out_specs=(P(None, axis), P(axis, None), kvspec,
                           kvspec),
                check_vma=False), donate_argnums=(0, 1, 2))
        # The arena is shared by every builder (identical weight
        # region; activation tails are per-run scratch) — the largest
        # footprint sizes and packs it.
        pack_builder = max(
            [b for b in (self.builder, self.prefill_builder,
                         self.verify_builder,
                         *self.chunk_builders.values())
             if b is not None],
            key=lambda b: b.arena_rows)
        self._arena = jax.jit(jax.shard_map(
            pack_builder.pack_arena, mesh=mesh, in_specs=(specs,),
            out_specs=P(axis, None), check_vma=False))(placed)
        # Re-pin to the verbatim spec spelling the jitted steps PIN
        # their outputs to (_build_step out_shardings): the pack jit's
        # normalized output spelling would otherwise differ from the
        # steady-state one and cost every step function one
        # transitional cache entry on its first dispatch.
        self._arena = jax.device_put(
            self._arena, NamedSharding(mesh, P(axis, None)))
        # After packing, decode no longer reads the params; keeping them
        # doubles weight HBM (useful only for tests/oracles).
        self.params = placed if keep_params else None

        self._build_step()

        n = mesh.shape[axis]
        kv = cfg.num_key_value_heads
        # Hybrid: KV rows exist only for the full-attention layers
        # (ordinal-indexed), plus the GDN recurrent-state buffer.
        self.states = None
        if cfg.is_hybrid:
            from triton_dist_tpu.models.qwen_next import _layer_kinds

            _, n_attn, n_gdn = _layer_kinds(cfg)
            kv_layers = max(n_attn, 1)
            self.states = jax.device_put(
                jnp.zeros((max(n_gdn, 1), batch, cfg.gdn_num_heads,
                           cfg.gdn_head_dim_k, cfg.gdn_head_dim_v),
                          jnp.float32),
                NamedSharding(mesh, P(None, None, axis, None, None)))
        else:
            kv_layers = cfg.num_hidden_layers
        if paged:
            # Page pools + identity block table (a serving layer swaps
            # in its own allocator's table per call).
            p_max = self.builder.p_max
            self.num_pages = num_pages or batch * p_max
            shape = (kv_layers, self.num_pages,
                     self.builder.page, kv, cfg.head_dim)
            self.block_table = jnp.arange(batch * p_max, dtype=jnp.int32)
            if self.num_pages < batch * p_max:
                raise ValueError(
                    f"num_pages {self.num_pages} < batch*p_max "
                    f"{batch * p_max} (identity table needs one page "
                    "per (batch, page index))")
        else:
            self.block_table = jnp.zeros((1,), jnp.int32)
            shape = (kv_layers, batch, max_len, kv,
                     cfg.head_dim)
        # qdtype still holds the ctor-top kv_quant_spec derivation.
        pool_dtype = jnp.float32 if qdtype is None else qdtype
        self.k_cache = jax.device_put(
            jnp.zeros(shape, pool_dtype), NamedSharding(mesh, kvspec))
        self.v_cache = jax.device_put(
            jnp.zeros(shape, pool_dtype), NamedSharding(mesh, kvspec))
        # Per-(layer, page, kv_head) fp32 dequant scales (quantized
        # pools): trailing singleton keeps the in-kernel scalar DMA a
        # 2-D (1, 1) copy. Init 1.0 — a page's first write RESETS it.
        self.k_scale = self.v_scale = None
        self._scale_sharding = None
        if qdtype is not None:
            self._scale_sharding = NamedSharding(
                mesh, P(None, None, axis, None))
            sshape = (kv_layers, self.num_pages, kv, 1)
            self.k_scale = jax.device_put(
                jnp.ones(sshape, jnp.float32), self._scale_sharding)
            self.v_scale = jax.device_put(
                jnp.ones(sshape, jnp.float32), self._scale_sharding)
        # Schema buffer registration: the engine-owned device buffers
        # (pools, scales, GDN state) join the decode builder's
        # described layout, so checkpoint/restore and the chaos
        # arena sweep address EVERYTHING by name.
        sch = self.builder.schema
        pool_dtype_name = np.dtype(pool_dtype).name
        sch.add_buffer("k_cache", shape, pool_dtype_name, kind="kv")
        sch.add_buffer("v_cache", shape, pool_dtype_name, kind="kv")
        if qdtype is not None:
            sch.add_buffer("k_scale", sshape, "float32", kind="scale")
            sch.add_buffer("v_scale", sshape, "float32", kind="scale")
        if self.states is not None:
            sch.add_buffer("gdn_states", self.states.shape, "float32",
                           kind="state")

    def _build_step(self):
        """(Re)jit the decode step from the builder's CURRENT slot
        tables. Called at construction and again by
        :meth:`set_expert_load` after a claim-order refresh — the
        tables are closed over by the step, so new tables need a new
        jit."""
        kvspec = P(None, None, None, self.axis, None)
        tblspec = P(None)
        sclspec = P(None, None, self.axis, None)
        # profile=True appends the slot-recorder output (per-rank rows;
        # rank 0's view is what the host keeps).
        prof_spec = (P(None, None),) if self.profile else ()

        # Output shardings PINNED to the construction placements (the
        # serving ChunkedPrefill out_shardings idiom): a step's pool
        # outputs feed the next step's inputs, and without pinning the
        # first dispatch re-spells the pool shardings (shard_map's
        # normalized output spelling differs from device_put's
        # verbatim one), costing every step function one transitional
        # jit entry. Pinning makes call 0 the fixed point — exactly
        # one entry per step function, which the serving
        # no-recompilation gates and the chunk bucket-count bound
        # (chunk_cache_size <= len(prefill_buckets)) rely on.
        def _sh(spec):
            return NamedSharding(self.mesh, spec)

        logit_sh = _sh(P(None, self.axis))
        prof_sh = (_sh(P(None, None)),) if self.profile else ()

        def _jit_step(builder, profile):
            step = builder.step_fn()
            pspec = prof_spec if profile else ()
            psh = prof_sh if profile else ()
            buf_sh = (_sh(P(self.axis, None)), _sh(kvspec),
                      _sh(kvspec))
            if self.cfg.is_hybrid:
                stspec = P(None, None, self.axis, None, None)
                return jax.jit(jax.shard_map(
                    step, mesh=self.mesh,
                    in_specs=(P(self.axis, None), kvspec, kvspec,
                              P(None), P(None), tblspec, stspec),
                    out_specs=(P(None, self.axis), P(self.axis, None),
                               kvspec, kvspec, stspec) + pspec,
                    check_vma=False), donate_argnums=(0, 1, 2, 6),
                    out_shardings=(logit_sh, *buf_sh, _sh(stspec))
                    + psh)
            if builder.kv_quant:
                return jax.jit(jax.shard_map(
                    lambda a, kc, vc, tok, ln, tb, ks, vs: step(
                        a, kc, vc, tok, ln, tb, k_scale=ks,
                        v_scale=vs),
                    mesh=self.mesh,
                    in_specs=(P(self.axis, None), kvspec, kvspec,
                              P(None), P(None), tblspec, sclspec,
                              sclspec),
                    out_specs=(P(None, self.axis), P(self.axis, None),
                               kvspec, kvspec, sclspec, sclspec)
                    + pspec,
                    check_vma=False), donate_argnums=(0, 1, 2, 6, 7),
                    out_shardings=(logit_sh, *buf_sh, _sh(sclspec),
                                   _sh(sclspec)) + psh)
            return jax.jit(jax.shard_map(
                step, mesh=self.mesh,
                in_specs=(P(self.axis, None), kvspec, kvspec, P(None),
                          P(None), tblspec),
                out_specs=(P(None, self.axis), P(self.axis, None),
                           kvspec, kvspec) + pspec,
                check_vma=False), donate_argnums=(0, 1, 2),
                out_shardings=(logit_sh, *buf_sh) + psh)

        self._step = _jit_step(self.builder, self.profile)
        self._verify_step = (None if self.verify_builder is None
                             else _jit_step(self.verify_builder,
                                            False))
        # One jitted chunk step per bucket — each holds exactly one
        # cache entry after warmup (the chunk shape IS the bucket), so
        # the step-cache total is bounded by the bucket count
        # (chunk_cache_size, gated inline by prefill_chunk).
        self._chunk_steps = {c: _jit_step(b, False)
                             for c, b in self.chunk_builders.items()}

    def expert_counts(self) -> np.ndarray:
        """Cumulative per-expert routed-token counts from the arena's
        in-kernel router counters (MoE builds): the router epilogue
        accumulates its top-k selection mask every layer, every step
        (kernels.moe_weights_body). Returns (num_experts,) int64 —
        monotonic; diff two snapshots for a window. Forces the
        in-flight step to complete (it reads the arena). Every builder
        sharing the arena (decode, Q-block verify, prefill-chunk,
        batched prefill) claims ONE ``moe_counts`` region at the same
        offset/rows, so the counters stay valid — and inclusive of
        routed verify/chunk rows — with chunked prefill active."""
        if not self.cfg.is_moe:
            raise ValueError("expert_counts() needs a MoE megakernel")
        reg = self.builder.schema.region("moe_counts")
        rows = np.asarray(self._arena[reg.offset:reg.offset + reg.rows])
        return rows.sum(axis=0)[:self.cfg.num_experts].round(
        ).astype(np.int64)

    def set_expert_load(self, load) -> None:
        """Hot-expert rebalance hook: recompute the dynamic claim order
        under a fresh per-expert load vector (see
        ``graph.comm_priority`` expert_load) and rebuild the jitted
        step around the new tables. Infrequent by design — the rebuild
        recompiles on the next decode step, so callers (the serving
        layer's ``rebalance_every``) apply hysteresis and only refresh
        when the hot-set ranking actually changed. A spec_k engine
        reprioritizes the verification builder too — under speculation
        its claim order IS the serving dispatch's."""
        self.builder.reprioritize(load)
        if self.verify_builder is not None:
            self.verify_builder.reprioritize(load)
        for b in self.chunk_builders.values():
            b.reprioritize(load)
        self._build_step()

    def progress(self) -> dict:
        """Last-completed progress counters (CommTimeoutError payload):
        completed megakernel launches plus the schedule geometry that
        frames where a wedged launch can be stuck. Dynamic mode reports
        CLAIM-COUNTER geometry (total claims, priority buckets, per-
        bucket claim totals) instead of a static queue shape: the
        in-flight position is a claim-counter value — resolve it with
        :meth:`describe_slot` / ``scheduler.describe_claim``, never as
        a static queue index."""
        out = {
            "steps_done": self.steps_done,
            "schedule": self.schedule,
            "qlen": self.builder.qlen,
            "num_cores": self.builder.num_cores,
            "n_edges": self.builder.n_edges,
        }
        if self.schedule == "dynamic":
            out["n_claims"] = self.builder.n_claims
            out["n_buckets"] = self.builder.n_buckets
            out["bucket_claims"] = [
                int(v) for v in self.builder.bucket_claims]
            out["progress_counter"] = "claim"
        else:
            out["progress_counter"] = "static_slot"
        return out

    def describe_slot(self, q: int, c: int = 0) -> dict:
        """Attribute a progress-counter position to the task occupying
        it: static mode maps a (queue position, core) pair through the
        packed queue; dynamic mode treats ``q * num_cores + c`` as the
        CLAIM-COUNTER value and names the claimed task, its priority
        bucket, and the edge semaphores it waits on — what a watchdog
        needs to attribute a wedged schedule."""
        from triton_dist_tpu.megakernel.scheduler import describe_slot

        return describe_slot(self.builder.sched, q, c)

    def _finish(self, out, op: str):
        """Bound the step's completion when a watchdog is armed; count
        completed steps either way (the counter advances only after the
        dispatch is known-good, so a raise cannot desync it)."""
        if self.timeout_s is not None:
            from triton_dist_tpu.resilience.watchdog import (
                block_until_ready)

            out = block_until_ready(out, timeout_s=self.timeout_s,
                                    op=op, progress_fn=self.progress)
        self.steps_done += 1
        return out

    def reset_states(self):
        """Zero the GDN recurrent states (hybrid family) — REQUIRED
        between independent prompts on a reused engine: unlike stale KV
        rows (masked beyond cache_len), the recurrent state has no
        position mask, so a previous prompt's S would contaminate the
        next. No-op for dense/MoE engines."""
        if self.states is not None:
            self.states = jax.tree.map(jnp.zeros_like, self.states)

    def reset_slot(self, slot: int):
        """Zero ONE batch slot's recurrent state (hybrid family) — the
        per-slot form of :meth:`reset_states` a continuous-batching
        scheduler calls when recycling a slot for a new request (stale
        KV needs no reset: the slot's fresh positions overwrite it and
        its per-slot length masks the tail). No-op for dense/MoE."""
        if self.states is not None:
            self.states = self.states.at[:, slot].set(0.0)

    # -- schema-driven checkpoint/restore -----------------------------

    def snapshot_state(self) -> dict:
        """Host snapshot of the SERVING-relevant arena regions, by
        schema name: the KV pools (stored bytes — bit-exact at any
        ``kv_dtype``), their scale tables, the hybrid GDN state, and
        the in-arena counter regions (per-rank rows). Weights are NOT
        snapshot (repacked from params on a fresh engine, the layer
        path's contract) and activations are per-step scratch.
        Forces in-flight work to complete (it reads device state)."""
        out = {"k_cache": np.asarray(self.k_cache),
               "v_cache": np.asarray(self.v_cache),
               "k_scale": (None if self.k_scale is None
                           else np.asarray(self.k_scale)),
               "v_scale": (None if self.v_scale is None
                           else np.asarray(self.v_scale)),
               "states": (None if self.states is None
                          else np.asarray(self.states)),
               "counters": {}}
        cb = self.verify_builder if self.spec_k else self.builder
        n = self.mesh.shape[self.axis]
        arena = np.asarray(self._arena)
        rows_per = arena.shape[0] // n
        for reg in cb.schema.regions(kind="counter"):
            out["counters"][reg.name] = arena.reshape(
                n, rows_per, -1)[:, reg.offset:reg.offset + reg.rows
                                 ].copy()
        return out

    def restore_state(self, snap: dict) -> None:
        """Adopt a :meth:`snapshot_state` snapshot into this (freshly
        built, identically-configured) engine: pools + scales + GDN
        state re-pinned to their construction shardings (the jitted
        steps never re-specialize), counter regions blitted back into
        every rank's arena shard. Decode then resumes bit-exact."""
        kvspec = P(None, None, None, self.axis, None)
        kv_sh = NamedSharding(self.mesh, kvspec)
        if snap["k_cache"].dtype != np.asarray(self.k_cache).dtype:
            raise ValueError(
                f"pool dtype mismatch: snapshot {snap['k_cache'].dtype}"
                f" vs engine {np.asarray(self.k_cache).dtype} "
                "(kv_dtype must match)")
        self.k_cache = jax.device_put(jnp.asarray(snap["k_cache"]),
                                      kv_sh)
        self.v_cache = jax.device_put(jnp.asarray(snap["v_cache"]),
                                      kv_sh)
        if (snap.get("k_scale") is None) != (self.k_scale is None):
            raise ValueError("scale-table mismatch: snapshot and "
                             "engine disagree on quantization")
        if snap.get("k_scale") is not None:
            self.k_scale = jax.device_put(
                jnp.asarray(snap["k_scale"]), self._scale_sharding)
            self.v_scale = jax.device_put(
                jnp.asarray(snap["v_scale"]), self._scale_sharding)
        if (snap.get("states") is None) != (self.states is None):
            raise ValueError("GDN-state mismatch: snapshot and engine "
                             "disagree on the hybrid family")
        if snap.get("states") is not None:
            self.states = jax.device_put(
                jnp.asarray(snap["states"]),
                NamedSharding(self.mesh,
                              P(None, None, self.axis, None, None)))
        counters = snap.get("counters") or {}
        if counters:
            cb = self.verify_builder if self.spec_k else self.builder
            n = self.mesh.shape[self.axis]
            # np.array (not asarray): jax arrays expose a READ-ONLY
            # buffer — the counter blit below needs a writable copy.
            arena = np.array(self._arena)
            rows_per = arena.shape[0] // n
            view = arena.reshape(n, rows_per, -1)
            for name, rows in counters.items():
                reg = cb.schema.region(name)
                view[:, reg.offset:reg.offset + reg.rows] = rows
            self._arena = jax.device_put(
                jnp.asarray(arena),
                NamedSharding(self.mesh, P(self.axis, None)))

    def decode_step(self, token_ids, cache_len) -> jax.Array:
        """token_ids: (B,) → logits (B, vocab). Embedding, the whole
        transformer stack, and the LM head all run inside the
        megakernel; the vocab-sharded logits are stitched by the
        out_specs.

        ``cache_len``: a scalar (uniform batch — the classic form) OR a
        (B,) vector of PER-SLOT positions, the live-slot serving form:
        each batch row appends and attends at its own length, so a
        continuous-batching scheduler can drive slots of different ages
        through ONE persistent kernel (parked slots simply keep a stale
        position the host ignores)."""
        lens = jnp.broadcast_to(
            jnp.asarray(cache_len, jnp.int32).reshape(-1),
            (self.batch,))
        if self.states is not None:
            outs = self._step(
                self._arena, self.k_cache, self.v_cache,
                jnp.asarray(token_ids, jnp.int32), lens,
                self.block_table, self.states)
            if self.profile:
                self.last_prof = outs[-1]
                outs = outs[:-1]
            (logits, self._arena, self.k_cache, self.v_cache,
             self.states) = outs
        elif self.k_scale is not None:
            outs = self._step(
                self._arena, self.k_cache, self.v_cache,
                jnp.asarray(token_ids, jnp.int32), lens,
                self.block_table, self.k_scale, self.v_scale)
            if self.profile:
                self.last_prof = outs[-1]
                outs = outs[:-1]
            (logits, self._arena, self.k_cache, self.v_cache,
             self.k_scale, self.v_scale) = outs
        else:
            outs = self._step(
                self._arena, self.k_cache, self.v_cache,
                jnp.asarray(token_ids, jnp.int32), lens,
                self.block_table)
            if self.profile:
                self.last_prof = outs[-1]
                outs = outs[:-1]
            logits, self._arena, self.k_cache, self.v_cache = outs
        return self._finish(logits, "megakernel.decode_step")

    def verify_step(self, token_rows, positions) -> jax.Array:
        """ONE Q-block verification launch (``spec_k`` builds):
        ``token_rows`` (B, K) or (B·K,) drafted candidates slot-major,
        ``positions`` (B·K,) each row's cache position (−1 masks a row
        — over-budget candidates and parked slots write nothing and
        their logits are garbage the host discards). Writes each valid
        row's K/V at its own position, attends under the per-query
        causal mask, and returns logits (B, K, vocab) — row j's logits
        are bit-identical to what :meth:`decode_step` would have
        produced at that position, which is what makes greedy
        acceptance token-exact by construction."""
        if self._verify_step is None:
            raise ValueError("engine built without spec_k: the Q-block "
                             "verification step was never compiled")
        kq = self.spec_k
        toks = jnp.asarray(token_rows, jnp.int32).reshape(-1)
        pos = jnp.asarray(positions, jnp.int32).reshape(-1)
        if self.k_scale is not None:
            outs = self._verify_step(
                self._arena, self.k_cache, self.v_cache, toks, pos,
                self.block_table, self.k_scale, self.v_scale)
            (logits, self._arena, self.k_cache, self.v_cache,
             self.k_scale, self.v_scale) = outs
        else:
            outs = self._verify_step(
                self._arena, self.k_cache, self.v_cache, toks, pos,
                self.block_table)
            logits, self._arena, self.k_cache, self.v_cache = outs
        logits = self._finish(logits, "megakernel.verify_step")
        return logits.reshape(self.batch, kq, -1)

    def prefill_chunk(self, token_row, codes, table_row) -> jax.Array:
        """ONE prefill-chunk launch (``prefill_buckets`` builds):
        ``token_row`` (C,) int32 chunk tokens padded to a bucket
        length; ``codes`` (C,) sign-encoded per-row positions
        (:func:`~triton_dist_tpu.ops.chunked_prefill.chunk_row_codes`
        — ``>= 0`` write+attend there, ``<= -2`` attend-only at
        ``-code-2`` (prefix-resident positions, never re-blitted),
        ``-1`` dead padding); ``table_row`` (p_max,) int32 — the
        slot's block-table row. Writes each writable row's K/V (fused
        quantize on int8/fp8 pools), attends under the per-query
        causal mask, and returns logits (C, vocab) — row r's logits
        are bit-identical to what the one-token prefill lane
        (:meth:`decode_step`) would have produced at that position.
        Scalars ride as DATA, so the jit cache is keyed only on the
        bucket length — the inline gate below raises if it ever grows
        past the bucket count (the megakernel half of the serving
        no-recompilation contract)."""
        toks = jnp.asarray(token_row, jnp.int32).reshape(-1)
        c = int(toks.shape[0])
        step = self._chunk_steps.get(c)
        if step is None:
            raise ValueError(
                f"no chunk step for bucket {c}: engine built with "
                f"prefill_buckets={self.prefill_buckets} — pad chunks "
                "to a configured bucket (ops.chunked_prefill."
                "plan_chunks)")
        enc = jnp.asarray(codes, jnp.int32).reshape(-1)
        tbl = jnp.asarray(table_row, jnp.int32).reshape(-1)
        if self.k_scale is not None:
            outs = step(self._arena, self.k_cache, self.v_cache, toks,
                        enc, tbl, self.k_scale, self.v_scale)
            (logits, self._arena, self.k_cache, self.v_cache,
             self.k_scale, self.v_scale) = outs
        else:
            outs = step(self._arena, self.k_cache, self.v_cache, toks,
                        enc, tbl)
            logits, self._arena, self.k_cache, self.v_cache = outs
        logits = self._finish(logits, "megakernel.prefill_chunk")
        n = self.chunk_cache_size()
        if n > len(self.prefill_buckets):
            raise RuntimeError(
                f"megakernel chunk-step jit cache grew to {n} entries "
                f"> {len(self.prefill_buckets)} buckets "
                f"{self.prefill_buckets} — a chunk dispatch "
                "re-specialized on something other than the bucket "
                "length")
        return logits

    def chunk_cache_size(self) -> int:
        """Total jit-cache entries across the per-bucket chunk steps
        (≤ bucket count) — the megakernel half of the serving
        no-recompilation gate."""
        return sum(fn._cache_size()
                   for fn in self._chunk_steps.values())

    def prefill_chain(self, prompt_ids):
        """Feed a (B, S) prompt token-by-token (fallback when no
        batched prefill builder was requested). Returns the last token
        to seed :meth:`generate` with ``start_pos=S-1``."""
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        for pos in range(prompt_ids.shape[1] - 1):
            self.decode_step(prompt_ids[:, pos], pos)
        return prompt_ids[:, -1]

    def prefill(self, prompt_ids, *, start_pos: int = 0):
        """Batched prefill: the whole (B, S) prompt runs as ONE
        megakernel launch (rows = (b, s) pairs; causal prefill
        attention + block cache writes). Returns the last position's
        logits (B, vocab); the cache then holds start_pos + S tokens.
        Requires ``prefill_seq=S`` at construction."""
        if self.prefill_builder is None:
            raise ValueError("engine built without prefill_seq")
        if self.paged and int(start_pos) % self.prefill_seq:
            # _kv_slice takes one slice per (batch, head) span; a base
            # that is not seq-aligned could cross a page silently.
            raise ValueError(
                f"paged prefill needs start_pos % prefill_seq == 0 "
                f"(got {start_pos} % {self.prefill_seq})")
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        bsz, s = prompt_ids.shape
        if s != self.prefill_seq or bsz != self.batch:
            raise ValueError(f"prompt {prompt_ids.shape} != "
                             f"({self.batch}, {self.prefill_seq})")
        logits, self._arena, self.k_cache, self.v_cache = (
            self._prefill_step(self._arena, self.k_cache, self.v_cache,
                               prompt_ids.reshape(-1),
                               jnp.full((bsz * s,), start_pos,
                                        jnp.int32),
                               self.block_table))
        logits = self._finish(logits, "megakernel.prefill")
        return logits.reshape(bsz, s, -1)[:, -1]

    def generate(self, first_tokens, steps: int, *, start_pos: int = 0):
        """Greedy chain from (B,) seed tokens at cache position
        ``start_pos``; returns (B, steps)."""
        tok = jnp.asarray(first_tokens, jnp.int32)
        out = []
        for i in range(steps):
            logits = self.decode_step(tok, start_pos + i)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out.append(tok)
        return jnp.stack(out, axis=1)


# ---------------------------------------------------------------------------
# static-vs-dynamic schedule autotune (persisted in the tune cache)
# ---------------------------------------------------------------------------

def _schedule_key(cfg, mesh, *, batch: int, num_cores: int, axis: str):
    from triton_dist_tpu import tune
    from triton_dist_tpu.parallel.mesh import MeshContext

    return tune.make_key(
        "megakernel_schedule", model=tune.model_key(cfg),
        mesh=tune.mesh_key(MeshContext.from_mesh(mesh)), batch=batch,
        cores=num_cores, axis=axis)


def lookup_schedule(cfg, mesh, *, batch: int, num_cores: int = 1,
                    axis: str = "tp") -> str:
    """Resolve ``schedule="auto"``: the persisted :func:`tune_schedule`
    winner for this (model, mesh, batch, cores) key, or ``"static"``
    when never tuned."""
    from triton_dist_tpu import tune

    cached = tune.load_autotune_data(
        _schedule_key(cfg, mesh, batch=batch, num_cores=num_cores,
                      axis=axis))
    if cached and cached.get("schedule") in ("static", "dynamic"):
        return cached["schedule"]
    return "static"


def tune_schedule(cfg, mesh, *, batch: int, max_len: int = 512,
                  axis: str = "tp", num_cores: int = 1, reps: int = 3,
                  params=None, seed: int = 0, use_cache: bool = True,
                  **builder_kw) -> str:
    """OFFLINE static-vs-dynamic sweep (the ``tune_spmd`` pattern):
    build one engine per schedule mode, run a warmup ``decode_step``
    (compile + profile-feedback primer), time ``reps`` steps each, and
    persist the winner under the (model, mesh, batch, cores) key so
    ``MegaKernelEngine(schedule="auto")`` picks it up. Returns the
    winning mode. Timing on the interpret backend tracks scheduler/
    interpreter overhead rather than silicon — meaningful relatively
    (same task bodies both modes), and re-keyed per backend by the tune
    cache's dependency stamp."""
    import time as _time

    from triton_dist_tpu import tune

    key = _schedule_key(cfg, mesh, batch=batch, num_cores=num_cores,
                        axis=axis)
    if use_cache:
        cached = tune.load_autotune_data(key)
        if cached and cached.get("schedule") in ("static", "dynamic"):
            return cached["schedule"]
    times = {}
    toks = jnp.zeros((batch,), jnp.int32)
    for mode in ("static", "dynamic"):
        eng = MegaKernelEngine(cfg, mesh, batch=batch, max_len=max_len,
                               axis=axis, num_cores=num_cores,
                               schedule=mode, params=params, seed=seed,
                               **builder_kw)
        np.asarray(eng.decode_step(toks, 0))        # compile + warmup
        best = float("inf")
        for i in range(reps):
            t0 = _time.perf_counter()
            np.asarray(eng.decode_step(toks, 1 + i))
            best = min(best, _time.perf_counter() - t0)
        times[mode] = best
    winner = min(times, key=times.get)
    tune.store_autotune_data(
        key, {"schedule": winner,
              "times_ms": {m: round(t * 1e3, 3)
                           for m, t in times.items()}},
        times[winner])
    return winner
