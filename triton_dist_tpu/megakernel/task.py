"""Task descriptors (reference: ``mega_triton_kernel/core/task_base.py``
``TaskBase`` :162 + ``TaskDependency`` :113 + tile descriptors
:137-161)."""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Tuple

ARGS_MAX = 8


class TaskType(enum.IntEnum):
    """Per-op device code selector (reference: the op→task registry,
    ``core/registry.py:30``; kernels in ``mega_triton_kernel/kernels/``)."""
    RMSNORM = 0        # args: in_off, w_off, out_off, rows, dim
    LINEAR = 1         # args: in_off, w_off, out_off, rows, k, n, accum
    ADD = 2            # args: a_off, b_off, out_off, rows, dim
    SILU_MUL = 3       # args: gate_off, up_off, out_off, rows, dim
    ATTN_DECODE = 4    # args: q_off, out_off, layer, h_loc, kv_loc, hd
    WRITE_KV = 5       # args: k_off, v_off, layer, kv_loc, hd
    ALLREDUCE = 6      # args: buf_off, rows, dim
    GATHER = 7         # args: table_off, out_off, d_tiles (ids via prefetch)
    NOOP = 8           # queue padding slot (multi-core schedules)
    WRITE_KV_PREFILL = 9   # args like WRITE_KV; rows are (b, s) pairs
    ATTN_PREFILL = 10      # args like ATTN_DECODE; causal over new rows
    MOE_WEIGHTS = 11       # args: rl_off, wout_off, n_experts, cnt_off
    WEIGHTED_ADD = 12      # args: acc_off, part_off, wbe_off, e, tiles, init
    GDN_DECODE = 13        # args: q,k,v,graw,braw,gbias,out offs, gdn_idx
    # Q-block speculative-verification pair (builder ``qblock=True``):
    # batch rows are (slot, j) pairs, each row at its OWN cache
    # position len_s[row] (< 0 masks the row) — the
    # ops/paged_flash_qblock per-query causal mask as megakernel tasks.
    ATTN_QBLOCK = 14       # args like ATTN_DECODE; per-row positions
    WRITE_KV_QBLOCK = 15   # args like WRITE_KV; per-row positions
    # Prefill-chunk pair (builder ``chunk=True``): batch rows are one
    # C-token prompt chunk for one slot, per-row global positions
    # SIGN-ENCODED in the cache_len vector (kernels._chunk_apos:
    # >= 0 write+attend, <= -2 attend-only resident prefix, -1 dead
    # padding) — the ops/chunked_prefill bucket contract as megakernel
    # tasks.
    ATTN_CHUNK = 16        # args like ATTN_QBLOCK; encoded positions
    WRITE_KV_CHUNK = 17    # args like WRITE_KV_QBLOCK; encoded positions


# Task types whose completion unblocks REMOTE peers: every other rank's
# matching collective blocks until this rank's contribution lands, so
# finishing one of these (or the work feeding it) releases n-1 chips,
# not one core. The dynamic scheduler's comm-aware priority
# (graph.comm_priority) is built on this set.
COLLECTIVE_TYPES = frozenset({TaskType.ALLREDUCE})


@dataclasses.dataclass
class Task:
    task_id: int
    task_type: TaskType
    args: Tuple[int, ...]
    deps: List[int] = dataclasses.field(default_factory=list)
    layer: int = -1
    # MoE provenance: which expert's FFN chain this task belongs to
    # (-1 = not expert work). Feeds the dynamic scheduler's expert-load
    # claim priority (graph.comm_priority expert_load).
    expert: int = -1

    @property
    def unblocks_remote(self) -> bool:
        """True for tasks remote peers wait on (collectives)."""
        return self.task_type in COLLECTIVE_TYPES

    def encoded_args(self) -> List[int]:
        a = list(self.args)[:ARGS_MAX]
        return a + [0] * (ARGS_MAX - len(a))
