"""Export profiler slot buffers to a Perfetto-loadable chrome trace.

Reference: ``tools/profiler/viewer.py:115`` ``export_to_perfetto_trace``
(track reconstruction :54-113). Slots carry (tag, value) in program
order; without an in-kernel clock the exporter synthesizes unit-spaced
instant events per device track — enough to inspect schedules and
progress interleaving (real timing lives in the xprof capture).

:func:`export_merged_trace` is the serving-telemetry superset: host
request spans (:mod:`triton_dist_tpu.obs`), megakernel slot records,
and xprof-extracted device spans merge into ONE trace file — one
Perfetto process per component, correlated by request id and step
index carried in every event's ``args``.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np

# Merged-trace process ids: one Perfetto "process" per component.
HOST_PID = 1        # host serving spans (engine clock)
MEGAKERNEL_PID = 2  # in-kernel slot records (program order / cost model)
XPROF_PID = 3       # device spans extracted from the xprof capture


def _slot_events(buffers, tag_names, durs, *, pid: int,
                 device_names=None, tid_base: int = 0,
                 t_off: float = 0.0, step: Optional[int] = None,
                 timing: str = "reconstructed"):
    """Shared track reconstruction for one (n_devices, capacity, 2)
    slot buffer: unit-spaced instants (program order), or spans at the
    cost model's cumulative times when ``durs`` is given."""
    events = []
    for dev, buf in enumerate(buffers):
        name = (device_names[dev] if device_names else f"device{dev}")
        t_cum = 0.0
        for t, (tag, value) in enumerate(buf):
            if tag == 0 and value == 0 and t > 0:
                continue  # unused slot
            args = {"value": int(value), "device": name,
                    "timing": timing}
            if step is not None:
                args["step"] = int(step)
            ev = {
                "name": tag_names.get(int(tag), f"tag{int(tag)}"),
                "pid": pid,
                "tid": tid_base + dev,
                "args": args,
            }
            if durs is not None:
                d_us = float(durs[dev, t]) * 1e6
                ev.update({"ph": "X", "ts": t_off + t_cum, "dur": d_us})
                t_cum += d_us
            else:
                ev.update({"ph": "i", "ts": t_off + t, "s": "t"})
            events.append(ev)
    return events


def export_to_perfetto_trace(slot_buffers, path: str,
                             tag_names: Optional[Dict[int, str]] = None,
                             device_names: Optional[Sequence[str]] = None,
                             slot_durations=None) -> str:
    """slot_buffers: (n_devices, capacity, 2) int32 array (or a list of
    per-device (capacity, 2) arrays). Writes chrome-trace JSON.

    TIMING HONESTY: every event is labeled with how its time was
    obtained. Without ``slot_durations`` (default) events are
    unit-spaced instants in PROGRAM ORDER — ``timing:
    "reconstructed"``, no duration claim (wall time lives in xprof).
    With ``slot_durations`` ((n_devices, capacity) seconds per slot —
    e.g. ``ModelBuilder.slot_durations`` fed by a MEASURED
    ``calibrate_cost_table``) events become spans at the model's
    cumulative times — ``timing: "calibrated"``, good to the cost
    model's least-squares fit, not a per-span measurement.
    """
    buffers = np.asarray(slot_buffers)
    if buffers.ndim == 2:
        buffers = buffers[None]
    durs = None
    if slot_durations is not None:
        durs = np.asarray(slot_durations, np.float64)
        if durs.ndim == 1:
            durs = durs[None]
    tag_names = tag_names or {}
    timing = "calibrated" if durs is not None else "reconstructed"
    events = [{
        "name": f"timing_model: {timing}",
        "ph": "M", "pid": 0, "tid": 0,
        "args": {"timing": timing},
    }]
    events += _slot_events(buffers, tag_names, durs, pid=0,
                           device_names=device_names, timing=timing)
    trace = {"traceEvents": events,
             "displayTimeUnit": "ns"}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def _meta(pid: int, name: str, threads: Dict[int, str]):
    evs = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}]
    for tid, tname in sorted(threads.items()):
        evs.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname}})
    return evs


def _host_events(host_spans):
    """Host spans/events (``obs.Span`` objects or their dicts) → one
    Perfetto process: tid = slot + 1 for slot-correlated entries,
    tid 0 ("engine") otherwise; times are µs relative to the first
    span's clock stamp."""
    from triton_dist_tpu.obs.spans import Span

    spans = [s if isinstance(s, Span) else Span.from_dict(s)
             for s in host_spans]
    if not spans:
        return [], {}
    base = min(s.t0 for s in spans)
    threads = {0: "engine"}
    events = []
    for s in spans:
        tid = 0 if s.slot is None else s.slot + 1
        if s.slot is not None:
            threads.setdefault(tid, f"slot{s.slot}")
        args = {"kind": s.kind, "timing": "host_clock"}
        for k in ("request_id", "slot", "step", "tenant"):
            v = getattr(s, k)
            if v is not None:
                args[k] = v
        args.update(s.attrs)
        ev = {"name": s.kind, "pid": HOST_PID, "tid": tid,
              "ts": (s.t0 - base) * 1e6, "args": args}
        if s.instant:
            ev.update({"ph": "i", "s": "t"})
        else:
            ev.update({"ph": "X",
                       "dur": max((s.t1 - s.t0) * 1e6, 1e-3)})
        events.append(ev)
    return events, threads


def export_merged_trace(path: str, *, host_spans=(),
                        slot_records=(),
                        tag_names: Optional[Dict[int, str]] = None,
                        slot_durations=None,
                        xprof_events=(),
                        xprof_reason: Optional[str] = None,
                        metadata: Optional[dict] = None) -> str:
    """Write ONE chrome-trace JSON merging every telemetry tier.

    - ``host_spans``: :class:`~triton_dist_tpu.obs.spans.Span` records
      (or their dicts) — pid 1, one thread per serving slot plus the
      engine thread; timestamps on the engine clock.
    - ``slot_records``: megakernel slot buffers — either one
      (n_cores, capacity, 2) array or a sequence of ``(step_index,
      buffers)`` pairs (one decode step each) — pid 2, one thread per
      core; program-order instants (or cost-model spans when
      ``slot_durations`` is given), each step offset on the synthetic
      axis and stamped with its ``step`` for correlation against the
      host decode spans.
    - ``xprof_events``: device spans from
      :func:`~triton_dist_tpu.obs.xprof.extract_xprof_spans` — pid 3,
      original thread ids, the capture's own µs clock. When absent the
      skip reason rides in the trace metadata (``xprof_reason``) so a
      merged file is honest about the missing tier.

    The three clock domains are NOT aligned (no shared epoch exists
    across host monotonic / program order / xprof); correlation is by
    the ``request_id`` / ``step`` keys in ``args``, which is what the
    serving debug loop joins on.
    """
    events = []
    host_evs, host_threads = _host_events(host_spans)
    events += _meta(HOST_PID, "host:serving", host_threads)
    events += host_evs

    tag_names = tag_names or {}
    recs = slot_records
    if recs is not None and not isinstance(recs, (list, tuple)):
        recs = [(0, recs)]
    mk_threads = {}
    if recs:
        durs = None
        if slot_durations is not None:
            durs = np.asarray(slot_durations, np.float64)
            if durs.ndim == 1:
                durs = durs[None]
        t_off = 0.0
        for step_idx, buffers in recs:
            buffers = np.asarray(buffers)
            if buffers.ndim == 2:
                buffers = buffers[None]
            for c in range(buffers.shape[0]):
                mk_threads.setdefault(c, f"core{c}")
            events += _slot_events(
                buffers, tag_names, durs, pid=MEGAKERNEL_PID,
                t_off=t_off, step=step_idx,
                timing=("calibrated" if durs is not None
                        else "reconstructed"))
            # Steps share the core tracks; each gets its own stretch of
            # the synthetic axis (no in-kernel clock to place it by).
            t_off += (float(durs.sum() * 1e6) if durs is not None
                      else buffers.shape[1] + 8)
        events += _meta(MEGAKERNEL_PID, "megakernel", mk_threads)

    if xprof_events:
        base = min(float(e.get("ts", 0.0)) for e in xprof_events)
        xp_threads = {}
        for e in xprof_events:
            tid = int(e.get("tid", 0)) % (1 << 20)
            name = (e.get("args", {}) or {}).get("xprof_thread")
            if name:
                xp_threads.setdefault(tid, name)
            ev = dict(e, pid=XPROF_PID, tid=tid,
                      ts=float(e.get("ts", 0.0)) - base)
            ev.setdefault("args", {})
            ev["args"] = dict(ev["args"], timing="xprof")
            events.append(ev)
        events += _meta(XPROF_PID, "device:xprof", xp_threads)

    meta = {"clock_domains": {
        "host:serving": "engine clock (injectable monotonic)",
        "megakernel": "program order / calibrated cost model",
        "device:xprof": "xprof capture clock",
    }}
    if xprof_reason:
        meta["xprof_reason"] = xprof_reason
    if metadata:
        meta.update(metadata)
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "metadata": meta}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
