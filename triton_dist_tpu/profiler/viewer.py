"""Export profiler slot buffers to a Perfetto-loadable chrome trace.

Reference: ``tools/profiler/viewer.py:115`` ``export_to_perfetto_trace``
(track reconstruction :54-113). Slots carry (tag, value) in program
order; without an in-kernel clock the exporter synthesizes unit-spaced
instant events per device track — enough to inspect schedules and
progress interleaving (real timing lives in the xprof capture).
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np


def export_to_perfetto_trace(slot_buffers, path: str,
                             tag_names: Optional[Dict[int, str]] = None,
                             device_names: Optional[Sequence[str]] = None
                             ) -> str:
    """slot_buffers: (n_devices, capacity, 2) int32 array (or a list of
    per-device (capacity, 2) arrays). Writes chrome-trace JSON."""
    buffers = np.asarray(slot_buffers)
    if buffers.ndim == 2:
        buffers = buffers[None]
    tag_names = tag_names or {}
    events = []
    for dev, buf in enumerate(buffers):
        name = (device_names[dev] if device_names else f"device{dev}")
        for t, (tag, value) in enumerate(buf):
            if tag == 0 and value == 0 and t > 0:
                continue  # unused slot
            events.append({
                "name": tag_names.get(int(tag), f"tag{int(tag)}"),
                "ph": "i",  # instant event
                "ts": t,     # program order (unitless)
                "pid": 0,
                "tid": dev,
                "s": "t",
                "args": {"value": int(value), "device": name},
            })
    trace = {"traceEvents": events,
             "displayTimeUnit": "ns"}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
