"""Export profiler slot buffers to a Perfetto-loadable chrome trace.

Reference: ``tools/profiler/viewer.py:115`` ``export_to_perfetto_trace``
(track reconstruction :54-113). Slots carry (tag, value) in program
order; without an in-kernel clock the exporter synthesizes unit-spaced
instant events per device track — enough to inspect schedules and
progress interleaving (real timing lives in the xprof capture).
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np


def export_to_perfetto_trace(slot_buffers, path: str,
                             tag_names: Optional[Dict[int, str]] = None,
                             device_names: Optional[Sequence[str]] = None,
                             slot_durations=None) -> str:
    """slot_buffers: (n_devices, capacity, 2) int32 array (or a list of
    per-device (capacity, 2) arrays). Writes chrome-trace JSON.

    TIMING HONESTY: every event is labeled with how its time was
    obtained. Without ``slot_durations`` (default) events are
    unit-spaced instants in PROGRAM ORDER — ``timing:
    "reconstructed"``, no duration claim (wall time lives in xprof).
    With ``slot_durations`` ((n_devices, capacity) seconds per slot —
    e.g. ``ModelBuilder.slot_durations`` fed by a MEASURED
    ``calibrate_cost_table``) events become spans at the model's
    cumulative times — ``timing: "calibrated"``, good to the cost
    model's least-squares fit, not a per-span measurement.
    """
    buffers = np.asarray(slot_buffers)
    if buffers.ndim == 2:
        buffers = buffers[None]
    durs = None
    if slot_durations is not None:
        durs = np.asarray(slot_durations, np.float64)
        if durs.ndim == 1:
            durs = durs[None]
    tag_names = tag_names or {}
    timing = "calibrated" if durs is not None else "reconstructed"
    events = [{
        "name": f"timing_model: {timing}",
        "ph": "M", "pid": 0, "tid": 0,
        "args": {"timing": timing},
    }]
    for dev, buf in enumerate(buffers):
        name = (device_names[dev] if device_names else f"device{dev}")
        t_cum = 0.0
        for t, (tag, value) in enumerate(buf):
            if tag == 0 and value == 0 and t > 0:
                continue  # unused slot
            ev = {
                "name": tag_names.get(int(tag), f"tag{int(tag)}"),
                "pid": 0,
                "tid": dev,
                "args": {"value": int(value), "device": name,
                         "timing": timing},
            }
            if durs is not None:
                d_us = float(durs[dev, t]) * 1e6
                ev.update({"ph": "X", "ts": t_cum, "dur": d_us})
                t_cum += d_us
            else:
                ev.update({"ph": "i", "ts": t, "s": "t"})
            events.append(ev)
    trace = {"traceEvents": events,
             "displayTimeUnit": "ns"}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
