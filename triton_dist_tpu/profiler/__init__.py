from triton_dist_tpu.profiler.language import (  # noqa: F401
    Profiler, record, trace_scalar,
)
from triton_dist_tpu.profiler.viewer import (  # noqa: F401
    export_to_perfetto_trace,
)
from triton_dist_tpu.profiler_utils import group_profile, perf_func  # noqa: F401
