"""Intra-kernel profiler: device-side slot recorder.

Reference: ``python/triton_dist/tools/profiler/language.py:38`` device
``Profiler`` struct recording ``(tag, timestamp)`` slots (``record``
:145, ``%globaltimer``-based) into a preallocated buffer
(``context.py:50-76``) with Perfetto export (``viewer.py:115``).

TPU differences: Mosaic exposes no in-kernel clock, so slots record
``(tag, value)`` pairs (progress counters, semaphore reads, tile ids)
in *program order*; true wall-time per region comes from the XLA/xprof
trace (``profiler_utils.group_profile``), into which
:func:`trace_scalar` (``pltpu.trace_value``) injects the same markers.
The combination covers the reference's use cases: megakernel
SM-activity metrics and per-tile progress inspection.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu


@dataclasses.dataclass(frozen=True)
class Profiler:
    """Handle over a profiler slot buffer.

    The host allocates an int32 output/scratch of shape (capacity, 2)
    plus a (1,) SMEM cursor; kernels call :func:`record` with it.
    """
    capacity: int = 256

    def scratch_shapes(self):
        return [pltpu.VMEM((self.capacity, 2), jnp.int32),
                pltpu.SMEM((1,), jnp.int32)]

    def out_shape(self):
        import jax
        return jax.ShapeDtypeStruct((self.capacity, 2), jnp.int32)


def record(buf_ref, cursor_ref, tag: int, value):
    """Append (tag, value) to the profiler buffer (drops on overflow).

    Reference ``Profiler.record`` (``tools/profiler/language.py:145``);
    tags are small ints mapped to names at export time.
    """
    import jax
    from jax.experimental import pallas as pl

    idx = cursor_ref[0]

    @pl.when(idx < buf_ref.shape[0])
    def _():
        row = jnp.stack([jnp.asarray(tag, jnp.int32),
                         jnp.asarray(value, jnp.int32)]).reshape(1, 2)
        buf_ref[pl.ds(idx, 1), :] = row

    cursor_ref[0] = idx + 1


def trace_scalar(label: str, value):
    """Emit a scalar into the xprof/Perfetto trace from inside a kernel
    (no-op outside a profiling capture)."""
    pltpu.trace_value(label, jnp.asarray(value, jnp.int32))
