"""Offline tuning CLI — the reference's ``tools/tune/tune_gemm.py``
analogue: sweep the fused-GEMM config spaces on the ATTACHED backend
and persist winners into the tune cache, so serving jobs hit tuned
configs on first use.

Timing cannot happen inside a jit/shard_map trace (a tracer has no
wall clock), so this CLI drives :func:`triton_dist_tpu.autotuner.
tune_spmd`: one jitted SPMD step per candidate config, compiled and
timed eagerly, winner persisted under the same cache key the op's
``*_tuned`` wrapper reads in-trace.

Run (real chip):  TDT_REAL_TPU=1 python -m triton_dist_tpu.tools.tune_cli \
    --op ag_gemm --m 2048 --k 4096 --n 4096
"""

import argparse
import os


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--op", default="ag_gemm",
                    choices=["ag_gemm", "gemm_rs", "gemm_ar"])
    ap.add_argument("--m", type=int, default=2048)
    ap.add_argument("--k", type=int, default=4096)
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--tp", type=int, default=None,
                    help="mesh size (default: all attached devices)")
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    import jax
    if os.environ.get("TDT_REAL_TPU") != "1":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import triton_dist_tpu as tdt
    from triton_dist_tpu import ops, tune
    from triton_dist_tpu.autotuner import tune_spmd

    ndev = args.tp or len(jax.devices())
    mesh = tdt.make_mesh(tp=ndev, devices=jax.devices()[:ndev])
    mctx = tdt.MeshContext.from_mesh(mesh)
    dt = jnp.dtype(args.dtype)
    ka, kb = jax.random.PRNGKey(0), jax.random.PRNGKey(1)

    # Per-op geometry: shardings, config space, step factory. Cache
    # keys mirror each *_tuned wrapper's key_fn so in-trace lookups hit
    # what this sweep stores.
    if args.op == "ag_gemm":
        sa, sb, so = P("tp", None), P(None, "tp"), P(None, "tp")
        configs = [
            {"block_m": 256, "block_n": 512, "block_k": 1024},
            {"block_m": 512, "block_n": 512, "block_k": 2048},
            {"block_m": 512, "block_n": 1024, "block_k": 1024},
            {"block_m": 256, "block_n": 256, "block_k": 512},
            {"block_m": 64, "block_n": 64, "block_k": 64},
        ]

        def make_step(cfg):
            ctx = ops.create_ag_gemm_context(mctx, "tp", **cfg)
            return jax.jit(jax.shard_map(
                lambda xs, ws: ops.ag_gemm(xs, ws, ctx,
                                           force_kernel=(ndev == 1)),
                mesh=mesh, in_specs=(sa, sb), out_specs=so,
                check_vma=False))
    elif args.op == "gemm_rs":
        sa, sb, so = P(None, "tp"), P("tp", None), P("tp", None)
        configs = [
            {"block_m": 1024, "block_n": 128, "block_k": 4096},
            {"block_m": 512, "block_n": 128, "block_k": 4096},
            {"block_m": 512, "block_n": 128, "block_k": 2048},
            {"block_m": 256, "block_n": 256, "block_k": 1024},
            {"block_m": 64, "block_n": 32, "block_k": 32},
        ]

        def make_step(cfg):
            ctx = ops.create_gemm_rs_context(mctx, "tp", **cfg)
            return jax.jit(jax.shard_map(
                lambda xs, ws: ops.gemm_rs(xs, ws, ctx,
                                           force_kernel=(ndev == 1)),
                mesh=mesh, in_specs=(sa, sb), out_specs=so,
                check_vma=False))
    else:
        sa, sb, so = P(None, "tp"), P("tp", None), P(None, None)
        configs = [
            {"variant": "ll", "block_n": 512, "block_k": 1024},
            {"variant": "ll", "block_n": 1024, "block_k": 1024},
            {"variant": "ll", "block_n": 512, "block_k": 2048},
            {"variant": "one_shot", "block_n": 512, "block_k": 1024},
            {"variant": "ll", "block_n": 32, "block_k": 32},
        ]

        def make_step(cfg):
            cfg = dict(cfg)
            variant = cfg.pop("variant", "ll")
            ctx = ops.create_gemm_ar_context(mctx, "tp", variant=variant,
                                             **cfg)
            return jax.jit(jax.shard_map(
                lambda xs, ws: ops.gemm_ar(xs, ws, ctx,
                                           force_kernel=(ndev == 1)),
                mesh=mesh, in_specs=(sa, sb), out_specs=so,
                check_vma=False))

    a = jax.device_put(jax.random.normal(ka, (args.m, args.k), dt),
                       NamedSharding(mesh, sa))
    b = jax.device_put(jax.random.normal(kb, (args.k, args.n), dt),
                       NamedSharding(mesh, sb))
    # The in-trace *_tuned wrappers key on PER-SHARD shapes (what they
    # see inside shard_map); mirror that here or the cache never hits.
    if args.op == "ag_gemm":       # A row-sharded, B col-sharded
        key_attrs = {"m": args.m // ndev, "k": args.k,
                     "n": args.n // ndev}
    else:                          # A col-sharded (K), B row-sharded
        key_attrs = {"m": args.m, "k": args.k // ndev, "n": args.n}
    key_attrs.update({"dtype": str(a.dtype), "world": ndev})
    best = tune_spmd(args.op, configs, make_step, (a, b), key_attrs)
    if best is None:
        raise SystemExit(f"no {args.op} config compiled at "
                         f"m={args.m} k={args.k} n={args.n}")
    print(f"tuned {args.op} m={args.m} k={args.k} n={args.n} "
          f"world={ndev}: winner {best}; cache at {tune.cache_path()}")


if __name__ == "__main__":
    main()
