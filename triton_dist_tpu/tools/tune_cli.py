"""Offline tuning CLI — the reference's ``tools/tune/tune_gemm.py``
analogue: sweep the fused-GEMM config spaces on the ATTACHED backend
and persist winners into the tune cache, so serving jobs hit tuned
configs on first use.

Run (real chip):  TDT_REAL_TPU=1 python -m triton_dist_tpu.tools.tune_cli \
    --op ag_gemm --m 2048 --k 4096 --n 4096
"""

import argparse
import os


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--op", default="ag_gemm",
                    choices=["ag_gemm", "gemm_rs", "gemm_ar"])
    ap.add_argument("--m", type=int, default=2048)
    ap.add_argument("--k", type=int, default=4096)
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--tp", type=int, default=None,
                    help="mesh size (default: all attached devices)")
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    import jax
    if os.environ.get("TDT_REAL_TPU") != "1":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import triton_dist_tpu as tdt
    from triton_dist_tpu import ops

    ndev = args.tp or len(jax.devices())
    mesh = tdt.make_mesh(tp=ndev, devices=jax.devices()[:ndev])
    mctx = tdt.MeshContext.from_mesh(mesh)
    dt = jnp.dtype(args.dtype)
    ka, kb = jax.random.PRNGKey(0), jax.random.PRNGKey(1)

    if args.op == "ag_gemm":
        a = jax.device_put(jax.random.normal(ka, (args.m, args.k), dt),
                           NamedSharding(mesh, P("tp", None)))
        b = jax.device_put(jax.random.normal(kb, (args.k, args.n), dt),
                           NamedSharding(mesh, P(None, "tp")))
        fn = jax.jit(jax.shard_map(
            lambda xs, ws: ops.ag_gemm_tuned(xs, ws, mctx),
            mesh=mesh, in_specs=(P("tp", None), P(None, "tp")),
            out_specs=P(None, "tp"), check_vma=False))
    else:
        a = jax.device_put(jax.random.normal(ka, (args.m, args.k), dt),
                           NamedSharding(mesh, P(None, "tp")))
        b = jax.device_put(jax.random.normal(kb, (args.k, args.n), dt),
                           NamedSharding(mesh, P("tp", None)))
        tuned = (ops.gemm_rs_tuned if args.op == "gemm_rs"
                 else ops.gemm_ar_tuned)
        out_spec = (P("tp", None) if args.op == "gemm_rs"
                    else P(None, None))
        fn = jax.jit(jax.shard_map(
            lambda xs, ws: tuned(xs, ws, mctx),
            mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=out_spec, check_vma=False))

    jax.block_until_ready(fn(a, b))   # the sweep runs on first call
    from triton_dist_tpu import tune

    print(f"tuned {args.op} m={args.m} k={args.k} n={args.n} "
          f"world={ndev}; cache at {tune.cache_path()}")


if __name__ == "__main__":
    main()
