"""Device-topology introspection (the TPU analogue of NVML queries).

Reference: ``python/triton_dist/utils/nv_utils.py`` / ``amd_utils.py`` —
NVML link-matrix / NUMA topology / clock queries feeding the perf models
and the launcher. TPUs expose their topology through the JAX device
objects themselves: torus ``coords``, ``slice_index`` (DCN boundaries),
``device_kind`` (chip generation), ``process_index`` (host mapping) — no
driver library needed. This module turns those into the structures the
rest of the stack consumes: a chip spec for the perf models, an ICI
neighbour/hop map for schedule choices, and slice groups marking where
DCN (not ICI) carries traffic.

Works on any backend: CPU/interpret devices (no coords) degrade to a
single-group, zero-topology answer instead of failing — the same
single-host fallback the reference's ``nvml_init``-less path takes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from triton_dist_tpu.tools.perf_model import ChipSpec, V5E, V5P


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    id: int
    kind: str
    process_index: int
    coords: Optional[Tuple[int, ...]]   # torus position (TPU only)
    core_on_chip: int
    slice_index: int                    # DCN island (0 on single-slice)


def describe_devices(devices: Optional[Sequence] = None) -> List[DeviceInfo]:
    """One record per device, NVML-topo style (reference
    ``nv_utils.get_gpu_topo``)."""
    if devices is None:
        devices = jax.devices()
    out = []
    for d in devices:
        out.append(DeviceInfo(
            id=d.id,
            kind=getattr(d, "device_kind", d.platform),
            process_index=d.process_index,
            coords=tuple(getattr(d, "coords", ()) or ()) or None,
            core_on_chip=getattr(d, "core_on_chip", 0),
            slice_index=getattr(d, "slice_index", 0) or 0,
        ))
    return out


_KIND_SPECS = (
    # (substring of device_kind lowercased, ChipSpec)
    ("v5 lite", V5E),
    ("v5e", V5E),
    ("v5p", V5P),
    ("v5", V5P),
    ("v6", ChipSpec(bf16_tflops=918.0, hbm_gbps=1638.0,
                    ici_gbps_per_link=100.0, ici_links=4)),  # v6e
    ("v4", ChipSpec(bf16_tflops=275.0, hbm_gbps=1228.0,
                    ici_gbps_per_link=100.0, ici_links=6)),
)


def detect_chip(devices: Optional[Sequence] = None) -> ChipSpec:
    """ChipSpec for the attached hardware (reference: clock/SM queries
    feeding ``gemm_perf_model``). Unknown/CPU backends get the V5P
    default — the perf models stay usable as relative estimators."""
    if devices is None:
        devices = jax.devices()
    kind = getattr(devices[0], "device_kind", devices[0].platform).lower()
    for sub, spec in _KIND_SPECS:
        if sub in kind:
            return spec
    return V5P


def torus_dims(infos: Sequence[DeviceInfo]) -> Tuple[int, ...]:
    """Extent of each torus axis covered by ``infos`` (coords max+1)."""
    coords = [i.coords for i in infos if i.coords is not None]
    if not coords:
        return ()
    nd = len(coords[0])
    return tuple(max(c[a] for c in coords) + 1 for a in range(nd))


def ici_hop_distance(a: DeviceInfo, b: DeviceInfo,
                     dims: Tuple[int, ...]) -> Optional[int]:
    """Manhattan distance on the wrapped torus; None across slices
    (traffic rides DCN there, not ICI)."""
    if a.slice_index != b.slice_index:
        return None
    if a.coords is None or b.coords is None:
        return 0 if a.id == b.id else 1   # topology-less backend
    hops = 0
    for x, y, n in zip(a.coords, b.coords, dims):
        d = abs(x - y)
        hops += min(d, n - d) if n > 1 else d
    return hops


def link_matrix(devices: Optional[Sequence] = None) -> List[List[Optional[int]]]:
    """Pairwise ICI hop counts (None = different slice / DCN) — the
    analogue of ``nvidia-smi topo -m`` the reference shells out for."""
    infos = describe_devices(devices)
    dims = torus_dims(infos)
    return [[ici_hop_distance(a, b, dims) for b in infos] for a in infos]


def _groups(infos: Sequence[DeviceInfo]) -> Dict[int, List[int]]:
    groups: Dict[int, List[int]] = {}
    for i in infos:
        groups.setdefault(i.slice_index, []).append(i.id)
    return groups


def slice_groups(devices: Optional[Sequence] = None) -> Dict[int, List[int]]:
    """Device ids per DCN slice (reference: NUMA/node grouping). Mesh
    axes laid over different groups cross DCN; keep them outermost
    (``parallel/mesh.AXIS_ORDER``)."""
    return _groups(describe_devices(devices))


def neighbors(devices: Optional[Sequence] = None) -> Dict[int, List[int]]:
    """1-hop ICI adjacency per device id (ring/torus schedule input)."""
    infos = describe_devices(devices)
    dims = torus_dims(infos)
    out: Dict[int, List[int]] = {}
    for a in infos:
        out[a.id] = [b.id for b in infos
                     if b.id != a.id
                     and ici_hop_distance(a, b, dims) == 1]
    return out


def summary(devices: Optional[Sequence] = None) -> dict:
    """One JSON-able blob: chip spec, torus shape, slices, hosts —
    what ``nv_utils`` prints at launcher startup. Devices are walked
    exactly once."""
    infos = describe_devices(devices)
    chip = V5P
    for sub, spec in _KIND_SPECS:
        if infos and sub in infos[0].kind.lower():
            chip = spec
            break
    return {
        "num_devices": len(infos),
        "device_kind": infos[0].kind if infos else "none",
        "torus_dims": list(torus_dims(infos)),
        "slices": {str(k): v for k, v in _groups(infos).items()},
        "hosts": sorted({i.process_index for i in infos}),
        "chip": dataclasses.asdict(chip),
    }
