"""Analytical performance models for autotuner pruning.

Reference: ``kernels/nvidia/gemm_perf_model.py`` (249 — tensorcore
TFLOPS estimator), ``comm_perf_model.py`` (116 — NVLink/IB transfer
times); used to prune autotune config spaces.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Peak numbers per chip. Defaults: TPU v5p-ish."""
    bf16_tflops: float = 459.0
    hbm_gbps: float = 2765.0
    ici_gbps_per_link: float = 100.0   # one direction, per link
    ici_links: int = 6                 # 3D torus
    dcn_gbps: float = 25.0
    mxu_util: float = 0.7              # achievable fraction of peak


V5P = ChipSpec()
V5E = ChipSpec(bf16_tflops=197.0, hbm_gbps=819.0,
               ici_gbps_per_link=100.0, ici_links=4)


def gemm_time_s(m: int, k: int, n: int, *, dtype_bytes: int = 2,
                chip: ChipSpec = V5P) -> float:
    """Roofline: max(compute, HBM) time for an (m,k)x(k,n) GEMM."""
    flops = 2.0 * m * k * n
    t_compute = flops / (chip.bf16_tflops * 1e12 * chip.mxu_util)
    traffic = (m * k + k * n + m * n) * dtype_bytes
    t_mem = traffic / (chip.hbm_gbps * 1e9)
    return max(t_compute, t_mem)


def collective_time_s(bytes_per_device: int, n_devices: int, *,
                      kind: str = "all_gather", inter_slice: bool = False,
                      chip: ChipSpec = V5P) -> float:
    """Ring-collective transfer-time estimate over ICI (or DCN).

    all_gather / reduce_scatter move (n-1)/n of the data per link step;
    all_reduce twice that; all_to_all one full shuffle.
    """
    bw = (chip.dcn_gbps if inter_slice
          else chip.ici_gbps_per_link * 2) * 1e9  # bidir ring
    factor = {"all_gather": (n_devices - 1) / n_devices,
              "reduce_scatter": (n_devices - 1) / n_devices,
              "all_reduce": 2.0 * (n_devices - 1) / n_devices,
              "all_to_all": (n_devices - 1) / n_devices,
              "p2p": 1.0}[kind]
    return bytes_per_device * factor / bw


def overlap_efficiency_bound(m: int, k: int, n: int, world: int, *,
                             dtype_bytes: int = 2,
                             chip: ChipSpec = V5P) -> float:
    """Upper bound on AG+GEMM overlap efficiency: comm fully hidden iff
    per-chunk transfer <= per-chunk compute."""
    t_gemm = gemm_time_s(m, k, n // world, dtype_bytes=dtype_bytes,
                         chip=chip)
    t_comm = collective_time_s(m * k * dtype_bytes // world, world,
                               kind="all_gather", chip=chip)
    return min(1.0, t_gemm / (t_gemm + max(t_comm - t_gemm, 0.0)))


def gemm_rs_vmem_bytes(block_m: int, block_n: int, block_k: int,
                       m_loc: int, k_loc: int, n_dim: int,
                       dtype_bytes: int = 2) -> int:
    """Model of ops/gemm_rs.py's VMEM footprint for a block config:
    double-buffered pipelined A (tm,tk) and B (tk,tn) tiles plus the
    acc/tmp/out scratch triple (gemm_rs.py scratch_shapes)."""
    tm = min(block_m, m_loc)
    tn = min(block_n, n_dim)
    tk = min(block_k, k_loc)
    a_tiles = 2 * tm * tk * dtype_bytes
    b_tiles = 2 * tk * tn * dtype_bytes
    scratch = tm * tn * (4 + 4 + dtype_bytes)
    return a_tiles + b_tiles + scratch


def grouped_gemm_vmem_bytes(block_m: int, block_n: int, block_k: int,
                            d_in: int, d_out: int,
                            dtype_bytes: int = 2) -> int:
    """Model of ops/group_gemm.grouped_gemm_tiles' footprint: pipelined
    X row tile (tm, tk), per-expert W tile (tk, tn), f32 accumulator.
    Mirrors the kernel's divisor snapping (tn/tk halve until they
    divide the weight dims) so the modeled footprint is what actually
    allocates."""
    tn = min(block_n, d_out)
    while tn > 1 and d_out % tn:
        tn //= 2
    tk = min(block_k, d_in)
    while tk > 1 and d_in % tk:
        tk //= 2
    return (2 * block_m * tk * dtype_bytes + 2 * tk * tn * dtype_bytes
            + block_m * tn * 4 + block_m * tn * dtype_bytes)


def gemm_time_model_s(m: int, k: int, n: int, block_m: int, block_n: int,
                      block_k: int, *, dtype_bytes: int = 2,
                      chip: ChipSpec = V5P) -> float:
    """Config-sensitive GEMM time estimate: roofline compute plus the
    HBM traffic this BLOCKING actually generates in the (i, j, kk)
    grid — B tiles re-fetched once per row-tile sweep (n_i) and A tiles
    once per column-tile sweep (n_j). Used to rank/prune autotune
    configs before any compile (reference: ``gemm_perf_model.py``
    estimates per-config tensorcore time the same way)."""
    tm = max(min(block_m, m), 1)
    tn = max(min(block_n, n), 1)
    n_i = -(-m // tm)
    n_j = -(-n // tn)
    flops = 2.0 * m * k * n
    t_compute = flops / (chip.bf16_tflops * 1e12 * chip.mxu_util)
    traffic = (n_j * m * k + n_i * k * n + m * n) * dtype_bytes
    t_mem = traffic / (chip.hbm_gbps * 1e9)
    return max(t_compute, t_mem)


def _sub_jaxprs(params):
    """Yield every jaxpr nested in an eqn's params (pjit/scan/remat
    hold ClosedJaxprs or Jaxprs under varying keys; duck-typed so it
    survives jax version drift)."""
    def is_jaxpr(v):
        return hasattr(v, "eqns") or hasattr(getattr(v, "jaxpr", None),
                                             "eqns")
    for v in params.values():
        if is_jaxpr(v):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if is_jaxpr(item):
                    yield item


def jaxpr_flops(jaxpr) -> float:
    """Deterministic matmul-FLOP count from a jaxpr — the synthetic
    cost table for backends whose ``compile().cost_analysis()`` reports
    no flops (CPU, interpret): 2*out_size*contraction per
    ``dot_general``, multiplied through ``scan`` trip counts, the MAX
    over ``cond`` branches, and recursing into every nested call
    (pjit, shard_map, remat, custom-derivative wrappers). A ``while``
    body counts once — a lower bound, documented rather than guessed.

    Inside a ``shard_map`` the inner jaxpr is the per-rank program, so
    the count is per-device flops — exactly what schedule tests assert
    on (each PP rank must compute ~1/S of the sequential total).
    """
    inner = getattr(jaxpr, "jaxpr", jaxpr)    # ClosedJaxpr -> Jaxpr
    total = 0.0
    for eqn in inner.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            (lhs_c, _), _ = eqn.params["dimension_numbers"]
            lhs_shape = eqn.invars[0].aval.shape
            contract = 1
            for d in lhs_c:
                contract *= lhs_shape[d]
            out_size = 1
            for s in eqn.outvars[0].aval.shape:
                out_size *= s
            total += 2.0 * out_size * contract
            continue
        if name == "cond":
            total += max((jaxpr_flops(b)
                          for b in eqn.params["branches"]), default=0.0)
            continue
        mult = eqn.params.get("length", 1) if name == "scan" else 1
        for sub in _sub_jaxprs(eqn.params):
            total += mult * jaxpr_flops(sub)
    return total


def ag_gemm_vmem_bytes(block_m: int, block_n: int, block_k: int,
                       m_loc: int, kdim: int, n_loc: int,
                       dtype_bytes: int = 2,
                       panel_budget: int = 9 * 1024 * 1024) -> int:
    """Model of ops/ag_gemm.py's VMEM footprint for a block config —
    used to prune configs that cannot lower before any compile attempt
    (reference: gemm_perf_model.py pruning the autotune space)."""
    tm = min(block_m, m_loc)
    while tm > 8 and tm * kdim * dtype_bytes > panel_budget:
        tm //= 2
    while tm > 1 and m_loc % tm:
        tm //= 2
    tn = min(block_n, n_loc)
    tk = min(block_k, kdim)
    panel = tm * kdim * dtype_bytes
    n_i = max(m_loc // max(tm, 1), 1)
    # Mirrors ops/ag_gemm.py exactly: double-buffering needs >1 panel.
    n_buf = 2 if (n_i > 1 and 2 * panel <= panel_budget) else 1
    b_tiles = 2 * tk * tn * dtype_bytes          # double-buffered
    acc = tm * tn * 4
    out = 2 * tm * tn * dtype_bytes
    return n_buf * panel + b_tiles + acc + out


def ag_gemm_pipelined_vmem_bytes(block_m: int, block_n: int,
                                 block_k: int, m_loc: int, kdim: int,
                                 n_loc: int, dtype_bytes: int = 2,
                                 panel_budget: int = 9 * 1024 * 1024
                                 ) -> int:
    """Model of the pipelined (scoped-VMEM streamed) ag_gemm variant's
    footprint: ``n_buf`` rotating (tm, tk) + (tk, tn) block pairs, the
    f32 accumulator, and the double-buffered output tile — independent
    of K (the panel model's footprint grows with K; this one streams
    K). Mirrors ``ops/ag_gemm.pipelined_blocks``'s tk budget clamp."""
    tm = min(block_m, m_loc)
    while tm > 1 and m_loc % tm:
        tm //= 2
    tn = min(block_n, n_loc)
    tk = min(block_k, kdim)
    while tk > 8 and kdim % tk:
        tk //= 2
    while (tk > 8 and 2 * (tm + tn) * tk * dtype_bytes > panel_budget
           and kdim % (tk // 2) == 0):
        tk //= 2
    pair = (tm * tk + tk * tn) * dtype_bytes
    n_buf = 2 if (kdim // max(tk, 1) > 1
                  and 2 * pair <= panel_budget) else 1
    acc = tm * tn * 4
    out = 2 * tm * tn * dtype_bytes
    return n_buf * pair + acc + out
