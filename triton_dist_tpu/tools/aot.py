"""Ahead-of-time compilation.

Reference: ``tools/compile_aot.py`` + ``tools/compile/compile.py:78-283``
compile listed kernels to C sources + cubins with a CUDA-driver C
runtime (``tools/runtime/triton_aot_runtime.{h,cc}``), gated by
``USE_TRITON_DISTRIBUTED_AOT``.

TPU redesign: ``jax.export`` serializes a lowered+compiled XLA program
(StableHLO) to a portable blob; ``load_aot`` rehydrates it without
retracing Python. This is the platform-native equivalent of the cubin +
driver-cache runtime — XLA's compilation cache plays the role of the
module/function cache in ``triton_aot_runtime.h:33``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Sequence

import jax


@dataclasses.dataclass
class AOTExecutable:
    rehydrated: object

    def __call__(self, *args):
        return self.rehydrated.call(*args)


def compile_aot(fn: Callable, example_args: Sequence, path: str,
                *, platforms: Sequence[str] = None) -> str:
    """Serialize ``jit(fn)`` for ``example_args`` to ``path``."""
    from jax import export as jexport

    exported = jexport.export(
        jax.jit(fn),
        platforms=list(platforms) if platforms else None,
    )(*[jax.ShapeDtypeStruct(a.shape, a.dtype) if hasattr(a, "shape")
        else a for a in example_args])
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return path


def load_aot(path: str) -> AOTExecutable:
    from jax import export as jexport

    with open(path, "rb") as f:
        blob = f.read()
    return AOTExecutable(jexport.deserialize(blob))
