"""Ahead-of-time compilation.

Reference: ``tools/compile_aot.py`` + ``tools/compile/compile.py:78-283``
compile listed kernels to C sources + cubins with a CUDA-driver C
runtime (``tools/runtime/triton_aot_runtime.{h,cc}``), gated by
``USE_TRITON_DISTRIBUTED_AOT``.

TPU redesign: ``jax.export`` serializes a lowered+compiled XLA program
(StableHLO) to a portable blob; ``load_aot`` rehydrates it without
retracing Python. This is the platform-native equivalent of the cubin +
driver-cache runtime — XLA's compilation cache plays the role of the
module/function cache in ``triton_aot_runtime.h:33``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Sequence

import jax


@dataclasses.dataclass
class AOTExecutable:
    rehydrated: object

    def __call__(self, *args):
        return self.rehydrated.call(*args)


def compile_aot(fn: Callable, example_args: Sequence, path: str,
                *, platforms: Sequence[str] = None) -> str:
    """Serialize ``jit(fn)`` for ``example_args`` to ``path``."""
    from jax import export as jexport

    exported = jexport.export(
        jax.jit(fn),
        platforms=list(platforms) if platforms else None,
    )(*[jax.ShapeDtypeStruct(a.shape, a.dtype) if hasattr(a, "shape")
        else a for a in example_args])
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return path


def load_aot(path: str) -> AOTExecutable:
    from jax import export as jexport

    with open(path, "rb") as f:
        blob = f.read()
    return AOTExecutable(jexport.deserialize(blob))


class AOTCache:
    """A directory of exported kernels with a manifest — the analogue of
    the reference's AOT bundle (``tools/compile_aot.py`` compiles a
    *list* of kernels into C sources + cubins consumed by a name-keyed
    runtime cache, ``triton_aot_runtime.h:33``).

    Layout: ``<dir>/manifest.json`` mapping name → {file, args
    signature, jax version}; one ``.jaxexport`` blob per kernel.
    ``get`` validates the call signature against the manifest (shape /
    dtype mismatches raise instead of mis-executing — the runtime-side
    argument checks the reference generates into its C stubs) and the
    recorded jax version (serialized StableHLO has bounded
    forward-compat).
    """

    def __init__(self, directory: str):
        self.dir = directory
        self._manifest_path = os.path.join(directory, "manifest.json")
        self._loaded = {}

    def _read_manifest(self) -> dict:
        import json

        if not os.path.exists(self._manifest_path):
            return {}
        with open(self._manifest_path) as f:
            return json.load(f)

    @staticmethod
    def _sig(args) -> list:
        return [[list(a.shape), str(a.dtype)] if hasattr(a, "shape")
                else [None, repr(a)] for a in args]

    def add(self, name: str, fn: Callable, example_args: Sequence,
            *, platforms: Sequence[str] = None) -> str:
        """Export ``fn`` under ``name`` and record it in the manifest."""
        import json

        path = os.path.join(self.dir, f"{name}.jaxexport")
        compile_aot(fn, example_args, path, platforms=platforms)
        manifest = self._read_manifest()
        manifest[name] = {"file": os.path.basename(path),
                          "signature": self._sig(example_args),
                          "jax": jax.__version__}
        with open(self._manifest_path, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        self._loaded.pop(name, None)
        return path

    def names(self):
        return sorted(self._read_manifest())

    def get(self, name: str) -> AOTExecutable:
        manifest = self._read_manifest()
        if name not in manifest:
            raise KeyError(
                f"{name!r} not in AOT cache {self.dir} "
                f"(have {sorted(manifest)})")
        if name not in self._loaded:
            self._loaded[name] = load_aot(
                os.path.join(self.dir, manifest[name]["file"]))
        return self._loaded[name]

    def call(self, name: str, *args):
        """Signature-checked call (the generated-stub arg validation)."""
        entry = self._read_manifest()[name]
        got = self._sig(args)
        want = entry["signature"]
        if [g for g in got if g[0] is not None] != \
                [w for w in want if w[0] is not None]:
            raise TypeError(
                f"AOT kernel {name!r} signature mismatch: exported "
                f"{want}, called with {got}")
        return self.get(name)(*args)
