"""Cross-cutting tools (reference: ``python/triton_dist/tools/``,
SURVEY.md §2.11): AOT compilation, tune helpers, perf models."""

from triton_dist_tpu.tools.aot import (  # noqa: F401
    compile_aot, load_aot, AOTExecutable,
)
from triton_dist_tpu.tools.perf_model import (  # noqa: F401
    gemm_time_s, collective_time_s, ChipSpec,
)
