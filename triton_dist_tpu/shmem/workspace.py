"""Host-side symmetric-buffer management ("icishmem").

Reference analogue: ``nvshmem_create_tensor`` / ``nvshmem_create_tensors``
(``python/triton_dist/utils.py:252,272``) allocate one buffer at the same
symmetric-heap offset on every GPU, plus per-peer P2P views.

On TPU the symmetric heap falls out of SPMD: a global array sharded over a
mesh axis gives every device an identically-shaped local shard at an
address the RDMA engine can target on any peer ("symmetric address" =
same Ref in the same kernel on the peer core). So:

- ``symm_tensor(mesh, local_shape, ...)`` returns a *global* zeros array
  whose per-device shard (under ``shard_map`` with ``symm_spec``) is
  ``local_shape`` — pass it into kernels as workspace, alias it to an
  output (``input_output_aliases``) if it must persist across calls.
- per-peer views need no API: a kernel addresses peer buffers directly in
  ``make_async_remote_copy(device_id=...)``.

PERSISTENT CONTEXTS (reference ctx-owned symmetric tensors,
``allgather_gemm.py:449-511``): ops whose workspace must persist
across calls thread it functionally — seed with ``symm_tensor``, pass
it back in each call, alias it to an output. ``ag_gemm`` no longer
needs this: both its variants expose the ring workspace as a plain
second output with no init cost to amortize (the old aliased-pipeline
variant, which pre-placed the local chunk into a zero-filled
workspace, is gone). The per-invocation entry barrier itself is
irreducible on TPU (``docs/primitives.md`` rule 3 — semaphore register
aliasing across kernels); to amortize IT, fuse the loop into one
kernel (``ops/low_latency.ll_a2a_steps``, the megakernel).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def symm_spec(axis: str = "tp", ndim: int = 2) -> P:
    """PartitionSpec placing the symmetric (per-rank) dim first."""
    return P(axis, *([None] * (ndim - 1)))


def symm_tensor(mesh: Mesh, local_shape: Tuple[int, ...], dtype=jnp.float32,
                axis: str = "tp") -> jax.Array:
    """Symmetric workspace: every device along ``axis`` owns a zeroed
    ``local_shape`` shard of one global array.

    Reference: ``nvshmem_create_tensor(shape, dtype)`` (utils.py:252).
    """
    n = mesh.shape[axis]
    global_shape = (n * local_shape[0],) + tuple(local_shape[1:])
    sharding = NamedSharding(mesh, symm_spec(axis, len(local_shape)))
    return jax.device_put(jnp.zeros(global_shape, dtype), sharding)


# Compiled host barriers, one per (mesh, axis): the closure used to be
# rebuilt and re-jitted on every call, so every test-scaffolding
# barrier paid a retrace (utils.jit_cache.CompiledCache documents the
# pattern; ops/p2p.py and ops/broadcast.py share it).
from triton_dist_tpu.utils.jit_cache import CompiledCache

_BARRIER_CACHE = CompiledCache(16)


def _compiled_barrier(mesh: Mesh, axis: str):
    def build():
        def inner(x):
            return jax.lax.psum(x, axis)

        return jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=P(), out_specs=P(), check_vma=False,
        ))
    return _BARRIER_CACHE.get_or_build((mesh, axis), build)


def barrier_all(mesh: Mesh, axis: str = "tp", *,
                timeout_s: Optional[float] = None) -> None:
    """Host-level device barrier along ``axis`` — the analogue of
    ``nvshmem_barrier_all_on_stream`` (utils.py:325).

    XLA programs are already bulk-synchronous per dispatch; this exists
    for test scaffolding and for flushing outstanding async work: it runs
    a trivial psum across the axis and blocks until ready.

    ``timeout_s`` bounds the wait: a peer wedged inside a comm kernel
    leaves this barrier blocked forever — with a deadline it raises a
    structured :class:`~triton_dist_tpu.resilience.CommTimeoutError`
    (rank + op) instead of hanging the host.
    """
    from triton_dist_tpu.resilience.watchdog import block_until_ready

    block_until_ready(_compiled_barrier(mesh, axis)(jnp.zeros((), jnp.int32)),
                      timeout_s=timeout_s,
                      op=f"shmem.barrier_all[{axis}]")
