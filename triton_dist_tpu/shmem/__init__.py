from triton_dist_tpu.shmem.workspace import (  # noqa: F401
    symm_tensor,
    symm_spec,
    barrier_all,
)
