"""Persistent autotune cache.

Reference: ``python/triton_dist/tune.py`` (503 LoC) — JSON records keyed
by tensor shapes/dtypes + dependency versions (``store_autotune_data``
:187, ``load_autotune_data`` :175, dependency check :228-246), consumed
by the ``triton_dist.tune.autotune(config_space, key_fn, prune_fn)``
decorator on ag_gemm etc.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
from typing import Any, Dict, Optional

_LOCK = threading.Lock()
_CACHE: Optional[Dict] = None
_CACHE_PATH: Optional[str] = None


def cache_dir() -> str:
    """The package's persistent cache root (``TRITON_DIST_TPU_CACHE_DIR``,
    default ``~/.cache/triton_dist_tpu``) — the single resolution point
    shared by the tune cache, the bench probe verdict, and the
    megakernel scheduler's read-only-checkout ``.so`` fallback."""
    base = os.environ.get(
        "TRITON_DIST_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "triton_dist_tpu"))
    os.makedirs(base, exist_ok=True)
    return base


def cache_path() -> str:
    global _CACHE_PATH
    if _CACHE_PATH is None:
        _CACHE_PATH = os.path.join(cache_dir(), "tune_cache.json")
    return _CACHE_PATH


def _dep_versions() -> Dict[str, str]:
    """Dependency stamp: cached entries are invalidated when the stack
    changes (reference ``tune.py:228-246``)."""
    import jax
    import triton_dist_tpu

    return {
        "jax": jax.__version__,
        "triton_dist_tpu": triton_dist_tpu.__version__,
        "backend": jax.default_backend(),
    }


def mesh_key(mesh) -> str:
    """Stable mesh-shape attribute for autotune cache keys (the ISSUE-2
    contract: tuned winners are keyed on (mesh shape, M/N/K, dtype)).
    ``mesh`` is a :class:`~triton_dist_tpu.parallel.mesh.MeshContext`."""
    return "x".join(f"{a}{s}" for a, s in zip(mesh.axes, mesh.sizes))


def model_key(cfg) -> str:
    """Stable identity for a model config (a frozen dataclass): the
    sha256 of its sorted field dict. The megakernel schedule autotune
    (``megakernel.engine.tune_schedule``) keys its static-vs-dynamic
    winner on (model_key, mesh_key, batch, cores) — the attributes the
    task graph and therefore the winning schedule depend on."""
    import dataclasses

    d = {k: str(v) for k, v in sorted(
        dataclasses.asdict(cfg).items())}
    blob = json.dumps(d, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def make_key(op: str, **attrs) -> str:
    """Stable key from op name + shapes/dtypes/mesh attributes
    (reference ``triton_dist_key``, ``utils.py:862``)."""
    blob = json.dumps({"op": op, **{k: str(v) for k, v in attrs.items()}},
                      sort_keys=True)
    return f"{op}:{hashlib.sha256(blob.encode()).hexdigest()[:16]}"


def _load() -> Dict:
    global _CACHE
    if _CACHE is None:
        try:
            with open(cache_path()) as f:
                _CACHE = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            _CACHE = {}
    return _CACHE


def load_autotune_data(key: str) -> Optional[Dict[str, Any]]:
    with _LOCK:
        rec = _load().get(key)
    if rec is None:
        return None
    if rec.get("versions") != _dep_versions():
        return None
    return rec["config"]


def store_autotune_data(key: str, config: Dict[str, Any],
                        seconds: Optional[float] = None) -> None:
    """Record a tuned winner and persist the whole cache atomically.

    ``_LOCK`` serializes in-process writers; the PRIVATE temp file (not
    a fixed ``.tmp`` suffix) + ``os.replace`` keeps concurrent
    PROCESSES from interleaving writes into one half-written file — a
    reader sees either the old complete JSON or the new one."""
    with _LOCK:
        cache = _load()
        cache[key] = {"config": config, "seconds": seconds,
                      "versions": _dep_versions()}
        fd, tmp = tempfile.mkstemp(
            prefix=".tune_", suffix=".tmp",
            dir=os.path.dirname(cache_path()))
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(cache, f, indent=1, sort_keys=True)
            os.chmod(tmp, 0o644)  # mkstemp's 0600 would break shared caches
            os.replace(tmp, cache_path())
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)


def clear_cache() -> None:
    global _CACHE
    with _LOCK:
        _CACHE = {}
        try:
            os.remove(cache_path())
        except FileNotFoundError:
            pass
