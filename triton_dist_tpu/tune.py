"""Persistent autotune cache.

Reference: ``python/triton_dist/tune.py`` (503 LoC) — JSON records keyed
by tensor shapes/dtypes + dependency versions (``store_autotune_data``
:187, ``load_autotune_data`` :175, dependency check :228-246), consumed
by the ``triton_dist.tune.autotune(config_space, key_fn, prune_fn)``
decorator on ag_gemm etc.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Dict, Optional

_LOCK = threading.Lock()
_CACHE: Optional[Dict] = None
_CACHE_PATH: Optional[str] = None


def cache_path() -> str:
    global _CACHE_PATH
    if _CACHE_PATH is None:
        base = os.environ.get(
            "TRITON_DIST_TPU_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "triton_dist_tpu"))
        os.makedirs(base, exist_ok=True)
        _CACHE_PATH = os.path.join(base, "tune_cache.json")
    return _CACHE_PATH


def _dep_versions() -> Dict[str, str]:
    """Dependency stamp: cached entries are invalidated when the stack
    changes (reference ``tune.py:228-246``)."""
    import jax
    import triton_dist_tpu

    return {
        "jax": jax.__version__,
        "triton_dist_tpu": triton_dist_tpu.__version__,
        "backend": jax.default_backend(),
    }


def make_key(op: str, **attrs) -> str:
    """Stable key from op name + shapes/dtypes/mesh attributes
    (reference ``triton_dist_key``, ``utils.py:862``)."""
    blob = json.dumps({"op": op, **{k: str(v) for k, v in attrs.items()}},
                      sort_keys=True)
    return f"{op}:{hashlib.sha256(blob.encode()).hexdigest()[:16]}"


def _load() -> Dict:
    global _CACHE
    if _CACHE is None:
        try:
            with open(cache_path()) as f:
                _CACHE = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            _CACHE = {}
    return _CACHE


def load_autotune_data(key: str) -> Optional[Dict[str, Any]]:
    with _LOCK:
        rec = _load().get(key)
    if rec is None:
        return None
    if rec.get("versions") != _dep_versions():
        return None
    return rec["config"]


def store_autotune_data(key: str, config: Dict[str, Any],
                        seconds: Optional[float] = None) -> None:
    with _LOCK:
        cache = _load()
        cache[key] = {"config": config, "seconds": seconds,
                      "versions": _dep_versions()}
        tmp = cache_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1, sort_keys=True)
        os.replace(tmp, cache_path())


def clear_cache() -> None:
    global _CACHE
    with _LOCK:
        _CACHE = {}
        try:
            os.remove(cache_path())
        except FileNotFoundError:
            pass
