"""Config autotuner for fused kernels.

Reference: ``python/triton_dist/autotuner.py`` (250 LoC) —
``ContextualAutoTuner`` steps all ranks through configs *in lockstep*
with error-sync so a crashed config can't deadlock the job
(``autotuner.py:43``, ``contextual_autotune(is_dist=True)`` :97).

JAX redesign: an SPMD program is already lockstep — every host traces
the same config sequence deterministically, and a config that fails to
compile fails identically everywhere, so the reference's error-sync
machinery reduces to a deterministic try/except. Timing uses the
chained-slope harness (``profiler_utils.perf_func``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Sequence

from triton_dist_tpu import tune
from triton_dist_tpu.profiler_utils import perf_func


@dataclasses.dataclass
class Config:
    """One tuning point (kwargs merged into the op call)."""
    kwargs: Dict[str, Any]

    def __repr__(self):
        return f"Config({self.kwargs})"


def autotune(op_name: str, configs: Sequence[Dict[str, Any]],
             key_fn: Callable[..., Dict[str, Any]],
             prune_fn: Optional[Callable] = None):
    """Decorator: ``fn(*args, **config_kwargs)`` is swept over
    ``configs`` on first use per cache key; the winner persists in the
    tune cache (reference ``triton_dist.tune.autotune``).

    ``key_fn(*args, **kwargs) -> dict`` of static attributes (shapes,
    dtypes, mesh) forming the cache key. ``prune_fn(config, *args)``
    may veto configs before timing.
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            attrs = key_fn(*args, **kwargs)
            key = tune.make_key(op_name, **attrs)
            cached = tune.load_autotune_data(key)
            if cached is not None:
                return fn(*args, **kwargs, **cached)

            candidates = [c for c in configs
                          if prune_fn is None or prune_fn(c, *args)]
            n_total = len(list(configs))
            if n_total - len(candidates):
                # Reference logs its perf-model pruning too
                # (gemm_perf_model.py); the count makes the veto
                # behaviour observable. Rank-0 only: every process
                # traces the same deterministic sweep.
                from triton_dist_tpu.utils.distributed import dist_print

                dist_print(f"[autotune:{op_name}] perf-model vetoed "
                           f"{n_total - len(candidates)}/{n_total} "
                           "configs", prefix=False)
            if not candidates:
                return fn(*args, **kwargs)
            # Under tracing (jit/shard_map) nothing can be TIMED — a
            # tracer has no wall clock. Use the cache (miss → first
            # pruned candidate, deterministic everywhere) and leave
            # sweeping to the offline paths: tune_spmd / tune_cli /
            # bench.py, which time concrete jitted steps.
            import jax

            if any(isinstance(a, jax.core.Tracer) for a in args):
                return fn(*args, **kwargs, **candidates[0])
            best_cfg, best_t = None, float("inf")
            for cfg in candidates:
                try:
                    t = perf_func(
                        lambda *a: fn(*a, **kwargs, **cfg), args)
                except Exception:
                    # Deterministic across hosts: every rank sees the
                    # same failure and skips the same config.
                    continue
                if t < best_t:
                    best_cfg, best_t = cfg, t
            if best_cfg is None:
                return fn(*args, **kwargs)
            tune.store_autotune_data(key, best_cfg, best_t)
            return fn(*args, **kwargs, **best_cfg)
        return wrapper
    return deco


def tune_spmd(op_name: str, configs: Sequence[Dict[str, Any]],
              make_step: Callable[[Dict[str, Any]], Callable],
              operands: Sequence[Any], key_attrs: Dict[str, Any],
              prune_fn: Optional[Callable] = None,
              reps: int = 3) -> Optional[Dict[str, Any]]:
    """OFFLINE config sweep for SPMD ops (the path that can actually
    time): ``make_step(cfg)`` returns a jitted callable over concrete
    arrays (typically ``jax.jit(jax.shard_map(op-with-cfg))``); each
    candidate is compiled and timed eagerly, the winner persists in
    the tune cache under ``key_attrs``, and subsequent in-trace calls
    of the op's ``*_tuned`` wrapper hit that cache. Configs that fail
    to compile are skipped (the reference autotuner's deterministic
    failure-skip policy). Returns the winning config (None if nothing
    compiled)."""
    import time as _time

    import numpy as _np

    key = tune.make_key(op_name, **key_attrs)
    candidates = [c for c in configs
                  if prune_fn is None or prune_fn(c, *operands)]
    best_cfg, best_t = None, float("inf")
    for cfg in candidates:
        try:
            step = make_step(cfg)
            _np.asarray(step(*operands))          # compile + correctness
            t = float("inf")
            for _ in range(reps):
                t0 = _time.perf_counter()
                _np.asarray(step(*operands))
                t = min(t, _time.perf_counter() - t0)
        except Exception:
            continue
        if t < best_t:
            best_cfg, best_t = cfg, t
    if best_cfg is not None:
        tune.store_autotune_data(key, best_cfg, best_t)
    return best_cfg
