"""Config autotuner for fused kernels.

Reference: ``python/triton_dist/autotuner.py`` (250 LoC) —
``ContextualAutoTuner`` steps all ranks through configs *in lockstep*
with error-sync so a crashed config can't deadlock the job
(``autotuner.py:43``, ``contextual_autotune(is_dist=True)`` :97).

JAX redesign: an SPMD program is already lockstep — every host traces
the same config sequence deterministically, and a config that fails to
compile fails identically everywhere, so the reference's error-sync
machinery reduces to a deterministic try/except. Timing uses the
chained-slope harness (``profiler_utils.perf_func``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Sequence

from triton_dist_tpu import tune
from triton_dist_tpu.profiler_utils import perf_func


@dataclasses.dataclass
class Config:
    """One tuning point (kwargs merged into the op call)."""
    kwargs: Dict[str, Any]

    def __repr__(self):
        return f"Config({self.kwargs})"


def autotune(op_name: str, configs: Sequence[Dict[str, Any]],
             key_fn: Callable[..., Dict[str, Any]],
             prune_fn: Optional[Callable] = None):
    """Decorator: ``fn(*args, **config_kwargs)`` is swept over
    ``configs`` on first use per cache key; the winner persists in the
    tune cache (reference ``triton_dist.tune.autotune``).

    ``key_fn(*args, **kwargs) -> dict`` of static attributes (shapes,
    dtypes, mesh) forming the cache key. ``prune_fn(config, *args)``
    may veto configs before timing.
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            attrs = key_fn(*args, **kwargs)
            key = tune.make_key(op_name, **attrs)
            cached = tune.load_autotune_data(key)
            if cached is not None:
                return fn(*args, **kwargs, **cached)

            candidates = [c for c in configs
                          if prune_fn is None or prune_fn(c, *args)]
            n_total = len(list(configs))
            if n_total - len(candidates):
                # Reference logs its perf-model pruning too
                # (gemm_perf_model.py); the count makes the veto
                # behaviour observable. Rank-0 only: every process
                # traces the same deterministic sweep.
                from triton_dist_tpu.utils.distributed import dist_print

                dist_print(f"[autotune:{op_name}] perf-model vetoed "
                           f"{n_total - len(candidates)}/{n_total} "
                           "configs", prefix=False)
            if not candidates:
                return fn(*args, **kwargs)
            best_cfg, best_t = None, float("inf")
            for cfg in candidates:
                try:
                    t = perf_func(
                        lambda *a: fn(*a, **kwargs, **cfg), args)
                except Exception:
                    # Deterministic across hosts: every rank sees the
                    # same failure and skips the same config.
                    continue
                if t < best_t:
                    best_cfg, best_t = cfg, t
            if best_cfg is None:
                return fn(*args, **kwargs)
            tune.store_autotune_data(key, best_cfg, best_t)
            return fn(*args, **kwargs, **best_cfg)
        return wrapper
    return deco
