"""Subprocess battery runner for deadlock-prone fault plans.

A dropped signal deadlocks the blocking interpreter *inside* a jitted
dispatch — no in-process timeout can cancel it, and the wedged device
thread would poison every later dispatch in the test process. So the
battery replays each adversarial schedule in a child process with a
hard deadline:

- child (``python -m triton_dist_tpu.resilience.harness --plan P
  --op O``): builds the 8-device CPU mesh, activates the plan, runs the
  op against its oracle, prints ``TDT-PROGRESS ...`` markers as it
  advances and a final ``TDT-RESULT OK|MISMATCH`` line;
- parent (:func:`run_plan`): enforces ``deadline_s``; a deadline miss
  kills the child and raises :class:`CommTimeoutError` whose
  ``progress`` field is the child's last progress marker — rank, op,
  and last-completed step, exactly what a hang never tells you.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

from triton_dist_tpu.resilience.watchdog import CommTimeoutError

__all__ = ["run_plan", "CHILD_OPS"]

CHILD_OPS = ("ag_gemm", "megakernel")


def _child_env(extra_env: Optional[dict] = None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    if extra_env:
        env.update(extra_env)
    return env


def run_plan(plan: str, op: str, *, deadline_s: float = 300.0,
             rank: int = 0, k: int = 0, iters: int = 20000,
             extra_env: Optional[dict] = None) -> Tuple[str, str]:
    """Replay fault ``plan`` against ``op`` in a child process.

    Returns ``(verdict, output)`` where verdict is ``"ok"`` (fault
    tolerated — bit-correct output) — raises
    :class:`CommTimeoutError` on a deadline miss (fault detected) and
    :class:`RuntimeError` on any other child failure (mismatch or
    crash: a protocol bug the battery just found).
    """
    cmd = [sys.executable, "-m", "triton_dist_tpu.resilience.harness",
           "--plan", plan, "--op", op, "--rank", str(rank),
           "--k", str(k), "--iters", str(iters)]
    try:
        proc = subprocess.run(
            cmd, env=_child_env(extra_env), cwd=_repo_root(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=deadline_s, text=True)
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        out = out.decode("utf-8", "replace") if isinstance(out, bytes) \
            else out
        raise CommTimeoutError(
            op=op, rank=rank, timeout_s=deadline_s,
            progress=_last_progress(out),
            detail=f"fault plan {plan!r} wedged the child process"
        ) from None
    out = proc.stdout or ""
    if proc.returncode == 0 and "TDT-RESULT OK" in out:
        return "ok", out
    raise RuntimeError(
        f"fault plan {plan!r} on op {op!r}: child exited "
        f"rc={proc.returncode} without OK verdict; last progress: "
        f"{_last_progress(out)!r}\n--- child output tail ---\n"
        + "\n".join(out.splitlines()[-25:]))


def _last_progress(output: str) -> Optional[str]:
    last = None
    for line in output.splitlines():
        if line.startswith("TDT-PROGRESS"):
            last = line[len("TDT-PROGRESS"):].strip()
    return last


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Child entry
# ---------------------------------------------------------------------------

def _progress(**kv) -> None:
    print("TDT-PROGRESS "
          + " ".join(f"{k}={v}" for k, v in kv.items()), flush=True)


def _child_ag_gemm(plan, rank):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from triton_dist_tpu.ops.ag_gemm import (
        ag_gemm, ag_gemm_ref, create_ag_gemm_context)
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.resilience import faults

    mesh = Mesh(np.array(jax.devices()[:8]), ("tp",))
    mctx = MeshContext.from_mesh(mesh)
    n, m_loc, kdim, nloc = 8, 16, 128, 128
    a = (jnp.arange(n * m_loc * kdim, dtype=jnp.float32)
         .reshape(n * m_loc, kdim) % 13) / 13.0
    b = (jnp.arange(kdim * nloc, dtype=jnp.float32)
         .reshape(kdim, nloc) % 7) / 7.0
    ctx = create_ag_gemm_context(mctx, "tp", block_m=m_loc,
                                 block_n=nloc, block_k=kdim)
    _progress(rank=rank, phase="trace")
    with faults.inject(plan):
        run = jax.jit(jax.shard_map(
            lambda a_, b_: ag_gemm(a_, b_, ctx), mesh=mesh,
            in_specs=(P("tp", None), P(None, None)),
            out_specs=P(None, None), check_vma=False))
        out = run(a, b)
        _progress(rank=rank, phase="dispatched")
        out = jax.block_until_ready(out)
    _progress(rank=rank, phase="complete")
    want = jax.block_until_ready(jax.jit(jax.shard_map(
        lambda a_, b_: ag_gemm_ref(a_, b_, axis="tp"), mesh=mesh,
        in_specs=(P("tp", None), P(None, None)),
        out_specs=P(None, None), check_vma=False))(a, b))
    return np.allclose(np.asarray(out), np.asarray(want),
                       rtol=1e-4, atol=1e-4)


def _child_megakernel(plan, rank):
    import os

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models.config import ModelConfig
    from triton_dist_tpu.resilience import faults

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    toks = np.array([3, 5], np.int32)
    # TDT_MK_SCHEDULE=dynamic replays the plan against the dynamic
    # scoreboard scheduler (claim-counter execution) instead of the
    # static queues — the dropped-edge plan must wedge or survive
    # identically; the progress markers below then carry claim-counter
    # semantics (engine.progress()["progress_counter"] == "claim").
    schedule = os.environ.get("TDT_MK_SCHEDULE", "static")

    _progress(rank=rank, phase="baseline", schedule=schedule)
    base = MegaKernelEngine(cfg, mesh, batch=2, max_len=32,
                            schedule=schedule)
    want = np.asarray(jax.block_until_ready(base.generate(toks, 4)))

    _progress(rank=rank, phase="faulted-trace", schedule=schedule)
    with faults.inject(plan):
        eng = MegaKernelEngine(cfg, mesh, batch=2, max_len=32,
                               schedule=schedule)
        _progress(rank=rank, phase="faulted-dispatch",
                  schedule=schedule, steps_done=eng.steps_done)
        got = np.asarray(jax.block_until_ready(eng.generate(toks, 4)))
    _progress(rank=rank, phase="complete", schedule=schedule,
              steps_done=eng.steps_done)
    return np.array_equal(got, want)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--plan", required=True)
    p.add_argument("--op", required=True, choices=CHILD_OPS)
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--k", type=int, default=0)
    p.add_argument("--iters", type=int, default=20000)
    args = p.parse_args(argv)

    from triton_dist_tpu.resilience import faults

    plan = faults.get_plan(args.plan, op=args.op, rank=args.rank,
                           k=args.k, iters=args.iters)
    runner = {"ag_gemm": _child_ag_gemm,
              "megakernel": _child_megakernel}[args.op]
    ok = runner(plan, args.rank)
    print("TDT-RESULT OK" if ok else "TDT-RESULT MISMATCH", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
