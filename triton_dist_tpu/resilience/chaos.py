"""Seeded chaos soak for the serving stack: randomized (but
seed-reproducible) fault schedules over a long mixed-traffic run, with
a full invariant sweep after every tick.

The fault-plan registry (:mod:`~triton_dist_tpu.resilience.faults`)
makes single failures injectable; this module composes them into a
SOAK — the test shape production incidents actually have: transients
and hard faults arriving at random points of a live workload, workers
dying mid-stream, the process checkpointing and restarting in the
middle. One ``seed`` fixes the arrival trace, every fault's tick and
kind, and every retry-backoff jitter, so a failing soak replays
bit-for-bit.

What a passing soak proves (the checker raises
:class:`InvariantViolation` otherwise):

- **no leaked pages** — every page is free xor referenced, refcounts
  equal the observable holders (slot lists + the prefix cache's own
  ref), free list has no duplicates, the scratch page is never
  allocated;
- **prefix publication is sound** — committed (published) entries are
  content-resident by construction of the two-phase protocol, and no
  page is simultaneously staged and published;
- **host mirrors cohere** — slot/handle bijection, live mask, and the
  length mirrors agree with the allocator's token accounting (up to
  the bounded skew a failed tick's idempotent pre-append leaves);
- **every submitted request terminally resolves** — done, failed, or
  timeout; nothing wedges or leaks a slot;
- **survivors are token-exact** — every ``done`` request's tokens
  equal the fault-free oracle (``Engine.serve`` on the same weights).

Usage (the tier-1 subset in ``tests/test_chaos.py`` and the
``chaos_survived_faults`` bench key both drive this)::

    from triton_dist_tpu.resilience import chaos
    report = chaos.run_soak(make_engine, seed=7, ticks=200,
                            n_faults=12, restore_at=90)
    assert report.survived_faults >= 10
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from triton_dist_tpu.resilience import faults

__all__ = ["ChaosEvent", "ChaosReport", "FleetChaosReport",
           "SupervisedChaosReport", "InvariantViolation",
           "DEFAULT_FAULT_KINDS", "TIER_FAULT_KINDS",
           "FLEET_FAULT_KINDS", "MK_FAULT_KINDS",
           "INTEGRITY_FAULT_KINDS", "SUPERVISED_FAULT_KINDS",
           "check_invariants", "check_fleet_invariants",
           "run_soak", "run_fleet_soak", "run_integrity_drill",
           "run_supervised_soak", "supervised_tiny_factory"]


class InvariantViolation(AssertionError):
    """A serving invariant broke under the soak — the bug class this
    harness exists to catch (leaked page, drifted refcount, corrupted
    mirror, unresolved request, token divergence)."""


# (name, op, fault_kind): the injectable menu. ``fail_call`` models a
# dropped transfer/dispatch; ``timeout_call`` a wedged one (the
# deterministic watchdog-miss stand-in — see faults.py); transient
# events target only the FIRST call of the tick (k=0: absorbed by one
# retry), hard events every call of the tick (k=None: retries exhaust,
# containment/failover takes over). ``kill_prefill_worker`` is the
# dead-role event (DisaggServingEngine.fail_prefill_worker).
DEFAULT_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                           ...] = (
    ("drop_migration", "page_migration", "fail_call"),
    ("wedge_migration", "page_migration", "timeout_call"),
    ("drop_chunk", "chunked_prefill", "fail_call"),
    ("delay_chunk", "chunked_prefill", "timeout_call"),
    ("drop_decode", "serving_decode", "fail_call"),
    ("wedge_decode", "serving_decode", "timeout_call"),
    ("kill_prefill_worker", None, None),
)

# The tiered-KV additions (engines built with ``kv_tiers``): dropped /
# wedged tier transfers — a faulted demote drops the (recomputable)
# prefix content, a faulted prefetch falls back to recompute, a
# faulted park leaves the request running, a faulted resume re-enters
# via the deterministic re-prefill; all token-exact by construction.
# Kept separate so un-tiered soaks (and their seeded schedules) stay
# byte-identical; pass ``kinds=DEFAULT_FAULT_KINDS + TIER_FAULT_KINDS``
# for a tiered engine.
TIER_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                        ...] = (
    ("drop_tier_transfer", "tier_transfer", "fail_call"),
    ("wedge_tier_transfer", "tier_transfer", "timeout_call"),
)

# The megakernel-lane menu (``run_soak`` over a paged
# ``MegaKernelEngine`` serving factory): the persistent lane has no
# migration/chunk/worker ops, so only the joint decode dispatch (the
# prefill LANE rides it too) is injectable — dropped and wedged
# decode/verification launches. Kept separate so layer-path soaks'
# seeded schedules stay byte-identical.
MK_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                      ...] = (
    ("drop_decode", "serving_decode", "fail_call"),
    ("wedge_decode", "serving_decode", "timeout_call"),
    ("drop_verify", "spec_verify", "fail_call"),
    ("wedge_verify", "spec_verify", "timeout_call"),
)

# The fleet-level menu (``run_fleet_soak`` over a ``FleetRouter``):
# dropped / wedged router→fleet links (``fleet_route`` — the send that
# places a request on a fleet's queue), dropped / wedged cross-fleet
# session handoffs (``fleet_handoff`` — the parked-payload hop during
# failover and drain/restore), and whole-fleet kills — a seeded coin
# picks reachable (parked-tier handoff path) vs vanished (deterministic
# re-prefill path). Kept separate so ``run_soak``'s seeded schedules
# stay byte-identical.
FLEET_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                         ...] = (
    ("kill_fleet", None, None),
    ("drop_route", "fleet_route", "fail_call"),
    ("wedge_route", "fleet_route", "timeout_call"),
    ("drop_handoff", "fleet_handoff", "fail_call"),
    ("wedge_handoff", "fleet_handoff", "timeout_call"),
)

# The payload-integrity menu (ISSUE 16): a seeded single-bit flip on
# the payload crossing each serialization boundary, detected by the
# crc32c digest check at the consuming edge (never by luck) and routed
# into that boundary's existing recovery path — tier get quarantines
# the entry and recomputes, a corrupted migration retries then
# re-prefills, a corrupted handoff hop retries against the victim's
# still-authoritative entry then re-prefills. Transient events (k=0)
# corrupt only the first attempt; hard ones (k=None) every attempt.
# Kept separate so existing soaks' seeded schedules stay
# byte-identical; compose per engine shape (tier kinds need
# ``kv_tiers``, handoff kinds a :class:`FleetRouter`).
INTEGRITY_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                             ...] = (
    ("corrupt_tier_transfer", "tier_transfer", "corrupt_payload"),
    ("corrupt_migration", "page_migration", "corrupt_payload"),
    ("corrupt_handoff", "fleet_handoff", "corrupt_payload"),
)

# The process-level menu (``run_supervised_soak`` over a
# :class:`~triton_dist_tpu.resilience.supervisor.ServingSupervisor`):
# events fire at seeded ACK-COUNT thresholds (real child processes
# make tick counts nondeterministic; the acked-token stream is the
# deterministic clock the parent actually observes). ``kill_child``
# is a parent-side SIGKILL (the OOM-killer model), ``crash_child`` an
# in-child ``os._exit`` (the segfault model — exercises the nonzero
# exit path), ``stall_child`` a heartbeat stall (wedged thread),
# ``corrupt_migration`` a one-tick in-child payload corruption.
SUPERVISED_FAULT_KINDS: Tuple[Tuple[str, Optional[str],
                                    Optional[str]], ...] = (
    ("kill_child", None, None),
    ("crash_child", None, None),
    ("stall_child", None, None),
    ("corrupt_migration", "page_migration", "corrupt_payload"),
)


@dataclasses.dataclass
class ChaosEvent:
    """One scheduled fault: where, what, and what it observably did.

    ``at`` is the serving engine's clock reading when the fault fired
    (None until then) — soak runs are trace-inspectable: the same
    timestamp domain the request spans and retry events use, so a
    fault lines up against its victims in the merged timeline."""

    tick: int
    name: str
    op: Optional[str]
    kind: Optional[str]       # fail_call | timeout_call | None (kill)
    transient: bool
    fired: bool = False       # the fault had a chance to act this tick
    observed: bool = False    # a failure/retry counter moved this tick
    at: Optional[float] = None  # engine-clock stamp when fired


@dataclasses.dataclass
class ChaosReport:
    """What a completed soak measured (a completed soak already means:
    server alive, invariants held every tick, all requests terminal,
    survivors token-exact — violations raise instead)."""

    seed: int
    ticks: int
    events: List[ChaosEvent]
    faults_injected: int
    survived_faults: int
    requests: Dict[str, int]
    counters: Dict[str, int]
    invariant_checks: int
    token_exact_requests: int
    restored_at: Optional[int]


@dataclasses.dataclass
class FleetChaosReport:
    """What a completed fleet soak measured (completion already means:
    router alive, per-tick fleet invariants held, every request
    terminal, done requests token-exact vs the single-engine oracle).
    ``requests`` adds the ``shed`` class; ``router`` is the final
    router counter dict (failovers, handoff resumes, sheds...)."""

    seed: int
    ticks: int
    fleets: int
    events: List[ChaosEvent]
    faults_injected: int
    survived_faults: int
    requests: Dict[str, int]
    router: Dict[str, int]
    invariant_checks: int
    token_exact_requests: int
    scaled_at: Optional[int]


@dataclasses.dataclass
class SupervisedChaosReport:
    """What a completed supervised soak measured (completion already
    means: every request ``done`` and token-exact vs the in-process
    oracle across every child kill/stall/corruption — violations
    raise).  ``supervisor`` is the parent's final counter view
    (restarts, crashes, stalls, dedup_dropped, restore_fallbacks,
    acked_tokens, last_recovery_ms...)."""

    seed: int
    events: List["ChaosEvent"]
    faults_injected: int
    survived_faults: int
    requests: Dict[str, int]
    supervisor: Dict[str, object]
    token_exact_requests: int


# ---------------------------------------------------------------------------
# Invariant checker
# ---------------------------------------------------------------------------

def _check_manager(mgr, name: str) -> None:
    from triton_dist_tpu.serving.blocks import SCRATCH_PAGE

    free = list(mgr._free)
    if len(set(free)) != len(free):
        raise InvariantViolation(
            f"[{name}] duplicate page ids on the free list: {free}")
    if SCRATCH_PAGE in free:
        raise InvariantViolation(
            f"[{name}] the reserved scratch page leaked into the free "
            "list")
    held = Counter(pid for pages in mgr._slot_pages.values()
                   for pid in pages)
    if SCRATCH_PAGE in held:
        raise InvariantViolation(
            f"[{name}] the scratch page was allocated to a slot")
    prefix_pids = set(mgr._prefix.values())
    free_set = set(free)
    for pid in range(1, mgr.num_pages):
        want = held.get(pid, 0) + (1 if pid in prefix_pids else 0)
        have = mgr._refs.get(pid, 0)
        if have != want:
            raise InvariantViolation(
                f"[{name}] page {pid} refcount {have} != observable "
                f"holders {want} (slots={held.get(pid, 0)}, "
                f"prefix={pid in prefix_pids})")
        if (pid in free_set) == (want > 0):
            raise InvariantViolation(
                f"[{name}] page {pid} {'free but referenced' if want else 'unreferenced but not free — LEAKED'}")
    if len(free) + len(mgr._refs) != mgr.num_pages - 1:
        raise InvariantViolation(
            f"[{name}] page accounting broke: {len(free)} free + "
            f"{len(mgr._refs)} referenced != {mgr.num_pages - 1} "
            "usable pages")
    staged = {pid for pairs in mgr._pending_prefix.values()
              for _, pid in pairs}
    if staged & prefix_pids:
        raise InvariantViolation(
            f"[{name}] page(s) {staged & prefix_pids} both staged and "
            "published — the two-phase prefix protocol broke")
    for slot, pairs in mgr._pending_prefix.items():
        owned = set(mgr._slot_pages.get(slot, []))
        for _, pid in pairs:
            if pid not in owned:
                raise InvariantViolation(
                    f"[{name}] staged prefix page {pid} not owned by "
                    f"its staging slot {slot}")
    for slot, n_tok in mgr._slot_tokens.items():
        cap = len(mgr._slot_pages.get(slot, [])) * mgr.page
        if n_tok > cap:
            raise InvariantViolation(
                f"[{name}] slot {slot} accounts {n_tok} tokens over "
                f"{cap} allocated-page capacity")


def check_invariants(srv) -> None:
    """One full sweep of the serving invariants (see module
    docstring). Call between ticks — the structures are host-side, so
    this never syncs the device."""
    if srv.manager is not None:
        _check_manager(srv.manager, "decode-pool")
    workers = getattr(srv, "prefill_workers", None) or []
    for i, w in enumerate(workers):
        if not w.dead and w.manager is not srv.manager:
            _check_manager(w.manager, f"prefill-pool[{i}]")
    spec_slack = max(1, getattr(srv, "spec_k", 0) or 0)
    for s in range(srv.num_slots):
        h = srv.sched.slots.get(s)
        if h is None:
            if srv._live[s] != 0:
                raise InvariantViolation(
                    f"slot {s} live={srv._live[s]} with no handle")
            continue
        if h.slot != s:
            raise InvariantViolation(
                f"slot {s} handle claims slot {h.slot}")
        if h.status == "running":
            if srv._live[s] != 1:
                raise InvariantViolation(
                    f"running slot {s} has live={srv._live[s]}")
            want = len(h.request.prompt) + len(h.tokens) - 1
            if srv._lens[s] != want:
                raise InvariantViolation(
                    f"slot {s} length mirror {srv._lens[s]} != "
                    f"prompt+generated-fed {want}")
            if srv.manager is not None:
                n = srv.manager._slot_tokens.get(s)
                if n is None or not (srv._lens[s] <= n
                                     <= srv._lens[s] + spec_slack):
                    raise InvariantViolation(
                        f"slot {s} allocator tokens {n} drifted from "
                        f"length mirror {srv._lens[s]} (allowed slack "
                        f"{spec_slack})")
        elif h.status in ("prefill", "migrating", "resuming"):
            if srv._live[s] != 0 and not srv.mega:
                raise InvariantViolation(
                    f"parked ({h.status}) slot {s} is marked live")
        else:
            raise InvariantViolation(
                f"slot {s} holds a terminal handle ({h.status})")
    for h in srv.sched.queue:
        if h.slot is not None:
            raise InvariantViolation(
                f"queued request {h.request.request_id} still holds "
                f"slot {h.slot}")
    _check_tiers(srv)
    _check_arena(srv)
    _check_slo(srv)


def _check_slo(srv) -> None:
    """Tenant-fairness sweep (engines built with ``slo=...``):

    - **single ownership**: a tenant-queued handle is ``"queued"``,
      holds no slot, and is never simultaneously in the scheduler
      queue or a slot (the relocation in ``SLOScheduler.submit`` /
      ``pump`` must move, not copy);
    - **bounded queues**: each tenant queue within its spec's
      ``max_queue``;
    - **bucket sanity**: the admission token bucket stays inside
      [0, burst];
    - **quota conservation**: ``tokens == granted - charged`` — the
      decode-quota bucket algebra neither mints nor leaks quota;
    - **no starvation under aging**: no quota-eligible queued handle
      has waited beyond ``slo.starve_limit_s`` (aging promotes it to
      the interactive rank long before that);
    - **preemption debt**: every park-path preemptee is still parked
      (and in the engine's parked registry) — it WILL be auto-resumed,
      so "preempted requests always reach a terminal status" holds.
    """
    slo = getattr(srv, "slo", None)
    if slo is None:
        return
    in_sched = {id(h) for h in srv.sched.queue}
    in_slots = {id(h) for h in srv.sched.slots.values()}
    now = srv.sched.now()
    for st in slo.registry.states():
        name = st.spec.name
        if len(st.queue) > st.spec.max_queue:
            raise InvariantViolation(
                f"tenant {name!r} queue {len(st.queue)} over its "
                f"bound {st.spec.max_queue}")
        if not (-1e-9 <= st.bucket <= st.spec.burst + 1e-9):
            raise InvariantViolation(
                f"tenant {name!r} admission bucket {st.bucket} left "
                f"[0, {st.spec.burst}]")
        if st.spec.decode_quota is not None:
            if abs(st.tokens - (st.granted - st.charged)) > 1e-6:
                raise InvariantViolation(
                    f"tenant {name!r} quota not conserved: bucket "
                    f"{st.tokens} != granted {st.granted} - charged "
                    f"{st.charged}")
            if st.tokens > st.quota_burst + 1e-9:
                raise InvariantViolation(
                    f"tenant {name!r} quota bucket {st.tokens} over "
                    f"its depth {st.quota_burst}")
        for h in st.queue:
            rid = h.request.request_id
            if h.status != "queued" or h.slot is not None:
                raise InvariantViolation(
                    f"tenant-queued request {rid} is {h.status!r} "
                    f"with slot {h.slot}")
            if id(h) in in_sched or id(h) in in_slots:
                raise InvariantViolation(
                    f"request {rid} owned by tenant {name!r} queue "
                    "AND the scheduler (dual ownership)")
            if st.quota_ok() and (now - h.queued_at
                                  > slo.starve_limit_s):
                raise InvariantViolation(
                    f"request {rid} (tenant {name!r}) starved: queued "
                    f"{now - h.queued_at:.3f}s > starve limit "
                    f"{slo.starve_limit_s}s with quota available")
    for h in slo._parked_by_slo:
        rid = h.request.request_id
        if h.status != "parked" or rid not in srv._parked:
            raise InvariantViolation(
                f"SLO-preempted request {rid} lost its park "
                f"(status={h.status!r}) — the auto-resume debt broke")


def _check_arena(srv) -> None:
    """Arena-coherence sweep (megakernel engines): the described
    memory layout must stay sound under faults —

    - **region disjointness**: the arena schema's in-arena regions
      tile [0, rows) with no overlap/gap (``ArenaSchema
      .check_disjoint``). The schema is build-time-frozen, so this
      half re-asserts a static invariant — it exists to catch a
      FUTURE builder change that starts mutating layouts at serve
      time, not a runtime fault (cheap: pure host arithmetic);
    - **scale/page consistency** (quantized pools): every
      per-(layer, page, kv_head) dequant scale is finite and > 0
      (write_kv's running-amax maintenance can never produce 0 or a
      NaN — either would silently zero or poison a page's dequant);
    - **monotonic counters**: the in-arena MoE router counters only
      ever grow between sweeps (the epilogue accumulates; a decrease
      means a clobbered counter region).
    """
    if not getattr(srv, "mega", False):
        return
    eng = srv.engine
    for b in (eng.builder, getattr(eng, "verify_builder", None)):
        if b is None:
            continue
        try:
            b.schema.check_disjoint()
        except ValueError as e:
            raise InvariantViolation(f"arena schema broke: {e}") from e
    if getattr(eng, "k_scale", None) is not None:
        for name in ("k_scale", "v_scale"):
            a = np.asarray(getattr(eng, name))
            if not np.isfinite(a).all() or (a <= 0).any():
                raise InvariantViolation(
                    f"quantized pool {name} left the sane range "
                    f"(finite, > 0): min={a.min()}, "
                    f"finite={np.isfinite(a).all()}")
    if getattr(srv.cfg, "is_moe", False) and hasattr(eng,
                                                     "expert_counts"):
        counts = eng.expert_counts()
        prev = getattr(srv, "_mk_counts_sweep", None)
        if prev is not None and (counts < prev).any():
            raise InvariantViolation(
                f"megakernel expert counters went BACKWARDS: "
                f"{prev.tolist()} -> {counts.tolist()}")
        srv._mk_counts_sweep = counts


def _check_tiers(srv) -> None:
    """Tier-coherence sweep (engines built with ``kv_tiers``): every
    payload lives in exactly ONE authoritative tier, no HBM free-list
    entry is backed by a pending (uncommitted) demotion, and the
    parked registry and tier store agree."""
    tiers = getattr(srv, "tiers", None)
    if tiers is None:
        return
    try:
        # Staged-demotion window empty between ticks + host/disk
        # disjoint + capacity bounds (the store's own algebra).
        tiers.check_coherence()
    except AssertionError as e:
        raise InvariantViolation(str(e)) from e
    # Exactly-one-tier across the hierarchy: a key committed in the
    # HBM prefix cache must not ALSO be tier-resident (demotion pops
    # it from HBM, promotion pops it from the tier).
    if srv.manager is not None:
        hbm_keys = set(srv.manager._prefix)
        for k in tiers.keys():
            k = tuple(k)
            if k[0] == "prefix" and k[1] in hbm_keys:
                raise InvariantViolation(
                    f"prefix key resident in BOTH the HBM cache and "
                    f"the tier store: {k[1]!r}")
    parked = getattr(srv, "_parked", {})
    for rid, h in parked.items():
        if h.status != "parked" or h.slot is not None:
            raise InvariantViolation(
                f"parked registry holds request {rid} in state "
                f"{h.status!r} (slot={h.slot})")
        if ("session", rid) not in tiers:
            raise InvariantViolation(
                f"parked request {rid} has no tier payload — its KV "
                "is unrecoverable")
        if h in srv.sched.queue:
            raise InvariantViolation(
                f"parked request {rid} is also queued")
    for k in tiers.keys():
        k = tuple(k)
        if k[0] != "session":
            continue
        e = tiers.entry(k)
        if e.pinned and k[1] not in parked and not any(
                getattr(h, "resume_key", None) == k
                for h in list(srv.sched.queue)
                + list(srv.sched.slots.values())):
            raise InvariantViolation(
                f"pinned session payload {k[1]!r} has no parked or "
                "resuming owner — leaked tier pages")


def check_fleet_invariants(router, tracked=None) -> None:
    """Fleet-level sweep over a :class:`~triton_dist_tpu.serving.
    router.FleetRouter` — the per-fleet :func:`check_invariants` plus
    the cross-fleet algebra:

    - every in-flight request is owned by exactly ONE place (the
      router queue, or one live fleet's queue / slots / parked
      registry) — never two;
    - no session payload is pinned in two fleets' tier stores at once
      (the cross-fleet handoff pops the source before the target
      resumes);
    - the router's health view is consistent with liveness (a fleet
      marked dead carries a dead health verdict; a declared-dead
      health verdict on a live fleet means the failover was skipped);
    - the drain gate holds: a draining fleet admits nothing (its
      queue stays empty);
    - router-queued handles are slotless and non-terminal.

    ``tracked`` (optional handles) must each be terminal or owned
    somewhere.
    """
    seen: Dict[str, str] = {}

    def note(h, where):
        rid = h.request.request_id
        if rid in seen:
            raise InvariantViolation(
                f"request {rid} owned by BOTH {seen[rid]} and {where}")
        seen[rid] = where

    # Cross-fleet session uniqueness first: a payload pinned on two
    # fleets is its own violation class (a handoff that copied
    # without popping), reported before the ownership scan can fold
    # it into a generic double-ownership message.
    session_owner: Dict[tuple, int] = {}
    for f in router.fleets:
        if f.dead or f.engine.tiers is None:
            continue
        for k in f.engine.tiers.keys():
            k = tuple(k)
            if k[0] != "session":
                continue
            if k in session_owner:
                raise InvariantViolation(
                    f"session payload {k[1]!r} pinned on BOTH fleet "
                    f"{session_owner[k]} and fleet {f.id}")
            session_owner[k] = f.id
    for h in router.queue:
        if h.slot is not None:
            raise InvariantViolation(
                f"router-queued request {h.request.request_id} still "
                f"holds slot {h.slot}")
        if h.done:
            raise InvariantViolation(
                f"terminal request {h.request.request_id} "
                f"({h.status}) sits in the router queue")
        note(h, "router-queue")
    for f in router.fleets:
        if f.dead:
            if not f.health.dead:
                raise InvariantViolation(
                    f"fleet {f.id} marked dead without a dead health "
                    "verdict")
            continue
        if f.health.dead:
            raise InvariantViolation(
                f"fleet {f.id} health declared dead "
                f"({f.health.cause!r}) but the router still routes to "
                "it — failover skipped")
        check_invariants(f.engine)
        if f.draining and f.engine.sched.queue:
            raise InvariantViolation(
                f"draining fleet {f.id} admitted new work (drain gate "
                f"broke): queue={[h.request.request_id for h in f.engine.sched.queue]}")
        for h in f.engine.sched.queue:
            note(h, f"fleet{f.id}-queue")
        for h in f.engine.sched.slots.values():
            note(h, f"fleet{f.id}-slot")
        for h in f.engine._parked.values():
            note(h, f"fleet{f.id}-parked")
        if getattr(f.engine, "slo", None) is not None:
            for h in f.engine.slo.queued_handles():
                note(h, f"fleet{f.id}-slo-queue")
    for h in tracked or ():
        if not h.done and h.request.request_id not in seen:
            raise InvariantViolation(
                f"in-flight request {h.request.request_id} "
                f"({h.status}) owned by NO fleet and not router-"
                "queued — lost")


# ---------------------------------------------------------------------------
# The soak
# ---------------------------------------------------------------------------

def _oracle_tokens(engine, prompt: Sequence[int], gen_len: int,
                   cache: Dict) -> List[int]:
    import jax.numpy as jnp

    key = (tuple(prompt), gen_len)
    if key not in cache:
        n = engine.mesh.shape[engine.axis]
        ids = np.tile(np.asarray([list(prompt)], np.int32), (n, 1))
        cache[key] = np.asarray(
            engine.serve(jnp.asarray(ids),
                         gen_len=gen_len))[0].tolist()
    return cache[key]


def _note_fault(srv, ev: ChaosEvent) -> None:
    """Land the injected fault in the engine's telemetry event log —
    the soak's faults and the serving spans share ONE timeline, so a
    retry burst or a failover reads directly against the fault that
    caused it."""
    srv.obs.event("chaos_fault", tick=ev.tick, name=ev.name,
                  op=ev.op, fault_kind=ev.kind,
                  transient=ev.transient)


def _plan_for(ev: ChaosEvent) -> faults.FaultPlan:
    k = 0 if ev.transient else None
    return faults.FaultPlan(
        name=f"chaos-{ev.name}",
        faults=(faults.Fault(ev.kind, op=ev.op, k=k),))


def run_soak(factory: Callable[[], object], *, seed: int = 0,
             ticks: int = 200, n_faults: int = 10,
             arrival_p: float = 0.35,
             kinds: Sequence = DEFAULT_FAULT_KINDS,
             transient_p: float = 0.5,
             gen_choices: Sequence[int] = (2, 3, 4, 6, 8),
             prompt_reuse_p: float = 0.3,
             restore_at: Optional[int] = None,
             max_drain_steps: Optional[int] = None,
             park_p: float = 0.0,
             tenants: Sequence[str] = ()) -> ChaosReport:
    """Drive ``ticks`` serving steps of seeded mixed traffic under
    ``n_faults`` seeded fault events, checking every invariant after
    every tick, then drain fault-free and verify terminal resolution +
    token-exactness of all survivors against the fault-free oracle.

    ``factory`` builds the serving engine (a fresh, identically-
    configured one each call — ``restore_at`` uses it again for the
    mid-soak kill/checkpoint/restore drill). Greedy traffic only (the
    exactness oracle is ``Engine.serve``; megakernel factories get a
    fresh fault-free serving engine instead — pass
    ``kinds=MK_FAULT_KINDS`` there, and the per-tick sweep adds the
    arena-coherence check). Raises
    :class:`InvariantViolation` (or the server's own crash) on any
    violation; returns a :class:`ChaosReport` otherwise.

    ``park_p`` > 0 (engines built with ``kv_tiers``) additionally
    parks a seeded-random running request with that per-tick
    probability and resumes it 1–4 ticks later — resumed sessions
    flow through the same token-exactness gate as everything else, so
    a park/resume byte drift fails the soak. Anything still parked
    when the soak ends resumes before the drain.

    ``tenants`` non-empty labels each submission with a seeded-random
    tenant from the list and a seeded-random ``slo_class`` — the
    multi-tenant soak mode for engines built with ``slo=...`` (the
    per-tick sweep then exercises the tenant-fairness invariants:
    quota conservation, bounded queues, no starvation, preemption
    debt). The extra rng draws are gated on the parameter, so a
    ``tenants=()`` soak's schedule stays byte-identical to the
    pre-SLO soaks. Greedy decoding means scheduling order never
    changes tokens — the oracle gate is unchanged.
    """
    rng = np.random.RandomState(seed)
    srv = factory()
    # Megakernel engines soak too (pass kinds=MK_FAULT_KINDS — the
    # persistent lane has no migration/chunk ops): the oracle is a
    # fresh fault-free serving engine from the same factory (the mk
    # engine has no Engine.serve), and the per-tick sweep additionally
    # runs the arena-coherence check (_check_arena).
    mk_oracle = {"srv": None} if srv.mega else None
    vocab = srv.cfg.vocab_size
    cap = min(srv.p_max * srv.page, srv.max_len)
    max_gen = max(g for g in gen_choices)
    max_prompt = max(1, min(12, cap - max_gen - 1))
    kinds = list(kinds)
    fault_ticks = sorted(
        int(t) for t in rng.choice(np.arange(1, max(ticks, 2)),
                                   size=min(n_faults, ticks - 1),
                                   replace=False))
    schedule: Dict[int, ChaosEvent] = {}
    for t in fault_ticks:
        name, op, kind = kinds[int(rng.randint(len(kinds)))]
        schedule[t] = ChaosEvent(
            tick=t, name=name, op=op, kind=kind,
            transient=bool(rng.rand() < transient_p))

    tracked: List[Tuple[Tuple[int, ...], int, object]] = []
    prior_prompts: List[List[int]] = []
    oracle_cache: Dict = {}
    invariant_checks = 0
    restored_tick = None

    def _submit_maybe():
        nonlocal prior_prompts
        if rng.rand() >= arrival_p:
            return
        if prior_prompts and rng.rand() < prompt_reuse_p:
            prompt = list(prior_prompts[
                int(rng.randint(len(prior_prompts)))])
        else:
            n = int(rng.randint(1, max_prompt + 1))
            prompt = [int(x) for x in rng.randint(0, vocab, n)]
            prior_prompts.append(prompt)
        gen = int(gen_choices[int(rng.randint(len(gen_choices)))])
        kw = {}
        if tenants:
            # Gated draws: a tenants=() soak never reaches these, so
            # its schedule stays byte-identical to pre-SLO soaks.
            kw["tenant"] = str(tenants[int(rng.randint(len(tenants)))])
            kw["slo_class"] = ("interactive", "standard",
                              "batch")[int(rng.randint(3))]
        from triton_dist_tpu.serving.scheduler import QueueFullError

        try:
            h = srv.submit(prompt, max_new_tokens=gen, **kw)
        except QueueFullError:
            return      # backpressure is correct behaviour, not a bug
        tracked.append((tuple(prompt), gen, h))

    def _tick_counters():
        return {k: srv.stats_counters[k] for k in
                ("retries", "comm_timeouts", "failovers")} | {
                    k: srv.sched.counters[k] for k in
                    ("failed", "timed_out")}

    # Seeded park/resume drill state: parked handles and the tick
    # each one resumes at. All rng draws are gated on park_p, so a
    # park_p=0 soak's random sequence (and therefore its entire
    # schedule) is byte-identical to the pre-tier soaks.
    resume_at: Dict[int, List[object]] = {}
    parked: List[object] = []

    def _park_maybe(tick: int):
        if not park_p or getattr(srv, "tiers", None) is None:
            return
        for h in resume_at.pop(tick, []):
            if h.status == "parked":
                srv.resume(h)
                parked.remove(h)
        if rng.rand() >= park_p:
            return
        cands = [h for h in srv.sched.running()
                 if h.status == "running" and h.tokens]
        if not cands:
            return
        h = cands[int(rng.randint(len(cands)))]
        from triton_dist_tpu.resilience.watchdog import CommTimeoutError
        from triton_dist_tpu.serving.tiers import TierFullError

        try:
            srv.park(h)
        except (TierFullError, CommTimeoutError,
                faults.InjectedFault):
            # Correct containment, not a bug: a full tier or a
            # dropped/wedged offload transfer aborts the park and the
            # request KEEPS RUNNING (the two-phase offload frees
            # nothing before the transfer commits) — on fault ticks
            # _park_maybe runs INSIDE the injection scope precisely
            # to exercise this.
            return
        parked.append(h)
        resume_at.setdefault(
            tick + 1 + int(rng.randint(4)), []).append(h)

    for tick in range(ticks):
        if restore_at is not None and tick == restore_at:
            # The mid-run kill/restore drill: snapshot, throw the
            # engine away, restore into a fresh one (same weights by
            # construction of the factory), rebind tracked handles.
            snap = srv.checkpoint()
            srv = factory()
            revived = {h.request.request_id: h
                       for h in srv.restore(snap)}
            tracked = [(p, g, revived.get(h.request.request_id, h))
                       for p, g, h in tracked]
            parked = [revived.get(h.request.request_id, h)
                      for h in parked]
            resume_at = {t: [revived.get(h.request.request_id, h)
                             for h in hs]
                         for t, hs in resume_at.items()}
            restored_tick = tick
            srv.obs.event("chaos_restore", tick=tick,
                          revived=len(revived))
        _submit_maybe()
        ev = schedule.get(tick)
        if ev is None:
            _park_maybe(tick)
            srv.step()
        elif ev.name == "kill_prefill_worker":
            ev.at = srv.sched.now()
            _note_fault(srv, ev)
            killed = bool(getattr(srv, "fail_prefill_worker",
                                  lambda: False)())
            ev.fired, ev.observed = True, killed
            _park_maybe(tick)
            srv.step()
        else:
            before = _tick_counters()
            ev.at = srv.sched.now()
            _note_fault(srv, ev)
            with faults.inject(_plan_for(ev)):
                # The park drill runs INSIDE the fault scope: a tier
                # fault can hit the park offload itself (aborted park,
                # request keeps running) as well as the step's
                # demotes/prefetches.
                _park_maybe(tick)
                srv.step()
            ev.fired = True
            ev.observed = _tick_counters() != before
        check_invariants(srv)
        invariant_checks += 1

    # Drain fault-free: everything still in flight must resolve —
    # parked sessions resume first (a park with no resume is a
    # deliberate suspension, not a drain blocker; the drill resumes
    # everything so its token-exactness is checked).
    for h in parked:
        if h.status == "parked":
            srv.resume(h)
    parked.clear()
    budget = max_drain_steps or (ticks * 4 + 200)
    for _ in range(budget):
        if srv._drained():
            break
        srv.step()
        check_invariants(srv)
        invariant_checks += 1
    else:
        raise InvariantViolation(
            f"serving loop failed to drain within {budget} post-soak "
            f"steps (queue={len(srv.sched.queue)}, "
            f"slots={sorted(srv.sched.slots)})")

    statuses = Counter(h.status for _, _, h in tracked)
    unresolved = [h.request.request_id for _, _, h in tracked
                  if not h.done]
    if unresolved:
        raise InvariantViolation(
            f"request(s) never terminally resolved: {unresolved}")
    token_exact = 0
    for prompt, gen, h in tracked:
        if h.status != "done":
            continue
        if mk_oracle is not None:
            key = (tuple(prompt), gen)
            if key not in oracle_cache:
                if mk_oracle["srv"] is None:
                    mk_oracle["srv"] = factory()
                oracle_cache[key] = mk_oracle["srv"].generate(
                    [list(prompt)], max_new_tokens=gen)[0]
            want = oracle_cache[key]
        else:
            want = _oracle_tokens(srv.engine, prompt, gen,
                                  oracle_cache)
        if list(h.tokens) != list(want):
            raise InvariantViolation(
                f"survivor {h.request.request_id} diverged from the "
                f"fault-free oracle: {h.tokens} != {want} "
                f"(prompt={list(prompt)})")
        token_exact += 1

    events = [schedule[t] for t in fault_ticks]
    return ChaosReport(
        seed=seed, ticks=ticks, events=events,
        faults_injected=len(events),
        survived_faults=sum(1 for e in events if e.fired),
        requests={"submitted": len(tracked), **{
            k: statuses.get(k, 0)
            for k in ("done", "failed", "timeout")}},
        counters={k: srv.stats_counters[k] for k in
                  ("retries", "failovers", "comm_timeouts",
                   "preemptions", "slo_preemptions",
                   "restored_requests", "parks", "resumes")},
        invariant_checks=invariant_checks,
        token_exact_requests=token_exact,
        restored_at=restored_tick)


def run_fleet_soak(factory: Callable[[], object], *,
                   fleets: int = 2, seed: int = 0, ticks: int = 200,
                   n_faults: int = 10, arrival_p: float = 0.35,
                   kinds: Sequence = (FLEET_FAULT_KINDS
                                      + TIER_FAULT_KINDS),
                   transient_p: float = 0.5,
                   gen_choices: Sequence[int] = (2, 3, 4, 6, 8),
                   prompt_reuse_p: float = 0.4,
                   deadline_p: float = 0.5,
                   scale_at: Optional[Tuple[int, int]] = None,
                   max_drain_steps: Optional[int] = None,
                   router_kw: Optional[Dict] = None
                   ) -> FleetChaosReport:
    """Fleet-level chaos soak: drive ``ticks`` router steps of seeded
    mixed traffic through a :class:`~triton_dist_tpu.serving.router.
    FleetRouter` over ``fleets`` replicas of ``factory()``, under a
    seeded schedule of whole-fleet kills (a seeded coin picks
    reachable — the parked-tier handoff path — vs vanished — the
    re-prefill path; never the last live fleet), dropped/wedged
    ``fleet_route`` / ``fleet_handoff`` links, and tier faults.
    :func:`check_fleet_invariants` sweeps after EVERY tick, the run
    drains fault-free, every request must reach a terminal state
    (``shed`` counts — graceful degradation is a terminal verdict,
    not a hang), and every ``done`` request's tokens must equal the
    single-engine ``Engine.serve`` oracle.

    ``deadline_p``: fraction of requests submitted with a (far)
    deadline — the interactive class, so fleet-loss shedding has both
    classes to discriminate. ``scale_at=(tick, R')`` additionally
    runs the drain/restore autoscale drill mid-soak. Raises
    :class:`InvariantViolation` on any violation; returns a
    :class:`FleetChaosReport` otherwise.
    """
    from triton_dist_tpu.serving.router import FleetRouter
    from triton_dist_tpu.serving.scheduler import QueueFullError

    rng = np.random.RandomState(seed)
    router = FleetRouter(factory, fleets=fleets, **(router_kw or {}))
    oracle_engine = router.fleets[0].engine.engine
    vocab = router.fleets[0].engine.cfg.vocab_size
    ref = router.fleets[0].engine
    cap = min(ref.p_max * ref.page, ref.max_len)
    max_gen = max(g for g in gen_choices)
    max_prompt = max(1, min(12, cap - max_gen - 1))
    kinds = list(kinds)
    fault_ticks = sorted(
        int(t) for t in rng.choice(np.arange(1, max(ticks, 2)),
                                   size=min(n_faults, ticks - 1),
                                   replace=False))
    schedule: Dict[int, ChaosEvent] = {}
    for t in fault_ticks:
        name, op, kind = kinds[int(rng.randint(len(kinds)))]
        schedule[t] = ChaosEvent(
            tick=t, name=name, op=op, kind=kind,
            transient=bool(rng.rand() < transient_p))

    tracked: List[Tuple[Tuple[int, ...], int, object]] = []
    prior_prompts: List[List[int]] = []
    oracle_cache: Dict = {}
    invariant_checks = 0
    scaled_tick = None

    def _submit_maybe():
        if rng.rand() >= arrival_p:
            return
        if prior_prompts and rng.rand() < prompt_reuse_p:
            # Prompt reuse = the affinity signal: same-prefix traffic
            # should keep landing on the fleet holding the pages.
            prompt = list(prior_prompts[
                int(rng.randint(len(prior_prompts)))])
        else:
            n = int(rng.randint(1, max_prompt + 1))
            prompt = [int(x) for x in rng.randint(0, vocab, n)]
            prior_prompts.append(prompt)
        gen = int(gen_choices[int(rng.randint(len(gen_choices)))])
        # Interactive (far-deadline) vs batch class — both present so
        # fleet-loss shedding has an ordering to exercise.
        deadline = (router.obs.now() + 1e6
                    if rng.rand() < deadline_p else None)
        try:
            h = router.submit(prompt, max_new_tokens=gen,
                              deadline=deadline)
        except QueueFullError:
            return      # backpressure is correct behaviour, not a bug
        tracked.append((tuple(prompt), gen, h))

    def _fault_tick(ev: ChaosEvent):
        before = (dict(router.counters),
                  tuple(f.health.total_failures
                        for f in router.fleets))
        ev.at = router.obs.now()
        router.obs.event("chaos_fault", tick=ev.tick, name=ev.name,
                         op=ev.op, fault_kind=ev.kind,
                         transient=ev.transient)
        if ev.name == "kill_fleet":
            live = router._live_fleets()
            if len(live) < 2:
                ev.fired = False        # nothing safely killable
                _submit_maybe()
                router.step()
                return
            victim = live[int(rng.randint(len(live)))]
            reachable = bool(rng.rand() < 0.5)
            router.kill_fleet(victim.id, reachable=reachable)
            ev.fired = ev.observed = True
            _submit_maybe()
            router.step()
            return
        # Route/handoff/tier faults: the injection window covers the
        # SUBMIT (where routing happens) and the step (queue drain,
        # failover handoffs, tier traffic).
        with faults.inject(_plan_for(ev)):
            _submit_maybe()
            router.step()
        ev.fired = True
        ev.observed = (dict(router.counters),
                       tuple(f.health.total_failures
                             for f in router.fleets)) != before

    for tick in range(ticks):
        if scale_at is not None and tick == scale_at[0]:
            router.scale_to(scale_at[1])
            scaled_tick = tick
            router.obs.event("chaos_scale", tick=tick, to=scale_at[1])
        ev = schedule.get(tick)
        if ev is None:
            _submit_maybe()
            router.step()
        else:
            _fault_tick(ev)
        check_fleet_invariants(router, [h for _, _, h in tracked])
        invariant_checks += 1

    budget = max_drain_steps or (ticks * 4 + 200)
    for _ in range(budget):
        if router.drained:
            break
        router.step()
        check_fleet_invariants(router, [h for _, _, h in tracked])
        invariant_checks += 1
    else:
        raise InvariantViolation(
            f"fleet serving failed to drain within {budget} post-soak "
            f"steps (router queue={len(router.queue)})")

    statuses = Counter(h.status for _, _, h in tracked)
    unresolved = [h.request.request_id for _, _, h in tracked
                  if not h.done]
    if unresolved:
        raise InvariantViolation(
            f"request(s) never terminally resolved: {unresolved}")
    token_exact = 0
    for prompt, gen, h in tracked:
        if h.status != "done":
            continue
        want = _oracle_tokens(oracle_engine, prompt, gen, oracle_cache)
        if list(h.tokens) != list(want):
            raise InvariantViolation(
                f"survivor {h.request.request_id} diverged from the "
                f"single-engine oracle: {h.tokens} != {want} "
                f"(prompt={list(prompt)})")
        token_exact += 1

    events = [schedule[t] for t in fault_ticks]
    return FleetChaosReport(
        seed=seed, ticks=ticks, fleets=fleets, events=events,
        faults_injected=len(events),
        survived_faults=sum(1 for e in events if e.fired),
        requests={"submitted": len(tracked), **{
            k: statuses.get(k, 0)
            for k in ("done", "failed", "timeout", "shed")}},
        router=dict(router.counters),
        invariant_checks=invariant_checks,
        token_exact_requests=token_exact,
        scaled_at=scaled_tick)


# ---------------------------------------------------------------------------
# Supervised soak: a REAL child process under seeded kills / stalls /
# corruption (ISSUE 16)
# ---------------------------------------------------------------------------

def supervised_tiny_factory(num_slots: int = 2, max_len: int = 32,
                            page: int = 8):
    """Importable child-side factory for the supervised soak: the
    tiny-model colocated disagg engine on one CPU device (chunked
    prefill + migration + retry reachable, deterministic greedy
    decode).  Module-level on purpose — the supervisor child resolves
    it by ``module:qualname`` string."""
    import jax
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.resilience.policy import RetryPolicy
    from triton_dist_tpu.serving import DisaggServingEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4,
                           num_key_value_heads=4, head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(cfg, mesh, mode="xla", max_len=max_len, seed=0)
    return DisaggServingEngine(
        eng, num_slots=num_slots, page=page, prefill_buckets=(4, 8),
        prefix_reuse=True, retry=RetryPolicy(max_attempts=2),
        worker_fail_threshold=2)


def run_supervised_soak(
        *, checkpoint_dir: str, seed: int = 0, n_requests: int = 8,
        n_faults: int = 6,
        factory: str = ("triton_dist_tpu.resilience.chaos:"
                        "supervised_tiny_factory"),
        factory_kwargs: Optional[Dict] = None, vocab: int = 64,
        gen_choices: Sequence[int] = (3, 4, 6, 8),
        kinds: Sequence = SUPERVISED_FAULT_KINDS,
        checkpoint_every: int = 2, heartbeat_timeout_s: float = 60.0,
        stall_detect_s: float = 2.0, tick_throttle_s: float = 0.04,
        deadline_s: float = 600.0) -> SupervisedChaosReport:
    """Drive a REAL supervised child process through ``n_faults``
    seeded kills / crashes / stalls / corruptions while it serves
    ``n_requests`` streams, then gate every finished stream token-
    exact against the in-process oracle (``Engine.serve`` on the same
    factory's weights — same seed, same weights by construction).

    Events fire when the parent's acked-token count crosses seeded
    thresholds (real process timing makes tick counts nondeterministic
    — the ack stream is the clock the parent actually observes), so
    one ``seed`` fixes the traffic AND where in each stream every
    fault lands.  A ``stall_child`` event tightens the heartbeat
    timeout to ``stall_detect_s`` until the recovery lands (child
    startup/compile gaps make a permanently-tight timeout
    false-trigger); a false stall during that window just becomes one
    more survived restart — the gate is token-exactness, not fault
    attribution.

    Raises :class:`InvariantViolation` on any non-``done`` request or
    token divergence; returns a :class:`SupervisedChaosReport`.
    """
    from triton_dist_tpu.resilience.supervisor import (
        ServingSupervisor, _resolve_factory)

    rng = np.random.RandomState(seed)
    fkw = dict(factory_kwargs or {})

    # Seeded traffic first (all rng draws in a fixed order).
    gen_choices = list(gen_choices)
    reqs = []
    for i in range(n_requests):
        n = int(rng.randint(1, 9))
        prompt = [int(x) for x in rng.randint(0, vocab, n)]
        gen = int(gen_choices[int(rng.randint(len(gen_choices)))])
        reqs.append((f"soak-{i}", prompt, gen))
    total = sum(g for _, _, g in reqs)
    # Thresholds stay under ~85% of the total stream so every event
    # fires while work is still in flight.
    hi = max(2, int(total * 0.85))
    thresholds = sorted(int(t) for t in rng.choice(
        np.arange(1, hi), size=min(n_faults, hi - 1), replace=False))
    events = []
    for t in thresholds:
        name, op, kind = kinds[int(rng.randint(len(kinds)))]
        events.append(ChaosEvent(tick=t, name=name, op=op, kind=kind,
                                 transient=True))

    # In-process oracle: same factory, same seed -> same weights.
    oracle_srv = _resolve_factory(factory)(**fkw)
    oracle_cache: Dict = {}
    want = {rid: _oracle_tokens(oracle_srv.engine, prompt, gen,
                                oracle_cache)
            for rid, prompt, gen in reqs}

    sup = ServingSupervisor(
        factory, checkpoint_dir=checkpoint_dir,
        heartbeat_timeout_s=heartbeat_timeout_s,
        checkpoint_every=checkpoint_every, factory_kwargs=fkw,
        tick_throttle_s=tick_throttle_s)
    sup.start()
    handles = {}
    stall_restore_at: Optional[int] = None
    try:
        for rid, prompt, gen in reqs:
            handles[rid] = sup.submit(prompt, request_id=rid,
                                      max_new_tokens=gen)
        pending = list(events)
        t0 = time.monotonic()
        while True:
            sup.pump()
            if (stall_restore_at is not None
                    and sup.counters["restarts"] >= stall_restore_at):
                # The stall (or a coincident crash) was detected and
                # recovered — relax the timeout before the restored
                # child's cold compile gap can false-trigger again.
                sup.heartbeat_timeout_s = heartbeat_timeout_s
                stall_restore_at = None
            acked = sup.counters["acked_tokens"]
            all_done = all(h.done for h in handles.values())
            while (pending and pending[0].tick <= acked
                   and not all_done):
                ev = pending.pop(0)
                ev.fired = True
                if ev.name == "kill_child":
                    sup.kill_child()
                elif ev.name == "crash_child":
                    sup.inject_crash()
                elif ev.name == "stall_child":
                    stall_restore_at = sup.counters["restarts"] + 1
                    sup.heartbeat_timeout_s = stall_detect_s
                    sup.inject_stall()
                else:
                    sup.inject_fault(
                        "corrupt_payload", op=ev.op,
                        k=0 if ev.transient else None)
            if all_done and not pending:
                break
            if all_done and pending:
                # Streams finished under the last thresholds — the
                # remaining events have nothing left to disrupt.
                break
            if time.monotonic() - t0 > deadline_s:
                open_rids = [r for r, h in handles.items()
                             if not h.done]
                raise InvariantViolation(
                    f"supervised soak exceeded {deadline_s}s with "
                    f"open requests {open_rids[:8]} "
                    f"(stats={sup.stats()})")
            time.sleep(0.02)

        statuses = Counter(h.status for h in handles.values())
        token_exact = 0
        for rid, prompt, gen in reqs:
            h = handles[rid]
            if h.status != "done":
                raise InvariantViolation(
                    f"supervised request {rid} ended {h.status!r} "
                    f"(error={h.error!r})")
            if list(h.tokens) != list(want[rid]):
                raise InvariantViolation(
                    f"supervised stream {rid} diverged from the "
                    f"oracle across restarts: {h.tokens} != "
                    f"{want[rid]} (prompt={prompt})")
            token_exact += 1
        stats = sup.stats()
    finally:
        sup.stop()

    return SupervisedChaosReport(
        seed=seed, events=events, faults_injected=len(events),
        survived_faults=sum(1 for e in events if e.fired),
        requests={"submitted": len(reqs), **{
            k: statuses.get(k, 0)
            for k in ("done", "failed", "timeout")}},
        supervisor=stats, token_exact_requests=token_exact)


# ---------------------------------------------------------------------------
# Integrity drill: deterministic corruption at each serialization
# boundary, in-process (the bench's integrity evidence)
# ---------------------------------------------------------------------------

def run_integrity_drill(engine=None, *, seed: int = 0) -> Dict:
    """Deterministically corrupt the KV payload at each of the three
    serving serialization boundaries — tier transfer (park/resume
    round trip), page migration (prefill->decode handoff), and the
    cross-fleet session handoff — and prove each one is DETECTED at
    the consuming edge (quarantine / integrity counters move) and
    RECOVERED through that boundary's existing path with the final
    stream token-exact.  Raises :class:`InvariantViolation` on a
    missed detection or a wrong token; returns the evidence counters
    (the ``integrity_checks`` bench key sums them).

    ``engine`` (optional) is a prebuilt tiny layer
    :class:`~triton_dist_tpu.models.Engine` to reuse (the tests pass
    their module fixture); built fresh otherwise.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.serving import (
        DisaggServingEngine, FleetRouter, ServingEngine)

    if engine is None:
        cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                               intermediate_size=32,
                               num_hidden_layers=2,
                               num_attention_heads=4,
                               num_key_value_heads=4, head_dim=8)
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        engine = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)

    def oracle(prompt, gen):
        ids = jnp.asarray(np.asarray([list(prompt)], np.int32))
        return np.asarray(engine.serve(ids, gen_len=gen))[0].tolist()

    def corrupt_plan(op, k=None):
        return faults.FaultPlan(
            name=f"drill-corrupt-{op}",
            faults=(faults.Fault("corrupt_payload", op=op, k=k,
                                 iters=seed),))

    out = {"tier_checks": 0, "tier_quarantined": 0,
           "migration_integrity_failures": 0,
           "handoff_integrity_failures": 0,
           "token_exact_requests": 0, "wrong_tokens": 0}

    # -- boundary 1: tier transfer (park -> corrupt resume fetch) ----
    srv = ServingEngine(engine, num_slots=2, page=4, num_pages=16,
                        prefix_reuse=True,
                        kv_tiers={"host_pages": 128})
    prompt, gen = [5, 3, 5, 3, 5, 3], 6
    h = srv.submit(prompt, max_new_tokens=gen)
    for _ in range(64):
        if h.status == "running" and h.tokens:
            break
        srv.step()
    srv.park(h)
    srv.resume(h)
    with faults.inject(corrupt_plan("tier_transfer")):
        # The admit-side tier get sees a corrupted payload: digest
        # mismatch -> quarantine -> miss -> deterministic re-prefill.
        srv.step()
    srv.run()
    if h.status != "done":
        raise InvariantViolation(
            f"tier-corruption drill ended {h.status!r}: {h.error!r}")
    if list(h.tokens) != oracle(prompt, gen):
        out["wrong_tokens"] += 1
        raise InvariantViolation(
            f"tier-corruption drill emitted wrong tokens: "
            f"{h.tokens} != {oracle(prompt, gen)}")
    out["token_exact_requests"] += 1
    out["tier_checks"] = srv.tiers.stats_counters["integrity_checks"]
    out["tier_quarantined"] = \
        srv.tiers.stats_counters["integrity_quarantined"]
    if out["tier_quarantined"] < 1:
        raise InvariantViolation(
            "tier-corruption drill: the corrupted payload was never "
            "quarantined — detection missed")

    # -- boundary 2: page migration (prefill -> decode handoff) ------
    dsrv = DisaggServingEngine(engine, num_slots=2, page=8,
                               prefill_buckets=(4, 8))
    prompt2, gen2 = [7, 1, 7, 1], 6
    h2 = dsrv.submit(prompt2, max_new_tokens=gen2)
    for _ in range(64):
        if dsrv._pending:
            break
        dsrv.step()
    with faults.inject(corrupt_plan("page_migration")):
        # Every migration attempt this tick is corrupted (k=None):
        # verify fails at the consuming edge before anything reaches
        # the decode pool, retries exhaust, the request re-queues for
        # a clean re-prefill.
        dsrv.step()
    dsrv.run()
    if h2.status != "done":
        raise InvariantViolation(
            f"migration-corruption drill ended {h2.status!r}: "
            f"{h2.error!r}")
    if list(h2.tokens) != oracle(prompt2, gen2):
        out["wrong_tokens"] += 1
        raise InvariantViolation(
            f"migration-corruption drill emitted wrong tokens: "
            f"{h2.tokens} != {oracle(prompt2, gen2)}")
    out["token_exact_requests"] += 1
    out["migration_integrity_failures"] = \
        dsrv.stats_counters["integrity_failures"]
    if out["migration_integrity_failures"] < 1:
        raise InvariantViolation(
            "migration-corruption drill: no integrity failure was "
            "recorded — detection missed")

    # -- boundary 3: cross-fleet session handoff ---------------------
    def fleet_factory():
        return ServingEngine(engine, num_slots=2, page=4,
                             num_pages=16, prefix_reuse=True,
                             kv_tiers={"host_pages": 128})

    router = FleetRouter(fleet_factory, fleets=2)
    prompt3, gen3 = [9, 2, 9, 2, 9, 2, 9, 2], 8
    h3 = router.submit(prompt3, max_new_tokens=gen3)
    for _ in range(64):
        if h3.status == "running" and h3.tokens:
            break
        router.step()
    victim = router._fleet_of(h3)
    with faults.inject(corrupt_plan("fleet_handoff")):
        # kill_fleet fails the victim's sessions over SYNCHRONOUSLY,
        # so the handoff hop happens inside this scope: every hop is
        # corrupted, the survivor's verify rejects the payload,
        # retries exhaust, and failover falls back to the
        # deterministic re-prefill path.
        router.kill_fleet(victim.id, reachable=True)
    router.run()
    if h3.status != "done":
        raise InvariantViolation(
            f"handoff-corruption drill ended {h3.status!r}: "
            f"{h3.error!r}")
    if list(h3.tokens) != oracle(prompt3, gen3):
        out["wrong_tokens"] += 1
        raise InvariantViolation(
            f"handoff-corruption drill emitted wrong tokens: "
            f"{h3.tokens} != {oracle(prompt3, gen3)}")
    out["token_exact_requests"] += 1
    out["handoff_integrity_failures"] = \
        router.counters["integrity_failures"]
    if out["handoff_integrity_failures"] < 1:
        raise InvariantViolation(
            "handoff-corruption drill: no integrity failure was "
            "recorded — detection missed")
    return out
