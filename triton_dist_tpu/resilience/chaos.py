"""Seeded chaos soak for the serving stack: randomized (but
seed-reproducible) fault schedules over a long mixed-traffic run, with
a full invariant sweep after every tick.

The fault-plan registry (:mod:`~triton_dist_tpu.resilience.faults`)
makes single failures injectable; this module composes them into a
SOAK — the test shape production incidents actually have: transients
and hard faults arriving at random points of a live workload, workers
dying mid-stream, the process checkpointing and restarting in the
middle. One ``seed`` fixes the arrival trace, every fault's tick and
kind, and every retry-backoff jitter, so a failing soak replays
bit-for-bit.

What a passing soak proves (the checker raises
:class:`InvariantViolation` otherwise):

- **no leaked pages** — every page is free xor referenced, refcounts
  equal the observable holders (slot lists + the prefix cache's own
  ref), free list has no duplicates, the scratch page is never
  allocated;
- **prefix publication is sound** — committed (published) entries are
  content-resident by construction of the two-phase protocol, and no
  page is simultaneously staged and published;
- **host mirrors cohere** — slot/handle bijection, live mask, and the
  length mirrors agree with the allocator's token accounting (up to
  the bounded skew a failed tick's idempotent pre-append leaves);
- **every submitted request terminally resolves** — done, failed, or
  timeout; nothing wedges or leaks a slot;
- **survivors are token-exact** — every ``done`` request's tokens
  equal the fault-free oracle (``Engine.serve`` on the same weights).

Usage (the tier-1 subset in ``tests/test_chaos.py`` and the
``chaos_survived_faults`` bench key both drive this)::

    from triton_dist_tpu.resilience import chaos
    report = chaos.run_soak(make_engine, seed=7, ticks=200,
                            n_faults=12, restore_at=90)
    assert report.survived_faults >= 10
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from triton_dist_tpu.resilience import faults

__all__ = ["ChaosEvent", "ChaosReport", "FleetChaosReport",
           "InvariantViolation",
           "DEFAULT_FAULT_KINDS", "TIER_FAULT_KINDS",
           "FLEET_FAULT_KINDS", "MK_FAULT_KINDS",
           "check_invariants", "check_fleet_invariants",
           "run_soak", "run_fleet_soak"]


class InvariantViolation(AssertionError):
    """A serving invariant broke under the soak — the bug class this
    harness exists to catch (leaked page, drifted refcount, corrupted
    mirror, unresolved request, token divergence)."""


# (name, op, fault_kind): the injectable menu. ``fail_call`` models a
# dropped transfer/dispatch; ``timeout_call`` a wedged one (the
# deterministic watchdog-miss stand-in — see faults.py); transient
# events target only the FIRST call of the tick (k=0: absorbed by one
# retry), hard events every call of the tick (k=None: retries exhaust,
# containment/failover takes over). ``kill_prefill_worker`` is the
# dead-role event (DisaggServingEngine.fail_prefill_worker).
DEFAULT_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                           ...] = (
    ("drop_migration", "page_migration", "fail_call"),
    ("wedge_migration", "page_migration", "timeout_call"),
    ("drop_chunk", "chunked_prefill", "fail_call"),
    ("delay_chunk", "chunked_prefill", "timeout_call"),
    ("drop_decode", "serving_decode", "fail_call"),
    ("wedge_decode", "serving_decode", "timeout_call"),
    ("kill_prefill_worker", None, None),
)

# The tiered-KV additions (engines built with ``kv_tiers``): dropped /
# wedged tier transfers — a faulted demote drops the (recomputable)
# prefix content, a faulted prefetch falls back to recompute, a
# faulted park leaves the request running, a faulted resume re-enters
# via the deterministic re-prefill; all token-exact by construction.
# Kept separate so un-tiered soaks (and their seeded schedules) stay
# byte-identical; pass ``kinds=DEFAULT_FAULT_KINDS + TIER_FAULT_KINDS``
# for a tiered engine.
TIER_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                        ...] = (
    ("drop_tier_transfer", "tier_transfer", "fail_call"),
    ("wedge_tier_transfer", "tier_transfer", "timeout_call"),
)

# The megakernel-lane menu (``run_soak`` over a paged
# ``MegaKernelEngine`` serving factory): the persistent lane has no
# migration/chunk/worker ops, so only the joint decode dispatch (the
# prefill LANE rides it too) is injectable — dropped and wedged
# decode/verification launches. Kept separate so layer-path soaks'
# seeded schedules stay byte-identical.
MK_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                      ...] = (
    ("drop_decode", "serving_decode", "fail_call"),
    ("wedge_decode", "serving_decode", "timeout_call"),
    ("drop_verify", "spec_verify", "fail_call"),
    ("wedge_verify", "spec_verify", "timeout_call"),
)

# The fleet-level menu (``run_fleet_soak`` over a ``FleetRouter``):
# dropped / wedged router→fleet links (``fleet_route`` — the send that
# places a request on a fleet's queue), dropped / wedged cross-fleet
# session handoffs (``fleet_handoff`` — the parked-payload hop during
# failover and drain/restore), and whole-fleet kills — a seeded coin
# picks reachable (parked-tier handoff path) vs vanished (deterministic
# re-prefill path). Kept separate so ``run_soak``'s seeded schedules
# stay byte-identical.
FLEET_FAULT_KINDS: Tuple[Tuple[str, Optional[str], Optional[str]],
                         ...] = (
    ("kill_fleet", None, None),
    ("drop_route", "fleet_route", "fail_call"),
    ("wedge_route", "fleet_route", "timeout_call"),
    ("drop_handoff", "fleet_handoff", "fail_call"),
    ("wedge_handoff", "fleet_handoff", "timeout_call"),
)


@dataclasses.dataclass
class ChaosEvent:
    """One scheduled fault: where, what, and what it observably did.

    ``at`` is the serving engine's clock reading when the fault fired
    (None until then) — soak runs are trace-inspectable: the same
    timestamp domain the request spans and retry events use, so a
    fault lines up against its victims in the merged timeline."""

    tick: int
    name: str
    op: Optional[str]
    kind: Optional[str]       # fail_call | timeout_call | None (kill)
    transient: bool
    fired: bool = False       # the fault had a chance to act this tick
    observed: bool = False    # a failure/retry counter moved this tick
    at: Optional[float] = None  # engine-clock stamp when fired


@dataclasses.dataclass
class ChaosReport:
    """What a completed soak measured (a completed soak already means:
    server alive, invariants held every tick, all requests terminal,
    survivors token-exact — violations raise instead)."""

    seed: int
    ticks: int
    events: List[ChaosEvent]
    faults_injected: int
    survived_faults: int
    requests: Dict[str, int]
    counters: Dict[str, int]
    invariant_checks: int
    token_exact_requests: int
    restored_at: Optional[int]


@dataclasses.dataclass
class FleetChaosReport:
    """What a completed fleet soak measured (completion already means:
    router alive, per-tick fleet invariants held, every request
    terminal, done requests token-exact vs the single-engine oracle).
    ``requests`` adds the ``shed`` class; ``router`` is the final
    router counter dict (failovers, handoff resumes, sheds...)."""

    seed: int
    ticks: int
    fleets: int
    events: List[ChaosEvent]
    faults_injected: int
    survived_faults: int
    requests: Dict[str, int]
    router: Dict[str, int]
    invariant_checks: int
    token_exact_requests: int
    scaled_at: Optional[int]


# ---------------------------------------------------------------------------
# Invariant checker
# ---------------------------------------------------------------------------

def _check_manager(mgr, name: str) -> None:
    from triton_dist_tpu.serving.blocks import SCRATCH_PAGE

    free = list(mgr._free)
    if len(set(free)) != len(free):
        raise InvariantViolation(
            f"[{name}] duplicate page ids on the free list: {free}")
    if SCRATCH_PAGE in free:
        raise InvariantViolation(
            f"[{name}] the reserved scratch page leaked into the free "
            "list")
    held = Counter(pid for pages in mgr._slot_pages.values()
                   for pid in pages)
    if SCRATCH_PAGE in held:
        raise InvariantViolation(
            f"[{name}] the scratch page was allocated to a slot")
    prefix_pids = set(mgr._prefix.values())
    free_set = set(free)
    for pid in range(1, mgr.num_pages):
        want = held.get(pid, 0) + (1 if pid in prefix_pids else 0)
        have = mgr._refs.get(pid, 0)
        if have != want:
            raise InvariantViolation(
                f"[{name}] page {pid} refcount {have} != observable "
                f"holders {want} (slots={held.get(pid, 0)}, "
                f"prefix={pid in prefix_pids})")
        if (pid in free_set) == (want > 0):
            raise InvariantViolation(
                f"[{name}] page {pid} {'free but referenced' if want else 'unreferenced but not free — LEAKED'}")
    if len(free) + len(mgr._refs) != mgr.num_pages - 1:
        raise InvariantViolation(
            f"[{name}] page accounting broke: {len(free)} free + "
            f"{len(mgr._refs)} referenced != {mgr.num_pages - 1} "
            "usable pages")
    staged = {pid for pairs in mgr._pending_prefix.values()
              for _, pid in pairs}
    if staged & prefix_pids:
        raise InvariantViolation(
            f"[{name}] page(s) {staged & prefix_pids} both staged and "
            "published — the two-phase prefix protocol broke")
    for slot, pairs in mgr._pending_prefix.items():
        owned = set(mgr._slot_pages.get(slot, []))
        for _, pid in pairs:
            if pid not in owned:
                raise InvariantViolation(
                    f"[{name}] staged prefix page {pid} not owned by "
                    f"its staging slot {slot}")
    for slot, n_tok in mgr._slot_tokens.items():
        cap = len(mgr._slot_pages.get(slot, [])) * mgr.page
        if n_tok > cap:
            raise InvariantViolation(
                f"[{name}] slot {slot} accounts {n_tok} tokens over "
                f"{cap} allocated-page capacity")


def check_invariants(srv) -> None:
    """One full sweep of the serving invariants (see module
    docstring). Call between ticks — the structures are host-side, so
    this never syncs the device."""
    if srv.manager is not None:
        _check_manager(srv.manager, "decode-pool")
    workers = getattr(srv, "prefill_workers", None) or []
    for i, w in enumerate(workers):
        if not w.dead and w.manager is not srv.manager:
            _check_manager(w.manager, f"prefill-pool[{i}]")
    spec_slack = max(1, getattr(srv, "spec_k", 0) or 0)
    for s in range(srv.num_slots):
        h = srv.sched.slots.get(s)
        if h is None:
            if srv._live[s] != 0:
                raise InvariantViolation(
                    f"slot {s} live={srv._live[s]} with no handle")
            continue
        if h.slot != s:
            raise InvariantViolation(
                f"slot {s} handle claims slot {h.slot}")
        if h.status == "running":
            if srv._live[s] != 1:
                raise InvariantViolation(
                    f"running slot {s} has live={srv._live[s]}")
            want = len(h.request.prompt) + len(h.tokens) - 1
            if srv._lens[s] != want:
                raise InvariantViolation(
                    f"slot {s} length mirror {srv._lens[s]} != "
                    f"prompt+generated-fed {want}")
            if srv.manager is not None:
                n = srv.manager._slot_tokens.get(s)
                if n is None or not (srv._lens[s] <= n
                                     <= srv._lens[s] + spec_slack):
                    raise InvariantViolation(
                        f"slot {s} allocator tokens {n} drifted from "
                        f"length mirror {srv._lens[s]} (allowed slack "
                        f"{spec_slack})")
        elif h.status in ("prefill", "migrating", "resuming"):
            if srv._live[s] != 0 and not srv.mega:
                raise InvariantViolation(
                    f"parked ({h.status}) slot {s} is marked live")
        else:
            raise InvariantViolation(
                f"slot {s} holds a terminal handle ({h.status})")
    for h in srv.sched.queue:
        if h.slot is not None:
            raise InvariantViolation(
                f"queued request {h.request.request_id} still holds "
                f"slot {h.slot}")
    _check_tiers(srv)
    _check_arena(srv)


def _check_arena(srv) -> None:
    """Arena-coherence sweep (megakernel engines): the described
    memory layout must stay sound under faults —

    - **region disjointness**: the arena schema's in-arena regions
      tile [0, rows) with no overlap/gap (``ArenaSchema
      .check_disjoint``). The schema is build-time-frozen, so this
      half re-asserts a static invariant — it exists to catch a
      FUTURE builder change that starts mutating layouts at serve
      time, not a runtime fault (cheap: pure host arithmetic);
    - **scale/page consistency** (quantized pools): every
      per-(layer, page, kv_head) dequant scale is finite and > 0
      (write_kv's running-amax maintenance can never produce 0 or a
      NaN — either would silently zero or poison a page's dequant);
    - **monotonic counters**: the in-arena MoE router counters only
      ever grow between sweeps (the epilogue accumulates; a decrease
      means a clobbered counter region).
    """
    if not getattr(srv, "mega", False):
        return
    eng = srv.engine
    for b in (eng.builder, getattr(eng, "verify_builder", None)):
        if b is None:
            continue
        try:
            b.schema.check_disjoint()
        except ValueError as e:
            raise InvariantViolation(f"arena schema broke: {e}") from e
    if getattr(eng, "k_scale", None) is not None:
        for name in ("k_scale", "v_scale"):
            a = np.asarray(getattr(eng, name))
            if not np.isfinite(a).all() or (a <= 0).any():
                raise InvariantViolation(
                    f"quantized pool {name} left the sane range "
                    f"(finite, > 0): min={a.min()}, "
                    f"finite={np.isfinite(a).all()}")
    if getattr(srv.cfg, "is_moe", False) and hasattr(eng,
                                                     "expert_counts"):
        counts = eng.expert_counts()
        prev = getattr(srv, "_mk_counts_sweep", None)
        if prev is not None and (counts < prev).any():
            raise InvariantViolation(
                f"megakernel expert counters went BACKWARDS: "
                f"{prev.tolist()} -> {counts.tolist()}")
        srv._mk_counts_sweep = counts


def _check_tiers(srv) -> None:
    """Tier-coherence sweep (engines built with ``kv_tiers``): every
    payload lives in exactly ONE authoritative tier, no HBM free-list
    entry is backed by a pending (uncommitted) demotion, and the
    parked registry and tier store agree."""
    tiers = getattr(srv, "tiers", None)
    if tiers is None:
        return
    try:
        # Staged-demotion window empty between ticks + host/disk
        # disjoint + capacity bounds (the store's own algebra).
        tiers.check_coherence()
    except AssertionError as e:
        raise InvariantViolation(str(e)) from e
    # Exactly-one-tier across the hierarchy: a key committed in the
    # HBM prefix cache must not ALSO be tier-resident (demotion pops
    # it from HBM, promotion pops it from the tier).
    if srv.manager is not None:
        hbm_keys = set(srv.manager._prefix)
        for k in tiers.keys():
            k = tuple(k)
            if k[0] == "prefix" and k[1] in hbm_keys:
                raise InvariantViolation(
                    f"prefix key resident in BOTH the HBM cache and "
                    f"the tier store: {k[1]!r}")
    parked = getattr(srv, "_parked", {})
    for rid, h in parked.items():
        if h.status != "parked" or h.slot is not None:
            raise InvariantViolation(
                f"parked registry holds request {rid} in state "
                f"{h.status!r} (slot={h.slot})")
        if ("session", rid) not in tiers:
            raise InvariantViolation(
                f"parked request {rid} has no tier payload — its KV "
                "is unrecoverable")
        if h in srv.sched.queue:
            raise InvariantViolation(
                f"parked request {rid} is also queued")
    for k in tiers.keys():
        k = tuple(k)
        if k[0] != "session":
            continue
        e = tiers.entry(k)
        if e.pinned and k[1] not in parked and not any(
                getattr(h, "resume_key", None) == k
                for h in list(srv.sched.queue)
                + list(srv.sched.slots.values())):
            raise InvariantViolation(
                f"pinned session payload {k[1]!r} has no parked or "
                "resuming owner — leaked tier pages")


def check_fleet_invariants(router, tracked=None) -> None:
    """Fleet-level sweep over a :class:`~triton_dist_tpu.serving.
    router.FleetRouter` — the per-fleet :func:`check_invariants` plus
    the cross-fleet algebra:

    - every in-flight request is owned by exactly ONE place (the
      router queue, or one live fleet's queue / slots / parked
      registry) — never two;
    - no session payload is pinned in two fleets' tier stores at once
      (the cross-fleet handoff pops the source before the target
      resumes);
    - the router's health view is consistent with liveness (a fleet
      marked dead carries a dead health verdict; a declared-dead
      health verdict on a live fleet means the failover was skipped);
    - the drain gate holds: a draining fleet admits nothing (its
      queue stays empty);
    - router-queued handles are slotless and non-terminal.

    ``tracked`` (optional handles) must each be terminal or owned
    somewhere.
    """
    seen: Dict[str, str] = {}

    def note(h, where):
        rid = h.request.request_id
        if rid in seen:
            raise InvariantViolation(
                f"request {rid} owned by BOTH {seen[rid]} and {where}")
        seen[rid] = where

    # Cross-fleet session uniqueness first: a payload pinned on two
    # fleets is its own violation class (a handoff that copied
    # without popping), reported before the ownership scan can fold
    # it into a generic double-ownership message.
    session_owner: Dict[tuple, int] = {}
    for f in router.fleets:
        if f.dead or f.engine.tiers is None:
            continue
        for k in f.engine.tiers.keys():
            k = tuple(k)
            if k[0] != "session":
                continue
            if k in session_owner:
                raise InvariantViolation(
                    f"session payload {k[1]!r} pinned on BOTH fleet "
                    f"{session_owner[k]} and fleet {f.id}")
            session_owner[k] = f.id
    for h in router.queue:
        if h.slot is not None:
            raise InvariantViolation(
                f"router-queued request {h.request.request_id} still "
                f"holds slot {h.slot}")
        if h.done:
            raise InvariantViolation(
                f"terminal request {h.request.request_id} "
                f"({h.status}) sits in the router queue")
        note(h, "router-queue")
    for f in router.fleets:
        if f.dead:
            if not f.health.dead:
                raise InvariantViolation(
                    f"fleet {f.id} marked dead without a dead health "
                    "verdict")
            continue
        if f.health.dead:
            raise InvariantViolation(
                f"fleet {f.id} health declared dead "
                f"({f.health.cause!r}) but the router still routes to "
                "it — failover skipped")
        check_invariants(f.engine)
        if f.draining and f.engine.sched.queue:
            raise InvariantViolation(
                f"draining fleet {f.id} admitted new work (drain gate "
                f"broke): queue={[h.request.request_id for h in f.engine.sched.queue]}")
        for h in f.engine.sched.queue:
            note(h, f"fleet{f.id}-queue")
        for h in f.engine.sched.slots.values():
            note(h, f"fleet{f.id}-slot")
        for h in f.engine._parked.values():
            note(h, f"fleet{f.id}-parked")
    for h in tracked or ():
        if not h.done and h.request.request_id not in seen:
            raise InvariantViolation(
                f"in-flight request {h.request.request_id} "
                f"({h.status}) owned by NO fleet and not router-"
                "queued — lost")


# ---------------------------------------------------------------------------
# The soak
# ---------------------------------------------------------------------------

def _oracle_tokens(engine, prompt: Sequence[int], gen_len: int,
                   cache: Dict) -> List[int]:
    import jax.numpy as jnp

    key = (tuple(prompt), gen_len)
    if key not in cache:
        n = engine.mesh.shape[engine.axis]
        ids = np.tile(np.asarray([list(prompt)], np.int32), (n, 1))
        cache[key] = np.asarray(
            engine.serve(jnp.asarray(ids),
                         gen_len=gen_len))[0].tolist()
    return cache[key]


def _note_fault(srv, ev: ChaosEvent) -> None:
    """Land the injected fault in the engine's telemetry event log —
    the soak's faults and the serving spans share ONE timeline, so a
    retry burst or a failover reads directly against the fault that
    caused it."""
    srv.obs.event("chaos_fault", tick=ev.tick, name=ev.name,
                  op=ev.op, fault_kind=ev.kind,
                  transient=ev.transient)


def _plan_for(ev: ChaosEvent) -> faults.FaultPlan:
    k = 0 if ev.transient else None
    return faults.FaultPlan(
        name=f"chaos-{ev.name}",
        faults=(faults.Fault(ev.kind, op=ev.op, k=k),))


def run_soak(factory: Callable[[], object], *, seed: int = 0,
             ticks: int = 200, n_faults: int = 10,
             arrival_p: float = 0.35,
             kinds: Sequence = DEFAULT_FAULT_KINDS,
             transient_p: float = 0.5,
             gen_choices: Sequence[int] = (2, 3, 4, 6, 8),
             prompt_reuse_p: float = 0.3,
             restore_at: Optional[int] = None,
             max_drain_steps: Optional[int] = None,
             park_p: float = 0.0) -> ChaosReport:
    """Drive ``ticks`` serving steps of seeded mixed traffic under
    ``n_faults`` seeded fault events, checking every invariant after
    every tick, then drain fault-free and verify terminal resolution +
    token-exactness of all survivors against the fault-free oracle.

    ``factory`` builds the serving engine (a fresh, identically-
    configured one each call — ``restore_at`` uses it again for the
    mid-soak kill/checkpoint/restore drill). Greedy traffic only (the
    exactness oracle is ``Engine.serve``; megakernel factories get a
    fresh fault-free serving engine instead — pass
    ``kinds=MK_FAULT_KINDS`` there, and the per-tick sweep adds the
    arena-coherence check). Raises
    :class:`InvariantViolation` (or the server's own crash) on any
    violation; returns a :class:`ChaosReport` otherwise.

    ``park_p`` > 0 (engines built with ``kv_tiers``) additionally
    parks a seeded-random running request with that per-tick
    probability and resumes it 1–4 ticks later — resumed sessions
    flow through the same token-exactness gate as everything else, so
    a park/resume byte drift fails the soak. Anything still parked
    when the soak ends resumes before the drain.
    """
    rng = np.random.RandomState(seed)
    srv = factory()
    # Megakernel engines soak too (pass kinds=MK_FAULT_KINDS — the
    # persistent lane has no migration/chunk ops): the oracle is a
    # fresh fault-free serving engine from the same factory (the mk
    # engine has no Engine.serve), and the per-tick sweep additionally
    # runs the arena-coherence check (_check_arena).
    mk_oracle = {"srv": None} if srv.mega else None
    vocab = srv.cfg.vocab_size
    cap = min(srv.p_max * srv.page, srv.max_len)
    max_gen = max(g for g in gen_choices)
    max_prompt = max(1, min(12, cap - max_gen - 1))
    kinds = list(kinds)
    fault_ticks = sorted(
        int(t) for t in rng.choice(np.arange(1, max(ticks, 2)),
                                   size=min(n_faults, ticks - 1),
                                   replace=False))
    schedule: Dict[int, ChaosEvent] = {}
    for t in fault_ticks:
        name, op, kind = kinds[int(rng.randint(len(kinds)))]
        schedule[t] = ChaosEvent(
            tick=t, name=name, op=op, kind=kind,
            transient=bool(rng.rand() < transient_p))

    tracked: List[Tuple[Tuple[int, ...], int, object]] = []
    prior_prompts: List[List[int]] = []
    oracle_cache: Dict = {}
    invariant_checks = 0
    restored_tick = None

    def _submit_maybe():
        nonlocal prior_prompts
        if rng.rand() >= arrival_p:
            return
        if prior_prompts and rng.rand() < prompt_reuse_p:
            prompt = list(prior_prompts[
                int(rng.randint(len(prior_prompts)))])
        else:
            n = int(rng.randint(1, max_prompt + 1))
            prompt = [int(x) for x in rng.randint(0, vocab, n)]
            prior_prompts.append(prompt)
        gen = int(gen_choices[int(rng.randint(len(gen_choices)))])
        from triton_dist_tpu.serving.scheduler import QueueFullError

        try:
            h = srv.submit(prompt, max_new_tokens=gen)
        except QueueFullError:
            return      # backpressure is correct behaviour, not a bug
        tracked.append((tuple(prompt), gen, h))

    def _tick_counters():
        return {k: srv.stats_counters[k] for k in
                ("retries", "comm_timeouts", "failovers")} | {
                    k: srv.sched.counters[k] for k in
                    ("failed", "timed_out")}

    # Seeded park/resume drill state: parked handles and the tick
    # each one resumes at. All rng draws are gated on park_p, so a
    # park_p=0 soak's random sequence (and therefore its entire
    # schedule) is byte-identical to the pre-tier soaks.
    resume_at: Dict[int, List[object]] = {}
    parked: List[object] = []

    def _park_maybe(tick: int):
        if not park_p or getattr(srv, "tiers", None) is None:
            return
        for h in resume_at.pop(tick, []):
            if h.status == "parked":
                srv.resume(h)
                parked.remove(h)
        if rng.rand() >= park_p:
            return
        cands = [h for h in srv.sched.running()
                 if h.status == "running" and h.tokens]
        if not cands:
            return
        h = cands[int(rng.randint(len(cands)))]
        from triton_dist_tpu.resilience.watchdog import CommTimeoutError
        from triton_dist_tpu.serving.tiers import TierFullError

        try:
            srv.park(h)
        except (TierFullError, CommTimeoutError,
                faults.InjectedFault):
            # Correct containment, not a bug: a full tier or a
            # dropped/wedged offload transfer aborts the park and the
            # request KEEPS RUNNING (the two-phase offload frees
            # nothing before the transfer commits) — on fault ticks
            # _park_maybe runs INSIDE the injection scope precisely
            # to exercise this.
            return
        parked.append(h)
        resume_at.setdefault(
            tick + 1 + int(rng.randint(4)), []).append(h)

    for tick in range(ticks):
        if restore_at is not None and tick == restore_at:
            # The mid-run kill/restore drill: snapshot, throw the
            # engine away, restore into a fresh one (same weights by
            # construction of the factory), rebind tracked handles.
            snap = srv.checkpoint()
            srv = factory()
            revived = {h.request.request_id: h
                       for h in srv.restore(snap)}
            tracked = [(p, g, revived.get(h.request.request_id, h))
                       for p, g, h in tracked]
            parked = [revived.get(h.request.request_id, h)
                      for h in parked]
            resume_at = {t: [revived.get(h.request.request_id, h)
                             for h in hs]
                         for t, hs in resume_at.items()}
            restored_tick = tick
            srv.obs.event("chaos_restore", tick=tick,
                          revived=len(revived))
        _submit_maybe()
        ev = schedule.get(tick)
        if ev is None:
            _park_maybe(tick)
            srv.step()
        elif ev.name == "kill_prefill_worker":
            ev.at = srv.sched.now()
            _note_fault(srv, ev)
            killed = bool(getattr(srv, "fail_prefill_worker",
                                  lambda: False)())
            ev.fired, ev.observed = True, killed
            _park_maybe(tick)
            srv.step()
        else:
            before = _tick_counters()
            ev.at = srv.sched.now()
            _note_fault(srv, ev)
            with faults.inject(_plan_for(ev)):
                # The park drill runs INSIDE the fault scope: a tier
                # fault can hit the park offload itself (aborted park,
                # request keeps running) as well as the step's
                # demotes/prefetches.
                _park_maybe(tick)
                srv.step()
            ev.fired = True
            ev.observed = _tick_counters() != before
        check_invariants(srv)
        invariant_checks += 1

    # Drain fault-free: everything still in flight must resolve —
    # parked sessions resume first (a park with no resume is a
    # deliberate suspension, not a drain blocker; the drill resumes
    # everything so its token-exactness is checked).
    for h in parked:
        if h.status == "parked":
            srv.resume(h)
    parked.clear()
    budget = max_drain_steps or (ticks * 4 + 200)
    for _ in range(budget):
        if srv._drained():
            break
        srv.step()
        check_invariants(srv)
        invariant_checks += 1
    else:
        raise InvariantViolation(
            f"serving loop failed to drain within {budget} post-soak "
            f"steps (queue={len(srv.sched.queue)}, "
            f"slots={sorted(srv.sched.slots)})")

    statuses = Counter(h.status for _, _, h in tracked)
    unresolved = [h.request.request_id for _, _, h in tracked
                  if not h.done]
    if unresolved:
        raise InvariantViolation(
            f"request(s) never terminally resolved: {unresolved}")
    token_exact = 0
    for prompt, gen, h in tracked:
        if h.status != "done":
            continue
        if mk_oracle is not None:
            key = (tuple(prompt), gen)
            if key not in oracle_cache:
                if mk_oracle["srv"] is None:
                    mk_oracle["srv"] = factory()
                oracle_cache[key] = mk_oracle["srv"].generate(
                    [list(prompt)], max_new_tokens=gen)[0]
            want = oracle_cache[key]
        else:
            want = _oracle_tokens(srv.engine, prompt, gen,
                                  oracle_cache)
        if list(h.tokens) != list(want):
            raise InvariantViolation(
                f"survivor {h.request.request_id} diverged from the "
                f"fault-free oracle: {h.tokens} != {want} "
                f"(prompt={list(prompt)})")
        token_exact += 1

    events = [schedule[t] for t in fault_ticks]
    return ChaosReport(
        seed=seed, ticks=ticks, events=events,
        faults_injected=len(events),
        survived_faults=sum(1 for e in events if e.fired),
        requests={"submitted": len(tracked), **{
            k: statuses.get(k, 0)
            for k in ("done", "failed", "timeout")}},
        counters={k: srv.stats_counters[k] for k in
                  ("retries", "failovers", "comm_timeouts",
                   "preemptions", "restored_requests", "parks",
                   "resumes")},
        invariant_checks=invariant_checks,
        token_exact_requests=token_exact,
        restored_at=restored_tick)


def run_fleet_soak(factory: Callable[[], object], *,
                   fleets: int = 2, seed: int = 0, ticks: int = 200,
                   n_faults: int = 10, arrival_p: float = 0.35,
                   kinds: Sequence = (FLEET_FAULT_KINDS
                                      + TIER_FAULT_KINDS),
                   transient_p: float = 0.5,
                   gen_choices: Sequence[int] = (2, 3, 4, 6, 8),
                   prompt_reuse_p: float = 0.4,
                   deadline_p: float = 0.5,
                   scale_at: Optional[Tuple[int, int]] = None,
                   max_drain_steps: Optional[int] = None,
                   router_kw: Optional[Dict] = None
                   ) -> FleetChaosReport:
    """Fleet-level chaos soak: drive ``ticks`` router steps of seeded
    mixed traffic through a :class:`~triton_dist_tpu.serving.router.
    FleetRouter` over ``fleets`` replicas of ``factory()``, under a
    seeded schedule of whole-fleet kills (a seeded coin picks
    reachable — the parked-tier handoff path — vs vanished — the
    re-prefill path; never the last live fleet), dropped/wedged
    ``fleet_route`` / ``fleet_handoff`` links, and tier faults.
    :func:`check_fleet_invariants` sweeps after EVERY tick, the run
    drains fault-free, every request must reach a terminal state
    (``shed`` counts — graceful degradation is a terminal verdict,
    not a hang), and every ``done`` request's tokens must equal the
    single-engine ``Engine.serve`` oracle.

    ``deadline_p``: fraction of requests submitted with a (far)
    deadline — the interactive class, so fleet-loss shedding has both
    classes to discriminate. ``scale_at=(tick, R')`` additionally
    runs the drain/restore autoscale drill mid-soak. Raises
    :class:`InvariantViolation` on any violation; returns a
    :class:`FleetChaosReport` otherwise.
    """
    from triton_dist_tpu.serving.router import FleetRouter
    from triton_dist_tpu.serving.scheduler import QueueFullError

    rng = np.random.RandomState(seed)
    router = FleetRouter(factory, fleets=fleets, **(router_kw or {}))
    oracle_engine = router.fleets[0].engine.engine
    vocab = router.fleets[0].engine.cfg.vocab_size
    ref = router.fleets[0].engine
    cap = min(ref.p_max * ref.page, ref.max_len)
    max_gen = max(g for g in gen_choices)
    max_prompt = max(1, min(12, cap - max_gen - 1))
    kinds = list(kinds)
    fault_ticks = sorted(
        int(t) for t in rng.choice(np.arange(1, max(ticks, 2)),
                                   size=min(n_faults, ticks - 1),
                                   replace=False))
    schedule: Dict[int, ChaosEvent] = {}
    for t in fault_ticks:
        name, op, kind = kinds[int(rng.randint(len(kinds)))]
        schedule[t] = ChaosEvent(
            tick=t, name=name, op=op, kind=kind,
            transient=bool(rng.rand() < transient_p))

    tracked: List[Tuple[Tuple[int, ...], int, object]] = []
    prior_prompts: List[List[int]] = []
    oracle_cache: Dict = {}
    invariant_checks = 0
    scaled_tick = None

    def _submit_maybe():
        if rng.rand() >= arrival_p:
            return
        if prior_prompts and rng.rand() < prompt_reuse_p:
            # Prompt reuse = the affinity signal: same-prefix traffic
            # should keep landing on the fleet holding the pages.
            prompt = list(prior_prompts[
                int(rng.randint(len(prior_prompts)))])
        else:
            n = int(rng.randint(1, max_prompt + 1))
            prompt = [int(x) for x in rng.randint(0, vocab, n)]
            prior_prompts.append(prompt)
        gen = int(gen_choices[int(rng.randint(len(gen_choices)))])
        # Interactive (far-deadline) vs batch class — both present so
        # fleet-loss shedding has an ordering to exercise.
        deadline = (router.obs.now() + 1e6
                    if rng.rand() < deadline_p else None)
        try:
            h = router.submit(prompt, max_new_tokens=gen,
                              deadline=deadline)
        except QueueFullError:
            return      # backpressure is correct behaviour, not a bug
        tracked.append((tuple(prompt), gen, h))

    def _fault_tick(ev: ChaosEvent):
        before = (dict(router.counters),
                  tuple(f.health.total_failures
                        for f in router.fleets))
        ev.at = router.obs.now()
        router.obs.event("chaos_fault", tick=ev.tick, name=ev.name,
                         op=ev.op, fault_kind=ev.kind,
                         transient=ev.transient)
        if ev.name == "kill_fleet":
            live = router._live_fleets()
            if len(live) < 2:
                ev.fired = False        # nothing safely killable
                _submit_maybe()
                router.step()
                return
            victim = live[int(rng.randint(len(live)))]
            reachable = bool(rng.rand() < 0.5)
            router.kill_fleet(victim.id, reachable=reachable)
            ev.fired = ev.observed = True
            _submit_maybe()
            router.step()
            return
        # Route/handoff/tier faults: the injection window covers the
        # SUBMIT (where routing happens) and the step (queue drain,
        # failover handoffs, tier traffic).
        with faults.inject(_plan_for(ev)):
            _submit_maybe()
            router.step()
        ev.fired = True
        ev.observed = (dict(router.counters),
                       tuple(f.health.total_failures
                             for f in router.fleets)) != before

    for tick in range(ticks):
        if scale_at is not None and tick == scale_at[0]:
            router.scale_to(scale_at[1])
            scaled_tick = tick
            router.obs.event("chaos_scale", tick=tick, to=scale_at[1])
        ev = schedule.get(tick)
        if ev is None:
            _submit_maybe()
            router.step()
        else:
            _fault_tick(ev)
        check_fleet_invariants(router, [h for _, _, h in tracked])
        invariant_checks += 1

    budget = max_drain_steps or (ticks * 4 + 200)
    for _ in range(budget):
        if router.drained:
            break
        router.step()
        check_fleet_invariants(router, [h for _, _, h in tracked])
        invariant_checks += 1
    else:
        raise InvariantViolation(
            f"fleet serving failed to drain within {budget} post-soak "
            f"steps (router queue={len(router.queue)})")

    statuses = Counter(h.status for _, _, h in tracked)
    unresolved = [h.request.request_id for _, _, h in tracked
                  if not h.done]
    if unresolved:
        raise InvariantViolation(
            f"request(s) never terminally resolved: {unresolved}")
    token_exact = 0
    for prompt, gen, h in tracked:
        if h.status != "done":
            continue
        want = _oracle_tokens(oracle_engine, prompt, gen, oracle_cache)
        if list(h.tokens) != list(want):
            raise InvariantViolation(
                f"survivor {h.request.request_id} diverged from the "
                f"single-engine oracle: {h.tokens} != {want} "
                f"(prompt={list(prompt)})")
        token_exact += 1

    events = [schedule[t] for t in fault_ticks]
    return FleetChaosReport(
        seed=seed, ticks=ticks, fleets=fleets, events=events,
        faults_injected=len(events),
        survived_faults=sum(1 for e in events if e.fired),
        requests={"submitted": len(tracked), **{
            k: statuses.get(k, 0)
            for k in ("done", "failed", "timeout", "shed")}},
        router=dict(router.counters),
        invariant_checks=invariant_checks,
        token_exact_requests=token_exact,
        scaled_at=scaled_tick)
