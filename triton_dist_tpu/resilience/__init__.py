"""Failure as a first-class, testable input (SURVEY.md north star:
production serving needs protocols that are correct under adverse
timing, not just on the happy path).

Three layers:

- :mod:`~triton_dist_tpu.resilience.faults` — a registry of named fault
  plans (delay a remote DMA, drop/duplicate a signal increment, skew a
  rank's barrier arrival, fail the k-th collective call) injected into
  the interpret-mode comm path through thin hooks in ``lang`` and the
  fused ops, so the full kernel battery replays under adversarial
  schedules on the CPU mesh.
- :mod:`~triton_dist_tpu.resilience.watchdog` — deadlines on host-
  visible futures: :class:`CommTimeoutError` (rank + op + progress
  counter) instead of an indistinguishable hang.
- :mod:`~triton_dist_tpu.resilience.policy` — graceful degradation:
  per-op fallback onto the plain-XLA collective path when a fused op
  raises or a startup health probe fails on the current platform.

``harness`` runs deadlock-prone fault plans in a subprocess with a hard
deadline (a wedged interpreter thread cannot be cancelled in-process).
:mod:`~triton_dist_tpu.resilience.chaos` composes the registry into a
seeded SOAK over live serving traffic — randomized fault schedules
with an invariant sweep after every tick and token-exactness vs the
fault-free oracle (imported lazily: ``from triton_dist_tpu.resilience
import chaos``).

The process-level fault domain (ISSUE 16) adds two more:

- :mod:`~triton_dist_tpu.resilience.integrity` — per-payload crc32c
  digests computed at every serialization boundary (tier put,
  migration send, fleet handoff, checkpoint write) and verified at the
  consuming edge; mismatch raises :class:`IntegrityError` into the
  boundary's existing recovery path.
- :mod:`~triton_dist_tpu.resilience.supervisor` — the serving engine
  tick loop in a CHILD process under
  :class:`~triton_dist_tpu.resilience.supervisor.ServingSupervisor`:
  per-tick heartbeats + token acks out, requests in; on crash or
  heartbeat stall the parent SIGKILLs, restores the newest good
  snapshot from a journaled keep-last-K checkpoint ring, and
  re-submits unacked work deduped by ``(request_id, token_index)`` —
  client streams resume token-exact.
"""

from triton_dist_tpu.resilience.faults import (  # noqa: F401
    Fault,
    FaultPlan,
    InjectedFault,
    active_plan,
    battery,
    get_plan,
    inject,
    corrupt_fault,
    on_op_call,
    register_plan,
)
from triton_dist_tpu.resilience.integrity import (  # noqa: F401
    CheckpointCorruptError,
    IntegrityError,
    maybe_corrupt,
    payload_digest,
    verify_payload,
)
from triton_dist_tpu.resilience.watchdog import (  # noqa: F401
    CommTimeoutError,
    HealthTracker,
    Watchdog,
    block_until_ready,
)
from triton_dist_tpu.resilience.policy import (  # noqa: F401
    FallbackPolicy,
    RetryPolicy,
    health_probe,
    note_failure,
    reset as reset_policy,
    should_fallback,
)
