"""Fault-injection registry and hooks for the interpret-mode comm path.

The signal/wait protocols this package is built on (ring puts certified
by DMA semaphores, scoreboard edge semaphores, entry barriers) are
exactly where a lost signal or a stalled remote DMA turns into a silent
hang or a corrupted tile. This module makes those failures *injectable*:
a :class:`FaultPlan` names a set of :class:`Fault` events, and thin
hooks in ``lang.shmem_device`` (puts / signals / barriers), the fused
ops (call counting), ``utils.distributed.interpret_arg`` (DMA-timing
overrides), and the megakernel builder (scoreboard edges) consult the
active plan at kernel-trace time.

USAGE — trace-time injection::

    from triton_dist_tpu.resilience import faults
    with faults.inject(faults.get_plan("skewed_barrier", op="ag_gemm",
                                       rank=2)):
        out = fresh_jitted_ag_gemm(a, b)   # trace INSIDE the scope

Faults are baked in when the kernel is traced, so callers must build a
fresh jitted closure inside the ``inject`` scope (the test harness
does); a function traced before the scope keeps its fault-free schedule.

Fault kinds (``Fault.kind``):

- ``"delay_dma"``  — spin ``iters`` dependent FLOP iterations on
  ``rank`` before issuing the ``k``-th remote put of ``op`` (``k=None``
  = every put). Plans may also set ``dma_on_wait=True`` to flip the
  interpreter's DMA completion to the maximally-late schedule
  (``InterpretParams(dma_execution_mode="on_wait")`` — newer-JAX
  thread-per-device interpreter only).
- ``"drop_put"``   — the ``k``-th remote put of ``op`` is never issued
  on ``rank``: no data, no send/recv semaphore counts.
- ``"dup_put"``    — the ``k``-th remote put of ``op`` is issued twice
  on ``rank``: duplicated data and doubled semaphore counts.
- ``"drop_signal"``/``"dup_signal"`` — a ``dl.notify`` increment from
  ``rank`` is dropped / doubled.
- ``"skew_barrier"`` — ``rank`` spins ``iters`` iterations before its
  entry-barrier arrival (vacuous under the bulk-synchronous discharge
  interpreter, where barriers are no-ops — see ``utils/compat.py``).
- ``"drop_edge"``  — the megakernel scoreboard signal for edge index
  ``k`` is never raised (every rank; the merged queue is SPMD). Unlike
  the put/call kinds, ``k=None`` here selects edge 0, not "all edges"
  (the builder suppresses exactly one edge's signal per plan).
- ``"fail_call"``  — the ``k``-th host-level call of ``op`` raises
  :class:`InjectedFault` (drives the watchdog / fallback machinery).
- ``"timeout_call"`` — the ``k``-th host-level call of ``op`` raises a
  :class:`~triton_dist_tpu.resilience.watchdog.CommTimeoutError`
  directly: the deterministic stand-in for "the transfer wedged and
  the watchdog fired" (a real wedge leaks an uncancellable worker
  thread — see the watchdog caveat — so soak-style tests inject the
  *detected* outcome instead; the genuine-deadlock plans stay in the
  subprocess harness). The serving retry/backoff and containment
  paths treat it exactly like a watchdog miss.
- ``"corrupt_payload"`` — the ``k``-th host-staged payload of ``op``
  (``tier_transfer`` / ``page_migration`` / ``fleet_handoff``) gets a
  seeded bit flip applied to a COPY of its staged bytes before the
  consuming edge verifies the digest (``iters`` seeds which bit;
  ``k=None`` = every staged payload). Consulted via
  :func:`corrupt_fault` by ``resilience.integrity.maybe_corrupt`` —
  the model of silent wire/storage corruption the end-to-end payload
  digests exist to catch (docs/resilience.md, "Payload integrity").
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Optional, Tuple

__all__ = [
    "Fault", "FaultPlan", "InjectedFault", "inject", "active_plan",
    "on_op_call", "corrupt_fault", "register_plan", "get_plan",
    "battery",
]


class InjectedFault(RuntimeError):
    """Raised by a ``fail_call`` fault at the targeted op invocation."""

    def __init__(self, op: str, call_index: int):
        self.op = op
        self.call_index = call_index
        super().__init__(
            f"injected fault: call #{call_index} of op {op!r}")


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    op: str = "*"                 # op name, or "*" = any op
    rank: int = -1                # target rank along the op's axis
    k: Optional[int] = None      # which put / call (None = all);
                                 # drop_edge: which edge (None = 0)
    iters: int = 0               # spin length for delay/skew kinds

    def matches_op(self, op: str) -> bool:
        return self.op == "*" or self.op == op


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A named, replayable adversarial schedule."""
    name: str
    faults: Tuple[Fault, ...] = ()
    # Newer-JAX interpreter: defer every DMA's completion to its wait
    # (the maximally-late arrival schedule).
    dma_on_wait: bool = False

    def faults_of(self, kind: str, op: str) -> Tuple[Fault, ...]:
        return tuple(f for f in self.faults
                     if f.kind == kind and f.matches_op(op))


# ---------------------------------------------------------------------------
# Active-plan state. Trace-time counters are keyed per op occurrence so
# "the k-th put of the op" is well-defined within one inject() scope.
# ---------------------------------------------------------------------------

_STATE = threading.local()


def _st():
    if not hasattr(_STATE, "plan"):
        _STATE.plan = None
        _STATE.op_stack = []
        _STATE.call_counts = {}
        _STATE.put_counts = {}
        _STATE.corrupt_counts = {}
    if not hasattr(_STATE, "corrupt_counts"):   # upgraded mid-thread
        _STATE.corrupt_counts = {}
    return _STATE


def active_plan() -> Optional[FaultPlan]:
    return _st().plan


def current_op() -> Optional[str]:
    st = _st()
    return st.op_stack[-1] if st.op_stack else None


@contextlib.contextmanager
def inject(plan: FaultPlan):
    """Activate ``plan`` for code traced inside the scope."""
    st = _st()
    prev = st.plan
    st.plan = plan
    st.call_counts = {}
    st.put_counts = {}
    st.corrupt_counts = {}
    try:
        yield plan
    finally:
        st.plan = prev


@contextlib.contextmanager
def _op_scope(op: str):
    st = _st()
    st.op_stack.append(op)
    # Save/restore so a nested same-op scope (an op composed from
    # another op) cannot clobber the outer scope's k-th-put counter.
    prev_puts = st.put_counts.get(op)
    st.put_counts[op] = 0
    try:
        yield
    finally:
        st.op_stack.pop()
        if prev_puts is None:
            st.put_counts.pop(op, None)
        else:
            st.put_counts[op] = prev_puts


def on_op_call(op: str):
    """Host/trace-time hook at a fused op's public entry.

    Counts the invocation, raises :class:`InjectedFault` when a
    ``fail_call`` fault targets it, and returns a context manager
    scoping kernel-level hooks (puts/signals/barriers) to this op::

        with faults.on_op_call("ag_gemm"):
            ... core_call(...)  # traced under the op scope

    Free when no plan is active (returns a no-op scope).
    """
    st = _st()
    plan = st.plan
    if plan is None:
        return contextlib.nullcontext()
    idx = st.call_counts.get(op, 0)
    st.call_counts[op] = idx + 1
    for f in plan.faults_of("fail_call", op):
        if f.k is None or f.k == idx:
            raise InjectedFault(op, idx)
    for f in plan.faults_of("timeout_call", op):
        if f.k is None or f.k == idx:
            from triton_dist_tpu.resilience.watchdog import (
                CommTimeoutError)

            raise CommTimeoutError(
                op=op, timeout_s=0.0, progress={"call_index": idx},
                detail="injected wedge (timeout_call fault): the "
                       "deterministic stand-in for a watchdog miss")
    return _op_scope(op)


def corrupt_fault(op: str) -> Optional[Fault]:
    """``corrupt_payload`` fault (if any) targeting the host-staged
    payload of ``op`` being serialized right now.

    Counts payload stagings per op (its OWN counter — independent of
    the call/put counters, so a retried transfer that re-stages the
    payload advances it) and returns the matching fault, whose
    ``iters`` field seeds the bit flip. Consumed by
    ``resilience.integrity.maybe_corrupt``; free when no plan is
    active.
    """
    st = _st()
    plan = st.plan
    if plan is None:
        return None
    faults = plan.faults_of("corrupt_payload", op)
    if not faults:
        return None
    idx = st.corrupt_counts.get(op, 0)
    st.corrupt_counts[op] = idx + 1
    for f in faults:
        if f.k is None or f.k == idx:
            return f
    return None


# ---------------------------------------------------------------------------
# Kernel-side (trace-time) consultation, called from lang.shmem_device
# and the megakernel builder. All return None on the fault-free path.
# ---------------------------------------------------------------------------

def put_fault() -> Optional[Fault]:
    """Fault (if any) targeting the remote put being traced right now.

    Increments the per-op put counter as a side effect — call exactly
    once per traced put (``dl.remote_put`` does).

    drop_put/dup_put need rank-divergent control flow (``pl.when(me ==
    rank)`` around the DMA), which the old generic discharge
    interpreter cannot execute (divergent sites deadlock its hidden
    collectives) — and is vacuous there anyway, since its semaphore
    waits never block. Those kinds are skipped under that backend;
    delay_dma (a uniform spin) always applies.
    """
    st = _st()
    plan, op = st.plan, current_op()
    if plan is None or op is None:
        return None
    idx = st.put_counts.get(op, 0)
    st.put_counts[op] = idx + 1
    kinds = ("delay_dma",) if _divergent_flow_unsupported() else (
        "drop_put", "dup_put", "delay_dma")
    for kind in kinds:
        for f in plan.faults_of(kind, op):
            if f.k is None or f.k == idx:
                return f
    return None


def _divergent_flow_unsupported() -> bool:
    from triton_dist_tpu.utils import compat

    return compat.degraded_interpret()


def signal_fault() -> Optional[Fault]:
    """drop_signal/dup_signal fault scoped to the op being traced."""
    st = _st()
    plan, op = st.plan, current_op()
    if plan is None or op is None:
        return None
    for kind in ("drop_signal", "dup_signal"):
        for f in plan.faults_of(kind, op):
            return f
    return None


def barrier_fault() -> Optional[Fault]:
    """skew_barrier fault scoped to the op being traced."""
    st = _st()
    plan, op = st.plan, current_op()
    if plan is None or op is None:
        return None
    for f in plan.faults_of("skew_barrier", op):
        return f
    return None


def edge_drop(op: str) -> Optional[int]:
    """Scoreboard edge index whose completion signal must be dropped."""
    plan = _st().plan
    if plan is None:
        return None
    for f in plan.faults_of("drop_edge", op):
        return f.k if f.k is not None else 0
    return None


def interpret_overrides() -> Dict[str, object]:
    """Extra ``InterpretParams`` kwargs requested by the active plan
    (consulted by ``utils.distributed.interpret_arg``)."""
    plan = _st().plan
    if plan is not None and plan.dma_on_wait:
        return {"dma_execution_mode": "on_wait"}
    return {}


def spin(iters: int, seed):
    """Dependent-FLOP busy loop (the only skew source that exists on
    both the compiled and interpreted backends — ``pl.delay`` is a
    no-op under interpret mode). Returns a float32 scalar the caller
    must fold into an effectful op's operand (e.g. ``peer + spin*0``)
    so XLA cannot dead-code it away."""
    import jax
    import jax.numpy as jnp

    return jax.lax.fori_loop(
        0, iters, lambda _, x: x * 1.0000001 + 1e-7,
        jnp.float32(1.0) + jnp.asarray(seed, jnp.float32) * 0.0)


def rank_spin_zero(axis: str, rank: int, iters: int):
    """Traced int32 zero that costs ``iters`` spin iterations on
    ``rank`` (and nothing elsewhere). Add it to a device id or
    semaphore increment to inject skew without changing semantics."""
    import jax
    import jax.numpy as jnp

    me = jax.lax.axis_index(axis)
    s = jax.lax.cond(me == rank,
                     lambda: spin(iters, me),
                     lambda: jnp.float32(1.0))
    return (s * 0.0).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Named plan registry — the standard battery.
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, object] = {}


def register_plan(name: str, factory) -> None:
    """Register a plan factory: ``factory(op=..., rank=..., k=...,
    iters=...) -> FaultPlan``."""
    _REGISTRY[name] = factory


def get_plan(name: str, **kw) -> FaultPlan:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown fault plan {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kw)


def battery():
    """Names of the standard adversarial-schedule battery."""
    return sorted(_REGISTRY)


def _delayed_dma(op="*", rank=0, k=None, iters=20000):
    return FaultPlan(
        name="delayed_dma", dma_on_wait=True,
        faults=(Fault("delay_dma", op=op, rank=rank, k=k, iters=iters),))


def _dropped_signal(op="*", rank=0, k=0, **_):
    return FaultPlan(
        name="dropped_signal",
        faults=(Fault("drop_put", op=op, rank=rank, k=k),
                Fault("drop_signal", op=op, rank=rank)))


def _dup_signal(op="*", rank=0, k=0, **_):
    return FaultPlan(
        name="dup_signal",
        faults=(Fault("dup_put", op=op, rank=rank, k=k),
                Fault("dup_signal", op=op, rank=rank)))


def _skewed_barrier(op="*", rank=0, iters=20000, **_):
    return FaultPlan(
        name="skewed_barrier",
        faults=(Fault("skew_barrier", op=op, rank=rank, iters=iters),))


def _dropped_edge(op="megakernel", k=0, **_):
    return FaultPlan(
        name="dropped_edge",
        faults=(Fault("drop_edge", op=op, k=k),))


def _fail_kth_call(op="*", k=0, **_):
    return FaultPlan(
        name="fail_kth_call",
        faults=(Fault("fail_call", op=op, k=k),))


def _wedge_kth_call(op="*", k=0, **_):
    return FaultPlan(
        name="wedge_kth_call",
        faults=(Fault("timeout_call", op=op, k=k),))


def _corrupt_payload(op="tier_transfer", k=0, iters=0, **_):
    # ``iters`` seeds the flipped bit (integrity.maybe_corrupt);
    # k=None corrupts every staged payload of the op.
    return FaultPlan(
        name="corrupt_payload",
        faults=(Fault("corrupt_payload", op=op, k=k, iters=iters),))


register_plan("delayed_dma", _delayed_dma)
register_plan("dropped_signal", _dropped_signal)
register_plan("dup_signal", _dup_signal)
register_plan("skewed_barrier", _skewed_barrier)
register_plan("dropped_edge", _dropped_edge)
register_plan("fail_kth_call", _fail_kth_call)
register_plan("wedge_kth_call", _wedge_kth_call)
register_plan("corrupt_payload", _corrupt_payload)
