"""Process-level fault domain: the serving tick loop in a supervised
CHILD process, with crash/hang recovery that resumes client streams
token-exact.

Everything below the serving API already tolerates *injected* faults
(dropped transfers, wedged dispatches, corrupted payloads) — but an
actual process death (OOM kill, segfault in a native dep, a wedged
interpreter thread) takes the whole engine with it, and no in-process
machinery can recover from its own demise.  The supervisor splits the
fault domain:

- the **child** (``python -m triton_dist_tpu.resilience.supervisor
  --child``) owns the engine: it builds it from an importable factory
  (``module:qualname``), runs the tick loop, prints a heartbeat line
  every loop and a ``tok`` ack line for every emitted token, and
  writes a journaled keep-last-K checkpoint ring
  (``ckpt-<seq>.pkl`` + atomic ``ring.json``) every
  ``checkpoint_every`` working ticks;
- the **parent** (:class:`ServingSupervisor`) owns the request queue
  and the client-visible streams: it submits work over the child's
  stdin, folds ack lines into per-request token lists, and watches for
  failure — a child exit (any code, or code 0 with work left) is a
  *crash*; heartbeat silence past ``heartbeat_timeout_s`` is a
  *stall* (SIGKILLed, since a wedged thread cannot be cancelled).

Recovery: the parent picks the newest *good* snapshot by walking the
ring journal newest-first through
:func:`~triton_dist_tpu.serving.server.load_checkpoint` — a corrupt
entry (:class:`~triton_dist_tpu.resilience.integrity.
CheckpointCorruptError`) bumps ``restore_fallbacks`` and the walk
continues to its predecessor — then respawns the child with
``--restore`` and re-submits every non-terminal request.  The restored
child re-emits the FULL token history of every revived handle; the
parent dedupes acks by ``(request_id, token_index)`` — a replayed
index must carry an identical token (anything else is a divergence
bug and raises), a fresh index appends and fires the client
``stream_cb`` exactly once.  Replay is therefore idempotent and the
resumed stream is token-exact, even when the SIGKILL landed between a
token's emission and its ack reaching the pipe: acks are flushed
before the checkpoint that contains them is written, so a restored
snapshot can only ever be *behind* the acked stream, never ahead.

Usage::

    from triton_dist_tpu.resilience.supervisor import ServingSupervisor
    sup = ServingSupervisor("tests.test_supervisor:make_engine",
                            checkpoint_dir="/tmp/ring",
                            heartbeat_timeout_s=30.0,
                            checkpoint_every=2)
    sup.start()
    h = sup.submit([3, 1, 2], max_new_tokens=8)
    sup.run_until_done(deadline_s=120)     # pumps acks + liveness
    assert h.status == "done"
    sup.stop()

``run_supervised_soak`` in :mod:`~triton_dist_tpu.resilience.chaos`
drives this through a seeded SIGKILL/stall/corruption schedule and
gates every finished stream against an in-process oracle.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

__all__ = ["CheckpointRing", "ServingSupervisor", "SupervisedHandle",
           "SupervisorProtocolError"]

# Child -> parent line protocol marker.  Every structured event is one
# line: the prefix + a compact JSON object with an ``ev`` tag.  Lines
# without the prefix (stray library prints in the child) are ignored.
_SUP_PREFIX = "TDT-SUP "

_TERMINAL = ("done", "failed", "timeout", "shed")


class SupervisorProtocolError(RuntimeError):
    """The child's ack stream violated the protocol (a token index gap,
    or a replayed index with a different token) — a supervisor bug, not
    a survivable fault; never silently re-emit."""


# ---------------------------------------------------------------------------
# Checkpoint ring (written by the child, walked by the parent)
# ---------------------------------------------------------------------------

class CheckpointRing:
    """Journaled keep-last-K snapshot ring in one directory.

    Files: ``ckpt-<seq>.pkl`` (versioned envelopes via
    :func:`~triton_dist_tpu.serving.server.save_checkpoint`) plus
    ``ring.json`` — the journal, written atomically (tmp + rename) so
    a crash mid-append leaves the previous journal intact.  The
    journal lists entries oldest-first; :meth:`entries` returns them
    newest-first, which is the parent's restore walk order.
    """

    JOURNAL = "ring.json"

    def __init__(self, dirpath: str, *, keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.dir = dirpath
        self.keep = keep
        os.makedirs(dirpath, exist_ok=True)
        self._journal = self._read_journal()
        self._seq = (self._journal[-1]["seq"] + 1) if self._journal \
            else 0

    def _read_journal(self) -> List[dict]:
        path = os.path.join(self.dir, self.JOURNAL)
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            return list(data.get("entries", []))
        except (OSError, ValueError):
            return []

    def _write_journal(self) -> None:
        path = os.path.join(self.dir, self.JOURNAL)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"entries": self._journal}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def append(self, snap, *, tick: int) -> str:
        """Write one snapshot, journal it, prune past ``keep``.
        Returns the checkpoint path."""
        from triton_dist_tpu.serving.server import save_checkpoint
        seq = self._seq
        self._seq += 1
        name = f"ckpt-{seq:06d}.pkl"
        path = os.path.join(self.dir, name)
        save_checkpoint(snap, path)
        self._journal.append({"seq": seq, "file": name, "tick": tick})
        pruned = self._journal[:-self.keep]
        self._journal = self._journal[-self.keep:]
        self._write_journal()
        for ent in pruned:
            try:
                os.remove(os.path.join(self.dir, ent["file"]))
            except OSError:
                pass
        return path

    def entries(self) -> List[dict]:
        """Journal entries newest-first (each: seq / file / tick),
        re-read from disk — the parent calls this on a ring the child
        wrote."""
        return list(reversed(self._read_journal()))

    def newest_good(self, *, on_fallback: Optional[
            Callable[[str, Exception], None]] = None) -> Optional[str]:
        """Path of the newest loadable snapshot, walking past corrupt
        entries (``on_fallback(path, exc)`` fires per skip).  ``None``
        when the ring has no loadable snapshot."""
        from triton_dist_tpu.resilience.integrity import (
            CheckpointCorruptError)
        from triton_dist_tpu.serving.server import load_checkpoint
        for ent in self.entries():
            path = os.path.join(self.dir, ent["file"])
            try:
                load_checkpoint(path)
                return path
            except (CheckpointCorruptError, FileNotFoundError) as e:
                if on_fallback is not None:
                    on_fallback(path, e)
        return None


# ---------------------------------------------------------------------------
# Parent-side request handle
# ---------------------------------------------------------------------------

class SupervisedHandle:
    """Parent-side mirror of one request's stream.  ``tokens`` only
    ever grows by deduped, verified acks; ``stream_cb`` fires exactly
    once per token index across any number of child restarts."""

    def __init__(self, request_id: str, prompt: List[int],
                 kwargs: dict,
                 stream_cb: Optional[Callable[[int], None]] = None):
        self.request_id = request_id
        self.prompt = list(prompt)
        self.kwargs = dict(kwargs)
        self.stream_cb = stream_cb
        self.tokens: List[int] = []
        self.status = "queued"
        self.error: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.status in _TERMINAL

    def __repr__(self) -> str:
        return (f"SupervisedHandle({self.request_id!r}, "
                f"status={self.status!r}, n={len(self.tokens)})")


# ---------------------------------------------------------------------------
# Parent
# ---------------------------------------------------------------------------

class ServingSupervisor:
    """Run a serving engine's tick loop in a supervised child process
    (module docstring has the full protocol).

    ``factory`` is an importable ``"module:qualname"`` string (or a
    module-level callable, stringified) returning an engine exposing
    ``submit / step / checkpoint / restore / _drained``;
    ``factory_kwargs`` must be JSON-serializable.  ``heartbeat_
    timeout_s`` only arms after the first heartbeat — child startup
    (imports + engine build + first-tick compile) is covered by the
    separate ``startup_timeout_s`` grace.
    """

    def __init__(self, factory: Union[str, Callable], *,
                 checkpoint_dir: str,
                 heartbeat_timeout_s: float = 30.0,
                 checkpoint_every: int = 4,
                 ring_k: int = 3,
                 factory_kwargs: Optional[dict] = None,
                 startup_timeout_s: float = 300.0,
                 max_restarts: int = 50,
                 tick_throttle_s: float = 0.0,
                 telemetry: str = "counters"):
        if isinstance(factory, str):
            self.factory_spec = factory
        else:
            self.factory_spec = (f"{factory.__module__}:"
                                 f"{factory.__qualname__}")
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.checkpoint_every = int(checkpoint_every)
        self.ring_k = int(ring_k)
        self.factory_kwargs = dict(factory_kwargs or {})
        self.startup_timeout_s = float(startup_timeout_s)
        self.max_restarts = int(max_restarts)
        # A warmed-up tiny engine ticks in microseconds — faster than
        # the parent's pump cadence — so fault drills that must land
        # MID-stream (tests, the supervised soak) pace the child.
        # Production pacing is 0: the engine runs flat out.
        self.tick_throttle_s = float(tick_throttle_s)

        from triton_dist_tpu.obs.telemetry import Telemetry
        self.obs = Telemetry(telemetry)
        self.counters: Dict[str, int] = {
            "restarts": 0, "crashes": 0, "stalls": 0,
            "acked_tokens": 0, "dedup_dropped": 0,
            "restore_fallbacks": 0, "resubmitted": 0,
            "checkpoints": 0,
        }
        self.last_recovery_ms: Optional[float] = None
        self.handles: Dict[str, SupervisedHandle] = {}
        self._order: List[str] = []
        self._ids = 0
        self._proc: Optional[subprocess.Popen] = None
        self._buf = b""
        self._last_hb: Optional[float] = None
        self._spawned_at: Optional[float] = None
        self._recovery_t0: Optional[float] = None
        self._stopping = False
        self._child_n = 0

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._proc is not None:
            raise RuntimeError("supervisor already started")
        self._spawn(restore=None)

    def __enter__(self) -> "ServingSupervisor":
        if self._proc is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _spawn(self, restore: Optional[str]) -> None:
        from triton_dist_tpu.resilience.harness import (
            _child_env, _repo_root)
        cmd = [sys.executable, "-m",
               "triton_dist_tpu.resilience.supervisor", "--child",
               "--factory", self.factory_spec,
               "--factory-kwargs", json.dumps(self.factory_kwargs),
               "--checkpoint-dir", self.checkpoint_dir,
               "--checkpoint-every", str(self.checkpoint_every),
               "--ring-k", str(self.ring_k)]
        if self.tick_throttle_s > 0:
            cmd += ["--tick-sleep", str(self.tick_throttle_s)]
        if restore is not None:
            cmd += ["--restore", restore]
        # Child stderr goes to a per-incarnation log file, not a pipe:
        # an undrained stderr pipe can wedge the child on a full
        # buffer, and the log is the post-mortem for a crash.
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        self._child_n += 1
        log_path = os.path.join(
            self.checkpoint_dir, f"child-{self._child_n:03d}.log")
        self._stderr_log = open(log_path, "wb")
        self._proc = subprocess.Popen(
            cmd, env=_child_env(), cwd=_repo_root(),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr_log)
        os.set_blocking(self._proc.stdout.fileno(), False)
        self._buf = b""
        self._last_hb = None
        self._spawned_at = time.monotonic()

    def stop(self) -> None:
        """Graceful shutdown: ask the child to exit, then make sure."""
        proc = self._proc
        if proc is None:
            return
        self._stopping = True
        try:
            self._send({"cmd": "shutdown"})
        except (OSError, ValueError):
            pass
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        self._drain_output()
        for f in (proc.stdin, proc.stdout):
            try:
                f.close()
            except OSError:
                pass
        try:
            self._stderr_log.close()
        except OSError:
            pass
        self._proc = None

    # -- request API --------------------------------------------------

    def submit(self, prompt, *, request_id: Optional[str] = None,
               stream_cb: Optional[Callable[[int], None]] = None,
               **kwargs) -> SupervisedHandle:
        """Queue one request on the child.  ``kwargs`` pass through to
        the engine's ``Request`` (``max_new_tokens``, ``eos_id``,
        ``temperature``, ``top_k``, ``seed``) and must be
        JSON-serializable — they are replayed verbatim on every
        re-submit after a restart."""
        if self._proc is None:
            raise RuntimeError("supervisor not started")
        if request_id is None:
            request_id = f"sup-{self._ids}"
            self._ids += 1
        if request_id in self.handles:
            raise ValueError(f"duplicate request_id {request_id!r}")
        h = SupervisedHandle(request_id, list(prompt), kwargs,
                             stream_cb=stream_cb)
        self.handles[request_id] = h
        self._order.append(request_id)
        self._send_submit(h)
        return h

    def _send_submit(self, h: SupervisedHandle) -> None:
        self._send({"cmd": "submit", "rid": h.request_id,
                    "prompt": h.prompt, **h.kwargs})

    def _send(self, obj: dict) -> None:
        proc = self._proc
        if proc is None or proc.stdin is None:
            raise OSError("no child")
        data = (json.dumps(obj, separators=(",", ":")) + "\n").encode()
        try:
            proc.stdin.write(data)
            proc.stdin.flush()
        except (BrokenPipeError, OSError):
            # Child died with commands in flight; liveness check will
            # recover and re-submit from parent state.
            pass

    # -- fault injection hooks (tests / chaos) ------------------------

    def kill_child(self) -> None:
        """SIGKILL the child outright (the external-crash model)."""
        if self._proc is not None:
            self._proc.kill()

    def inject_crash(self) -> None:
        """Ask the child to ``os._exit`` at the next loop top (the
        internal-crash model — exercises the nonzero-exit path)."""
        self._send({"cmd": "crash"})

    def inject_stall(self, seconds: float = 3600.0) -> None:
        """Ask the child to stop heartbeating (sleep) — exercises the
        heartbeat-stall detection path."""
        self._send({"cmd": "stall", "s": float(seconds)})

    def inject_fault(self, plan: str, **plan_kw) -> None:
        """Activate a named fault plan inside the child for exactly one
        tick (the in-process fault families, e.g. ``corrupt_payload``)."""
        self._send({"cmd": "fault", "plan": plan, "kw": plan_kw})

    def checkpoint_now(self) -> None:
        """Force a ring checkpoint at the child's next tick boundary."""
        self._send({"cmd": "ckpt"})

    # -- pump ---------------------------------------------------------

    def pump(self) -> int:
        """Process pending child output, then run failure detection.
        Returns the number of protocol events handled.  Call this in
        the client's wait loop (or use :meth:`run_until_done`)."""
        n = self._drain_output()
        self._check_liveness()
        return n

    def run_until_done(self, *, deadline_s: float = 600.0,
                       poll_s: float = 0.02) -> None:
        """Pump until every submitted request is terminal."""
        t0 = time.monotonic()
        while not all(h.done for h in self.handles.values()):
            self.pump()
            if time.monotonic() - t0 > deadline_s:
                open_rids = [r for r, h in self.handles.items()
                             if not h.done]
                raise TimeoutError(
                    f"supervised run exceeded {deadline_s}s with "
                    f"{len(open_rids)} open requests: {open_rids[:8]}")
            time.sleep(poll_s)

    def stats(self) -> dict:
        out = dict(self.counters)
        out["last_recovery_ms"] = self.last_recovery_ms
        out["child_alive"] = bool(
            self._proc is not None and self._proc.poll() is None)
        out["open_requests"] = sum(
            1 for h in self.handles.values() if not h.done)
        return out

    # -- child output -------------------------------------------------

    def _drain_output(self) -> int:
        proc = self._proc
        if proc is None or proc.stdout is None:
            return 0
        fd = proc.stdout.fileno()
        while True:
            try:
                chunk = os.read(fd, 65536)
            except BlockingIOError:
                break
            except (OSError, ValueError):
                break
            if not chunk:
                break
            self._buf += chunk
        n = 0
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            text = line.decode("utf-8", "replace")
            if not text.startswith(_SUP_PREFIX):
                continue
            try:
                ev = json.loads(text[len(_SUP_PREFIX):])
            except ValueError:
                continue
            self._on_event(ev)
            n += 1
        return n

    def _on_event(self, ev: dict) -> None:
        kind = ev.get("ev")
        now = time.monotonic()
        if kind == "hb" or kind == "hello":
            self._last_hb = now
            if self._recovery_t0 is not None:
                # Recovery completes at the restored child's first
                # sign of life: detection -> kill -> ring walk ->
                # respawn -> engine rebuilt and restored.
                self.last_recovery_ms = \
                    (now - self._recovery_t0) * 1000.0
                self.obs.complete_span(
                    "supervise_restart", self._recovery_t0, now,
                    restarts=self.counters["restarts"])
                self._recovery_t0 = None
        elif kind == "tok":
            self._on_tok(ev["rid"], int(ev["i"]), int(ev["tok"]))
        elif kind == "done":
            h = self.handles.get(ev.get("rid"))
            if h is not None and not h.done:
                h.status = ev.get("status", "done")
                h.error = ev.get("error")
        elif kind == "ckpt":
            self.counters["checkpoints"] += 1
        elif kind == "reject":
            h = self.handles.get(ev.get("rid"))
            if h is not None and not h.done:
                h.status = "failed"
                h.error = ev.get("error", "rejected")

    def _on_tok(self, rid: str, i: int, tok: int) -> None:
        h = self.handles.get(rid)
        if h is None:
            return
        if i < len(h.tokens):
            # Replay of an already-acked index (restored child
            # re-emits full history): must be identical.
            if h.tokens[i] != tok:
                raise SupervisorProtocolError(
                    f"request {rid!r} token {i} diverged on replay: "
                    f"acked {h.tokens[i]}, child re-sent {tok}")
            self.counters["dedup_dropped"] += 1
            return
        if i > len(h.tokens):
            # Acks are flushed before the checkpoint containing them
            # is written, so a restored child can never legitimately
            # skip ahead of the acked stream.
            raise SupervisorProtocolError(
                f"request {rid!r} ack gap: have {len(h.tokens)} "
                f"tokens, child sent index {i}")
        h.tokens.append(tok)
        self.counters["acked_tokens"] += 1
        if h.stream_cb is not None:
            h.stream_cb(tok)

    # -- failure detection + recovery ---------------------------------

    def _check_liveness(self) -> None:
        proc = self._proc
        if proc is None:
            return
        rc = proc.poll()
        now = time.monotonic()
        if rc is not None:
            if self._stopping:
                return
            # Final lines may still sit in the pipe (incl. acks
            # emitted just before death) — fold them in BEFORE
            # deciding what needs re-submitting.
            self._drain_output()
            if all(h.done for h in self.handles.values()) and rc == 0:
                return  # clean exit with nothing left: not a crash
            self.counters["crashes"] += 1
            self._recover(reason=f"child exit rc={rc}")
        elif self._last_hb is None:
            if (self._spawned_at is not None
                    and now - self._spawned_at > self.startup_timeout_s):
                self.counters["stalls"] += 1
                self._recover(reason="startup timeout")
        elif now - self._last_hb > self.heartbeat_timeout_s:
            self.counters["stalls"] += 1
            self._recover(reason="heartbeat stall")

    def _recover(self, *, reason: str) -> None:
        if self.counters["restarts"] >= self.max_restarts:
            raise RuntimeError(
                f"supervisor exceeded max_restarts="
                f"{self.max_restarts} (last: {reason})")
        self._recovery_t0 = time.monotonic()
        self.obs.event("supervise_restart_begin", reason=reason)
        proc = self._proc
        if proc is not None:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            self._drain_output()
            for f in (proc.stdin, proc.stdout):
                try:
                    f.close()
                except OSError:
                    pass
            try:
                self._stderr_log.close()
            except OSError:
                pass
            self._proc = None

        def _fb(path, exc):
            self.counters["restore_fallbacks"] += 1
            self.obs.event("restore_fallback", path=path,
                           error=type(exc).__name__)

        ring = CheckpointRing(self.checkpoint_dir, keep=self.ring_k)
        restore = ring.newest_good(on_fallback=_fb)
        self.counters["restarts"] += 1
        self._spawn(restore=restore)
        # Re-submit everything non-terminal (in submission order).
        # The restored child ignores rids its snapshot already
        # revived; a request the snapshot predates (or a fresh child
        # with no snapshot) re-runs from the prompt — deterministic
        # decode regenerates the same tokens and the ack dedupe makes
        # the replay invisible to the client stream.
        for rid in self._order:
            h = self.handles[rid]
            if not h.done:
                self._send_submit(h)
                self.counters["resubmitted"] += 1


# ---------------------------------------------------------------------------
# Child entry
# ---------------------------------------------------------------------------

def _resolve_factory(spec: str) -> Callable:
    mod_name, _, qual = spec.partition(":")
    if not mod_name or not qual:
        raise ValueError(
            f"factory spec must be 'module:qualname', got {spec!r}")
    import importlib
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


def _child_out(ev: str, **kw) -> None:
    print(_SUP_PREFIX
          + json.dumps({"ev": ev, **kw}, separators=(",", ":")),
          flush=True)


def _child_main(args) -> int:
    from triton_dist_tpu.resilience import faults
    from triton_dist_tpu.serving.scheduler import Request

    factory = _resolve_factory(args.factory)
    srv = factory(**json.loads(args.factory_kwargs))
    ring = CheckpointRing(args.checkpoint_dir, keep=args.ring_k)

    handles: Dict[str, object] = {}
    emitted: Dict[str, int] = {}
    reported_done = set()

    if args.restore:
        from triton_dist_tpu.serving.server import load_checkpoint
        snap = load_checkpoint(args.restore)  # parent pre-validated
        for h in srv.restore(snap):
            rid = h.request.request_id
            handles[rid] = h
            # Re-emit the FULL history: the parent dedupes, and this
            # closes the window where an ack line died with the
            # previous child before reaching the pipe.
            emitted[rid] = 0
    _child_out("hello", pid=os.getpid(),
               restored=sorted(handles))

    # Raw non-blocking stdin with manual line assembly: buffered
    # readline() would slurp SEVERAL pending command lines into
    # Python's buffer while returning one, and select() on the then-
    # empty fd would leave the rest unread until new bytes arrive.
    stdin_fd = sys.stdin.fileno()
    os.set_blocking(stdin_fd, False)
    cmd_buf = b""
    tick = 0
    ticks_since_ckpt = 0
    force_ckpt = False
    crash_armed = False
    stall_s: Optional[float] = None
    one_tick_plan = None
    last_hb = 0.0
    shutdown = False

    def flush_acks() -> None:
        for rid, h in handles.items():
            toks = h.tokens
            for i in range(emitted[rid], len(toks)):
                _child_out("tok", rid=rid, i=i, tok=int(toks[i]))
            emitted[rid] = len(toks)
            if h.done and rid not in reported_done:
                reported_done.add(rid)
                err = getattr(h, "error", None)
                _child_out("done", rid=rid, status=h.status,
                           n=len(toks),
                           error=repr(err) if err else None)

    while True:
        # Drain every pending command before stepping.
        while True:
            try:
                chunk = os.read(stdin_fd, 65536)
            except BlockingIOError:
                break
            if not chunk:
                return 0  # parent closed stdin: orderly exit
            cmd_buf += chunk
        while b"\n" in cmd_buf:
            line, cmd_buf = cmd_buf.split(b"\n", 1)
            try:
                cmd = json.loads(line)
            except ValueError:
                continue
            op = cmd.get("cmd")
            if op == "submit":
                rid = cmd["rid"]
                if rid in handles:
                    continue  # restore already owns this stream
                kw = {k: v for k, v in cmd.items()
                      if k not in ("cmd", "rid", "prompt")}
                try:
                    h = srv.submit(Request(
                        prompt=list(cmd["prompt"]), request_id=rid,
                        **kw))
                except Exception as e:  # queue full / bad request
                    _child_out("reject", rid=rid, error=repr(e))
                    continue
                handles[rid] = h
                emitted[rid] = 0
            elif op == "crash":
                crash_armed = True
            elif op == "stall":
                stall_s = float(cmd.get("s", 3600.0))
            elif op == "fault":
                one_tick_plan = faults.get_plan(
                    cmd["plan"], **cmd.get("kw", {}))
            elif op == "ckpt":
                force_ckpt = True
            elif op == "shutdown":
                shutdown = True
        if crash_armed:
            os._exit(13)
        if stall_s is not None:
            # Model a wedged engine: no heartbeats, no acks.  The
            # parent SIGKILLs us mid-sleep; if it somehow doesn't,
            # resume (the sleep is the whole fault).
            time.sleep(stall_s)
            stall_s = None
        if shutdown:
            flush_acks()
            _child_out("bye", tick=tick)
            return 0

        # A prefill-only tick returns 0 decoded slots but is still
        # work — "worked" means a step RAN, so heartbeats and the
        # checkpoint cadence track ticks, not decode occupancy.
        worked = 0
        if not srv._drained():
            if one_tick_plan is not None:
                with faults.inject(one_tick_plan):
                    srv.step()
                one_tick_plan = None
            else:
                srv.step()
            worked = 1
            tick += 1
            ticks_since_ckpt += 1
            if args.tick_sleep > 0:
                time.sleep(args.tick_sleep)

        # Ack order matters: tokens reach the pipe BEFORE the
        # checkpoint containing them is written, so a restored
        # snapshot is never ahead of the acked stream.
        flush_acks()
        now = time.monotonic()
        if worked or now - last_hb >= 0.05:
            _child_out("hb", tick=tick)
            last_hb = now
        if force_ckpt or (args.checkpoint_every > 0 and worked
                          and ticks_since_ckpt >= args.checkpoint_every):
            path = ring.append(srv.checkpoint(), tick=tick)
            ticks_since_ckpt = 0
            force_ckpt = False
            _child_out("ckpt", path=path, tick=tick)
        if not worked:
            time.sleep(0.005)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--child", action="store_true", required=True)
    p.add_argument("--factory", required=True)
    p.add_argument("--factory-kwargs", default="{}")
    p.add_argument("--checkpoint-dir", required=True)
    p.add_argument("--checkpoint-every", type=int, default=4)
    p.add_argument("--ring-k", type=int, default=3)
    p.add_argument("--tick-sleep", type=float, default=0.0)
    p.add_argument("--restore", default=None)
    return _child_main(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
