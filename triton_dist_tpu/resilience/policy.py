"""Graceful degradation: per-op fallback onto the plain-XLA path.

Every fused op in this package has a semantically-equivalent XLA
collective form (the ``mode="xla"`` oracles). This module decides —
per op, automatically, logged once — when to take it:

- the platform cannot express the fused op at all (e.g. the old
  generic discharge interpreter cannot run rank-divergent one-sided
  puts — see ``utils/compat.py``);
- a fused dispatch raised at runtime (recorded via
  :func:`note_failure`; subsequent calls re-route);
- the operator forced it (``TRITON_DIST_TPU_FORCE_XLA="ag_gemm,p2p"``
  or ``"*"``);
- a startup :func:`health_probe` failed.

The fused ops consult :func:`should_fallback` at dispatch — ``ag_gemm``,
``gemm_rs``, ``all_to_all``, ``p2p``, ``broadcast``, ``ulysses_fused``,
and ``sp_ag_attention`` each route to their XLA oracle when it answers
True. ``ep_dispatch``/``ep_combine`` inherit the policy through the
``all_to_all`` transport they ride on (their drop-free mode is already
pure ``lax.ragged_all_to_all``), and ``flash_decode`` is pure XLA to
begin with, so neither consults the policy under its own name. The
model :class:`~triton_dist_tpu.models.engine.Engine` additionally wraps
whole prefill/decode dispatches (``fallback="xla"``) so a mid-flight
kernel failure degrades the serving path instead of killing it.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger("triton_dist_tpu.resilience")

__all__ = ["FallbackPolicy", "RetryPolicy", "should_fallback",
           "note_failure", "health_probe", "reset"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Deterministic retry-with-exponential-backoff for transient
    comm/op failures — the layer BETWEEN the watchdog (which detects a
    wedge) and the fail-one-request containment (which gives up).

    A retried op must be IDEMPOTENT at the caller: the serving paths
    that consume this (page migration, chunked prefill, the bench
    backend probe) all are — staging pages, two-phase prefix
    publication, and position-keyed append accounting make a replay
    write the same bytes to the same places.

    ``max_attempts`` counts total tries (1 = no retry). Delay before
    retry ``i`` (1-based) is ``base_delay_s * multiplier**(i-1)``,
    capped at ``max_delay_s``, plus a seeded jitter fraction in
    ``[0, jitter]`` — jitter is drawn from ``random.Random(seed)`` per
    :meth:`call`, so two runs with one seed sleep identically (the
    chaos harness and the tests replay schedules bit-for-bit).
    """

    max_attempts: int = 3
    base_delay_s: float = 0.0
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.0
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter is a fraction in [0, 1], got "
                             f"{self.jitter}")

    def delay_s(self, attempt: int, rng: Optional[random.Random] = None
                ) -> float:
        """Backoff before retry ``attempt`` (1-based: the sleep after
        the ``attempt``-th failure)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * rng.random()
        return d

    def delays(self) -> Tuple[float, ...]:
        """The full deterministic backoff schedule (one fresh seeded
        rng — what :meth:`call` will actually sleep)."""
        rng = random.Random(self.seed)
        return tuple(self.delay_s(i, rng)
                     for i in range(1, self.max_attempts))

    def call(self, fn: Callable, *, op: str = "",
             retry_on: Tuple = (Exception,),
             deadline_s: Optional[float] = None,
             on_retry: Optional[Callable] = None,
             event_cb: Optional[Callable] = None,
             sleep: Callable[[float], None] = time.sleep):
        """Run ``fn()`` under the policy; returns ``(result, attempts)``.

        Only exceptions matching ``retry_on`` are retried; anything
        else propagates immediately (a logic bug is not a transient).
        ``deadline_s`` bounds the TOTAL wall clock (monotonic): when the
        next backoff would land past it, the last error re-raises even
        with attempts left — the bench probe's budget semantics.
        ``on_retry(attempt, exc)`` fires before each backoff sleep
        (telemetry: the serving counters and ``probe_attempts`` hang
        off it). ``event_cb(kind, **attrs)`` — when given — receives
        the policy's timeline events (``"retry_backoff"`` with the
        scheduled delay before each sleep, ``"retry_giveup"`` when the
        attempts or the deadline exhaust); the serving telemetry layer
        passes its span-log emitter here so backoff schedules are
        trace-inspectable (docs/observability.md). ``sleep`` is
        injectable so tests never wall-clock.
        """
        rng = random.Random(self.seed)
        t_end = (None if deadline_s is None
                 else time.monotonic() + deadline_s)
        attempt = 0

        def _emit(kind, **attrs):
            if event_cb is not None:
                event_cb(kind, op=op, **attrs)

        while True:
            attempt += 1
            try:
                return fn(), attempt
            except retry_on as e:
                if attempt >= self.max_attempts:
                    _emit("retry_giveup", attempts=attempt,
                          error=type(e).__name__)
                    raise
                d = self.delay_s(attempt, rng)
                if t_end is not None and time.monotonic() + d > t_end:
                    _emit("retry_giveup", attempts=attempt,
                          error=type(e).__name__, deadline=True)
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                _emit("retry_backoff", attempt=attempt,
                      delay_s=round(d, 6), error=type(e).__name__)
                logger.warning(
                    "op %r attempt %d/%d failed (%r); retrying in "
                    "%.3fs", op or "<fn>", attempt, self.max_attempts,
                    e, d)
                if d > 0:
                    sleep(d)

    def run(self, fn: Callable, **kw):
        """:meth:`call` without the attempt count."""
        return self.call(fn, **kw)[0]

# Fused ops whose signal protocol is rank-divergent (one-sided puts
# issued under a rank-dependent predicate — ``me == root``, causal
# ``peer < n`` send pruning): inexpressible on the old bulk-synchronous
# discharge interpreter, which resolves remote DMA through uniform
# hidden collectives — a divergent site deadlocks the CPU mesh instead
# of failing. Routed to XLA up front.
DIVERGENT_PUT_OPS = frozenset(
    {"p2p", "ulysses_fused", "broadcast", "sp_ag_attention"})


class FallbackPolicy:
    """Per-op fused-vs-XLA dispatch decisions with log-once semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._failed: Dict[str, str] = {}
        self._logged: set = set()

    # -- queries ----------------------------------------------------------

    def forced_ops(self) -> frozenset:
        raw = os.environ.get("TRITON_DIST_TPU_FORCE_XLA", "")
        return frozenset(s.strip() for s in raw.split(",") if s.strip())

    def platform_unsupported(self, op: str) -> Optional[str]:
        from triton_dist_tpu.utils import compat

        if op in DIVERGENT_PUT_OPS and compat.degraded_interpret():
            return ("rank-divergent one-sided puts are inexpressible on "
                    "the generic discharge interpreter")
        return None

    def should_fallback(self, op: str) -> bool:
        forced = self.forced_ops()
        if "*" in forced or op in forced:
            self._log_once(op, "forced via TRITON_DIST_TPU_FORCE_XLA")
            return True
        reason = self.platform_unsupported(op)
        if reason is not None:
            self._log_once(op, reason)
            return True
        with self._lock:
            if op in self._failed:
                return True
        return False

    # -- recording --------------------------------------------------------

    def note_failure(self, op: str, exc: BaseException) -> None:
        """Record a fused-path failure; later calls of ``op`` fall back."""
        with self._lock:
            first = op not in self._failed
            self._failed[op] = repr(exc)
        if first:
            logger.warning(
                "fused op %r failed (%r); falling back to the XLA "
                "collective path for subsequent calls", op, exc)

    def _log_once(self, op: str, reason: str) -> None:
        key = (op, reason)
        with self._lock:
            if key in self._logged:
                return
            self._logged.add(key)
        logger.warning("op %r dispatching via XLA fallback: %s", op, reason)

    def reset(self) -> None:
        with self._lock:
            self._failed.clear()
            self._logged.clear()


_GLOBAL = FallbackPolicy()


def should_fallback(op: str) -> bool:
    return _GLOBAL.should_fallback(op)


def note_failure(op: str, exc: BaseException) -> None:
    _GLOBAL.note_failure(op, exc)


def reset() -> None:
    """Clear recorded failures (test scaffolding)."""
    _GLOBAL.reset()


def health_probe(mesh, axis: str = "tp", *, timeout_s: float = 120.0) -> bool:
    """Startup canary: run one tiny fused ``ag_gemm`` on ``mesh`` and
    check it against the XLA oracle under a deadline.

    Returns True when the fused comm path is healthy on this platform;
    False (after logging) on mismatch, exception, or timeout — callers
    (``Engine(fallback="xla", probe=True)``) then route through XLA.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import numpy as np

    from triton_dist_tpu.ops.ag_gemm import (
        ag_gemm, ag_gemm_ref, create_ag_gemm_context)
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.resilience.watchdog import (
        CommTimeoutError, Watchdog)

    mctx = MeshContext.from_mesh(mesh)
    n = mesh.shape[axis]
    m_loc, k, nn = 8, 128, 128
    a = jnp.arange(n * m_loc * k, dtype=jnp.float32).reshape(
        n * m_loc, k) / (m_loc * k)
    b = jnp.ones((k, nn), jnp.float32) / k
    ctx = create_ag_gemm_context(mctx, axis, block_m=m_loc, block_n=nn,
                                 block_k=k)

    def probe():
        # force_kernel=True: the canary must exercise the REAL fused
        # path — an already-active fallback (FORCE_XLA, a recorded
        # failure) would otherwise reroute it to the oracle and the
        # probe would compare XLA against XLA, vacuously healthy.
        run = jax.jit(jax.shard_map(
            lambda a_, b_: ag_gemm(a_, b_, ctx, force_kernel=True),
            mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(None, None), check_vma=False))
        ref = jax.jit(jax.shard_map(
            lambda a_, b_: ag_gemm_ref(a_, b_, axis=axis), mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(None, None), check_vma=False))
        out = jax.block_until_ready(run(a, b))
        want = jax.block_until_ready(ref(a, b))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        return True

    try:
        return Watchdog(timeout_s, op="health_probe[ag_gemm]").run(probe)
    except CommTimeoutError as e:
        logger.warning("health probe timed out: %s", e)
        return False
    except Exception as e:  # noqa: BLE001 — any failure means unhealthy
        logger.warning("health probe failed: %r", e)
        return False
