"""Graceful degradation: per-op fallback onto the plain-XLA path.

Every fused op in this package has a semantically-equivalent XLA
collective form (the ``mode="xla"`` oracles). This module decides —
per op, automatically, logged once — when to take it:

- the platform cannot express the fused op at all (e.g. the old
  generic discharge interpreter cannot run rank-divergent one-sided
  puts — see ``utils/compat.py``);
- a fused dispatch raised at runtime (recorded via
  :func:`note_failure`; subsequent calls re-route);
- the operator forced it (``TRITON_DIST_TPU_FORCE_XLA="ag_gemm,p2p"``
  or ``"*"``);
- a startup :func:`health_probe` failed.

The fused ops consult :func:`should_fallback` at dispatch — ``ag_gemm``,
``gemm_rs``, ``all_to_all``, ``p2p``, ``broadcast``, ``ulysses_fused``,
and ``sp_ag_attention`` each route to their XLA oracle when it answers
True. ``ep_dispatch``/``ep_combine`` inherit the policy through the
``all_to_all`` transport they ride on (their drop-free mode is already
pure ``lax.ragged_all_to_all``), and ``flash_decode`` is pure XLA to
begin with, so neither consults the policy under its own name. The
model :class:`~triton_dist_tpu.models.engine.Engine` additionally wraps
whole prefill/decode dispatches (``fallback="xla"``) so a mid-flight
kernel failure degrades the serving path instead of killing it.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger("triton_dist_tpu.resilience")

__all__ = ["FallbackPolicy", "should_fallback", "note_failure",
           "health_probe", "reset"]

# Fused ops whose signal protocol is rank-divergent (one-sided puts
# issued under a rank-dependent predicate — ``me == root``, causal
# ``peer < n`` send pruning): inexpressible on the old bulk-synchronous
# discharge interpreter, which resolves remote DMA through uniform
# hidden collectives — a divergent site deadlocks the CPU mesh instead
# of failing. Routed to XLA up front.
DIVERGENT_PUT_OPS = frozenset(
    {"p2p", "ulysses_fused", "broadcast", "sp_ag_attention"})


class FallbackPolicy:
    """Per-op fused-vs-XLA dispatch decisions with log-once semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._failed: Dict[str, str] = {}
        self._logged: set = set()

    # -- queries ----------------------------------------------------------

    def forced_ops(self) -> frozenset:
        raw = os.environ.get("TRITON_DIST_TPU_FORCE_XLA", "")
        return frozenset(s.strip() for s in raw.split(",") if s.strip())

    def platform_unsupported(self, op: str) -> Optional[str]:
        from triton_dist_tpu.utils import compat

        if op in DIVERGENT_PUT_OPS and compat.degraded_interpret():
            return ("rank-divergent one-sided puts are inexpressible on "
                    "the generic discharge interpreter")
        return None

    def should_fallback(self, op: str) -> bool:
        forced = self.forced_ops()
        if "*" in forced or op in forced:
            self._log_once(op, "forced via TRITON_DIST_TPU_FORCE_XLA")
            return True
        reason = self.platform_unsupported(op)
        if reason is not None:
            self._log_once(op, reason)
            return True
        with self._lock:
            if op in self._failed:
                return True
        return False

    # -- recording --------------------------------------------------------

    def note_failure(self, op: str, exc: BaseException) -> None:
        """Record a fused-path failure; later calls of ``op`` fall back."""
        with self._lock:
            first = op not in self._failed
            self._failed[op] = repr(exc)
        if first:
            logger.warning(
                "fused op %r failed (%r); falling back to the XLA "
                "collective path for subsequent calls", op, exc)

    def _log_once(self, op: str, reason: str) -> None:
        key = (op, reason)
        with self._lock:
            if key in self._logged:
                return
            self._logged.add(key)
        logger.warning("op %r dispatching via XLA fallback: %s", op, reason)

    def reset(self) -> None:
        with self._lock:
            self._failed.clear()
            self._logged.clear()


_GLOBAL = FallbackPolicy()


def should_fallback(op: str) -> bool:
    return _GLOBAL.should_fallback(op)


def note_failure(op: str, exc: BaseException) -> None:
    _GLOBAL.note_failure(op, exc)


def reset() -> None:
    """Clear recorded failures (test scaffolding)."""
    _GLOBAL.reset()


def health_probe(mesh, axis: str = "tp", *, timeout_s: float = 120.0) -> bool:
    """Startup canary: run one tiny fused ``ag_gemm`` on ``mesh`` and
    check it against the XLA oracle under a deadline.

    Returns True when the fused comm path is healthy on this platform;
    False (after logging) on mismatch, exception, or timeout — callers
    (``Engine(fallback="xla", probe=True)``) then route through XLA.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import numpy as np

    from triton_dist_tpu.ops.ag_gemm import (
        ag_gemm, ag_gemm_ref, create_ag_gemm_context)
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.resilience.watchdog import (
        CommTimeoutError, Watchdog)

    mctx = MeshContext.from_mesh(mesh)
    n = mesh.shape[axis]
    m_loc, k, nn = 8, 128, 128
    a = jnp.arange(n * m_loc * k, dtype=jnp.float32).reshape(
        n * m_loc, k) / (m_loc * k)
    b = jnp.ones((k, nn), jnp.float32) / k
    ctx = create_ag_gemm_context(mctx, axis, block_m=m_loc, block_n=nn,
                                 block_k=k)

    def probe():
        # force_kernel=True: the canary must exercise the REAL fused
        # path — an already-active fallback (FORCE_XLA, a recorded
        # failure) would otherwise reroute it to the oracle and the
        # probe would compare XLA against XLA, vacuously healthy.
        run = jax.jit(jax.shard_map(
            lambda a_, b_: ag_gemm(a_, b_, ctx, force_kernel=True),
            mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(None, None), check_vma=False))
        ref = jax.jit(jax.shard_map(
            lambda a_, b_: ag_gemm_ref(a_, b_, axis=axis), mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(None, None), check_vma=False))
        out = jax.block_until_ready(run(a, b))
        want = jax.block_until_ready(ref(a, b))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        return True

    try:
        return Watchdog(timeout_s, op="health_probe[ag_gemm]").run(probe)
    except CommTimeoutError as e:
        logger.warning("health probe timed out: %s", e)
        return False
    except Exception as e:  # noqa: BLE001 — any failure means unhealthy
        logger.warning("health probe failed: %r", e)
        return False
