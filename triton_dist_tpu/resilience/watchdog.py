"""Watchdogs and deadlines on host-visible futures.

A lost signal inside a comm kernel makes ``block_until_ready`` hang
with no diagnostic — indistinguishable from a slow step. The watchdog
bounds every host-side wait and converts a miss into a structured
:class:`CommTimeoutError` carrying rank, op name, and the last-completed
progress counter.

CAVEAT — in-process timeouts cannot *cancel* the stuck dispatch: the
worker thread stays blocked (daemonized) and the device it wedged may
be unusable for subsequent dispatches. The watchdog is therefore the
right tool for *serving* (fail the request, alert, drain the replica)
and for slow-but-terminating anomalies; the fault-injection *battery*
additionally isolates guaranteed-deadlock plans in a subprocess
(:mod:`~triton_dist_tpu.resilience.harness`) so a wedged interpreter
cannot poison the test process.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

__all__ = ["CommTimeoutError", "HealthTracker", "Watchdog",
           "block_until_ready"]


class CommTimeoutError(TimeoutError):
    """A bounded wait on a communication-dependent future expired.

    Fields: ``op`` (which dispatch), ``rank`` (host process index),
    ``timeout_s``, ``progress`` (last-completed step/scoreboard counter
    the caller could observe — e.g. decode-step number or megakernel
    queue slot), ``detail`` (free text).
    """

    def __init__(self, *, op: str, rank: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 progress: Any = None, detail: str = ""):
        self.op = op
        self.rank = rank
        self.timeout_s = timeout_s
        self.progress = progress
        self.detail = detail
        msg = (f"communication timeout in op {op!r}"
               f" on rank {rank}"
               f" after {timeout_s}s; last completed progress counter: "
               f"{progress!r}")
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class HealthTracker:
    """Heartbeat/progress-based liveness for one worker/role.

    The serving failover layer needs to separate "one transfer hit a
    transient" from "this worker is gone": a single timeout retries;
    ``fail_threshold`` CONSECUTIVE post-retry failures — or no
    heartbeat for ``dead_after_s`` while work was in flight — declare
    the role dead, and the caller fails over. ``beat()`` on every
    completed unit of work resets the streak; ``fail()`` records one
    exhausted-retries failure and returns whether the role just died.
    ``clock`` is injectable (fake-clock tests, the chaos harness).

    Observability: every ``fail``/``dead`` verdict is appended to
    ``history`` (a bounded ring of ``(clock_time, kind, cause)``
    tuples) and forwarded to ``on_event(kind, clock_time, cause)``
    when given — the serving telemetry layer wires this into its span
    timeline so role health reads off the same trace as the request
    spans (docs/observability.md). Beats reset streaks but are NOT
    forwarded (one per completed chunk would drown the log).
    """

    HISTORY = 64

    def __init__(self, *, fail_threshold: int = 3,
                 dead_after_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_event: Optional[Callable[[str, float, str],
                                             None]] = None):
        if fail_threshold < 1:
            raise ValueError(f"fail_threshold must be >= 1, got "
                             f"{fail_threshold}")
        self.fail_threshold = fail_threshold
        self.dead_after_s = dead_after_s
        self.clock = clock
        self.on_event = on_event
        self.consecutive_failures = 0
        self.total_failures = 0
        self.last_beat = clock()
        self.dead = False
        self.cause: Optional[str] = None
        from collections import deque

        self.history: "deque" = deque(maxlen=self.HISTORY)

    def _note(self, kind: str, cause: str) -> None:
        t = self.clock()
        self.history.append((t, kind, cause))
        if self.on_event is not None:
            self.on_event(kind, t, cause)

    def beat(self) -> None:
        """One unit of work completed — the role is alive."""
        self.consecutive_failures = 0
        self.last_beat = self.clock()

    def fail(self, cause: str = "") -> bool:
        """Record one (post-retry) failure; True iff this one crossed
        the death threshold (fires once — callers fail over exactly
        once per death)."""
        self.total_failures += 1
        self.consecutive_failures += 1
        self._note("fail", cause)
        if self.dead:
            return False
        if self.consecutive_failures >= self.fail_threshold:
            return self.declare_dead(
                cause or f"{self.consecutive_failures} consecutive "
                         "failures")
        return False

    def stalled(self) -> bool:
        """No heartbeat inside ``dead_after_s`` (None = never)."""
        return (self.dead_after_s is not None
                and self.clock() - self.last_beat > self.dead_after_s)

    def declare_dead(self, cause: str = "declared dead") -> bool:
        """Force the verdict (operator kill, chaos harness). True iff
        the role was alive until now."""
        if self.dead:
            return False
        self.dead = True
        self.cause = cause
        self._note("dead", cause)
        return True


def _default_rank() -> int:
    import jax

    try:
        return jax.process_index()
    except Exception:  # bring-up failure — rank unknown
        return -1


class Watchdog:
    """Bounded execution of blocking host calls.

    ``progress_fn`` (optional) is sampled when the deadline expires and
    becomes ``CommTimeoutError.progress`` — wire it to the engine's
    step counter / scoreboard position so a timeout names the last
    completed unit of work instead of just "it hung".
    """

    def __init__(self, timeout_s: float, *, op: str = "",
                 progress_fn: Optional[Callable[[], Any]] = None):
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got {timeout_s}")
        self.timeout_s = timeout_s
        self.op = op
        self.progress_fn = progress_fn

    def run(self, fn: Callable, *args, op: Optional[str] = None, **kwargs):
        """Run ``fn(*args, **kwargs)``; raise :class:`CommTimeoutError`
        if it does not return within the deadline."""
        if self.timeout_s is None:
            return fn(*args, **kwargs)
        result: list = []
        error: list = []

        def _target():
            try:
                result.append(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                error.append(e)

        t = threading.Thread(target=_target, daemon=True,
                             name=f"tdt-watchdog[{op or self.op}]")
        t.start()
        t.join(self.timeout_s)
        if t.is_alive():
            progress = None
            if self.progress_fn is not None:
                try:
                    progress = self.progress_fn()
                except Exception as e:  # progress probe itself wedged
                    progress = f"<progress_fn failed: {e!r}>"
            raise CommTimeoutError(
                op=op or self.op, rank=_default_rank(),
                timeout_s=self.timeout_s, progress=progress,
                detail="worker thread still blocked; the wedged dispatch "
                       "cannot be cancelled in-process")
        if error:
            raise error[0]
        return result[0]

    def block_until_ready(self, x, *, op: Optional[str] = None):
        import jax

        return self.run(jax.block_until_ready, x, op=op)


def block_until_ready(x, *, timeout_s: Optional[float], op: str,
                      progress_fn: Optional[Callable[[], Any]] = None):
    """``jax.block_until_ready`` with a deadline (None = unbounded)."""
    import jax

    if timeout_s is None:
        return jax.block_until_ready(x)
    return Watchdog(timeout_s, op=op,
                    progress_fn=progress_fn).block_until_ready(x)
