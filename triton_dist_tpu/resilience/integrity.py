"""End-to-end payload integrity for every host serialization boundary.

The serving stack moves KV pages across four boundaries where the
bytes leave the producing array and are reconstructed later: the tier
store (host spill / disk spill / promote), the disaggregated page
migration (prefill worker → decode pool, optionally through the p2p
bridge), the fleet session handoff (victim tier → target tier), and
the checkpoint pickle. None of those paths previously verified what
arrived — a flipped bit in a spilled page would be scattered back into
the decode pool and *served*. This module provides the digest contract
(ISSUE 16 / docs/resilience.md "Payload integrity"):

- :func:`payload_digest` — crc32c over dtype + shape + raw bytes of
  every array in the payload (quant scale planes included), computed
  ONCE at the producing edge and carried alongside the payload (tier
  ``TierEntry.meta["digest"]``, migration tuple, handoff meta,
  checkpoint envelope). Uses the ``crc32c`` library when the
  environment ships it, else ``zlib.crc32`` — same contract (a fixed
  32-bit checksum), and both sides of every boundary run in the same
  environment so the constant never mixes.
- :func:`verify_payload` — recompute at the consuming edge, raise
  :class:`IntegrityError` on mismatch. The *caller* routes the error
  into the recovery path that already exists at that boundary: tier
  get → quarantine + miss (recompute via re-prefill), migration →
  retry (source pool still authoritative) then re-prefill, handoff →
  retry then ``fleet_handoff_failed`` re-prefill, checkpoint restore →
  previous ring snapshot.
- :func:`maybe_corrupt` — the adversary: consults the active
  :class:`~triton_dist_tpu.resilience.faults.FaultPlan` for a
  ``corrupt_payload`` fault on the boundary's op and returns a COPY of
  the payload with one seeded bit flipped. Always a copy, never in
  place — ``tiers.get`` may alias the stored entry's arrays, and the
  fault models the *wire*, not the source of truth.

A digest is a detection contract, not a cryptographic one: crc32c
catches the silent bit flips and truncations this layer models; it is
not tamper-proofing.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from triton_dist_tpu.resilience import faults

__all__ = [
    "IntegrityError", "CheckpointCorruptError", "payload_digest",
    "digest_bytes", "verify_payload", "maybe_corrupt",
]

try:                                    # pragma: no cover - env-dependent
    from crc32c import crc32c as _crc32
except Exception:                       # noqa: BLE001 — any import issue
    from zlib import crc32 as _crc32


class IntegrityError(RuntimeError):
    """A payload failed its digest check at a consuming edge.

    ``boundary`` names the serialization boundary (``"tier_get"``,
    ``"page_migration"``, ``"fleet_handoff"``, ``"checkpoint"``);
    ``key`` identifies the payload when the boundary has one (tier
    key, request id, checkpoint path)."""

    def __init__(self, boundary: str, *, key=None,
                 want: Optional[int] = None, got: Optional[int] = None,
                 detail: str = ""):
        self.boundary = boundary
        self.key = key
        self.want = want
        self.got = got
        msg = (f"payload integrity violation at {boundary!r}"
               + (f" key={key!r}" if key is not None else "")
               + (f": digest {got:#010x} != expected {want:#010x}"
                  if want is not None and got is not None else "")
               + (f" ({detail})" if detail else ""))
        super().__init__(msg)


class CheckpointCorruptError(IntegrityError):
    """A checkpoint file is truncated, unpicklable, or fails its
    envelope digest — raised by ``serving.server.load_checkpoint``
    instead of a raw pickle traceback, so the supervisor's ring can
    fall back to the previous snapshot."""

    def __init__(self, path, detail: str = "", *, want=None, got=None):
        super().__init__("checkpoint", key=str(path), want=want,
                         got=got, detail=detail)
        self.path = path


def digest_bytes(data: bytes, crc: int = 0) -> int:
    """Fold ``data`` into a running 32-bit digest."""
    return _crc32(data, crc) & 0xFFFFFFFF


def payload_digest(arrays: Sequence) -> int:
    """crc32c over dtype, shape, and raw bytes of every array.

    Accepts numpy or jax arrays (jax arrays are pulled to host — the
    producing edges already stage on host, so this is free there).
    Folding dtype+shape means a reinterpreted or resliced payload of
    identical bytes still mismatches."""
    crc = 0
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        crc = digest_bytes(
            f"{a.dtype.str}:{a.shape};".encode("ascii"), crc)
        crc = digest_bytes(a.tobytes(), crc)
    return crc


def verify_payload(arrays: Sequence, want: Optional[int], *,
                   boundary: str, key=None) -> int:
    """Recompute the payload digest and compare against ``want``.

    Returns the recomputed digest. ``want=None`` (a payload produced
    before digests existed, e.g. a pre-upgrade tier entry) verifies
    vacuously — the digest contract is adopted at the producing edge,
    enforced at the consuming edge."""
    got = payload_digest(arrays)
    if want is not None and got != want:
        raise IntegrityError(boundary, key=key, want=want, got=got)
    return got


def _flip_one_bit(arrays: Tuple[np.ndarray, ...], seed: int):
    """Deterministically flip one bit across the payload's bytes."""
    sizes = [a.nbytes for a in arrays]
    total = sum(sizes)
    if total == 0:
        return arrays
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    bit = int(rng.randint(0, total * 8))
    byte, bitpos = divmod(bit, 8)
    for a, n in zip(arrays, sizes):
        if byte < n:
            flat = a.reshape(-1).view(np.uint8)
            flat[byte] ^= np.uint8(1 << bitpos)
            break
        byte -= n
    return arrays


def maybe_corrupt(arrays: Sequence, op: str) -> Tuple:
    """Apply an active ``corrupt_payload`` fault for ``op`` — the
    seeded adversary at a staging hop.

    Fault-free (the common case): returns ``arrays`` as a tuple,
    untouched and unconverted. Under a matching fault: returns DEEP
    COPIES with one bit flipped (seeded by ``Fault.iters``), so the
    producing side's arrays — which ``tiers.get`` may alias — stay
    pristine; only the simulated wire is corrupted."""
    f = faults.corrupt_fault(op)
    if f is None:
        return tuple(arrays)
    copies = tuple(
        np.array(np.ascontiguousarray(np.asarray(a))) for a in arrays)
    return _flip_one_bit(copies, int(f.iters) + 1)
