"""Inference engine (reference: ``models/engine.py:37`` ``Engine`` —
CUDA-graph capture :75, ``serve()`` decode loop :113).

TPU form: no CUDA-graph analogue is needed — ``jax.jit`` already compiles
the whole decode step into one XLA program (the role cudagraph capture
plays in the reference); donated KV-cache buffers keep decode in-place.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import dense
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.parallel.mesh import MeshContext


class Engine:
    """Greedy-decoding TP inference engine over a mesh.

    ``model`` is any module exposing the dense functional contract
    (``init_params`` / ``param_specs`` / ``prefill`` / ``decode_step`` /
    ``cache_specs``) — ``models.dense`` by default,
    ``models.qwen_next`` for the hybrid GDN family.
    """

    def __init__(self, cfg: ModelConfig, mesh: Mesh, *, axis: str = "tp",
                 mode: str = "xla", dtype=jnp.float32, max_len: int = 512,
                 params=None, seed: int = 0,
                 block_m: int = 256, block_n: int = 256,
                 block_k: int = 512, model=None):
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.mode = mode
        self.max_len = max_len
        model = model if model is not None else dense
        self.model = model
        mctx = MeshContext.from_mesh(mesh)
        self.ctxs = dense.make_fwd_contexts(mctx, axis, block_m, block_n,
                                            block_k)

        specs = model.param_specs(cfg, axis)
        if params is None:
            params = model.init_params(jax.random.PRNGKey(seed), cfg, dtype)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, jax.Array)
            or isinstance(x, np.ndarray))
        self._specs = specs

        def _prefill(params, ids):
            return model.prefill(params, ids, cfg, mode=mode, axis=axis,
                                 ctxs=self.ctxs, max_len=max_len)

        def _decode(params, tok, cache):
            return model.decode_step(params, tok, cache, cfg, mode=mode,
                                     axis=axis, ctxs=self.ctxs)

        kv_spec = model.cache_specs(axis)
        self._prefill = jax.jit(jax.shard_map(
            _prefill, mesh=mesh,
            in_specs=(specs, P(None, None)),
            out_specs=(P(None, None), kv_spec),
            check_vma=False))
        self._decode = jax.jit(jax.shard_map(
            _decode, mesh=mesh,
            in_specs=(specs, P(None), kv_spec),
            out_specs=(P(None, None), kv_spec),
            check_vma=False), donate_argnums=(2,))

    def prefill(self, input_ids) -> Tuple[jax.Array, KVCache]:
        input_ids = jnp.asarray(input_ids)
        # Host-side mirror of cache.length: lets decode() guard overruns
        # without forcing a device sync per generated token.
        self._host_len = int(input_ids.shape[1])
        return self._prefill(self.params, input_ids)

    def decode(self, tokens, cache) -> Tuple[jax.Array, KVCache]:
        # dynamic_update_slice clamps out-of-range starts, which would
        # silently overwrite the last cache slot — fail loudly instead.
        # The host counter tracks engine-driven prefill/decode; fall back
        # to a (synchronizing) device read for externally-built caches.
        length = getattr(self, "_host_len", None)
        if length is None:
            length = int(np.asarray(cache.length))
        if length >= self.max_len:
            raise ValueError(
                f"KV cache full ({self.max_len}); cannot decode further")
        out = self._decode(self.params, tokens, cache)
        self._host_len = length + 1
        return out

    def serve(self, input_ids, gen_len: int = 32):
        """Greedy generation (reference ``Engine.serve`` decode loop,
        ``engine.py:113``). input_ids: (B, S) → (B, gen_len) tokens."""
        input_ids = jnp.asarray(input_ids)
        b, s = input_ids.shape
        if s + gen_len > self.max_len:
            raise ValueError(
                f"sequence {s}+{gen_len} exceeds max_len={self.max_len}")
        logits, cache = self.prefill(input_ids)
        out = []
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out.append(tok)
        for _ in range(gen_len - 1):
            logits, cache = self.decode(tok, cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out.append(tok)
        return jnp.stack(out, axis=1)
