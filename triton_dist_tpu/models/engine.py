"""Inference engine (reference: ``models/engine.py:37`` ``Engine`` —
CUDA-graph capture :75, ``serve()`` decode loop :113).

TPU form: no CUDA-graph analogue is needed — ``jax.jit`` already compiles
the whole decode step into one XLA program (the role cudagraph capture
plays in the reference); donated KV-cache buffers keep decode in-place.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import dense
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.parallel.mesh import MeshContext


def _donated_lost(args) -> bool:
    """True when any array argument was already donated into the failed
    dispatch (decode donates the KV cache): a retry would dispatch on
    deleted buffers and mask the original error, so the caller must
    re-raise instead. Trace-time failures (the common fused-path case)
    happen before donation and retry safely."""
    for leaf in jax.tree.leaves(args):
        if isinstance(leaf, jax.Array) and leaf.is_deleted():
            return True
    return False


class Engine:
    """Greedy-decoding TP inference engine over a mesh.

    ``model`` is any module exposing the dense functional contract
    (``init_params`` / ``param_specs`` / ``prefill`` / ``decode_step`` /
    ``cache_specs``) — ``models.dense`` by default,
    ``models.qwen_next`` for the hybrid GDN family.
    """

    def __init__(self, cfg: ModelConfig, mesh: Mesh, *, axis: str = "tp",
                 mode: str = "xla", dtype=jnp.float32, max_len: int = 512,
                 params=None, seed: int = 0,
                 block_m: int = 256, block_n: int = 256,
                 block_k: int = 512, model=None,
                 moe_impl: Optional[str] = None, ep_axis=None,
                 ep_capacity: Optional[int] = None,
                 ep_transport: Optional[str] = None,
                 fallback: Optional[str] = None, probe: bool = False,
                 timeout_s: Optional[float] = None):
        """``moe_impl`` selects the MoE regime for ``models.qwen_moe``
        ("tp" | "ep"); with ``"ep"`` the Engine builds the EPContext
        itself (reference: the Engine serving the MoE demo). ``ep_axis``
        is the expert axis name, or an ``(outer, inner)`` tuple for the
        hierarchical ICI-by-DCN dispatch (``create_ep2d_context``);
        ``ep_capacity`` opts into the capped-drop dispatch (see
        ``create_ep_context`` for the drop-free mode's memory scaling).
        ``ep_transport`` picks the DECODE dispatch path
        ("ar" | "ragged" | "ll" | "auto" — see
        :func:`triton_dist_tpu.layers.ep_moe.fwd_decode`); prefill
        always rides the full dispatch/combine. ``"auto"`` resolves
        against the tune cache at trace time with the actual decode
        batch shape.

        Resilience knobs:

        - ``fallback="xla"``: when a fused prefill/decode dispatch
          raises, log once, rebuild that dispatch with ``mode="xla"``
          (the plain-XLA collective path), and re-serve the request —
          graceful degradation instead of a dead replica. Retry is
          never attempted for a :class:`CommTimeoutError` — the wedged
          dispatch still holds the device (and on decode the KV cache
          was donated into it), so the timeout is re-raised as-is.
        - ``probe=True`` (with ``fallback``): run
          ``resilience.policy.health_probe`` at construction; if the
          fused comm path is unhealthy on this platform, start degraded
          immediately.
        - ``timeout_s``: bound every prefill/decode wait; a miss raises
          :class:`~triton_dist_tpu.resilience.CommTimeoutError`
          carrying rank, op, and the last-completed decode-step
          counter.
        """
        if fallback not in (None, "xla"):
            raise ValueError(f"fallback must be None or 'xla', "
                             f"got {fallback!r}")
        if probe and fallback is None:
            raise ValueError(
                "probe=True requires fallback='xla' — a failed probe "
                "has nowhere to degrade to otherwise")
        self.fallback = fallback
        self.timeout_s = timeout_s
        if probe and fallback == "xla" and mode != "xla":
            from triton_dist_tpu.resilience import policy as _policy

            if not _policy.health_probe(mesh, axis):
                _policy.note_failure(
                    f"engine[mode={mode}]",
                    RuntimeError("startup health probe failed"))
                mode = "xla"
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.mode = mode
        self.max_len = max_len
        model = model if model is not None else dense
        self.model = model
        mctx = MeshContext.from_mesh(mesh)
        self.ctxs = dense.make_fwd_contexts(mctx, axis, block_m, block_n,
                                            block_k)

        # A MoE-contract model (param_specs takes moe_impl) defaults to
        # TP experts when the caller didn't pick a regime — so
        # Engine(model=qwen_moe) works out of the box.
        import inspect

        takes_moe = "moe_impl" in inspect.signature(
            model.param_specs).parameters
        if moe_impl is None and takes_moe:
            moe_impl = "tp"
        if moe_impl is not None and not takes_moe:
            # Without this the call below dies in a confusing TypeError
            # inside param_specs (ADVICE r4).
            raise ValueError(
                f"moe_impl={moe_impl!r} given, but model "
                f"{getattr(model, '__name__', model)!r} is not a MoE "
                "model (its param_specs takes no moe_impl)")

        model_kwargs = {}
        if moe_impl is not None:
            from triton_dist_tpu.ops.ep_a2a import (
                create_ep_context, create_ep2d_context,
            )

            ep_ctx = None
            if moe_impl == "ep":
                if isinstance(ep_axis, (tuple, list)):
                    ep_ctx = create_ep2d_context(
                        mctx, num_experts=cfg.num_experts,
                        topk=cfg.num_experts_per_tok,
                        outer_axis=ep_axis[0], inner_axis=ep_axis[1])
                else:
                    ep_ctx = create_ep_context(
                        mctx, num_experts=cfg.num_experts,
                        topk=cfg.num_experts_per_tok,
                        capacity=ep_capacity, axis=ep_axis or axis)
            model_kwargs = {"moe_impl": moe_impl, "ep_ctx": ep_ctx}
            if ep_transport is not None:
                from triton_dist_tpu.layers.ep_moe import (
                    DECODE_TRANSPORTS)

                if ep_transport not in DECODE_TRANSPORTS:
                    raise ValueError(
                        f"ep_transport={ep_transport!r} not in "
                        f"{DECODE_TRANSPORTS}")
                if moe_impl != "ep":
                    raise ValueError(
                        "ep_transport is an EP decode knob; it needs "
                        f"moe_impl='ep' (got {moe_impl!r})")
                model_kwargs["transport"] = ep_transport
            spec_ep_axis = (tuple(ep_axis) if isinstance(
                ep_axis, (tuple, list)) else (ep_axis or axis))
            specs = model.param_specs(cfg, moe_impl=moe_impl, axis=axis,
                                      ep_axis=spec_ep_axis)
        else:
            specs = model.param_specs(cfg, axis)
        self.model_kwargs = model_kwargs
        self.ep_transport = (model_kwargs.get("transport")
                             if moe_impl == "ep" else None)
        if params is None:
            params = model.init_params(jax.random.PRNGKey(seed), cfg, dtype)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, jax.Array)
            or isinstance(x, np.ndarray))
        self._specs = specs

        self._prefill, self._decode = self._build(mode)

    def _build(self, mode):
        """Jit the prefill/decode dispatches for ``mode`` (called once
        at construction, and again with mode="xla" on degradation)."""
        model, cfg, axis = self.model, self.cfg, self.axis
        model_kwargs, specs = self.model_kwargs, self._specs
        max_len = self.max_len

        def _prefill(params, ids):
            return model.prefill(params, ids, cfg, mode=mode, axis=axis,
                                 ctxs=self.ctxs, max_len=max_len,
                                 **model_kwargs)

        def _decode(params, tok, cache):
            return model.decode_step(params, tok, cache, cfg, mode=mode,
                                     axis=axis, ctxs=self.ctxs,
                                     **model_kwargs)

        kv_spec = model.cache_specs(axis)
        pre = jax.jit(jax.shard_map(
            _prefill, mesh=self.mesh,
            in_specs=(specs, P(None, None)),
            out_specs=(P(None, None), kv_spec),
            check_vma=False))
        dec = jax.jit(jax.shard_map(
            _decode, mesh=self.mesh,
            in_specs=(specs, P(None), kv_spec),
            out_specs=(P(None, None), kv_spec),
            check_vma=False), donate_argnums=(2,))
        return pre, dec

    def _degrade(self):
        """Rebuild both dispatches on the plain-XLA collective path."""
        if self.mode != "xla":
            self.mode = "xla"
            self._prefill, self._decode = self._build("xla")

    def _dispatch(self, op: str, *args, retriable: bool = True):
        """Run one prefill/decode dispatch under the resilience policy:
        optional watchdog deadline, and (``fallback="xla"``) one
        degrade-and-retry when the fused path raises."""
        from triton_dist_tpu.resilience import policy as _policy
        from triton_dist_tpu.resilience.watchdog import (
            CommTimeoutError, block_until_ready)

        fn = self._prefill if op == "prefill" else self._decode
        try:
            out = fn(self.params, *args)
            if self.timeout_s is not None:
                out = block_until_ready(
                    out, timeout_s=self.timeout_s, op=f"engine.{op}",
                    progress_fn=lambda: getattr(self, "_host_len", None))
            return out
        except CommTimeoutError:
            raise          # wedged dispatch: inputs may be donated/lost
        except Exception as e:  # noqa: BLE001 — degrade-and-retry
            if (self.fallback != "xla" or self.mode == "xla"
                    or not retriable or _donated_lost(args)):
                raise
            _policy.note_failure(f"engine.{op}[mode={self.mode}]", e)
            self._degrade()
            return self._dispatch(op, *args, retriable=False)

    def prefill(self, input_ids) -> Tuple[jax.Array, KVCache]:
        input_ids = jnp.asarray(input_ids)
        out = self._dispatch("prefill", input_ids)
        # Host-side mirror of cache.length: lets decode() guard overruns
        # without forcing a device sync per generated token. Set only
        # after the dispatch is known-good so a raise cannot desync it.
        self._host_len = int(input_ids.shape[1])
        return out

    def decode(self, tokens, cache) -> Tuple[jax.Array, KVCache]:
        # dynamic_update_slice clamps out-of-range starts, which would
        # silently overwrite the last cache slot — fail loudly instead.
        # The host counter tracks engine-driven prefill/decode; fall back
        # to a (synchronizing) device read for externally-built caches.
        length = getattr(self, "_host_len", None)
        if length is None:
            length = int(np.asarray(cache.length))
        if length >= self.max_len:
            raise ValueError(
                f"KV cache full ({self.max_len}); cannot decode further")
        out = self._dispatch("decode", tokens, cache)
        # Advance only after _decode returned: a raised step must leave
        # the overflow guard exactly where it was.
        self._host_len = length + 1
        return out

    def serving(self, **kw):
        """Wrap this engine in a continuous-batching
        :class:`~triton_dist_tpu.serving.ServingEngine` (paged KV pool,
        request queue, streaming) — the production request path;
        :meth:`serve` below stays the fixed-batch loop it is token-
        exact against. Keyword args pass through (num_slots, page,
        policy, deadlines, ...)."""
        from triton_dist_tpu.serving import ServingEngine

        return ServingEngine(self, **kw)

    def serve(self, input_ids, gen_len: int = 32, *,
              temperature: float = 0.0, top_k: int = 0,
              seed: int = 0):
        """Token generation (reference ``Engine.serve`` decode loop,
        ``engine.py:113`` — greedy there; sampling is capability-plus).

        input_ids: (B, S) → (B, gen_len) tokens. ``temperature`` 0
        (default) is greedy argmax; > 0 samples from the softmax at
        that temperature, optionally truncated to the ``top_k``
        highest-probability tokens. Sampling is deterministic per
        ``seed`` (a fold of jax PRNG keys, one per step).
        """
        input_ids = jnp.asarray(input_ids)
        b, s = input_ids.shape
        if s + gen_len > self.max_len:
            raise ValueError(
                f"sequence {s}+{gen_len} exceeds max_len={self.max_len}")

        if top_k < 0 or top_k > self.cfg.vocab_size:
            raise ValueError(f"top_k={top_k} outside [0, vocab="
                             f"{self.cfg.vocab_size}]")

        def pick(logits, step):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            lg = logits.astype(jnp.float32) / temperature
            if top_k > 0:
                # O(V log k) threshold, not a full vocab sort per token.
                kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
                lg = jnp.where(lg < kth, -jnp.inf, lg)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            return jax.random.categorical(key, lg, axis=-1
                                          ).astype(jnp.int32)

        logits, cache = self.prefill(input_ids)
        out = [pick(logits, 0)]
        for i in range(gen_len - 1):
            logits, cache = self.decode(out[-1], cache)
            out.append(pick(logits, i + 1))
        return jnp.stack(out, axis=1)
