"""Qwen3-MoE model (reference: ``models/qwen_moe.py`` — Qwen3-MoE with
EP; demo model for the EP dispatch/combine stack).

Same transformer skeleton as :mod:`triton_dist_tpu.models.dense` with
the MLP replaced by a MoE block. Two parallelization regimes (mirroring
the reference's TP_MoE vs EP_MoE layers):

- ``moe_impl="tp"``: experts replicated, ffn dim sharded over tp —
  tokens stay sequence-parallel.
- ``moe_impl="ep"``: experts sharded over the axis; each rank routes its
  own token shard through the dispatch/combine all-to-all.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import tp_attn, ep_moe, tp_moe
from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import FwdContexts
from triton_dist_tpu.ops.ep_a2a import EPContext, create_ep_context
from triton_dist_tpu.parallel.mesh import MeshContext


def init_params(key, cfg: ModelConfig, dtype=jnp.float32) -> Dict:
    keys = jax.random.split(key, cfg.num_hidden_layers + 2)
    layers = []
    for li in range(cfg.num_hidden_layers):
        ka, km = jax.random.split(keys[li])
        layers.append({
            "attn": tp_attn.init(ka, cfg, dtype),
            "moe": ep_moe.init(km, cfg, dtype),
            "ln_attn": jnp.ones((cfg.hidden_size,), dtype),
            "ln_mlp": jnp.ones((cfg.hidden_size,), dtype),
        })
    emb = jax.random.normal(keys[-2], (cfg.vocab_size, cfg.hidden_size),
                            dtype) * 0.02
    lm_head = (emb if cfg.tie_word_embeddings else
               jax.random.normal(keys[-1],
                                 (cfg.vocab_size, cfg.hidden_size),
                                 dtype) * 0.02)
    return {"embed": emb, "layers": layers,
            "ln_f": jnp.ones((cfg.hidden_size,), dtype),
            "lm_head": lm_head}


def param_specs(cfg: ModelConfig, *, moe_impl: str = "tp",
                axis: str = "tp", ep_axis: str = "ep") -> Dict:
    moe_specs = (tp_moe.param_specs(axis, cfg) if moe_impl == "tp"
                 else ep_moe.param_specs(ep_axis, cfg))
    layer_spec = {
        "attn": tp_attn.param_specs(axis, cfg),
        "moe": moe_specs,
        "ln_attn": P(None),
        "ln_mlp": P(None),
    }
    return {"embed": P(None, None),
            "layers": [layer_spec] * cfg.num_hidden_layers,
            "ln_f": P(None),
            "lm_head": P(axis, None)}


def moe_ffn(moe, h, cfg: ModelConfig, *, moe_impl, mode, axis, ctxs,
            ep_ctx, moe_block_m=None):
    """One MoE FFN block in the requested parallel regime. ``moe`` is
    the MoE param dict (router/experts/shared); shared between
    ``qwen_moe`` and the hybrid ``qwen_next`` FFN. ``moe_block_m=None``
    takes the fused context's row tile (the Engine's ``block_m`` knob)."""
    if moe_impl == "tp":
        if mode == "fused" and ctxs.ag is not None:
            # Fully-fused pipeline: AG-fused grouped GEMM + Pallas
            # down-proj + fused RS epilogue (the reference's
            # ag_group_gemm/moe_reduce_rs layer pairing).
            return tp_moe.fwd_fused(
                moe, h, topk=cfg.num_experts_per_tok,
                num_experts=cfg.num_experts,
                mesh_ctx=ctxs.ag.mesh, axis=axis,
                block_m=(ctxs.ag.block_m if moe_block_m is None
                         else moe_block_m),
                block_n=ctxs.ag.block_n, block_k=ctxs.ag.block_k,
                norm_topk_prob=cfg.norm_topk_prob)
        return tp_moe.fwd(
            moe, h, topk=cfg.num_experts_per_tok,
            num_experts=cfg.num_experts, axis=axis,
            norm_topk_prob=cfg.norm_topk_prob)
    from triton_dist_tpu.ops.ep_a2a import EP2DContext

    if isinstance(ep_ctx, EP2DContext):
        return ep_moe.fwd_2d(moe, h, ep_ctx,
                             topk=cfg.num_experts_per_tok,
                             norm_topk_prob=cfg.norm_topk_prob)
    return ep_moe.fwd(moe, h, ep_ctx,
                      topk=cfg.num_experts_per_tok,
                      norm_topk_prob=cfg.norm_topk_prob)


def moe_ffn_decode(moe, h, cfg: ModelConfig, *, moe_impl, axis, ep_ctx,
                   transport=None, replicas=None, layer: int = 0,
                   counts=None):
    """Small-batch (decode) MoE FFN: TP experts via ``tp_moe.fwd_ar``
    (the GEMM+AR pairing), EP experts via ``ep_moe.fwd_decode`` with
    the decode ``transport`` knob (``"ar"`` masked-local + psum,
    ``"ragged"`` exact-splits round-trip, ``"ll"`` low-latency
    count-free quantized exchange, ``"ll2d"`` the hierarchical 2-hop
    ICI×DCN variant for an ``EP2DContext``, ``"auto"`` tune-cache
    winner — see :mod:`triton_dist_tpu.layers.ep_moe`). ``replicas`` is the FULL
    hot-expert replica state (:func:`ep_moe.init_replicas`); ``layer``
    selects its slice and the ll slot parity. ``counts`` (a list)
    collects this layer's per-expert routed counts."""
    from triton_dist_tpu.ops.ep_a2a import EP2DContext

    if moe_impl == "tp":
        return tp_moe.fwd_ar(moe, h, topk=cfg.num_experts_per_tok,
                             num_experts=cfg.num_experts, axis=axis,
                             norm_topk_prob=cfg.norm_topk_prob)
    if isinstance(ep_ctx, EP2DContext):
        ep_axis = (ep_ctx.outer_axis, ep_ctx.inner_axis)
    elif isinstance(ep_ctx, EPContext):
        ep_axis = ep_ctx.axis
    else:
        ep_axis = axis
    rep_layer = (ep_moe.replica_layer(replicas, layer)
                 if replicas is not None else None)
    return ep_moe.fwd_decode(moe, h, topk=cfg.num_experts_per_tok,
                             axis=ep_axis,
                             norm_topk_prob=cfg.norm_topk_prob,
                             transport=transport or "ar",
                             ep_ctx=(ep_ctx if isinstance(
                                 ep_ctx, (EPContext, EP2DContext))
                                 else None),
                             replicas=rep_layer, layer=layer,
                             counts=counts)


def _moe_block(lp, h, cfg: ModelConfig, *, moe_impl, mode, axis, ctxs,
               ep_ctx, moe_block_m=None):
    """Dense-trunk ``ffn_fn`` hook form (receives the whole layer
    param dict)."""
    return moe_ffn(lp["moe"], h, cfg, moe_impl=moe_impl, mode=mode,
                   axis=axis, ctxs=ctxs, ep_ctx=ep_ctx,
                   moe_block_m=moe_block_m)


def _moe_ffn_decode(lp, h, cfg: ModelConfig, *, moe_impl, axis, ep_ctx,
                    transport=None, replicas=None, counts=None,
                    _layer_cursor=None):
    """Dense-trunk decode hook form. ``_layer_cursor`` (a one-element
    list) tracks the layer index across the trunk's in-order ffn calls
    — the hook receives only the layer's params, but the replica slice
    and the ll slot parity are per-layer."""
    li = 0
    if _layer_cursor is not None:
        li = _layer_cursor[0]
        _layer_cursor[0] += 1
    return moe_ffn_decode(lp["moe"], h, cfg, moe_impl=moe_impl,
                          axis=axis, ep_ctx=ep_ctx, transport=transport,
                          replicas=replicas, layer=li, counts=counts)


def forward_tokens(params, input_ids, cfg: ModelConfig, *,
                   moe_impl: str = "tp", mode: str = "xla",
                   axis: str = "tp", ep_ctx: Optional[EPContext] = None,
                   ctxs: FwdContexts = FwdContexts(),
                   moe_block_m: Optional[int] = None):
    """Per-shard all-token forward → (B, S, vocab) logits.

    For ``moe_impl="ep"`` the residual stream is token-sharded along the
    *ep* axis (each rank owns its tokens); attention still runs TP over
    ``axis`` (= the same axis for a 1D mesh: tp and ep traffic share it,
    matching the reference's single-group EP demos). ``ep_ctx`` may be
    an :class:`EPContext` (flat) or ``EP2DContext`` (hierarchical
    ICI-then-DCN dispatch, ``ops/ep_a2a.ep_dispatch_2d``).

    The transformer trunk is ``dense._forward_trunk`` with the MoE
    block plugged in via its ``ffn_fn`` hook — one trunk, two models.
    """
    import functools

    from triton_dist_tpu.models.dense import _forward_trunk, _lm_head

    b, s = input_ids.shape
    ffn = functools.partial(_moe_block, cfg=cfg, moe_impl=moe_impl,
                            mode=mode, axis=axis, ctxs=ctxs,
                            ep_ctx=ep_ctx, moe_block_m=moe_block_m)
    x, _ = _forward_trunk(params, input_ids, cfg, mode=mode, axis=axis,
                          ctxs=ctxs, cache=None, ffn_fn=ffn)
    return _lm_head(params, x, axis).reshape(b, s, cfg.vocab_size)


# --- Engine serve contract (delegates to models.dense with the MoE
# --- ffn_fn hook) -----------------------------------------------------------

def cache_specs(axis: str = "tp"):
    from triton_dist_tpu.models import dense as _dense

    return _dense.cache_specs(axis)


def prefill(params, input_ids, cfg: ModelConfig, *, mode: str = "xla",
            axis: str = "tp", ctxs: FwdContexts = FwdContexts(),
            max_len: Optional[int] = None, moe_impl: str = "tp",
            ep_ctx: Optional[EPContext] = None,
            moe_block_m: Optional[int] = None, transport=None,
            replicas=None):
    """Per-shard prefill → (last-position logits (B, vocab), KVCache).
    Same contract as ``dense.prefill`` (the Engine's model protocol,
    reference ``Engine._init_model`` + ``DenseLLM.inference``).
    ``transport``/``replicas`` are decode-path knobs accepted here so
    one model_kwargs dict serves both dispatches; prefill always rides
    the full dispatch/combine path."""
    del transport, replicas
    import functools

    from triton_dist_tpu.models import dense as _dense

    ffn = functools.partial(_moe_block, cfg=cfg, moe_impl=moe_impl,
                            mode=mode, axis=axis, ctxs=ctxs,
                            ep_ctx=ep_ctx, moe_block_m=moe_block_m)
    return _dense.prefill(params, input_ids, cfg, mode=mode, axis=axis,
                          ctxs=ctxs, max_len=max_len, ffn_fn=ffn)


def decode_step(params, token_ids, cache, cfg: ModelConfig, *,
                mode: str = "xla", axis: str = "tp",
                ctxs: FwdContexts = FwdContexts(), moe_impl: str = "tp",
                ep_ctx=None, transport=None, replicas=None,
                with_expert_counts: bool = False):
    """One decode step on a replicated (B,) token batch — the dense
    decode loop with the MoE small-batch FFN plugged in.
    ``with_expert_counts=True`` appends the step's per-expert routed
    assignment counts (E,) int32, summed over layers, to the return
    tuple (the serving layer's load telemetry)."""
    import functools

    from triton_dist_tpu.models import dense as _dense

    counts = [] if with_expert_counts else None
    ffn = functools.partial(_moe_ffn_decode, cfg=cfg, moe_impl=moe_impl,
                            axis=axis, ep_ctx=ep_ctx,
                            transport=transport, replicas=replicas,
                            counts=counts, _layer_cursor=[0])
    out = _dense.decode_step(params, token_ids, cache, cfg, mode=mode,
                             axis=axis, ctxs=ctxs, ffn_fn=ffn)
    if not with_expert_counts:
        return out
    return out + (_sum_counts(counts, cfg),)


def _sum_counts(counts, cfg: ModelConfig):
    """Stack per-layer expert counts into one (E,) int32 vector (zeros
    when the TP regime collected nothing)."""
    if counts:
        return jnp.sum(jnp.stack(counts, axis=0), axis=0
                       ).astype(jnp.int32)
    return jnp.zeros((cfg.num_experts,), jnp.int32)


def paged_cache_specs(axis: str = "tp", quantized: bool = False):
    from triton_dist_tpu.models import dense as _dense

    return _dense.paged_cache_specs(axis, quantized=quantized)


def verify_step_paged(params, token_ids, cache, cfg: ModelConfig, *,
                      budget=None, mode: str = "xla", axis: str = "tp",
                      ctxs: FwdContexts = FwdContexts(),
                      attn_impl: str = "ref",
                      moe_impl: str = "tp", ep_ctx=None, transport=None,
                      replicas=None, with_expert_counts: bool = False):
    """Speculative K-token verification with the MoE FFN in the AR
    decode regime — like the prefill chunk, the verification block's
    S·K replicated rows fit the masked-local + psum expert path for
    any K, so the verify dispatch needs no transport of its own.
    ``transport``/``replicas``/counts are decode-dispatch knobs the
    verification contract ignores."""
    del transport, replicas, with_expert_counts
    import functools

    from triton_dist_tpu.models import dense as _dense

    ffn = functools.partial(_moe_ffn_decode, cfg=cfg, moe_impl=moe_impl,
                            axis=axis, ep_ctx=ep_ctx, transport="ar",
                            counts=None, _layer_cursor=[0])
    return _dense.verify_step_paged(params, token_ids, cache, cfg,
                                    budget=budget, mode=mode, axis=axis,
                                    ctxs=ctxs, attn_impl=attn_impl,
                                    ffn_fn=ffn)


def prefill_chunk_paged(params, chunk_toks, cache, table_row,
                        cfg: ModelConfig, *, start, wfrom, valid,
                        mode: str = "xla", axis: str = "tp",
                        ctxs: FwdContexts = FwdContexts(),
                        attn_impl: str = "ref",
                        moe_impl: str = "tp", ep_ctx=None, transport=None,
                        replicas=None, with_expert_counts: bool = False):
    """One bucketed chunk of a paged prefill with the MoE FFN in the
    AR decode regime (the chunk residual is replicated, so the
    masked-local + psum expert path is the transport that fits any
    chunk length exactly). ``transport``/``replicas``/counts are
    decode-dispatch knobs — prefill chunks ignore them; decode keeps
    its own resolved transport."""
    del transport, replicas, with_expert_counts
    import functools

    from triton_dist_tpu.models import dense as _dense

    ffn = functools.partial(_moe_ffn_decode, cfg=cfg, moe_impl=moe_impl,
                            axis=axis, ep_ctx=ep_ctx, transport="ar",
                            counts=None, _layer_cursor=[0])
    return _dense.prefill_chunk_paged(params, chunk_toks, cache,
                                      table_row, cfg, start=start,
                                      wfrom=wfrom, valid=valid,
                                      mode=mode, axis=axis, ctxs=ctxs,
                                      attn_impl=attn_impl, ffn_fn=ffn)


def decode_step_paged(params, token_ids, cache, cfg: ModelConfig, *,
                      mode: str = "xla", axis: str = "tp",
                      ctxs: FwdContexts = FwdContexts(),
                      attn_impl: str = "ref", moe_impl: str = "tp",
                      ep_ctx=None, transport=None, replicas=None,
                      with_expert_counts: bool = False):
    """Continuous-batching decode over a PagedKVCache — the dense
    serving step with the MoE small-batch FFN plugged in (the
    ServingEngine's model contract). ``transport`` routes the EP
    dispatch (see :func:`moe_ffn_decode`); ``replicas`` is the full
    hot-expert replica state (data, refreshed between steps);
    ``with_expert_counts=True`` appends the step's (E,) int32 expert
    counts to the return tuple."""
    import functools

    from triton_dist_tpu.models import dense as _dense

    counts = [] if with_expert_counts else None
    ffn = functools.partial(_moe_ffn_decode, cfg=cfg, moe_impl=moe_impl,
                            axis=axis, ep_ctx=ep_ctx,
                            transport=transport, replicas=replicas,
                            counts=counts, _layer_cursor=[0])
    out = _dense.decode_step_paged(params, token_ids, cache, cfg,
                                   mode=mode, axis=axis, ctxs=ctxs,
                                   attn_impl=attn_impl, ffn_fn=ffn)
    if not with_expert_counts:
        return out
    return out + (_sum_counts(counts, cfg),)
