"""Qwen3-MoE model (reference: ``models/qwen_moe.py`` — Qwen3-MoE with
EP; demo model for the EP dispatch/combine stack).

Same transformer skeleton as :mod:`triton_dist_tpu.models.dense` with
the MLP replaced by a MoE block. Two parallelization regimes (mirroring
the reference's TP_MoE vs EP_MoE layers):

- ``moe_impl="tp"``: experts replicated, ffn dim sharded over tp —
  tokens stay sequence-parallel.
- ``moe_impl="ep"``: experts sharded over the axis; each rank routes its
  own token shard through the dispatch/combine all-to-all.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import tp_attn, ep_moe, tp_moe
from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import FwdContexts
from triton_dist_tpu.ops.ep_a2a import EPContext, create_ep_context
from triton_dist_tpu.parallel.mesh import MeshContext


def init_params(key, cfg: ModelConfig, dtype=jnp.float32) -> Dict:
    keys = jax.random.split(key, cfg.num_hidden_layers + 2)
    layers = []
    for li in range(cfg.num_hidden_layers):
        ka, km = jax.random.split(keys[li])
        layers.append({
            "attn": tp_attn.init(ka, cfg, dtype),
            "moe": ep_moe.init(km, cfg, dtype),
            "ln_attn": jnp.ones((cfg.hidden_size,), dtype),
            "ln_mlp": jnp.ones((cfg.hidden_size,), dtype),
        })
    emb = jax.random.normal(keys[-2], (cfg.vocab_size, cfg.hidden_size),
                            dtype) * 0.02
    lm_head = (emb if cfg.tie_word_embeddings else
               jax.random.normal(keys[-1],
                                 (cfg.vocab_size, cfg.hidden_size),
                                 dtype) * 0.02)
    return {"embed": emb, "layers": layers,
            "ln_f": jnp.ones((cfg.hidden_size,), dtype),
            "lm_head": lm_head}


def param_specs(cfg: ModelConfig, *, moe_impl: str = "tp",
                axis: str = "tp", ep_axis: str = "ep") -> Dict:
    moe_specs = (tp_moe.param_specs(axis) if moe_impl == "tp"
                 else ep_moe.param_specs(ep_axis))
    layer_spec = {
        "attn": tp_attn.param_specs(axis),
        "moe": moe_specs,
        "ln_attn": P(None),
        "ln_mlp": P(None),
    }
    return {"embed": P(None, None),
            "layers": [layer_spec] * cfg.num_hidden_layers,
            "ln_f": P(None),
            "lm_head": P(axis, None)}


def forward_tokens(params, input_ids, cfg: ModelConfig, *,
                   moe_impl: str = "tp", mode: str = "xla",
                   axis: str = "tp", ep_ctx: Optional[EPContext] = None,
                   ctxs: FwdContexts = FwdContexts(),
                   moe_block_m: int = 64):
    """Per-shard all-token forward → (B, S, vocab) logits.

    For ``moe_impl="ep"`` the residual stream is token-sharded along the
    *ep* axis (each rank owns its tokens); attention still runs TP over
    ``axis`` (= the same axis for a 1D mesh: tp and ep traffic share it,
    matching the reference's single-group EP demos).
    """
    from triton_dist_tpu.models.dense import _embed_tokens, _lm_head

    b, s = input_ids.shape
    x = _embed_tokens(params, input_ids, mode=mode, axis=axis)

    for lp in params["layers"]:
        h = rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps)
        attn_out, _ = tp_attn.fwd_prefill(
            lp["attn"], h, cfg, batch=b, mode=mode, axis=axis,
            ag_ctx=ctxs.ag, rs_ctx=ctxs.rs, ar_ctx=ctxs.ar)
        x = x + attn_out
        h = rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps)
        if moe_impl == "tp":
            if mode == "fused" and ctxs.ag is not None:
                # Fully-fused pipeline: AG-fused grouped GEMM + Pallas
                # down-proj + fused RS epilogue (the reference's
                # ag_group_gemm/moe_reduce_rs layer pairing).
                moe_out = tp_moe.fwd_fused(
                    lp["moe"], h, topk=cfg.num_experts_per_tok,
                    num_experts=cfg.num_experts,
                    mesh_ctx=ctxs.ag.mesh, axis=axis,
                    block_m=moe_block_m,
                    norm_topk_prob=cfg.norm_topk_prob)
            else:
                moe_out = tp_moe.fwd(
                    lp["moe"], h, topk=cfg.num_experts_per_tok,
                    num_experts=cfg.num_experts, axis=axis,
                    norm_topk_prob=cfg.norm_topk_prob)
        else:
            moe_out = ep_moe.fwd(lp["moe"], h, ep_ctx,
                                 topk=cfg.num_experts_per_tok,
                                 norm_topk_prob=cfg.norm_topk_prob)
        x = x + moe_out

    from triton_dist_tpu.models.dense import _lm_head

    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    if mode in ("xla", "fused"):
        x = jax.lax.all_gather(x, axis, axis=0, tiled=True)
    return _lm_head(params, x, axis).reshape(b, s, cfg.vocab_size)


