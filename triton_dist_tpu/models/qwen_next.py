"""Qwen3-Next-style hybrid model: GDN linear-attention layers with a
full-attention layer every ``cfg.full_attn_interval``.

Reference capability: ``kernels/nvidia/gdn.py`` ships the chunked
gated-delta-rule kernel *for* Qwen3-Next; this module supplies the model
family around it (the reference's models/ tree stops at dense +
Qwen3-MoE). Same functional conventions as
:mod:`triton_dist_tpu.models.dense`: ``init_params`` / ``param_specs`` /
``forward_tokens`` / ``prefill`` / ``decode_step`` run inside
``shard_map``; mode "xla" is the lax-collective oracle, "fused" rides
ag_gemm/gemm_rs (prefill) and gemm_ar (decode).

The hybrid cache pairs the softmax layers' :class:`KVCache` with the GDN
layers' recurrent states (B, H_loc, dk, dv) — constant memory in
sequence length, the point of the architecture for long context.

MoE configs (``cfg.is_moe``, e.g. ``qwen3_next_80b_a3b``) replace the
dense FFN with a TP-MoE block: grouped SwiGLU over the local ffn shard
(fused AG-grouped-GEMM pipeline in "fused" prefill) and the GEMM+AR
regime for replicated decode rows.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import ep_moe, gdn_attn, tp_attn, tp_mlp, tp_moe
from triton_dist_tpu.models.qwen_moe import moe_ffn, moe_ffn_decode
from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import (
    FwdContexts, _embed_tokens, _lm_head,
)
from triton_dist_tpu.models.kv_cache import KVCache


@dataclasses.dataclass
class HybridCache:
    """kv: softmax layers' cache (indexed by full-attn layer ordinal);
    states: (num_gdn_layers, B, H_loc, dk, dv) recurrent states;
    conv: (num_gdn_layers, B, C_loc, K-1) short-conv tails — zero-size
    for the simplified (conv-free) cell."""
    kv: KVCache
    states: jax.Array
    conv: jax.Array

    @property
    def length(self):
        """Tokens cached so far — one counter, owned by the KV cache
        (the GDN states are position-free)."""
        return self.kv.length

    def tree_flatten(self):
        return (self.kv, self.states, self.conv), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    HybridCache, HybridCache.tree_flatten, HybridCache.tree_unflatten)


def _layer_kinds(cfg: ModelConfig):
    """Per-layer ("attn"| "gdn", ordinal within its kind)."""
    kinds = []
    n_attn = n_gdn = 0
    for li in range(cfg.num_hidden_layers):
        if cfg.layer_is_full_attn(li):
            kinds.append(("attn", n_attn))
            n_attn += 1
        else:
            kinds.append(("gdn", n_gdn))
            n_gdn += 1
    return kinds, n_attn, n_gdn


def init_params(key, cfg: ModelConfig, dtype=jnp.float32) -> Dict:
    keys = jax.random.split(key, cfg.num_hidden_layers + 2)
    layers = []
    for li in range(cfg.num_hidden_layers):
        ka, km = jax.random.split(keys[li])
        mixer = (tp_attn.init(ka, cfg, dtype)
                 if cfg.layer_is_full_attn(li)
                 else gdn_attn.init(ka, cfg, dtype))
        layers.append({
            "mixer": mixer,
            # MoE FFN when configured (Qwen3-Next-80B-A3B is MoE; the
            # r2 advisor flagged that dropping it silently served the
            # wrong architecture). Router/expert weights are shared
            # between the tp and ep layer forms.
            "mlp": (ep_moe.init(km, cfg, dtype) if cfg.is_moe
                    else tp_mlp.init(km, cfg, dtype)),
            "ln_attn": jnp.ones((cfg.hidden_size,), dtype),
            "ln_mlp": jnp.ones((cfg.hidden_size,), dtype),
        })
    emb = jax.random.normal(keys[-2], (cfg.vocab_size, cfg.hidden_size),
                            dtype) * 0.02
    lm_head = (emb if cfg.tie_word_embeddings else
               jax.random.normal(keys[-1],
                                 (cfg.vocab_size, cfg.hidden_size),
                                 dtype) * 0.02)
    return {"embed": emb, "layers": layers,
            "ln_f": jnp.ones((cfg.hidden_size,), dtype),
            "lm_head": lm_head}


def param_specs(cfg: ModelConfig, axis: str = "tp", *,
                moe_impl: str = "tp", ep_axis: str = "ep") -> Dict:
    """``moe_impl`` selects the FFN regime for MoE configs (same
    contract as ``qwen_moe.param_specs`` — the Engine introspects the
    kwarg and plumbs ``moe_impl``/``ep_ctx`` into prefill/decode)."""
    if moe_impl not in ("tp", "ep"):
        raise ValueError(f"unknown moe_impl {moe_impl!r}")
    if moe_impl == "ep" and not cfg.is_moe:
        raise ValueError("moe_impl='ep' on a non-MoE hybrid config")
    if cfg.is_moe:
        moe_specs = (tp_moe.param_specs(axis, cfg) if moe_impl == "tp"
                     else ep_moe.param_specs(ep_axis, cfg))
    layers = []
    for li in range(cfg.num_hidden_layers):
        mixer = (tp_attn.param_specs(axis, cfg)
                 if cfg.layer_is_full_attn(li)
                 else gdn_attn.param_specs(axis, cfg))
        layers.append({
            "mixer": mixer,
            "mlp": (moe_specs if cfg.is_moe
                    else tp_mlp.param_specs(axis)),
            "ln_attn": P(None),
            "ln_mlp": P(None),
        })
    return {"embed": P(None, None), "layers": layers,
            "ln_f": P(None), "lm_head": P(axis, None)}


def cache_specs(axis: str = "tp") -> "HybridCache":
    """PartitionSpec pytree for :class:`HybridCache` (KV heads, GDN
    heads, and conv channels all sharded along ``axis``) — consumed by
    the Engine's shard_map in/out specs."""
    return HybridCache(
        kv=KVCache(k=P(None, None, None, axis, None),
                   v=P(None, None, None, axis, None),
                   length=P()),
        states=P(None, None, axis, None, None),
        conv=P(None, None, axis, None))


def _conv_channels(cfg: ModelConfig) -> int:
    """Global conv channel count of the HF cell (0 = conv-free cell)."""
    if not cfg.gdn_conv_kernel:
        return 0
    return (2 * cfg.gdn_num_kh * cfg.gdn_head_dim_k
            + cfg.gdn_num_heads * cfg.gdn_head_dim_v)


def empty_cache(cfg: ModelConfig, batch: int, max_len: int, n: int,
                dtype=jnp.float32) -> HybridCache:
    _, n_attn, n_gdn = _layer_kinds(cfg)
    kv_loc = max(cfg.num_key_value_heads // n, 1)
    h_loc = max(cfg.gdn_num_heads // n, 1)
    return HybridCache(
        kv=KVCache.empty(max(n_attn, 1), batch, max_len, kv_loc,
                         cfg.head_dim, dtype=dtype),
        states=jnp.zeros((max(n_gdn, 1), batch, h_loc,
                          cfg.gdn_head_dim_k, cfg.gdn_head_dim_v),
                         jnp.float32),
        conv=jnp.zeros((max(n_gdn, 1), batch, _conv_channels(cfg) // n,
                        max(cfg.gdn_conv_kernel - 1, 0)), dtype))


def _trunk(params, input_ids, cfg, *, mode, axis, ctxs, cache,
           moe_impl="tp", ep_ctx=None, moe_block_m=None):
    b, s = input_ids.shape
    kinds, _, _ = _layer_kinds(cfg)
    x = _embed_tokens(params, input_ids, mode=mode, axis=axis)
    for li, lp in enumerate(params["layers"]):
        kind, ordinal = kinds[li]
        h = rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps)
        if kind == "attn":
            mix_out, kv = tp_attn.fwd_prefill(
                lp["mixer"], h, cfg, batch=b, mode=mode, axis=axis,
                ag_ctx=ctxs.ag, rs_ctx=ctxs.rs, ar_ctx=ctxs.ar)
            if cache is not None:
                cache.kv = cache.kv.write_prefill(ordinal, *kv)
        elif cfg.gdn_conv_kernel:
            mix_out, (state, conv) = gdn_attn.fwd_prefill_hf(
                lp["mixer"], h, cfg, batch=b, mode=mode, axis=axis,
                ag_ctx=ctxs.ag, rs_ctx=ctxs.rs, ar_ctx=ctxs.ar)
            if cache is not None:
                cache.states = jax.lax.dynamic_update_slice(
                    cache.states, state[None], (ordinal, 0, 0, 0, 0))
                cache.conv = jax.lax.dynamic_update_slice(
                    cache.conv, conv[None].astype(cache.conv.dtype),
                    (ordinal, 0, 0, 0))
        else:
            mix_out, state = gdn_attn.fwd_prefill(
                lp["mixer"], h, cfg, batch=b, mode=mode, axis=axis,
                ag_ctx=ctxs.ag, rs_ctx=ctxs.rs, ar_ctx=ctxs.ar)
            if cache is not None:
                cache.states = jax.lax.dynamic_update_slice(
                    cache.states, state[None],
                    (ordinal, 0, 0, 0, 0))
        x = x + mix_out
        h = rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps)
        if cfg.is_moe:
            # Same regime dispatch as qwen_moe (tp-fused / tp / ep /
            # ep-2d) — one helper, two models.
            ffn_out = moe_ffn(
                lp["mlp"], h, cfg, moe_impl=moe_impl, mode=mode,
                axis=axis, ctxs=ctxs, ep_ctx=ep_ctx,
                moe_block_m=moe_block_m)
        else:
            ffn_out = tp_mlp.fwd(lp["mlp"], h, mode=mode, axis=axis,
                                 ag_ctx=ctxs.ag, rs_ctx=ctxs.rs,
                                 ar_ctx=ctxs.ar)
        x = x + ffn_out
    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    if mode in ("xla", "fused"):
        x = jax.lax.all_gather(x, axis, axis=0, tiled=True)
    return x, cache


def forward_tokens(params, input_ids, cfg: ModelConfig, *,
                   mode: str = "xla", axis: str = "tp",
                   ctxs: FwdContexts = FwdContexts(),
                   moe_impl: str = "tp", ep_ctx=None,
                   moe_block_m: Optional[int] = None):
    b, s = input_ids.shape
    x, _ = _trunk(params, input_ids, cfg, mode=mode, axis=axis,
                  ctxs=ctxs, cache=None, moe_impl=moe_impl,
                  ep_ctx=ep_ctx, moe_block_m=moe_block_m)
    return _lm_head(params, x, axis).reshape(b, s, cfg.vocab_size)


def prefill(params, input_ids, cfg: ModelConfig, *, mode: str = "xla",
            axis: str = "tp", ctxs: FwdContexts = FwdContexts(),
            max_len: Optional[int] = None, moe_impl: str = "tp",
            ep_ctx=None, moe_block_m: Optional[int] = None):
    n = jax.lax.axis_size(axis)
    b, s = input_ids.shape
    cache = empty_cache(cfg, b, max_len or s, n,
                        dtype=params["embed"].dtype)
    x, cache = _trunk(params, input_ids, cfg, mode=mode, axis=axis,
                      ctxs=ctxs, cache=cache, moe_impl=moe_impl,
                      ep_ctx=ep_ctx, moe_block_m=moe_block_m)
    cache.kv = dataclasses.replace(cache.kv,
                                   length=jnp.asarray(s, jnp.int32))
    last = x.reshape(b, s, cfg.hidden_size)[:, -1]
    return _lm_head(params, last, axis), cache


def decode_step(params, token_ids, cache: HybridCache,
                cfg: ModelConfig, *, mode: str = "xla",
                axis: str = "tp", ctxs: FwdContexts = FwdContexts(),
                moe_impl: str = "tp", ep_ctx=None):
    """One decode step; GDN layers advance their recurrent state in
    O(1), softmax layers append to the KV cache."""
    b = token_ids.shape[0]
    kinds, _, _ = _layer_kinds(cfg)
    x = params["embed"][token_ids]
    pos = cache.kv.length
    dec_mode = "xla" if mode == "xla" else "fused_ar"

    new_k, new_v = cache.kv.k, cache.kv.v
    new_states = cache.states
    new_conv = cache.conv
    for li, lp in enumerate(params["layers"]):
        kind, ordinal = kinds[li]
        h = rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps)
        if kind == "attn":
            mix_out, (lk, lv) = tp_attn.fwd_decode(
                lp["mixer"], h, cfg, new_k[ordinal], new_v[ordinal],
                pos, mode=dec_mode, axis=axis, ar_ctx=ctxs.ar)
            new_k = jax.lax.dynamic_update_slice(
                new_k, lk[None], (ordinal, 0, 0, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                new_v, lv[None], (ordinal, 0, 0, 0, 0))
        elif cfg.gdn_conv_kernel:
            mix_out, st, cv = gdn_attn.fwd_decode_hf(
                lp["mixer"], h, cfg, new_states[ordinal],
                new_conv[ordinal], mode=dec_mode, axis=axis,
                ar_ctx=ctxs.ar)
            new_states = jax.lax.dynamic_update_slice(
                new_states, st[None], (ordinal, 0, 0, 0, 0))
            new_conv = jax.lax.dynamic_update_slice(
                new_conv, cv[None].astype(new_conv.dtype),
                (ordinal, 0, 0, 0))
        else:
            mix_out, st = gdn_attn.fwd_decode(
                lp["mixer"], h, cfg, new_states[ordinal],
                mode=dec_mode, axis=axis, ar_ctx=ctxs.ar)
            new_states = jax.lax.dynamic_update_slice(
                new_states, st[None], (ordinal, 0, 0, 0, 0))
        x = x + mix_out
        h = rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps)
        if cfg.is_moe:
            # Small-batch decode FFN in the requested regime (TP
            # GEMM+AR, or EP masked-local-experts + psum).
            x = x + moe_ffn_decode(lp["mlp"], h, cfg,
                                   moe_impl=moe_impl, axis=axis,
                                   ep_ctx=ep_ctx)
        else:
            mlp_mode = "xla_ar" if dec_mode == "xla" else dec_mode
            x = x + tp_mlp.fwd(lp["mlp"], h, mode=mlp_mode, axis=axis,
                               ag_ctx=ctxs.ag, rs_ctx=ctxs.rs,
                               ar_ctx=ctxs.ar)

    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    logits = _lm_head(params, x, axis)
    cache = HybridCache(
        kv=KVCache(k=new_k, v=new_v, length=cache.kv.length + 1),
        states=new_states, conv=new_conv)
    return logits, cache
