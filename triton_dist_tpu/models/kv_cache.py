"""KV cache (reference: ``models/kv_cache.py`` ``KV_Cache``).

Per-shard layout: ``(num_layers, batch, max_len, kv_heads_loc, head_dim)``
— KV heads sharded along ``tp`` (each device holds its heads' cache, the
same placement the reference uses for split-KV flash decode)."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class KVCache:
    k: jax.Array  # (L, B, T, KV_loc, hd)
    v: jax.Array
    length: jax.Array  # scalar int32 — tokens currently cached

    @classmethod
    def empty(cls, num_layers: int, batch: int, max_len: int,
              kv_heads_loc: int, head_dim: int, dtype=jnp.float32):
        shape = (num_layers, batch, max_len, kv_heads_loc, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))

    def write_prefill(self, layer: int, k, v):
        """k/v: (B, S, KV_loc, hd) from prefill."""
        self.k = jax.lax.dynamic_update_slice(
            self.k, k[None].astype(self.k.dtype), (layer, 0, 0, 0, 0))
        self.v = jax.lax.dynamic_update_slice(
            self.v, v[None].astype(self.v.dtype), (layer, 0, 0, 0, 0))
        return self

    def append_decode(self, layer: int, k_tok, v_tok) -> "KVCache":
        """Append one decode token's K/V to ``layer`` at ``length``.

        k_tok/v_tok: (B, 1, KV_loc, hd). This is the dense half of the
        shared cache-update contract (its paged sibling is
        :meth:`~triton_dist_tpu.serving.blocks.PagedKVCache.append_decode`):
        the model projects the token, the cache owns WHERE the bytes
        land. Replaces the ad-hoc per-layer ``dynamic_update_slice``
        round-trips the Engine's decode loop used to do (which copied a
        full (B, T, KV, hd) layer cache per layer per step).

        Position does NOT advance here — every layer of one decode step
        writes the same slot; call :meth:`advance` once per step.
        """
        k5 = k_tok[None].astype(self.k.dtype)      # (1, B, 1, KV, hd)
        v5 = v_tok[None].astype(self.v.dtype)
        pos = self.length
        return KVCache(
            k=jax.lax.dynamic_update_slice(self.k, k5,
                                           (layer, 0, pos, 0, 0)),
            v=jax.lax.dynamic_update_slice(self.v, v5,
                                           (layer, 0, pos, 0, 0)),
            length=self.length)

    def advance(self, steps: int = 1) -> "KVCache":
        """Bump ``length`` after all layers of a decode step appended."""
        return KVCache(k=self.k, v=self.v,
                       length=self.length + jnp.asarray(steps, jnp.int32))

    def tree_flatten(self):
        return (self.k, self.v, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    KVCache, KVCache.tree_flatten, KVCache.tree_unflatten)
