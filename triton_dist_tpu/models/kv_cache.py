"""KV cache (reference: ``models/kv_cache.py`` ``KV_Cache``).

Per-shard layout: ``(num_layers, batch, max_len, kv_heads_loc, head_dim)``
— KV heads sharded along ``tp`` (each device holds its heads' cache, the
same placement the reference uses for split-KV flash decode)."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class KVCache:
    k: jax.Array  # (L, B, T, KV_loc, hd)
    v: jax.Array
    length: jax.Array  # scalar int32 — tokens currently cached

    @classmethod
    def empty(cls, num_layers: int, batch: int, max_len: int,
              kv_heads_loc: int, head_dim: int, dtype=jnp.float32):
        shape = (num_layers, batch, max_len, kv_heads_loc, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))

    def write_prefill(self, layer: int, k, v):
        """k/v: (B, S, KV_loc, hd) from prefill."""
        self.k = jax.lax.dynamic_update_slice(
            self.k, k[None].astype(self.k.dtype), (layer, 0, 0, 0, 0))
        self.v = jax.lax.dynamic_update_slice(
            self.v, v[None].astype(self.v.dtype), (layer, 0, 0, 0, 0))
        return self

    def tree_flatten(self):
        return (self.k, self.v, self.length), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    KVCache, KVCache.tree_flatten, KVCache.tree_unflatten)
