"""Model configuration (reference: ``models/config.py:53`` ModelConfig).

Presets cover the reference's demo models (Qwen3 dense family,
``docs/getting-started/e2e/e2e_dense.md``) plus a tiny config for the
CPU-mesh test battery.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 151936
    hidden_size: int = 4096
    intermediate_size: int = 12288
    num_hidden_layers: int = 36
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    head_dim: int = 128
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1_000_000.0
    max_position_embeddings: int = 40960
    tie_word_embeddings: bool = False
    model_name: str = "qwen3"
    # MoE fields (0 experts = dense; reference: models/qwen_moe.py)
    num_experts: int = 0
    num_experts_per_tok: int = 8
    moe_intermediate_size: int = 768
    norm_topk_prob: bool = True
    # Hybrid linear-attention fields (Qwen3-Next: GDN gated-delta-rule
    # layers with a full-attention layer every `full_attn_interval`;
    # 0 GDN heads = pure full attention). The reference ships the GDN
    # kernel (``kernels/nvidia/gdn.py``) for this family.
    # Attention projection biases (Seed-OSS / Qwen2-style checkpoints;
    # Qwen3 family is bias-free) and the Qwen3 per-head q/k RMS norm
    # (absent in Seed-OSS/llama-style models).
    attention_bias: bool = False
    qk_norm: bool = True
    gdn_num_heads: int = 0          # value heads (HF linear_num_value_heads)
    # Key heads may differ from value heads in real Qwen3-Next configs
    # (HF linear_num_key_heads); 0 means "same as gdn_num_heads". The
    # in-framework GDN family uses equal counts; a future HF hybrid
    # mapper needs the split (ADVICE r4).
    gdn_num_key_heads: int = 0
    gdn_head_dim_k: int = 128
    gdn_head_dim_v: int = 128
    full_attn_interval: int = 4
    # HF-faithful Qwen3-Next cell fields. gdn_conv_kernel > 0 selects
    # the checkpoint-compatible GatedDeltaNet parameterization (short
    # causal depthwise conv + z-gated RMSNorm + A_log/dt_bias decay,
    # HF ``linear_conv_kernel_dim``); 0 keeps the in-framework
    # simplified cell (wg/g_bias gates, no conv).
    gdn_conv_kernel: int = 0
    # Qwen3-Next full-attention extras: per-head sigmoid output gate
    # (q_proj emits [q | gate]) and partial RoPE (rotary on the first
    # ``partial_rotary_factor`` fraction of head_dim).
    attn_gate: bool = False
    partial_rotary_factor: float = 1.0
    # Qwen3-Next MoE shared expert (0 = none).
    shared_expert_intermediate_size: int = 0
    # Qwen3-Next RMSNorms are zero-centered ((1+w)·x̂, Gemma-style).
    # Runtime layers always compute standard w·x̂ — the HF mapper folds
    # the +1 into the stored weights at load time under this flag.
    norm_zero_centered: bool = False

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_hybrid(self) -> bool:
        return self.gdn_num_heads > 0

    @property
    def gdn_num_kh(self) -> int:
        """Key-head count (0 in the config means 'same as value
        heads', the in-framework family's shape)."""
        return self.gdn_num_key_heads or self.gdn_num_heads

    def kv_cache_plan(self, *, max_len: int, page: int,
                      num_slots: int, tp: int = 1,
                      dtype_bytes: int = 4,
                      kv_dtype: str = "bf16") -> dict:
        """Serving pool sizing off the model geometry — what the
        serving subsystem allocates from this config: pages per
        block-table row, pool pages for full residency (+1 reserved
        scratch page), and the per-rank HBM bytes of K+V pools.
        ``tp`` divides the KV heads (each rank holds its heads' pages,
        the same placement as the dense cache).

        ``kv_dtype="int8"|"fp8"`` plans a PER-PAGE QUANTIZED pool:
        storage at 1 byte/element plus one fp32 scale per (layer,
        page, kv_head) per K/V pool. The plan then also reports
        ``native_page_bytes_per_rank`` (what the page would cost
        unquantized at ``dtype_bytes``), ``bytes_per_token``, and
        ``capacity_ratio_vs_native`` — the 2–4x more-pages-per-HBM-GB
        the quantization buys at fixed pool bytes."""
        if max_len % page:
            raise ValueError(f"page={page} must divide max_len="
                             f"{max_len}")
        from triton_dist_tpu.serving.blocks import kv_quant_spec

        qdtype, _ = kv_quant_spec(kv_dtype)
        kv_loc = max(self.num_key_value_heads // tp, 1)
        p_max = max_len // page
        num_pages = 1 + num_slots * p_max
        native_bytes = (self.num_hidden_layers * kv_loc * page
                        * self.head_dim * dtype_bytes)
        if qdtype is None:
            page_bytes = native_bytes
        else:
            # 1 byte/element storage + the per-page per-head scale.
            page_bytes = (self.num_hidden_layers * kv_loc
                          * (page * self.head_dim + 4))
        plan = {
            "page": page, "p_max": p_max, "num_pages": num_pages,
            "kv_heads_loc": kv_loc,
            "kv_dtype": "bf16" if qdtype is None else kv_dtype,
            "page_bytes_per_rank": 2 * page_bytes,      # K and V
            "native_page_bytes_per_rank": 2 * native_bytes,
            "pool_bytes_per_rank": 2 * page_bytes * num_pages,
            "bytes_per_token": 2 * page_bytes / page,
            "capacity_ratio_vs_native": round(
                native_bytes / page_bytes, 4),
            "tokens_per_page": page,
        }
        return plan

    def layer_is_full_attn(self, layer_idx: int) -> bool:
        """Hybrid schedule: layers (interval-1, 2·interval-1, …) are full
        attention, the rest GDN (Qwen3-Next places the softmax layer
        last in each block of `full_attn_interval`)."""
        if not self.is_hybrid:
            return True
        return layer_idx % self.full_attn_interval == (
            self.full_attn_interval - 1)

    @classmethod
    def qwen3_8b(cls) -> "ModelConfig":
        return cls(hidden_size=4096, intermediate_size=12288,
                   num_hidden_layers=36, num_attention_heads=32,
                   num_key_value_heads=8, head_dim=128,
                   model_name="qwen3-8b")

    @classmethod
    def qwen3_32b(cls) -> "ModelConfig":
        return cls(hidden_size=5120, intermediate_size=25600,
                   num_hidden_layers=64, num_attention_heads=64,
                   num_key_value_heads=8, head_dim=128,
                   model_name="qwen3-32b")

    @classmethod
    def qwen3_moe_30b_a3b(cls) -> "ModelConfig":
        """Qwen3-30B-A3B (reference MoE demo, models/qwen_moe.py)."""
        return cls(hidden_size=2048, intermediate_size=6144,
                   num_hidden_layers=48, num_attention_heads=32,
                   num_key_value_heads=4, head_dim=128,
                   num_experts=128, num_experts_per_tok=8,
                   moe_intermediate_size=768,
                   model_name="qwen3-moe-30b-a3b")

    @classmethod
    def tiny_moe(cls, **kw) -> "ModelConfig":
        base = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=8,
                    num_key_value_heads=8, head_dim=8, num_experts=16,
                    num_experts_per_tok=2, moe_intermediate_size=32,
                    model_name="qwen3-moe-tiny")
        base.update(kw)
        return cls(**base)

    @classmethod
    def qwen3_next_80b_a3b(cls) -> "ModelConfig":
        """Qwen3-Next-80B-A3B geometry: 48 layers, 3 GDN : 1 full-attn,
        MoE FFN (512 experts, 10 active + shared omitted)."""
        return cls(hidden_size=2048, intermediate_size=5120,
                   num_hidden_layers=48, num_attention_heads=16,
                   num_key_value_heads=2, head_dim=256,
                   num_experts=512, num_experts_per_tok=10,
                   moe_intermediate_size=512,
                   gdn_num_heads=32, gdn_head_dim_k=128,
                   gdn_head_dim_v=128, full_attn_interval=4,
                   model_name="qwen3-next-80b-a3b")

    @classmethod
    def tiny_next(cls, **kw) -> "ModelConfig":
        """Hybrid GDN/full-attention tiny config for the CPU mesh."""
        base = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=4, num_attention_heads=8,
                    num_key_value_heads=8, head_dim=8,
                    gdn_num_heads=8, gdn_head_dim_k=8, gdn_head_dim_v=8,
                    full_attn_interval=2, model_name="qwen3-next-tiny")
        base.update(kw)
        return cls(**base)

    @classmethod
    def tiny(cls, *, vocab_size: int = 256, hidden_size: int = 32,
             intermediate_size: int = 64, num_hidden_layers: int = 2,
             num_attention_heads: int = 8, num_key_value_heads: int = 8,
             head_dim: int = 8) -> "ModelConfig":
        """Small enough that every pallas buffer stays under the
        interpret-mode 64 KB/device limit on the CPU test mesh."""
        return cls(vocab_size=vocab_size, hidden_size=hidden_size,
                   intermediate_size=intermediate_size,
                   num_hidden_layers=num_hidden_layers,
                   num_attention_heads=num_attention_heads,
                   num_key_value_heads=num_key_value_heads,
                   head_dim=head_dim, model_name="qwen3-tiny")

    @classmethod
    def from_hf_config(cls, hf_cfg) -> "ModelConfig":
        """Build from a transformers AutoConfig OR a raw ``config.json``
        dict (reference loads HF checkpoints, ``models/dense.py:150``).
        The single HF→ModelConfig mapper: covers dense, MoE
        (Qwen3-MoE), and hybrid GDN (Qwen3-Next) field sets.
        """
        if isinstance(hf_cfg, dict):
            get = lambda k, d=None: hf_cfg.get(k, d)
        else:
            get = lambda k, d=None: getattr(hf_cfg, k, d)

        def req(k):
            # Core architecture fields stay REQUIRED: silently
            # defaulting them would build a default-shaped model from a
            # malformed or wrong-schema config.json (ADVICE r4).
            v = get(k)
            if v is None:
                raise KeyError(
                    f"HF config missing required field {k!r} — is this "
                    "a supported config.json?")
            return v

        d = req("hidden_size")
        heads = req("num_attention_heads")

        # Hybrid layer schedule: real qwen3_next checkpoints serialize
        # an explicit layer_types list; this config expresses the
        # schedule as an interval (softmax layer last in each block),
        # so verify the list IS that pattern rather than silently
        # reinterpreting a custom schedule.
        #
        # Same fail-fast policy for the MoE schedule: every MoE layer
        # is assumed sparse (decoder_sparse_step 1, no dense-only
        # layers). Rejecting a non-default schedule HERE — before
        # load_hf_checkpoint reads tens of GB of shards — beats an
        # opaque KeyError from the per-layer mapper afterwards.
        if get("num_experts", 0):
            if get("decoder_sparse_step", 1) not in (None, 1) or \
                    get("mlp_only_layers"):
                raise NotImplementedError(
                    "only the every-layer MoE schedule is supported "
                    f"(decoder_sparse_step={get('decoder_sparse_step')}"
                    f", mlp_only_layers={get('mlp_only_layers')})")
        interval = get("full_attention_interval", 4) or 4
        layer_types = get("layer_types")
        # Only hybrid (GDN) models consult the schedule; non-hybrid
        # layer_types lists (e.g. sliding-window patterns) are not this
        # config's concern and must not block loading.
        if layer_types and (get("linear_num_value_heads", 0) or 0):
            fulls = [i for i, t in enumerate(layer_types)
                     if t == "full_attention"]
            if not fulls:
                interval = len(layer_types) + 1  # pure linear attention
            else:
                interval = fulls[0] + 1
                want = [i for i in range(len(layer_types))
                        if i % interval == interval - 1]
                if fulls != want:
                    raise NotImplementedError(
                        "layer_types is not an every-Nth-layer "
                        f"full-attention schedule (got {layer_types})")
        return cls(
            vocab_size=req("vocab_size"),
            hidden_size=d,
            intermediate_size=get("intermediate_size", 4 * d),
            num_hidden_layers=req("num_hidden_layers"),
            num_attention_heads=heads,
            num_key_value_heads=get("num_key_value_heads", heads),
            head_dim=get("head_dim") or d // heads,
            # Qwen2-family configs omit the key but hardcode q/k/v
            # biases in the HF implementation — default from the model
            # type so those checkpoints don't silently drop biases.
            attention_bias=bool(get(
                "attention_bias",
                str(get("model_type", "")).startswith("qwen2"))),
            # The per-head q/k RMS norm is a Qwen3-family trait; bias-
            # carrying llama-style checkpoints (Seed-OSS, the whole
            # Qwen2 family incl. qwen2_moe/qwen2_vl) have no
            # q_norm/k_norm weights.
            qk_norm=not (
                str(get("model_type", "")).startswith("qwen2")
                or get("model_type", "qwen3") in (
                    "seed_oss", "llama", "mistral")),
            rms_norm_eps=get("rms_norm_eps", 1e-6),
            rope_theta=get("rope_theta", 1_000_000.0),
            max_position_embeddings=get("max_position_embeddings", 40960),
            tie_word_embeddings=get("tie_word_embeddings", False),
            model_name=get("model_type", "qwen3"),
            num_experts=get("num_experts", 0) or 0,
            num_experts_per_tok=get("num_experts_per_tok", 8) or 8,
            moe_intermediate_size=get("moe_intermediate_size", 768) or 768,
            norm_topk_prob=get("norm_topk_prob", True),
            gdn_num_heads=get("linear_num_value_heads", 0) or 0,
            gdn_num_key_heads=get("linear_num_key_heads", 0) or 0,
            gdn_head_dim_k=get("linear_key_head_dim", 128) or 128,
            gdn_head_dim_v=get("linear_value_head_dim", 128) or 128,
            full_attn_interval=interval,
            # qwen3_next checkpoints use the HF GatedDeltaNet cell,
            # gated attention, and partial RoPE; other model types keep
            # the plain-field defaults.
            gdn_conv_kernel=(get("linear_conv_kernel_dim", 4) or 4
                             if get("model_type") == "qwen3_next" else 0),
            attn_gate=get("model_type") == "qwen3_next",
            partial_rotary_factor=(
                get("partial_rotary_factor", 0.25) or 0.25
                if get("model_type") == "qwen3_next" else 1.0),
            shared_expert_intermediate_size=get(
                "shared_expert_intermediate_size", 0) or 0,
            norm_zero_centered=get("model_type") == "qwen3_next",
        )
