from triton_dist_tpu.models.config import ModelConfig  # noqa: F401
from triton_dist_tpu.models.kv_cache import KVCache  # noqa: F401
from triton_dist_tpu.models import dense  # noqa: F401
from triton_dist_tpu.models import qwen_moe  # noqa: F401
from triton_dist_tpu.models import qwen_next  # noqa: F401
from triton_dist_tpu.models import checkpoint  # noqa: F401
from triton_dist_tpu.models.engine import Engine  # noqa: F401
