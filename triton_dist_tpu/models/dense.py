"""Dense Qwen3-style LLM (reference: ``models/dense.py:117`` ``DenseLLM``
/ ``:53`` ``DenseLLMLayer``).

Functional model: ``init_params`` builds the (per-device logical) weight
pytree, ``param_specs`` gives the PartitionSpec pytree, and
``prefill``/``decode_step`` are per-shard functions to run inside
``shard_map`` over a mesh. Forward mode mirrors the reference's
``set_fwd('torch'|'triton_dist'|'triton_dist_AR')`` (``dense.py:146``):
``"xla"``, ``"fused"`` (AG+GEMM / GEMM+RS), ``"fused_ar"`` (GEMM+AR).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import tp_attn, tp_mlp
from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.kv_cache import KVCache
from triton_dist_tpu.ops import (
    create_ag_gemm_context, create_gemm_rs_context, create_gemm_ar_context,
)
from triton_dist_tpu.parallel.mesh import MeshContext


@dataclasses.dataclass(frozen=True)
class FwdContexts:
    """Per-layer fused-op contexts (reference ``dense.py:169-208``
    init_triton_dist_ctx: per-layer create_ag_gemm_context +
    create_gemm_rs_context)."""
    ag: object = None
    rs: object = None
    ar: object = None


def make_fwd_contexts(mesh: MeshContext, axis: str = "tp",
                      block_m: int = 256, block_n: int = 256,
                      block_k: int = 512) -> FwdContexts:
    return FwdContexts(
        ag=create_ag_gemm_context(mesh, axis, block_m, block_n, block_k),
        rs=create_gemm_rs_context(mesh, axis, block_m, block_n, block_k),
        ar=create_gemm_ar_context(mesh, axis, block_n, block_k),
    )


def cache_specs(axis: str = "tp") -> KVCache:
    """PartitionSpec pytree for :class:`KVCache` (KV heads sharded along
    ``axis``) — the Engine's shard_map in/out spec for the cache."""
    return KVCache(k=P(None, None, None, axis, None),
                   v=P(None, None, None, axis, None),
                   length=P())


def init_params(key, cfg: ModelConfig, dtype=jnp.float32) -> Dict:
    keys = jax.random.split(key, cfg.num_hidden_layers + 2)
    layers = []
    for li in range(cfg.num_hidden_layers):
        ka, km = jax.random.split(keys[li])
        layers.append({
            "attn": tp_attn.init(ka, cfg, dtype),
            "mlp": tp_mlp.init(km, cfg, dtype),
            "ln_attn": jnp.ones((cfg.hidden_size,), dtype),
            "ln_mlp": jnp.ones((cfg.hidden_size,), dtype),
        })
    emb = jax.random.normal(keys[-2], (cfg.vocab_size, cfg.hidden_size),
                            dtype) * 0.02
    lm_head = (emb if cfg.tie_word_embeddings else
               jax.random.normal(keys[-1],
                                 (cfg.vocab_size, cfg.hidden_size),
                                 dtype) * 0.02)
    return {
        "embed": emb,
        "layers": layers,
        "ln_f": jnp.ones((cfg.hidden_size,), dtype),
        "lm_head": lm_head,
    }


def param_specs(cfg: ModelConfig, axis: str = "tp") -> Dict:
    layer_spec = {
        "attn": tp_attn.param_specs(axis, cfg),
        "mlp": tp_mlp.param_specs(axis),
        "ln_attn": P(None),
        "ln_mlp": P(None),
    }
    return {
        "embed": P(None, None),
        "layers": [layer_spec] * cfg.num_hidden_layers,
        "ln_f": P(None),
        "lm_head": P(axis, None),  # vocab-sharded head
    }


def _layer_fwd_prefill(layer_params, x, cfg, *, batch, mode, axis, ctxs,
                       ffn_fn=None):
    """``ffn_fn(layer_params, h) -> h`` overrides the FFN block — the
    hook the MoE model plugs its expert block into (dense default:
    tp_mlp)."""
    h = rms_norm(x, layer_params["ln_attn"], cfg.rms_norm_eps)
    attn_out, kv = tp_attn.fwd_prefill(
        layer_params["attn"], h, cfg, batch=batch, mode=mode, axis=axis,
        ag_ctx=ctxs.ag, rs_ctx=ctxs.rs, ar_ctx=ctxs.ar)
    x = x + attn_out
    h = rms_norm(x, layer_params["ln_mlp"], cfg.rms_norm_eps)
    if ffn_fn is None:
        x = x + tp_mlp.fwd(layer_params["mlp"], h, mode=mode, axis=axis,
                           ag_ctx=ctxs.ag, rs_ctx=ctxs.rs, ar_ctx=ctxs.ar)
    else:
        x = x + ffn_fn(layer_params, h)
    return x, kv


def _embed_tokens(params, input_ids, *, mode, axis):
    """Embed with slice-before-gather: each tp rank embeds only its
    token slice in the token-sharded modes."""
    n = jax.lax.axis_size(axis)
    b, s = input_ids.shape
    flat = input_ids.reshape(b * s)
    if mode in ("xla", "fused"):
        me = jax.lax.axis_index(axis)
        loc = (b * s) // n
        flat = jax.lax.dynamic_slice_in_dim(flat, me * loc, loc, axis=0)
    return params["embed"][flat]


def _forward_trunk(params, input_ids, cfg: ModelConfig, *, mode, axis,
                   ctxs, cache: Optional[KVCache], ffn_fn=None):
    """Shared prefill/all-token forward: embed → layers (optionally
    recording KV) → final norm → gather to full tokens. Returns
    (x (B*S, d) full, cache)."""
    b, s = input_ids.shape
    x = _embed_tokens(params, input_ids, mode=mode, axis=axis)
    for li, layer_params in enumerate(params["layers"]):
        x, kv = _layer_fwd_prefill(
            layer_params, x, cfg, batch=b, mode=mode, axis=axis,
            ctxs=ctxs, ffn_fn=ffn_fn)
        if cache is not None:
            cache = cache.write_prefill(li, *kv)
    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    if mode in ("xla", "fused"):
        x = jax.lax.all_gather(x, axis, axis=0, tiled=True)
    return x, cache


def _lm_head(params, x, axis):
    logits_loc = jnp.dot(x, params["lm_head"].T,
                         preferred_element_type=jnp.float32)
    return jax.lax.all_gather(logits_loc, axis, axis=x.ndim - 1,
                              tiled=True)


def prefill(params, input_ids, cfg: ModelConfig, *, mode: str = "xla",
            axis: str = "tp", ctxs: FwdContexts = FwdContexts(),
            max_len: Optional[int] = None, ffn_fn=None):
    """Per-shard prefill. input_ids: (B, S) replicated. Returns
    (logits (B, vocab) for the last position, KVCache per-shard).

    Token-sharded residual stream ("sequence parallel"): requires B*S
    divisible by the axis size in xla/fused modes.
    """
    n = jax.lax.axis_size(axis)
    b, s = input_ids.shape
    kv_loc = max(cfg.num_key_value_heads // n, 1)
    max_len = max_len or s
    cache = KVCache.empty(cfg.num_hidden_layers, b, max_len, kv_loc,
                          cfg.head_dim,
                          dtype=params["embed"].dtype)
    x, cache = _forward_trunk(params, input_ids, cfg, mode=mode,
                              axis=axis, ctxs=ctxs, cache=cache,
                              ffn_fn=ffn_fn)
    cache = dataclasses.replace(cache, length=jnp.asarray(s, jnp.int32))
    last = x.reshape(b, s, cfg.hidden_size)[:, -1]
    return _lm_head(params, last, axis), cache


def forward_tokens(params, input_ids, cfg: ModelConfig, *,
                   mode: str = "xla", axis: str = "tp",
                   ctxs: FwdContexts = FwdContexts()):
    """Per-shard forward returning logits for every position —
    the training-loss forward (B, S, vocab). Same token-sharded layout
    rules as :func:`prefill`."""
    b, s = input_ids.shape
    x, _ = _forward_trunk(params, input_ids, cfg, mode=mode, axis=axis,
                          ctxs=ctxs, cache=None)
    return _lm_head(params, x, axis).reshape(b, s, cfg.vocab_size)


def decode_step(params, token_ids, cache: KVCache, cfg: ModelConfig, *,
                mode: str = "xla", axis: str = "tp",
                ctxs: FwdContexts = FwdContexts(), ffn_fn=None):
    """One decode step. token_ids: (B,) replicated. Returns
    (logits (B, vocab), updated cache). Decode always runs with a
    replicated (B, d) residual (M is tiny) — the reference's
    AR/gemm_ar decode regime (``e2e_dense.md:25,34``).

    Cache updates go through :meth:`KVCache.append_decode` — the same
    project → append → attend → output contract the paged serving path
    (:func:`decode_step_paged`) drives, so dense and paged caches stay
    interchangeable at the model layer.

    ``ffn_fn(layer_params, h) -> h`` overrides the FFN block (the MoE
    model's hook); the dense default is tp_mlp in the AR regime.
    """
    b = token_ids.shape[0]
    x = params["embed"][token_ids]
    pos = cache.length
    dec_mode = "xla" if mode == "xla" else "fused_ar"
    positions = jnp.broadcast_to(pos, (b,)).astype(jnp.int32)
    kv_len = jnp.full((b,), pos + 1, dtype=jnp.int32)

    for li, layer_params in enumerate(params["layers"]):
        h = rms_norm(x, layer_params["ln_attn"], cfg.rms_norm_eps)
        q, k_tok, v_tok = tp_attn.decode_project(
            layer_params["attn"], h, cfg, positions, axis=axis)
        cache = cache.append_decode(li, k_tok, v_tok)
        o = tp_attn.sdpa(q, cache.k[li], cache.v[li], causal=False,
                         kv_len=kv_len)
        x = x + tp_attn.decode_output(
            layer_params["attn"], o.reshape(b, -1), h, mode=dec_mode,
            axis=axis, ar_ctx=ctxs.ar)
        h = rms_norm(x, layer_params["ln_mlp"], cfg.rms_norm_eps)
        if ffn_fn is None:
            mlp_mode = "xla_ar" if dec_mode == "xla" else dec_mode
            x = x + tp_mlp.fwd(layer_params["mlp"], h, mode=mlp_mode,
                               axis=axis, ag_ctx=ctxs.ag, rs_ctx=ctxs.rs,
                               ar_ctx=ctxs.ar)
        else:
            x = x + ffn_fn(layer_params, h)

    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    logits_loc = jnp.dot(x, params["lm_head"].T,
                         preferred_element_type=jnp.float32)
    logits = jax.lax.all_gather(logits_loc, axis, axis=1, tiled=True)
    return logits, cache.advance()


def verify_step_paged(params, token_ids, cache, cfg: ModelConfig, *,
                      budget=None, mode: str = "xla", axis: str = "tp",
                      ctxs: FwdContexts = FwdContexts(),
                      attn_impl: str = "ref", ffn_fn=None):
    """One SPECULATIVE-VERIFICATION step over a
    :class:`~triton_dist_tpu.serving.blocks.PagedKVCache`: K candidate
    tokens per slot through one fixed-shape dispatch.

    token_ids: (S, K) replicated — slot s's candidates are fed at
    positions ``lens[s]..lens[s]+K-1`` (K is STATIC, so the jit cache
    stays at one entry regardless of how many candidates end up
    accepted); ``budget`` (S,) int32 caps how many candidates may
    WRITE real pages per slot (over-budget rows near a request's
    token limit land in scratch — data, not shape).
    Per layer: project all S·K rows through the decode
    contract (:func:`tp_attn.decode_project` at per-row positions),
    write every candidate's K/V via :meth:`PagedKVCache.append_block`
    (parked slots land in the scratch page), then attend each
    candidate over the slot's gathered page view with the per-query
    causal mask (:func:`~triton_dist_tpu.ops.chunked_prefill.
    block_attend`) — candidate j sees exactly what a sequential decode
    of the accepted prefix would see, which is what makes accepted
    tokens token-exact with non-speculative greedy decode.

    ``attn_impl``: ``"ref"`` attends through the gather path
    (:func:`~triton_dist_tpu.ops.chunked_prefill.block_attend` over
    :meth:`PagedKVCache.dense_layer` — materializes every slot's
    dense row); ``"flash"`` streams pages through the K-query
    :func:`~triton_dist_tpu.ops.paged_flash_qblock.paged_flash_qblock`
    kernel with the same per-query causal positions riding as data —
    no dense-row materialization, work scales with resident pages.

    Returns ``(logits (S, K, vocab), cache)``. ``logits[s, j]`` is the
    next-token distribution AFTER feeding candidates 0..j. The cache's
    ``lens`` are NOT advanced — the host commits the accepted prefix
    by advancing its length mirrors (rejected suffixes simply stay
    masked garbage the next block overwrites), and rolls page
    accounting back via ``BlockManager.truncate_to``.
    """
    from triton_dist_tpu.ops.chunked_prefill import block_attend

    s, k = token_ids.shape
    x = params["embed"][token_ids.reshape(s * k)]     # (S·K, d)
    dec_mode = "xla" if mode == "xla" else "fused_ar"
    lens = cache.lens
    positions = (lens[:, None]
                 + jnp.arange(k, dtype=jnp.int32)[None]).reshape(s * k)

    for li, layer_params in enumerate(params["layers"]):
        h = rms_norm(x, layer_params["ln_attn"], cfg.rms_norm_eps)
        q, k_tok, v_tok = tp_attn.decode_project(
            layer_params["attn"], h, cfg, positions, axis=axis)
        hl, hd = q.shape[2], q.shape[3]
        kvl = k_tok.shape[2]
        cache = cache.append_block(
            li, k_tok[:, 0].reshape(s, k, kvl, hd),
            v_tok[:, 0].reshape(s, k, kvl, hd), budget=budget)
        if attn_impl == "flash":
            from triton_dist_tpu.ops.paged_flash_qblock import (
                paged_flash_qblock)

            # Candidate j of a live slot attends positions
            # <= lens[s]+j (its paged history + the candidate prefix
            # through itself — block_attend's kv_len-1); parked slots
            # clamp to position 0 (garbage the scheduler ignores).
            qpos = jnp.maximum(
                lens[:, None] + cache.live[:, None]
                * (jnp.arange(k, dtype=jnp.int32)[None] + 1), 1) - 1
            ksc, vsc = cache.layer_scales(li)
            o = paged_flash_qblock(
                q[:, 0].reshape(s, k, hl, hd), cache.k_pages[li],
                cache.v_pages[li], cache.block_table, qpos,
                k_scale=ksc, v_scale=vsc)
        else:
            kd, vd = cache.dense_layer(li)
            o = block_attend(q[:, 0].reshape(s, k, hl, hd), kd, vd,
                             lens, cache.live)
        x = x + tp_attn.decode_output(
            layer_params["attn"], o.reshape(s * k, -1), h,
            mode=dec_mode, axis=axis, ar_ctx=ctxs.ar)
        h = rms_norm(x, layer_params["ln_mlp"], cfg.rms_norm_eps)
        if ffn_fn is None:
            mlp_mode = "xla_ar" if dec_mode == "xla" else dec_mode
            x = x + tp_mlp.fwd(layer_params["mlp"], h, mode=mlp_mode,
                               axis=axis, ag_ctx=ctxs.ag, rs_ctx=ctxs.rs,
                               ar_ctx=ctxs.ar)
        else:
            x = x + ffn_fn(layer_params, h)

    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    logits_loc = jnp.dot(x, params["lm_head"].T,
                         preferred_element_type=jnp.float32)
    logits = jax.lax.all_gather(logits_loc, axis, axis=1, tiled=True)
    return logits.reshape(s, k, -1), cache


def paged_cache_specs(axis: str = "tp", quantized: bool = False):
    """PartitionSpec pytree for the serving
    :class:`~triton_dist_tpu.serving.blocks.PagedKVCache` (KV heads
    sharded along ``axis``; page pool, table, and lengths replicated in
    every other dim) — the ServingEngine's shard_map spec.
    ``quantized=True`` adds the per-page scale arrays' specs (their KV
    dim shards with the heads whose pages they dequantize)."""
    from triton_dist_tpu.serving.blocks import PagedKVCache

    scale = P(None, None, axis) if quantized else None
    return PagedKVCache(
        k_pages=P(None, None, axis, None, None),
        v_pages=P(None, None, axis, None, None),
        block_table=P(None, None), lens=P(None), live=P(None),
        k_scale=scale, v_scale=scale)


def prefill_chunk_paged(params, chunk_toks, cache, table_row,
                        cfg: ModelConfig, *, start, wfrom, valid,
                        mode: str = "xla", axis: str = "tp",
                        ctxs: FwdContexts = FwdContexts(),
                        attn_impl: str = "ref", ffn_fn=None):
    """One FIXED-SHAPE chunk of a bucketed paged prefill (per-shard).

    The chunked half of the serving split: instead of one monolithic
    prefill dispatch per prompt length (which XLA specializes per
    length), the prompt streams through this step in bucketed chunks —
    the trace signature depends only on the chunk length ``C``, so the
    prefill jit cache is bounded by the bucket count.

    chunk_toks: (C,) int32 replicated, padded past ``valid``;
    ``table_row``: (p_max,) int32 — the slot's block-table row (data);
    ``start``: scalar — global position of the chunk's first token;
    ``wfrom``: scalar — positions below it are already resident
    (prefix-shared pages; computed but never rewritten); ``valid``:
    scalar — real tokens in this chunk. All three ride as data.

    Per layer: project the chunk through the decode contract
    (:func:`tp_attn.decode_project` at per-row positions), write K/V
    into the slot's pages (:meth:`PagedKVCache.write_chunk`), then
    attend the chunk's queries over the slot's gathered position-major
    page view with the global causal mask
    (:func:`~triton_dist_tpu.ops.chunked_prefill.chunk_attend`) — so
    earlier chunks and the shared prefix are attended exactly and
    chunk boundaries are invisible to the math. The residual stays
    replicated (the decode AR regime — no token-sharding divisibility
    constraint ties C to the mesh).

    ``attn_impl``: ``"ref"`` gathers the slot's dense row per layer
    (:meth:`PagedKVCache.dense_row` + ``chunk_attend`` — O(p_max·page)
    HBM traffic per chunk regardless of the prompt's actual length);
    ``"flash"`` streams only the RESIDENT pages through the Q-block
    :func:`~triton_dist_tpu.ops.paged_flash_qblock.paged_flash_qblock`
    kernel (positions ride as data — the trace still keys only on the
    bucket length).

    Returns ``(logits (vocab,) of the LAST VALID token, cache)`` — the
    final chunk's logits seed the first generated token; earlier
    chunks' logits are discarded.
    """
    from triton_dist_tpu.ops.chunked_prefill import chunk_attend

    c = chunk_toks.shape[0]
    x = params["embed"][chunk_toks]          # (C, d) replicated
    dec_mode = "xla" if mode == "xla" else "fused_ar"
    positions = (jnp.asarray(start, jnp.int32)
                 + jnp.arange(c, dtype=jnp.int32))

    for li, layer_params in enumerate(params["layers"]):
        h = rms_norm(x, layer_params["ln_attn"], cfg.rms_norm_eps)
        q, k_tok, v_tok = tp_attn.decode_project(
            layer_params["attn"], h, cfg, positions, axis=axis)
        cache = cache.write_chunk(li, k_tok, v_tok, table_row,
                                  positions, valid, wfrom)
        if attn_impl == "flash":
            from triton_dist_tpu.ops.paged_flash_qblock import (
                paged_flash_qblock)

            # Bucket-padding rows clamp to the last VALID position:
            # their outputs are discarded garbage either way, but
            # unclamped they would stretch the kernel's page-walk
            # bound (max position) to the padded tail — 8x the DMA
            # traffic for exactly the short-prompt-in-a-big-bucket
            # case the kernel exists to make cheap.
            i = jnp.arange(c, dtype=jnp.int32)
            last_valid = (jnp.asarray(start, jnp.int32)
                          + jnp.maximum(jnp.asarray(valid, jnp.int32)
                                        - 1, 0))
            qpos = jnp.where(i < valid, positions, last_valid)
            ksc, vsc = cache.layer_scales(li)
            o = paged_flash_qblock(
                q[:, 0][None], cache.k_pages[li], cache.v_pages[li],
                table_row[None], qpos[None],
                k_scale=ksc, v_scale=vsc)[0]
        else:
            kd, vd = cache.dense_row(li, table_row)
            o = chunk_attend(q[:, 0], kd, vd, positions)
        x = x + tp_attn.decode_output(
            layer_params["attn"], o.reshape(c, -1), h, mode=dec_mode,
            axis=axis, ar_ctx=ctxs.ar)
        h = rms_norm(x, layer_params["ln_mlp"], cfg.rms_norm_eps)
        if ffn_fn is None:
            mlp_mode = "xla_ar" if dec_mode == "xla" else dec_mode
            x = x + tp_mlp.fwd(layer_params["mlp"], h, mode=mlp_mode,
                               axis=axis, ag_ctx=ctxs.ag, rs_ctx=ctxs.rs,
                               ar_ctx=ctxs.ar)
        else:
            x = x + ffn_fn(layer_params, h)

    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    last = jax.lax.dynamic_slice_in_dim(
        x, jnp.maximum(jnp.asarray(valid, jnp.int32) - 1, 0), 1, axis=0)
    logits_loc = jnp.dot(last, params["lm_head"].T,
                         preferred_element_type=jnp.float32)
    logits = jax.lax.all_gather(logits_loc, axis, axis=1, tiled=True)
    return logits[0], cache


def decode_step_paged(params, token_ids, cache, cfg: ModelConfig, *,
                      mode: str = "xla", axis: str = "tp",
                      ctxs: FwdContexts = FwdContexts(),
                      attn_impl: str = "ref", ffn_fn=None):
    """One CONTINUOUS-BATCHING decode step over a
    :class:`~triton_dist_tpu.serving.blocks.PagedKVCache`.

    token_ids: (S,) replicated — one per batch slot; ``cache`` carries
    per-slot block tables, lengths, and the live mask. Every slot ropes
    and attends at its OWN length, so requests of different ages share
    one fixed-shape dispatch (the continuous-batching decode step the
    serving scheduler drives — no recompilation as requests join and
    leave). Parked slots (live == 0) still flow through the math (the
    shape is fixed) but their appends land in the manager's reserved
    scratch page, their lengths do not advance, and their logits are
    garbage the scheduler ignores.

    ``attn_impl``: ``"ref"`` gathers each layer's pages to a dense
    (S, cap, KV_loc, hd) view and reuses :func:`tp_attn.sdpa` — the
    token-exact-with-``Engine.serve`` path (and the CPU default);
    ``"kernel"`` streams pages through
    :func:`~triton_dist_tpu.ops.paged_flash_decode.paged_flash_decode`
    without materializing the dense view (the TPU path). ``"flash"``
    is an alias for ``"kernel"`` here (the one-query decode step IS
    the paged flash kernel) — it exists so the serving engine can
    spell "Pallas paged attention everywhere" with one knob value
    covering decode, chunked prefill, and speculative verification.

    ``ffn_fn(layer_params, h) -> h`` overrides the FFN block (the MoE
    model's hook), exactly as in :func:`decode_step`.
    """
    b = token_ids.shape[0]
    x = params["embed"][token_ids]
    dec_mode = "xla" if mode == "xla" else "fused_ar"
    lens = cache.lens
    # Active slots attend including the token appended this step;
    # parked slots clamp to 1 so a fully-masked row cannot NaN the
    # softmax (their output is discarded anyway).
    kv_len = jnp.maximum(lens + cache.live, 1).astype(jnp.int32)

    for li, layer_params in enumerate(params["layers"]):
        h = rms_norm(x, layer_params["ln_attn"], cfg.rms_norm_eps)
        q, k_tok, v_tok = tp_attn.decode_project(
            layer_params["attn"], h, cfg, lens, axis=axis)
        cache = cache.append_decode(li, k_tok, v_tok)
        if attn_impl in ("kernel", "flash"):
            from triton_dist_tpu.ops.paged_flash_decode import (
                paged_flash_decode)

            ksc, vsc = cache.layer_scales(li)
            o = paged_flash_decode(
                q[:, 0], cache.k_pages[li], cache.v_pages[li],
                cache.block_table, kv_len, axis=None,
                k_scale=ksc, v_scale=vsc)
        else:
            kd, vd = cache.dense_layer(li)
            o = tp_attn.sdpa(q, kd, vd, causal=False, kv_len=kv_len)
        x = x + tp_attn.decode_output(
            layer_params["attn"], o.reshape(b, -1), h, mode=dec_mode,
            axis=axis, ar_ctx=ctxs.ar)
        h = rms_norm(x, layer_params["ln_mlp"], cfg.rms_norm_eps)
        if ffn_fn is None:
            mlp_mode = "xla_ar" if dec_mode == "xla" else dec_mode
            x = x + tp_mlp.fwd(layer_params["mlp"], h, mode=mlp_mode,
                               axis=axis, ag_ctx=ctxs.ag, rs_ctx=ctxs.rs,
                               ar_ctx=ctxs.ar)
        else:
            x = x + ffn_fn(layer_params, h)

    x = rms_norm(x, params["ln_f"], cfg.rms_norm_eps)
    logits_loc = jnp.dot(x, params["lm_head"].T,
                         preferred_element_type=jnp.float32)
    logits = jax.lax.all_gather(logits_loc, axis, axis=1, tiled=True)
    return logits, cache.advance()
