"""HuggingFace checkpoint loading (reference: ``models/dense.py:150``
``init_parameters`` — weights come from HF checkpoints sharded per
rank; ``models/utils.py``).

Zero-egress environments can't download weights; this maps an
already-local safetensors/torch state dict onto the param pytree of
:mod:`triton_dist_tpu.models.dense`.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.config import ModelConfig


def _to_np(t):
    try:
        import torch
        if isinstance(t, torch.Tensor):
            return t.float().cpu().numpy()
    except ImportError:
        pass
    return np.asarray(t)


def _norm_weight(state: Dict, key: str, cfg: ModelConfig, dtype):
    """Plain RMSNorm weight; qwen3_next stores zero-centered weights
    ((1+w)·x̂, ``Qwen3NextRMSNorm``) — fold the +1 here so runtime
    layers stay standard w·x̂. The GDN cell's gated norm is NOT
    zero-centered and must not come through this helper."""
    w = jnp.asarray(_to_np(state[key]), dtype)
    if getattr(cfg, "norm_zero_centered", False):
        w = w + jnp.asarray(1.0, dtype)
    return w


def _attn_from_hf(state: Dict, cfg: ModelConfig, prefix: str,
                  dtype) -> Dict:
    """Attention sub-dict for one layer, matching ``tp_attn.init``'s
    conditional keys (q/k norm when ``cfg.qk_norm``; Seed-OSS /
    Qwen2-style projection biases when ``cfg.attention_bias``)."""
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)
    gn = lambda k: _norm_weight(state, k, cfg, dtype)
    attn = {
        "wk": gT(prefix + "self_attn.k_proj.weight"),
        "wv": gT(prefix + "self_attn.v_proj.weight"),
        "wo": gT(prefix + "self_attn.o_proj.weight"),
    }
    if getattr(cfg, "attn_gate", False):
        # Qwen3-Next gated attention: q_proj rows are per-head
        # [hd q | hd gate] (Qwen3NextAttention chunks the doubled
        # projection per head) — de-interleave so both matrices are
        # plain head-major column-parallel.
        h, hd = cfg.num_attention_heads, cfg.head_dim
        qg = _to_np(state[prefix + "self_attn.q_proj.weight"])
        qg = qg.reshape(h, 2 * hd, qg.shape[-1])
        attn["wq"] = jnp.asarray(qg[:, :hd].reshape(h * hd, -1).T, dtype)
        attn["wqg"] = jnp.asarray(qg[:, hd:].reshape(h * hd, -1).T, dtype)
    else:
        attn["wq"] = gT(prefix + "self_attn.q_proj.weight")
    if cfg.qk_norm:
        attn["q_norm"] = gn(prefix + "self_attn.q_norm.weight")
        attn["k_norm"] = gn(prefix + "self_attn.k_norm.weight")
    if cfg.attention_bias:
        attn["bq"] = g(prefix + "self_attn.q_proj.bias")
        attn["bk"] = g(prefix + "self_attn.k_proj.bias")
        attn["bv"] = g(prefix + "self_attn.v_proj.bias")
        bo_key = prefix + "self_attn.o_proj.bias"
        attn["bo"] = (g(bo_key) if bo_key in state else
                      jnp.zeros((cfg.hidden_size,), dtype))
    return attn


def gdn_attn_from_hf(state: Dict, cfg: ModelConfig, prefix: str,
                     dtype) -> Dict:
    """De-interleave one HF Qwen3NextGatedDeltaNet layer into the
    head-major TP-shardable layout of ``layers.gdn_attn``'s HF cell.

    HF packs ``in_proj_qkvz`` as hk row-groups of
    ``[dk q | dk k | rep·dv v | rep·dv z]`` and ``in_proj_ba`` as hk
    groups of ``[rep b | rep a]``
    (``modeling_qwen3_next.fix_query_key_value_ordering``); the
    de-interleave makes every projection globally head-major so plain
    column sharding = head sharding. ``conv1d.weight`` channels are
    already flat ``[q | k | v]`` post-ordering, so they split directly.
    """
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    hk, hv = cfg.gdn_num_kh, cfg.gdn_num_heads
    dk, dv = cfg.gdn_head_dim_k, cfg.gdn_head_dim_v
    rep = hv // hk
    d = cfg.hidden_size

    qkvz = _to_np(state[prefix + "in_proj_qkvz.weight"])  # (out, d)
    qkvz = qkvz.reshape(hk, 2 * dk + 2 * rep * dv, d)
    wq = qkvz[:, :dk].reshape(hk * dk, d)
    wk = qkvz[:, dk:2 * dk].reshape(hk * dk, d)
    wv = qkvz[:, 2 * dk:2 * dk + rep * dv].reshape(hv * dv, d)
    wz = qkvz[:, 2 * dk + rep * dv:].reshape(hv * dv, d)

    ba = _to_np(state[prefix + "in_proj_ba.weight"]).reshape(
        hk, 2 * rep, d)
    wb = ba[:, :rep].reshape(hv, d)
    wa = ba[:, rep:].reshape(hv, d)

    conv = _to_np(state[prefix + "conv1d.weight"])  # (C, 1, K)
    conv = conv.reshape(conv.shape[0], conv.shape[-1])
    key_dim = hk * dk

    asj = lambda a: jnp.asarray(a.T, dtype)
    return {
        "wq": asj(wq), "wk": asj(wk), "wv": asj(wv), "wz": asj(wz),
        "wb": asj(wb), "wa": asj(wa),
        "conv_q": jnp.asarray(conv[:key_dim], dtype),
        "conv_k": jnp.asarray(conv[key_dim:2 * key_dim], dtype),
        "conv_v": jnp.asarray(conv[2 * key_dim:], dtype),
        "A_log": g(prefix + "A_log"),
        "dt_bias": g(prefix + "dt_bias"),
        "norm_w": g(prefix + "norm.weight"),
        "wo": jnp.asarray(_to_np(state[prefix + "out_proj.weight"]).T,
                          dtype),
    }


def params_from_hf_state_dict(state: Dict, cfg: ModelConfig,
                              dtype=jnp.bfloat16) -> Dict:
    """Map a Qwen3 HF state dict to the DenseLLM param pytree.

    Linear weights are stored (out, in) in torch; we keep (in, out).
    """
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": _attn_from_hf(state, cfg, p, dtype),
            "mlp": {
                "w_gate": gT(p + "mlp.gate_proj.weight"),
                "w_up": gT(p + "mlp.up_proj.weight"),
                "w_down": gT(p + "mlp.down_proj.weight"),
            },
            "ln_attn": _norm_weight(state, p + "input_layernorm.weight",
                                    cfg, dtype),
            "ln_mlp": _norm_weight(
                state, p + "post_attention_layernorm.weight", cfg, dtype),
        })
    embed = g("model.embed_tokens.weight")
    lm_head = (embed if cfg.tie_word_embeddings
               else g("lm_head.weight"))
    return {
        "embed": embed,
        "layers": layers,
        "ln_f": _norm_weight(state, "model.norm.weight", cfg, dtype),
        "lm_head": lm_head,
    }


def _moe_from_hf(state: Dict, cfg: ModelConfig, prefix: str,
                 dtype) -> Dict:
    """One layer's MoE block: per-expert gate/up/down stacked to
    (E, d, f) / (E, f, d) (HF ``mlp.experts.N.{gate,up,down}_proj``,
    router = ``mlp.gate``), plus the qwen3_next shared expert when the
    config carries one."""
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)

    def stack_T(proj):
        return jnp.stack([
            jnp.asarray(_to_np(
                state[f"{prefix}experts.{e}.{proj}.weight"]).T, dtype)
            for e in range(cfg.num_experts)])

    moe = {
        "router": gT(prefix + "gate.weight"),
        "w_gate": stack_T("gate_proj"),
        "w_up": stack_T("up_proj"),
        "w_down": stack_T("down_proj"),
    }
    if getattr(cfg, "shared_expert_intermediate_size", 0):
        moe["w_shared_gate"] = gT(
            prefix + "shared_expert.gate_proj.weight")
        moe["w_shared_up"] = gT(prefix + "shared_expert.up_proj.weight")
        moe["w_shared_down"] = gT(
            prefix + "shared_expert.down_proj.weight")
        # (1, d) single-logit gate → (d,) vector.
        moe["shared_gate"] = jnp.asarray(
            _to_np(state[prefix + "shared_expert_gate.weight"])
            .reshape(-1), dtype)
    return moe


def moe_params_from_hf_state_dict(state: Dict, cfg: ModelConfig,
                                  dtype=jnp.bfloat16) -> Dict:
    """Map a Qwen3-MoE HF state dict to the qwen_moe param pytree."""
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)

    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": _attn_from_hf(state, cfg, p, dtype),
            "moe": _moe_from_hf(state, cfg, p + "mlp.", dtype),
            "ln_attn": _norm_weight(state, p + "input_layernorm.weight",
                                    cfg, dtype),
            "ln_mlp": _norm_weight(
                state, p + "post_attention_layernorm.weight", cfg, dtype),
        })
    embed = g("model.embed_tokens.weight")
    return {
        "embed": embed,
        "layers": layers,
        "ln_f": _norm_weight(state, "model.norm.weight", cfg, dtype),
        "lm_head": (embed if cfg.tie_word_embeddings
                    else g("lm_head.weight")),
    }


def hybrid_params_from_hf_state_dict(state: Dict, cfg: ModelConfig,
                                     dtype=jnp.bfloat16) -> Dict:
    """Map a Qwen3-Next HF state dict to the ``models.qwen_next``
    param pytree: ``linear_attention`` layers through the GDN
    de-interleave (:func:`gdn_attn_from_hf`), ``full_attention`` layers
    through the gated-attention split (:func:`_attn_from_hf`), MoE
    blocks with the shared expert (:func:`_moe_from_hf`), dense MLP
    otherwise. All plain RMSNorms go through the zero-centered fold."""
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)

    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        if cfg.layer_is_full_attn(i):
            mixer = _attn_from_hf(state, cfg, p, dtype)
        else:
            mixer = gdn_attn_from_hf(state, cfg, p + "linear_attn.",
                                     dtype)
        if cfg.is_moe:
            mlp = _moe_from_hf(state, cfg, p + "mlp.", dtype)
        else:
            mlp = {
                "w_gate": gT(p + "mlp.gate_proj.weight"),
                "w_up": gT(p + "mlp.up_proj.weight"),
                "w_down": gT(p + "mlp.down_proj.weight"),
            }
        layers.append({
            "mixer": mixer,
            "mlp": mlp,
            "ln_attn": _norm_weight(state, p + "input_layernorm.weight",
                                    cfg, dtype),
            "ln_mlp": _norm_weight(
                state, p + "post_attention_layernorm.weight", cfg,
                dtype),
        })
    embed = g("model.embed_tokens.weight")
    return {
        "embed": embed,
        "layers": layers,
        "ln_f": _norm_weight(state, "model.norm.weight", cfg, dtype),
        "lm_head": (embed if cfg.tie_word_embeddings
                    else g("lm_head.weight")),
    }


def config_from_hf(hf: Dict) -> ModelConfig:
    """Alias of :meth:`ModelConfig.from_hf_config` (the single
    HF→ModelConfig mapper — dense, MoE, and hybrid GDN fields)."""
    return ModelConfig.from_hf_config(hf)


def load_hf_checkpoint(path: str, dtype=jnp.bfloat16):
    """Load a LOCAL HuggingFace checkpoint directory (``config.json`` +
    ``*.safetensors`` shards) → ``(ModelConfig, params pytree)``.

    The zero-egress analogue of the reference's from-pretrained path
    (``models/dense.py:150`` init_parameters): point it at an
    already-downloaded snapshot directory. Dense Qwen3 state dicts map
    via :func:`params_from_hf_state_dict`, MoE (``num_experts > 0``)
    via :func:`moe_params_from_hf_state_dict`.
    """
    import glob as _glob
    import json
    import os

    from safetensors.numpy import load_file

    with open(os.path.join(path, "config.json")) as f:
        cfg = config_from_hf(json.load(f))
    state: Dict = {}
    shards = sorted(_glob.glob(os.path.join(path, "*.safetensors")))
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for shard in shards:
        state.update(load_file(shard))
    if cfg.is_hybrid:
        mapper = hybrid_params_from_hf_state_dict
    elif cfg.is_moe:
        mapper = moe_params_from_hf_state_dict
    else:
        mapper = params_from_hf_state_dict
    return cfg, mapper(state, cfg, dtype)
