"""HuggingFace checkpoint loading (reference: ``models/dense.py:150``
``init_parameters`` — weights come from HF checkpoints sharded per
rank; ``models/utils.py``).

Zero-egress environments can't download weights; this maps an
already-local safetensors/torch state dict onto the param pytree of
:mod:`triton_dist_tpu.models.dense`.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.config import ModelConfig


def _to_np(t):
    try:
        import torch
        if isinstance(t, torch.Tensor):
            return t.float().cpu().numpy()
    except ImportError:
        pass
    return np.asarray(t)


def params_from_hf_state_dict(state: Dict, cfg: ModelConfig,
                              dtype=jnp.bfloat16) -> Dict:
    """Map a Qwen3 HF state dict to the DenseLLM param pytree.

    Linear weights are stored (out, in) in torch; we keep (in, out).
    """
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": {
                "wq": gT(p + "self_attn.q_proj.weight"),
                "wk": gT(p + "self_attn.k_proj.weight"),
                "wv": gT(p + "self_attn.v_proj.weight"),
                "wo": gT(p + "self_attn.o_proj.weight"),
                "q_norm": g(p + "self_attn.q_norm.weight"),
                "k_norm": g(p + "self_attn.k_norm.weight"),
            },
            "mlp": {
                "w_gate": gT(p + "mlp.gate_proj.weight"),
                "w_up": gT(p + "mlp.up_proj.weight"),
                "w_down": gT(p + "mlp.down_proj.weight"),
            },
            "ln_attn": g(p + "input_layernorm.weight"),
            "ln_mlp": g(p + "post_attention_layernorm.weight"),
        })
    embed = g("model.embed_tokens.weight")
    lm_head = (embed if cfg.tie_word_embeddings
               else g("lm_head.weight"))
    return {
        "embed": embed,
        "layers": layers,
        "ln_f": g("model.norm.weight"),
        "lm_head": lm_head,
    }
