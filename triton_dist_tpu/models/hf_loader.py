"""HuggingFace checkpoint loading (reference: ``models/dense.py:150``
``init_parameters`` — weights come from HF checkpoints sharded per
rank; ``models/utils.py``).

Zero-egress environments can't download weights; this maps an
already-local safetensors/torch state dict onto the param pytree of
:mod:`triton_dist_tpu.models.dense`.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.config import ModelConfig


def _to_np(t):
    try:
        import torch
        if isinstance(t, torch.Tensor):
            return t.float().cpu().numpy()
    except ImportError:
        pass
    return np.asarray(t)


def _attn_from_hf(state: Dict, cfg: ModelConfig, prefix: str,
                  dtype) -> Dict:
    """Attention sub-dict for one layer, matching ``tp_attn.init``'s
    conditional keys (q/k norm when ``cfg.qk_norm``; Seed-OSS /
    Qwen2-style projection biases when ``cfg.attention_bias``)."""
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)
    attn = {
        "wq": gT(prefix + "self_attn.q_proj.weight"),
        "wk": gT(prefix + "self_attn.k_proj.weight"),
        "wv": gT(prefix + "self_attn.v_proj.weight"),
        "wo": gT(prefix + "self_attn.o_proj.weight"),
    }
    if cfg.qk_norm:
        attn["q_norm"] = g(prefix + "self_attn.q_norm.weight")
        attn["k_norm"] = g(prefix + "self_attn.k_norm.weight")
    if cfg.attention_bias:
        attn["bq"] = g(prefix + "self_attn.q_proj.bias")
        attn["bk"] = g(prefix + "self_attn.k_proj.bias")
        attn["bv"] = g(prefix + "self_attn.v_proj.bias")
        bo_key = prefix + "self_attn.o_proj.bias"
        attn["bo"] = (g(bo_key) if bo_key in state else
                      jnp.zeros((cfg.hidden_size,), dtype))
    return attn


def params_from_hf_state_dict(state: Dict, cfg: ModelConfig,
                              dtype=jnp.bfloat16) -> Dict:
    """Map a Qwen3 HF state dict to the DenseLLM param pytree.

    Linear weights are stored (out, in) in torch; we keep (in, out).
    """
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)
    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": _attn_from_hf(state, cfg, p, dtype),
            "mlp": {
                "w_gate": gT(p + "mlp.gate_proj.weight"),
                "w_up": gT(p + "mlp.up_proj.weight"),
                "w_down": gT(p + "mlp.down_proj.weight"),
            },
            "ln_attn": g(p + "input_layernorm.weight"),
            "ln_mlp": g(p + "post_attention_layernorm.weight"),
        })
    embed = g("model.embed_tokens.weight")
    lm_head = (embed if cfg.tie_word_embeddings
               else g("lm_head.weight"))
    return {
        "embed": embed,
        "layers": layers,
        "ln_f": g("model.norm.weight"),
        "lm_head": lm_head,
    }


def moe_params_from_hf_state_dict(state: Dict, cfg: ModelConfig,
                                  dtype=jnp.bfloat16) -> Dict:
    """Map a Qwen3-MoE HF state dict to the qwen_moe param pytree
    (per-expert gate/up/down stacked to (E, d, f) / (E, f, d);
    HF names: ``mlp.experts.N.{gate,up,down}_proj``, router =
    ``mlp.gate``)."""
    g = lambda k: jnp.asarray(_to_np(state[k]), dtype)
    gT = lambda k: jnp.asarray(_to_np(state[k]).T, dtype)

    def stack_T(prefix, proj):
        return jnp.stack([
            jnp.asarray(_to_np(
                state[f"{prefix}experts.{e}.{proj}.weight"]).T, dtype)
            for e in range(cfg.num_experts)])

    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": _attn_from_hf(state, cfg, p, dtype),
            "moe": {
                "router": gT(p + "mlp.gate.weight"),
                "w_gate": stack_T(p + "mlp.", "gate_proj"),
                "w_up": stack_T(p + "mlp.", "up_proj"),
                "w_down": stack_T(p + "mlp.", "down_proj"),
            },
            "ln_attn": g(p + "input_layernorm.weight"),
            "ln_mlp": g(p + "post_attention_layernorm.weight"),
        })
    embed = g("model.embed_tokens.weight")
    return {
        "embed": embed,
        "layers": layers,
        "ln_f": g("model.norm.weight"),
        "lm_head": (embed if cfg.tie_word_embeddings
                    else g("lm_head.weight")),
    }


def config_from_hf(hf: Dict) -> ModelConfig:
    """Alias of :meth:`ModelConfig.from_hf_config` (the single
    HF→ModelConfig mapper — dense, MoE, and hybrid GDN fields)."""
    return ModelConfig.from_hf_config(hf)


def load_hf_checkpoint(path: str, dtype=jnp.bfloat16):
    """Load a LOCAL HuggingFace checkpoint directory (``config.json`` +
    ``*.safetensors`` shards) → ``(ModelConfig, params pytree)``.

    The zero-egress analogue of the reference's from-pretrained path
    (``models/dense.py:150`` init_parameters): point it at an
    already-downloaded snapshot directory. Dense Qwen3 state dicts map
    via :func:`params_from_hf_state_dict`, MoE (``num_experts > 0``)
    via :func:`moe_params_from_hf_state_dict`.
    """
    import glob as _glob
    import json
    import os

    from safetensors.numpy import load_file

    with open(os.path.join(path, "config.json")) as f:
        cfg = config_from_hf(json.load(f))
    if cfg.is_hybrid:
        # Fail BEFORE reading shards (tens of GB for 80B-class
        # checkpoints): a dense/MoE mapper would die with an opaque
        # KeyError on the GDN projection keys (ADVICE r4).
        raise NotImplementedError(
            "load_hf_checkpoint has no weight mapper for hybrid "
            "(Qwen3-Next / GDN) checkpoints yet — the in-framework "
            "hybrid family initializes via models.qwen_next.init_params; "
            "a hybrid mapper needs the separate gdn_num_key_heads / "
            "gdn_num_heads projection split now carried by ModelConfig")
    state: Dict = {}
    shards = sorted(_glob.glob(os.path.join(path, "*.safetensors")))
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    for shard in shards:
        state.update(load_file(shard))
    mapper = (moe_params_from_hf_state_dict if cfg.is_moe
              else params_from_hf_state_dict)
    return cfg, mapper(state, cfg, dtype)
