"""Native checkpoint save/restore for model params (orbax-backed).

The reference has no checkpointing of its own (inference library —
weights always come from HF files, SURVEY.md §5 "Checkpoint/resume:
none"); serving restarts re-read safetensors. Here params can
round-trip through orbax so a sharded serving state restores directly
to devices (sharding-aware, no host-side detour through torch), which
matters once a pod slice holds the weights: restore places each shard
on its owner.

API:
    save_params(path, params)
    params = restore_params(path, like=abstract_or_concrete_pytree)
"""

from __future__ import annotations

import os

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_params(path: str, params) -> str:
    """Write the param pytree to ``path`` (an empty/new directory).
    Sharded arrays are written per-shard by their owning processes."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    ckpt.save(path, params)
    ckpt.wait_until_finished()
    return path


def restore_params(path: str, like=None):
    """Restore a param pytree. ``like`` (optional) is a pytree of
    arrays or ShapeDtypeStructs with shardings — restored arrays are
    placed onto those shardings directly (device-direct multi-host
    restore)."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    if like is None:
        return ckpt.restore(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding",
                                                        None)),
        like)
    return ckpt.restore(path, abstract)
