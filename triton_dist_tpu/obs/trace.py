"""One trace session = one output directory, every telemetry tier.

:class:`TraceSession` is what :meth:`ServingEngine.trace` yields: it
owns the session directory, runs the xprof capture inside it (when
available — a failed profiler start records a skip reason instead of
killing the serve), collects megakernel slot records per decode step
while active, and exports ONE merged Perfetto file plus a
``metrics.json`` snapshot on demand.

``os.fspath(session)`` / ``str(session)`` return the session directory
— pre-existing callers that treated the old ``trace()`` yield as a
path string keep working.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

__all__ = ["TraceSession"]


def _mk_tag_names() -> dict:
    try:
        from triton_dist_tpu.megakernel.task import TaskType

        # Slot records store task_type + 1 (0 is the unused-slot
        # sentinel) — the same mapping the standalone exporter uses.
        return {int(t) + 1: t.name for t in TaskType}
    except Exception:  # pragma: no cover — megakernel optional
        return {}


class TraceSession:
    """See module docstring. Built by ``ServingEngine.trace()``.

    ``xprof``: ``"auto"`` starts a ``jax.profiler.trace`` capture and
    degrades to a recorded reason on failure; ``True`` propagates the
    failure; ``False`` skips the capture (reason recorded). ``markers``
    / ``top_ops`` feed
    :func:`~triton_dist_tpu.obs.xprof.extract_xprof_spans` at export.
    ``mk_keep`` bounds how many decode steps' megakernel slot records
    the session retains (newest win).
    """

    def __init__(self, path: str, telemetry, *, xprof="auto",
                 markers=None, top_ops: int = 0, mk_keep: int = 4,
                 create_perfetto_link: bool = False):
        self.path = path
        self.telemetry = telemetry
        self.xprof = xprof
        self.markers = markers
        self.top_ops = top_ops
        self.mk_keep = mk_keep
        self.create_perfetto_link = create_perfetto_link
        self.xprof_reason: Optional[str] = None
        self._xprof_cm = None
        self._mk_records: List[Tuple[int, object]] = []
        self.merged_path: Optional[str] = None

    # -- path compatibility ------------------------------------------

    def __fspath__(self) -> str:
        return self.path

    def __str__(self) -> str:
        return self.path

    # -- lifecycle ----------------------------------------------------

    def __enter__(self) -> "TraceSession":
        os.makedirs(self.path, exist_ok=True)
        if self.xprof is False:
            self.xprof_reason = "xprof disabled by caller (xprof=False)"
            return self
        try:
            # The shared capture entry point: one xprof session in this
            # directory, with the Perfetto-ready artifact materialized
            # alongside the raw capture (on jax versions that can).
            from triton_dist_tpu.profiler_utils import group_profile

            self._xprof_cm = group_profile(
                os.path.basename(self.path),
                log_dir=os.path.dirname(self.path) or ".",
                create_perfetto_link=self.create_perfetto_link,
                create_perfetto_trace=True)
            self._xprof_cm.__enter__()
        except Exception as e:  # noqa: BLE001 — degrade, don't kill
            self._xprof_cm = None
            if self.xprof is True:
                raise
            self.xprof_reason = f"xprof capture unavailable: {e!r}"
        return self

    def __exit__(self, *exc) -> bool:
        if self._xprof_cm is not None:
            try:
                self._xprof_cm.__exit__(*exc)
            except Exception as e:  # noqa: BLE001 — capture teardown
                self.xprof_reason = f"xprof capture failed on stop: {e!r}"
            self._xprof_cm = None
        return False

    # -- collection ----------------------------------------------------

    def add_slot_record(self, step: int, tracks) -> None:
        """Retain one decode step's megakernel slot tracks
        ((num_cores, qlen, 2) — ``ModelBuilder.prof_tracks``); newest
        ``mk_keep`` steps win."""
        self._mk_records.append((int(step), tracks))
        if len(self._mk_records) > self.mk_keep:
            self._mk_records.pop(0)

    # -- export ---------------------------------------------------------

    def export(self, path: Optional[str] = None) -> str:
        """Write the merged Perfetto trace (host spans + megakernel
        slot records + marker-keyed xprof device spans). Returns the
        file path; the xprof tier degrades to a recorded
        ``xprof_reason`` when the capture is absent or markerless."""
        from triton_dist_tpu.obs.xprof import extract_xprof_spans
        from triton_dist_tpu.profiler.viewer import export_merged_trace

        path = path or os.path.join(self.path, "merged_trace.json")
        xprof_events, reason = [], self.xprof_reason
        if reason is None:
            xprof_events, reason = extract_xprof_spans(
                self.path, markers=self.markers, top_ops=self.top_ops)
        tel = self.telemetry
        meta = {"telemetry_mode": getattr(tel, "mode", None)}
        if tel is not None and tel.spans_on and tel.log.dropped:
            meta["host_spans_dropped"] = tel.log.dropped
        self.merged_path = export_merged_trace(
            path,
            host_spans=(tel.log.spans() if tel is not None
                        and tel.spans_on else ()),
            slot_records=list(self._mk_records),
            tag_names=_mk_tag_names(),
            xprof_events=xprof_events,
            xprof_reason=reason,
            metadata=meta)
        return self.merged_path

    def export_metrics(self, stats: dict,
                       path: Optional[str] = None) -> str:
        """Write ``metrics.json``: the engine ``stats()`` dict (which
        already embeds the latency-histogram summaries) plus the
        session's trace bookkeeping."""
        path = path or os.path.join(self.path, "metrics.json")
        payload = {"stats": stats,
                   "trace": {"dir": self.path,
                             "merged": self.merged_path,
                             "xprof_reason": self.xprof_reason}}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True, default=str)
        return path
