"""Extract device spans from an xprof capture directory.

``jax.profiler.trace(log_dir)`` writes, per host, a TensorBoard
trace-viewer JSON (``plugins/profile/<run>/<host>.trace.json.gz``)
containing every XLA/device event of the capture. This module mines
that file for the spans the serving timeline wants to correlate:

- **marker-keyed spans** — events whose name carries a
  :func:`~triton_dist_tpu.profiler.trace_scalar` label
  (``pltpu.trace_value`` markers; VERDICT task 7's documented
  alternative to an in-kernel clock). On jax 0.4.x the marker label
  appears verbatim in the event name, so a substring match keys them.
- optionally the longest raw XLA op spans (``top_ops``) — useful
  context when no markers were compiled in (e.g. a CPU interpret run,
  where Mosaic never executes and ``trace_value`` lowers to nothing).

Extraction is best-effort by design: a missing capture, an old jax, or
a markerless build returns ``([], reason)`` — callers surface the
reason (skip-with-reason) instead of failing the trace export.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import List, Optional, Sequence, Tuple

__all__ = ["extract_xprof_spans"]

# Default marker substrings: trace_scalar labels conventionally start
# with "tdt." in this package; "trace_value" catches unlabeled lowering
# artifacts.
DEFAULT_MARKERS = ("tdt.", "trace_value")


def _trace_files(session_dir: str) -> List[str]:
    pats = (os.path.join(session_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(session_dir, "*.trace.json.gz"))
    out: List[str] = []
    for p in pats:
        out.extend(sorted(glob.glob(p)))
    return out


def extract_xprof_spans(session_dir: str, *,
                        markers: Optional[Sequence[str]] = None,
                        top_ops: int = 0,
                        ) -> Tuple[List[dict], Optional[str]]:
    """Return ``(events, reason)`` from the newest capture under
    ``session_dir``.

    ``events`` are chrome-trace dicts (``ph`` "X"/"i", ``ts``/``dur``
    in µs on the capture's own clock, original ``pid``/``tid``)
    whose names match any ``markers`` substring (default
    ``DEFAULT_MARKERS``), plus — when ``top_ops`` > 0 — the that-many
    longest complete ("X") spans regardless of name. ``reason`` is
    None on success and a human-readable skip reason when nothing
    could be extracted (no capture, unreadable file, no matches).
    """
    markers = tuple(markers) if markers is not None else DEFAULT_MARKERS
    files = _trace_files(session_dir)
    if not files:
        return [], (f"no xprof capture under {session_dir!r} "
                    "(jax.profiler.trace never ran, or an old jax "
                    "wrote no trace.json.gz)")
    path = files[-1]
    try:
        with gzip.open(path, "rt") as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        return [], f"unreadable xprof trace {path!r}: {e!r}"
    events = trace.get("traceEvents", [])
    names = {}
    marked: List[dict] = []
    timed: List[dict] = []
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            if ev.get("name") == "thread_name":
                names[(ev.get("pid"), ev.get("tid"))] = (
                    ev.get("args", {}).get("name"))
            continue
        name = ev.get("name") or ""
        if ph in ("X", "i"):
            if any(m in name for m in markers):
                marked.append(ev)
            elif ph == "X" and ev.get("dur"):
                timed.append(ev)
    picked = list(marked)
    if top_ops > 0:
        timed.sort(key=lambda e: -float(e.get("dur", 0.0)))
        picked.extend(timed[:top_ops])
    if not picked:
        return [], (f"xprof capture {os.path.basename(path)!r} holds "
                    f"{len(events)} events but none match markers "
                    f"{list(markers)} (markers lower to nothing off-"
                    "TPU; pass top_ops= to keep the longest raw ops)")
    out = []
    for ev in picked:
        e = {k: ev[k] for k in ("name", "ph", "ts", "dur", "pid",
                                "tid", "args") if k in ev}
        thread = names.get((ev.get("pid"), ev.get("tid")))
        if thread:
            e.setdefault("args", {})
            e["args"] = dict(e["args"], xprof_thread=thread)
        out.append(e)
    return out, None
