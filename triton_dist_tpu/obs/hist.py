"""Fixed log-spaced-bucket latency histograms.

The serving telemetry's distribution primitive: a histogram with
PRECOMPUTED geometric bucket boundaries (no per-observation allocation,
no dynamic resizing — the counters-mode hot path is one bisect plus an
integer increment), percentile summaries read off the cumulative
counts, and per-tenant grouping via :class:`HistogramSet`.

Bucket semantics: boundaries ``b_0 < b_1 < ... < b_n`` with a constant
ratio ``b_{i+1}/b_i = 10^(1/buckets_per_decade)``; bucket ``i`` covers
``[b_i, b_{i+1})``, plus an underflow bucket below ``b_0`` and an
overflow bucket at/above ``b_n``. A percentile answers with the
GEOMETRIC MIDPOINT of its bucket (clamped to the observed min/max), so
the relative error is bounded by the bucket ratio (~±21% at the
default 6 buckets/decade) — the right trade for serving dashboards,
where the shape of the tail matters and exact sub-bucket rank does
not. Values are SECONDS internally; summaries report milliseconds.
"""

from __future__ import annotations

import math
from bisect import bisect_right
from typing import Dict, List, Optional, Tuple

__all__ = ["LatencyHistogram", "HistogramSet"]


class LatencyHistogram:
    """Log-spaced-bucket histogram over positive values (seconds).

    ``lo``/``hi`` bound the bucketed range (values outside land in the
    under/overflow buckets — counted, never lost); the defaults span
    1µs..1000s, wide enough for both a fake-clock unit test and a real
    multi-minute prefill.
    """

    def __init__(self, lo: float = 1e-6, hi: float = 1e3,
                 buckets_per_decade: int = 6):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
        if buckets_per_decade < 1:
            raise ValueError("buckets_per_decade must be >= 1")
        decades = math.log10(hi / lo)
        n = max(1, int(round(decades * buckets_per_decade)))
        ratio = (hi / lo) ** (1.0 / n)
        # Exact geometric ladder; the last bound is pinned to hi so
        # float accumulation cannot shift the overflow edge.
        self.bounds: List[float] = [lo * ratio ** i for i in range(n)]
        self.bounds.append(hi)
        self.ratio = ratio
        # counts[0] = underflow, counts[1..n] = buckets, counts[n+1] =
        # overflow.
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def bucket_index(self, v: float) -> int:
        """Index into ``counts`` for value ``v`` (0 = underflow,
        ``len(bounds)`` = overflow)."""
        return bisect_right(self.bounds, v)

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[self.bucket_index(v)] += 1
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v

    def percentile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1] — the geometric midpoint
        of the bucket holding the q-th observation, clamped to the
        observed min/max. None when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        idx = len(self.counts) - 1
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                idx = i
                break
        if idx == 0:                       # underflow bucket
            rep = self.bounds[0]
        elif idx >= len(self.bounds):      # overflow bucket
            rep = self.bounds[-1]
        else:
            rep = math.sqrt(self.bounds[idx - 1] * self.bounds[idx])
        return min(max(rep, self.min), self.max)

    def summary(self) -> Optional[dict]:
        """p50/p95/p99 + count/mean/min/max in MILLISECONDS (None when
        nothing was observed)."""
        if self.count == 0:
            return None
        ms = lambda v: round(v * 1e3, 4)  # noqa: E731 — local fmt
        return {
            "count": self.count,
            "p50": ms(self.percentile(0.50)),
            "p95": ms(self.percentile(0.95)),
            "p99": ms(self.percentile(0.99)),
            "mean": ms(self.total / self.count),
            "min": ms(self.min),
            "max": ms(self.max),
        }

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold ``other`` in (bucket layouts must match)."""
        if other.bounds != self.bounds:
            raise ValueError("histogram bucket layouts differ")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        if other.min is not None:
            self.min = (other.min if self.min is None
                        else min(self.min, other.min))
        if other.max is not None:
            self.max = (other.max if self.max is None
                        else max(self.max, other.max))


class HistogramSet:
    """Named histograms with optional per-tenant/per-tag grouping.

    ``observe(name, v)`` updates the aggregate series; a non-None
    ``tenant`` additionally updates the ``(name, tenant)`` series — so
    the aggregate is always the sum of its groups plus the untagged
    traffic, and summaries never double-count.
    """

    def __init__(self, **hist_kw):
        self._hist_kw = hist_kw
        self._series: Dict[Tuple[str, Optional[str]],
                           LatencyHistogram] = {}

    def _get(self, name: str, tenant: Optional[str]) -> LatencyHistogram:
        key = (name, tenant)
        h = self._series.get(key)
        if h is None:
            h = self._series[key] = LatencyHistogram(**self._hist_kw)
        return h

    def observe(self, name: str, v: float,
                tenant: Optional[str] = None) -> None:
        self._get(name, None).observe(v)
        if tenant is not None:
            self._get(name, tenant).observe(v)

    def get(self, name: str, tenant: Optional[str] = None
            ) -> Optional[LatencyHistogram]:
        return self._series.get((name, tenant))

    def summary(self) -> dict:
        """``{name: summary}`` for the aggregates plus
        ``{"per_tenant": {tenant: {name: summary}}}`` when any tagged
        traffic was observed."""
        out: dict = {}
        tenants: dict = {}
        for (name, tenant), h in sorted(
                self._series.items(),
                key=lambda kv: (kv[0][0], kv[0][1] or "")):
            s = h.summary()
            if s is None:
                continue
            if tenant is None:
                out[name] = s
            else:
                tenants.setdefault(tenant, {})[name] = s
        if tenants:
            out["per_tenant"] = tenants
        return out
