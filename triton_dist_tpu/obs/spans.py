"""Typed spans and the bounded event ring.

The serving stack's per-request timeline is a sequence of SPANS —
(kind, t0, t1) intervals stamped by the engine's injectable monotonic
clock — and instant EVENTS (t1 is None). Everything is host-side data:
spans are never traced into a jit, so recording them cannot grow any
dispatch cache (the serving no-recompilation gates hold with spans
active).

The span taxonomy (``SPAN_KINDS``) names every stage a request can
pass through plus the resilience events that can interleave with it;
see docs/observability.md for the full table. Kinds outside the
taxonomy are allowed (callers may invent attrs-only kinds), but the
serving engine itself emits only these.

:class:`EventLog` is a bounded ring (drop-oldest) so a long-running
server's telemetry cost is O(capacity), with JSONL import/export for
offline inspection and the Perfetto merge
(:func:`~triton_dist_tpu.profiler.viewer.export_merged_trace`).
"""

from __future__ import annotations

import dataclasses
import json
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["SPAN_KINDS", "Span", "EventLog"]

# The serving span/event taxonomy (docs/observability.md). Interval
# spans carry t0 < t1 on the engine clock; instant events have t1 None.
SPAN_KINDS = (
    # request lifecycle
    "submit",            # event: request entered the wait queue
    "queue_wait",        # span: submit -> slot admission
    "admit",             # event: slot assigned (status -> prefill)
    "prefill",           # span: monolithic prefill dispatch + blit
    "prefill_chunk",     # span: one bucketed chunk dispatch (1 attempt)
    "migration",         # span: one KV page-migration attempt (disagg)
    "decode",            # span: one joint decode dispatch
    "spec_draft",        # span: host-side draft proposal (all slots)
    "spec_verify",       # span: one K-token verification dispatch
    "spec_rollback",     # event: rejected suffix rolled back
    "first_token",       # event: TTFT edge (request's first emission)
    "request",           # span: submit -> terminal status
    # KV memory hierarchy (docs/serving.md, "KV memory hierarchy")
    "kv_offload",        # span: page payload demoted into the tier
    "kv_prefetch",       # span: tier payload scattered back into HBM
    "park",              # span: session offloaded + slot released
    "resume",            # span: resume() -> token-exact reactivation
    # fleet serving (docs/serving.md, "Fleet serving")
    "route",             # span: routing decision -> fleet admission
    "fleet_failover",    # span: dead fleet's work rehomed on survivors
    "drain",             # span: fleet drained (park/finish in-flight)
    "restore_fleet",     # span: fleet state restored on new topology
    "shed",              # event: request shed by deadline class
    # resilience
    "retry",             # event: one absorbed transient (attempt n)
    "retry_backoff",     # event: backoff sleep scheduled (policy)
    "retry_giveup",      # event: retries exhausted (policy)
    "preempt",           # event: pool-dry eviction, requeued at head
    "failover",          # event: prefill role moved, handles requeued
    "role_fail",         # event: one post-retry role failure recorded
    "role_dead",         # event: health tracker declared a role dead
    "timeout",           # event: a watchdog deadline fired
    "checkpoint",        # span: full serving-state snapshot
    "restore",           # span: snapshot adopted into a fresh engine
    "chaos_fault",       # event: the chaos soak injected a fault
    "chaos_restore",     # event: the soak's mid-run kill/restore drill
)


@dataclasses.dataclass
class Span:
    """One timeline entry. ``t1 is None`` marks an instant event.

    ``request_id`` / ``slot`` / ``step`` are the correlation keys the
    Perfetto merge threads across components (host track <-> megakernel
    step <-> xprof span); ``tenant`` is the histogram grouping key;
    everything else rides in ``attrs``.
    """

    kind: str
    t0: float
    t1: Optional[float] = None
    request_id: Optional[str] = None
    slot: Optional[int] = None
    step: Optional[int] = None
    tenant: Optional[str] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def instant(self) -> bool:
        return self.t1 is None

    @property
    def duration(self) -> float:
        return 0.0 if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "t0": self.t0}
        for k in ("t1", "request_id", "slot", "step", "tenant"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(kind=d["kind"], t0=d["t0"], t1=d.get("t1"),
                   request_id=d.get("request_id"), slot=d.get("slot"),
                   step=d.get("step"), tenant=d.get("tenant"),
                   attrs=dict(d.get("attrs", {})))


class EventLog:
    """Bounded drop-oldest ring of :class:`Span` records.

    ``capacity`` bounds memory for arbitrarily long runs; ``dropped``
    counts evictions so an exported timeline is honest about what it no
    longer holds. Appends are O(1) host work — the serving loop calls
    this on its hot path only in ``telemetry="spans"`` mode.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self.total = 0

    def append(self, span: Span) -> None:
        self._ring.append(span)
        self.total += 1

    def spans(self) -> List[Span]:
        """Oldest-first snapshot of the retained window."""
        return list(self._ring)

    @property
    def dropped(self) -> int:
        return self.total - len(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self.total = 0

    # -- JSONL round-trip --------------------------------------------

    def to_jsonl(self, path: str) -> str:
        """One span per line, oldest first. Returns ``path``."""
        with open(path, "w") as f:
            for s in self._ring:
                f.write(json.dumps(s.to_dict(), sort_keys=True) + "\n")
        return path

    @classmethod
    def from_jsonl(cls, path: str, capacity: Optional[int] = None
                   ) -> "EventLog":
        """Rebuild a log from :meth:`to_jsonl` output (``capacity``
        defaults to at least the line count, so nothing re-drops)."""
        spans = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    spans.append(Span.from_dict(json.loads(line)))
        log = cls(capacity or max(len(spans), 1))
        for s in spans:
            log.append(s)
        return log
