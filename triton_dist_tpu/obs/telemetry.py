"""The serving telemetry facade: one object per engine.

Three modes, chosen at engine construction
(``ServingEngine(telemetry=...)``):

- ``"off"`` — every hook is a no-op (the pre-existing counters in
  ``stats()`` still work; nothing here runs on the hot path).
- ``"counters"`` — the cheap default: latency histograms (TTFT,
  inter-token latency, per-op durations) and named counters. No span
  objects are allocated; the hot-path cost is two clock reads and one
  histogram bisect per instrumented region.
- ``"spans"`` — everything above PLUS the full typed-span timeline in
  the bounded :class:`~triton_dist_tpu.obs.spans.EventLog` (JSONL
  export, Perfetto merge).

All stamping is host-side on the engine's injectable clock — a fake
clock makes timelines deterministic in tests, and nothing here is ever
traced into a jit, so the decode/prefill no-growth gates hold with
spans active.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from triton_dist_tpu.obs.hist import HistogramSet
from triton_dist_tpu.obs.spans import EventLog, Span

__all__ = ["TELEMETRY_MODES", "Telemetry"]

TELEMETRY_MODES = ("off", "counters", "spans")

# Span kinds whose durations feed the per-op histogram series
# ("op:<kind>" in the latency summary).
_OP_HIST_KINDS = frozenset({
    "queue_wait", "prefill", "prefill_chunk", "migration", "decode",
    "spec_draft", "spec_verify", "checkpoint", "restore", "request",
    "kv_offload", "kv_prefetch", "park", "resume",
    "route", "fleet_failover", "drain", "restore_fleet",
})


class _NullSpan:
    """Shared no-op context (``telemetry="off"`` / events disabled)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _SpanCtx:
    """One timed region: clock at enter/exit, histogram fold, and (in
    spans mode) an EventLog append — error type recorded when the
    region raised."""

    __slots__ = ("tel", "kind", "fields")

    def __init__(self, tel: "Telemetry", kind: str, fields: dict):
        self.tel = tel
        self.kind = kind
        self.fields = fields

    def __enter__(self):
        self.fields["_t0"] = self.tel.clock()
        return self

    def __exit__(self, etype, exc, tb):
        tel = self.tel
        fields = self.fields
        t0 = fields.pop("_t0")
        t1 = tel.clock()
        if etype is not None:
            fields["error"] = etype.__name__
        tel._finish_span(self.kind, t0, t1, fields)
        return False


class Telemetry:
    """Per-engine telemetry sink (see module docstring).

    ``clock`` is the engine's monotonic clock (injectable);
    ``capacity`` bounds the spans-mode event ring.
    """

    def __init__(self, mode: str = "counters", *,
                 clock: Callable[[], float] = time.monotonic,
                 capacity: int = 4096, **hist_kw):
        if mode not in TELEMETRY_MODES:
            raise ValueError(
                f"telemetry must be one of {TELEMETRY_MODES}, got "
                f"{mode!r}")
        self.mode = mode
        self.clock = clock
        self.log = EventLog(capacity)
        self.hist = HistogramSet(**hist_kw)
        self.counters: Dict[str, int] = {}

    # -- mode predicates ---------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    @property
    def spans_on(self) -> bool:
        return self.mode == "spans"

    def now(self) -> float:
        return self.clock()

    # -- recording ----------------------------------------------------

    def span(self, kind: str, **fields):
        """Context manager timing one region. In counters mode the
        duration folds into the ``op:<kind>`` histogram; in spans mode
        a :class:`Span` is appended too. Off mode: a shared no-op."""
        if self.mode == "off":
            return _NULL
        return _SpanCtx(self, kind, fields)

    def _finish_span(self, kind: str, t0: float, t1: float,
                     fields: dict) -> None:
        tenant = fields.get("tenant")
        if kind in _OP_HIST_KINDS:
            self.hist.observe(f"op:{kind}", t1 - t0, tenant)
        if self.mode == "spans":
            self.log.append(Span(
                kind=kind, t0=t0, t1=t1,
                request_id=fields.pop("request_id", None),
                slot=fields.pop("slot", None),
                step=fields.pop("step", None),
                tenant=fields.pop("tenant", None),
                attrs=fields))

    def complete_span(self, kind: str, t0: float,
                      t1: Optional[float] = None, **fields) -> None:
        """Record a span whose start was stamped earlier (e.g.
        queue-wait: ``t0`` is the submit time). ``t1`` defaults to
        now."""
        if self.mode == "off":
            return
        self._finish_span(kind, t0, self.clock() if t1 is None else t1,
                          fields)

    def event(self, kind: str, **fields) -> None:
        """Instant event (spans mode only — events are timeline
        entries, not distributions). Also bumps the ``kind`` counter in
        any enabled mode."""
        if self.mode == "off":
            return
        self.counters[kind] = self.counters.get(kind, 0) + 1
        if self.mode == "spans":
            self.log.append(Span(
                kind=kind, t0=self.clock(), t1=None,
                request_id=fields.pop("request_id", None),
                slot=fields.pop("slot", None),
                step=fields.pop("step", None),
                tenant=fields.pop("tenant", None),
                attrs=fields))

    def observe(self, name: str, seconds: float,
                tenant: Optional[str] = None) -> None:
        """Fold one duration into the named histogram (TTFT / ITL /
        custom series)."""
        if self.mode != "off":
            self.hist.observe(name, seconds, tenant)

    def count(self, name: str, inc: int = 1) -> None:
        if self.mode != "off":
            self.counters[name] = self.counters.get(name, 0) + inc

    # -- readout ------------------------------------------------------

    def latency_summary(self) -> Optional[dict]:
        """The ``stats()["latency"]`` payload: named histogram
        summaries in ms (``ttft_ms`` / ``itl_ms`` aliased from the
        raw series names), per-op durations under ``ops``, per-tenant
        groups, counters, and the event-ring accounting. None in off
        mode."""
        if self.mode == "off":
            return None
        raw = self.hist.summary()
        out: dict = {
            "ttft_ms": raw.pop("ttft", None),
            "itl_ms": raw.pop("itl", None),
        }
        ops = {k[len("op:"):]: raw.pop(k)
               for k in sorted(raw) if k.startswith("op:")}
        if ops:
            out["ops"] = ops
        per_tenant = raw.pop("per_tenant", None)
        if per_tenant:
            out["per_tenant"] = {
                t: {("ttft_ms" if n == "ttft" else
                     "itl_ms" if n == "itl" else n): s
                    for n, s in series.items()}
                for t, series in per_tenant.items()}
        out.update(raw)          # any remaining custom series
        if self.counters:
            out["counters"] = dict(sorted(self.counters.items()))
        if self.spans_on:
            out["events"] = {"recorded": self.log.total,
                             "retained": len(self.log),
                             "dropped": self.log.dropped}
        return out
