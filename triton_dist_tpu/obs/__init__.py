"""Serving observability: span timelines, latency histograms, merged
Perfetto traces.

The telemetry substrate ROADMAP item 5c's "at production traffic you
debug with traces, not reruns" calls for (see docs/observability.md):

- :mod:`~triton_dist_tpu.obs.spans` — the typed span taxonomy and the
  bounded :class:`EventLog` ring with JSONL round-trip;
- :mod:`~triton_dist_tpu.obs.hist` — fixed log-spaced-bucket latency
  histograms (TTFT / inter-token / per-op) with percentile summaries
  and per-tenant grouping;
- :mod:`~triton_dist_tpu.obs.telemetry` — the per-engine facade behind
  ``ServingEngine(telemetry="off"|"counters"|"spans")``;
- :mod:`~triton_dist_tpu.obs.xprof` — best-effort device-span
  extraction from an xprof capture, keyed to
  :func:`~triton_dist_tpu.profiler.trace_scalar` markers;
- :mod:`~triton_dist_tpu.obs.trace` — the one-directory trace session
  ``ServingEngine.trace()`` yields (xprof + host spans + megakernel
  slot records -> one merged Perfetto file).

Everything here is host-side bookkeeping on the engine's injectable
clock: recording never touches a jitted dispatch, so the serving
no-recompilation gates hold with full span recording active.
"""

from triton_dist_tpu.obs.spans import (  # noqa: F401
    SPAN_KINDS,
    EventLog,
    Span,
)
from triton_dist_tpu.obs.hist import (  # noqa: F401
    HistogramSet,
    LatencyHistogram,
)
from triton_dist_tpu.obs.telemetry import (  # noqa: F401
    TELEMETRY_MODES,
    Telemetry,
)
from triton_dist_tpu.obs.xprof import extract_xprof_spans  # noqa: F401
from triton_dist_tpu.obs.trace import TraceSession  # noqa: F401
