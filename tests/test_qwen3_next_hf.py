"""HF-faithful Qwen3-Next parity (layer-by-layer vs transformers).

The reference serves Qwen3-Next through its GDN kernel + megakernel
(``kernels/nvidia/gdn.py``); checkpoint compatibility means matching
the EXACT HF cell — conv, z-gate, A_log/dt_bias decay, GQA repeat,
gated RMSNorm — not just the delta-rule core. Every test here builds
the real ``transformers.models.qwen3_next`` torch module with random
weights, maps its state dict through the loader's de-interleave, and
matches activations on the 8-device CPU mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.utils.testing import spmd

torch = pytest.importorskip("torch")

from transformers.models.qwen3_next.configuration_qwen3_next import (  # noqa: E402
    Qwen3NextConfig,
)

B, S = 2, 16
D, HK, HV, DK, DV, CONV = 32, 8, 16, 4, 4, 4


def _hf_config(**kw):
    base = dict(
        vocab_size=64, hidden_size=D, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=8,
        num_key_value_heads=8, head_dim=8,
        linear_num_key_heads=HK, linear_num_value_heads=HV,
        linear_key_head_dim=DK, linear_value_head_dim=DV,
        linear_conv_kernel_dim=CONV,
        partial_rotary_factor=0.25, rope_theta=1e4,
        num_experts=0, rms_norm_eps=1e-6, hidden_act="silu")
    base.update(kw)
    return Qwen3NextConfig(**base)


def _cfg():
    return ModelConfig.from_hf_config(_hf_config().to_dict())


def _randomize(module, seed):
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for p in module.parameters():
            p.copy_(torch.randn(p.shape, generator=g) * 0.2)
    return module


def test_from_hf_config_qwen3_next_fields():
    cfg = _cfg()
    assert cfg.is_hybrid and cfg.gdn_conv_kernel == CONV
    assert cfg.gdn_num_kh == HK and cfg.gdn_num_heads == HV
    assert cfg.attn_gate and cfg.partial_rotary_factor == 0.25
    # 2 layers, both linear (the serialized layer_types) — no
    # full-attention layer in range.
    assert not any(cfg.layer_is_full_attn(i) for i in range(2))
    # A 3:1 hybrid schedule round-trips through layer_types.
    cfg8 = ModelConfig.from_hf_config(
        _hf_config(num_hidden_layers=8).to_dict())
    assert cfg8.full_attn_interval == 4
    assert [cfg8.layer_is_full_attn(i) for i in range(8)] == [
        False, False, False, True, False, False, False, True]


def test_gdn_cell_prefill_matches_transformers(tp8_mesh):
    from transformers.models.qwen3_next.modeling_qwen3_next import (
        Qwen3NextGatedDeltaNet)
    from triton_dist_tpu.layers import gdn_attn
    from triton_dist_tpu.models.hf_loader import gdn_attn_from_hf

    layer = _randomize(
        Qwen3NextGatedDeltaNet(_hf_config(), layer_idx=0).float().eval(),
        seed=0)
    hidden = torch.randn(B, S, D, generator=torch.Generator()
                         .manual_seed(1))
    with torch.no_grad():
        want = layer(hidden).numpy()

    cfg = _cfg()
    params = gdn_attn_from_hf(
        {k: v for k, v in layer.state_dict().items()}, cfg, "",
        jnp.float32)
    x = jnp.asarray(hidden.numpy().reshape(B * S, D))

    out = spmd(
        tp8_mesh,
        lambda p, xx: gdn_attn.fwd_prefill_hf(p, xx, cfg, batch=B)[0],
        (gdn_attn.param_specs_hf(), P("tp", None)),
        P("tp", None))(params, x)
    np.testing.assert_allclose(np.asarray(out).reshape(B, S, D), want,
                               rtol=2e-4, atol=2e-4)


def test_gdn_cell_decode_matches_transformers(tp8_mesh):
    """Prefill S tokens, then 3 recurrent decode steps (conv state +
    delta-rule state handoff) must reproduce the torch layer run on
    the full S+3 sequence."""
    from transformers.models.qwen3_next.modeling_qwen3_next import (
        Qwen3NextGatedDeltaNet)
    from triton_dist_tpu.layers import gdn_attn
    from triton_dist_tpu.models.hf_loader import gdn_attn_from_hf

    extra = 3
    layer = _randomize(
        Qwen3NextGatedDeltaNet(_hf_config(), layer_idx=0).float().eval(),
        seed=2)
    hidden = torch.randn(B, S + extra, D, generator=torch.Generator()
                         .manual_seed(3))
    with torch.no_grad():
        want = layer(hidden).numpy()

    cfg = _cfg()
    params = gdn_attn_from_hf(
        {k: v for k, v in layer.state_dict().items()}, cfg, "",
        jnp.float32)
    x_prefill = jnp.asarray(
        hidden.numpy()[:, :S].reshape(B * S, D))

    def prefill(p, xx):
        out, (state, conv) = gdn_attn.fwd_prefill_hf(p, xx, cfg,
                                                     batch=B)
        return out, state, conv

    out_p, state, conv = spmd(
        tp8_mesh, prefill,
        (gdn_attn.param_specs_hf(), P("tp", None)),
        (P("tp", None), P(None, "tp", None, None),
         P(None, "tp", None)))(params, x_prefill)
    np.testing.assert_allclose(np.asarray(out_p).reshape(B, S, D),
                               want[:, :S], rtol=2e-4, atol=2e-4)

    def decode(p, xx, st, cv):
        out, st2, cv2 = gdn_attn.fwd_decode_hf(p, xx, cfg, st, cv)
        return out, st2, cv2

    dec = spmd(
        tp8_mesh, decode,
        (gdn_attn.param_specs_hf(), P(None, None),
         P(None, "tp", None, None), P(None, "tp", None)),
        (P(None, None), P(None, "tp", None, None), P(None, "tp", None)))
    for t in range(extra):
        xt = jnp.asarray(hidden.numpy()[:, S + t])
        out_d, state, conv = dec(params, xt, state, conv)
        np.testing.assert_allclose(np.asarray(out_d), want[:, S + t],
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"decode step {t}")


def test_gated_attention_matches_transformers(tp8_mesh):
    """Full-attention layer parity: per-head output gate + partial
    RoPE + q/k head-dim norms, vs the eager torch forward."""
    from transformers.models.qwen3_next.modeling_qwen3_next import (
        Qwen3NextAttention, Qwen3NextRotaryEmbedding)
    from triton_dist_tpu.layers import tp_attn
    from triton_dist_tpu.models.hf_loader import _attn_from_hf

    hf_cfg = _hf_config()
    hf_cfg._attn_implementation = "eager"
    layer = _randomize(
        Qwen3NextAttention(hf_cfg, layer_idx=0).float().eval(), seed=4)
    hidden = torch.randn(B, S, D, generator=torch.Generator()
                         .manual_seed(5))
    rot = Qwen3NextRotaryEmbedding(hf_cfg)
    pos = torch.arange(S)[None].expand(B, S)
    # Eager attention applies ONLY the passed mask — build the causal
    # one explicitly.
    causal = torch.triu(torch.full((S, S), float("-inf")), diagonal=1)
    causal = causal[None, None].expand(B, 1, S, S)
    with torch.no_grad():
        cos_sin = rot(hidden, pos)
        want = layer(hidden, cos_sin, attention_mask=causal)[0].numpy()

    cfg = _cfg()
    state = {f"self_attn.{k}": v for k, v in layer.state_dict().items()}
    params = _attn_from_hf(state, cfg, "", jnp.float32)
    assert "wqg" in params
    x = jnp.asarray(hidden.numpy().reshape(B * S, D))

    out = spmd(
        tp8_mesh,
        lambda p, xx: tp_attn.fwd_prefill(p, xx, cfg, batch=B,
                                          kv_out=False),
        (tp_attn.param_specs("tp", cfg), P("tp", None)),
        P("tp", None))(params, x)
    np.testing.assert_allclose(np.asarray(out).reshape(B, S, D), want,
                               rtol=2e-4, atol=2e-4)


def test_moe_shared_expert_matches_transformers(tp8_mesh):
    """Sparse MoE block with the always-on sigmoid-gated shared
    expert, vs the torch block (routed combine + shared add)."""
    from transformers.models.qwen3_next.modeling_qwen3_next import (
        Qwen3NextSparseMoeBlock)
    from triton_dist_tpu.layers import tp_moe
    from triton_dist_tpu.models.hf_loader import _moe_from_hf

    hf_cfg = _hf_config(num_experts=4, num_experts_per_tok=2,
                        moe_intermediate_size=16,
                        shared_expert_intermediate_size=16,
                        norm_topk_prob=True)
    block = _randomize(Qwen3NextSparseMoeBlock(hf_cfg).float().eval(),
                       seed=6)
    hidden = torch.randn(B, S, D, generator=torch.Generator()
                         .manual_seed(7))
    with torch.no_grad():
        want = block(hidden)[0].numpy()

    cfg = ModelConfig.from_hf_config(hf_cfg.to_dict())
    assert cfg.shared_expert_intermediate_size == 16
    params = _moe_from_hf(
        {k: v for k, v in block.state_dict().items()}, cfg, "",
        jnp.float32)
    assert "shared_gate" in params
    x = jnp.asarray(hidden.numpy().reshape(B * S, D))

    out = spmd(
        tp8_mesh,
        lambda p, xx: tp_moe.fwd(p, xx, topk=2, num_experts=4),
        (tp_moe.param_specs("tp", cfg), P("tp", None)),
        P("tp", None))(params, x)
    np.testing.assert_allclose(np.asarray(out).reshape(B, S, D), want,
                               rtol=2e-4, atol=2e-4)

    # Replicated decode regime agrees with the same oracle.
    out_ar = spmd(
        tp8_mesh,
        lambda p, xx: tp_moe.fwd_ar(p, xx, topk=2, num_experts=4),
        (tp_moe.param_specs("tp", cfg), P(None, None)),
        P(None, None))(params, x)
    np.testing.assert_allclose(np.asarray(out_ar).reshape(B, S, D),
                               want, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Full-model parity against the committed real-format checkpoint
# ---------------------------------------------------------------------------

import os  # noqa: E402

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "qwen3_next_tiny")


def _torch_logits(ids):
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(FIXTURE).float().eval()
    with torch.no_grad():
        return model(torch.from_numpy(np.asarray(ids))).logits.numpy()


def test_hybrid_checkpoint_logits_parity(tp8_mesh):
    """load_hf_checkpoint on a REAL-format Qwen3-Next checkpoint →
    logits parity with the torch reference forward, sharded over the
    full 8-device mesh (GDN de-interleave, gated attention, shared
    expert, zero-centered norms all load-bearing)."""
    from triton_dist_tpu.models import qwen_next
    from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

    cfg, params = load_hf_checkpoint(FIXTURE, dtype=jnp.float32)
    assert cfg.is_hybrid and cfg.gdn_conv_kernel == 4 and cfg.is_moe
    ids = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0,
                           cfg.vocab_size))
    want = _torch_logits(ids)

    got = spmd(
        tp8_mesh,
        lambda p, i: qwen_next.forward_tokens(p, i, cfg),
        (qwen_next.param_specs(cfg), P(None, None)),
        P(None, None, None))(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3,
                               atol=2e-3)


def test_hybrid_checkpoint_prefill_decode_parity(tp8_mesh):
    """Prefill + recurrent/KV decode continuation must match the torch
    all-tokens forward at every decoded position."""
    from triton_dist_tpu.models import qwen_next
    from triton_dist_tpu.models.dense import FwdContexts
    from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

    cfg, params = load_hf_checkpoint(FIXTURE, dtype=jnp.float32)
    s0, extra = 8, 3
    ids = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (2, s0 + extra), 0,
                           cfg.vocab_size))
    want = _torch_logits(ids)

    specs = qwen_next.param_specs(cfg)
    cspec = qwen_next.cache_specs()

    pre = spmd(
        tp8_mesh,
        lambda p, i: qwen_next.prefill(p, i, cfg, max_len=32),
        (specs, P(None, None)), (P(None, None), cspec))
    logits, cache = pre(params, jnp.asarray(ids[:, :s0]))
    np.testing.assert_allclose(np.asarray(logits), want[:, s0 - 1],
                               rtol=2e-3, atol=2e-3)

    dec = spmd(
        tp8_mesh,
        lambda p, t, c: qwen_next.decode_step(p, t, c, cfg),
        (specs, P(None), cspec), (P(None, None), cspec))
    for t in range(extra):
        logits, cache = dec(params, jnp.asarray(ids[:, s0 + t]), cache)
        np.testing.assert_allclose(
            np.asarray(logits), want[:, s0 + t], rtol=2e-3, atol=2e-3,
            err_msg=f"decode step {t}")


def test_hybrid_checkpoint_engine_serve(tp8_mesh):
    """Engine.serve on the real-format checkpoint: greedy tokens agree
    between the XLA oracle and the fused path."""
    from triton_dist_tpu.models import Engine, qwen_next
    from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

    cfg, params = load_hf_checkpoint(FIXTURE, dtype=jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                             cfg.vocab_size)
    outs = {}
    for mode in ("xla", "fused"):
        eng = Engine(cfg, tp8_mesh, mode=mode, max_len=32,
                     params=params, model=qwen_next,
                     block_m=8, block_n=8, block_k=32)
        outs[mode] = np.asarray(eng.serve(ids, gen_len=4))
    assert outs["xla"].shape == (2, 4)
    np.testing.assert_array_equal(outs["xla"], outs["fused"])


def test_hybrid_checkpoint_ep_regime(tp8_mesh):
    """EP expert sharding for the hybrid family: Engine(moe_impl='ep')
    on the real checkpoint serves the same greedy tokens as the TP
    regime (the regime that matters for 512-expert Qwen3-Next-80B)."""
    from triton_dist_tpu.models import Engine, qwen_next
    from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

    from jax.sharding import Mesh

    cfg, params = load_hf_checkpoint(FIXTURE, dtype=jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                             cfg.vocab_size)
    # 4 experts → EP degree 4 (expert count bounds the ep axis).
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("tp",))
    eng_tp = Engine(cfg, mesh4, mode="xla", max_len=32,
                    params=params, model=qwen_next, moe_impl="tp")
    eng_ep = Engine(cfg, mesh4, mode="xla", max_len=32,
                    params=params, model=qwen_next, moe_impl="ep",
                    ep_axis="tp")
    toks_tp = np.asarray(eng_tp.serve(ids, gen_len=4))
    toks_ep = np.asarray(eng_ep.serve(ids, gen_len=4))
    np.testing.assert_array_equal(toks_ep, toks_tp)
