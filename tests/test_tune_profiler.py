"""Autotuner, tune cache, and profiler tests."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu import tune
from triton_dist_tpu.autotuner import autotune
from triton_dist_tpu.profiler import (
    Profiler, record, export_to_perfetto_trace,
)
from triton_dist_tpu.profiler_utils import perf_func


@pytest.fixture(autouse=True)
def fresh_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("TRITON_DIST_TPU_CACHE_DIR", str(tmp_path))
    tune._CACHE = None
    tune._CACHE_PATH = None
    yield
    tune._CACHE = None
    tune._CACHE_PATH = None


def test_tune_cache_roundtrip():
    key = tune.make_key("ag_gemm", m=128, k=64, dtype="float32", tp=8)
    assert tune.load_autotune_data(key) is None
    tune.store_autotune_data(key, {"block_m": 64}, 0.001)
    assert tune.load_autotune_data(key) == {"block_m": 64}
    # Same attrs → same key; different attrs → different key.
    assert key == tune.make_key("ag_gemm", m=128, k=64, dtype="float32",
                                tp=8)
    assert key != tune.make_key("ag_gemm", m=256, k=64, dtype="float32",
                                tp=8)


def test_tune_cache_version_invalidation():
    key = tune.make_key("op", a=1)
    tune.store_autotune_data(key, {"x": 1})
    cache = tune._load()
    cache[key]["versions"]["jax"] = "0.0.0"
    assert tune.load_autotune_data(key) is None


def test_autotune_picks_and_caches():
    calls = []

    @autotune("toy_op",
              configs=[{"scale": 1.0}, {"scale": 2.0}],
              key_fn=lambda x: {"shape": x.shape})
    def toy(x, scale=1.0):
        calls.append(scale)
        return x * scale

    x = jnp.ones((8, 8))
    toy(x)
    n_first = len(calls)
    assert n_first > 2  # swept both configs (timed repeatedly) + final
    calls.clear()
    toy(x)  # cached now: single call, no sweep
    assert len(calls) == 1


def test_autotune_in_trace_uses_cache_not_sweep():
    """Under jit tracing nothing can be timed: the wrapper must use the
    cache (or the first pruned candidate on a miss) and never attempt
    perf_func on tracers."""
    calls = []

    @autotune("toy_traced",
              configs=[{"scale": 3.0}, {"scale": 5.0}],
              key_fn=lambda x: {"shape": x.shape})
    def toy(x, scale=1.0):
        calls.append(scale)
        return x * scale

    x = jnp.ones((4, 4))
    out = jax.jit(toy)(x)          # miss → first candidate, no sweep
    np.testing.assert_allclose(np.asarray(out), 3.0 * np.asarray(x))
    assert calls == [3.0]

    key = tune.make_key("toy_traced", shape=x.shape)
    tune.store_autotune_data(key, {"scale": 5.0})
    # The config binds at TRACE time (it selects the compiled program),
    # so a fresh trace is required to pick up newly-tuned entries —
    # the real flow: tune offline first, then build the serving jit.
    jax.clear_caches()
    out2 = jax.jit(toy)(x)         # hit → cached config
    np.testing.assert_allclose(np.asarray(out2), 5.0 * np.asarray(x))


def test_tune_spmd_persists_for_in_trace_hits(tp8_mesh, tp8_ctx):
    """The offline sweep (tune_spmd, what tune_cli drives) must persist
    under the same key the in-trace *_tuned wrapper reads — the full
    tune-offline / serve-in-trace round trip."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_dist_tpu.autotuner import tune_spmd
    from triton_dist_tpu.ops import (ag_gemm, ag_gemm_tuned, ag_gemm_ref,
                                     create_ag_gemm_context)
    from triton_dist_tpu.utils.testing import spmd

    m, k, n_dim = 128, 64, 64
    a = jax.device_put(jax.random.normal(jax.random.PRNGKey(0), (m, k)),
                       NamedSharding(tp8_mesh, P("tp", None)))
    b = jax.device_put(jax.random.normal(jax.random.PRNGKey(1),
                                         (k, n_dim)),
                       NamedSharding(tp8_mesh, P(None, "tp")))

    def make_step(cfg):
        ctx = create_ag_gemm_context(tp8_ctx, "tp", **cfg)
        return jax.jit(jax.shard_map(
            lambda xs, ws: ag_gemm(xs, ws, ctx),
            mesh=tp8_mesh, in_specs=(P("tp", None), P(None, "tp")),
            out_specs=P(None, "tp"), check_vma=False))

    best = tune_spmd(
        "ag_gemm",
        [{"block_m": 16, "block_n": 8, "block_k": 32},
         {"block_m": 8, "block_n": 8, "block_k": 16}],
        make_step, (a, b),
        {"m": m // 8, "k": k, "n": n_dim // 8,
         "dtype": "float32", "world": 8}, reps=1)
    assert best is not None
    key = tune.make_key("ag_gemm", m=m // 8, k=k, n=n_dim // 8,
                        dtype="float32", world=8)
    assert tune.load_autotune_data(key) == best

    got = spmd(tp8_mesh, lambda x, w: ag_gemm_tuned(x, w, tp8_ctx),
               (P("tp", None), P(None, "tp")), P(None, "tp"))(a, b)
    want = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
                (P("tp", None), P(None, "tp")), P(None, "tp"))(a, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_perf_func_unchained():
    f = jax.jit(lambda x: x * 2.0)
    t = perf_func(f, (jnp.ones((16, 16)),), chain=False, iters_hi=4,
                  repeats=1)
    assert t >= 0


def test_profiler_slots_and_export(tmp_path):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from triton_dist_tpu.lang import core_call

    prof = Profiler(capacity=8)

    def kernel(x_ref, o_ref, prof_out, buf, cursor):
        cursor[0] = 0
        record(buf, cursor, tag=1, value=x_ref.shape[0])
        o_ref[...] = x_ref[...] * 2.0
        record(buf, cursor, tag=2, value=cursor[0])
        prof_out[...] = buf[...]

    x = jnp.ones((8, 128))
    out, slots = core_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(x.shape, x.dtype),
                   prof.out_shape()),
        scratch_shapes=prof.scratch_shapes(),
    )(x)
    slots = np.asarray(slots)
    assert slots[0, 0] == 1 and slots[0, 1] == 8
    assert slots[1, 0] == 2

    path = export_to_perfetto_trace(slots, str(tmp_path / "t.json"),
                                    tag_names={1: "start", 2: "end"})
    data = json.load(open(path))
    names = [e["name"] for e in data["traceEvents"]]
    assert "start" in names and "end" in names
