"""Inner SPMD worker for the multi-host integration test.

Launched by ``scripts/launch.py`` (2 processes x 4 virtual CPU devices)
— the localhost analogue of a 2-host x 4-chip pod slice. Exercises the
full multi-host contract: env bring-up (initialize_distributed), the
canonical mesh with the DCN axis outermost (docs/build.md), cross-
process collectives over both axes, and MeshContext logical-id
addressing.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from triton_dist_tpu.utils.distributed import (  # noqa: E402
    initialize_distributed, dist_print,
)

initialize_distributed()   # reads COORDINATOR_ADDRESS/NUM_PROCESSES/...

import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402
import numpy as np                               # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import triton_dist_tpu as tdt                    # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

# dp is the outer (DCN) axis: each process' 4 local devices form its tp
# group, matching the pod model where ICI is intra-host and DCN crosses.
mesh = tdt.make_mesh(dp=2, tp=4, devices=jax.devices())
mctx = tdt.MeshContext.from_mesh(mesh)
assert mctx.size("dp") == 2 and mctx.size("tp") == 4

x = jax.device_put(
    jnp.arange(16.0).reshape(8, 2),
    NamedSharding(mesh, P(("dp", "pp", "ep", "sp", "tp"), None)))


def spmd(v):
    def inner(u):
        total = jax.lax.psum(u, ("dp", "tp"))              # DCN + ICI
        row = jax.lax.all_gather(u, "tp", axis=0, tiled=True)  # ICI only
        return total, jax.lax.psum(row, ("dp",)) / 2.0
    return jax.shard_map(
        inner, mesh=mesh,
        in_specs=P(("dp", "pp", "ep", "sp", "tp"), None),
        out_specs=(P(None, None), P(None, None)), check_vma=False)(v)


total, row_mean = jax.jit(spmd)(x)
np.testing.assert_allclose(
    np.asarray(jax.device_get(total))[0], [56.0, 64.0])
assert np.asarray(jax.device_get(row_mean)).shape == (4, 2)
dist_print("multihost contract OK", allowed_ranks="all")

# --- fused Pallas kernel under jax.distributed (VERDICT r4 #8) -------
# The pod pattern: ag_gemm's RDMA ring rides the intra-host tp axis
# while the dp (DCN) hop is an XLA collective on its output. On silicon
# both live in ONE jit over the global mesh. The CPU battery must split
# them: Mosaic interpret mode sizes its simulated-chip state from the
# *global* axis env and gates kernel entry on a
# ``threading.Barrier(num_devices)`` (jax _src/pallas/mosaic/interpret/
# interpret_pallas_call.py:209) — in a 2-process run each process hosts
# only half the mesh's callback threads, so an interpret pallas call
# inside a global-mesh shard_map deadlocks by construction. So: the
# fused kernel runs per-process over the local 4-device tp submesh
# (exactly what interpret can simulate), proving the Pallas+RDMA path
# compiles and executes under an initialized jax.distributed runtime,
# and the cross-process reduce runs on the global mesh.
from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context  # noqa: E402

m, kdim, ndim = 32, 16, 16   # small: 2-proc interpret compile dominates
local_mesh = tdt.make_mesh(tp=4, devices=jax.local_devices())
local_ctx = tdt.MeshContext.from_mesh(local_mesh)
a_l = jax.device_put(
    jax.random.normal(jax.random.PRNGKey(5), (m, kdim), jnp.float32),
    NamedSharding(local_mesh, P("tp", None)))
b_l = jax.device_put(
    jax.random.normal(jax.random.PRNGKey(6 + jax.process_index()),
                      (kdim, ndim), jnp.float32),
    NamedSharding(local_mesh, P(None, "tp")))
agc = create_ag_gemm_context(local_ctx, axis="tp", block_m=8, block_n=8)


def fused_local(a, b):
    return jax.shard_map(
        lambda aa, bb: ag_gemm(aa, bb, agc),   # Pallas RDMA ring (ICI)
        mesh=local_mesh,
        in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp"), check_vma=False)(a, b)


c_l = jax.jit(fused_local)(a_l, b_l)           # per-process fused kernel
c_np = np.asarray(jax.device_get(c_l))
want_l = (np.asarray(jax.device_get(a_l)) @ np.asarray(jax.device_get(b_l)))
np.testing.assert_allclose(c_np, want_l, rtol=1e-4, atol=1e-4)
dist_print("fused ag_gemm under jax.distributed OK", allowed_ranks="all")

# DCN hop on the fused kernel's output: global-mesh mean over dp.
c_g = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp", None)),
    c_np.reshape(1, m * ndim))


def dcn_mean(v):
    return jax.shard_map(
        lambda u: jax.lax.psum(u, "dp") / 2.0, mesh=mesh,
        in_specs=P(("dp", "pp", "ep", "sp"), None),
        out_specs=P(None, None), check_vma=False)(v)


got_mean = np.asarray(jax.device_get(jax.jit(dcn_mean)(c_g))).reshape(m, ndim)
# Seeds are rank-keyed, so every process can rebuild both oracles.
b_all = [np.asarray(jax.random.normal(jax.random.PRNGKey(6 + r),
                                      (kdim, ndim), jnp.float32))
         for r in range(2)]
a_np = np.asarray(jax.device_get(a_l))
want_mean = (a_np @ b_all[0] + a_np @ b_all[1]) / 2.0
np.testing.assert_allclose(got_mean, want_mean, rtol=1e-4, atol=1e-4)
dist_print("DCN reduce over fused output OK", allowed_ranks="all")
print(f"RESULT_OK rank={jax.process_index()}", flush=True)
