"""Inner SPMD worker for the multi-host integration test.

Launched by ``scripts/launch.py`` (2 processes x 4 virtual CPU devices)
— the localhost analogue of a 2-host x 4-chip pod slice. Exercises the
full multi-host contract: env bring-up (initialize_distributed), the
canonical mesh with the DCN axis outermost (docs/build.md), cross-
process collectives over both axes, and MeshContext logical-id
addressing.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from triton_dist_tpu.utils.distributed import (  # noqa: E402
    initialize_distributed, dist_print,
)

initialize_distributed()   # reads COORDINATOR_ADDRESS/NUM_PROCESSES/...

import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402
import numpy as np                               # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import triton_dist_tpu as tdt                    # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

# dp is the outer (DCN) axis: each process' 4 local devices form its tp
# group, matching the pod model where ICI is intra-host and DCN crosses.
mesh = tdt.make_mesh(dp=2, tp=4, devices=jax.devices())
mctx = tdt.MeshContext.from_mesh(mesh)
assert mctx.size("dp") == 2 and mctx.size("tp") == 4

x = jax.device_put(
    jnp.arange(16.0).reshape(8, 2),
    NamedSharding(mesh, P(("dp", "pp", "ep", "sp", "tp"), None)))


def spmd(v):
    def inner(u):
        total = jax.lax.psum(u, ("dp", "tp"))              # DCN + ICI
        row = jax.lax.all_gather(u, "tp", axis=0, tiled=True)  # ICI only
        return total, jax.lax.psum(row, ("dp",)) / 2.0
    return jax.shard_map(
        inner, mesh=mesh,
        in_specs=P(("dp", "pp", "ep", "sp", "tp"), None),
        out_specs=(P(None, None), P(None, None)), check_vma=False)(v)


total, row_mean = jax.jit(spmd)(x)
np.testing.assert_allclose(
    np.asarray(jax.device_get(total))[0], [56.0, 64.0])
assert np.asarray(jax.device_get(row_mean)).shape == (4, 2)
dist_print("multihost contract OK", allowed_ranks="all")

# --- fused Pallas kernel under jax.distributed (VERDICT r4 #8) -------
# ag_gemm's RDMA ring runs over the intra-process tp axis while the
# same program crosses processes with a dp psum — the pod pattern
# (fused kernels ride ICI, DCN hops stay XLA collectives). Interpret
# mode simulates remote DMA within one process's devices only, so the
# ring cannot span dp here; on silicon the identical code spans any
# Mosaic-reachable axis.
from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context  # noqa: E402

m, kdim, ndim = 64, 16, 32
ka = jax.random.PRNGKey(5)
a_g = jax.device_put(
    jax.random.normal(ka, (m, kdim), jnp.float32),
    NamedSharding(mesh, P("tp", None)))
b_g = jax.device_put(
    jax.random.normal(jax.random.PRNGKey(6), (kdim, ndim), jnp.float32),
    NamedSharding(mesh, P(None, "tp")))
agc = create_ag_gemm_context(mctx, axis="tp", block_m=8, block_n=8)


def fused(a, b):
    def inner(aa, bb):
        c = ag_gemm(aa, bb, agc)               # Pallas RDMA ring (ICI)
        return jax.lax.psum(c, "dp") / 2.0     # DCN hop in the same jit
    return jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp"), check_vma=False)(a, b)


got = np.asarray(jax.device_get(jax.jit(fused)(a_g, b_g)))
want = (np.asarray(jax.device_get(a_g))
        @ np.asarray(jax.device_get(b_g)))
np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
dist_print("fused ag_gemm under jax.distributed OK",
           allowed_ranks="all")
print(f"RESULT_OK rank={jax.process_index()}", flush=True)
