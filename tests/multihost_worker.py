"""Inner SPMD worker for the multi-host integration test.

Launched by ``scripts/launch.py`` (2 processes x 4 virtual CPU devices)
— the localhost analogue of a 2-host x 4-chip pod slice. Exercises the
full multi-host contract: env bring-up (initialize_distributed), the
canonical mesh with the DCN axis outermost (docs/build.md), cross-
process collectives over both axes, and MeshContext logical-id
addressing.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from triton_dist_tpu.utils.distributed import (  # noqa: E402
    initialize_distributed, dist_print,
)

initialize_distributed()   # reads COORDINATOR_ADDRESS/NUM_PROCESSES/...

import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402
import numpy as np                               # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import triton_dist_tpu as tdt                    # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

# dp is the outer (DCN) axis: each process' 4 local devices form its tp
# group, matching the pod model where ICI is intra-host and DCN crosses.
mesh = tdt.make_mesh(dp=2, tp=4, devices=jax.devices())
mctx = tdt.MeshContext.from_mesh(mesh)
assert mctx.size("dp") == 2 and mctx.size("tp") == 4

x = jax.device_put(
    jnp.arange(16.0).reshape(8, 2),
    NamedSharding(mesh, P(("dp", "pp", "ep", "sp", "tp"), None)))


def spmd(v):
    def inner(u):
        total = jax.lax.psum(u, ("dp", "tp"))              # DCN + ICI
        row = jax.lax.all_gather(u, "tp", axis=0, tiled=True)  # ICI only
        return total, jax.lax.psum(row, ("dp",)) / 2.0
    return jax.shard_map(
        inner, mesh=mesh,
        in_specs=P(("dp", "pp", "ep", "sp", "tp"), None),
        out_specs=(P(None, None), P(None, None)), check_vma=False)(v)


total, row_mean = jax.jit(spmd)(x)
np.testing.assert_allclose(
    np.asarray(jax.device_get(total))[0], [56.0, 64.0])
assert np.asarray(jax.device_get(row_mean)).shape == (4, 2)
dist_print("multihost contract OK", allowed_ranks="all")
print(f"RESULT_OK rank={jax.process_index()}", flush=True)
