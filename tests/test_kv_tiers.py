"""Tiered KV memory hierarchy battery: the host/disk tier store, the
scored (frequency/recency) prefix eviction that demotes instead of
dropping, session park/resume token-exactness, tier coherence under
chaos, and the seeded heavy-tailed multi-turn acceptance trace
(docs/serving.md, "KV memory hierarchy").

Everything is seeded; token-exactness gates diff against the
``Engine.serve`` oracle like the rest of the serving batteries.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.resilience import chaos, faults
from triton_dist_tpu.serving import (
    BlockManager, KVTierStore, OutOfPagesError, ServingEngine,
    TierFullError, heavy_tail_trace,
)
from triton_dist_tpu.serving.tiers import extend_session

CFG = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                       intermediate_size=32, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=4,
                       head_dim=8)
MAX_LEN = 32


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:1]), ("tp",))


@pytest.fixture(scope="module")
def engine(mesh):
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)


def _oracle(engine, prompt, gen_len):
    ids = jnp.asarray(np.asarray([list(prompt)], np.int32))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


def _payload(seed=0, pages=1, layers=2, kv=2, page=4, hd=3):
    rng = np.random.RandomState(seed)
    k = rng.randn(layers, pages, kv, page, hd).astype(np.float32)
    v = rng.randn(layers, pages, kv, page, hd).astype(np.float32)
    return k, v


# ---------------------------------------------------------------------------
# KVTierStore units (pure host logic)
# ---------------------------------------------------------------------------

def test_tier_store_roundtrip_and_stats():
    st = KVTierStore(host_pages=8)
    k, v = _payload(0)
    st.put(("prefix", ("a",)), (k, v), pages=1)
    assert ("prefix", ("a",)) in st and len(st) == 1
    got = st.get(("prefix", ("a",)))
    np.testing.assert_array_equal(got[0], k)
    np.testing.assert_array_equal(got[1], v)
    # get leaves the entry resident (promotion pops explicitly).
    assert ("prefix", ("a",)) in st
    assert st.get(("nope",)) is None
    e = st.pop(("prefix", ("a",)))
    assert e is not None and ("prefix", ("a",)) not in st
    s = st.stats()
    assert s["puts"] == 1 and s["hits"] == 1 and s["misses"] == 1
    assert s["offloaded_pages"] == 1 and s["host_pages_used"] == 0
    st.check_coherence()


def test_tier_store_spill_to_disk_and_promote(tmp_path):
    st = KVTierStore(host_pages=2, disk_pages=8,
                     disk_dir=str(tmp_path))
    payloads = {i: _payload(i) for i in range(4)}
    for i in range(4):
        st.put(("prefix", i), payloads[i], pages=1)
    st.check_coherence()
    s = st.stats()
    # Host holds the 2 newest; the 2 oldest spilled to disk, bytes
    # intact through the uint8 spill codec.
    assert s["host_pages_used"] == 2 and s["disk_pages_used"] == 2
    assert s["spills"] == 2 and s["dropped_entries"] == 0
    got = st.get(("prefix", 0))          # disk hit -> promoted
    np.testing.assert_array_equal(got[0], payloads[0][0])
    st.check_coherence()
    # Promoted into the (full) host tier: its LRU victim spilled the
    # other way, so entry 0 now lives host-side.
    assert ("prefix", 0) in st._host
    assert st.stats()["host_pages_used"] == 2


def test_tier_store_promotion_cascade_never_evicts_fetchee(tmp_path):
    """Regression: with BOTH tiers at capacity, promoting a disk hit
    spills a host victim into the disk tier — that cascade must never
    evict (and delete the spill file of) the entry being fetched."""
    st = KVTierStore(host_pages=1, disk_pages=1,
                     disk_dir=str(tmp_path))
    ka, va = _payload(1)
    st.put(("prefix", "a"), (ka, va), pages=1)
    st.put(("prefix", "b"), _payload(2), pages=1)   # a spills to disk
    got = st.get(("prefix", "a"))                   # disk hit, full cascade
    np.testing.assert_array_equal(got[0], ka)
    st.check_coherence()
    assert ("prefix", "a") in st
    # And it stays readable on the next fetch too.
    np.testing.assert_array_equal(st.get(("prefix", "a"))[0], ka)


def test_tier_store_oversized_payload_goes_straight_to_disk(tmp_path):
    """A session payload larger than the WHOLE host tier must still
    park when the disk tier has room (pinned payloads are
    never-dropped by contract, so 'host too small' alone cannot be a
    permanent park failure)."""
    st = KVTierStore(host_pages=2, disk_pages=16,
                     disk_dir=str(tmp_path))
    big = _payload(9, pages=6)
    st.put(("session", "big"), big, pages=6, pinned=True)
    st.check_coherence()
    assert st.stats()["disk_pages_used"] == 6
    np.testing.assert_array_equal(st.get(("session", "big"))[0],
                                  big[0])
    # Without a disk tier it IS a (loud) failure — and the store is
    # left unchanged.
    st2 = KVTierStore(host_pages=2)
    with pytest.raises(TierFullError):
        st2.put(("session", "big"), big, pages=6, pinned=True)
    assert len(st2) == 0
    st2.check_coherence()


def test_tier_store_samekey_replace_never_double_counts():
    st = KVTierStore(host_pages=4)
    st.put(("session", "r"), _payload(1, pages=4), pages=4,
           pinned=True)
    # Refreshing the SAME key at full capacity is a pure replace —
    # the old copy must not count against the new one's room.
    newer = _payload(2, pages=4)
    st.put(("session", "r"), newer, pages=4, pinned=True)
    np.testing.assert_array_equal(st.get(("session", "r"))[0],
                                  newer[0])
    assert st.stats()["host_pages_used"] == 4
    st.check_coherence()
    # And a FAILED replace (faulted transfer) keeps the old payload.
    plan = faults.FaultPlan(
        name="drop-tier",
        faults=(faults.Fault("fail_call", op="tier_transfer", k=0),))
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            st.put(("session", "r"), _payload(3, pages=4), pages=4,
                   pinned=True)
    np.testing.assert_array_equal(st.get(("session", "r"))[0],
                                  newer[0])
    st.check_coherence()


def test_tier_store_failed_spill_cascade_keeps_pinned(tmp_path):
    """Regression: the host tier holds a pinned session while the
    disk tier is full of pinned payloads — a put() that needs room
    must fail WITHOUT destroying the host victim mid-cascade (the
    spill write happens before the entry leaves the host index)."""
    st = KVTierStore(host_pages=1, disk_pages=1,
                     disk_dir=str(tmp_path))
    pa = _payload(1)
    st.put(("session", "disk"), _payload(0), pages=1, pinned=True)
    st.put(("session", "host"), pa, pages=1, pinned=True)  # spills 'disk'? no:
    # host full after this put; 'disk' got spilled to the disk tier.
    st.check_coherence()
    with pytest.raises(TierFullError):
        st.put(("prefix", "x"), _payload(2), pages=1)
    st.check_coherence()
    # Both pinned payloads survive the failed put, bytes intact.
    np.testing.assert_array_equal(st.get(("session", "host"))[0],
                                  pa[0])
    assert ("session", "disk") in st


def test_tier_store_pinned_full_disk_falls_back_to_droppable(tmp_path):
    """Regression: a pinned-full DISK tier must not fail a put that
    evicting recomputable host content could satisfy — the spill
    fallback drops the droppable host entry instead of raising."""
    st = KVTierStore(host_pages=4, disk_pages=2,
                     disk_dir=str(tmp_path))
    st.put(("session", "d"), _payload(0, pages=2), pages=2,
           pinned=True)
    st.put(("session", "h"), _payload(1, pages=2), pages=2,
           pinned=True)
    st.put(("prefix", "x"), _payload(2, pages=2), pages=2)
    st.check_coherence()         # host: [h(pinned), x]; disk: [d]
    assert st.stats()["disk_pages_used"] == 2
    pa = _payload(3, pages=2)
    st.put(("session", "new"), pa, pages=2, pinned=True)
    st.check_coherence()
    # The droppable prefix entry made way; all three pinned sessions
    # survive with bytes intact.
    for k in (("session", "d"), ("session", "h"), ("session", "new")):
        assert k in st, k
    assert ("prefix", "x") not in st
    np.testing.assert_array_equal(st.get(("session", "new"))[0], pa[0])


def test_tier_store_pinned_never_dropped():
    st = KVTierStore(host_pages=2)
    st.put(("session", "r1"), _payload(1), pages=1, pinned=True)
    st.put(("prefix", 1), _payload(2), pages=1)
    # A third put evicts the LRU DROPPABLE entry, never the pinned
    # session (no disk tier here — dropping it would lose a parked
    # request's only KV copy).
    st.put(("prefix", 2), _payload(3), pages=1)
    assert ("session", "r1") in st and ("prefix", 1) not in st
    assert st.stats()["dropped_entries"] == 1
    st.put(("session", "r2"), _payload(4), pages=1, pinned=True)
    with pytest.raises(TierFullError):
        st.put(("session", "r3"), _payload(5), pages=1, pinned=True)
    st.check_coherence()


def test_tier_store_two_phase_fault_leaves_store_unchanged():
    st = KVTierStore(host_pages=8)
    st.put(("prefix", 1), _payload(1), pages=1)
    plan = faults.FaultPlan(
        name="drop-tier",
        faults=(faults.Fault("fail_call", op="tier_transfer", k=0),))
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            st.put(("prefix", 2), _payload(2), pages=1)
    # The staged entry was discarded, nothing committed, the earlier
    # entry untouched — and a faulted GET keeps the entry resident.
    st.check_coherence()
    assert ("prefix", 2) not in st and ("prefix", 1) in st
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            st.get(("prefix", 1))
    assert ("prefix", 1) in st
    np.testing.assert_array_equal(st.get(("prefix", 1))[0],
                                  _payload(1)[0])


def test_tier_store_snapshot_roundtrip(tmp_path):
    st = KVTierStore(host_pages=2, disk_pages=4,
                     disk_dir=str(tmp_path / "a"))
    st.put(("session", "r"), _payload(7), pages=1, pinned=True,
           meta={"n_tok": 5})
    for i in range(2):
        st.put(("prefix", i), _payload(i), pages=1)
    snap = st.snapshot()
    st2 = KVTierStore(host_pages=4)          # no disk on the restorer
    st2.load_snapshot(snap)
    st2.check_coherence()
    assert len(st2) == 3
    np.testing.assert_array_equal(st2.get(("session", "r"))[0],
                                  _payload(7)[0])
    assert st2.entry(("session", "r")).meta["n_tok"] == 5


def test_tier_bridge_put_roundtrip():
    """The tier hop over the one-sided p2p edge (the multi-controller
    host-memory hop's shape): bytes bit-exact through the put."""
    from triton_dist_tpu.ops.p2p import tier_pages_host

    bridge = Mesh(np.array(jax.devices()[:2]), ("role",))
    k, v = _payload(3, pages=2)
    k2, v2 = tier_pages_host(k, v, bridge, axis="role", src=0, dst=1)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)
    st = KVTierStore(host_pages=8, bridge=(bridge, "role", 0, 1))
    st.put(("prefix", 0), (k, v), pages=2)
    got = st.get(("prefix", 0))
    np.testing.assert_array_equal(got[0], k)
    np.testing.assert_array_equal(got[1], v)
    assert st.stats()["transport"] == "p2p"


# ---------------------------------------------------------------------------
# Scored eviction (BlockManager)
# ---------------------------------------------------------------------------

def _commit_prefix(m, slot, tokens):
    pages = m.alloc_prefill(slot, tokens)
    m.commit_prefix(slot)
    return pages


def test_scored_eviction_prefers_cold():
    m = BlockManager(num_pages=8, page=4, p_max=4, prefix_reuse=True)
    cold = list(range(8))                 # 2 full pages, committed 1st
    hot = list(range(100, 108))
    _commit_prefix(m, 0, cold)
    _commit_prefix(m, 1, hot)
    m.free_slot(0)
    m.free_slot(1)
    # Touch the HOT prefix repeatedly: its EWMA score grows while the
    # cold one decays.
    for s in (2, 3, 4):
        m.alloc_prefill(s, hot)
        m.free_slot(s)
    assert m.stats["prefix_hits"] >= 6
    demoted = []
    m.on_demote = lambda key, pid: demoted.append((key, pid)) or True
    # Insertion order would evict the COLD-first entry anyway here, so
    # force two: the second victim must still not be the hot set.
    victims = m.evict(2)
    assert len(victims) == 2 and len(demoted) == 2
    assert m.stats["demotions"] == 2 and m.stats["evictions"] == 2
    # Both cold pages left; both hot pages survive.
    hot_alloc = m.alloc_prefill(5, hot)
    assert m.stats["prefix_hits"] >= 8, "hot prefix was evicted"
    m.free_slot(5)
    # Reverse check: recommit cold, touch it, starve-evict — the
    # (now untouched) hot entries go first despite later insertion.
    _commit_prefix(m, 6, cold)
    m.free_slot(6)
    for s in (2, 3, 4):
        m.alloc_prefill(s, cold)
        m.free_slot(s)
    v2 = m.evict(2)
    cold_pages = set(m.alloc_prefill(7, cold))
    assert m.stats["prefix_hits"] >= 13, \
        f"cold-turned-hot prefix evicted: {v2} vs {cold_pages}"


def test_evict_skips_pages_live_sharers_hold():
    m = BlockManager(num_pages=6, page=4, p_max=4, prefix_reuse=True)
    shared = list(range(4))
    _commit_prefix(m, 0, shared)           # slot 0 HOLDS the page
    assert m.evict(4) == [], "evicted a page a live slot references"
    m.free_slot(0)
    assert len(m.evict(4)) == 1            # now unreferenced -> fair game


def test_manager_snapshot_keeps_scores():
    m = BlockManager(num_pages=8, page=4, p_max=4, prefix_reuse=True)
    _commit_prefix(m, 0, list(range(4)))
    m.free_slot(0)
    m.alloc_prefill(1, list(range(4)))
    m.free_slot(1)
    snap = m.snapshot()
    m2 = BlockManager(num_pages=8, page=4, p_max=4, prefix_reuse=True)
    m2.load_snapshot(snap)
    assert m2._score == m._score and m2._tick == m._tick


# ---------------------------------------------------------------------------
# Park / resume (serving engine)
# ---------------------------------------------------------------------------

def test_park_resume_token_exact(engine):
    srv = ServingEngine(engine, num_slots=2, page=8, prefix_reuse=True,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([5, 6, 7], max_new_tokens=6)
    srv.step()
    srv.step()
    srv.step()
    assert h.status == "running" and len(h.tokens) >= 2
    srv.park(h)
    assert h.status == "parked" and h.slot is None
    st = srv.stats()
    assert st["parked_sessions"] == 1 and st["parks"] == 1
    assert st["tier_pages"] >= 1 and st["offloaded_pages"] >= 1
    chaos.check_invariants(srv)
    srv.resume(h)
    srv.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, [5, 6, 7], 6), \
        "park/resume diverged from the uninterrupted serve"
    assert srv.decode_cache_size() == 1
    assert srv.stats()["resumes"] == 1
    chaos.check_invariants(srv)


def test_park_frees_slot_for_other_traffic(engine):
    srv = ServingEngine(engine, num_slots=1, page=8,
                        kv_tiers={"host_pages": 32})
    a = srv.submit([1, 2, 3], max_new_tokens=6)
    srv.step()
    srv.step()
    srv.park(a)
    # The single slot is free again: b serves END TO END while a sits
    # parked — the capacity the park verb exists to reclaim.
    b = srv.submit([9, 8], max_new_tokens=4)
    srv.run()
    assert b.status == "done" and a.status == "parked"
    assert b.tokens == _oracle(engine, [9, 8], 4)
    srv.resume(a)
    srv.run()
    assert a.tokens == _oracle(engine, [1, 2, 3], 6)


def test_park_resume_quantized_pool(mesh):
    eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
    kw = dict(num_slots=2, page=8, kv_dtype="int8")
    plain = ServingEngine(eng, **kw)
    want = plain.generate([[4, 5, 6]], max_new_tokens=6)[0]
    srv = ServingEngine(eng, kv_tiers={"host_pages": 32}, **kw)
    h = srv.submit([4, 5, 6], max_new_tokens=6)
    srv.step()
    srv.step()
    srv.step()
    srv.park(h)
    # Quantized pools park their STORED bytes + scales — bit-exact.
    e = srv.tiers.entry(("session", h.request.request_id))
    assert len(e.arrays) == 4 and e.arrays[0].dtype == np.int8
    srv.resume(h)
    srv.run()
    assert h.tokens == want, "quantized park/resume drifted"


def test_park_quant_harder(engine):
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32},
                        park_quant="int8")
    h = srv.submit([3, 1, 4], max_new_tokens=6)
    srv.step()
    srv.step()
    srv.step()
    n_pre = len(h.tokens)
    srv.park(h)
    e = srv.tiers.entry(("session", h.request.request_id))
    # "Quantize harder": the parked payload stores at 1 B/elem with
    # fp32 scales alongside (vs the pool's fp32) — 4x smaller host
    # bytes; resume is approximate, not bit-exact (documented).
    assert e.arrays[0].dtype == np.int8 and len(e.arrays) == 4
    assert e.meta["park_quant"] == "int8"
    srv.resume(h)
    srv.run()
    assert h.status == "done" and len(h.tokens) == 6
    assert h.tokens[:n_pre] == _oracle(engine, [3, 1, 4], 6)[:n_pre]


def test_park_after_failed_dispatch_page_skew(engine):
    """Regression: a failed decode dispatch leaves the allocator one
    idempotent pre-appended page AHEAD of the length mirror — a park
    in that state must payload exactly the mirror's pages, or resume's
    alloc_resume re-derives a different count and the scatter
    corrupts/crashes."""
    srv = ServingEngine(engine, num_slots=2, page=4,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([5, 6, 7], max_new_tokens=8)
    srv.step()
    while int(srv._lens[h.slot]) % 4 != 0:
        srv.step()                      # land exactly on a page edge
    assert h.status == "running"
    # The failed tick's surviving pre-append: allocator grows a page,
    # the mirror does not advance (the step's results were dropped).
    srv.manager.append(h.slot, int(srv._lens[h.slot]))
    assert (len(srv.manager._slot_pages[h.slot]) * 4
            > int(srv._lens[h.slot]))
    srv.park(h)
    srv.resume(h)
    srv.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, [5, 6, 7], 8)
    chaos.check_invariants(srv)


def test_fits_snapshot_matches_actual_load(tmp_path):
    """The restore gate's dry-run placement must agree with what
    load_snapshot actually does — including greedy-spill failures on
    sets a smarter packing could fit."""
    def snap_of(entries):
        return {"host": [{"key": ("session", str(i)), "pages": p,
                          "pinned": pin, "meta": {},
                          "arrays": _payload(i, pages=p)}
                         for i, (p, pin) in enumerate(entries)],
                "disk": [], "counters": {}}

    cases = [
        # (entries, host, disk): one oversized pinned entry — sum fits
        # host+disk but the atomic entry fits neither tier's spill.
        ([(6, True)], 4, 4),
        # greedy spill order fails though an optimal packing exists
        ([(4, True), (4, True), (2, True)], 5, 6),
        # loadable: overflow spills, droppables drop
        ([(2, True), (2, False), (2, True)], 4, 2),
        ([(1, False)] * 3, 4, 0),
        # pinned-full disk mid-load: the droppable-host fallback
        # makes this loadable where a spill-only policy would fail
        ([(2, True), (2, False), (2, True)], 2, 2),
        # ... and with nothing droppable it genuinely cannot fit
        ([(2, True), (2, True), (2, True)], 2, 2),
    ]
    for i, (entries, hp, dp) in enumerate(cases):
        kw = ({"disk_pages": dp, "disk_dir": str(tmp_path / str(i))}
              if dp else {})
        st = KVTierStore(host_pages=hp, **kw)
        verdict = st.fits_snapshot(snap_of(entries))
        try:
            st.load_snapshot(snap_of(entries))
            loaded = True
            st.check_coherence()
        except TierFullError:
            loaded = False
        assert (verdict is None) == loaded, \
            f"case {i}: dry-run said {verdict!r}, load said {loaded}"


def test_restore_into_undersized_tiers_rejected_before_mutation(
        mesh, engine):
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([5, 6, 7], max_new_tokens=6)
    srv.step()
    srv.step()
    srv.park(h)
    snap = srv.checkpoint()
    small = ServingEngine(engine, num_slots=2, page=8,
                          kv_tiers={"host_pages": 32})
    # Shrink the would-be restorer's host tier below the pinned
    # payload: the up-front gate must fire BEFORE any mutation.
    small.tiers.host_pages = 0
    with pytest.raises(ValueError, match="do not fit"):
        small.restore(snap)
    assert not small.sched.slots and not small.sched.queue
    assert not small._parked and len(small.tiers) == 0
    srv.resume(h)
    srv.run()
    assert h.tokens == _oracle(engine, [5, 6, 7], 6)


def test_faulted_park_leaves_request_running(engine):
    """The two-phase park: a dropped offload transfer (past retries)
    aborts the park with NOTHING freed — the request keeps running
    and finishes token-exact; a later un-faulted park succeeds."""
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([5, 6, 7], max_new_tokens=6)
    srv.step()
    srv.step()
    srv.step()
    plan = faults.FaultPlan(
        name="drop-park",
        faults=(faults.Fault("fail_call", op="tier_transfer",
                             k=None),))
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            srv.park(h)
    assert h.status == "running" and h.slot is not None
    assert srv.stats()["parked_sessions"] == 0
    assert len(srv.tiers) == 0 and not srv.tiers._staged
    chaos.check_invariants(srv)
    srv.park(h)                        # un-faulted retry works
    srv.resume(h)
    srv.run()
    assert h.tokens == _oracle(engine, [5, 6, 7], 6)


def test_park_payload_is_materialized_not_a_gather_view(engine):
    """Regression: the parked payload must own exactly its pages'
    bytes — a slice VIEW would pin the whole p_max-wide gather buffer
    in host RAM behind every parked session, defeating host_pages."""
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([5, 6, 7], max_new_tokens=4)
    srv.step()
    srv.step()
    srv.park(h)
    e = srv.tiers.entry(("session", h.request.request_id))
    for a in e.arrays:
        assert a.base is None and a.flags["C_CONTIGUOUS"], \
            "parked payload retains the full gather buffer (view)"
        assert a.shape[1] == e.pages
    srv.resume(h)
    srv.run()
    assert h.tokens == _oracle(engine, [5, 6, 7], 4)


def test_park_bad_states(engine):
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([1, 2], max_new_tokens=4)
    with pytest.raises(ValueError, match="running"):
        srv.park(h)                         # still queued
    with pytest.raises(ValueError, match="parked"):
        srv.resume(h)
    srv.run()
    plain = ServingEngine(engine, num_slots=2, page=8)
    g = plain.submit([1, 2], max_new_tokens=4)
    plain.step()
    with pytest.raises(RuntimeError, match="kv_tiers"):
        plain.park(g)
    plain.run()
    with pytest.raises(ValueError, match="park_quant"):
        ServingEngine(engine, num_slots=2, page=8, park_quant="int8")
    with pytest.raises(ValueError, match="UNQUANTIZED"):
        ServingEngine(engine, num_slots=2, page=8, kv_dtype="int8",
                      kv_tiers={"host_pages": 8}, park_quant="fp8")
    with pytest.raises(TypeError, match="kv_tiers"):
        ServingEngine(engine, num_slots=2, page=8, kv_tiers=3.5)


def test_megakernel_rejects_kv_tiers(mesh):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=8)
    mk = MegaKernelEngine(cfg, mesh, batch=2, max_len=32, tile_w=16,
                          t_tile=16)
    # A proper NotImplementedError naming the arena-tier limitation
    # and the ROADMAP item tracking it (Open item 3).
    with pytest.raises(NotImplementedError,
                       match="arena-tier limitation"):
        ServingEngine(mk, kv_tiers=True)
    with pytest.raises(NotImplementedError, match="Open item 3"):
        ServingEngine(mk, kv_tiers=True)


# ---------------------------------------------------------------------------
# Prefix demote -> tier refetch
# ---------------------------------------------------------------------------

PREFIX = [9, 10, 11, 12, 13, 14, 15, 16, 2]      # 2 full pages @ page=4


def _tiered_prefix_engine(mesh, **kw):
    eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
    kw.setdefault("kv_tiers", {"host_pages": 64})
    return ServingEngine(eng, num_slots=2, page=4, num_pages=10,
                         prefix_reuse=True, prefill_buckets=(4, 8),
                         **kw)


def test_prefix_demote_and_tier_refetch_token_exact(mesh):
    srv = _tiered_prefix_engine(mesh)
    want = _oracle(srv.engine, PREFIX, 3)
    assert srv.generate([PREFIX], max_new_tokens=3)[0] == want
    # Unrelated traffic starves the pool: the cold committed prefix
    # DEMOTES into the host tier instead of dropping.
    for i in range(4):
        srv.generate([[20 + i, 21, 22, 23, 24, 25, 26, 27]],
                     max_new_tokens=3)
    st = srv.stats()
    assert st["pool"]["demotions"] >= 1, "eviction dropped, not demoted"
    assert st["tier_pages"] >= 1
    # The same prefix returns: its pages prefetch back from the tier
    # (tier_hits), the chunk stream skips them, tokens stay exact.
    assert srv.generate([PREFIX], max_new_tokens=3)[0] == want
    st = srv.stats()
    assert st["tier_hits"] >= 1 and st["prefetched_pages"] >= 1
    # Promotion popped the tier entries — exactly one authoritative
    # tier per page, checkable.
    chaos.check_invariants(srv)
    assert srv.decode_cache_size() == 1
    assert srv.prefill_cache_size() <= 2


def test_demoted_prefix_under_live_sharer_not_corrupted(mesh):
    srv = _tiered_prefix_engine(mesh)
    want6 = _oracle(srv.engine, PREFIX, 6)
    # a holds the shared prefix pages LIVE while the pool starves:
    # eviction must never pick (or demote) its pages.
    a = srv.submit(PREFIX, max_new_tokens=6)
    for _ in range(4):
        srv.step()
    assert a.status == "running"
    with pytest.raises(OutOfPagesError):
        # a's live pages (prefix ones included) are not evictable, so
        # a near-pool-sized ask must starve instead of demoting them.
        srv.manager.alloc_prefill(63, list(range(30, 62)))
    assert srv.manager.stats["demotions"] == 0
    srv.run()
    assert a.tokens == want6, "live sharer's pages were corrupted"
    # Sharer gone: the prefix CAN now demote (explicit evict — the
    # same path pool pressure takes), and a newcomer refetches the
    # first sharer's exact bytes from the tier.
    assert len(srv.manager.evict(2)) == 2
    assert srv.manager.stats["demotions"] == 2
    tier_hits0 = srv.stats()["tier_hits"]
    assert srv.generate([PREFIX], max_new_tokens=6)[0] == want6
    assert srv.stats()["tier_hits"] >= tier_hits0 + 2


def test_tier_transfer_fault_falls_back_to_recompute(mesh):
    srv = _tiered_prefix_engine(mesh)
    want = _oracle(srv.engine, PREFIX, 3)
    srv.generate([PREFIX], max_new_tokens=3)
    for i in range(4):
        srv.generate([[20 + i, 21, 22, 23, 24, 25, 26, 27]],
                     max_new_tokens=3)
    assert srv.stats()["pool"]["demotions"] >= 1
    # Every tier transfer dropped: the prefetch degrades to a miss and
    # the prompt recomputes — tokens identical, nothing stuck.
    plan = faults.FaultPlan(
        name="drop-all-tier",
        faults=(faults.Fault("fail_call", op="tier_transfer", k=None),))
    with faults.inject(plan):
        assert srv.generate([PREFIX], max_new_tokens=3)[0] == want
    assert srv.stats()["tier_misses"] >= 1
    chaos.check_invariants(srv)


def test_disagg_composes_with_tiers(mesh):
    from triton_dist_tpu.serving import DisaggServingEngine

    eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
    srv = DisaggServingEngine(eng, num_slots=2, page=4, num_pages=10,
                              prefill_buckets=(4, 8),
                              prefix_reuse=True,
                              kv_tiers={"host_pages": 64})
    want = _oracle(eng, PREFIX, 3)
    assert srv.generate([PREFIX], max_new_tokens=3)[0] == want
    for i in range(4):
        srv.generate([[20 + i, 21, 22, 23, 24, 25, 26, 27]],
                     max_new_tokens=3)
    # Decode-pool demotions refetch at HANDOFF time (migration rows
    # skip tier-resident pages like warm prefix hits).
    assert srv.generate([PREFIX], max_new_tokens=3)[0] == want
    st = srv.stats()
    if st["pool"]["demotions"]:
        assert st["tier_hits"] >= 1
    # Park/resume rides the decode side unchanged.
    h = srv.submit([5, 6, 7], max_new_tokens=4)
    while h.status != "running":
        srv.step()
    srv.step()
    srv.park(h)
    srv.resume(h)
    srv.run()
    assert h.tokens == _oracle(eng, [5, 6, 7], 4)
    chaos.check_invariants(srv)


def test_disagg_prefill_worker_consults_tier(mesh):
    """PR 12 known-limit regression: tier-resident leading pages now
    skip recompute on the prefill WORKER too — the staging pool
    scatters them in at chunk-stream start, so the second serve of a
    demoted prefix needs fewer chunk dispatches (and the tier entry
    survives for the decode-side handoff fetch), token-exact."""
    from triton_dist_tpu.serving import DisaggServingEngine

    eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
    srv = DisaggServingEngine(eng, num_slots=2, page=4,
                              prefill_buckets=(4, 8),
                              prefix_reuse=True,
                              kv_tiers={"host_pages": 64})
    prompt = list(range(1, 13))                # three full pages
    want = _oracle(eng, prompt, 4)
    assert srv.generate([prompt], max_new_tokens=4)[0] == want
    chunks_first = srv.stats_counters["prefill_chunks"]
    assert chunks_first == 2                   # cold: bucket 8 + 4
    # Demote the committed prefix out of BOTH pools: the decode side
    # offloads into the tier (on_demote), the worker side just drops.
    srv.manager.evict(len(srv.manager._prefix))
    pw = srv.prefill_worker
    pw.manager.evict(len(pw.manager._prefix))
    assert len(srv.tiers) >= 3
    h = srv.submit(prompt, max_new_tokens=4)
    srv.run()
    assert h.tokens == want
    st = srv.stats()
    assert st["worker_prefetched_pages"] >= 3
    # The chunk stream started PAST the fetched pages: one small tail
    # chunk instead of the cold serve's two.
    assert st["prefill_chunks"] - chunks_first == 1
    assert h.chunks == [(11, 4, 1)]            # start, bucket, valid
    chaos.check_invariants(srv)


def test_router_time_prefetch_warms_admission(mesh):
    """ROADMAP item 4 remainder: tier_prefetch runs the transfer at
    ROUTE time into the warm buffer; the admission-time fetch then
    consumes it without a second tier hop (gets counter flat), still
    token-exact. Without a prefetch the admission path is unchanged."""
    eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
    srv = ServingEngine(eng, num_slots=2, page=4, num_pages=16,
                        prefix_reuse=True, kv_tiers={"host_pages": 64})
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    want = _oracle(eng, prompt, 4)
    assert srv.generate([prompt], max_new_tokens=4)[0] == want
    srv.manager.evict(len(srv.manager._prefix))
    assert srv.tier_prefetch(prompt) == 2
    assert len(srv._tier_warm) == 2
    gets_after_warm = srv.tiers.stats()["gets"]
    assert srv.generate([prompt], max_new_tokens=4)[0] == want
    assert srv.tiers.stats()["gets"] == gets_after_warm, (
        "admission re-transferred despite the route-time warm buffer")
    assert not srv._tier_warm                 # consumed on use
    st = srv.stats()
    assert st["router_prefetched_pages"] == 2
    assert st["tier_hits"] >= 2
    assert srv.decode_cache_size() == 1
    # No-tiers / no-prefix engines: a safe no-op.
    srv2 = ServingEngine(eng, num_slots=2, page=4)
    assert srv2.tier_prefetch(prompt) == 0


# ---------------------------------------------------------------------------
# Telemetry, checkpoint, chaos, and the acceptance trace
# ---------------------------------------------------------------------------

def test_tier_spans_and_latency(engine):
    clock = {"t": 0.0}

    def fake_clock():
        clock["t"] += 0.5
        return clock["t"]

    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32},
                        telemetry="spans", clock=fake_clock)
    h = srv.submit([5, 6, 7], max_new_tokens=5)
    srv.step()
    srv.step()
    srv.park(h)
    srv.resume(h)
    srv.run()
    kinds = [s.kind for s in srv.obs.log.spans()]
    for k in ("park", "kv_offload", "kv_prefetch", "resume"):
        assert k in kinds, f"span kind {k!r} missing from the timeline"
    # The resume span closes at REACTIVATION (requeue -> running), on
    # the injectable clock, and feeds the per-op histogram — the
    # session_resume_ms bench surface.
    ops = srv.stats()["latency"]["ops"]
    for k in ("park", "kv_offload", "kv_prefetch", "resume"):
        assert ops[k]["count"] >= 1 and ops[k]["mean"] > 0
    resume_span = [s for s in srv.obs.log.spans()
                   if s.kind == "resume" and s.t1 is not None][0]
    assert resume_span.duration > 0


def test_checkpoint_restore_with_parked_and_offloaded(mesh, tmp_path):
    from triton_dist_tpu.serving import load_checkpoint, save_checkpoint

    def build():
        return _tiered_prefix_engine(mesh)

    srv = build()
    want_park = _oracle(srv.engine, [5, 6, 7], 6)
    srv.generate([PREFIX], max_new_tokens=3)
    for i in range(4):
        srv.generate([[20 + i, 21, 22, 23, 24, 25, 26, 27]],
                     max_new_tokens=3)
    assert srv.stats()["pool"]["demotions"] >= 1   # offloaded pages
    h = srv.submit([5, 6, 7], max_new_tokens=6)
    while h.status != "running":
        srv.step()
    srv.step()
    srv.park(h)
    path = save_checkpoint(srv.checkpoint(), str(tmp_path / "t.ckpt"))
    srv2 = build()
    revived = srv2.restore(load_checkpoint(path))
    h2 = next(x for x in revived if x.status == "parked")
    # The snapshot carried the tier wholesale: parked payload AND the
    # demoted prefix pages survive the process boundary.
    assert ("session", h2.request.request_id) in srv2.tiers
    assert srv2.stats()["tier_pages"] == srv.stats()["tier_pages"]
    srv2.resume(h2)
    srv2.run()
    assert h2.tokens == want_park
    assert srv2.generate([PREFIX], max_new_tokens=3)[0] == \
        _oracle(srv2.engine, PREFIX, 3)
    chaos.check_invariants(srv2)


def test_restore_tiered_snapshot_needs_tiers(mesh, engine):
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32})
    snap = srv.checkpoint()
    plain = ServingEngine(engine, num_slots=2, page=8)
    with pytest.raises(ValueError, match="mismatch|kv_tiers"):
        plain.restore(snap)


def test_chaos_soak_with_tier_faults_and_parks(mesh):
    from triton_dist_tpu.resilience.policy import RetryPolicy

    def factory():
        eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
        return ServingEngine(eng, num_slots=2, page=4, num_pages=12,
                             prefix_reuse=True,
                             kv_tiers={"host_pages": 64},
                             retry=RetryPolicy(max_attempts=2))

    rep = chaos.run_soak(
        factory, seed=5, ticks=30, n_faults=4,
        kinds=(chaos.DEFAULT_FAULT_KINDS[:6] + chaos.TIER_FAULT_KINDS),
        park_p=0.25)
    # A completed soak already proved tier coherence every tick and
    # token-exactness of every survivor (parked/resumed included).
    assert rep.survived_faults == rep.faults_injected == 4
    assert rep.counters["parks"] >= 1
    assert rep.counters["parks"] == rep.counters["resumes"]


def test_tier_invariant_checker_catches_corruption(engine):
    srv = ServingEngine(engine, num_slots=2, page=8,
                        kv_tiers={"host_pages": 32})
    h = srv.submit([5, 6], max_new_tokens=4)
    srv.step()
    srv.step()
    srv.park(h)
    chaos.check_invariants(srv)
    # Corrupt: drop the parked payload behind the registry's back.
    srv.tiers.pop(("session", h.request.request_id))
    with pytest.raises(chaos.InvariantViolation, match="no tier payload"):
        chaos.check_invariants(srv)


def test_heavy_tail_trace_runs_to_drain(mesh):
    """The acceptance shape, scaled to the CPU battery: a seeded
    multi-turn trace over a 100k-session heavy-tailed id space served
    through an HBM pool sized WELL below the working set — the tier
    keeps it draining, hot-set hit rate and resume latency land as
    real numbers, and a spot-checked session is token-exact."""
    eng = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)
    srv = ServingEngine(eng, num_slots=2, page=4, num_pages=12,
                        prefix_reuse=True, prefill_buckets=(4, 8),
                        kv_tiers={"host_pages": 256})
    events = heavy_tail_trace(24, n_sessions=100_000, vocab=64,
                              seed=7, max_total=20)
    history, done = {}, []
    distinct = {ev["session"] for ev in events}
    assert any(ev["turn"] > 0 for ev in events), \
        "heavy tail produced no session reuse — trace shape broken"
    for ev in events:
        prompt = extend_session(history, ev, max_prompt=12)
        h = srv.submit(prompt, max_new_tokens=ev["gen"])
        srv.run()
        assert h.status == "done", (h.status, h.error)
        extend_session(history, ev, reply=h.tokens)
        done.append((list(prompt), ev["gen"], h))
    st = srv.stats()
    assert st["kv_hot_hit_rate"] is not None
    assert st["pool"]["demotions"] + st["tier_hits"] >= 0  # coherent
    # Spot-check token-exactness on the 3 longest prompts.
    for prompt, gen, h in sorted(done, key=lambda t: -len(t[0]))[:3]:
        assert h.tokens == _oracle(eng, prompt, gen), \
            f"trace request diverged (prompt={prompt})"
    # Park/resume a final session so the resume histogram is non-null
    # (the session_resume_ms bench key reads exactly this).
    h = srv.submit([1, 2, 3], max_new_tokens=5)
    while h.status != "running":
        srv.step()
    srv.step()
    srv.park(h)
    srv.resume(h)
    srv.run()
    assert h.tokens == _oracle(eng, [1, 2, 3], 5)
    assert srv.stats()["latency"]["ops"]["resume"]["count"] >= 1
    assert len(distinct) >= 2
    chaos.check_invariants(srv)
