"""Stress and fault-injection tests.

Reference: ``test/stress/stress_test_ag_gemm.py`` (randomized shapes in
a loop) and the straggler simulation hook
(``kernels/nvidia/allgather_gemm.py:662`` — sleep one rank inside the
kernel to prove the overlap schedule tolerates skew)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import (
    ag_gemm, ag_gemm_ref, create_ag_gemm_context,
    all_gather, all_gather_ref,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def test_stress_ag_gemm_random_shapes(tp8_mesh, tp8_ctx):
    rng = np.random.RandomState(0)
    for trial in range(6):
        m_loc = int(rng.choice([8, 16, 32]))
        k = int(rng.choice([16, 32]))
        n_loc = int(rng.choice([8, 16]))
        m, n_dim = m_loc * 8, n_loc * 8
        a = jax.random.normal(jax.random.PRNGKey(trial), (m, k))
        b = jax.random.normal(jax.random.PRNGKey(100 + trial), (k, n_dim))
        ctx = create_ag_gemm_context(tp8_ctx, block_m=m_loc,
                                     block_n=min(8, n_loc), block_k=16)
        f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
                 (P("tp", None), P(None, "tp")), P(None, "tp"))
        g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
                 (P("tp", None), P(None, "tp")), P(None, "tp"))
        assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4,
                        msg=f"trial {trial} m={m} k={k} n={n_dim}")


def test_straggler_does_not_corrupt(tp8_mesh, tp8_ctx):
    """One delayed rank must not change the result — the per-step
    semaphore protocol tolerates arbitrary skew."""
    a = jax.random.normal(jax.random.PRNGKey(1), (256, 32))
    b = jax.random.normal(jax.random.PRNGKey(2), (32, 64))
    ctx = create_ag_gemm_context(tp8_ctx, block_m=32, block_n=8,
                                 straggler_rank=3,
                                 straggler_delay_iters=20_000)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_stress_ep_dispatch_random_skew(tp8_mesh, tp8_ctx):
    """Randomized skewed routing through drop-free dispatch/combine
    (reference stress pattern extended per-family, VERDICT r3 weak #5):
    each trial draws a different concentration — from uniform to
    near-one-expert-takes-all — and the identity-expert roundtrip must
    hold exactly."""
    from triton_dist_tpu.ops.ep_a2a import (
        create_ep_context, ep_dispatch, ep_combine,
    )

    T, d, E, K = 8, 16, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, axis="tp")
    rng = np.random.RandomState(7)
    for trial in range(5):
        tokens = jax.random.normal(jax.random.PRNGKey(trial), (8 * T, d))
        conc = [50.0, 5.0, 1.0, 0.2, 0.05][trial]  # uniform → spiky
        probs = rng.dirichlet([conc] * E)
        ids = jnp.asarray(
            rng.choice(E, size=(8 * T, K), p=probs), jnp.int32)
        w = jax.nn.softmax(jax.random.normal(
            jax.random.PRNGKey(100 + trial), (8 * T, K)), axis=-1)

        def run(tok, ids_, w_):
            recv, rexp, state = ep_dispatch(tok, ids_, ctx)
            return ep_combine(recv, state, w_, ctx)

        f = spmd(tp8_mesh, run,
                 (P("tp", None), P("tp", None), P("tp", None)),
                 P("tp", None))
        out = f(tokens, ids, w)
        expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
        assert_allclose(out, expected, rtol=1e-5, atol=1e-5,
                        msg=f"trial {trial} conc={conc}")


def test_stress_ep_fused_capacity_edges(tp8_mesh, tp8_ctx):
    """Mega-EP fused pipeline at capacity edges: random routing against
    capacity 1 (heavy drops), exact fit, and ample headroom. Drops must
    be counted, never corrupt (output finite, ample == dense oracle)."""
    from triton_dist_tpu.layers import ep_moe
    from triton_dist_tpu.ops.ep_fused import create_ep_fused_context
    from triton_dist_tpu.ops.ep_a2a import ep_moe_ref

    T, D, F, E, K, N = 4, 16, 16, 8, 2, 8
    cfg_params = ep_moe.init(
        jax.random.PRNGKey(11),
        type("C", (), {"hidden_size": D, "moe_intermediate_size": F,
                       "num_experts": E})())
    tokens = jax.random.normal(jax.random.PRNGKey(12), (N * T, D))
    for cap in (1, T * K, 4 * T * K):
        ctx = create_ep_fused_context(tp8_ctx, num_experts=E, topk=K,
                                      capacity_per_expert=cap, axis="tp",
                                      block_f=F, block_d=D)

        def run(p, t):
            out, dropped = ep_moe.fwd_fused(p, t, ctx, topk=K)
            return out, dropped[None]

        f = spmd(tp8_mesh, run,
                 (ep_moe.param_specs("tp"), P("tp", None)),
                 (P("tp", None), P("tp")))
        out, dropped = f(cfg_params, tokens)
        out = np.asarray(out, np.float32)
        assert np.isfinite(out).all(), f"cap={cap} produced non-finite"
        n_drop = int(np.asarray(dropped).sum())
        if cap >= T * K:
            assert n_drop == 0, (cap, n_drop)
            ids, w = ep_moe.route(cfg_params["router"], tokens, K)
            expected = ep_moe_ref(
                tokens, ids, w,
                lambda tok, e: (jax.nn.silu(
                    tok @ cfg_params["w_gate"][e])
                    * (tok @ cfg_params["w_up"][e])
                    ) @ cfg_params["w_down"][e], E)
            assert_allclose(out, np.asarray(expected), rtol=1e-4,
                            atol=1e-4, msg=f"cap={cap}")
        else:
            assert n_drop > 0  # capacity 1 with K=2 must overflow


def test_stress_ulysses_fused_random_shapes(tp8_mesh, tp8_ctx):
    """Randomized shapes through the fused QKV-projection A2A."""
    from triton_dist_tpu.ops import (
        create_ulysses_fused_context, qkv_gemm_a2a,
    )

    rng = np.random.RandomState(3)
    N = 8
    for trial in range(3):
        s_loc = int(rng.choice([4, 8]))
        d = int(rng.choice([16, 32]))
        cols = int(rng.choice([8, 16]))
        ctx = create_ulysses_fused_context(tp8_ctx, axis="tp",
                                           block_m=4, block_n=4)
        x = jax.random.normal(jax.random.PRNGKey(trial), (N * s_loc, d))
        w = jax.random.normal(jax.random.PRNGKey(50 + trial),
                              (N, d, cols)) * d ** -0.5

        def per_rank(xs, ws):
            me = jax.lax.axis_index("tp")
            out = qkv_gemm_a2a(xs, ws, ctx)
            return out[None]

        f = spmd(tp8_mesh, per_rank,
                 (P("tp", None), P(None, None, None)),
                 P("tp", None, None, None))
        got = np.asarray(f(x, w))       # (N, n_src, s_loc, cols)
        xs = np.asarray(x).reshape(N, s_loc, d)
        wn = np.asarray(w)
        for me in range(N):
            want = np.einsum("nsd,dc->nsc", xs, wn[me])
            np.testing.assert_allclose(
                got[me], want, rtol=2e-4, atol=2e-4,
                err_msg=f"trial {trial} s={s_loc} d={d} c={cols} me={me}")


def test_stress_a2a_gemm_random_shapes(tp8_mesh, tp8_ctx):
    """Randomized shapes through the fused A2A+GEMM."""
    from triton_dist_tpu.ops import a2a_gemm_fused, create_a2a_gemm_context

    rng = np.random.RandomState(5)
    for trial in range(3):
        s = int(rng.choice([8, 16]))
        d = int(rng.choice([32, 64]))
        n_out = int(rng.choice([16, 32]))
        ctx = create_a2a_gemm_context(tp8_ctx, "tp", block_m=8,
                                      block_n=8, block_k=16)
        x = jax.random.normal(jax.random.PRNGKey(trial), (8, s, d))
        w = jax.random.normal(jax.random.PRNGKey(60 + trial),
                              (d, n_out)) * d ** -0.5
        f = spmd(tp8_mesh,
                 lambda v, ww: a2a_gemm_fused(v, ww, ctx),
                 (P(None, "tp", None), P(None, None)), P("tp", None))
        got = np.asarray(f(x, w), np.float32)
        want = (np.asarray(x, np.float32).reshape(8 * s, d)
                @ np.asarray(w, np.float32))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4,
                                   err_msg=f"trial {trial}")


def test_gemm_rs_tuned_prunes_and_matches(tp8_mesh, tp8_ctx):
    """The perf-model-pruned gemm_rs sweep vetoes VMEM-infeasible
    configs without compiling them and still matches the oracle."""
    from triton_dist_tpu.ops import gemm_rs_tuned, gemm_rs_ref

    a = jax.random.normal(jax.random.PRNGKey(21), (256, 64))
    b = jax.random.normal(jax.random.PRNGKey(22), (64, 32))
    configs = [
        {"block_m": 16, "block_n": 8, "block_k": 32},
        # Modeled VMEM far over budget → vetoed before compile.
        {"block_m": 8192, "block_n": 8192, "block_k": 8192},
    ]
    f = spmd(tp8_mesh,
             lambda x, w: gemm_rs_tuned(x, w, tp8_ctx, configs=configs),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    g = spmd(tp8_mesh, lambda x, w: gemm_rs_ref(x, w),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_stress_all_gather_repeat(tp8_mesh, tp8_ctx):
    """Repeated invocations of the same traced collective stay stable
    (semaphores fully drained between runs)."""
    x = jax.random.normal(jax.random.PRNGKey(3), (64, 64))
    f = spmd(tp8_mesh, lambda v: all_gather(v, ctx=tp8_ctx),
             P("tp", None), P(None, None))
    expected = np.asarray(
        spmd(tp8_mesh, lambda v: all_gather_ref(v), P("tp", None),
             P(None, None))(x))
    for _ in range(5):
        assert_allclose(f(x), expected)
