"""Stress and fault-injection tests.

Reference: ``test/stress/stress_test_ag_gemm.py`` (randomized shapes in
a loop) and the straggler simulation hook
(``kernels/nvidia/allgather_gemm.py:662`` — sleep one rank inside the
kernel to prove the overlap schedule tolerates skew)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import (
    ag_gemm, ag_gemm_ref, create_ag_gemm_context,
    all_gather, all_gather_ref,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def test_stress_ag_gemm_random_shapes(tp8_mesh, tp8_ctx):
    rng = np.random.RandomState(0)
    for trial in range(6):
        m_loc = int(rng.choice([8, 16, 32]))
        k = int(rng.choice([16, 32]))
        n_loc = int(rng.choice([8, 16]))
        m, n_dim = m_loc * 8, n_loc * 8
        a = jax.random.normal(jax.random.PRNGKey(trial), (m, k))
        b = jax.random.normal(jax.random.PRNGKey(100 + trial), (k, n_dim))
        ctx = create_ag_gemm_context(tp8_ctx, block_m=m_loc,
                                     block_n=min(8, n_loc), block_k=16)
        f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
                 (P("tp", None), P(None, "tp")), P(None, "tp"))
        g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
                 (P("tp", None), P(None, "tp")), P(None, "tp"))
        assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4,
                        msg=f"trial {trial} m={m} k={k} n={n_dim}")


def test_straggler_does_not_corrupt(tp8_mesh, tp8_ctx):
    """One delayed rank must not change the result — the per-step
    semaphore protocol tolerates arbitrary skew."""
    a = jax.random.normal(jax.random.PRNGKey(1), (256, 32))
    b = jax.random.normal(jax.random.PRNGKey(2), (32, 64))
    ctx = create_ag_gemm_context(tp8_ctx, block_m=32, block_n=8,
                                 straggler_rank=3,
                                 straggler_delay_iters=20_000)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_stress_all_gather_repeat(tp8_mesh, tp8_ctx):
    """Repeated invocations of the same traced collective stay stable
    (semaphores fully drained between runs)."""
    x = jax.random.normal(jax.random.PRNGKey(3), (64, 64))
    f = spmd(tp8_mesh, lambda v: all_gather(v, ctx=tp8_ctx),
             P("tp", None), P(None, None))
    expected = np.asarray(
        spmd(tp8_mesh, lambda v: all_gather_ref(v), P("tp", None),
             P(None, None))(x))
    for _ in range(5):
        assert_allclose(f(x), expected)
