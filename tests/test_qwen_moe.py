"""Qwen3-MoE model: TP-MoE vs EP-MoE forward cross-check (same math,
different parallelization — the reference's TP_MoE / EP_MoE pair)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models import qwen_moe
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.ops.ep_a2a import create_ep_context
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def test_moe_model_tp_vs_ep(tp8_mesh, tp8_ctx):
    cfg = ModelConfig.tiny_moe()
    params = qwen_moe.init_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    # Capacity sized to keep pallas buffers under the interpret-mode
    # 64 KB/device limit: (8, 16, 32) f32 = 16 KB.
    ep_ctx = create_ep_context(tp8_ctx, num_experts=cfg.num_experts,
                               topk=cfg.num_experts_per_tok,
                               capacity=16, axis="tp")

    f_tp = spmd(tp8_mesh,
                lambda p, i: qwen_moe.forward_tokens(p, i, cfg,
                                                     moe_impl="tp"),
                (qwen_moe.param_specs(cfg, moe_impl="tp"), P(None, None)),
                P(None, None, None))
    f_ep = spmd(tp8_mesh,
                lambda p, i: qwen_moe.forward_tokens(p, i, cfg,
                                                     moe_impl="ep",
                                                     ep_ctx=ep_ctx),
                (qwen_moe.param_specs(cfg, moe_impl="ep", ep_axis="tp"),
                 P(None, None)),
                P(None, None, None))
    logits_tp = f_tp(params, ids)
    logits_ep = f_ep(params, ids)
    assert logits_tp.shape == (2, 32, cfg.vocab_size)
    assert_allclose(logits_ep, logits_tp, rtol=2e-3, atol=2e-3)


def test_engine_serves_ep_moe(tp8_mesh, tp8_ctx):
    """Engine(model=qwen_moe, moe_impl="ep") must build its own
    EPContext and serve end-to-end (VERDICT r3 weak #7: the Engine
    hard-coded dense contexts and could not reach the EP regime).
    Greedy tokens must match the TP-regime serve on the same params."""
    from triton_dist_tpu.models import Engine

    cfg = ModelConfig.tiny_moe(num_experts=8)
    params = qwen_moe.init_params(jax.random.PRNGKey(4), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0,
                             cfg.vocab_size)

    eng_ep = Engine(cfg, tp8_mesh, mode="xla", max_len=64,
                    model=qwen_moe, moe_impl="ep", params=params)
    toks_ep = np.asarray(eng_ep.serve(ids, gen_len=4))

    # Default regime: no moe_impl → Engine must infer the MoE contract
    # (TP experts) instead of crashing on param_specs' signature.
    eng_tp = Engine(cfg, tp8_mesh, mode="xla", max_len=64,
                    model=qwen_moe, params=params)
    toks_tp = np.asarray(eng_tp.serve(ids, gen_len=4))

    assert toks_ep.shape == (2, 4)
    np.testing.assert_array_equal(toks_ep, toks_tp)


def test_engine_serves_ep_moe_2d(dp2tp4_mesh, dp2tp4_ctx):
    """Engine with ep_axis=(outer, inner) builds the hierarchical
    EP2DContext: experts shard over both axes, dispatch hops ICI first
    then one aggregated DCN exchange; attention stays TP on the inner
    axis. Tokens must match a TP-regime serve on the inner axis."""
    from triton_dist_tpu.models import Engine

    cfg = ModelConfig.tiny_moe(num_experts=8)
    params = qwen_moe.init_params(jax.random.PRNGKey(8), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(9), (2, 16), 0,
                             cfg.vocab_size)

    eng_2d = Engine(cfg, dp2tp4_mesh, axis="tp", mode="xla", max_len=64,
                    model=qwen_moe, moe_impl="ep", ep_axis=("dp", "tp"),
                    params=params)
    toks_2d = np.asarray(eng_2d.serve(ids, gen_len=4))

    eng_tp = Engine(cfg, dp2tp4_mesh, axis="tp", mode="xla", max_len=64,
                    model=qwen_moe, moe_impl="tp", params=params)
    toks_tp = np.asarray(eng_tp.serve(ids, gen_len=4))
    np.testing.assert_array_equal(toks_2d, toks_tp)


def test_ep_moe_decode_vs_dispatch(tp8_mesh, tp8_ctx):
    """ep_moe.fwd_decode (masked-local-experts + psum, the small-batch
    decode regime) must equal the dispatch/combine path on the same
    tokens."""
    from triton_dist_tpu.layers import ep_moe

    cfg = ModelConfig.tiny_moe(num_experts=8)
    params = ep_moe.init(jax.random.PRNGKey(6), cfg)
    x = jax.random.normal(jax.random.PRNGKey(7), (8, cfg.hidden_size))
    ep_ctx = create_ep_context(tp8_ctx, num_experts=cfg.num_experts,
                               topk=cfg.num_experts_per_tok, axis="tp")

    specs = ep_moe.param_specs("tp")
    dec = spmd(tp8_mesh,
               lambda p, v: ep_moe.fwd_decode(
                   p, v, topk=cfg.num_experts_per_tok, axis="tp"),
               (specs, P(None, None)), P(None, None))(params, x)
    # Dispatch path consumes token-sharded input; shard then gather.
    disp = spmd(tp8_mesh,
                lambda p, v: jax.lax.all_gather(
                    ep_moe.fwd(p, v, ep_ctx,
                               topk=cfg.num_experts_per_tok),
                    "tp", axis=0, tiled=True),
                (specs, P("tp", None)), P(None, None))(params, x)
    assert_allclose(dec, disp, rtol=2e-3, atol=2e-3)


def test_moe_model_fused_vs_xla(tp8_mesh, tp8_ctx):
    """mode="fused" (fused attention GEMMs + fully-fused TP-MoE blocks)
    matches the XLA-collective forward token-for-token."""
    from triton_dist_tpu.models.dense import make_fwd_contexts

    # 8 experts keeps the AG-MoE ring workspace (E·block_m-bounded) well
    # under the interpret harness's ~96 KB starvation ceiling.
    cfg = ModelConfig.tiny_moe(num_experts=8)
    params = qwen_moe.init_params(jax.random.PRNGKey(2), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0,
                             cfg.vocab_size)
    ctxs = make_fwd_contexts(tp8_ctx, "tp", block_m=8, block_n=8,
                             block_k=32)

    def run(mode):
        return spmd(
            tp8_mesh,
            lambda p, i: qwen_moe.forward_tokens(
                p, i, cfg, moe_impl="tp", mode=mode, ctxs=ctxs,
                # block_m=4 keeps the AG-MoE ring workspace under the
                # interpret harness's ~96 KB buffer ceiling.
                moe_block_m=4),
            (qwen_moe.param_specs(cfg, moe_impl="tp"), P(None, None)),
            P(None, None, None))(params, ids)

    assert_allclose(run("fused"), run("xla"), rtol=2e-3, atol=2e-3)
