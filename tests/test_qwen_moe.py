"""Qwen3-MoE model: TP-MoE vs EP-MoE forward cross-check (same math,
different parallelization — the reference's TP_MoE / EP_MoE pair)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models import qwen_moe
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.ops.ep_a2a import create_ep_context
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def test_moe_model_tp_vs_ep(tp8_mesh, tp8_ctx):
    cfg = ModelConfig.tiny_moe()
    params = qwen_moe.init_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    # Capacity sized to keep pallas buffers under the interpret-mode
    # 64 KB/device limit: (8, 16, 32) f32 = 16 KB.
    ep_ctx = create_ep_context(tp8_ctx, num_experts=cfg.num_experts,
                               topk=cfg.num_experts_per_tok,
                               capacity=16, axis="tp")

    f_tp = spmd(tp8_mesh,
                lambda p, i: qwen_moe.forward_tokens(p, i, cfg,
                                                     moe_impl="tp"),
                (qwen_moe.param_specs(cfg, moe_impl="tp"), P(None, None)),
                P(None, None, None))
    f_ep = spmd(tp8_mesh,
                lambda p, i: qwen_moe.forward_tokens(p, i, cfg,
                                                     moe_impl="ep",
                                                     ep_ctx=ep_ctx),
                (qwen_moe.param_specs(cfg, moe_impl="ep", ep_axis="tp"),
                 P(None, None)),
                P(None, None, None))
    logits_tp = f_tp(params, ids)
    logits_ep = f_ep(params, ids)
    assert logits_tp.shape == (2, 32, cfg.vocab_size)
    assert_allclose(logits_ep, logits_tp, rtol=2e-3, atol=2e-3)


def test_moe_model_fused_vs_xla(tp8_mesh, tp8_ctx):
    """mode="fused" (fused attention GEMMs + fully-fused TP-MoE blocks)
    matches the XLA-collective forward token-for-token."""
    from triton_dist_tpu.models.dense import make_fwd_contexts

    # 8 experts keeps the AG-MoE ring workspace (E·block_m-bounded) well
    # under the interpret harness's ~96 KB starvation ceiling.
    cfg = ModelConfig.tiny_moe(num_experts=8)
    params = qwen_moe.init_params(jax.random.PRNGKey(2), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0,
                             cfg.vocab_size)
    ctxs = make_fwd_contexts(tp8_ctx, "tp", block_m=8, block_n=8,
                             block_k=32)

    def run(mode):
        return spmd(
            tp8_mesh,
            lambda p, i: qwen_moe.forward_tokens(
                p, i, cfg, moe_impl="tp", mode=mode, ctxs=ctxs,
                # block_m=4 keeps the AG-MoE ring workspace under the
                # interpret harness's ~96 KB buffer ceiling.
                moe_block_m=4),
            (qwen_moe.param_specs(cfg, moe_impl="tp"), P(None, None)),
            P(None, None, None))(params, ids)

    assert_allclose(run("fused"), run("xla"), rtol=2e-3, atol=2e-3)
