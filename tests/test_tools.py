"""AOT compile/load and perf-model tests."""

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.tools import (
    compile_aot, load_aot, gemm_time_s, collective_time_s,
    ChipSpec,
)
from triton_dist_tpu.tools.perf_model import overlap_efficiency_bound


def test_aot_roundtrip(tmp_path):
    def fn(x, y):
        return jnp.dot(x, y, preferred_element_type=jnp.float32)

    x = jnp.ones((16, 32))
    y = jnp.ones((32, 8))
    path = compile_aot(fn, (x, y), str(tmp_path / "fn.bin"))
    exe = load_aot(path)
    out = exe(x, y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(fn(x, y)))


def test_perf_model_sanity():
    # Bigger GEMMs take longer; memory-bound for skinny shapes.
    assert gemm_time_s(4096, 4096, 4096) > gemm_time_s(1024, 1024, 1024)
    assert collective_time_s(1 << 26, 8) > collective_time_s(1 << 20, 8)
    assert collective_time_s(1 << 20, 8, kind="all_reduce") > \
        collective_time_s(1 << 20, 8, kind="all_gather")
    # Overlap bound in (0, 1]; big compute → full hiding.
    b = overlap_efficiency_bound(8192, 8192, 8192, 8)
    assert 0.0 < b <= 1.0
