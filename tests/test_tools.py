"""AOT compile/load and perf-model tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.tools import (
    compile_aot, load_aot, gemm_time_s, collective_time_s,
    ChipSpec,
)
from triton_dist_tpu.tools.perf_model import overlap_efficiency_bound


def test_aot_roundtrip(tmp_path):
    def fn(x, y):
        return jnp.dot(x, y, preferred_element_type=jnp.float32)

    x = jnp.ones((16, 32))
    y = jnp.ones((32, 8))
    path = compile_aot(fn, (x, y), str(tmp_path / "fn.bin"))
    exe = load_aot(path)
    out = exe(x, y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(fn(x, y)))


def test_perf_model_sanity():
    # Bigger GEMMs take longer; memory-bound for skinny shapes.
    assert gemm_time_s(4096, 4096, 4096) > gemm_time_s(1024, 1024, 1024)
    assert collective_time_s(1 << 26, 8) > collective_time_s(1 << 20, 8)
    assert collective_time_s(1 << 20, 8, kind="all_reduce") > \
        collective_time_s(1 << 20, 8, kind="all_gather")
    # Overlap bound in (0, 1]; big compute → full hiding.
    b = overlap_efficiency_bound(8192, 8192, 8192, 8)
    assert 0.0 < b <= 1.0


def test_jaxpr_flops_counts_dots_through_structure():
    """The synthetic flops table (CPU cost_analysis fallback): exact
    2*m*k*n per dot_general, scan bodies multiplied by trip count,
    cond branches maxed, nested jit recursed."""
    from triton_dist_tpu.tools.perf_model import jaxpr_flops

    a = jnp.ones((16, 32))
    b = jnp.ones((32, 8))

    plain = jax.make_jaxpr(lambda x, y: x @ y)(a, b)
    assert jaxpr_flops(plain) == 2.0 * 16 * 32 * 8

    def scanned(x, y):
        def body(c, _):
            return c, x @ y
        return jax.lax.scan(body, 0.0, None, length=5)[1]

    assert jaxpr_flops(jax.make_jaxpr(scanned)(a, b)) == 5 * 2.0 * 16 * 32 * 8

    def branched(p, x, y):
        return jax.lax.cond(p, lambda: (x @ y).sum(),
                            lambda: jnp.float32(0.0))

    # max over branches: the dot branch dominates the scalar one.
    assert (jaxpr_flops(jax.make_jaxpr(branched)(True, a, b))
            == 2.0 * 16 * 32 * 8)

    nested = jax.make_jaxpr(jax.jit(lambda x, y: x @ y))(a, b)
    assert jaxpr_flops(nested) == 2.0 * 16 * 32 * 8


# ---------------------------------------------------------------------------
# Topology introspection (tools/topology.py)
# ---------------------------------------------------------------------------

class _FakeDev:
    """Stub with the TPU device attribute surface."""

    def __init__(self, id, coords, slice_index=0, kind="TPU v5p",
                 process_index=0):
        self.id = id
        self.coords = coords
        self.slice_index = slice_index
        self.device_kind = kind
        self.platform = "tpu"
        self.process_index = process_index
        self.core_on_chip = 0


def test_topology_torus_hops_and_neighbors():
    from triton_dist_tpu.tools import topology as T

    # 4x2 torus, one slice.
    devs = [_FakeDev(i, (i % 4, i // 4)) for i in range(8)]
    mat = T.link_matrix(devs)
    assert mat[0][0] == 0
    assert mat[0][1] == 1           # +x neighbour
    assert mat[0][3] == 1           # x wraps: 0 -> 3 is one hop
    assert mat[0][4] == 1           # +y neighbour (y=2: no wrap gain)
    assert mat[0][7] == 2           # (3,1): wrap x (1) + y (1)
    nb = T.neighbors(devs)
    assert set(nb[0]) == {1, 3, 4}  # 2-long y axis: single y link

    dims = T.torus_dims(T.describe_devices(devs))
    assert dims == (4, 2)


def test_topology_slices_and_chip():
    from triton_dist_tpu.tools import topology as T
    from triton_dist_tpu.tools.perf_model import V5E

    devs = ([_FakeDev(i, (i, 0), slice_index=0) for i in range(4)]
            + [_FakeDev(4 + i, (i, 0), slice_index=1) for i in range(4)])
    groups = T.slice_groups(devs)
    assert groups == {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
    # Cross-slice pairs ride DCN: hop distance is None.
    mat = T.link_matrix(devs)
    assert mat[0][4] is None and mat[0][1] == 1

    assert T.detect_chip([_FakeDev(0, (0, 0), kind="TPU v5 lite")]) is V5E
    s = T.summary(devs)
    assert s["num_devices"] == 8 and s["torus_dims"] == [4, 1]


def test_topology_cpu_fallback():
    """CPU/interpret devices (no coords) degrade gracefully."""
    from triton_dist_tpu.tools import topology as T

    infos = T.describe_devices(jax.devices()[:2])
    assert all(i.coords is None for i in infos)
    mat = T.link_matrix(jax.devices()[:2])
    assert mat[0][0] == 0 and mat[0][1] == 1
    assert T.summary(jax.devices()[:2])["num_devices"] == 2


def test_aot_cache_manifest(tmp_path):
    """AOT bundle: multiple named kernels, manifest round-trip through
    a FRESH cache object, signature validation on call."""
    import jax.numpy as jnp
    from triton_dist_tpu.tools.aot import AOTCache

    cache = AOTCache(str(tmp_path / "aot"))
    x = jnp.ones((8, 16), jnp.float32)
    y = jnp.ones((16, 4), jnp.float32)
    cache.add("matmul", lambda a, b: a @ b, (x, y))
    cache.add("double", lambda a: a * 2.0, (x,))
    assert cache.names() == ["double", "matmul"]

    fresh = AOTCache(str(tmp_path / "aot"))  # rehydrate from disk only
    out = fresh.call("matmul", x, y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ y))
    np.testing.assert_allclose(np.asarray(fresh.call("double", x)),
                               2.0 * np.asarray(x))

    with pytest.raises(TypeError, match="signature mismatch"):
        fresh.call("matmul", y, x)
    with pytest.raises(KeyError):
        fresh.get("missing")


def test_aot_fused_decode_step(tmp_path):
    """AOT-export the fused split-KV decode step (reference exposes AOT
    host APIs for flash decode, flash_decode.py:763-1095).

    Interpret-mode kernels ride host callbacks, which ``jax.export``
    cannot serialize — so the export uses the REAL Mosaic lowering
    (available without a TPU chip) targeting the tpu platform, and the
    test asserts the serialize→rehydrate round-trip; execution parity
    is covered on the CPU mesh by ``test_sp.py`` and on silicon by the
    bench battery."""
    import jax
    from triton_dist_tpu.ops import sp_flash_decode_fused
    from triton_dist_tpu.utils.distributed import interpret_mode

    b, h, kvh, hd, t = 2, 4, 2, 16, 32
    key = jax.random.PRNGKey(9)
    q = jax.random.normal(key, (b, h, hd), jnp.float32) * 0.4
    k_hm = jax.random.normal(key, (b, kvh, t, hd), jnp.float32) * 0.4
    v_hm = jax.random.normal(jax.random.PRNGKey(10), (b, kvh, t, hd),
                             jnp.float32) * 0.4
    kv_len = jnp.array([t, 11], jnp.int32)

    def step(qq, kc, vc, l):
        return sp_flash_decode_fused(qq, kc, vc, l, ctx=None, axis="sp",
                                     page=8)

    with interpret_mode(False):
        path = compile_aot(step, (q, k_hm, v_hm, kv_len),
                           str(tmp_path / "decode.bin"),
                           platforms=["tpu"])
    exe = load_aot(path)
    assert exe.rehydrated.platforms == ("tpu",)
    assert exe.rehydrated.out_avals[0].shape == (b, h, hd)
