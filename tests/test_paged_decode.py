"""Paged split-KV flash decode tests (kernel form).

Oracle: dense-cache attention (``flash_decode_ref``), the reference's
torch oracle pattern for ``gqa_fwd_batch_decode`` (paged, ragged
lengths, shuffled page tables).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.flash_decode import flash_decode_ref
from triton_dist_tpu.ops.paged_flash_decode import (
    paged_flash_decode, paged_flash_decode_ref,
)
from triton_dist_tpu.utils.testing import spmd

N = 8          # ranks
B = 2          # batch
PAGE = 8       # tokens per page
P_MAX = 2      # pages per (rank, sequence)
KVH = 2        # kv heads
REP = 2        # GQA ratio → H = 4
HD = 8         # head dim
H = KVH * REP
SHARD = PAGE * P_MAX
T = N * SHARD  # global max context


def _build(seed, n_ranks, dense=None):
    """Dense cache + per-rank shuffled page pools covering it."""
    rng = np.random.RandomState(seed)
    if dense is None:
        k_dense = rng.randn(B, T, KVH, HD).astype(np.float32)
        v_dense = rng.randn(B, T, KVH, HD).astype(np.float32)
    else:
        k_dense, v_dense = dense
    num_pages = B * P_MAX
    kp = np.zeros((n_ranks, num_pages, KVH, PAGE, HD), np.float32)
    vp = np.zeros_like(kp)
    tbl = np.zeros((n_ranks, B, P_MAX), np.int32)
    for r in range(n_ranks):
        perm = rng.permutation(num_pages)
        slot = 0
        for b in range(B):
            for p in range(P_MAX):
                pid = perm[slot]; slot += 1
                lo = r * SHARD + p * PAGE
                kp[r, pid] = k_dense[b, lo:lo + PAGE].transpose(1, 0, 2)
                vp[r, pid] = v_dense[b, lo:lo + PAGE].transpose(1, 0, 2)
                tbl[r, b, p] = pid
    return k_dense, v_dense, kp, vp, tbl


def test_paged_decode_single_rank():
    """1 rank: paged kernel == dense oracle on ragged lengths."""
    k_dense, v_dense, kp, vp, tbl = _build(0, 1)
    q = jax.random.normal(jax.random.PRNGKey(1), (B, H, HD))
    kv_len = jnp.array([SHARD - 3, 5], jnp.int32)

    out = jax.jit(lambda *a: paged_flash_decode(*a))(
        q, jnp.asarray(kp[0]), jnp.asarray(vp[0]),
        jnp.asarray(tbl[0]), kv_len)
    want = flash_decode_ref(q, jnp.asarray(k_dense[:, :SHARD]),
                            jnp.asarray(v_dense[:, :SHARD]), kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_paged_decode_8_ranks_ragged(tp8_mesh, tp8_ctx):
    """8 ranks: KV sharded by position; ragged global lengths hit
    different subsets of ranks (some ranks fully masked)."""
    k_dense, v_dense, kp, vp, tbl = _build(2, N)
    q = jax.random.normal(jax.random.PRNGKey(3), (B, H, HD))
    # Batch 0 spans ~6.5 shards; batch 1 only 1.5 (ranks 2..7 masked).
    kv_len = jnp.array([6 * SHARD + 5, SHARD + PAGE - 2], jnp.int32)

    def run(kp_r, vp_r, tbl_r, q_, len_):
        return paged_flash_decode(q_, kp_r[0], vp_r[0], tbl_r[0], len_,
                                  ctx=tp8_ctx, axis="tp")

    f = spmd(tp8_mesh, run,
             (P("tp", None, None, None, None),
              P("tp", None, None, None, None),
              P("tp", None, None), P(None, None, None), P(None)),
             P(None, None, None))
    out = f(jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tbl), q,
            kv_len)
    want = flash_decode_ref(q, jnp.asarray(k_dense),
                            jnp.asarray(v_dense), kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_paged_decode_ragged_final_page():
    """Serving edge: a slot whose length ends mid-page (neither at a
    page boundary nor filling its final table entry)."""
    k_dense, v_dense, kp, vp, tbl = _build(10, 1)
    q = jax.random.normal(jax.random.PRNGKey(11), (B, H, HD))
    # Batch 0: one full page + 3 tokens into the ragged final page;
    # batch 1: 1 token (first page barely started).
    kv_len = jnp.array([PAGE + 3, 1], jnp.int32)
    out = jax.jit(lambda *a: paged_flash_decode(*a))(
        q, jnp.asarray(kp[0]), jnp.asarray(vp[0]),
        jnp.asarray(tbl[0]), kv_len)
    want = flash_decode_ref(q, jnp.asarray(k_dense[:, :SHARD]),
                            jnp.asarray(v_dense[:, :SHARD]), kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_paged_decode_zero_length_slot():
    """A freed/parked batch slot (kv_len 0) must stay finite and not
    perturb live rows — the fixed-shape serving batch's empty lane."""
    k_dense, v_dense, kp, vp, tbl = _build(12, 1)
    q = jax.random.normal(jax.random.PRNGKey(13), (B, H, HD))
    kv_len = jnp.array([0, PAGE + 2], jnp.int32)
    out = np.asarray(jax.jit(lambda *a: paged_flash_decode(*a))(
        q, jnp.asarray(kp[0]), jnp.asarray(vp[0]),
        jnp.asarray(tbl[0]), kv_len))
    assert np.isfinite(out).all(), "parked slot produced non-finite"
    want = flash_decode_ref(q, jnp.asarray(k_dense[:, :SHARD]),
                            jnp.asarray(v_dense[:, :SHARD]), kv_len)
    np.testing.assert_allclose(out[1], np.asarray(want)[1],
                               rtol=2e-4, atol=2e-4)


def test_paged_decode_freed_and_reused_slot():
    """Recycling: free batch 0's pages, hand the SAME pool slots to a
    new sequence (new contents, new table) — results must track only
    the table, with no leakage from the freed request's data."""
    rng = np.random.RandomState(14)
    k_dense, v_dense, kp, vp, tbl = _build(14, 1)
    q = jax.random.normal(jax.random.PRNGKey(15), (B, H, HD))
    kv_len = jnp.array([SHARD - 2, SHARD - 5], jnp.int32)
    f = jax.jit(lambda kp_, vp_, tbl_: paged_flash_decode(
        q, kp_, vp_, tbl_, kv_len))
    o1 = np.asarray(f(jnp.asarray(kp[0]), jnp.asarray(vp[0]),
                      jnp.asarray(tbl[0])))

    # "Free" batch 0's pages and re-fill those pool slots with a new
    # request's KV (batch 0 becomes a fresh sequence in-place).
    k_new = rng.randn(SHARD, KVH, HD).astype(np.float32)
    v_new = rng.randn(SHARD, KVH, HD).astype(np.float32)
    kp2, vp2 = kp.copy(), vp.copy()
    for p in range(P_MAX):
        pid = tbl[0, 0, p]
        kp2[0, pid] = k_new[p * PAGE:(p + 1) * PAGE].transpose(1, 0, 2)
        vp2[0, pid] = v_new[p * PAGE:(p + 1) * PAGE].transpose(1, 0, 2)
    o2 = np.asarray(f(jnp.asarray(kp2[0]), jnp.asarray(vp2[0]),
                      jnp.asarray(tbl[0])))
    want0 = flash_decode_ref(q[0:1], jnp.asarray(k_new[None]),
                             jnp.asarray(v_new[None]), kv_len[0:1])
    np.testing.assert_allclose(o2[0], np.asarray(want0)[0],
                               rtol=2e-4, atol=2e-4)
    # Batch 1 (untouched pages) is bit-identical across the reuse.
    np.testing.assert_array_equal(o1[1], o2[1])


def test_paged_decode_longer_than_table_row_raises():
    """A request longer than one block-table row (kv_len beyond
    p_max·page) must fail loudly, naming the offending slot."""
    _, _, kp, vp, tbl = _build(16, 1)
    q = jax.random.normal(jax.random.PRNGKey(17), (B, H, HD))
    kv_len = jnp.array([SHARD + 1, 3], jnp.int32)
    with pytest.raises(ValueError, match="slot 0.*table row"):
        paged_flash_decode(q, jnp.asarray(kp[0]), jnp.asarray(vp[0]),
                           jnp.asarray(tbl[0]), kv_len)


def test_paged_decode_ref_matches_kernel():
    """The XLA gather oracle (the serving engine's attn_impl='ref')
    agrees with the Pallas kernel on ragged lengths."""
    _, _, kp, vp, tbl = _build(18, 1)
    q = jax.random.normal(jax.random.PRNGKey(19), (B, H, HD))
    kv_len = jnp.array([SHARD - 3, PAGE + 1], jnp.int32)
    out = jax.jit(lambda *a: paged_flash_decode(*a))(
        q, jnp.asarray(kp[0]), jnp.asarray(vp[0]),
        jnp.asarray(tbl[0]), kv_len)
    ref = paged_flash_decode_ref(q, jnp.asarray(kp[0]),
                                 jnp.asarray(vp[0]),
                                 jnp.asarray(tbl[0]), kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# quantized pools (int8/fp8 per-page scales)
# ---------------------------------------------------------------------------

def _quantize_pool(kp, vp, qdtype, qmax):
    """Whole-page max-abs quantization of a (N, KV, page, hd) pool →
    (k_q, v_q, k_scale, v_scale) — the write_prompt blit's math."""
    ks = np.abs(kp).max(axis=(2, 3)) / qmax
    vs = np.abs(vp).max(axis=(2, 3)) / qmax
    ks = np.where(ks > 0, ks, 1.0).astype(np.float32)
    vs = np.where(vs > 0, vs, 1.0).astype(np.float32)
    kq = kp / ks[:, :, None, None]
    vq = vp / vs[:, :, None, None]
    if qdtype == jnp.int8:
        kq, vq = np.round(kq), np.round(vq)
    return (jnp.asarray(kq).astype(qdtype), jnp.asarray(vq).astype(qdtype),
            jnp.asarray(ks), jnp.asarray(vs))


@pytest.mark.parametrize("qdtype,qmax,tol", [
    (jnp.int8, 127.0, 5e-2),
    (jnp.float8_e4m3fn, 448.0, 2e-1),
])
def test_paged_decode_quantized_fused_dequant(qdtype, qmax, tol):
    """int8/fp8 pools through the kernel's FUSED page-prefetch dequant
    == the dequantizing gather oracle (float-exact), and both within
    the quantization tolerance of the fp32 ground truth."""
    k_dense, v_dense, kp, vp, tbl = _build(30, 1)
    kq, vq, ks, vs = _quantize_pool(kp[0], vp[0], qdtype, qmax)
    q = jax.random.normal(jax.random.PRNGKey(31), (B, H, HD))
    kv_len = jnp.array([SHARD - 3, PAGE + 1], jnp.int32)
    out = jax.jit(lambda *a: paged_flash_decode(
        *a, k_scale=ks, v_scale=vs))(q, kq, vq, jnp.asarray(tbl[0]),
                                     kv_len)
    ref = paged_flash_decode_ref(q, kq, vq, jnp.asarray(tbl[0]),
                                 kv_len, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    exact = flash_decode_ref(q, jnp.asarray(k_dense[:, :SHARD]),
                             jnp.asarray(v_dense[:, :SHARD]), kv_len)
    assert np.abs(np.asarray(out) - np.asarray(exact)).max() < tol


def test_quantized_ragged_final_page_scale():
    """A slot ending mid-page: the ragged final page's scale comes
    from its VALID tokens (zero padding never inflates it), so the
    partial page reconstructs as accurately as a full one."""
    from triton_dist_tpu.serving.blocks import PagedKVCache

    rng = np.random.RandomState(40)
    c = PagedKVCache.empty(1, 4, PAGE, KVH, HD, num_slots=1, p_max=2,
                           kv_dtype="int8")
    import dataclasses
    c = dataclasses.replace(
        c, block_table=jnp.asarray([[1, 2]], jnp.int32),
        live=jnp.ones((1,), jnp.int32))
    # 3 tokens of a tiny magnitude — if padding (or stale garbage)
    # leaked into the scale, round(x/scale) would collapse to zero.
    toks = 1e-3 * rng.randn(3, KVH, HD).astype(np.float32)
    for t in range(3):
        c = c.append_decode(0, jnp.asarray(toks[t][None, None]),
                            jnp.asarray(toks[t][None, None]))
        c = c.advance()
    kd, _ = c.dense_layer(0)
    err = np.abs(np.asarray(kd)[0, :3] - toks).max()
    assert err < 1e-3 * 2 / 127, f"ragged-page scale inflated: {err}"


def test_quantized_freed_and_reused_page_fresh_scale():
    """Pool-slot recycling: a page that held LARGE values, freed and
    reused by a small-valued sequence, must re-quantize under a fresh
    scale — no precision inherited from the dead request."""
    from triton_dist_tpu.serving.blocks import PagedKVCache

    rng = np.random.RandomState(41)
    import dataclasses
    c = PagedKVCache.empty(1, 3, PAGE, KVH, HD, num_slots=1, p_max=1,
                           kv_dtype="int8")
    c = dataclasses.replace(
        c, block_table=jnp.asarray([[1]], jnp.int32),
        live=jnp.ones((1,), jnp.int32))
    big = 100.0 * rng.randn(1, 1, KVH, HD).astype(np.float32)
    c = c.append_decode(0, jnp.asarray(big), jnp.asarray(big)).advance()
    big_scale = float(np.asarray(c.k_scale)[0, 1].max())
    # "Free" the slot: lens reset to 0, same pool page reused.
    c = dataclasses.replace(c, lens=jnp.zeros((1,), jnp.int32))
    small = 1e-2 * rng.randn(1, 1, KVH, HD).astype(np.float32)
    c = c.append_decode(0, jnp.asarray(small),
                        jnp.asarray(small)).advance()
    new_scale = float(np.asarray(c.k_scale)[0, 1].max())
    assert new_scale < big_scale / 100, (new_scale, big_scale)
    kd, _ = c.dense_layer(0)
    err = np.abs(np.asarray(kd)[0, 0] - small[0, 0]).max()
    assert err < 1e-2 * 2 / 127, f"stale scale survived reuse: {err}"


def test_quantized_pool_scaleless_reader_fails_loudly():
    """A quantized pool handed to a bf16-era reader (no scales — e.g.
    a prefix page shared across mismatched kv_dtype configs) raises
    instead of attending raw quantized bytes."""
    _, _, kp, vp, tbl = _build(42, 1)
    kq, vq, ks, vs = _quantize_pool(kp[0], vp[0], jnp.int8, 127.0)
    q = jax.random.normal(jax.random.PRNGKey(43), (B, H, HD))
    kv_len = jnp.array([PAGE, 2], jnp.int32)
    with pytest.raises(ValueError, match="QUANTIZED pool"):
        paged_flash_decode(q, kq, vq, jnp.asarray(tbl[0]), kv_len)
    with pytest.raises(ValueError, match="QUANTIZED pool"):
        paged_flash_decode_ref(q, kq, vq, jnp.asarray(tbl[0]), kv_len)
    # And the reverse mismatch: scales with an unquantized pool.
    with pytest.raises(ValueError, match="unquantized"):
        paged_flash_decode(q, jnp.asarray(kp[0]), jnp.asarray(vp[0]),
                           jnp.asarray(tbl[0]), kv_len,
                           k_scale=ks, v_scale=vs)


def test_paged_decode_page_shuffle_invariance():
    """The block table fully decouples pool layout from positions: two
    different pool permutations give identical results."""
    k_dense, v_dense, kp1, vp1, tbl1 = _build(4, 1)
    # Pool 2: same dense cache, different page permutation.
    _, _, kp2, vp2, tbl2 = _build(5, 1, dense=(k_dense, v_dense))
    q = jax.random.normal(jax.random.PRNGKey(6), (B, H, HD))
    kv_len = jnp.array([SHARD, SHARD - 7], jnp.int32)
    f = jax.jit(lambda kp, vp, tbl: paged_flash_decode(
        q, kp, vp, tbl, kv_len))
    o1 = f(jnp.asarray(kp1[0]), jnp.asarray(vp1[0]), jnp.asarray(tbl1[0]))
    o2 = f(jnp.asarray(kp2[0]), jnp.asarray(vp2[0]), jnp.asarray(tbl2[0]))
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-5, atol=1e-5)
