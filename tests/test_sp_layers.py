"""SP/PP layer wrappers vs single-device oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import tp_attn, ulysses_sp, sp_flash_decode
from triton_dist_tpu.layers.pp_comm import pipeline_forward, send_next
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.utils.testing import spmd, assert_allclose

CFG = ModelConfig.tiny()


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def test_ulysses_layer_vs_dense(tp8_mesh, tp8_ctx):
    params = tp_attn.init(jax.random.PRNGKey(0), CFG)
    s = 64
    x = _rand((s, CFG.hidden_size), 1)

    f = spmd(tp8_mesh,
             lambda p, v: ulysses_sp.fwd(p, v, CFG, axis="tp",
                                         ctx=tp8_ctx),
             (ulysses_sp.param_specs(), P("tp", None)), P("tp", None))
    out = f(params, x)

    # Dense oracle: same math on one device (tp=1 semantics).
    hd, h, kvh = CFG.head_dim, CFG.num_attention_heads, \
        CFG.num_key_value_heads
    from triton_dist_tpu.layers.norm import rms_norm
    from triton_dist_tpu.layers.rope import apply_rope, rope_freqs
    q = (x @ params["wq"]).reshape(s, h, hd)
    k = (x @ params["wk"]).reshape(s, kvh, hd)
    v = (x @ params["wv"]).reshape(s, kvh, hd)
    inv = rope_freqs(hd, CFG.rope_theta)
    pos = jnp.arange(s)[None]
    q = apply_rope(rms_norm(q, params["q_norm"], CFG.rms_norm_eps)[None],
                   pos, inv)[0]
    k = apply_rope(rms_norm(k, params["k_norm"], CFG.rms_norm_eps)[None],
                   pos, inv)[0]
    o = tp_attn.sdpa(q[None], k[None], v[None], causal=True)[0]
    expected = o.reshape(s, h * hd) @ params["wo"]
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)

    # The fused path (QKV-GEMM+A2A / O-A2A+GEMM kernels) must match the
    # same oracle — the layer switch for ops/ulysses_fused.
    g = spmd(tp8_mesh,
             lambda p, v: ulysses_sp.fwd(p, v, CFG, axis="tp",
                                         ctx=tp8_ctx, impl="fused"),
             (ulysses_sp.param_specs(), P("tp", None)), P("tp", None))
    assert_allclose(g(params, x), expected, rtol=1e-4, atol=1e-4)


def test_sp_flash_decode_layer(tp8_mesh, tp8_ctx):
    params = tp_attn.init(jax.random.PRNGKey(2), CFG)
    b, t_loc = 2, 8  # global cache = 64 slots
    kvh, hd = CFG.num_key_value_heads, CFG.head_dim
    x = _rand((b, CFG.hidden_size), 3)
    k_cache = _rand((b, 8 * t_loc, kvh, hd), 4)
    v_cache = _rand((b, 8 * t_loc, kvh, hd), 5)
    cache_len = jnp.asarray(37, jnp.int32)

    f = spmd(tp8_mesh,
             lambda p, xx, kc, vc: sp_flash_decode.fwd(
                 p, xx, CFG, kc, vc, cache_len, axis="tp"),
             (ulysses_sp.param_specs(), P(None, None),
              P(None, "tp", None, None), P(None, "tp", None, None)),
             (P(None, None), (P(None, "tp", None, None),
                              P(None, "tp", None, None))))
    y, (kc2, vc2) = f(params, x, k_cache, v_cache)

    # Oracle: single-device same computation on the full cache.
    from triton_dist_tpu.layers.norm import rms_norm
    from triton_dist_tpu.layers.rope import apply_rope, rope_freqs
    from triton_dist_tpu.ops.flash_decode import flash_decode_ref
    h = CFG.num_attention_heads
    q = (x @ params["wq"]).reshape(b, 1, h, hd)
    k = (x @ params["wk"]).reshape(b, 1, kvh, hd)
    v = (x @ params["wv"]).reshape(b, 1, kvh, hd)
    inv = rope_freqs(hd, CFG.rope_theta)
    pos = jnp.full((b, 1), 37, jnp.int32)
    q = apply_rope(rms_norm(q, params["q_norm"], CFG.rms_norm_eps),
                   pos, inv)
    k = apply_rope(rms_norm(k, params["k_norm"], CFG.rms_norm_eps),
                   pos, inv)
    kf = k_cache.at[:, 37:38].set(k)
    vf = v_cache.at[:, 37:38].set(v)
    o = flash_decode_ref(q[:, 0], kf, vf, jnp.full((b,), 38, jnp.int32))
    expected = o.reshape(b, h * hd) @ params["wo"]
    assert_allclose(y, expected, rtol=1e-4, atol=1e-4)
    # Cache updated at global slot 37 only.
    assert_allclose(np.asarray(kc2)[:, 37:38], np.asarray(k))
    assert_allclose(np.asarray(kc2)[:, :37], np.asarray(k_cache)[:, :37])


def test_sp_flash_decode_layer_2d(dp2tp4_mesh, dp2tp4_ctx):
    """The decode layer over a multi-slice (dp x tp) sequence-sharded
    cache: owner-rank append + two-axis LSE combine must match the
    1-axis layout on the same global cache."""
    from triton_dist_tpu.layers import sp_flash_decode as sfd
    from triton_dist_tpu.layers import tp_attn
    from triton_dist_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny()
    b, t = 2, 64
    params = tp_attn.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, cfg.hidden_size))
    kvh, hd = cfg.num_key_value_heads, cfg.head_dim
    k_cache = jax.random.normal(jax.random.PRNGKey(2), (b, t, kvh, hd))
    v_cache = jax.random.normal(jax.random.PRNGKey(3), (b, t, kvh, hd))
    cache_len = jnp.asarray(41, jnp.int32)

    kv2 = P(None, ("dp", "tp"), None, None)
    y2d, _ = spmd(dp2tp4_mesh,
                  lambda p, xx, kc, vc, cl: sfd.fwd(
                      p, xx, cfg, kc, vc, cl, axis=("dp", "tp")),
                  (tp_attn.param_specs(None), P(None, None), kv2, kv2,
                   P()),
                  (P(None, None), (kv2, kv2)))(
        params, x, k_cache, v_cache, cache_len)

    kv1 = P(None, "tp", None, None)
    mesh1d = tp8_mesh_from(dp2tp4_mesh)
    y1d, _ = spmd(mesh1d,
                  lambda p, xx, kc, vc, cl: sfd.fwd(
                      p, xx, cfg, kc, vc, cl, axis="tp"),
                  (tp_attn.param_specs(None), P(None, None), kv1, kv1,
                   P()),
                  (P(None, None), (kv1, kv1)))(
        params, x, k_cache, v_cache, cache_len)
    assert_allclose(y2d, y1d, rtol=1e-4, atol=1e-4)


def tp8_mesh_from(mesh2d):
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.asarray(mesh2d.devices).reshape(-1), ("tp",))


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_pp_send_next(tp8_mesh, tp8_ctx, impl):
    x = _rand((64, 32), 6)
    f = spmd(tp8_mesh,
             lambda v: send_next(v, axis="tp", ctx=tp8_ctx, impl=impl),
             P("tp", None), P("tp", None))
    got = np.asarray(f(x)).reshape(8, 8, 32)
    exp = np.roll(np.asarray(x).reshape(8, 8, 32), 1, axis=0)
    np.testing.assert_allclose(got, exp)


def test_pipeline_forward_relay(tp8_mesh, tp8_ctx):
    """4-stage pipeline over an 8-rank axis folds stage outputs in
    sequence: y = (((x+1)*2)+3)... each stage applies its own affine."""
    x = _rand((8, 32), 7)

    def stage_fn(stage, h):
        return h + float(stage + 1)

    f = spmd(tp8_mesh,
             lambda v: pipeline_forward(stage_fn, v, num_stages=8,
                                        axis="tp"),
             P(None, None), P(None, None))
    out = f(x)
    expected = x + sum(range(1, 9))
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_sp_flash_decode_layer_fused_matches_xla(tp8_mesh, tp8_ctx):
    """fused=True (one-kernel head-major decode) must match the XLA
    composition layer path on the same logical cache."""
    params = tp_attn.init(jax.random.PRNGKey(2), CFG)
    b, t_loc = 2, 8
    kvh, hd = CFG.num_key_value_heads, CFG.head_dim
    x = _rand((b, CFG.hidden_size), 3)
    k_cache = _rand((b, 8 * t_loc, kvh, hd), 4)
    v_cache = _rand((b, 8 * t_loc, kvh, hd), 5)
    cache_len = jnp.asarray(37, jnp.int32)

    f = spmd(tp8_mesh,
             lambda p, xx, kc, vc: sp_flash_decode.fwd(
                 p, xx, CFG, kc, vc, cache_len, axis="tp"),
             (ulysses_sp.param_specs(), P(None, None),
              P(None, "tp", None, None), P(None, "tp", None, None)),
             (P(None, None), (P(None, "tp", None, None),
                              P(None, "tp", None, None))))
    y_ref, (kc_ref, _) = f(params, x, k_cache, v_cache)

    # Same caches in head-major layout through the fused kernel.
    k_hm = jnp.transpose(k_cache, (0, 2, 1, 3))
    v_hm = jnp.transpose(v_cache, (0, 2, 1, 3))
    g = spmd(tp8_mesh,
             lambda p, xx, kc, vc: sp_flash_decode.fwd(
                 p, xx, CFG, kc, vc, cache_len, axis="tp", fused=True,
                 ctx=tp8_ctx, page=8),
             (ulysses_sp.param_specs(), P(None, None),
              P(None, None, "tp", None), P(None, None, "tp", None)),
             (P(None, None), (P(None, None, "tp", None),
                              P(None, None, "tp", None))))
    y_fused, (kc_hm2, _) = g(params, x, k_hm, v_hm)
    assert_allclose(y_fused, y_ref, rtol=2e-4, atol=2e-4)
    # Same cache content in the other layout after the append.
    assert_allclose(jnp.transpose(kc_hm2, (0, 2, 1, 3)), kc_ref,
                    rtol=1e-6, atol=1e-6)
