"""Production-row-count correctness per fused family (VERDICT r3 #3).

The interpret-mode CPU harness starves when a single pallas buffer
exceeds ~64 KB/device (tests/test_fused_gemm.py note), which previously
capped every multi-device test at a few hundred rows — the Mosaic-
relevant failure class this suite targets is INDEX ARITHMETIC at real
row counts (>=2048 rows: multi-chunk ring offsets, tile/expert maps,
page tables), so each family runs TALL-AND-NARROW: real M/S/T, small
d/K, every buffer under the limit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.utils.testing import spmd, assert_allclose


def _rand(shape, seed, dtype=jnp.float32, scale=1.0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype) * scale


def test_real_rows_ag_gemm(tp8_mesh, tp8_ctx):
    """M = 2048 global rows (256/rank, 4 row tiles per ring chunk)."""
    from triton_dist_tpu.ops import (ag_gemm, ag_gemm_ref,
                                     create_ag_gemm_context)

    a = _rand((2048, 8), 0, jnp.bfloat16)
    b = _rand((8, 8), 1, jnp.bfloat16)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=64, block_n=8,
                                 block_k=8)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(jnp.asarray(f(a, b), jnp.float32),
                    jnp.asarray(g(a, b), jnp.float32),
                    rtol=2e-2, atol=2e-2)


def test_real_rows_gemm_rs(tp8_mesh, tp8_ctx):
    """M = 2048 with the ring-accumulate workspace at 256 rows/rank."""
    from triton_dist_tpu.ops import (gemm_rs, gemm_rs_ref,
                                     create_gemm_rs_context)

    a = _rand((2048, 64), 2, jnp.bfloat16, 0.2)
    b = _rand((64, 8), 3, jnp.bfloat16, 0.2)
    ctx = create_gemm_rs_context(tp8_ctx, block_m=64, block_n=8,
                                 block_k=8)
    f = spmd(tp8_mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    g = spmd(tp8_mesh, lambda x, w: gemm_rs_ref(x, w),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    assert_allclose(jnp.asarray(f(a, b), jnp.float32),
                    jnp.asarray(g(a, b), jnp.float32),
                    rtol=2e-2, atol=2e-1)


def test_real_rows_ep_dispatch(tp8_mesh, tp8_ctx):
    """T = 2048 tokens PER RANK (16384 global assignments at K=2)
    through the drop-free exact-splits dispatch/combine."""
    from triton_dist_tpu.ops.ep_a2a import (
        create_ep_context, ep_dispatch, ep_combine,
    )

    T, d, E, K = 2048, 4, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, axis="tp")
    tokens = _rand((8 * T, d), 4)
    ids = jax.random.randint(jax.random.PRNGKey(5), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 6), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, state, w_, ctx)

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)),
             P("tp", None))
    out = f(tokens, ids, w)
    expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_real_rows_ulysses_qkv_a2a(tp8_mesh, tp8_ctx):
    """S = 2048 global sequence rows through the fused QKV A2A."""
    from triton_dist_tpu.ops import (create_ulysses_fused_context,
                                     qkv_gemm_a2a)

    N, s_loc, d, cols = 8, 256, 8, 4
    ctx = create_ulysses_fused_context(tp8_ctx, axis="tp", block_m=32,
                                       block_n=4)
    x = _rand((N * s_loc, d), 7)
    w = _rand((N, d, cols), 8, scale=d ** -0.5)

    f = spmd(tp8_mesh,
             lambda xs, ws: qkv_gemm_a2a(xs, ws, ctx)[None],
             (P("tp", None), P(None, None, None)),
             P("tp", None, None, None))
    got = np.asarray(f(x, w))
    xs = np.asarray(x).reshape(N, s_loc, d)
    for me in range(N):
        want = np.einsum("nsd,dc->nsc", xs, np.asarray(w)[me])
        np.testing.assert_allclose(got[me], want, rtol=2e-4, atol=2e-4)


def test_real_rows_sp_ag_attention_fused(tp8_mesh, tp8_ctx):
    """S = 2048 global sequence through the fused ring-attention
    kernel (8 query tiles x 4 KV tiles per chunk per rank)."""
    from triton_dist_tpu.ops import sp_ag_attention_fused
    from triton_dist_tpu.ops.sp_ag_attention import sp_ag_attention_ref

    s_loc, h, hd = 256, 1, 4
    q = _rand((s_loc * 8, h, hd), 9, scale=0.5)
    k = _rand((s_loc * 8, h, hd), 10, scale=0.5)
    v = _rand((s_loc * 8, h, hd), 11, scale=0.5)
    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_fused(
                 a, b, c, ctx=tp8_ctx, axis="tp", block_q=32,
                 block_kv=64),
             (P("tp", None, None),) * 3, P("tp", None, None))
    g = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_ref(a, b, c, axis="tp"),
             (P("tp", None, None),) * 3, P("tp", None, None))
    assert_allclose(f(q, k, v), g(q, k, v), rtol=1e-4, atol=1e-4)


def test_real_rows_paged_decode():
    """KV length 2000 over a 32-page pool (page 64) — real-scale block
    tables and page-boundary arithmetic."""
    from triton_dist_tpu.ops import paged_flash_decode

    npages, kvh, page, hd, B = 64, 1, 64, 4, 2
    kp = _rand((npages, kvh, page, hd), 12, jnp.bfloat16, 0.3)
    vp = _rand((npages, kvh, page, hd), 13, jnp.bfloat16, 0.3)
    per = npages // B
    tbl = jnp.arange(B * per, dtype=jnp.int32).reshape(B, per)
    kv_len = jnp.array([2000, 1537], jnp.int32)
    q = _rand((B, 4, hd), 14, jnp.bfloat16, 0.3)
    out = jax.jit(lambda q_: paged_flash_decode(
        q_, kp, vp, tbl, kv_len))(q)
    out = np.asarray(out, np.float32)
    assert out.shape == (B, 4, hd) and np.isfinite(out).all()

    # Dense oracle from the same pages.
    kf = np.asarray(kp, np.float32).reshape(npages * page, hd)
    vf = np.asarray(vp, np.float32).reshape(npages * page, hd)
    qf = np.asarray(q, np.float32)
    for b in range(B):
        rows = np.asarray(tbl[b]).reshape(-1)
        kk = kf[np.concatenate([np.arange(p * page, (p + 1) * page)
                                for p in rows])][:int(kv_len[b])]
        vv = vf[np.concatenate([np.arange(p * page, (p + 1) * page)
                                for p in rows])][:int(kv_len[b])]
        s = (qf[b] @ kk.T) / np.sqrt(hd)
        p_ = np.exp(s - s.max(-1, keepdims=True))
        p_ /= p_.sum(-1, keepdims=True)
        np.testing.assert_allclose(out[b], p_ @ vv, rtol=5e-2, atol=5e-2)
