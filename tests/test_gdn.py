"""Gated DeltaNet vs per-step oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.ops.gdn import gdn_fwd, gdn_ref
from triton_dist_tpu.utils.testing import assert_allclose


def test_gdn_scan_matches_loop():
    s, h, dk, dv = 16, 2, 8, 8
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (s, h, dk))
    k = jax.random.normal(ks[1], (s, h, dk))
    v = jax.random.normal(ks[2], (s, h, dv))
    g = -jax.nn.softplus(jax.random.normal(ks[3], (s, h)))
    beta = jax.nn.sigmoid(jax.random.normal(ks[4], (s, h)))
    o, S = gdn_fwd(q, k, v, g, beta)
    o_ref = gdn_ref(q, k, v, g, beta)
    assert_allclose(o, o_ref, rtol=1e-5, atol=1e-5)
    assert S.shape == (h, dk, dv)


@pytest.mark.parametrize("s,chunk", [(32, 8), (37, 8), (16, 64)])
def test_gdn_chunked_matches_scan(s, chunk):
    """Chunked WY-form prefill == the sequential scan (incl. ragged
    tails shorter than a chunk and chunk > sequence)."""
    from triton_dist_tpu.ops.gdn import gdn_fwd, gdn_fwd_chunked

    h, dk, dv = 3, 16, 8
    key = jax.random.PRNGKey(s)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (s, h, dk))
    k = jax.random.normal(ks[1], (s, h, dk))
    v = jax.random.normal(ks[2], (s, h, dv))
    g = -jnp.abs(jax.random.normal(ks[3], (s, h))) * 0.1
    beta = jax.nn.sigmoid(jax.random.normal(ks[4], (s, h)))

    o_scan, S_scan = gdn_fwd(q, k, v, g, beta)
    o_chunk, S_chunk = gdn_fwd_chunked(q, k, v, g, beta, chunk=chunk)
    np.testing.assert_allclose(np.asarray(o_chunk), np.asarray(o_scan),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(S_chunk), np.asarray(S_scan),
                               rtol=2e-4, atol=2e-4)


def test_gdn_chunked_then_decode():
    """Chunked prefill state seeds the decode step seamlessly."""
    from triton_dist_tpu.ops.gdn import (gdn_fwd, gdn_fwd_chunked,
                                         gdn_decode_step)

    s, h, dk, dv = 24, 2, 8, 8
    ks = jax.random.split(jax.random.PRNGKey(9), 7)
    q = jax.random.normal(ks[0], (s + 1, h, dk))
    k = jax.random.normal(ks[1], (s + 1, h, dk))
    v = jax.random.normal(ks[2], (s + 1, h, dv))
    g = -jnp.abs(jax.random.normal(ks[3], (s + 1, h))) * 0.1
    beta = jax.nn.sigmoid(jax.random.normal(ks[4], (s + 1, h)))

    _, S_pre = gdn_fwd_chunked(q[:s], k[:s], v[:s], g[:s], beta[:s],
                               chunk=8)
    o_dec, _ = gdn_decode_step(S_pre, q[s], k[s], v[s], g[s], beta[s])
    o_full, _ = gdn_fwd(q, k, v, g, beta)
    np.testing.assert_allclose(np.asarray(o_dec), np.asarray(o_full[s]),
                               rtol=2e-4, atol=2e-4)
