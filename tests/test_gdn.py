"""Gated DeltaNet vs per-step oracle."""

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.gdn import gdn_fwd, gdn_ref
from triton_dist_tpu.utils.testing import assert_allclose


def test_gdn_scan_matches_loop():
    s, h, dk, dv = 16, 2, 8, 8
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (s, h, dk))
    k = jax.random.normal(ks[1], (s, h, dk))
    v = jax.random.normal(ks[2], (s, h, dv))
    g = -jax.nn.softplus(jax.random.normal(ks[3], (s, h)))
    beta = jax.nn.sigmoid(jax.random.normal(ks[4], (s, h)))
    o, S = gdn_fwd(q, k, v, g, beta)
    o_ref = gdn_ref(q, k, v, g, beta)
    assert_allclose(o, o_ref, rtol=1e-5, atol=1e-5)
    assert S.shape == (h, dk, dv)
