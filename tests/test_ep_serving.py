"""EP serving battery: low-latency decode dispatch + hot-expert
rebalancing (ISSUE 6 / ROADMAP open item 2).

Covers the decode ``transport`` knob (ragged exact-splits vs the
count-free wire-quantized ``ll`` path vs the tune-resolved ``auto``)
under uniform AND adversarially skewed routing, on both serving
backends; hot-expert replication staying token-exact; the on-device
expert-load telemetry; and the dynamic scoreboard's expert-load claim
priority.

Adversarial skew construction: the router has no bias, so "all tokens
to one expert" is forged with a ±pair — column 0 = +g, column 1 = -g,
the rest exactly zero. Every token's top-1 lands on expert 0 or 1 and
the tied-at-zero second pick deterministically on expert 2 (top_k
breaks ties by index) — ALL routed assignments hit ep rank 0's expert
shard (experts 0-3 at TP=2), the hot-rank regime the rebalancer must
react to.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.layers import ep_moe
from triton_dist_tpu.models import Engine, ModelConfig, qwen_moe
from triton_dist_tpu.serving import ServingEngine

TP = 2
CFG = ModelConfig.tiny_moe(num_experts=8)
MAX_LEN = 32
PAGE = 8
VOCAB = CFG.vocab_size
PROMPTS = [[3, 5, 7], [11, 2]]
GEN = 3


def _skewed(params):
    """Force every routed assignment onto ep rank 0's experts (the
    ±pair trick, module docstring): top-1 on expert 0 or 1, the tied
    second pick on expert 2."""
    p = jax.tree.map(lambda x: x, params)
    rng = np.random.RandomState(0)
    for lp in p["layers"]:
        d, e = lp["moe"]["router"].shape
        g = rng.randn(d).astype(np.float32)
        r = np.zeros((d, e), np.float32)
        r[:, 0] = g
        r[:, 1] = -g
        lp["moe"]["router"] = jnp.asarray(r)
    return p


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:TP]), ("tp",))


@pytest.fixture(scope="module")
def engines(mesh):
    """Lazily-built (routing, transport) -> Engine cache: engine
    construction compiles the fused ll kernels, so tests share them."""
    base = qwen_moe.init_params(jax.random.PRNGKey(0), CFG)
    params = {"uniform": base, "skew": _skewed(base)}
    cache = {}

    def get(routing: str, transport: str) -> Engine:
        key = (routing, transport)
        if key not in cache:
            cache[key] = Engine(CFG, mesh, mode="xla", max_len=MAX_LEN,
                                model=qwen_moe, moe_impl="ep",
                                ep_transport=transport,
                                params=params[routing])
        return cache[key]

    return get


def _solo(eng, prompt, gen):
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (TP, 1)))
    return np.asarray(eng.serve(ids, gen_len=gen))[0].tolist()


# ---------------------------------------------------------------------------
# layer engine: transport × routing token-exactness
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("routing", ["uniform", "skew"])
@pytest.mark.parametrize("transport", ["ragged", "ll", "auto"])
def test_layer_transport_token_exact(engines, routing, transport):
    """Continuous-batching decode through each transport matches the
    solo Engine.serve baseline per request, uniform and skewed.
    ``auto`` (untuned cache) resolves to ``ll`` and shares its engine —
    the resolution itself is what's under test."""
    eng = engines(routing, "ll" if transport == "auto" else transport)
    want = [_solo(eng, p, GEN) for p in PROMPTS]
    srv = ServingEngine(eng, num_slots=2, page=PAGE,
                        transport=transport)
    got = srv.generate(PROMPTS, max_new_tokens=GEN)
    assert got == want
    st = srv.stats()
    assert st["dispatch_transport"] == (
        "ll" if transport == "auto" else transport)
    # On-device telemetry: every decode dispatch routed
    # num_slots * topk * n_layers assignments.
    per_step = 2 * CFG.num_experts_per_tok * CFG.num_hidden_layers
    assert sum(st["expert_totals"]) == (
        st["decode_dispatches"] * per_step)
    assert srv.decode_cache_size() <= 2  # PR-4 fixed-shape gate


def test_skew_concentrates_expert_load(engines):
    """The ±pair router sends every top-1 to experts {0, 1}: the load
    EWMA's argmax must sit there, and trace() must record per-step
    histograms whose hot mass dominates."""
    eng = engines("skew", "ll")
    srv = ServingEngine(eng, num_slots=2, page=PAGE)
    with srv.trace("ep-load"):
        srv.generate(PROMPTS, max_new_tokens=GEN)
    st = srv.stats()
    load = np.asarray(st["expert_load"])
    assert int(np.argmax(load)) in (0, 1, 2)
    # EVERY routed assignment hits rank 0's expert shard (0-3).
    tot = np.asarray(st["expert_totals"], np.float64)
    assert tot[:4].sum() == tot.sum() and tot.sum() > 0
    assert len(srv.expert_hist) == st["decode_dispatches"]
    assert all(h.sum() > 0 for h in srv.expert_hist)


def test_ll_replication_token_exact(engines):
    """Hot-expert replication under skew: the rebalancer installs a
    replica on the other rank, routing splits to it (data, no
    recompile), and greedy tokens stay EXACTLY those of the
    replica-free run."""
    eng = engines("skew", "ll")
    plain = ServingEngine(eng, num_slots=2, page=PAGE)
    want = plain.generate(PROMPTS, max_new_tokens=GEN)

    srv = ServingEngine(eng, num_slots=2, page=PAGE, replica_slots=1,
                        rebalance_every=2, hot_expert_factor=1.2)
    srv.generate([[9, 1], [4]], max_new_tokens=3)   # warm the EWMA
    warm = srv.decode_cache_size()
    got = srv.generate(PROMPTS, max_new_tokens=GEN)
    st = srv.stats()
    assert st["replicated_experts"], "skewed load never replicated"
    e, rank = next(iter(st["replicated_experts"].items()))
    assert e in (0, 1, 2) and rank == 1  # hot expert copied off rank 0
    assert got == want
    assert srv.decode_cache_size() == warm, (
        "replica refresh re-specialized the decode dispatch")


def test_replication_requires_ll(engines):
    with pytest.raises(ValueError, match="transport='ll'"):
        ServingEngine(engines("uniform", "ragged"), num_slots=2,
                      page=PAGE, replica_slots=1)


def test_transport_validation(engines):
    with pytest.raises(ValueError, match="not in"):
        ServingEngine(engines("uniform", "ll"), num_slots=2, page=PAGE,
                      transport="bogus")


# ---------------------------------------------------------------------------
# transport autotune store
# ---------------------------------------------------------------------------

def test_auto_transport_tune_roundtrip(mesh, tmp_path, monkeypatch):
    """tune_transport sweeps ragged vs ll, persists a winner, and
    ``transport="auto"`` resolution loads it back."""
    from triton_dist_tpu import tune
    from triton_dist_tpu.ops.ep_a2a import create_ep_context
    from triton_dist_tpu.parallel.mesh import MeshContext

    monkeypatch.setenv("TRITON_DIST_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(tune, "_CACHE", None)
    monkeypatch.setattr(tune, "_CACHE_PATH", None)

    mctx = MeshContext.from_mesh(mesh)
    ctx = create_ep_context(mctx, num_experts=CFG.num_experts,
                            topk=CFG.num_experts_per_tok, axis="tp")
    params = ep_moe.init(jax.random.PRNGKey(1), CFG)
    kw = dict(ctx=ctx, batch=2, hidden=CFG.hidden_size,
              dtype=jnp.float32, topk=CFG.num_experts_per_tok)
    assert ep_moe.resolve_transport("auto", **kw) == "ll"  # untuned
    winner = ep_moe.tune_transport(mesh, params, ctx, batch=2,
                                   topk=CFG.num_experts_per_tok,
                                   reps=1)
    assert winner in ("ragged", "ll")
    assert ep_moe.resolve_transport("auto", **kw) == winner
    # second call is a cache hit (no re-timing)
    assert ep_moe.tune_transport(mesh, params, ctx, batch=2,
                                 topk=CFG.num_experts_per_tok) == winner
    # resolution honors whatever the store says, independent of this
    # host's timing noise (jnp.float32 and np.dtype must key alike).
    forced = "ragged" if winner == "ll" else "ll"
    tune.store_autotune_data(
        ep_moe._transport_key(ctx, batch=2, hidden=CFG.hidden_size,
                              dtype=np.dtype("float32"),
                              topk=CFG.num_experts_per_tok),
        {"transport": forced})
    assert ep_moe.resolve_transport("auto", **kw) == forced


# ---------------------------------------------------------------------------
# megakernel engine: skewed routing + expert-load claim priority
# ---------------------------------------------------------------------------

def _mk_engine(cfg, params=None, **kw):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    return MegaKernelEngine(cfg, mesh1, batch=2, max_len=16, tile_w=16,
                            t_tile=16, params=params, **kw)


@pytest.fixture(scope="module")
def mk_cfg_params():
    cfg = ModelConfig.tiny_moe(vocab_size=128, num_experts=8)
    params = _skewed(qwen_moe.init_params(jax.random.PRNGKey(0), cfg))
    return cfg, params


@pytest.mark.parametrize("transport", ["ragged", "ll"])
def test_megakernel_skew_serving_token_exact(mk_cfg_params, transport):
    """Megakernel serving under adversarial skew: the transport knob is
    accepted (experts are served in-kernel, TP regime — stats say so),
    tokens match solo runs, and the in-kernel router counters surface
    the hot experts."""
    cfg, params = mk_cfg_params

    def solo(prompt):
        e = _mk_engine(cfg, params=params)
        tiled = jnp.asarray(np.tile(np.asarray([prompt], np.int32),
                                    (2, 1)))
        seed = e.prefill_chain(tiled)
        return np.asarray(e.generate(
            seed, steps=GEN, start_pos=len(prompt) - 1))[0].tolist()

    want = [solo(p) for p in PROMPTS]
    mk = _mk_engine(cfg, params=params)
    srv = ServingEngine(mk, transport=transport)
    h = [srv.submit(p, max_new_tokens=GEN) for p in PROMPTS]
    srv.run()
    assert [x.tokens for x in h] == want
    st = srv.stats()
    assert st["dispatch_transport"] == "in-kernel-tp"
    tot = np.asarray(st["expert_totals"], np.float64)
    assert tot.sum() > 0 and tot[:3].sum() == tot.sum()


def test_megakernel_dynamic_rebalance_token_exact(mk_cfg_params):
    """schedule="dynamic" + rebalance: the serving loop feeds the load
    EWMA into the scoreboard (claim tables rebuilt mid-serve) and the
    tokens still match the static-schedule solo baseline."""
    cfg, params = mk_cfg_params

    def solo(prompt):
        e = _mk_engine(cfg, params=params)          # static baseline
        tiled = jnp.asarray(np.tile(np.asarray([prompt], np.int32),
                                    (2, 1)))
        seed = e.prefill_chain(tiled)
        return np.asarray(e.generate(
            seed, steps=GEN, start_pos=len(prompt) - 1))[0].tolist()

    want = [solo(p) for p in PROMPTS]
    mk = _mk_engine(cfg, params=params, schedule="dynamic")
    srv = ServingEngine(mk, rebalance_every=2, hot_expert_factor=0.0)
    h = [srv.submit(p, max_new_tokens=GEN) for p in PROMPTS]
    srv.run()
    assert [x.tokens for x in h] == want
    assert srv._mk_load_sig is not None, "rebalance never applied"
    assert mk.builder.expert_load is not None


def test_claim_order_shifts_under_skew():
    """graph.comm_priority expert_load: a hot expert's FFN chain is
    claimed measurably earlier than under uniform load, and the
    schedule stays a permutation of the task set (fairness)."""
    from triton_dist_tpu.megakernel.builder import ModelBuilder

    cfg = ModelConfig.tiny_moe(vocab_size=128, num_experts=8)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    kw = dict(batch=2, max_len=16, tile_w=16, t_tile=16,
              schedule="dynamic")
    hot = 7
    load = [1.0] * cfg.num_experts
    load[hot] = 50.0
    b_uni = ModelBuilder(cfg, mesh1, **kw)
    b_hot = ModelBuilder(cfg, mesh1, expert_load=load, **kw)

    def check(b):
        claims = b.claims.reshape(-1)
        real = claims[claims >= 0]
        assert sorted(real.tolist()) == list(range(len(b.graph.tasks)))
        pos = {int(t): i for i, t in enumerate(claims)}
        return np.mean([pos[t.task_id] for t in b.graph.tasks
                        if t.expert == hot])

    mean_uni, mean_hot = check(b_uni), check(b_hot)
    assert mean_hot < mean_uni, (
        f"hot-expert chain not promoted: {mean_hot} !< {mean_uni}")
    # reprioritize back to uniform restores the original order
    b_hot.reprioritize(None)
    assert np.array_equal(b_hot.claims, b_uni.claims)


def test_mk_expert_counts_exact(mk_cfg_params):
    """The in-kernel router counters count exactly
    batch * topk * n_layers selections per decode step."""
    cfg, params = mk_cfg_params
    mk = _mk_engine(cfg, params=params)
    mk.decode_step(jnp.asarray([1, 2], jnp.int32), 0)
    c1 = mk.expert_counts()
    mk.decode_step(jnp.asarray([3, 4], jnp.int32), 1)
    c2 = mk.expert_counts()
    per_step = 2 * cfg.num_experts_per_tok * cfg.num_hidden_layers
    assert c1.sum() == per_step
    assert (c2 - c1).sum() == per_step
    assert (c2 >= c1).all()
